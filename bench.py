"""Benchmark: PH iterations/sec on a 1000-scenario farmer via batched ADMM.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The workload mirrors the reference's headline shape (SURVEY §6: PH iters/sec /
wall-clock to gap on scenario ladders up to 1000 scenarios).  ``vs_baseline``
measures against the reference *architecture* on this host: a serial
one-LP-per-scenario PH iteration through an external simplex solver (HiGHS via
scipy — the stand-in for the Gurobi/CPLEX per-rank solve loop of
``spopt.py:226-307``), extrapolated from a timed sample of scenarios.
"""

import json
import os
import sys
import time

import numpy as np


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def main():
    import jax

    import tpusppy

    tpusppy.disable_tictoc_output()
    from tpusppy.ir import ScenarioBatch
    from tpusppy.models import farmer
    from tpusppy.parallel import sharded
    from tpusppy.solvers import scipy_backend
    from tpusppy.solvers.admm import ADMMSettings

    S = int(os.environ.get("BENCH_SCENS", "1000"))
    mult = int(os.environ.get("BENCH_CROPS_MULT", "4"))
    iters = int(os.environ.get("BENCH_ITERS", "20"))

    platform = jax.devices()[0].platform
    on_tpu = platform not in ("cpu",)
    dtype = "float32" if on_tpu else "float64"
    if dtype == "float64":
        jax.config.update("jax_enable_x64", True)
    eps = 1e-5 if dtype == "float32" else 1e-8
    # polish_passes=1: warm-started PH iterations start from near-correct
    # active sets, so one refinement pass reaches the same polished residual
    # as four at a third of the (batched-LU-dominated) cost
    settings = ADMMSettings(
        dtype=dtype, eps_abs=eps, eps_rel=eps, max_iter=200, restarts=2,
        scaling_iters=6, polish_passes=1,
    )

    log(f"platform={platform} S={S} crops_mult={mult} dtype={dtype}")
    names = farmer.scenario_names_creator(S)
    batch = ScenarioBatch.from_problems([
        farmer.scenario_creator(nm, num_scens=S, crops_multiplier=mult)
        for nm in names
    ])
    log(f"batch: {batch.num_scenarios} x ({batch.num_rows} rows, "
        f"{batch.num_vars} vars)")

    mesh = sharded.make_mesh()
    arr = sharded.shard_batch(batch, mesh)
    step = sharded.make_ph_step(batch.tree.nonant_indices, settings, mesh)
    state = sharded.init_state(arr, 1.0, settings)

    # warmup/compile + Iter0
    t0 = time.time()
    state, out = step(state, arr, 0.0)
    jax.block_until_ready(out.conv)
    log(f"compile+iter0: {time.time() - t0:.1f}s eobj={float(out.eobj):.2f}")

    window = sharded.dispatch_window(mesh)
    t0 = time.time()
    for i in range(iters):
        state, out = step(state, arr, 1.0)
        if (i + 1) % window == 0:
            jax.block_until_ready(out.conv)
    jax.block_until_ready(out.conv)
    dt_ours = (time.time() - t0) / iters
    iters_per_sec = 1.0 / dt_ours
    log(f"tpusppy: {iters_per_sec:.3f} PH iters/sec "
        f"(conv={float(out.conv):.3e}, eobj={float(out.eobj):.2f})")

    # Baseline: serial per-scenario LP loop through HiGHS (reference
    # architecture), timed on a sample and extrapolated to all S scenarios.
    sample = min(24, S)
    t0 = time.time()
    for s in range(sample):
        scipy_backend.solve_lp(
            batch.c[s], batch.A[s], batch.cl[s], batch.cu[s],
            batch.lb[s], batch.ub[s],
        )
    t_per_scen = (time.time() - t0) / sample
    baseline_iters_per_sec = 1.0 / (t_per_scen * S)
    log(f"baseline (serial HiGHS loop): {t_per_scen * 1e3:.2f} ms/scenario "
        f"=> {baseline_iters_per_sec:.4f} PH iters/sec")

    print(json.dumps({
        "metric": f"ph_iters_per_sec_farmer{S}",
        "value": round(iters_per_sec, 4),
        "unit": "iter/s",
        "vs_baseline": round(iters_per_sec / baseline_iters_per_sec, 2),
    }))


if __name__ == "__main__":
    main()
