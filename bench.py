"""Benchmark: PH iterations/sec on a 1000-scenario farmer via batched ADMM.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The workload mirrors the reference's headline shape (SURVEY §6: PH iters/sec /
wall-clock to gap on scenario ladders up to 1000 scenarios).  ``vs_baseline``
measures against the reference *architecture* on this host: a serial
one-LP-per-scenario PH iteration through an external simplex solver (HiGHS via
scipy — the stand-in for the Gurobi/CPLEX per-rank solve loop of
``spopt.py:226-307``), extrapolated from a timed sample of scenarios.

PH iterations run on the factorization-amortized path (periodic adaptive
refresh + sweep-only frozen steps, `sharded.make_ph_step_pair`); subproblems
are solved to 1e-5 scaled residuals each iteration — comparable to external
solver default feasibility/optimality tolerances.

Timing note: on the axon TPU plugin ``jax.block_until_ready`` returns before
execution completes, so all timing fences are host fetches (``np.asarray``).
Set BENCH_UC=1 for the UC metric (see bench_uc.py).
"""

import json
import os
import sys
import time

import numpy as np


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def main():
    if os.environ.get("BENCH_UC"):
        import bench_uc
        bench_uc.main()
        return

    import jax

    import tpusppy

    tpusppy.disable_tictoc_output()
    from tpusppy.ir import ScenarioBatch
    from tpusppy.models import farmer
    from tpusppy.parallel import sharded
    from tpusppy.solvers import scipy_backend
    from tpusppy.solvers.admm import ADMMSettings

    S = int(os.environ.get("BENCH_SCENS", "1000"))
    mult = int(os.environ.get("BENCH_CROPS_MULT", "4"))
    iters = int(os.environ.get("BENCH_ITERS", "60"))
    refresh_every = max(1, int(os.environ.get("BENCH_REFRESH", "16")))

    platform = jax.devices()[0].platform
    on_tpu = platform not in ("cpu",)
    dtype = "float32" if on_tpu else "float64"
    if dtype == "float64":
        jax.config.update("jax_enable_x64", True)
    eps = 1e-5 if dtype == "float32" else 1e-8
    # polish only on refresh iterations (1 in refresh_every): PH iterates
    # need solver-tolerance accuracy, not vertex-exactness; the periodic
    # polished refresh keeps xbar/W on exact solutions
    settings = ADMMSettings(
        dtype=dtype, eps_abs=eps, eps_rel=eps, max_iter=200, restarts=2,
        scaling_iters=6, polish_passes=1,
    )

    log(f"platform={platform} S={S} crops_mult={mult} dtype={dtype} "
        f"refresh_every={refresh_every}")
    names = farmer.scenario_names_creator(S)
    batch = ScenarioBatch.from_problems([
        farmer.scenario_creator(nm, num_scens=S, crops_multiplier=mult)
        for nm in names
    ])
    log(f"batch: {batch.num_scenarios} x ({batch.num_rows} rows, "
        f"{batch.num_vars} vars)")

    mesh = sharded.make_mesh()
    arr = sharded.shard_batch(batch, mesh)
    refresh, frozen = sharded.make_ph_step_pair(
        batch.tree.nonant_indices, settings, mesh)
    state = sharded.init_state(arr, 1.0, settings)

    # warmup/compile + Iter0
    t0 = time.time()
    state, out, _ = refresh(state, arr, 0.0)
    eobj0 = float(np.asarray(out.eobj))
    log(f"compile+iter0: {time.time() - t0:.1f}s eobj={eobj0:.2f}")
    state, out, factors = refresh(state, arr, 1.0)
    state, out = frozen(state, arr, 1.0, factors)
    np.asarray(out.conv)  # compile the frozen program too

    t0 = time.time()
    for i in range(iters):
        if i % refresh_every == 0:
            state, out, factors = refresh(state, arr, 1.0)
        else:
            state, out = frozen(state, arr, 1.0, factors)
    conv = float(np.asarray(out.conv))  # host fetch = the only real fence
    dt_ours = (time.time() - t0) / iters
    iters_per_sec = 1.0 / dt_ours
    log(f"tpusppy: {iters_per_sec:.3f} PH iters/sec "
        f"(conv={conv:.3e}, eobj={float(np.asarray(out.eobj)):.2f}, "
        f"worst pri={float(np.max(np.asarray(out.pri_res))):.2e})")

    # Baseline: serial per-scenario LP loop through HiGHS (reference
    # architecture), timed on a sample and extrapolated to all S scenarios.
    sample = min(24, S)
    t0 = time.time()
    for s in range(sample):
        scipy_backend.solve_lp(
            batch.c[s], batch.A[s], batch.cl[s], batch.cu[s],
            batch.lb[s], batch.ub[s],
        )
    t_per_scen = (time.time() - t0) / sample
    baseline_iters_per_sec = 1.0 / (t_per_scen * S)
    log(f"baseline (serial HiGHS loop): {t_per_scen * 1e3:.2f} ms/scenario "
        f"=> {baseline_iters_per_sec:.4f} PH iters/sec")

    line = {
        "metric": f"ph_iters_per_sec_farmer{S}",
        "value": round(iters_per_sec, 4),
        "unit": "iter/s",
        "vs_baseline": round(iters_per_sec / baseline_iters_per_sec, 2),
    }
    if not os.environ.get("BENCH_SKIP_UC"):
        try:
            import bench_uc
            line["uc"] = bench_uc.uc_metrics()
        except Exception as e:   # UC numbers are additive; never lose farmer
            log(f"uc benchmark failed: {e!r}")
            line["uc"] = {"error": repr(e)}
    print(json.dumps(line))


if __name__ == "__main__":
    main()
