"""Benchmark: PH iterations/sec on a 1000-scenario farmer via batched ADMM.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...} and
ALWAYS exits 0.

Orchestration (this file, parent process — imports no jax): the TPU runtime
here is a remote tunnel that can be down, wedged, or flaky; a benchmark that
dies with rc=1 when it is (BENCH_r02.json) loses the round's flagship number.
So the parent
  1. probes TPU availability in a SUBPROCESS with a hard timeout (a downed
     tunnel makes ``import jax``/``jax.devices()`` hang, not raise),
  2. retries the probe with backoff (transient tunnel hiccups),
  3. runs the real workload (``--workload``) as a child with a timeout,
  4. on persistent TPU unavailability, re-runs the child on CPU with a
     scrubbed environment and marks the JSON with ``"tpu_unavailable": true``
     — a CPU number beats no number,
  5. if everything fails, still prints a JSON line with an ``error`` field.
Children are strictly sequential: two concurrent TPU processes can wedge the
remote-compile tunnel.

The workload mirrors the reference's headline shape (SURVEY §6: PH iters/sec /
wall-clock to gap on scenario ladders up to 1000 scenarios).  Baselines:
  - ``vs_baseline``: vs the reference *architecture* on this host — a serial
    one-LP-per-scenario PH iteration through an external simplex solver
    (HiGHS via scipy, the stand-in for the per-rank Gurobi loop of
    ``spopt.py:226-307``), extrapolated from a timed sample.
  - ``vs_baseline_32rank``: the honest north-star figure (BASELINE.md:
    ≥10x vs 32-rank MPI+solver PH) — the serial baseline divided by 32,
    i.e. IDEAL 32-way scaling of the reference architecture, stated as such.

PH iterations run on the factorization-amortized path (periodic adaptive
refresh + sweep-only frozen steps, `sharded.make_ph_step_pair`); subproblems
are swept to 1e-5 scaled residuals or to their residual plateau (hard LP
families park around 5e-2 at ANY budget; the certified bounds never depend
on prox exactness, and the host tolerance ladder + rescue covers the tail
— see ADMMSettings.segment_plateau_rtol).

Timing note: on the axon TPU plugin ``jax.block_until_ready`` returns before
execution completes, so all timing fences are host fetches (``np.asarray``).
Set BENCH_UC=1 for the UC metric alone (see bench_uc.py).
"""

import json
import os
import subprocess
import sys
import time

RANKS = 32  # north-star comparison width (BASELINE.md: 32-rank MPI PH)


def log(msg):
    print(msg, file=sys.stderr, flush=True)


# --------------------------------------------------------------------------
# Parent-side orchestration (no jax in this process)
# --------------------------------------------------------------------------

def _scrubbed_cpu_env():
    """Environment for a CPU-only child: drop the TPU plugin's trigger vars
    (a sitecustomize on PYTHONPATH force-registers the remote TPU runtime and
    proxies XLA compiles through a tunnel that may be down)."""
    env = {
        k: v for k, v in os.environ.items()
        if k != "PYTHONPATH" and "AXON" not in k and not k.startswith("TPU_")
    }
    env["JAX_PLATFORMS"] = "cpu"
    env.setdefault("JAX_ENABLE_X64", "1")
    return env


def _run_child(args, env, timeout):
    """Run a child; return (ok, last_json_or_None, tail). stderr streams
    through (progress logs); stdout is captured for the JSON line."""
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)] + args,
            env=env, stdout=subprocess.PIPE, stderr=None, timeout=timeout,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        return False, None, f"timeout after {timeout}s"
    out = proc.stdout.decode(errors="replace")
    for line in reversed(out.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                parsed = json.loads(line)
            except json.JSONDecodeError:
                break
            # a complete JSON line is a finished measurement even if the
            # child's interpreter teardown then crashed (flaky TPU plugin):
            # keep the number, note the rc
            if proc.returncode != 0:
                parsed["child_rc"] = proc.returncode
            return True, parsed, out[-2000:]
    return False, None, f"rc={proc.returncode} out={out[-2000:]!r}"


def _probe_tpu(timeout):
    """True iff a TPU backend initializes in a fresh process within timeout."""
    code = ("import jax; d = jax.devices(); "
            "print('PROBE_OK', d[0].platform, len(d))")
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], env=dict(os.environ),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        return False, f"probe hang (>{timeout}s) — tunnel down"
    out = proc.stdout.decode(errors="replace")
    for line in out.splitlines():
        if line.startswith("PROBE_OK"):
            plat = line.split()[1]
            if plat != "cpu":
                return True, line.strip()
            return False, f"probe found only cpu backend: {line.strip()}"
    return False, f"probe rc={proc.returncode}: {out[-500:]!r}"


def main():
    # persistent XLA compile cache: reference-shape UC programs cost minutes
    # of (remote) compile; cacheing them makes re-runs and the driver's
    # round-end run start warm
    os.environ.setdefault(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(os.path.expanduser("~"), ".cache", "tpusppy_xla_tpu"))
    force_cpu = (os.environ.get("BENCH_FORCE_CPU")
                 or os.environ.get("JAX_PLATFORMS") == "cpu")
    attempts = int(os.environ.get("BENCH_TPU_ATTEMPTS", "3"))
    probe_timeout = float(os.environ.get("BENCH_PROBE_TIMEOUT", "180"))
    # headroom accounting (full-scale wheel default): farmer ~250s + UC
    # batch/iter0 ~300s + rate loop ~200s + h48 probe ~250s + MIP baseline
    # ~100s + S=1000 wheel ~1850s-to-gap + teardown ~900s ≈ 3900s typical,
    # plus compile variance — the child's deadline-derived watchdog shrinks
    # the wheel budget to whatever actually remains
    run_timeout = float(os.environ.get("BENCH_TPU_TIMEOUT", "5200"))
    cpu_timeout = float(os.environ.get("BENCH_CPU_TIMEOUT", "2400"))
    backoff = float(os.environ.get("BENCH_BACKOFF", "30"))

    tpu_error = None
    if not force_cpu:
        for attempt in range(attempts):
            if attempt:
                log(f"bench: backoff {backoff * attempt:.0f}s before "
                    f"TPU attempt {attempt + 1}/{attempts}")
                time.sleep(backoff * attempt)
            ok, info = _probe_tpu(probe_timeout)
            log(f"bench: TPU probe attempt {attempt + 1}/{attempts}: {info}")
            if not ok:
                tpu_error = info
                continue
            env = dict(os.environ)
            # hand the child its wall-clock deadline so the UC wheel can
            # size its watchdog to the budget actually remaining after the
            # farmer/rate/baseline phases (high-variance compiles)
            env.setdefault("BENCH_CHILD_DEADLINE",
                           str(time.time() + run_timeout - 60))
            ok, line, tail = _run_child(["--workload"], env, run_timeout)
            if ok and line is not None:
                line["tpu_unavailable"] = False
                print(json.dumps(line))
                return
            tpu_error = f"workload failed: {tail}"
            log(f"bench: TPU workload attempt {attempt + 1} failed: "
                f"{tail[:500]}")
    else:
        tpu_error = "forced cpu (BENCH_FORCE_CPU/JAX_PLATFORMS)"

    # CPU fallback — scrubbed env so the TPU plugin can't hang the child
    log(f"bench: falling back to CPU ({tpu_error})")
    env = _scrubbed_cpu_env()
    # trim the in-child UC wheel watchdog on CPU unless the caller pinned it
    env.setdefault("BENCH_UC_WHEEL_TIMEOUT", "600")
    ok, line, tail = _run_child(["--workload"], env, cpu_timeout)
    if ok and line is not None:
        line["tpu_unavailable"] = not force_cpu
        if tpu_error and not force_cpu:
            line["tpu_error"] = str(tpu_error)[:500]
        print(json.dumps(line))
        return

    # Last resort: a structured failure line, rc still 0 (a parseable
    # artifact with an error field beats a dead artifact)
    if os.environ.get("BENCH_UC"):
        metric = f"ph_iters_per_sec_uc{os.environ.get('BENCH_UC_SCENS', '1000')}"
    else:
        metric = f"ph_iters_per_sec_farmer{os.environ.get('BENCH_SCENS', '1000')}"
    print(json.dumps({
        "metric": metric,
        "value": 0.0,
        "unit": "iter/s",
        "vs_baseline": 0.0,
        "tpu_unavailable": True,
        "error": f"tpu: {str(tpu_error)[:400]}; cpu: {str(tail)[:400]}",
    }))


# --------------------------------------------------------------------------
# Child-side workload (runs under an already-validated backend)
# --------------------------------------------------------------------------

def workload():
    if os.environ.get("BENCH_UC"):
        import bench_uc
        bench_uc.main()
        return

    import jax
    import numpy as np

    import tpusppy

    if not os.environ.get("BENCH_TRACE"):
        tpusppy.disable_tictoc_output()
    from tpusppy.ir import ScenarioBatch
    from tpusppy.models import farmer
    from tpusppy.parallel import sharded
    from tpusppy.solvers import scipy_backend
    from tpusppy.solvers.admm import ADMMSettings

    S = int(os.environ.get("BENCH_SCENS", "1000"))
    iters = int(os.environ.get("BENCH_ITERS", "128"))
    refresh_every = max(1, int(os.environ.get("BENCH_REFRESH", "16")))
    chunk_req = int(os.environ.get("BENCH_CHUNK", "64"))

    platform = jax.devices()[0].platform
    on_tpu = platform not in ("cpu",)
    dtype = "float32" if on_tpu else "float64"
    if dtype == "float64":
        jax.config.update("jax_enable_x64", True)
    eps = 1e-5 if dtype == "float32" else 1e-8
    # polish only on refresh iterations (1 in refresh_every): PH iterates
    # need solver-tolerance accuracy, not vertex-exactness; the periodic
    # polished refresh keeps xbar/W on exact solutions
    settings = ADMMSettings(
        dtype=dtype, eps_abs=eps, eps_rel=eps, max_iter=200, restarts=2,
        scaling_iters=6, polish_passes=1,
    )

    def measure_farmer(mult, n_iters):
        """PH rate for one crops_multiplier; returns a metrics dict.

        Iterations run FUSED — one jitted program per `chunk` PH iterations
        (refresh every `refresh_every` inside it, `sharded.make_ph_fused_step`)
        — so the number is latency-proof: a slow remote-dispatch tunnel can
        no longer collapse the rate 25x (VERDICT r4 weak #1).  The per-step
        path remains as fallback for segmentation-regime shapes.
        """
        log(f"platform={platform} S={S} crops_mult={mult} dtype={dtype} "
            f"refresh_every={refresh_every}")
        names = farmer.scenario_names_creator(S)
        batch = ScenarioBatch.from_problems([
            farmer.scenario_creator(nm, num_scens=S, crops_multiplier=mult)
            for nm in names
        ])
        log(f"batch: {batch.num_scenarios} x ({batch.num_rows} rows, "
            f"{batch.num_vars} vars)")

        mesh = sharded.make_mesh()
        arr = sharded.shard_batch(batch, mesh)
        idx = batch.tree.nonant_indices
        refresh, frozen = sharded.make_ph_step_pair(idx, settings, mesh)
        state = sharded.init_state(arr, 1.0, settings)

        # warmup/compile + Iter0
        t0 = time.time()
        state, out, _ = refresh(state, arr, 0.0)
        eobj0 = float(np.asarray(out.eobj))
        log(f"compile+iter0: {time.time() - t0:.1f}s eobj={eobj0:.2f}")

        cap = sharded.fused_iteration_cap(arr, settings, mesh, refresh_every)
        chunk = min(chunk_req, cap) // refresh_every * refresh_every
        if chunk >= refresh_every:
            fused = sharded.make_ph_fused_step(
                idx, settings, mesh, chunk=chunk,
                refresh_every=refresh_every)
            t0 = time.time()
            state, out = fused(state, arr, 1.0)  # compile (+chunk iters)
            np.asarray(out.conv)
            log(f"fused chunk={chunk} compile: {time.time() - t0:.1f}s")
            n_chunks = max(1, n_iters // chunk)
            t0 = time.time()
            for _ in range(n_chunks):
                state, out = fused(state, arr, 1.0)
            conv = float(np.asarray(out.conv))  # host fetch = the fence
            measured = n_chunks * chunk
        else:  # segmentation-regime shapes: per-step dispatches
            state, out, factors = refresh(state, arr, 1.0)
            state, out = frozen(state, arr, 1.0, factors)
            np.asarray(out.conv)  # compile the frozen program too
            t0 = time.time()
            for i in range(n_iters):
                if i % refresh_every == 0:
                    state, out, factors = refresh(state, arr, 1.0)
                else:
                    state, out = frozen(state, arr, 1.0, factors)
            conv = float(np.asarray(out.conv))
            measured = n_iters
        iters_per_sec = measured / (time.time() - t0)
        log(f"tpusppy[m{mult}]: {iters_per_sec:.3f} PH iters/sec "
            f"({measured} iters, conv={conv:.3e}, "
            f"eobj={float(np.asarray(out.eobj)):.2f}, "
            f"worst pri={float(np.max(np.asarray(out.pri_res))):.2e})")

        # Baseline: serial per-scenario LP loop through HiGHS (reference
        # architecture), timed on a sample, extrapolated to all S scenarios.
        sample = min(24, S)
        t0 = time.time()
        for s in range(sample):
            scipy_backend.solve_lp(
                batch.c[s], batch.A[s], batch.cl[s], batch.cu[s],
                batch.lb[s], batch.ub[s],
            )
        t_per_scen = (time.time() - t0) / sample
        baseline_iters_per_sec = 1.0 / (t_per_scen * S)
        base32 = baseline_iters_per_sec * RANKS  # IDEAL 32-way scaling
        log(f"baseline[m{mult}] (serial HiGHS loop): "
            f"{t_per_scen * 1e3:.2f} ms/scenario "
            f"=> {baseline_iters_per_sec:.4f} PH iters/sec serial, "
            f"{base32:.4f} at ideal {RANKS}-rank scaling")
        return {
            "value": round(iters_per_sec, 4),
            "chunk": chunk,
            "vs_baseline": round(iters_per_sec / baseline_iters_per_sec, 2),
            "vs_baseline_32rank": round(iters_per_sec / base32, 2),
        }

    mult = int(os.environ.get("BENCH_CROPS_MULT", "4"))
    m_primary = measure_farmer(mult, iters)
    line = {
        "metric": f"ph_iters_per_sec_farmer{S}",
        "value": m_primary["value"],
        "unit": "iter/s",
        "platform": platform,
        "chunk": m_primary["chunk"],
        "vs_baseline": m_primary["vs_baseline"],
        # honest north-star figure: vs IDEAL 32-way scaling of the serial
        # reference architecture (serial/32 accounting, BASELINE.md)
        "vs_baseline_32rank": m_primary["vs_baseline_32rank"],
    }
    if mult != 1 and not os.environ.get("BENCH_SKIP_CM1"):
        try:  # latency-bound companion shape (VERDICT r4 weak #7)
            line["crops1"] = measure_farmer(1, iters)
        except Exception as e:
            line["crops1"] = {"error": repr(e)}
    if not os.environ.get("BENCH_SKIP_UC"):
        try:
            import bench_uc
            line["uc"] = bench_uc.uc_metrics()
        except Exception as e:   # UC numbers are additive; never lose farmer
            log(f"uc benchmark failed: {e!r}")
            line["uc"] = {"error": repr(e)}
    print(json.dumps(line))
    sys.stdout.flush()
    sys.stderr.flush()
    # hard-exit: a wheel watchdog timeout leaves a daemon spoke thread
    # mid-device-call, and normal interpreter teardown then aborts the
    # whole process (exit 134, "FATAL: exception not rethrown") AFTER the
    # artifact line was printed — losing the rc=0 the driver records.
    os._exit(0)


if __name__ == "__main__":
    if "--workload" in sys.argv[1:]:
        workload()
    else:
        main()
