"""Benchmark: PH iterations/sec on a 1000-scenario farmer via batched ADMM.

Prints parsed-JSON lines: a PARTIAL line (``"partial": true``) after every
completed segment and one final line at the end, and ALWAYS exits 0.  The
driver keeps the LAST parseable line, so a kill at ANY point (rc=124
included) still leaves the artifact with every segment that finished —
the incremental-artifact contract, regression-guarded by
``tests/test_bench_smoke.py``.

Orchestration (this file, parent process — imports no jax): the TPU runtime
here is a remote tunnel that can be down, wedged, or flaky; a benchmark that
dies with rc=1 when it is (BENCH_r02.json) loses the round's flagship number.
So the parent
  1. probes TPU availability in a SUBPROCESS with a hard timeout (a downed
     tunnel makes ``import jax``/``jax.devices()`` hang, not raise),
  2. retries the probe with backoff (transient tunnel hiccups),
  3. runs the real workload (``--workload``) as a child with a timeout,
     STREAMING its stdout — every JSON line the child prints is relayed
     (flushed) the moment it lands, so a SIGKILL of this parent cannot
     lose a finished segment,
  4. on persistent TPU unavailability, re-runs the child on CPU with a
     scrubbed environment and marks the JSON with ``"tpu_unavailable": true``
     — a CPU number beats no number (a PARTIAL TPU number beats both, and
     is kept instead of rerunning),
  5. if everything fails, still prints a JSON line with an ``error`` field.
Children are strictly sequential: two concurrent TPU processes can wedge the
remote-compile tunnel.

Budgets derive from ONE deadline: ``BENCH_DEADLINE`` (absolute epoch secs,
set by a driver that knows its own kill time) or now + ``BENCH_TPU_TIMEOUT``.
Every child timeout — including the in-child UC wheel watchdog
(``BENCH_CHILD_DEADLINE``) and the CPU fallback — is sized to what actually
remains of that deadline, so no fixed sub-budget can outlive the driver.

The workload mirrors the reference's headline shape (SURVEY §6: PH iters/sec /
wall-clock to gap on scenario ladders up to 1000 scenarios).  Baselines:
  - ``vs_baseline``: vs the reference *architecture* on this host — a serial
    one-LP-per-scenario PH iteration through an external simplex solver
    (HiGHS via scipy, the stand-in for the per-rank Gurobi loop of
    ``spopt.py:226-307``), EXTRAPOLATED from a timed sample (not a measured
    32-rank run).
  - ``vs_baseline_32rank``: the honest north-star figure (BASELINE.md:
    ≥10x vs 32-rank MPI+solver PH) — the serial baseline divided by 32,
    i.e. IDEAL 32-way scaling of the reference architecture, stated as such.
  - ``mfu_pct``: model-flop utilization (tpusppy/solvers/flops.py) — the
    absolute-efficiency number the ratios above can't give; conservative
    by construction (model matmul flops only over nominal peak).

PH iterations run FUSED — ``chunk`` iterations per device dispatch with a
refresh every ``refresh_every`` (``sharded.make_ph_fused_step``, buffer
donation on), the cadence picked per shape by the warmup autotuner
(``tpusppy.tune``; pin with BENCH_CHUNK/BENCH_REFRESH, disable with
BENCH_AUTOTUNE=0).  Subproblems are swept to 1e-5 scaled residuals or to
their residual plateau (see ADMMSettings.segment_plateau_rtol).

Timing note: on the axon TPU plugin ``jax.block_until_ready`` returns before
execution completes, so all timing fences are host fetches (``np.asarray``).
Set BENCH_UC=1 for the UC metric alone (see bench_uc.py).
BENCH_SMOKE=1 shrinks everything (tiny S, pinned cadence, no UC) for the
CI kill-safety test.

``--resume`` (with ``--ladder``) continues a killed ladder run
(tpusppy.resilience): finished rungs reload from the atomic state file
under BENCH_RESUME_DIR (default BENCH_TRACE_DIR/bench_resume), the
interrupted rung's WHEEL warm-starts from its own checkpoint directory
(BENCH_UC_CKPT_DIR, wired automatically), and the autotuner's verdicts
persist via TPUSPPY_TUNE_CACHE — so a SIGKILL costs at most one
checkpoint cadence of wheel progress, not the rung.

``--trace`` (or BENCH_TRACE=1) arms the flight recorder (tpusppy.obs):
every finished segment dumps ``BENCH_TRACE_DIR/bench_<tag>.perfetto.json``
(open at ui.perfetto.dev) plus a ``.report.json`` summary, the parsed
lines carry {path, report} per segment, and a small certified farmer
WHEEL segment is added whose trace shows the hub/spoke/dispatch/host-sync
tracks and whose report's gap-vs-wall array ends at the certified gap.
The wheel segment also times a hub-only IN-WHEEL certification leg
(``in_wheel_bounds``: the megastep's fused bound pass, zero spoke device
programs) and banks its wall as ``certified_wall_s`` next to the
3-cylinder golden's (doc/pipeline.md "In-wheel certification").
See doc/observability.md.

BENCH_TRACE_DIR defaults to ``bench_results/`` — every artifact this
process writes (traces, reports, resume state) lands there, not at the
repo root (root-level ``BENCH_*.json`` strays are gitignored).
"""

import dataclasses
import json
import os
import subprocess
import sys
import threading
import time

RANKS = 32  # north-star comparison width (BASELINE.md: 32-rank MPI PH)


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def _smoke():
    return bool(os.environ.get("BENCH_SMOKE"))


def _apply_smoke_defaults():
    """Tiny-everything posture for the CI kill-safety test (CPU, seconds
    not minutes, >=2 segments so a mid-run kill lands between them)."""
    for k, v in {
        "BENCH_SCENS": "8", "BENCH_ITERS": "8", "BENCH_CHUNK": "4",
        "BENCH_REFRESH": "4", "BENCH_AUTOTUNE": "0", "BENCH_SKIP_UC": "1",
        "BENCH_CROPS_MULT": "2",
        # --ladder smoke: two tiny rate-only rungs on the lite UC family
        "BENCH_LADDER_SCENS": "2,3", "BENCH_LADDER_RATE_ONLY": "1",
        "BENCH_UC_GENS": "2", "BENCH_UC_HORIZON": "4",
        "BENCH_UC_ITERS": "2",
        # serving segment smoke: tiny family, still 4 requests so the
        # warm-hit-rate / percentile fields are exercised
        "BENCH_SERVING_SCENS": "3", "BENCH_SERVING_ITERS": "40",
    }.items():
        os.environ.setdefault(k, v)


# --------------------------------------------------------------------------
# Parent-side orchestration (no jax in this process)
# --------------------------------------------------------------------------

def _scrubbed_cpu_env():
    """Environment for a CPU-only child: drop the TPU plugin's trigger vars
    (a sitecustomize on PYTHONPATH force-registers the remote TPU runtime and
    proxies XLA compiles through a tunnel that may be down)."""
    env = {
        k: v for k, v in os.environ.items()
        if k != "PYTHONPATH" and "AXON" not in k and not k.startswith("TPU_")
    }
    env["JAX_PLATFORMS"] = "cpu"
    env.setdefault("JAX_ENABLE_X64", "1")
    return env


def _run_child(args, env, timeout):
    """Run a child, STREAMING its stdout: JSON lines are relayed to this
    process's stdout the moment they arrive (the incremental-artifact
    contract — a kill of parent or child never loses a finished segment).
    Returns (ok, last_json_or_None, tail); ``last_json`` is the last
    parseable line even if the child timed out or crashed after printing
    it.  stderr streams through (progress logs)."""
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__)] + args,
        env=env, stdout=subprocess.PIPE, stderr=None,
        cwd=os.path.dirname(os.path.abspath(__file__)),
    )
    lines = []
    parsed_box = []

    def _reader():
        for raw in proc.stdout:
            line = raw.decode(errors="replace")
            lines.append(line)
            cand = line.strip()
            if cand.startswith("{"):
                try:
                    obj = json.loads(cand)
                except json.JSONDecodeError:
                    continue
                parsed_box.append(obj)
                # relay immediately: this line is already a valid artifact
                print(cand, flush=True)

    th = threading.Thread(target=_reader, daemon=True)
    th.start()
    timed_out = False
    try:
        proc.wait(timeout=timeout)
    except subprocess.TimeoutExpired:
        timed_out = True
        proc.kill()
        proc.wait()
    th.join(timeout=10)
    tail = "".join(lines)[-2000:]
    parsed = parsed_box[-1] if parsed_box else None
    if parsed is not None:
        # a parseable line is a finished measurement even if the child was
        # then killed (timeout) or its interpreter teardown crashed (flaky
        # TPU plugin): keep the number, note how the child ended
        if timed_out:
            parsed["child_rc"] = "timeout"
            parsed.setdefault("partial", True)
        elif proc.returncode != 0:
            parsed["child_rc"] = proc.returncode
        return True, parsed, tail
    if timed_out:
        return False, None, f"timeout after {timeout}s"
    return False, None, f"rc={proc.returncode} out={tail!r}"


def _probe_tpu(timeout):
    """True iff a TPU backend initializes in a fresh process within timeout."""
    code = ("import jax; d = jax.devices(); "
            "print('PROBE_OK', d[0].platform, len(d))")
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], env=dict(os.environ),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        return False, f"probe hang (>{timeout}s) — tunnel down"
    out = proc.stdout.decode(errors="replace")
    for line in out.splitlines():
        if line.startswith("PROBE_OK"):
            plat = line.split()[1]
            if plat != "cpu":
                return True, line.strip()
            return False, f"probe found only cpu backend: {line.strip()}"
    return False, f"probe rc={proc.returncode}: {out[-500:]!r}"


def main():
    if _smoke():
        _apply_smoke_defaults()
    # persistent XLA compile cache: reference-shape UC programs cost minutes
    # of (remote) compile; cacheing them makes re-runs and the driver's
    # round-end run start warm
    os.environ.setdefault(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(os.path.expanduser("~"), ".cache", "tpusppy_xla_tpu"))
    # AOT executable cache (tpusppy/solvers/aot.py): serialized compiled
    # programs, so a repeated bench reaches iter-1 in milliseconds — the
    # warm tier above the XLA source cache.  BENCH_AOT=0 disables.
    # --ladder runs defer to ladder_workload's own default (one cache
    # under BENCH_RESUME_DIR shared across rungs and --resume re-runs):
    # defaulting here would inherit into the child and silently warm a
    # documented-cold ladder from the machine-global cache.
    if os.environ.get("BENCH_AOT", "1") == "0":
        os.environ["TPUSPPY_AOT_CACHE"] = ""
    elif "--ladder" not in sys.argv[1:]:
        os.environ.setdefault(
            "TPUSPPY_AOT_CACHE",
            os.path.join(os.path.expanduser("~"), ".cache", "tpusppy_aot"))
    force_cpu = (os.environ.get("BENCH_FORCE_CPU")
                 or os.environ.get("JAX_PLATFORMS") == "cpu")
    attempts = int(os.environ.get("BENCH_TPU_ATTEMPTS", "3"))
    probe_timeout = float(os.environ.get("BENCH_PROBE_TIMEOUT", "180"))
    # headroom accounting (full-scale wheel default): farmer ~250s + UC
    # batch/iter0 ~300s + rate loop ~200s + h48 probe ~250s + MIP baseline
    # ~100s + S=1000 wheel ~1850s-to-gap + teardown ~900s ≈ 3900s typical,
    # plus compile variance
    run_timeout = float(os.environ.get("BENCH_TPU_TIMEOUT", "5200"))
    cpu_timeout = float(os.environ.get("BENCH_CPU_TIMEOUT", "2400"))
    backoff = float(os.environ.get("BENCH_BACKOFF", "30"))
    # ONE deadline rules every budget below.  A driver that will SIGKILL
    # this process exports BENCH_DEADLINE (absolute epoch secs); without it
    # the deadline is the parent's own nominal budget.
    deadline = float(os.environ.get("BENCH_DEADLINE", "0") or 0)
    if not deadline:
        deadline = time.time() + run_timeout

    def _remaining(margin=60.0):
        return max(120.0, deadline - time.time() - margin)

    # --ladder: the certified-gap wheel over a scenario ladder (one parsed
    # entry per rung) instead of the farmer/UC flagship line; the child
    # reuses the same kill-safe partial-line protocol.  --trace: the
    # flight recorder rides the run (tpusppy.obs) — one Perfetto JSON +
    # report per segment (BENCH_TRACE_DIR), plus a small traced farmer
    # WHEEL segment whose gap-vs-wall array the report carries
    # --resume: the ladder continues from its banked rung state file and
    # each rung's wheel warm-starts from its own checkpoint dir
    # (tpusppy.resilience) — a SIGKILLed bench re-run picks up where the
    # kill landed instead of restarting the rung
    child_args = ["--workload"] + (
        ["--ladder"] if "--ladder" in sys.argv[1:] else []) + (
        ["--trace"] if "--trace" in sys.argv[1:] else []) + (
        ["--resume"] if "--resume" in sys.argv[1:] else [])

    tpu_error = None
    if not force_cpu:
        for attempt in range(attempts):
            if attempt:
                log(f"bench: backoff {backoff * attempt:.0f}s before "
                    f"TPU attempt {attempt + 1}/{attempts}")
                time.sleep(backoff * attempt)
            ok, info = _probe_tpu(min(probe_timeout, _remaining()))
            log(f"bench: TPU probe attempt {attempt + 1}/{attempts}: {info}")
            if not ok:
                tpu_error = info
                continue
            env = dict(os.environ)
            # hand the child its wall-clock deadline so the UC wheel can
            # size its watchdog to the budget ACTUALLY remaining after the
            # farmer/rate/baseline phases (high-variance compiles)
            child_budget = min(run_timeout, _remaining())
            env["BENCH_CHILD_DEADLINE"] = str(time.time() + child_budget - 60)
            ok, line, tail = _run_child(child_args, env, child_budget)
            if ok and line is not None:
                line["tpu_unavailable"] = False
                print(json.dumps(line))
                return
            tpu_error = f"workload failed: {tail}"
            log(f"bench: TPU workload attempt {attempt + 1} failed: "
                f"{tail[:500]}")
    else:
        tpu_error = "forced cpu (BENCH_FORCE_CPU/JAX_PLATFORMS)"

    # CPU fallback — scrubbed env so the TPU plugin can't hang the child
    log(f"bench: falling back to CPU ({tpu_error})")
    env = _scrubbed_cpu_env()
    # trim the in-child UC wheel watchdog on CPU unless the caller pinned it
    env.setdefault("BENCH_UC_WHEEL_TIMEOUT", "600")
    child_budget = min(cpu_timeout, _remaining())
    env["BENCH_CHILD_DEADLINE"] = str(time.time() + child_budget - 30)
    ok, line, tail = _run_child(child_args, env, child_budget)
    if ok and line is not None:
        line["tpu_unavailable"] = not force_cpu
        if tpu_error and not force_cpu:
            line["tpu_error"] = str(tpu_error)[:500]
        print(json.dumps(line))
        return

    # Last resort: a structured failure line, rc still 0 (a parseable
    # artifact with an error field beats a dead artifact)
    if "--ladder" in sys.argv[1:]:
        metric = "uc_certified_ladder"
    elif os.environ.get("BENCH_UC"):
        metric = f"ph_iters_per_sec_uc{os.environ.get('BENCH_UC_SCENS', '1000')}"
    else:
        metric = f"ph_iters_per_sec_farmer{os.environ.get('BENCH_SCENS', '1000')}"
    print(json.dumps({
        "metric": metric,
        "value": 0.0,
        "unit": "iter/s",
        "vs_baseline": 0.0,
        "tpu_unavailable": True,
        "error": f"tpu: {str(tpu_error)[:400]}; cpu: {str(tail)[:400]}",
    }))


# --------------------------------------------------------------------------
# Child-side workload (runs under an already-validated backend)
# --------------------------------------------------------------------------

def emit_partial(line):
    """Print an intermediate artifact line NOW: the segment it describes is
    finished and must survive any later kill (the parent relays it
    immediately; the driver keeps the last parseable line)."""
    out = dict(line)
    out["partial"] = True
    print(json.dumps(out), flush=True)


def _compile_span_secs(since: float):
    """Sum of the EXPLICIT compile-time spans ("aot.compile" = lower+XLA,
    "aot.load" = executable deserialize) recorded on the trace ring since
    ``since`` (a perf_counter stamp).  This is the satellite fix for the
    old compile_s heuristic: "first-dispatch wall minus steady-state mean"
    goes negative-clamped-to-zero on noisy CPU runs, while these spans
    time the compile work itself and nothing else.  Returns None when
    tracing is off or no compile spans landed (heuristic fallback)."""
    from tpusppy.obs import trace

    if not trace.enabled():
        return None
    secs = sum(e.dur or 0.0 for e in trace.events()
               if e.kind == "span" and e.t >= since
               and e.name in ("aot.compile", "aot.load"))
    return secs if secs > 0.0 else None


def _aot_segment_stats(base: dict):
    """{hits, misses, unserializable, compile_s, deserialize_s} deltas
    since ``base`` (see :func:`_aot_stats_mark`) — the per-segment
    warm-start evidence every bench segment now carries."""
    from tpusppy.obs import metrics

    return {k: round(metrics.value(f"aot.{k}") - base[k], 3)
            for k in base}


def _aot_stats_mark() -> dict:
    from tpusppy.obs import metrics

    return {k: metrics.value(f"aot.{k}")
            for k in ("hits", "misses", "unserializable", "compile_s",
                      "deserialize_s")}


def _mem_fields() -> dict:
    """{peak_rss_mb, device_peak_mb} for a segment line — refreshes the
    ``mem.host_peak`` / ``mem.device_peak`` gauges (tpusppy.obs.sysmem).
    Host peak is a process HIGH-WATER mark (monotone across segments);
    device peak reads 0.0 on XLA:CPU, which reports no memory stats."""
    from tpusppy.obs import sysmem

    return sysmem.sample()


def _tracing_on():
    """Flight recorder armed for this child?  --trace / BENCH_TRACE are
    the bench knobs; a recorder already enabled some other way (the
    TPUSPPY_TRACE env knob enables at import) counts too, so the bench
    behaves identically — per-segment windows, wheel showcase — no
    matter which documented switch armed it."""
    if "--trace" in sys.argv[1:] or os.environ.get("BENCH_TRACE"):
        return True
    try:
        from tpusppy.obs import trace

        return trace.enabled()
    except ImportError:      # parent process posture: no tpusppy import
        return False


# metrics window spanning the CURRENT trace segment (armed when tracing
# turns on, re-armed after each dump) so each segment's report carries
# its own counter deltas, not the process-cumulative totals
_SEG_WIN = None


def _arm_segment_window():
    global _SEG_WIN
    from tpusppy.obs import metrics

    _SEG_WIN = metrics.window().__enter__()


def trace_segment_dump(tag):
    """Bank the trace ring accumulated during one finished segment as
    ``BENCH_TRACE_DIR/bench_<tag>.perfetto.json`` (+ ``.report.json``)
    and return {path, report} for the segment's parsed-JSON entry; the
    ring is then cleared (and the counter window re-armed) so the next
    segment's artifact stands alone.  No-op (None) when tracing is off —
    and NEVER raises: a dump I/O failure (unwritable dir, full disk)
    must not cost the measurement it describes (the kill-safe bench
    contract)."""
    from tpusppy.obs import metrics, perfetto, report, trace

    if not trace.enabled():
        return None
    try:
        out_dir = os.environ.get("BENCH_TRACE_DIR", "bench_results")
        os.makedirs(out_dir, exist_ok=True)
        path = os.path.join(out_dir, f"bench_{tag}.perfetto.json")
        evs = trace.events()
        dropped = trace.dropped()
        win = _SEG_WIN if _SEG_WIN is not None else metrics.Window()
        rep = report.build_report(evs, counters=win.deltas(),
                                  dropped=dropped)
        perfetto.export(evs, path=path)
        with open(path + ".report.json", "w") as f:
            json.dump(rep, f, indent=1)
        log(f"trace[{tag}]: {len(evs)} events -> {path}")
        return {"path": path, "report": rep}
    except Exception as e:
        log(f"trace dump failed for segment {tag} (measurement kept): "
            f"{e!r}")
        return None
    finally:
        trace.reset()
        _arm_segment_window()


def traced_farmer_wheel():
    """A small certified farmer WHEEL under the flight recorder: PH hub +
    Lagrangian outer + XhatShuffle inner (the minimum full wheel), traced
    end to end so the artifact shows hub iterations, spoke bound passes,
    dispatches, mailbox traffic and host syncs on one timeline — and the
    report's gap-vs-wall array ends at the final certified gap.  Runs
    only under ``--trace`` (it is the recorder's showcase segment, not a
    rate measurement)."""
    from tpusppy.cylinders import (LagrangianOuterBound, PHHub,
                                   XhatShuffleInnerBound)
    from tpusppy.models import farmer
    from tpusppy.opt.ph import PH
    from tpusppy.phbase import PHBase
    from tpusppy.spin_the_wheel import WheelSpinner
    from tpusppy.xhat_eval import Xhat_Eval

    from tpusppy.obs import metrics as obs_metrics

    S = int(os.environ.get("BENCH_TRACE_WHEEL_SCENS", "3"))
    iters = int(os.environ.get("BENCH_TRACE_WHEEL_ITERS", "40"))

    def opt_kwargs(megastep=0):
        return {
            "options": {
                "defaultPHrho": 1.0, "PHIterLimit": iters,
                "convthresh": -1.0,
                "xhat_looper_options": {"scen_limit": 3},
                "solver_options": {"megastep": megastep},
            },
            "all_scenario_names": farmer.scenario_names_creator(S),
            "scenario_creator": farmer.scenario_creator,
            "scenario_creator_kwargs": {"num_scens": S},
        }

    def wheel_dicts(megastep=0):
        hub_dict = {
            "hub_class": PHHub,
            "hub_kwargs": {"options": {"rel_gap": 1e-3, "abs_gap": 1.0,
                                       "linger_secs": 60.0}},
            "opt_class": PH, "opt_kwargs": opt_kwargs(megastep),
        }
        spokes = [
            {"spoke_class": LagrangianOuterBound, "spoke_kwargs": {},
             "opt_class": PHBase, "opt_kwargs": opt_kwargs(megastep)},
            {"spoke_class": XhatShuffleInnerBound, "spoke_kwargs": {},
             "opt_class": Xhat_Eval, "opt_kwargs": opt_kwargs(megastep)},
        ]
        return hub_dict, spokes

    t0 = time.time()
    aot_base = _aot_stats_mark()
    with obs_metrics.window() as mwin:
        ws = WheelSpinner(*wheel_dicts()).spin()
    # one more gap computation AFTER the wheel finishes: it emits the
    # final rel_gap sample, so the report's gap-vs-wall array ends at
    # exactly the gap this entry reports
    abs_gap, rel_gap = ws.spcomm.compute_gaps()
    megasteps = int(mwin.delta("dispatch.megasteps"))
    mega_iters = int(mwin.delta("dispatch.mega_iterations"))
    hub_iters = int(ws.spcomm.opt._iter)
    entry = {
        "S": S,
        "wall_secs": round(time.time() - t0, 2),
        "inner": float(ws.BestInnerBound),
        "outer": float(ws.BestOuterBound),
        "abs_gap": float(abs_gap),
        "rel_gap": float(rel_gap),
        # wheel-wide host-sync accounting under the megakernel (one
        # packed fetch per megastep instead of one per hub iteration)
        "host_sync_count": int(mwin.delta("host_sync.count")),
        "megasteps": megasteps,
        "mega_iterations": mega_iters,
        "megastep_n": (round(mega_iters / megasteps, 1)
                       if megasteps else 0),
        # hub-scoped measurement-fetch accounting, exact by construction
        # (one packed fetch per solve window: legacy iterations pay one
        # each, a megastep pays one for all its iterations) — counted
        # from the hub's ACTUAL final iteration (rel_gap termination can
        # end the wheel early), not the configured limit.  The
        # process-wide host_sync_count above includes the spokes' own
        # (unchanged) bound fetches.
        "hub_iter_fetches": hub_iters - mega_iters + megasteps,
        "hub_iter_fetches_legacy": hub_iters,
        "hub_fetch_drop_factor": round(
            hub_iters / max(1, hub_iters - mega_iters + megasteps), 2),
        # executable-cache evidence for the wheel segment (the same
        # counters land in the flight-recorder report's counter dump)
        "aot": _aot_segment_stats(aot_base),
        **_mem_fields(),
    }
    # bank the megakernel wheel's trace BEFORE the legacy comparison run:
    # the artifact's gap-vs-wall series must end at THIS entry's gap, and
    # the comparison wheel's events must not bleed into it
    dump = trace_segment_dump(f"wheel_farmer{S}")
    if dump is not None:
        entry["trace"] = dump
        gvw = dump["report"]["gap_vs_wall"]
        assert gvw and abs(gvw[-1][1] - entry["rel_gap"]) < 1e-12, \
            "flight-recorder gap series must end at the reported gap"
    # IN-WHEEL certification leg (doc/pipeline.md "In-wheel
    # certification"): the same certified shape as a hub-ONLY wheel —
    # the megastep's fused bound pass produces both bounds, zero spoke
    # threads/device programs — timed to the certified gap.  Its wall is
    # the headline `certified_wall_s`; the 3-cylinder golden's wall and
    # gap ride next to it so the artifact carries the comparison whole.
    if not os.environ.get("BENCH_SKIP_WHEEL_INWHEEL"):
        try:
            hub_iw, _ = wheel_dicts()
            hub_iw = dict(hub_iw)
            hub_iw["opt_kwargs"] = dict(hub_iw["opt_kwargs"])
            iw_options = dict(hub_iw["opt_kwargs"]["options"],
                              in_wheel_bounds=True)
            hub_iw["opt_kwargs"]["options"] = iw_options
            t_iw = time.time()
            with obs_metrics.window() as iwin:
                ws_iw = WheelSpinner(hub_iw, []).spin()
            abs_iw, rel_iw = ws_iw.spcomm.compute_gaps()
            entry["in_wheel"] = {
                # wall to the certified gap, hub-only (the wall-clock
                # flagship of the self-certifying megastep)
                "certified_wall_s": round(time.time() - t_iw, 2),
                "certified_wall_s_3cyl": entry["wall_secs"],
                "abs_gap": float(abs_iw),
                "rel_gap": float(rel_iw),
                "inner": float(ws_iw.BestInnerBound),
                "outer": float(ws_iw.BestOuterBound),
                "host_sync_count": int(iwin.delta("host_sync.count")),
                "host_sync_count_3cyl": entry["host_sync_count"],
                "bound_passes": int(iwin.delta("megastep.bound_passes")),
                "spoke_cylinders": 0,
            }
            # flagship field at the wheel-entry top level (the driver
            # artifact's `certified_wall_s`)
            entry["certified_wall_s"] = \
                entry["in_wheel"]["certified_wall_s"]
            trace_segment_dump(f"wheel_farmer{S}_inwheel")
        except Exception as e:
            log(f"in-wheel certification leg failed: {e!r}")
            entry["in_wheel"] = {"error": repr(e)}
            trace_segment_dump(f"wheel_farmer{S}_inwheel_failed")
    # legacy-dispatch comparison wheel (ADMMSettings.megastep = 1): the
    # same certified run, one dispatch + one fetch per hub iteration —
    # the host-sync drop factor is the megakernel's headline number
    if not os.environ.get("BENCH_SKIP_WHEEL_LEGACY"):
        with obs_metrics.window() as lwin:
            ws_l = WheelSpinner(*wheel_dicts(megastep=1)).spin()
        ws_l.spcomm.compute_gaps()
        entry["host_sync_count_legacy"] = int(lwin.delta("host_sync.count"))
        if entry["host_sync_count"]:
            entry["host_sync_drop_factor"] = round(
                entry["host_sync_count_legacy"]
                / entry["host_sync_count"], 2)
        # bank + reset the comparison run's events so they can never
        # bleed into the NEXT segment's window
        trace_segment_dump(f"wheel_farmer{S}_legacy")
    return entry


def integer_segment():
    """Batched integer wheel (doc/integer.md): hub-only in-wheel wheels
    on the two INTEGER families (netdes + sizes, ``relax_integers=
    False``) — certified gap, wall, host escalation seconds, and the
    ``integer.*`` counter deltas per family.  The per-family LP-only
    floor (the EF integrality gap) rides next to the certified gap so
    the artifact shows the wheel certifying PAST what LP-only bounds
    can ever reach; ``all_host_lift_secs`` is the measured wall of one
    full UNRANKED gap-closed MILP lift over every scenario (the
    pure-host posture's unit of work) for the escalation-fraction
    comparison.
    """
    from tpusppy.cylinders import PHHub
    from tpusppy.models import netdes as netdes_model
    from tpusppy.models import sizes as sizes_model
    from tpusppy.obs import metrics as obs_metrics
    from tpusppy.opt.ph import PH
    from tpusppy.solvers import integer as integer_solvers
    from tpusppy.spin_the_wheel import WheelSpinner

    S = int(os.environ.get("BENCH_INT_SCENS", "3"))
    fams = {
        "netdes": dict(
            module=netdes_model, rho=1.0, iters=60, rel_gap=0.04,
            budget_s=20.0,
            kw={"num_scens": S, "relax_integers": False}),
        # sizes: the MIP-rescue leg alone prices ~10s/scenario before
        # the lift runs — the budget must cover both tiers
        "sizes": dict(
            module=sizes_model, rho=0.01, iters=80, rel_gap=0.02,
            budget_s=60.0,
            kw={"scenario_count": S, "relax_integers": False}),
    }
    out = {"S": S}
    for name, f in fams.items():
        mod = f["module"]
        opt_kwargs = {
            "options": {"defaultPHrho": f["rho"],
                        "PHIterLimit": f["iters"], "convthresh": -1.0,
                        "in_wheel_bounds": True,
                        "integer_escalation_budget_s": f["budget_s"]},
            "all_scenario_names": mod.scenario_names_creator(S),
            "scenario_creator": mod.scenario_creator,
            "scenario_creator_kwargs": f["kw"],
        }
        hub_dict = {"hub_class": PHHub,
                    "hub_kwargs": {"options": {"rel_gap": f["rel_gap"]}},
                    "opt_class": PH, "opt_kwargs": opt_kwargs}
        t0 = time.time()
        with obs_metrics.window() as w:
            ws = WheelSpinner(hub_dict, []).spin()
        wall = time.time() - t0
        abs_gap, rel_gap = ws.spcomm.compute_gaps()
        entry = {
            "wall_secs": round(wall, 2),
            "rel_gap": float(rel_gap),
            "inner": float(ws.BestInnerBound),
            "outer": float(ws.BestOuterBound),
            "escalation_secs": round(
                w.delta("integer.escalation_secs"), 3),
            "candidates": int(w.delta("integer.candidates")),
            "feasible_hits": int(w.delta("integer.feasible_hits")),
            "rcfix_slots": int(w.delta("integer.rcfix_slots")),
            "escalations": int(w.delta("integer.escalations")),
            "bound_passes": int(w.delta("megastep.bound_passes")),
        }
        # the pure-host comparison: ONE full unranked gap-closed MILP
        # lift over every scenario from the final W is what a MIP-backed
        # bound spoke pays PER ITERATION — the baseline wall is the
        # measured unit times the iterations this wheel ran
        try:
            from tpusppy.solvers.milp_bound import milp_lift

            qL = integer_solvers._waug_q(ws.opt)
            base = ws.opt.Edualbound_perscen(q=qL, q2=ws.opt.batch.q2)
            t0 = time.time()
            milp_lift(ws.opt.batch, qL, base, budget_s=120.0,
                      mip_rel_gap=1e-4)
            unit = time.time() - t0
            iters_run = max(1, int(getattr(ws.opt, "_iter", 1)))
            entry["lift_unit_secs"] = round(unit, 3)
            entry["all_host_lift_secs"] = round(unit * iters_run, 3)
        except Exception as e:
            entry["all_host_lift_secs"] = None
            log(f"integer all-host baseline failed ({name}): {e!r}")
        out[name] = entry
        trace_segment_dump(f"integer_{name}")
    return out


def serving_segment():
    """Serving SLOs through the wheel-as-a-service path (tpusppy.service,
    doc/serving.md): one in-process SolveServer receives
    ``BENCH_SERVING_REQUESTS`` isomorphic farmer requests — the first is
    the family's COLD compile, the rest must bind warm (zero
    ``aot.misses``) — and the parsed line banks requests/s, p50/p95
    latency, the warm-hit rate, and the cold-vs-warm time-to-iter-1 pair
    (the PR-7 ">= 3x to iter-1" bar measured through the serving path;
    asserted by scripts/serving_smoke.py in the nightly, recorded here).
    Note the segment inherits any ambient TPUSPPY_AOT_CACHE, so on a
    reused bench cache dir even the FIRST request may start warm —
    ``ttfi_cold_s`` is then already-warm and the speedup ~1x by design.
    """
    import tempfile

    from tpusppy.service import SolveRequest, SolveServer

    S = int(os.environ.get("BENCH_SERVING_SCENS", "4"))
    n_req = int(os.environ.get("BENCH_SERVING_REQUESTS", "4"))
    iters = int(os.environ.get("BENCH_SERVING_ITERS", "80"))
    work = tempfile.mkdtemp(prefix="bench_srv_")
    # context manager: a wedged request (result timeout) must still shut
    # the executor down, or its daemon thread keeps dispatching queued
    # wheels under every LATER bench segment's measurement
    with SolveServer(work_dir=work,
                     quantum_secs=1.0, linger_secs=45.0) as srv:
        t0 = time.time()
        rids = [srv.submit(SolveRequest(
            model="farmer", num_scens=S,
            creator_kwargs={"seedoffset": 137 * i},
            options={"PHIterLimit": iters})) for i in range(n_req)]
        recs = [srv.result(r, timeout=1200) for r in rids]
        wall = time.time() - t0
        summary = srv.slo_summary()
    warm = [r for r in recs if r["warm_hit"]]
    warm_ttfi = [r["ttfi_s"] for r in warm if r["ttfi_s"] is not None]
    entry = {
        "S": S,
        "requests": n_req,
        "completed": summary["completed"],
        "wall_secs": round(wall, 2),
        "requests_per_sec": round(n_req / wall, 3),
        "p50_latency_s": summary["p50_latency_s"],
        "p95_latency_s": summary["p95_latency_s"],
        "warm_hit_rate": summary["warm_hit_rate"],
        "preemptions": summary["preemptions"],
        "ttfi_cold_s": recs[0]["ttfi_s"],
        "ttfi_warm_s": min(warm_ttfi, default=None),
        "aot_misses_warm": sum(r["aot_misses"] for r in warm),
        "certified": all(r["certified"] for r in recs),
        "gaps": [None if r["rel_gap"] is None else round(r["rel_gap"], 6)
                 for r in recs],
        **_mem_fields(),
    }
    if warm_ttfi and entry["ttfi_cold_s"]:
        entry["warm_ttfi_speedup"] = round(
            entry["ttfi_cold_s"] / max(min(warm_ttfi), 1e-9), 1)
    # recovery-warm TTFI (doc/serving.md "Durability"): a SECOND server
    # LIFETIME over the same work dir (recover_from) serves a fresh
    # isomorphic request — the restart path through journal replay +
    # re-armed caches.  In-process the executables are still resident,
    # so this measures the restart machinery's overhead on the warm
    # path; the cross-process cold/warm truth is the serving-chaos
    # smoke's job.
    try:
        with SolveServer.recover_from(work, quantum_secs=1.0,
                                      linger_secs=45.0) as srv2:
            rec = srv2.result(srv2.submit(SolveRequest(
                model="farmer", num_scens=S,
                creator_kwargs={"seedoffset": 4242},
                options={"PHIterLimit": iters})), timeout=1200)
        entry["recovery_warm_ttfi_s"] = rec["ttfi_s"]
        entry["recovery_certified"] = bool(rec["certified"])
    except Exception as e:   # recovery SLOs are additive, never fatal
        entry["recovery_error"] = repr(e)
    # continuous batching vs forced time-slicing (doc/serving.md
    # "Continuous batching"): the same isomorphic burst through a
    # batch_slots=K server and through a FORCED time-sliced baseline —
    # batch_slots=None plus a churn driver that preempt()s the running
    # tenant every quantum, because family affinity would otherwise run
    # the burst serially FCFS, which is not time-slicing.  Banks the
    # aggregate requests/s pair, the speedup, and the batched p50 queue
    # wait (the >=3x bar asserted nightly by scripts/batching_smoke.py).
    try:
        n_b = int(os.environ.get("BENCH_BATCH_REQUESTS", "6"))
        slots = int(os.environ.get("BENCH_BATCH_SLOTS", "3"))
        S_b = int(os.environ.get("BENCH_BATCH_SCENS", "3"))
        quantum = float(os.environ.get("BENCH_BATCH_QUANTUM", "0.2"))
        reps = int(os.environ.get("BENCH_BATCH_REPS", "2"))

        def _breq(rid, i):
            return SolveRequest(
                model="farmer", num_scens=S_b, request_id=rid,
                creator_kwargs={"seedoffset": 31 * i},
                options={"PHIterLimit": 400})

        def _burst(batch_slots, tag):
            wd = tempfile.mkdtemp(prefix=f"bench_srv_batch_{tag}_")
            with SolveServer(work_dir=wd, batch_slots=batch_slots,
                             in_wheel_bounds=True, quantum_secs=300.0,
                             linger_secs=0.0) as s2:
                s2.result(s2.submit(_breq(f"warm-{tag}", 99)),
                          timeout=1200)
                stop = threading.Event()
                if batch_slots is None:
                    def _churn():
                        while not stop.is_set():
                            time.sleep(quantum)
                            for t in list(s2._tenants.values()):
                                if (t.status == "running"
                                        and t.id != f"warm-{tag}"):
                                    s2.preempt(t.id)
                                    break
                    threading.Thread(target=_churn, daemon=True).start()
                # min-of-reps: a steady-state rate, not a one-shot
                # sample (same protocol as scripts/batching_smoke.py)
                walls = []
                for rep in range(reps):
                    t0 = time.time()
                    rb = [s2.submit(_breq(f"{tag}{rep}_{i}", i))
                          for i in range(n_b)]
                    recs_b = [s2.result(r, timeout=1200) for r in rb]
                    walls.append(time.time() - t0)
                stop.set()
                qsum = s2.slo_summary()
            return min(walls), recs_b, qsum

        wall_k, recs_k, sum_k = _burst(slots, "bk")
        wall_1, recs_1, _ = _burst(None, "bt")
        entry["batched_requests_per_s"] = round(n_b / wall_k, 3)
        entry["timesliced_requests_per_s"] = round(n_b / wall_1, 3)
        entry["batched_speedup"] = round(wall_1 / max(wall_k, 1e-9), 2)
        entry["p50_queue_wait"] = sum_k["p50_queue_wait_s"]
        entry["batched_certified"] = all(
            r["certified"] and r["batched"] for r in recs_k)
        entry["timesliced_certified"] = all(
            r["certified"] for r in recs_1)
    except Exception as e:   # batching SLOs are additive, never fatal
        entry["batching_error"] = repr(e)
    # telemetry overhead (doc/observability.md): the SAME warm
    # isomorphic burst with the trace ring recording request-scoped
    # spans/counters vs with it off.  Two figures land in the entry:
    # the wall-clock A/B delta (telemetry_overhead_pct — bounded by
    # machine noise, see telemetry_noise_floor_pct) and the accounting
    # bound (telemetry_overhead_accounted_pct = recorded events x
    # measured per-event ring cost / traced wall — deterministic; the
    # <2% budget is asserted against THIS one).
    try:
        from tpusppy.obs import trace as _tr

        if _tr.enabled():
            # bench --trace: no clean untraced baseline exists in this
            # process — skip rather than bank a meaningless 0%
            entry["telemetry_overhead_pct"] = None
        else:
            n_t = int(os.environ.get("BENCH_TELEMETRY_REQUESTS", "4"))
            S_t = int(os.environ.get("BENCH_SERVING_SCENS", "4"))
            # 3x the serving iterations: the delta being measured is
            # ~0.1% (one lock+append per host-side event), so the burst
            # must be long enough that fixed scheduling noise (tens of
            # ms) stays under the 2% budget being asserted
            iters_t = int(os.environ.get("BENCH_TELEMETRY_ITERS",
                                         str(3 * iters)))

            def _treq(rid, i):
                # rel_gap 1e-12: gap-certified termination lands at a
                # DIFFERENT iteration every run (async cylinder timing)
                # — an unreachable target pins every request to exactly
                # iters_t iterations so the two arms do identical work
                return SolveRequest(
                    model="farmer", num_scens=S_t, request_id=rid,
                    creator_kwargs={"seedoffset": 53 * i},
                    options={"PHIterLimit": iters_t,
                             "rel_gap": 1e-12})

            def _tburst(tag, traced):
                wd = tempfile.mkdtemp(prefix=f"bench_srv_tel_{tag}_")
                if traced:
                    _tr.enable()
                try:
                    with SolveServer(work_dir=wd, quantum_secs=300.0,
                                     linger_secs=0.0) as s3:
                        s3.result(s3.submit(_treq(f"twarm-{tag}", 97)),
                                  timeout=1200)
                        t0 = time.time()
                        rt = [s3.submit(_treq(f"t{tag}_{i}", i))
                              for i in range(n_t)]
                        for r in rt:
                            s3.result(r, timeout=1200)
                        wall = time.time() - t0
                        n_ev = len(_tr.events()) if traced else 0
                        return wall, n_ev
                finally:
                    if traced:
                        _tr.disable()
                        _tr.reset()

            # min-of-reps with ALTERNATING arm order: single one-shot
            # bursts wobble +/-10-30% on a contended CPU host, far
            # above the overhead being measured, and a fixed off-then-on
            # order folds monotone process drift into one arm — min
            # over reps is the batching burst's steady-state protocol
            reps_t = int(os.environ.get("BENCH_TELEMETRY_REPS", "4"))
            offs, ons, ev_counts = [], [], []
            for rep in range(reps_t):
                order = ((False, True) if rep % 2 == 0
                         else (True, False))
                for traced in order:
                    w, n_ev = _tburst(
                        f"{'on' if traced else 'off'}{rep}", traced)
                    (ons if traced else offs).append(w)
                    if traced:
                        ev_counts.append(n_ev)
            w_off, w_on = min(offs), min(ons)
            entry["telemetry_overhead_pct"] = round(
                100.0 * (w_on - w_off) / max(w_off, 1e-9), 2)
            # spread of the SAME arm across reps = what the A/B delta
            # above can resolve on this host; a |delta| under this is
            # indistinguishable from zero
            entry["telemetry_noise_floor_pct"] = round(
                100.0 * min(max(offs) - min(offs),
                            max(ons) - min(ons)) / max(w_off, 1e-9), 2)
            # accounting bound: measured per-event enabled-ring cost
            # (lock + deque append, calibrated here) x the events a
            # traced burst actually records, over the traced wall —
            # deterministic where the wall A/B is noise-dominated
            _tr.enable()
            try:
                n_cal = 20000
                t0 = time.perf_counter()
                for _ in range(n_cal):
                    _tr.instant("bench", "telemetry_cal")
                per_event_s = (time.perf_counter() - t0) / n_cal
            finally:
                _tr.disable()
                _tr.reset()
            entry["telemetry_event_cost_us"] = round(
                per_event_s * 1e6, 3)
            entry["telemetry_events_per_burst"] = int(
                sum(ev_counts) / max(len(ev_counts), 1))
            entry["telemetry_overhead_accounted_pct"] = round(
                100.0 * entry["telemetry_events_per_burst"]
                * per_event_s / max(w_on, 1e-9), 3)
    except Exception as e:   # additive, never fatal
        entry["telemetry_error"] = repr(e)
    return entry


def ladder_workload():
    """Certified-gap wheel over a scenario ladder (VERDICT r5 item 5):
    one :func:`bench_uc.uc_metrics` run per rung S, all inside ONE
    ``BENCH_DEADLINE``, one parsed-JSON partial line banked per rung —
    the same kill-safe protocol as the flagship line, so a kill at any
    rung keeps every rung that finished.

    Budgeting: the remaining deadline is split evenly over the remaining
    rungs — small rungs finish early and their surplus flows to the big
    ones.  Rungs that no longer fit are reported as skipped, never
    silently dropped.  ``BENCH_LADDER_SCENS`` overrides the rung list;
    ``BENCH_LADDER_RATE_ONLY=1`` skips the wheels (smoke posture).
    """
    rungs = [int(s) for s in os.environ.get(
        "BENCH_LADDER_SCENS",
        "3,50,100,250,500,1000,2500,10000").split(",")]
    wheel = os.environ.get("BENCH_LADDER_RATE_ONLY", "0") == "0"
    # certified-gap budget ceiling: rungs above it run RATE-ONLY — a
    # 10k-scenario certified wheel would eat the whole deadline on one
    # rung, and the scale-out signal there is rate + memory watermarks
    # (doc/scaling.md), not another gap certificate
    cert_max = int(os.environ.get("BENCH_LADDER_CERT_MAX", "1000"))
    deadline = float(os.environ.get("BENCH_CHILD_DEADLINE", "0") or 0)
    if not deadline:
        deadline = time.time() + 3600.0
    entries = []
    line = {"metric": "uc_certified_ladder", "unit": "rungs", "value": 0,
            "rungs": entries}

    # --resume (tpusppy.resilience): rung results bank into a state file
    # after each rung, each rung's WHEEL checkpoints into its own dir, and
    # the autotuner's verdicts persist — a killed ladder re-run skips the
    # finished rungs, warm-starts the interrupted rung's wheel from its
    # last checkpoint, and pays no warmup probes again.
    resuming = "--resume" in sys.argv[1:]
    state_dir = os.environ.get(
        "BENCH_RESUME_DIR",
        os.path.join(os.environ.get("BENCH_TRACE_DIR", "bench_results"),
                     "bench_resume"))
    os.makedirs(state_dir, exist_ok=True)
    state_path = os.path.join(state_dir, "ladder_state.json")
    os.environ.setdefault("TPUSPPY_TUNE_CACHE",
                          os.path.join(state_dir, "tune_cache.json"))
    # ONE executable cache shared across rungs (and across --resume
    # re-runs): rung k+1 with an already-seen shape class deserializes
    # its programs instead of recompiling — like the tune cache, the AOT
    # cache survives fresh (non-resume) runs: serialized executables are
    # measurement-neutral warm starts, not results
    if os.environ.get("BENCH_AOT", "1") != "0":
        os.environ.setdefault("TPUSPPY_AOT_CACHE",
                              os.path.join(state_dir, "aot"))
    # resume is EXPLICIT end to end: without --resume a fresh run must be
    # a fresh measurement, so stale rung state (the banked result file
    # AND the rungs' wheel checkpoints) is wiped — a prior run's final
    # checkpoint silently warm-starting a "cold" wheel would bank
    # near-instant time-to-gap numbers as if measured cold.  The tune
    # cache survives (verdicts are measurement-neutral warmup skips).
    os.environ["BENCH_UC_RESUME"] = "1" if resuming else "0"
    done_rungs = {}
    if resuming and os.path.exists(state_path):
        try:
            with open(state_path) as f:
                done_rungs = {int(k): v
                              for k, v in json.load(f)["rungs"].items()}
            log(f"ladder resume: rungs already banked: "
                f"{sorted(done_rungs)}")
        except (OSError, ValueError, KeyError) as e:
            log(f"ladder resume: unreadable state file ({e!r}) — cold run")
    if not resuming:
        import shutil

        for stale in [state_path] + [
                os.path.join(state_dir, d) for d in os.listdir(state_dir)
                if d.startswith("rung_S")]:
            if os.path.isdir(stale):
                shutil.rmtree(stale, ignore_errors=True)
            elif os.path.exists(stale):
                os.remove(stale)

    def _bank_state():
        """Atomic rung-state write (the checkpoint engine's shared
        helper) so a kill can't tear the resume file."""
        from tpusppy.resilience.checkpoint import atomic_write_json

        atomic_write_json(state_path, {
            "rungs": {str(e["S"]): e for e in entries
                      if "error" not in e and "skipped" not in e}})

    def _n_ok():
        """Completed rungs — errored and deadline-skipped ones excluded."""
        return len([e for e in entries
                    if "error" not in e and "skipped" not in e])

    import bench_uc

    for i, S in enumerate(rungs):
        if S in done_rungs:
            m = dict(done_rungs[S], resumed_from_state=True)
            entries.append(m)
            line["value"] = _n_ok()
            emit_partial(line)
            log(f"ladder rung S={S}: banked result reloaded (--resume)")
            continue
        remaining = deadline - time.time()
        if remaining < 120.0:
            entries.extend({"S": s, "skipped": "deadline"}
                           for s in rungs[i:])
            line["value"] = _n_ok()
            emit_partial(line)
            break
        rung_budget = remaining / (len(rungs) - i)
        os.environ["BENCH_UC_SCENS"] = str(S)
        os.environ["BENCH_UC_WHEEL_SCENS"] = str(S)
        # mid-rung continuation: the rung's wheel checkpoints here, and a
        # resumed run warm-starts from the newest snapshot (bench_uc)
        os.environ["BENCH_UC_CKPT_DIR"] = os.path.join(
            state_dir, f"rung_S{S}")
        os.environ["BENCH_CHILD_DEADLINE"] = str(
            time.time() + rung_budget)
        # the per-rung budget must actually bind: uc_metrics' deadline-
        # derived wheel watchdog floors at 600s (teardown margin), which
        # would let one stuck small rung starve the large rungs — an
        # EXPLICIT wheel timeout is only ever shrunk, never floored.  The
        # 30s comfort floor applies only within the rung's own budget (a
        # stuck wheel may never overrun the rung)
        os.environ["BENCH_UC_WHEEL_TIMEOUT"] = str(
            min(rung_budget, max(30.0, 0.7 * rung_budget)))
        log(f"ladder rung S={S}: budget {rung_budget:.0f}s "
            f"({len(rungs) - i} rungs left)")
        rung_wheel = wheel and S <= cert_max
        try:
            m = bench_uc.uc_metrics(
                progress=lambda p, S=S: emit_partial(
                    dict(line, running=dict(p, S=S))),
                wheel=rung_wheel)
            if wheel and not rung_wheel:
                m["rate_only"] = f"S > BENCH_LADDER_CERT_MAX ({cert_max})"
            # keep uc_metrics' ACTUAL scenario count (dataset-truncated
            # rungs must not report the requested S as measured)
            m.setdefault("S", S)
            if m["S"] != S:
                m["S_requested"] = S
            m.update(_mem_fields())
        except Exception as e:   # a failed rung never loses earlier rungs
            log(f"ladder rung S={S} failed: {e!r}")
            m = {"S": S, "error": repr(e), **_mem_fields()}
        # per-rung flight-recorder artifact (no-op when tracing is off;
        # also resets ring + counter window so rungs never bleed)
        d = trace_segment_dump(f"ladder_S{S}")
        if d is not None:
            m["trace"] = {"path": d["path"]}
        entries.append(m)
        line["value"] = _n_ok()
        emit_partial(line)
        try:
            _bank_state()   # the rung is durable the moment it finishes
        except OSError as e:
            log(f"ladder resume state write failed (kept going): {e!r}")
        # drop the rung's device residency before the next shape compiles
        import gc
        import jax
        from tpusppy import spopt as _spopt
        _spopt.clear_device_caches()
        gc.collect()
        jax.clear_caches()
    print(json.dumps(line))
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(0)   # daemon wheel threads abort normal teardown (see below)


def workload():
    if _smoke():
        _apply_smoke_defaults()
    if _tracing_on():
        # arm the flight recorder for the whole child (segments dump +
        # clear the ring as they finish via trace_segment_dump) and the
        # first segment's counter window
        from tpusppy.obs import trace as _obs_trace

        _obs_trace.enable()
        _arm_segment_window()
    if "--ladder" in sys.argv[1:]:
        ladder_workload()
        return
    if os.environ.get("BENCH_UC"):
        import bench_uc
        bench_uc.main()
        return

    import jax
    import numpy as np

    import tpusppy

    if not os.environ.get("BENCH_TRACE"):
        tpusppy.disable_tictoc_output()
    from tpusppy import tune as tuner
    from tpusppy.ir import ScenarioBatch
    from tpusppy.models import farmer
    from tpusppy.parallel import sharded
    from tpusppy.solvers import flops as flops_model
    from tpusppy.solvers import scipy_backend
    from tpusppy.solvers.admm import ADMMSettings

    S = int(os.environ.get("BENCH_SCENS", "1000"))
    iters = int(os.environ.get("BENCH_ITERS", "128"))
    refresh_env = os.environ.get("BENCH_REFRESH")
    chunk_env = os.environ.get("BENCH_CHUNK")
    autotune = os.environ.get("BENCH_AUTOTUNE", "1") != "0"

    platform = jax.devices()[0].platform
    on_tpu = platform not in ("cpu",)
    dtype = "float32" if on_tpu else "float64"
    if dtype == "float64":
        jax.config.update("jax_enable_x64", True)
    eps = 1e-5 if dtype == "float32" else 1e-8
    # polish only on refresh iterations (1 in refresh_every): PH iterates
    # need solver-tolerance accuracy, not vertex-exactness; the periodic
    # polished refresh keeps xbar/W on exact solutions
    settings = ADMMSettings(
        dtype=dtype, eps_abs=eps, eps_rel=eps, max_iter=200, restarts=2,
        scaling_iters=6, polish_passes=1,
    )
    n_dev = len(jax.devices())

    def measure_farmer(mult, n_iters):
        """PH rate for one crops_multiplier; returns a metrics dict.

        Iterations run FUSED — one jitted program per `chunk` PH iterations
        (refresh every `refresh_every` inside it, `sharded.make_ph_fused_step`
        with buffer donation) — so the number is latency-proof: a slow
        remote-dispatch tunnel can no longer collapse the rate 25x (VERDICT
        r4 weak #1).  The (chunk, refresh_every) cadence is MEASURED per
        shape by the warmup autotuner unless pinned via env; the per-step
        path remains as fallback for segmentation-regime shapes.
        """
        refresh_every = max(1, int(refresh_env or "16"))
        st = settings
        prec_env = os.environ.get("BENCH_PRECISION")
        if prec_env:   # operator-pinned sweep precision: no sweep stage
            st = dataclasses.replace(st, sweep_precision=prec_env)
        log(f"platform={platform} S={S} crops_mult={mult} dtype={dtype}")
        names = farmer.scenario_names_creator(S)
        batch = ScenarioBatch.from_problems([
            farmer.scenario_creator(nm, num_scens=S, crops_multiplier=mult)
            for nm in names
        ])
        log(f"batch: {batch.num_scenarios} x ({batch.num_rows} rows, "
            f"{batch.num_vars} vars)")

        mesh = sharded.make_mesh()
        arr = sharded.shard_batch(batch, mesh)
        idx = batch.tree.nonant_indices
        # AOT warm start: SYNCHRONOUSLY deserialize banked executables
        # before any program builds/compiles — the loader is only
        # reliable in a clean XLA state (see tune.prewarm_aot), so the
        # loads are front-loaded here, not overlapped
        import time as _t

        t_seg = _t.perf_counter()
        aot_base = _aot_stats_mark()
        tuner.prewarm_aot()
        refresh, frozen = sharded.make_ph_step_pair(idx, st, mesh)
        state = sharded.init_state(arr, 1.0, st)

        # warmup/compile + Iter0 — under a "compile" span so the cold
        # start (farmer ~3.5s, UC ~17s per BENCH_r05) is visible on the
        # Perfetto timeline; with the AOT executable cache armed
        # (TPUSPPY_AOT_CACHE, the default) a repeat run loads serialized
        # programs here instead of compiling
        from tpusppy.obs import trace as obs_trace

        t0 = time.time()
        with obs_trace.span("compile", "compile.iter0"):
            state, out, _ = refresh(state, arr, 0.0)
            eobj0 = float(np.asarray(out.eobj))
        compile_iter0_s = time.time() - t0
        log(f"compile+iter0: {compile_iter0_s:.1f}s eobj={eobj0:.2f}")

        sweeps = None
        tuned = None
        if autotune and not (chunk_env and refresh_env):
            cands = ((int(refresh_env),) if refresh_env else (8, 16, 32))
            # a pinned BENCH_CHUNK alone still bounds the tuned chunk: the
            # operator's per-dispatch cap holds, the tuner only picks the
            # refresh cadence under it (candidates above the cap can't even
            # probe — keep at least the cap itself as a candidate)
            max_chunk = int(os.environ.get("BENCH_MAX_CHUNK", "256"))
            if chunk_env:
                max_chunk = min(max_chunk, int(chunk_env))
                cands = (tuple(r for r in cands if r <= max_chunk)
                         or (max_chunk,))
            # precision sweep rides the autotuner: fastest certified mode
            # per shape (skipped when the operator pinned BENCH_PRECISION)
            prec_cands = (None if prec_env
                          else ("default", "high"))
            t0 = time.time()
            tuned = tuner.autotune_fused(
                idx, st, arr, state, mesh,
                refresh_candidates=cands, max_chunk=max_chunk,
                precision_candidates=prec_cands)
            if tuned is not None:
                state = tuned.state
                chunk, refresh_every = tuned.chunk, tuned.refresh_every
                sweeps = tuned.sweeps_per_iter
                if tuned.precision != (st.sweep_precision or "highest"):
                    st = dataclasses.replace(
                        st, sweep_precision=tuned.precision)
                log(f"autotune ({time.time() - t0:.1f}s): chunk={chunk} "
                    f"refresh_every={refresh_every} "
                    f"precision={tuned.precision} "
                    f"{tuned.iters_per_sec:.2f} it/s projected; "
                    f"table={tuned.table}")
        if tuned is None:
            chunk_req = int(chunk_env or "64")
            cap = sharded.fused_iteration_cap(arr, st, mesh,
                                              refresh_every)
            chunk = min(chunk_req, cap) // refresh_every * refresh_every

        from tpusppy.obs import metrics as obs_metrics
        from tpusppy.solvers import hostsync

        if chunk >= refresh_every:
            # collect="trace" carries per-iteration conv/eobj/sweeps
            # device-side across the whole window; the measurement loop
            # double-buffers each chunk's trace D2H against the next
            # chunk's compute (sharded.collect_traces) so no fetch ever
            # idles the device
            fused = sharded.make_ph_fused_step(
                idx, st, mesh, chunk=chunk,
                refresh_every=refresh_every, collect="trace")
            t0 = time.time()
            with obs_trace.span("compile", "compile.fused"):
                state, trace = fused(state, arr, 1.0)  # compile+chunk iters
                np.asarray(trace.conv)
            t_first_dispatch = time.time() - t0
            log(f"fused chunk={chunk} compile: {t_first_dispatch:.1f}s")
            n_chunks = max(1, n_iters // chunk)
            t0 = time.time()
            with obs_metrics.window() as mwin, hostsync.track() as sync_tr:
                state, trace = sharded.collect_traces(
                    fused, state, arr, 1.0, n_chunks)
            wall = time.time() - t0
            conv = float(trace.conv[-1])
            measured = n_chunks * chunk
            sweeps = float(trace.iters.mean())
            out = sharded.PHStepOut(*(np.asarray(a)[-1] for a in trace))
            # compile_s HEURISTIC (untraced fallback): first-dispatch wall
            # minus the steady-state dispatch (the measured window's
            # per-chunk mean); noisy CPU runs clamp it to zero — the
            # trace-ring compile spans below replace it when tracing is on
            compile_s = max(0.0, t_first_dispatch - wall / n_chunks)
        else:  # segmentation-regime shapes: per-step dispatches
            t0 = time.time()
            with obs_trace.span("compile", "compile.steps"):
                state, out, factors = refresh(state, arr, 1.0)
                state, out = frozen(state, arr, 1.0, factors)
                np.asarray(out.conv)  # compile the frozen program too
            t_first_dispatch = time.time() - t0
            t0 = time.time()
            with obs_metrics.window() as mwin, hostsync.track() as sync_tr:
                for i in range(n_iters):
                    if i % refresh_every == 0:
                        state, out, factors = refresh(state, arr, 1.0)
                    else:
                        state, out = frozen(state, arr, 1.0, factors)
                conv = float(hostsync.fetch(out.conv))
            wall = time.time() - t0
            measured = n_iters
            sweeps = float(np.asarray(out.iters))
            # two warmup dispatches ran inside the compile window
            # (untraced-fallback heuristic, as above)
            compile_s = max(0.0, t_first_dispatch - 2 * wall / n_iters)
        # satellite fix (the negative-clamped heuristic): when the flight
        # recorder is on, compile_s comes from the explicit aot.compile/
        # aot.load spans — the compile work itself, with the estimator
        # that produced the number LABELED either way
        compile_span = _compile_span_secs(t_seg)
        if compile_span is not None:
            compile_s = compile_span
            compile_estimator = "trace_spans"
        else:
            compile_estimator = "dispatch_heuristic"
        iters_per_sec = measured / wall
        # host-sync accounting, now SOURCED FROM THE METRICS REGISTRY
        # (tpusppy/obs/metrics.py; hostsync feeds it on every fetch): how
        # many decision-path fetches the window performed, and what share
        # of the wall was spent host-BLOCKED in them (overlapped fetches —
        # further device work already queued — excluded).  Same meaning as
        # the legacy thread-local tracker (sync_tr, kept as the scoped
        # cross-check: single-threaded windows agree exactly — the
        # absorption-parity test pins this).  CPU caveat: in-process
        # fetches are ~free here; the counts are the portable signal, the
        # pct becomes meaningful on the remote-tunnel posture.
        host_sync_count = int(mwin.delta("host_sync.count"))
        blocked_secs = mwin.delta("host_sync.blocked_secs")
        dispatch_overhead_pct = round(
            min(100.0, 100.0 * blocked_secs / wall) if wall > 0 else 0.0, 3)
        if host_sync_count != sync_tr.count:
            # registry (process-global) vs tracker (thread-local) can
            # legitimately differ when ANOTHER thread fetched during the
            # window — e.g. a hung wheel spoke the spinner deliberately
            # survives.  Say so loudly, keep the registry number, and
            # NEVER kill the bench over it (the kill-safe contract; the
            # single-threaded parity equality is pinned in test_obs.py)
            log(f"WARNING: host-sync registry window ({host_sync_count}) "
                f"!= thread tracker ({sync_tr.count}) — cross-thread "
                f"fetches during the measured window")
        log(f"tpusppy[m{mult}]: {iters_per_sec:.3f} PH iters/sec "
            f"({measured} iters, conv={conv:.3e}, "
            f"eobj={float(np.asarray(out.eobj)):.2f}, "
            f"sweeps/iter={sweeps:.0f}, "
            f"worst pri={float(np.max(np.asarray(out.pri_res))):.2e})")

        # FLOP-model MFU: measured rate x model flops/iter over nominal
        # peak — the absolute-utilization number (solvers/flops.py; model
        # matmul flops only, so conservative)
        flops_it = flops_model.ph_iteration_flops(
            batch.num_scenarios, batch.num_vars, batch.num_rows,
            sweeps or st.max_iter, refresh_every, st.restarts,
            factor_batch=batch.num_scenarios)
        # MFU peak adjusted to the SWEEP precision (sweeps dominate the
        # iteration): a certified bf16x3 pick both raises the rate and
        # raises the achievable ceiling it is measured against
        mfu, mfu_note = flops_model.mfu_pct(
            iters_per_sec, flops_it, n_dev, jax.devices()[0],
            st.sweep_mode())
        # bank the segment's headline numbers as registry gauges so the
        # flight-recorder report's counter dump carries them too
        obs_metrics.gauge(f"bench.iters_per_sec.m{mult}").set(iters_per_sec)
        if mfu is not None:
            obs_metrics.gauge(f"bench.mfu_pct.m{mult}").set(mfu)

        # Baseline: serial per-scenario LP loop through HiGHS (reference
        # architecture), timed on a sample, EXTRAPOLATED to all S scenarios
        # (and to 32 ideal ranks for vs_baseline_32rank — never measured).
        sample = min(24, S)
        t0 = time.time()
        for s in range(sample):
            scipy_backend.solve_lp(
                batch.c[s], batch.A[s], batch.cl[s], batch.cu[s],
                batch.lb[s], batch.ub[s],
            )
        t_per_scen = (time.time() - t0) / sample
        baseline_iters_per_sec = 1.0 / (t_per_scen * S)
        base32 = baseline_iters_per_sec * RANKS  # IDEAL 32-way scaling
        log(f"baseline[m{mult}] (serial HiGHS loop): "
            f"{t_per_scen * 1e3:.2f} ms/scenario "
            f"=> {baseline_iters_per_sec:.4f} PH iters/sec serial, "
            f"{base32:.4f} at ideal {RANKS}-rank scaling")
        return {
            "value": round(iters_per_sec, 4),
            "chunk": chunk,
            "refresh_every": refresh_every,
            "autotuned": tuned is not None,
            "precision": st.sweep_mode(),
            "sweeps_per_iter": round(sweeps, 1) if sweeps else None,
            "mfu_pct": round(mfu, 2) if mfu is not None else None,
            "mfu_note": mfu_note,
            "host_sync_count": host_sync_count,
            "dispatch_overhead_pct": dispatch_overhead_pct,
            "compile_s": round(compile_s, 2),
            "compile_s_estimator": compile_estimator,
            "compile_iter0_s": round(compile_iter0_s, 2),
            # warm-start evidence (tpusppy/solvers/aot.py): executable
            # cache hits/misses + explicit compile/deserialize seconds
            # accumulated over THIS segment
            "aot": _aot_segment_stats(aot_base),
            "vs_baseline": round(iters_per_sec / baseline_iters_per_sec, 2),
            "vs_baseline_32rank": round(iters_per_sec / base32, 2),
            **_mem_fields(),
        }

    mult = int(os.environ.get("BENCH_CROPS_MULT", "4"))
    m_primary = measure_farmer(mult, iters)
    line = {
        "metric": f"ph_iters_per_sec_farmer{S}",
        "value": m_primary["value"],
        "unit": "iter/s",
        "platform": platform,
        "chunk": m_primary["chunk"],
        "refresh_every": m_primary["refresh_every"],
        "autotuned": m_primary["autotuned"],
        "precision": m_primary["precision"],
        "sweeps_per_iter": m_primary["sweeps_per_iter"],
        "mfu_pct": m_primary["mfu_pct"],
        "mfu_note": m_primary["mfu_note"],
        "host_sync_count": m_primary["host_sync_count"],
        "dispatch_overhead_pct": m_primary["dispatch_overhead_pct"],
        "compile_s": m_primary["compile_s"],
        "compile_s_estimator": m_primary["compile_s_estimator"],
        "compile_iter0_s": m_primary["compile_iter0_s"],
        "aot": m_primary["aot"],
        "vs_baseline": m_primary["vs_baseline"],
        # honest north-star figure: vs IDEAL 32-way scaling of the serial
        # reference architecture (serial/32 accounting, BASELINE.md) —
        # extrapolated, not a measured 32-rank run
        "vs_baseline_32rank": m_primary["vs_baseline_32rank"],
        "peak_rss_mb": m_primary["peak_rss_mb"],
        "device_peak_mb": m_primary["device_peak_mb"],
    }
    dump = trace_segment_dump(f"farmer{S}_m{mult}")
    if dump is not None:
        line["trace"] = dump
    emit_partial(line)   # farmer primary segment banked
    if _tracing_on():
        # the flight-recorder showcase: a small certified farmer wheel
        # whose trace shows hub/spoke/dispatch/host-sync tracks and whose
        # report's gap-vs-wall array ends at the certified gap
        try:
            line["wheel"] = traced_farmer_wheel()
        except Exception as e:
            log(f"traced wheel segment failed: {e!r}")
            line["wheel"] = {"error": repr(e)}
            trace_segment_dump("wheel_failed")   # bank + reset
        emit_partial(line)   # wheel segment banked
    if mult != 1 and not os.environ.get("BENCH_SKIP_CM1"):
        try:  # latency-bound companion shape (VERDICT r4 weak #7)
            line["crops1"] = measure_farmer(1, iters)
            d = trace_segment_dump(f"farmer{S}_m1")
            if d is not None:
                line["crops1"]["trace"] = {"path": d["path"]}
        except Exception as e:
            line["crops1"] = {"error": repr(e)}
            # dump-and-reset even on failure: the partial trace is the
            # diagnostic artifact, and a dirty ring/window would bleed
            # this segment's events into the next segment's report
            trace_segment_dump(f"farmer{S}_m1_failed")
        emit_partial(line)   # crops1 segment banked
    if not os.environ.get("BENCH_SKIP_UC"):
        try:
            import bench_uc
            line["uc"] = bench_uc.uc_metrics(
                progress=lambda m: emit_partial(dict(line, uc=m)))
            d = trace_segment_dump("uc")
            if d is not None:
                line["uc"]["trace"] = {"path": d["path"]}
        except Exception as e:   # UC numbers are additive; never lose farmer
            log(f"uc benchmark failed: {e!r}")
            line["uc"] = {"error": repr(e)}
            trace_segment_dump("uc_failed")   # bank + reset (see crops1)
    if not os.environ.get("BENCH_SKIP_SERVING"):
        try:   # serving SLOs are additive; never lose the rate segments
            line["serving"] = serving_segment()
            d = trace_segment_dump("serving")
            if d is not None:
                line["serving"]["trace"] = {"path": d["path"]}
        except Exception as e:
            log(f"serving segment failed: {e!r}")
            line["serving"] = {"error": repr(e)}
            trace_segment_dump("serving_failed")   # bank + reset
        emit_partial(line)   # serving segment banked
    if not os.environ.get("BENCH_SKIP_INTEGER"):
        try:   # integer-wheel numbers are additive too
            line["integer"] = integer_segment()
        except Exception as e:
            log(f"integer segment failed: {e!r}")
            line["integer"] = {"error": repr(e)}
            trace_segment_dump("integer_failed")   # bank + reset
        emit_partial(line)   # integer segment banked
    print(json.dumps(line))
    sys.stdout.flush()
    sys.stderr.flush()
    # hard-exit: a wheel watchdog timeout leaves a daemon spoke thread
    # mid-device-call, and normal interpreter teardown then aborts the
    # whole process (exit 134, "FATAL: exception not rethrown") AFTER the
    # artifact line was printed — losing the rc=0 the driver records.
    os._exit(0)


if __name__ == "__main__":
    if "--workload" in sys.argv[1:]:
        workload()
    else:
        main()
