"""UC benchmark: integer stochastic unit commitment at scale.

The reference's headline family is 1000-scenario stochastic UC with integer
commitment (paperruns/larger_uc/quartz/1000scen_fw:1-16, examples/uc/
uc_cylinders.py:74-80).  Two numbers:

- ``ph_iters_per_sec``: hub PH iteration rate over the S-scenario integer UC
  (LP-relaxed subproblems — exactly what the PH hub iterates on here), on the
  factorization-amortized sharded path.
- ``wall_s_to_gap``: wall-clock for a full in-process wheel (PH hub +
  Lagrangian outer bound + XhatShuffle integer-diving incumbents) to reach a
  certified MIP gap of ``BENCH_UC_GAP`` (default 1%).

``vs_baseline`` compares the PH iteration rate against the reference
architecture on this host: serial per-scenario HiGHS MIP solves.

Standalone: prints ONE JSON line.  Or imported by bench.py for the combined
line (`uc_metrics()`).
"""

import json
import os
import sys
import time

if os.environ.get("BENCH_TRACE"):
    import faulthandler
    faulthandler.dump_traceback_later(
        120, repeat=True, file=open("/tmp/bench_stacks.log", "w"))

import numpy as np


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def uc_metrics(progress=None, wheel=True):
    """UC metrics dict.  ``progress(partial_dict)`` (optional) is called
    with the rate-metric fields the moment they exist — BEFORE the
    long-running wheel — so a kill during the wheel still leaves the
    rate/MFU numbers in the artifact (bench.py relays them as a partial
    JSON line).  ``wheel=False`` skips the certified-gap wheel entirely
    (the ladder's rate-only smoke posture)."""
    import jax

    import tpusppy

    if not os.environ.get("BENCH_TRACE"):
        tpusppy.disable_tictoc_output()
    from tpusppy.ir import ScenarioBatch
    from tpusppy.parallel import sharded
    from tpusppy.solvers import flops as flops_model
    from tpusppy.solvers import scipy_backend
    from tpusppy.solvers import segmented as segmented_solvers
    from tpusppy.solvers.admm import ADMMSettings
    from tpusppy.solvers.sparse import SparseA

    # Default: the reference-shape scaled UC (30 gens x 24 h with min-up/
    # down, startup ramps, reserves — models/uc.py, shared-A engine),
    # matching examples/uc + paperruns/larger_uc in the reference.
    # BENCH_UC_MODEL=lite selects the small self-contained family.
    # Platform-matched defaults: the TPU run benches the reference's OWN
    # wind-ladder dataset when mounted (85-gen WECC-240; its LP relaxation
    # is ~0.07% tight, so 1% certification rides LP-quality bounds); the
    # CPU fallback degrades to the small self-contained family — the
    # 1-core fallback host cannot spin a 5-cylinder wheel on a 20+ gen
    # fleet inside the watchdog, and the artifact's job there is to prove
    # the certified pipeline end-to-end, flagged degraded.
    _wind_dir = os.environ.get(
        "BENCH_UC_DATA",
        "/root/reference/paperruns/larger_uc/1000scenarios_wind")
    platform = jax.devices()[0].platform
    if "BENCH_UC_MODEL" in os.environ:
        model_name = os.environ["BENCH_UC_MODEL"]
    elif platform == "cpu":
        model_name = "lite"
    elif os.path.isdir(_wind_dir):
        model_name = "data"
    else:
        model_name = "full"
    if model_name == "lite":
        from tpusppy.models import uc_lite as uc_model
        default_gens, default_horizon = 5, 12
    elif model_name == "data":
        # the reference's ACTUAL WECC-240 datasets (85 gens; demand
        # uncertainty in *scenarios_r1, wind ladders in paperruns) —
        # data-comparable benchmarking when the reference tree is mounted
        from tpusppy.models import uc_data as uc_model
        default_gens, default_horizon = 85, 48
    else:
        from tpusppy.models import uc as uc_model
        default_gens, default_horizon = 30, 24

    # CPU fallback (tunnel down): degrade scenario count AND problem shape
    # so the fallback artifact lands within its timeout — flagged in the
    # output (degraded_cpu_run + the model name in the metric)
    degraded = platform == "cpu" and not os.environ.get("BENCH_UC_SCENS")
    S = int(os.environ.get("BENCH_UC_SCENS", "16" if degraded else "1000"))
    gens = int(os.environ.get(
        "BENCH_UC_GENS",
        str(min(5, default_gens) if degraded else default_gens)))
    horizon = int(os.environ.get(
        "BENCH_UC_HORIZON",
        str(min(12, default_horizon) if degraded
            else min(24, default_horizon))))
    # rate-metric iteration count: the real-data family runs ~40 s per PH
    # iteration at S=1000 (n=16008) — 8 iterations measure the steady rate
    # without blowing the parent's workload timeout
    iters = int(os.environ.get(
        "BENCH_UC_ITERS",
        "4" if degraded else ("8" if model_name == "data" else "30")))
    refresh_every = max(1, int(os.environ.get("BENCH_REFRESH", "16")))
    gap_target = float(os.environ.get("BENCH_UC_GAP", "0.01"))
    dtype = "float32" if platform != "cpu" else "float64"
    if dtype == "float64":
        jax.config.update("jax_enable_x64", True)
    eps = 1e-5 if dtype == "float32" else 1e-8
    # sweep_plateau: reference-scale UC batches park at a ~1e-1 worst /
    # 1e-2 median scaled residual regardless of budget (the frozen
    # 200-sweep loop never reaches eps and every extra sweep is waste);
    # the in-loop plateau exit stops the while_loop after 2 consecutive
    # non-improving windows.  The window ladder was measured end-to-end
    # on real WECC data (rate at S=1000 / wheel certification):
    #   w32: 0.124 it/s, 0.198% in 279.7 s   (med floor 8.0e-3)
    #   w16: 0.193 it/s, 0.198% in 233.6 s   (med floor 9.4e-3)
    #   w8:  0.316 it/s, 0.236% in 226.3 s   (med floor 1.4e-2)
    # Per-iteration PH progress (conv at a fixed iteration count) is
    # IDENTICAL across the ladder — the extra sweeps were pure waste —
    # and certification quality is unchanged vs the 1% target, so 8 is
    # the default; the artifact records the window used.
    # solve_refine=1: with the block/Woodbury structured KKT the x-update
    # preconditioner is built from EXACT small block inverses, and one
    # refinement pass holds the same residual floor as two (A/B at S=256:
    # identical median floor, 0.05% eobj drift, 1.22x faster sweeps);
    # refine=0 measurably corrupts the trajectory (16% eobj drift).
    plateau_window = int(os.environ.get("BENCH_PLATEAU_WINDOW", "8"))
    settings = ADMMSettings(
        dtype=dtype, eps_abs=eps, eps_rel=eps, max_iter=200, restarts=2,
        scaling_iters=6, polish_passes=1, solve_refine=1,
        sweep_plateau_rtol=0.05, sweep_plateau_window=plateau_window,
    )
    if os.environ.get("BENCH_PRECISION"):
        # operator-pinned frozen-sweep precision (the farmer bench's
        # autotuner sweeps it; the UC rate path takes the pin directly)
        import dataclasses
        settings = dataclasses.replace(
            settings, sweep_precision=os.environ["BENCH_PRECISION"])

    if model_name == "data":
        data_dir = _wind_dir
        if os.environ.get("BENCH_UC_GENS"):
            log("uc[data]: fleet comes from the dataset; "
                "BENCH_UC_GENS ignored (use BENCH_UC_HORIZON/SCENS)")
        names = uc_model.scenario_names_creator(data_dir=data_dir)
        if len(names) > S:
            names = names[:S]
        S = len(names)
        kw = {"data_dir": data_dir, "horizon": horizon,
              "relax_integers": False, "num_scens": S}
    else:
        kw = {"num_gens": gens, "horizon": horizon, "num_scens": S,
              "relax_integers": False}
        names = uc_model.scenario_names_creator(S)
    batch = ScenarioBatch.from_problems(
        [uc_model.scenario_creator(nm, **kw) for nm in names])
    log(f"uc[{model_name}] batch: {batch.num_scenarios} x "
        f"({batch.num_rows} rows, {batch.num_vars} vars, "
        f"{int(batch.is_int.sum())} ints, "
        f"shared_A={batch.A_shared is not None})")

    # ---- metric 1: hub PH iteration rate ---------------------------------
    from bench import _aot_segment_stats, _aot_stats_mark, _compile_span_secs
    from tpusppy.obs.sysmem import sample as _mem_sample

    from tpusppy import tune as tuner

    mesh = sharded.make_mesh()
    arr = sharded.shard_batch(batch, mesh)
    # AOT warm start (tpusppy/solvers/aot.py): SYNCHRONOUSLY deserialize
    # banked executables before anything compiles — the loader needs a
    # clean XLA state (see tune.prewarm_aot), so no overlap by design
    t_seg = time.perf_counter()
    aot_base = _aot_stats_mark()
    tuner.prewarm_aot()
    refresh, frozen = sharded.make_ph_step_pair(
        batch.tree.nonant_indices, settings, mesh)
    state = sharded.init_state(arr, 1.0, settings)
    from tpusppy.obs import trace as obs_trace

    t0 = time.time()
    with obs_trace.span("compile", "compile.iter0"):
        state, out, _ = refresh(state, arr, 0.0)
        np.asarray(out.conv)
    compile_iter0_s = time.time() - t0
    log(f"uc compile+iter0: {compile_iter0_s:.1f}s "
        f"eobj={float(np.asarray(out.eobj)):.2f}")
    t0 = time.time()
    with obs_trace.span("compile", "compile.steps"):
        state, out, factors = refresh(state, arr, 1.0)
        state, out = frozen(state, arr, 1.0, factors)
        np.asarray(out.conv)
    t_first_dispatch = time.time() - t0

    t0 = time.time()
    for i in range(iters):
        if i % refresh_every == 0:
            state, out, factors = refresh(state, arr, 1.0)
        else:
            state, out = frozen(state, arr, 1.0, factors)
    conv = float(np.asarray(out.conv))
    iters_per_sec = iters / (time.time() - t0)
    sweeps = float(np.asarray(out.iters))
    log(f"uc PH: {iters_per_sec:.3f} iters/sec (conv={conv:.3e}, "
        f"sweeps/iter={sweeps:.0f})")

    # FLOP-model MFU for the UC rate segment (solvers/flops.py): shared-A
    # engine => one factorization per refresh; the SparseA engine's model
    # flops are the dense accounting scaled by the same measured factor
    # the dispatch model uses
    sparse_f = (segmented_solvers.SPARSE_DISPATCH_FACTOR
                if isinstance(arr.A, SparseA) else 1.0)
    flops_it = flops_model.ph_iteration_flops(
        batch.num_scenarios, batch.num_vars, batch.num_rows, sweeps,
        refresh_every, settings.restarts, factor_batch=1,
        sparse_factor=sparse_f)
    mfu, mfu_note = flops_model.mfu_pct(
        iters_per_sec, flops_it, len(mesh.devices.flat), jax.devices()[0],
        settings.sweep_mode())

    # FULL-reference-horizon submetric (horizon 48, n=32016 at S=1000):
    # the shape the dense engine could never fit on one chip (4.1 GB
    # Kinv + 3.2 GB dense A); the sparse/block-Woodbury engine runs it —
    # record the rate as capability evidence.  TPU real-data runs only.
    h48_rate = None
    if (model_name == "data" and platform != "cpu"
            and horizon < 48 and not os.environ.get("BENCH_UC_NO_H48")):
        try:
            kw48 = dict(kw, horizon=48)
            b48 = ScenarioBatch.from_problems(
                [uc_model.scenario_creator(nm, **kw48) for nm in names])
            arr48 = sharded.shard_batch(b48, mesh)
            r48, f48 = sharded.make_ph_step_pair(
                b48.tree.nonant_indices, settings, mesh)
            st48 = sharded.init_state(arr48, 1.0, settings)
            st48, o48, _ = r48(st48, arr48, 0.0)
            np.asarray(o48.conv)
            st48, o48, fac48 = r48(st48, arr48, 1.0)
            np.asarray(o48.conv)
            t0 = time.time()
            n48 = 3
            for _ in range(n48):
                st48, o48 = f48(st48, arr48, 1.0, fac48)
            np.asarray(o48.conv)
            h48_rate = n48 / (time.time() - t0)
            log(f"uc h48 (n={b48.num_vars}): {h48_rate:.4f} iters/sec")
            del arr48, st48, o48, fac48, r48, f48, b48
        except Exception as e:          # capability metric is additive
            log(f"uc h48 probe failed: {e!r}")

    # baseline: serial per-scenario HiGHS MIP loop (reference architecture),
    # sampled ADAPTIVELY — reference-scale UC MIPs cost tens of seconds each
    # on this host, so the sample stops once ~90s of baseline evidence is
    # in.  The cap is 24 (not 8): per-scenario MIP difficulty varies ~2x
    # across the wind scenarios and an 8-sample mean wobbled the headline
    # ratio run-to-run; more samples inside the same budget tighten it
    sample_cap = min(24, S)
    budget_s = float(os.environ.get("BENCH_UC_BASELINE_BUDGET", "90"))
    t0 = time.time()
    sample = 0
    for s in range(sample_cap):
        scipy_backend.solve_lp(
            batch.c[s], batch.A[s], batch.cl[s], batch.cu[s],
            batch.lb[s], batch.ub[s], is_int=batch.is_int,
            mip_rel_gap=1e-4, time_limit=60,
        )
        sample += 1
        if time.time() - t0 > budget_s:
            break
    from bench import RANKS
    t_mip = (time.time() - t0) / sample
    base_ips = 1.0 / (t_mip * S)
    base32 = base_ips * RANKS  # IDEAL rank scaling (BASELINE.md accounting)
    log(f"uc baseline (serial HiGHS MIP): {t_mip*1e3:.1f} ms/scenario "
        f"=> {base_ips:.4f} iters/sec serial, {base32:.4f} at ideal "
        f"{RANKS}-rank scaling")

    # compile_s: the trace-ring compile spans when the recorder is on
    # (exact — aot.compile/aot.load time nothing but the compile work),
    # else the first-dispatch heuristic, labeled either way (bench.py's
    # _compile_span_secs; the negative-clamp satellite fix)
    compile_span = _compile_span_secs(t_seg)
    if compile_span is not None:
        compile_s, compile_estimator = compile_span, "trace_spans"
    else:
        compile_s = max(0.0, t_first_dispatch
                        - 2.0 / max(iters_per_sec, 1e-9))
        compile_estimator = "dispatch_heuristic"
    rate_fields = {
        "model": model_name,
        "ph_iters_per_sec": round(iters_per_sec, 4),
        # cold-start observability (ROADMAP item 3): explicit compile-
        # span seconds when traced, the first-dispatch heuristic
        # otherwise, plus the raw compile+iter0 wall the r5 artifacts
        # quote (~17s UC) and the executable-cache evidence
        "compile_s": round(compile_s, 2),
        "compile_s_estimator": compile_estimator,
        "compile_iter0_s": round(compile_iter0_s, 2),
        "aot": _aot_segment_stats(aot_base),
        "precision": settings.sweep_mode(),
        "plateau_window": plateau_window,
        "sweeps_per_iter": round(sweeps, 1),
        "mfu_pct": round(mfu, 2) if mfu is not None else None,
        "mfu_note": mfu_note,
        "h48_ph_iters_per_sec": (round(h48_rate, 4) if h48_rate else None),
        "vs_baseline": round(iters_per_sec / base_ips, 2),
        "vs_baseline_32rank": round(iters_per_sec / base32, 2),
        "S": S, "degraded_cpu_run": degraded,
        # memory watermarks (tpusppy.obs.sysmem; doc/scaling.md): host
        # peak RSS is a process high-water mark, device peak reads 0 on
        # XLA:CPU (no backend memory stats)
        **_mem_sample(),
    }
    if progress is not None:
        # bank the rate/MFU segment NOW: the wheel below can run for
        # thousands of seconds and a kill there must not lose these
        progress(dict(rate_fields, wall_s_to_gap=None, gap_pct=None,
                      gap_target_pct=gap_target * 100, certified=False,
                      wheel_pending=True))
    if not wheel:
        return dict(rate_fields, wall_s_to_gap=None, gap_pct=None,
                    gap_target_pct=gap_target * 100, certified=False,
                    wheel_skipped=True)

    # free the rate-metric's device residency before the wheel: the S=1000
    # arrays + factors (~6 GB at reference shape) plus the compiled S=1000
    # executables (~0.5 GB code each) otherwise coexist with the wheel's
    # per-cylinder factors and OOM the chip
    del arr, state, out, factors, refresh, frozen
    import gc

    from tpusppy import spopt as _spopt
    _spopt.clear_device_caches()
    gc.collect()
    jax.clear_caches()

    # ---- metric 2: wall-clock to certified MIP gap (full wheel) ----------
    from tpusppy.cylinders import (
        LagrangianOuterBound, PHHub, SlamMaxHeuristic, XhatRestrictedEF,
        XhatShuffleInnerBound, XhatXbarInnerBound)
    from tpusppy.opt.ph import PH
    from tpusppy.phbase import PHBase
    from tpusppy.spin_the_wheel import WheelSpinner
    from tpusppy.xhat_eval import Xhat_Eval

    # FULL-SCALE wheel by default (r5): the donor-dual outer bound,
    # repair-based certified evaluation, shared batch cache and the
    # trimmed full-scale cylinder set certify the complete 1000-scenario
    # reference UC on one chip (r5 runs: 0.56% <= 1% in ~1725 s to gap).
    # The artifact reports wheel_S honestly either way.
    S_wheel = min(S, int(os.environ.get(
        "BENCH_UC_WHEEL_SCENS", str(S) if degraded else "1000")))
    if S_wheel != S:
        names = names[:S_wheel]
        kw = dict(kw, num_scens=S_wheel)

    # trimmed adaptive budget: UC prox/LP batches plateau around 1e-3
    # primal regardless of sweeps, so a deep budget only burns time — the
    # rescue-tolerance ladder + host rescue covers the tail, and frozen
    # iterations accept at the ladder (spopt._solve_amortized).  The
    # non-degraded (TPU) wheel runs the budget the S=64 certification was
    # validated with.
    if degraded:
        so = {"dtype": dtype, "eps_abs": eps, "eps_rel": eps,
              "max_iter": 300, "restarts": 3, "scaling_iters": 10,
              "polish_passes": 1}
    else:
        so = {"dtype": dtype, "eps_abs": eps, "eps_rel": eps,
              "max_iter": 100, "restarts": 2, "scaling_iters": 6,
              "polish_passes": 1, "solve_refine": 1,
              "sweep_plateau_rtol": 0.05,
              "sweep_plateau_window": plateau_window}

    # host-MILP budgets scale with problem size: the degraded CPU shape
    # solves scenario MIPs in ~0.5-2 s (full lifts + dual ascent are
    # affordable); the reference 30x24 shape costs 20-120 s per MIP, so
    # lifts are partial there (still certified — any completed subset is)
    lift_budget = float(os.environ.get("BENCH_UC_LIFT_S",
                                       "45" if degraded else "120"))
    ascent_budget = float(os.environ.get("BENCH_UC_ASCENT_S",
                                         "90" if degraded else "120"))
    # full-S wheel (wheel_S == S == 1000): everything is ~15x the S=64
    # device work on the same single chip + single host core, so the
    # budget goes to what certification actually needs — the real
    # WECC-240 LP relaxation is 0.07-0.12% tight, so LP-dual Lagrangian
    # bounds (lift every 4th pass, not every pass) + ONE good incumbent
    # close 1% without the per-iteration MILP machinery
    full_scale = S_wheel >= 512
    lift_every = int(os.environ.get("BENCH_UC_LIFT_EVERY",
                                    "4" if full_scale else "1"))
    if full_scale:
        lift_budget = float(os.environ.get("BENCH_UC_LIFT_S", "60"))
    # inner-bound cylinders: with the model repair (uc_data.repair_fn) the
    # certified incumbent quality IS the eval solve quality (repair prices
    # the leftover slack at VOLL) — deeper budget, no plateau shortcuts
    # (measured at the fixture shape: 200/2 -> +4.7% over exact, 1000/4 ->
    # +0.07%).  The dict is reused by the spoke configs below.
    so_eval = dict(so, max_iter=1000, restarts=4, sweep_plateau_rtol=0.0)

    trace_prefix = os.environ.get("BENCH_UC_TRACE_PREFIX")

    def okw(iters=60):
        return {
            # one 1000-scenario batch build costs minutes of the 1-core
            # host; all cylinders share it (read-only by contract)
            "options": {"batch_cache": True,
                        **({"trace_prefix": trace_prefix}
                           if trace_prefix else {}),
                        "defaultPHrho": 500.0, "PHIterLimit": iters,
                        "convthresh": -1.0, "xhat_dive_rounds": 16,
                        "solver_options": so,
                        "xhat_looper_options": {"scen_limit": 3},
                        "xhat_xbar_options": {
                            "thresholds": [0.5, 0.4, 0.35, 0.3, 0.25]
                            if degraded else [0.5, 0.35]},
                        # every=2, NOT 1 (A/B'd at full scale): every=1
                        # lands the FIRST restricted-EF candidate one hub
                        # iteration earlier but it is a WORSE incumbent —
                        # the wheel certified 0.899% (thin margin) vs the
                        # 0.34% the one-iteration-later consensus gives,
                        # with no wall-clock win on an idle host
                        "xhat_ef_options": {"every": 2, "ksub": 6,
                                            "time_limit": 120.0},
                        "lagrangian_milp_lift": {"budget_s": lift_budget,
                                                 "every": lift_every,
                                                 "mip_rel_gap": 1e-4,
                                                 "time_limit": 30.0},
                        # full scale: exact donor duals transferred
                        # batch-wide (spopt.dual_donor_bounds) — the
                        # certified outer bound no longer rides S=1000
                        # plateaued ADMM duals
                        **({"lagrangian_dual_donors": {
                            "k": 24, "budget_s": 120.0,
                            "time_limit": 20.0},
                            # the S=1000 batched solve starves the wheel
                            # and its plateaued duals lose to donors
                            # anyway — donors ARE the outer bound here
                            "lagrangian_skip_solve": True}
                           if full_scale else {}),
                        # full scale: no subgradient ascent at teardown —
                        # each of its steps is a batched S-solve (the exact
                        # cost lagrangian_skip_solve removes), and the
                        # donor pass at the final W is the polish
                        **({} if full_scale else {
                            "lagrangian_milp_ascent": {
                                "steps": 10, "budget_s": ascent_budget,
                                "mip_rel_gap": 1e-3, "time_limit": 30.0,
                                "skip_if_gap_at": gap_target}})},
            "all_scenario_names": names,
            "scenario_creator": uc_model.scenario_creator,
            "scenario_creator_kwargs": kw,
        }

    hub_iters = int(os.environ.get(
        "BENCH_UC_PH_ITERS", "16" if full_scale else "40"))
    # resilience (tpusppy.resilience): with BENCH_UC_CKPT_DIR set (the
    # ladder's --resume path wires it per rung) the wheel checkpoints
    # asynchronously and a re-run warm-starts from the newest snapshot —
    # a SIGKILLed rung loses at most one checkpoint cadence, not the rung
    hub_opts = {"rel_gap": gap_target}
    wheel_resume = None
    ckpt_dir = os.environ.get("BENCH_UC_CKPT_DIR")
    if ckpt_dir:
        hub_opts.update(
            checkpoint_dir=ckpt_dir,
            checkpoint_every_secs=float(
                os.environ.get("BENCH_UC_CKPT_SECS", "60")),
            checkpoint_every_iters=int(
                os.environ.get("BENCH_UC_CKPT_ITERS", "0")) or None)
        # resuming is EXPLICIT (BENCH_UC_RESUME, set by bench.py's
        # --resume): a stale checkpoint must never silently warm-start a
        # run that claims to be a cold measurement
        if os.environ.get("BENCH_UC_RESUME") == "1":
            from tpusppy.resilience import checkpoint as _ckpt
            if _ckpt.latest(ckpt_dir) is not None:
                wheel_resume = ckpt_dir
                log(f"uc wheel: resuming from checkpoint dir {ckpt_dir}")
    hub_dict = {
        "hub_class": PHHub,
        "hub_kwargs": {"options": hub_opts},
        "opt_class": PH,
        "opt_kwargs": okw(hub_iters),
    }
    def okw_eval(**extra):
        o = okw()
        o["options"] = dict(o["options"], solver_options=so_eval, **extra)
        return o

    spokes = [
        {"spoke_class": LagrangianOuterBound, "opt_class": PHBase,
         "opt_kwargs": okw()},
        {"spoke_class": XhatRestrictedEF, "opt_class": Xhat_Eval,
         "opt_kwargs": okw_eval()},
        # donor-MILP shuffle: exact scenario-MIP first stages as candidates
        # (the reference's donor semantics) — lands integer-feasible
        # incumbents within the first hub iterations instead of waiting for
        # consensus to crystallize for the restricted EF
        {"spoke_class": XhatShuffleInnerBound, "opt_class": Xhat_Eval,
         "opt_kwargs": okw_eval(
             xhat_looper_options={"scen_limit": 2, "donor_milp": True,
                                  "donor_milp_time": 60.0})},
    ]
    if not full_scale:
        # the threshold-ladder xbar evaluator earns its keep at S=64 but
        # each ladder entry costs a full cold S-batch solve: at S=1000 it
        # starves the chip (and its candidates carry plateaued LP
        # scenarios — the restricted EF is what lands incumbents there)
        spokes.insert(1, {"spoke_class": XhatXbarInnerBound,
                          "opt_class": Xhat_Eval, "opt_kwargs": okw_eval()})
    if degraded:
        # the small CPU family benefits from donor cycling + slam too
        spokes += [
            {"spoke_class": XhatShuffleInnerBound, "opt_class": Xhat_Eval,
             "opt_kwargs": okw()},
            {"spoke_class": SlamMaxHeuristic, "opt_class": Xhat_Eval,
             "opt_kwargs": okw()},
        ]
    # watchdog: the wheel must never block the bench line (daemon thread +
    # bounded join; on timeout the farmer metric still prints)
    import threading

    # measured on chip: the real-data S=64 wheel certifies ~0.15% around
    # 610-1370 s (in-wheel compiles + when the restricted-EF incumbent
    # lands are both high-variance), so the watchdog stretches to whatever
    # budget remains before the parent's deadline (minus teardown margin)
    # rather than a fixed number.
    explicit = "BENCH_UC_WHEEL_TIMEOUT" in os.environ
    budget = float(os.environ.get("BENCH_UC_WHEEL_TIMEOUT", "1500"))
    deadline = float(os.environ.get("BENCH_CHILD_DEADLINE", "0") or 0)
    if deadline:
        # grow OR shrink to what actually remains (the parent SIGKILLs the
        # child at its deadline, losing the whole JSON line); an explicit
        # BENCH_UC_WHEEL_TIMEOUT is only ever shrunk, never overridden up
        remaining = max(600.0, deadline - time.time() - 300.0)
        budget = min(budget, remaining) if explicit else remaining
        log(f"uc wheel watchdog: {budget:.0f}s (deadline-derived)")
    result = {}

    def _spin():
        t0 = time.time()
        try:
            ws = WheelSpinner(hub_dict, spokes, resume=wheel_resume).spin()
        except Exception as e:       # error != timeout; surface which
            result["error"] = repr(e)
            return
        total = time.time() - t0
        # wall to the hub's gap-based termination (construction + hub
        # loop); the extra teardown minutes (final spoke passes) are
        # reported separately as wall_s_total
        result["wall"] = float(getattr(ws, "gap_wall_secs", total))
        result["wall_total"] = total
        result["ib"] = ws.BestInnerBound
        result["ob"] = ws.BestOuterBound

    th = threading.Thread(target=_spin, daemon=True)
    th.start()
    th.join(timeout=budget)
    if "wall" not in result:
        why = result.get("error", f"timeout after {budget:.0f}s")
        log(f"uc wheel: {why}")
        out = dict(
            rate_fields, wheel_S=S_wheel,
            wall_s_to_gap=None, gap_pct=None,
            gap_target_pct=gap_target * 100, certified=False,
        )
        if "error" in result:
            out["wheel_error"] = result["error"]
        else:
            out["wheel_timeout_s"] = budget
        return out
    wall, ib, ob = result["wall"], result["ib"], result["ob"]
    wall_total = result.get("wall_total", wall)
    gap = (ib - ob) / max(abs(ib), 1e-9) if np.isfinite(ib) else float("inf")
    # sanity: certified bounds can cross only by tolerance dust; a materially
    # negative gap means an INVALID bound slipped in — never report it as a
    # certification (this caught the primal trivial-bound bug in r5)
    crossed = np.isfinite(gap) and gap < -1e-6
    log(f"uc wheel: {wall:.1f}s inner={ib:.2f} outer={ob:.2f} "
        f"gap={gap*100:.2f}%" + (" CROSSED-BOUNDS" if crossed else ""))

    return dict(
        rate_fields, wheel_S=S_wheel,
        wall_s_to_gap=round(wall, 1),
        wall_s_total=round(wall_total, 1),
        gap_pct=round(gap * 100, 3),
        gap_target_pct=gap_target * 100,
        certified=bool(np.isfinite(ib) and np.isfinite(ob)
                       and not crossed and gap <= gap_target + 1e-9),
        **({"crossed_bounds": True} if crossed else {}),
        **_mem_sample(),        # wheel-phase memory high-water
    )


def main():
    m = uc_metrics()
    print(json.dumps({
        "metric": f"ph_iters_per_sec_uc{m['S']}",
        "value": m["ph_iters_per_sec"],
        "unit": "iter/s",
        "vs_baseline": m["vs_baseline"],
        "uc": m,
    }))
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(0)      # see bench.py: daemon wheel threads abort teardown


if __name__ == "__main__":
    main()
