"""Aircond (multistage production/inventory) cylinders driver.

Behavioral analogue of the reference's ``examples/aircond/aircond_cylinders.py``:
multistage PH hub + lagrangian / lagranger / fwph / xhatshuffle spokes over a
branching-factor tree (the reference's MPI smoke test drives exactly this
combination, straight_tests.py).  Example::

    python aircond_cylinders.py --branching-factors "3 2" \
        --max-iterations 30 --default-rho 1.0 --rel-gap 0.02 \
        --lagrangian --xhatshuffle
"""

from tpusppy.models import aircond
from tpusppy.spin_the_wheel import WheelSpinner
from tpusppy.utils import cfg_vanilla as vanilla
from tpusppy.utils import config

write_solution = True


def _parse_args():
    cfg = config.Config()
    cfg.multistage()   # includes popular_args
    cfg.num_scens_optional()   # multistage: scenario count = prod(BFs)
    cfg.two_sided_args()
    cfg.ph_args()
    cfg.fwph_args()
    cfg.lagrangian_args()
    cfg.lagranger_args()
    cfg.xhatshuffle_args()
    aircond.inparser_adder(cfg)
    cfg.parse_command_line("aircond_cylinders")
    return cfg


def main():
    cfg = _parse_args()
    if cfg.default_rho is None:
        raise RuntimeError("specify --default-rho")
    if cfg.branching_factors is None:
        raise RuntimeError("specify --branching-factors (e.g. \"3 2\")")
    bf = [int(f) for f in cfg.branching_factors]
    num_scens = 1
    for f in bf:
        num_scens *= f
    all_scenario_names = aircond.scenario_names_creator(num_scens)
    kw = aircond.kw_creator(cfg)
    kw["branching_factors"] = bf
    beans = dict(
        cfg=cfg, scenario_creator=aircond.scenario_creator,
        scenario_denouement=aircond.scenario_denouement,
        all_scenario_names=all_scenario_names,
        scenario_creator_kwargs=kw,
    )
    hub_dict = vanilla.ph_hub(**beans)

    spokes = []
    if cfg.fwph:
        spokes.append(vanilla.fwph_spoke(**beans))
    if cfg.lagrangian:
        spokes.append(vanilla.lagrangian_spoke(**beans))
    if cfg.lagranger:
        spokes.append(vanilla.lagranger_spoke(**beans))
    if cfg.xhatshuffle:
        spokes.append(vanilla.xhatshuffle_spoke(**beans))

    ws = WheelSpinner(hub_dict, spokes)
    ws.spin()
    if write_solution:
        ws.write_first_stage_solution("aircond_first_stage.csv")
    return ws


if __name__ == "__main__":
    main()
