"""Solution writers for the USAR example (reference:
examples/usar/write_solutions.py + plot.py, Pyomo/matplotlib based).

The reference plots rescue-team walks (geographic) and Gantt charts from a
solved Pyomo model.  Here the same figures are drawn from the flat solution
vector of one scenario (`tpusppy.models.usar` variable layout); writers
degrade to CSV when matplotlib is unavailable.
"""

import csv
import os

import numpy as np

from tpusppy.models import usar


def _var_index(kw):
    """(a, dd, sd, st, ita) index arrays for the flat layout."""
    T, D, N = kw["time_horizon"], kw["num_depots"], kw["num_households"]
    i = 0
    a = np.arange(i, i + D); i += D
    dd = np.arange(i, i + T * D * N).reshape(T, D, N); i += T * D * N
    sd = np.arange(i, i + T * N * N).reshape(T, N, N); i += T * N * N
    st = np.arange(i, i + T * N).reshape(T, N); i += T * N
    ita = np.arange(i, i + T * T * N).reshape(T, T, N); i += T * T * N
    return a, dd, sd, st, ita


def walks_writer(walks_dir, scen_name, x, kw):
    """Geographic plot of team movements for one scenario solution ``x``
    (reference plot.plot_walks); CSV of arcs when matplotlib is missing."""
    os.makedirs(walks_dir, exist_ok=True)
    a, dd, sd, _, _ = _var_index(kw)
    depot_coords, site_coords = usar.generate_coords(**kw)
    x = np.asarray(x)
    arcs = []
    for (t, d, s) in zip(*np.nonzero(np.round(x[dd]) > 0)):
        arcs.append(("depot", int(d), int(s), int(t)))
    for (t, s1, s2) in zip(*np.nonzero(np.round(x[sd]) > 0)):
        arcs.append(("site", int(s1), int(s2), int(t)))
    try:
        import matplotlib
        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
    except Exception:
        with open(os.path.join(walks_dir, scen_name + ".csv"), "w",
                  newline="") as f:
            w = csv.writer(f)
            w.writerow(["kind", "from", "to", "time"])
            w.writerows(arcs)
        return
    fig, ax = plt.subplots()
    dx, dy = (np.array([c[i] for c in depot_coords]) for i in (0, 1)) \
        if depot_coords else (np.array([]), np.array([]))
    sx, sy = (np.array([c[i] for c in site_coords]) for i in (0, 1)) \
        if site_coords else (np.array([]), np.array([]))
    ax.scatter(dx, dy, marker="s", label="depots")
    ax.scatter(sx, sy, marker="o", label="sites")
    for kind, frm, to, t in arcs:
        p0 = depot_coords[frm] if kind == "depot" else site_coords[frm]
        p1 = site_coords[to]
        ax.annotate("", xy=p1, xytext=p0,
                    arrowprops={"arrowstyle": "->", "alpha": 0.6})
    ax.set_title(f"USAR walks — {scen_name}")
    ax.legend()
    fig.savefig(os.path.join(walks_dir, scen_name + ".pdf"))
    plt.close(fig)


def gantt_writer(gantt_dir, scen_name, x, kw):
    """Gantt chart of rescues (reference plot.plot_gantt): for each site,
    the interval [arrival, arrival + rescue_time)."""
    os.makedirs(gantt_dir, exist_ok=True)
    _, _, _, _, ita = _var_index(kw)
    T = kw["time_horizon"]
    rescue = kw["constant_rescue_time"]
    x = np.asarray(x)
    bars = [(int(s), int(t), rescue)
            for (t, s) in zip(*np.nonzero(np.round(x[ita][:, 0, :]) > 0))]
    try:
        import matplotlib
        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
    except Exception:
        with open(os.path.join(gantt_dir, scen_name + ".csv"), "w",
                  newline="") as f:
            w = csv.writer(f)
            w.writerow(["site", "arrival", "duration"])
            w.writerows(bars)
        return
    fig, ax = plt.subplots()
    for s, t, dur in bars:
        ax.barh(s, dur, left=t)
    ax.set_xlim(0, T)
    ax.set_xlabel("time step")
    ax.set_ylabel("site")
    ax.set_title(f"USAR rescues — {scen_name}")
    fig.savefig(os.path.join(gantt_dir, scen_name + ".pdf"))
    plt.close(fig)
