"""USAR extensive form CLI (reference: examples/usar/extensive_form.py).

Solves the urban search and rescue stochastic MILP as one extensive form
(HiGHS MIP validation path) and writes walk/Gantt plots per scenario.

    python usar_ef.py --num-scens 3 --time-horizon 6 --num-depots 3 \
        --num-active-depots 2 --num-households 4 --output-dir /tmp/usar
"""

import os
import sys

from tpusppy.ef import solve_ef
from tpusppy.ir import ScenarioBatch
from tpusppy.models import usar
from tpusppy.utils.config import Config

from write_solutions import gantt_writer, walks_writer


def _parse(args):
    cfg = Config()
    usar.inparser_adder(cfg)
    cfg.add_to_config("output_dir", description="directory for output files",
                      domain=str, default=".")
    cfg.parse_command_line("usar_ef", args)
    return cfg


def main(args=None):
    cfg = _parse(args)
    kw = usar.kw_creator(cfg)
    names = usar.scenario_names_creator(cfg.num_scens)
    batch = ScenarioBatch.from_problems(
        [usar.scenario_creator(nm, **kw) for nm in names])
    obj, xs = solve_ef(batch, solver="highs")
    # the IR minimizes the negated lives count (usar.py module docstring)
    print(f"USAR EF objective {obj:.4f} => expected lives saved "
          f"{-obj:.4f}")
    out = cfg.output_dir
    for s, nm in enumerate(names):
        walks_writer(os.path.join(out, "walks"), nm, xs[s], kw)
        gantt_writer(os.path.join(out, "gantts"), nm, xs[s], kw)
    return obj


if __name__ == "__main__":
    main(sys.argv[1:])
