"""USAR wheel CLI (reference: examples/usar/wheel_spinner.py).

PH (or APH with --run-async) hub over the USAR MILP with the reference's
supported spoke set.

    python usar_cylinders.py --num-scens 3 --default-rho 1 \
        --max-iterations 10 --rel-gap 0.01 --lagrangian --xhatshuffle \
        --output-dir /tmp/usar
"""

import os
import sys

from tpusppy.models import usar
from tpusppy.spin_the_wheel import WheelSpinner
from tpusppy.utils import cfg_vanilla as vanilla
from tpusppy.utils.config import Config

# the reference driver's spoke set (wheel_spinner.py:22-31) plus
# xhatrestrictedef — USAR's depot cardinality row makes naive rounding of
# the (often symmetric, fractional) hub consensus infeasible; the
# relax-and-fix restricted EF is the incumbent mechanism that respects it
SUPPORTED_SPOKES = (
    "fwph",
    "lagrangian",
    "lagranger",
    "xhatlooper",
    "xhatshuffle",
    "xhatlshaped",
    "slammax",
    "slammin",
    "xhatrestrictedef",
)


def _parse(args):
    cfg = Config()
    cfg.num_scens_required()
    cfg.popular_args()
    cfg.two_sided_args()
    cfg.ph_args()
    cfg.aph_args()
    cfg.add_to_config("run_async",
                      description="run APH instead of PH as the hub",
                      domain=bool, default=False)
    for spoke in SUPPORTED_SPOKES:
        getattr(cfg, spoke + "_args")()
    usar.inparser_adder(cfg)
    cfg.add_to_config("output_dir", description="directory for output files",
                      domain=str, default=".")
    cfg.parse_command_line("usar_cylinders", args)
    return cfg


def main(args=None):
    cfg = _parse(args)
    kw = usar.kw_creator(cfg)
    names = usar.scenario_names_creator(cfg.num_scens)
    hub_fn = vanilla.aph_hub if cfg.run_async else vanilla.ph_hub
    hub = hub_fn(cfg, usar.scenario_creator, all_scenario_names=names,
                 scenario_creator_kwargs=kw,
                 scenario_denouement=usar.scenario_denouement)
    spokes = []
    for spoke in SUPPORTED_SPOKES:
        if getattr(cfg, spoke, False):
            fn = getattr(vanilla, spoke + "_spoke")
            spokes.append(fn(cfg, usar.scenario_creator,
                             all_scenario_names=names,
                             scenario_creator_kwargs=kw,
                             scenario_denouement=usar.scenario_denouement))
    # USAR's second stage is all-binary scheduling: incumbent evaluation
    # uses exact per-scenario host MILPs (solver-trivial at this size)
    # instead of rounding dives, which wedge on the coupled binaries
    for d in [hub] + spokes:
        d["opt_kwargs"].setdefault("options", {})[
            "xhat_integer_strategy"] = "milp"
    ws = WheelSpinner(hub, spokes).spin()
    print(f"BestInnerBound={ws.BestInnerBound:.4f} "
          f"BestOuterBound={ws.BestOuterBound:.4f} "
          f"(lives saved >= {-ws.BestInnerBound:.4f})")
    out = cfg.output_dir
    os.makedirs(out, exist_ok=True)
    ws.write_first_stage_solution(
        os.path.join(out, "usar_first_stage.csv"))
    ws.write_tree_solution(os.path.join(out, "usar_tree"))
    return ws


if __name__ == "__main__":
    main(sys.argv[1:])
