"""SIZES cylinders driver with rho setter + fixer.

Analogue of ``examples/sizes/sizes_cylinders.py``.  Example::

    python sizes_cylinders.py --num-scens 3 --max-iterations 100 \
        --default-rho 0.01 --rel-gap 0.01 --lagrangian --xhatshuffle --fixer
"""

from tpusppy.extensions.fixer import Fixer
from tpusppy.models import sizes
from tpusppy.spin_the_wheel import WheelSpinner
from tpusppy.utils import cfg_vanilla as vanilla
from tpusppy.utils import config


def _parse_args():
    cfg = config.Config()
    cfg.num_scens_required()
    cfg.popular_args()
    cfg.two_sided_args()
    cfg.ph_args()
    cfg.fixer_args()
    cfg.fwph_args()
    cfg.lagrangian_args()
    cfg.xhatshuffle_args()
    cfg.parse_command_line("sizes_cylinders")
    return cfg


def main():
    cfg = _parse_args()
    names = sizes.scenario_names_creator(cfg.num_scens)
    kwargs = {"scenario_count": cfg.num_scens}
    beans = dict(
        cfg=cfg, scenario_creator=sizes.scenario_creator,
        scenario_denouement=sizes.scenario_denouement,
        all_scenario_names=names, scenario_creator_kwargs=kwargs,
    )
    hub_dict = vanilla.ph_hub(
        rho_setter=lambda batch: sizes._rho_setter(batch), **beans)
    if cfg.fixer:
        hub_dict["opt_kwargs"]["options"]["fixeroptions"] = {
            "verbose": cfg.verbose,
            "boundtol": cfg.fixer_tol,
            "id_fix_list_fct": sizes.id_fix_list_fct,
        }
        vanilla.extension_adder(hub_dict, Fixer)

    spokes = []
    if cfg.fwph:
        spokes.append(vanilla.fwph_spoke(**beans))
    if cfg.lagrangian:
        spokes.append(vanilla.lagrangian_spoke(**beans))
    if cfg.xhatshuffle:
        spokes.append(vanilla.xhatshuffle_spoke(**beans))
    ws = WheelSpinner(hub_dict, spokes)
    ws.spin()
    ws.write_first_stage_solution("sizes_first_stage.csv")
    return ws


if __name__ == "__main__":
    main()
