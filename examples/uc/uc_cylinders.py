"""Stochastic unit-commitment cylinders driver (UC-lite family).

The analogue of ``examples/uc/uc_cylinders.py``: PH hub + bound spokes on the
self-contained UC-lite model (the reference reads Egret/Prescient data files;
see tpusppy/models/uc_lite.py).  Example::

    python uc_cylinders.py --num-scens 10 --uc-num-gens 10 --uc-horizon 24 \
        --max-iterations 50 --default-rho 100 --rel-gap 0.005 \
        --lagrangian --xhatshuffle
"""

from tpusppy.models import uc_lite
from tpusppy.spin_the_wheel import WheelSpinner
from tpusppy.utils import cfg_vanilla as vanilla
from tpusppy.utils import config


def _parse_args():
    cfg = config.Config()
    cfg.num_scens_required()
    cfg.popular_args()
    cfg.two_sided_args()
    cfg.ph_args()
    cfg.fixer_args()
    cfg.fwph_args()
    cfg.lagrangian_args()
    cfg.xhatshuffle_args()
    uc_lite.inparser_adder(cfg)
    cfg.parse_command_line("uc_cylinders")
    return cfg


def main():
    cfg = _parse_args()
    kwargs = uc_lite.kw_creator(cfg)
    names = uc_lite.scenario_names_creator(cfg.num_scens)
    beans = dict(
        cfg=cfg, scenario_creator=uc_lite.scenario_creator,
        scenario_denouement=uc_lite.scenario_denouement,
        all_scenario_names=names, scenario_creator_kwargs=kwargs,
    )
    hub_dict = vanilla.ph_hub(**beans)
    spokes = []
    if cfg.fwph:
        spokes.append(vanilla.fwph_spoke(**beans))
    if cfg.lagrangian:
        spokes.append(vanilla.lagrangian_spoke(**beans))
    if cfg.xhatshuffle:
        spokes.append(vanilla.xhatshuffle_spoke(**beans))
    ws = WheelSpinner(hub_dict, spokes)
    ws.spin()
    ws.write_first_stage_solution("uc_first_stage.csv")
    return ws


if __name__ == "__main__":
    main()
