"""Stochastic unit-commitment cylinders driver.

The analogue of ``examples/uc/uc_cylinders.py``: PH hub + bound spokes on
either UC family — ``--uc-model full`` (default: tpusppy/models/uc.py, the
reference-shape fleet with min-up/down, ramps and reserves on the shared-A
engine) or ``--uc-model lite`` (the small self-contained uc_lite).  The
reference reads Egret/Prescient data files; both families here are seeded
self-contained.  Example::

    python uc_cylinders.py --num-scens 10 --uc-num-gens 10 --uc-horizon 24 \
        --max-iterations 50 --default-rho 100 --rel-gap 0.005 \
        --lagrangian --xhatshuffle
"""

from tpusppy.spin_the_wheel import WheelSpinner
from tpusppy.utils import cfg_vanilla as vanilla
from tpusppy.utils import config


def _parse_args():
    cfg = config.Config()
    cfg.num_scens_required()
    cfg.popular_args()
    cfg.two_sided_args()
    cfg.ph_args()
    cfg.fixer_args()
    cfg.fwph_args()
    cfg.lagrangian_args()
    cfg.xhatshuffle_args()
    cfg.add_to_config("uc_model",
                      "UC family: 'full' (reference-shape), 'lite', or "
                      "'data' (real reference datasets via --uc-data)",
                      str, "full")
    cfg.add_to_config("uc_data",
                      "reference UC scenario directory (uc_model='data'): "
                      "examples/uc/*scenarios_r1 or a paperruns wind ladder",
                      str, None)
    # both families share the uc_num_gens / uc_horizon arg names; register
    # WITHOUT defaults so each family's kw_creator fallbacks (30/24 full,
    # 5/12 lite) apply when the flags are not passed
    cfg.add_to_config("uc_num_gens", "number of generators", int, None)
    cfg.add_to_config("uc_horizon", "scheduling horizon (hours)", int, None)
    cfg.add_to_config("uc_wind_frac",
                      "mean wind share of peak thermal capacity (full model)",
                      float, 0.25)
    # full-scale certified-bound machinery (what the S=1000 bench wheel
    # runs): donor-dual Lagrangian bounds with the batched solve skipped,
    # shared batch cache across cylinders
    cfg.add_to_config("dual_donors",
                      "Lagrangian outer bounds from k host-exact donor "
                      "duals transferred batch-wide (0 = off); at full "
                      "scale also skips the spoke's batched solve",
                      int, 0)
    cfg.parse_command_line("uc_cylinders")
    if cfg.uc_model not in ("full", "lite", "data"):
        raise ValueError(f"--uc-model must be 'full', 'lite' or 'data', "
                         f"got {cfg.uc_model!r}")
    if cfg.uc_model == "data" and not cfg.uc_data:
        raise ValueError("--uc-model data requires --uc-data <directory>")
    return cfg


def main():
    cfg = _parse_args()
    if cfg.uc_model == "lite":
        from tpusppy.models import uc_lite as uc_model
    elif cfg.uc_model == "data":
        from tpusppy.models import uc_data as uc_model
    else:
        from tpusppy.models import uc as uc_model
    kwargs = uc_model.kw_creator(cfg)
    # drop unset shared args so each family's own defaults apply
    kwargs = {k: v for k, v in kwargs.items() if v is not None}
    if cfg.uc_model == "data":
        names = uc_model.scenario_names_creator(
            cfg.num_scens, data_dir=cfg.uc_data)
        if len(names) < cfg.num_scens:
            print(f"uc_cylinders: --num-scens {cfg.num_scens} truncated to "
                  f"the {len(names)} scenarios in {cfg.uc_data}")
    else:
        names = uc_model.scenario_names_creator(cfg.num_scens)
    beans = dict(
        cfg=cfg, scenario_creator=uc_model.scenario_creator,
        scenario_denouement=uc_model.scenario_denouement,
        all_scenario_names=names, scenario_creator_kwargs=kwargs,
    )
    hub_dict = vanilla.ph_hub(**beans)
    spokes = []
    if cfg.fwph:
        spokes.append(vanilla.fwph_spoke(**beans))
    if cfg.lagrangian:
        spokes.append(vanilla.lagrangian_spoke(**beans))
    if cfg.xhatshuffle:
        spokes.append(vanilla.xhatshuffle_spoke(**beans))
    if cfg.dual_donors:
        # the full-scale posture (bench_uc S=1000): one shared batch,
        # donor-dual Lagrangian with no batched solve in the spoke
        extra = {"batch_cache": True,
                 "lagrangian_dual_donors": {"k": int(cfg.dual_donors),
                                            "budget_s": 120.0,
                                            "time_limit": 20.0},
                 "lagrangian_skip_solve": True,
                 # integer UC candidates need exact donor first stages —
                 # rounding dives wedge on commitment clocks (bench_uc
                 # posture); repair-based evaluation prices them
                 "xhat_looper_options": {"scen_limit": 2,
                                         "donor_milp": True,
                                         "donor_milp_time": 60.0}}
        for d in [hub_dict] + spokes:
            d["opt_kwargs"]["options"].update(extra)
    ws = WheelSpinner(hub_dict, spokes)
    ws.spin()
    ws.write_first_stage_solution("uc_first_stage.csv")
    return ws


if __name__ == "__main__":
    main()
