"""Solve the farmer extensive form directly.

Port of ``examples/farmer/farmer_ef.py`` usage: golden 3-scenario objective
is -108390.  Example::

    python farmer_ef.py --num-scens 3 --EF-solver-name admm
"""

from tpusppy.ef import solve_ef
from tpusppy.ir import ScenarioBatch
from tpusppy.models import farmer
from tpusppy.utils import config


def main():
    cfg = config.Config()
    cfg.EF2()
    cfg.add_to_config("crops_mult", "crops multiplier", int, 1)
    cfg.parse_command_line("farmer_ef")
    n = cfg.num_scens or 3
    batch = ScenarioBatch.from_problems([
        farmer.scenario_creator(nm, num_scens=n,
                                crops_multiplier=cfg.crops_mult)
        for nm in farmer.scenario_names_creator(n)
    ])
    solver = cfg.EF_solver_name or "admm"
    obj, x = solve_ef(batch, solver=solver)
    print(f"EF objective: {obj}")
    root = x[0][batch.tree.nonant_indices[batch.tree.nonant_stage == 1]]
    print(f"first-stage solution: {root}")
    return obj


if __name__ == "__main__":
    main()
