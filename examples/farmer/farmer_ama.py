"""Declarative farmer run through the Amalgamator (reference:
examples/farmer/farmer_ama.py): the model module's protocol (scenario_creator,
scenario_names_creator, inparser_adder, kw_creator) is turned into an EF
solve or a wheel spin from the command line alone.  Examples::

    python farmer_ama.py --num-scens 3 --EF-solver-name admm
    python farmer_ama.py --num-scens 3 --max-iterations 20 \
        --default-rho 1.0 --rel-gap 0.005 --lagrangian --xhatshuffle
"""

import sys

from tpusppy.utils.amalgamator import from_module
from tpusppy.utils.config import Config


def main(args=None):
    args = sys.argv[1:] if args is None else args
    cfg = Config()
    if any(a.startswith("--EF-solver-name") or a == "--EF" for a in args):
        cfg.add_and_assign("EF_2stage", "2stage EF", bool, None, True)
    else:
        cfg.add_and_assign("2stage", "2stage", bool, None, True)
        spokes = [s[2:] for s in args
                  if s in ("--lagrangian", "--xhatshuffle", "--fwph",
                           "--lagranger", "--xhatlooper")]
        cfg.quick_assign("cylinders", list, ["ph"] + spokes)
    ama = from_module("tpusppy.models.farmer", cfg, args=args)
    ama.run()
    if getattr(ama, "EF_Obj", None) is not None:
        print(f"EF objective: {ama.EF_Obj:.2f}")
    else:
        print(f"inner bound: {ama.best_inner_bound:.2f}  "
              f"outer bound: {ama.best_outer_bound:.2f}")
    return ama


if __name__ == "__main__":
    main()
