"""Farmer with cross-scenario cuts (reference: examples/farmer/cs_farmer.py):
a CrossScenarioHub whose spoke solves per-scenario relaxations to generate
optimality cuts that steer the hub's subproblems and feed a cutting-plane
outer bound.  Example::

    python cs_farmer.py --num-scens 3 --max-iterations 30 \
        --default-rho 1.0 --rel-gap 0.005 --xhatshuffle
"""

import sys

from tpusppy.models import farmer
from tpusppy.spin_the_wheel import WheelSpinner
from tpusppy.utils import cfg_vanilla as vanilla
from tpusppy.utils.config import Config


def main(args=None):
    cfg = Config()
    cfg.popular_args()
    cfg.num_scens_required()
    cfg.two_sided_args()
    cfg.ph_args()
    cfg.cross_scenario_cuts_args()
    cfg.xhatshuffle_args()
    cfg.parse_command_line("cs_farmer",
                           sys.argv[1:] if args is None else args)
    cfg.cross_scenario_cuts = True
    names = farmer.scenario_names_creator(cfg.num_scens)
    kw = {"num_scens": cfg.num_scens}
    beans = dict(cfg=cfg, scenario_creator=farmer.scenario_creator,
                 all_scenario_names=names, scenario_creator_kwargs=kw)
    hub_dict = vanilla.ph_hub(**beans)
    vanilla.add_cross_scenario_cuts(hub_dict, cfg)
    spokes = [vanilla.cross_scenario_cuts_spoke(**beans)]
    if cfg.xhatshuffle:
        spokes.append(vanilla.xhatshuffle_spoke(**beans))
    ws = WheelSpinner(hub_dict, spokes).spin()
    print(f"BestInnerBound={ws.BestInnerBound:.4f} "
          f"BestOuterBound={ws.BestOuterBound:.4f}")
    return ws


if __name__ == "__main__":
    main()
