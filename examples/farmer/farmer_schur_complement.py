"""Farmer through the batched Schur-complement interior point (reference:
examples/farmer/schur_complement.py over parapint).  Example::

    python farmer_schur_complement.py --num-scens 10
"""

import argparse

from tpusppy.models import farmer
from tpusppy.opt.sc import SchurComplement


def main(args=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-scens", type=int, default=3)
    ns = ap.parse_args(args)
    names = farmer.scenario_names_creator(ns.num_scens)
    sc = SchurComplement({}, names, farmer.scenario_creator,
                         scenario_creator_kwargs={"num_scens": ns.num_scens})
    obj = sc.solve()
    print(f"objective: {obj:.2f}  (crossover={sc.crossover_applied}, "
          f"ipm iters={sc.ipm_result.iters})")
    return sc


if __name__ == "__main__":
    main()
