"""Farmer with an L-shaped (Benders) HUB and an xhatshuffle spoke
(reference: examples/farmer/farmer_lshapedhub.py).  Example::

    python farmer_lshapedhub.py --num-scens 3 --max-iterations 40 \
        --rel-gap 0.001 --xhatshuffle
"""

import sys

from tpusppy.models import farmer
from tpusppy.spin_the_wheel import WheelSpinner
from tpusppy.utils import cfg_vanilla as vanilla
from tpusppy.utils.config import Config


def main(args=None):
    cfg = Config()
    cfg.popular_args()
    cfg.num_scens_required()
    cfg.two_sided_args()
    cfg.xhatshuffle_args()
    cfg.parse_command_line("farmer_lshapedhub",
                           sys.argv[1:] if args is None else args)
    names = farmer.scenario_names_creator(cfg.num_scens)
    kw = {"num_scens": cfg.num_scens}
    beans = dict(cfg=cfg, scenario_creator=farmer.scenario_creator,
                 all_scenario_names=names, scenario_creator_kwargs=kw)
    hub_dict = vanilla.lshaped_hub(**beans)
    spokes = []
    if cfg.xhatshuffle:
        spokes.append(vanilla.xhatshuffle_spoke(**beans))
    ws = WheelSpinner(hub_dict, spokes).spin()
    print(f"BestInnerBound={ws.BestInnerBound:.4f} "
          f"BestOuterBound={ws.BestOuterBound:.4f}")
    return ws


if __name__ == "__main__":
    main()
