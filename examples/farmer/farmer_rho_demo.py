"""Gradient-based rho demo on farmer (reference:
examples/farmer/farmer_rho_demo.py over pynumero): run a few PH iterations,
compute gradient-based costs (jax.grad replaces pynumero) and
denominator-based rho suggestions, write them to CSV, and re-run PH with
the suggested rho.  Example::

    python farmer_rho_demo.py --num-scens 3
"""

import argparse
import os
import tempfile

from tpusppy.models import farmer
from tpusppy.opt.ph import PH
from tpusppy.utils.find_rho import Find_Rho, Set_Rho
from tpusppy.utils.gradient import Find_Grad


def main(args=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-scens", type=int, default=3)
    ap.add_argument("--iters", type=int, default=10)
    ns = ap.parse_args(args)
    names = farmer.scenario_names_creator(ns.num_scens)
    kw = {"num_scens": ns.num_scens}

    ph = PH({"defaultPHrho": 1.0, "PHIterLimit": ns.iters,
             "convthresh": -1.0}, names, farmer.scenario_creator,
            scenario_creator_kwargs=kw)
    ph.ph_main()

    grads = Find_Grad(ph, {}).compute_grad()   # jax.grad replaces pynumero
    print("per-scenario objective gradients at the nonants (first rows):")
    for s in range(min(2, grads.shape[0])):
        print(f"  {names[s]}: {grads[s]}")

    fr = Find_Rho(ph, {"order_stat": 0.5})
    rho = fr.compute_rho()
    print("suggested rho:", rho)

    from tpusppy.utils.rho_utils import rhos_to_csv

    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "rho.csv")
        rhos_to_csv(rho, path)
        setter = Set_Rho({"rho_path": path}).rho_setter
        ph2 = PH({"defaultPHrho": 1.0, "PHIterLimit": ns.iters,
                  "convthresh": -1.0}, names, farmer.scenario_creator,
                 scenario_creator_kwargs=kw, rho_setter=setter)
        conv, eobj, _ = ph2.ph_main()
        print(f"PH with suggested rho: conv={conv:.3e} eobj={eobj:.2f}")


if __name__ == "__main__":
    main()
