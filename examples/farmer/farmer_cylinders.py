"""General example driver for farmer with cylinders.

Behavioral port of ``examples/farmer/farmer_cylinders.py`` from the
reference: a Config-driven CLI assembling a PH (or APH) hub plus any of the
fwph / lagrangian / lagranger / xhatlooper / xhatshuffle spokes, spun by the
WheelSpinner.  Example::

    python farmer_cylinders.py --num-scens 3 --max-iterations 50 \
        --default-rho 1.0 --rel-gap 0.001 --lagrangian --xhatshuffle
"""

from tpusppy.convergers.norm_rho_converger import NormRhoConverger
from tpusppy.convergers.primal_dual_converger import PrimalDualConverger
from tpusppy.extensions.norm_rho_updater import NormRhoUpdater
from tpusppy.models import farmer
from tpusppy.spin_the_wheel import WheelSpinner
from tpusppy.utils import cfg_vanilla as vanilla
from tpusppy.utils import config

write_solution = True


def _parse_args():
    cfg = config.Config()
    cfg.num_scens_required()
    cfg.popular_args()
    cfg.two_sided_args()
    cfg.ph_args()
    cfg.aph_args()
    cfg.xhatlooper_args()
    cfg.fwph_args()
    cfg.lagrangian_args()
    cfg.lagranger_args()
    cfg.xhatshuffle_args()
    cfg.converger_args()
    cfg.wxbar_read_write_args()
    cfg.tracking_args()
    cfg.add_to_config("crops_mult",
                      "There will be 3x this many crops (default 1)",
                      int, 1)
    cfg.add_to_config("use_norm_rho_updater",
                      "Use the norm rho updater extension", bool, False)
    cfg.add_to_config("run_async",
                      "Run with async projective hedging instead of PH",
                      bool, False)
    cfg.parse_command_line("farmer_cylinders")
    return cfg


def main():
    cfg = _parse_args()
    num_scen = cfg.num_scens
    if cfg.default_rho is None:
        raise RuntimeError("specify --default-rho")

    if cfg.use_norm_rho_converger:
        if not cfg.use_norm_rho_updater:
            raise RuntimeError(
                "--use-norm-rho-converger requires --use-norm-rho-updater")
        ph_converger = NormRhoConverger
    elif cfg.primal_dual_converger:
        ph_converger = PrimalDualConverger
    else:
        ph_converger = None

    scenario_creator = farmer.scenario_creator
    scenario_denouement = farmer.scenario_denouement
    all_scenario_names = farmer.scenario_names_creator(num_scen)
    scenario_creator_kwargs = {
        "use_integer": False,
        "crops_multiplier": cfg.crops_mult,
        "num_scens": num_scen,
    }

    beans = dict(
        cfg=cfg, scenario_creator=scenario_creator,
        scenario_denouement=scenario_denouement,
        all_scenario_names=all_scenario_names,
        scenario_creator_kwargs=scenario_creator_kwargs,
    )
    if cfg.run_async:
        hub_dict = vanilla.aph_hub(ph_converger=ph_converger, **beans)
    else:
        hub_dict = vanilla.ph_hub(ph_converger=ph_converger, **beans)
    if cfg.use_norm_rho_updater:
        vanilla.extension_adder(hub_dict, NormRhoUpdater)

    list_of_spoke_dict = []
    if cfg.fwph:
        list_of_spoke_dict.append(vanilla.fwph_spoke(**beans))
    if cfg.lagrangian:
        list_of_spoke_dict.append(vanilla.lagrangian_spoke(**beans))
    if cfg.lagranger:
        list_of_spoke_dict.append(vanilla.lagranger_spoke(**beans))
    if cfg.xhatlooper:
        list_of_spoke_dict.append(vanilla.xhatlooper_spoke(**beans))
    if cfg.xhatshuffle:
        list_of_spoke_dict.append(vanilla.xhatshuffle_spoke(**beans))

    ws = WheelSpinner(hub_dict, list_of_spoke_dict)
    ws.spin()

    if write_solution:
        ws.write_first_stage_solution("farmer_first_stage.csv")
        ws.write_first_stage_solution("farmer_first_stage.npy")
        ws.write_tree_solution("farmer_full_solution")
    return ws


if __name__ == "__main__":
    main()
