"""Sequential-sampling confidence interval on farmer (reference:
examples/farmer/farmer_seqsampling.py): Bayraksan-Pierre-Louis stopping to a
fixed-width CI around the candidate's optimality gap.  Example::

    python farmer_seqsampling.py --BPL-eps 2000 --max-iterations 8
"""

import argparse

from tpusppy.confidence_intervals.seqsampling import (
    SeqSampling, xhat_generator_farmer)
from tpusppy.utils.config import Config


def main(args=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--BPL-eps", type=float, default=2000.0)
    ap.add_argument("--BPL-c0", type=int, default=12)
    ap.add_argument("--max-iterations", type=int, default=8)
    ns = ap.parse_args(args)
    cfg = Config()
    cfg.quick_assign("solver_name", str, "admm")
    cfg.quick_assign("BPL_eps", float, ns.BPL_eps)
    cfg.quick_assign("BPL_c0", int, ns.BPL_c0)
    cfg.quick_assign("xhat_gen_kwargs", dict, {"crops_multiplier": 1})
    ss = SeqSampling("tpusppy.models.farmer", xhat_generator_farmer, cfg,
                     stochastic_sampling=False, stopping_criterion="BPL",
                     solving_type="EF_2stage")
    res = ss.run(maxit=ns.max_iterations)
    print(f"T={res['T']}  CI=[{res['CI'][0]:.2f}, {res['CI'][1]:.2f}]  "
          f"candidate={res['Candidate_solution']['ROOT']}")
    return res


if __name__ == "__main__":
    main()
