"""Network design cylinders driver.

Behavioral analogue of the reference's ``examples/netdes/netdes_cylinders.py``:
PH hub + fwph / lagrangian / xhat spokes + cross-scenario cuts (the family the
reference uses to showcase them).  Example::

    python netdes_cylinders.py --num-scens 4 --max-iterations 30 \
        --default-rho 1.0 --rel-gap 0.02 --lagrangian --xhatshuffle \
        --cross-scenario-cuts
"""

from tpusppy.models import netdes
from tpusppy.spin_the_wheel import WheelSpinner
from tpusppy.utils import cfg_vanilla as vanilla
from tpusppy.utils import config

write_solution = True


def _parse_args():
    cfg = config.Config()
    cfg.num_scens_required()
    cfg.popular_args()
    cfg.two_sided_args()
    cfg.ph_args()
    cfg.fwph_args()
    cfg.lagrangian_args()
    cfg.xhatlooper_args()
    cfg.xhatshuffle_args()
    cfg.slammax_args()
    cfg.cross_scenario_cuts_args()
    netdes.inparser_adder(cfg)
    # the batched integer wheel (doc/integer.md): true-integer arcs +
    # hub-side in-wheel certification with the rounding sweep and the
    # gap-ranked host escalation tier — spokes become optional
    cfg.add_to_config("integer", "solve the TRUE integer instance "
                      "(relax_integers=False) with in-wheel integer "
                      "bounds", bool, False)
    cfg.parse_command_line("netdes_cylinders")
    return cfg


def main():
    cfg = _parse_args()
    if cfg.default_rho is None:
        raise RuntimeError("specify --default-rho")
    all_scenario_names = netdes.scenario_names_creator(cfg.num_scens)
    kw = netdes.kw_creator(cfg)
    if cfg.integer:
        kw["relax_integers"] = False
    beans = dict(
        cfg=cfg, scenario_creator=netdes.scenario_creator,
        scenario_denouement=netdes.scenario_denouement,
        all_scenario_names=all_scenario_names,
        scenario_creator_kwargs=kw,
    )
    hub_dict = vanilla.ph_hub(**beans)
    if cfg.integer:
        hub_dict["opt_kwargs"]["options"].update(
            in_wheel_bounds=True, integer_escalation_budget_s=20.0)
    if cfg.cross_scenario_cuts:
        vanilla.add_cross_scenario_cuts(hub_dict, cfg)

    spokes = []
    if cfg.fwph:
        spokes.append(vanilla.fwph_spoke(**beans))
    if cfg.lagrangian:
        spokes.append(vanilla.lagrangian_spoke(**beans))
    if cfg.xhatlooper:
        spokes.append(vanilla.xhatlooper_spoke(**beans))
    if cfg.xhatshuffle:
        spokes.append(vanilla.xhatshuffle_spoke(**beans))
    if cfg.slammax:
        spokes.append(vanilla.slammax_spoke(**beans))
    if cfg.cross_scenario_cuts:
        spokes.append(vanilla.cross_scenario_cuts_spoke(**beans))

    ws = WheelSpinner(hub_dict, spokes)
    ws.spin()
    if write_solution:
        ws.write_first_stage_solution("netdes_first_stage.csv")
    return ws


if __name__ == "__main__":
    main()
