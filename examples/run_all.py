"""Run ALL example drivers end-to-end; collect failures in `badguys`.

The analogue of the reference's ``examples/run_all.py`` (the de-facto
regression harness per examples/AAAReadme.txt / SURVEY §4): every family's
cylinder driver runs at small scale, exit status asserted.  ``afew.py`` is
the quick subset.  Usage::

    python run_all.py            # everything
    python run_all.py nouc       # skip the UC family (reference flag parity)
"""

from __future__ import annotations

import os
import subprocess
import sys

EXDIR = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, EXDIR)
from _harness_env import child_env  # noqa: E402

RUNS = [
    ("farmer/farmer_ef.py",
     ["--num-scens", "3", "--EF-solver-name", "admm"]),
    ("farmer/farmer_ef.py",
     ["--num-scens", "3", "--EF-solver-name", "highs"]),
    ("farmer/farmer_cylinders.py",
     ["--num-scens", "3", "--max-iterations", "20", "--default-rho", "1.0",
      "--rel-gap", "0.01", "--lagrangian", "--xhatshuffle"]),
    ("farmer/farmer_cylinders.py",
     ["--num-scens", "3", "--max-iterations", "10", "--default-rho", "1.0",
      "--rel-gap", "0.02", "--fwph", "--lagranger", "--xhatlooper"]),
    ("sizes/sizes_cylinders.py",
     ["--num-scens", "3", "--max-iterations", "30", "--default-rho", "0.01",
      "--rel-gap", "0.05", "--lagrangian", "--xhatshuffle"]),
    ("sslp/sslp_cylinders.py",
     ["--num-scens", "4", "--max-iterations", "20", "--default-rho", "5.0",
      "--rel-gap", "0.05", "--lagrangian", "--xhatshuffle"]),
    ("netdes/netdes_cylinders.py",
     ["--num-scens", "3", "--max-iterations", "20", "--default-rho", "1.0",
      "--rel-gap", "0.05", "--lagrangian", "--xhatshuffle"]),
    ("netdes/netdes_cylinders.py",
     ["--num-scens", "3", "--max-iterations", "12", "--default-rho", "1.0",
      "--rel-gap", "0.05", "--cross-scenario-cuts", "--xhatshuffle"]),
    ("hydro/hydro_pysp.py", []),
    ("hydro/hydro_cylinders.py",
     ["--branching-factors", "3 3", "--max-iterations", "20",
      "--default-rho", "1.0", "--rel-gap", "0.02", "--lagrangian",
      "--xhatshuffle"]),
    ("aircond/aircond_cylinders.py",
     ["--branching-factors", "3 2", "--max-iterations", "10",
      "--default-rho", "1.0", "--rel-gap", "0.05", "--lagrangian",
      "--xhatshuffle"]),
    ("uc/uc_cylinders.py",
     ["--num-scens", "4", "--uc-num-gens", "3", "--uc-horizon", "6",
      "--max-iterations", "20", "--default-rho", "50.0",
      "--rel-gap", "0.02", "--lagrangian", "--xhatshuffle"]),
    ("battery/battery_cylinders.py",
     ["--num-scens", "6", "--battery-lam", "0.1", "--battery-use-lp",
      "--max-iterations", "8", "--default-rho", "0.5",
      "--rel-gap", "0.02", "--lagrangian", "--xhatshuffle"]),
    ("acopf3/ccopf_cylinders.py",
     ["--branching-factors", "2 2", "--max-iterations", "20",
      "--default-rho", "0.1", "--rel-gap", "0.01", "--lagrangian",
      "--xhatshuffle"]),
    ("usar/usar_ef.py",
     ["--num-scens", "3", "--output-dir", "/tmp/tpusppy_usar_out"]),
    ("usar/usar_cylinders.py",
     ["--num-scens", "3", "--max-iterations", "20", "--default-rho", "1.0",
      "--rel-gap", "0.05", "--lagrangian", "--xhatrestrictedef",
      "--xhat-ef-every", "1", "--output-dir", "/tmp/tpusppy_usar_out"]),
]


def main():
    skip_uc = "nouc" in sys.argv[1:]
    badguys = []
    for script, args in RUNS:
        if skip_uc and script.startswith("uc/"):
            continue
        path = os.path.join(EXDIR, script)
        cmd = [sys.executable, path] + args
        print("==>", " ".join(cmd), flush=True)
        # scrubbed env: repo root on PYTHONPATH, broken-TPU-plugin vars
        # dropped, cpu pinned (EXAMPLES_KEEP_ENV=1 opts out)
        env = child_env(os.path.dirname(EXDIR))
        res = subprocess.run(cmd, cwd=os.path.dirname(path), env=env)
        if res.returncode != 0:
            badguys.append(script + " " + " ".join(args))
    if badguys:
        print("BAD GUYS:")
        for b in badguys:
            print("  ", b)
        sys.exit(1)
    print(f"All {len(RUNS)} example runs succeeded.")


if __name__ == "__main__":
    main()
