"""Run ALL example drivers end-to-end; assert objectives, not just rc==0.

The analogue of the reference's ``examples/run_all.py`` (the de-facto
regression harness per examples/AAAReadme.txt / SURVEY §4) — EXCEEDING it
on the axis SURVEY §4 flags as its liability ("exit code 0 only"): wheel
drivers write a ``TPUSPPY_RESULT_JSON`` sidecar ({inner, outer, rel_gap})
and runs with an ``expect`` entry are asserted against golden objectives
and certified-gap ceilings, so a 1%-level objective regression fails the
harness.  Usage::

    python run_all.py            # everything
    python run_all.py nouc       # skip the UC family (reference flag parity)
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile

EXDIR = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, EXDIR)
from _harness_env import child_env  # noqa: E402

# ``expect`` semantics (all optional):
#   obj: golden EF objective — the sidecar INNER bound must match within
#        rel (incumbent at/above the optimum, within the driver's gap)
#   rel: relative tolerance for obj (default 1e-2)
#   gap: ceiling on the certified rel_gap (inner vs outer)
RUNS = [
    ("farmer/farmer_ef.py",
     ["--num-scens", "3", "--EF-solver-name", "admm"], None),
    ("farmer/farmer_ef.py",
     ["--num-scens", "3", "--EF-solver-name", "highs"], None),
    ("farmer/farmer_cylinders.py",
     ["--num-scens", "3", "--max-iterations", "20", "--default-rho", "1.0",
      "--rel-gap", "0.01", "--lagrangian", "--xhatshuffle"],
     {"obj": -108390.0, "rel": 1e-2, "gap": 0.02}),
    ("farmer/farmer_cylinders.py",
     ["--num-scens", "3", "--max-iterations", "10", "--default-rho", "1.0",
      "--rel-gap", "0.02", "--fwph", "--lagranger", "--xhatlooper"],
     {"obj": -108390.0, "rel": 1e-2, "gap": 0.05}),
    ("sizes/sizes_cylinders.py",
     ["--num-scens", "3", "--max-iterations", "30", "--default-rho", "0.01",
      "--rel-gap", "0.05", "--lagrangian", "--xhatshuffle"],
     {"obj": 219842.875, "rel": 2e-2, "gap": 0.10}),
    ("sslp/sslp_cylinders.py",
     # NEUTRAL rho: the driver's default adaptive-rho posture
     # (NormRhoUpdater, on unless --no-adaptive-rho) replaces the
     # hand-tuned rho=100 this entry used to need — with a static rho,
     # 5.0 parked the incumbent 16% off optimum (gap 26%).  Adaptation
     # needs runway: rho doubles per firing iteration, so 200 hub
     # iterations replace 40 (measured from rho=5: gap 4.2-4.8% by 200
     # even on a loaded host; 120 leaves 5.9-7.3% under load — the async
     # spokes' progress per hub iteration is machine-dependent).
     ["--num-scens", "4", "--max-iterations", "200", "--default-rho", "5.0",
      "--rel-gap", "0.02", "--lagrangian", "--xhatshuffle"],
     {"obj": -24.0285, "rel": 2e-2, "gap": 0.05}),
    ("netdes/netdes_cylinders.py",
     ["--num-scens", "3", "--max-iterations", "20", "--default-rho", "1.0",
      "--rel-gap", "0.05", "--lagrangian", "--xhatshuffle"],
     {"obj": 376.3056, "rel": 2e-2, "gap": 0.10}),
    ("netdes/netdes_cylinders.py",
     ["--num-scens", "3", "--max-iterations", "12", "--default-rho", "1.0",
      "--rel-gap", "0.05", "--cross-scenario-cuts", "--xhatshuffle"],
     {"obj": 376.3056, "rel": 2e-2}),
    # the batched integer wheel (doc/integer.md): the TRUE integer
    # instance, hub-only — in-wheel bounds + rounding sweep + gap-ranked
    # MILP escalation must certify strictly inside the family's ~5.5%
    # EF integrality gap (golden MIP objective 398.333; no spokes)
    ("netdes/netdes_cylinders.py",
     ["--num-scens", "3", "--max-iterations", "60", "--default-rho", "1.0",
      "--rel-gap", "0.04", "--integer"],
     {"obj": 398.3333, "rel": 2e-2, "gap": 0.04}),
    ("hydro/hydro_pysp.py", [], None),
    ("hydro/hydro_cylinders.py",
     ["--branching-factors", "3 3", "--max-iterations", "20",
      "--default-rho", "1.0", "--rel-gap", "0.02", "--lagrangian",
      "--xhatshuffle"],
     {"obj": 186.1739, "rel": 1e-2, "gap": 0.05}),
    ("aircond/aircond_cylinders.py",
     ["--branching-factors", "3 2", "--max-iterations", "10",
      "--default-rho", "1.0", "--rel-gap", "0.05", "--lagrangian",
      "--xhatshuffle"], None),
    ("uc/uc_cylinders.py",
     ["--num-scens", "4", "--uc-num-gens", "3", "--uc-horizon", "6",
      "--max-iterations", "20", "--default-rho", "50.0",
      "--rel-gap", "0.02", "--lagrangian", "--xhatshuffle"], None),
    ("battery/battery_cylinders.py",
     ["--num-scens", "6", "--battery-lam", "0.1", "--battery-use-lp",
      "--max-iterations", "8", "--default-rho", "0.5",
      "--rel-gap", "0.02", "--lagrangian", "--xhatshuffle"], None),
    ("acopf3/ccopf_cylinders.py",
     ["--branching-factors", "2 2", "--max-iterations", "20",
      "--default-rho", "0.1", "--rel-gap", "0.01", "--lagrangian",
      "--xhatshuffle"], None),
    ("usar/usar_ef.py",
     ["--num-scens", "3", "--output-dir", "/tmp/tpusppy_usar_out"], None),
    ("usar/usar_cylinders.py",
     ["--num-scens", "3", "--max-iterations", "20", "--default-rho", "1.0",
      "--rel-gap", "0.05", "--lagrangian", "--xhatrestrictedef",
      "--xhat-ef-every", "1", "--output-dir", "/tmp/tpusppy_usar_out"],
     {"gap": 0.05}),
]


def check_expect(expect, sidecar_path):
    """Returns a failure string or None."""
    if expect is None:
        return None
    if not os.path.exists(sidecar_path):
        return "no result sidecar written"
    with open(sidecar_path) as f:
        res = json.load(f)
    inner, gap = res.get("inner"), res.get("rel_gap")
    if "obj" in expect:
        rel = expect.get("rel", 1e-2)
        if not (abs(inner - expect["obj"])
                <= rel * max(1.0, abs(expect["obj"]))):
            return (f"inner bound {inner:.4f} off golden "
                    f"{expect['obj']:.4f} (rel tol {rel})")
    if "gap" in expect and not (gap <= expect["gap"]):
        return f"certified rel_gap {gap:.4f} > ceiling {expect['gap']}"
    return None


def main():
    skip_uc = "nouc" in sys.argv[1:]
    badguys = []
    for script, args, expect in RUNS:
        if skip_uc and script.startswith("uc/"):
            continue
        path = os.path.join(EXDIR, script)
        cmd = [sys.executable, path] + args
        print("==>", " ".join(cmd), flush=True)
        # scrubbed env: repo root on PYTHONPATH, broken-TPU-plugin vars
        # dropped, cpu pinned (EXAMPLES_KEEP_ENV=1 opts out)
        env = child_env(os.path.dirname(EXDIR))
        sidecar = os.path.join(
            tempfile.gettempdir(),
            f"tpusppy_runall_{os.getpid()}_{script.replace('/', '_')}.json")
        if os.path.exists(sidecar):
            os.remove(sidecar)
        env["TPUSPPY_RESULT_JSON"] = sidecar
        res = subprocess.run(cmd, cwd=os.path.dirname(path), env=env)
        why = (f"rc={res.returncode}" if res.returncode != 0
               else check_expect(expect, sidecar))
        if why:
            badguys.append(f"{script} {' '.join(args)}: {why}")
        if os.path.exists(sidecar):
            os.remove(sidecar)
    if badguys:
        print("BAD GUYS:")
        for b in badguys:
            print("  ", b)
        sys.exit(1)
    print(f"All {len(RUNS)} example runs succeeded (objectives asserted).")


if __name__ == "__main__":
    main()
