"""Scrubbed child environment for the example harnesses.

Same recipe as ``bench.py``'s ``_scrubbed_cpu_env``: the ambient environment
may carry a sitecustomize on PYTHONPATH that force-registers a remote TPU
runtime whose tunnel can be down — with it present every driver hangs or dies
in jax init.  The harnesses therefore run children with AXON*/TPU_* dropped,
PYTHONPATH replaced (repo root only), and JAX_PLATFORMS pinned to cpu, so
``run_all.py``/``afew.py`` are green in any shell (reference CI posture:
``straight.yml`` runs anywhere).

Set ``EXAMPLES_KEEP_ENV=1`` to opt out (e.g. to run the examples on real TPU
hardware through a known-good ambient env).
"""

from __future__ import annotations

import os


def child_env(repo_root: str) -> dict:
    """Environment for an example-driver child process."""
    if os.environ.get("EXAMPLES_KEEP_ENV"):
        env = dict(os.environ)
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
        return env
    env = {
        k: v for k, v in os.environ.items()
        if k != "PYTHONPATH" and "AXON" not in k and not k.startswith("TPU_")
    }
    env["PYTHONPATH"] = repo_root
    env["JAX_PLATFORMS"] = "cpu"
    env.setdefault("JAX_ENABLE_X64", "1")
    return env
