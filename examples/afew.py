"""Run a few example drivers end-to-end and fail on any error.

The analogue of the reference's ``examples/afew.py`` smoke harness (the
de-facto integration suite posture of SURVEY §4): shell out to driver CLIs,
assert exit status 0, collect the bad guys.
"""

from __future__ import annotations

import os
import subprocess
import sys

EXDIR = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, EXDIR)
from _harness_env import child_env  # noqa: E402

RUNS = [
    ("farmer/farmer_ef.py",
     ["--num-scens", "3", "--EF-solver-name", "admm"]),
    ("farmer/farmer_cylinders.py",
     ["--num-scens", "3", "--max-iterations", "20", "--default-rho", "1.0",
      "--rel-gap", "0.01", "--lagrangian", "--xhatshuffle"]),
    ("sizes/sizes_cylinders.py",
     ["--num-scens", "3", "--max-iterations", "30", "--default-rho", "0.01",
      "--rel-gap", "0.05", "--lagrangian", "--xhatshuffle"]),
    ("uc/uc_cylinders.py",
     ["--num-scens", "4", "--uc-num-gens", "3", "--uc-horizon", "6",
      "--max-iterations", "20", "--default-rho", "50.0",
      "--rel-gap", "0.02", "--lagrangian", "--xhatshuffle"]),
]


def main():
    badguys = []
    for script, args in RUNS:
        path = os.path.join(EXDIR, script)
        cmd = [sys.executable, path] + args
        print("==>", " ".join(cmd), flush=True)
        # scrubbed env: repo root on PYTHONPATH, broken-TPU-plugin vars
        # dropped, cpu pinned (EXAMPLES_KEEP_ENV=1 opts out)
        env = child_env(os.path.dirname(EXDIR))
        res = subprocess.run(cmd, cwd=os.path.dirname(path), env=env)
        if res.returncode != 0:
            badguys.append(script)
    if badguys:
        print("BAD GUYS:", badguys)
        sys.exit(1)
    print("All example runs succeeded.")


if __name__ == "__main__":
    main()
