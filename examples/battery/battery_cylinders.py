"""Battery wheel CLI: PH hub + Lagrangian + xhatshuffle on the
solar-battery Lagrangian relaxation (reference: examples/battery/
batterymain.py).  Usage:

    python battery_cylinders.py --num-scens 20 --battery-lam 0.1 \
        --default-rho 0.5 --max-iterations 20 --rel-gap 0.01 \
        --lagrangian --xhatshuffle
"""

import sys

from tpusppy.models import battery
from tpusppy.spin_the_wheel import WheelSpinner
from tpusppy.utils import cfg_vanilla as vanilla
from tpusppy.utils.config import Config


def _parse(args):
    cfg = Config()
    cfg.popular_args()
    cfg.num_scens_required()
    cfg.ph_args()
    cfg.two_sided_args()
    cfg.lagrangian_args()
    cfg.xhatshuffle_args()
    battery.inparser_adder(cfg)
    cfg.parse_command_line("battery_cylinders", args)
    return cfg


def main(args=None):
    cfg = _parse(args)
    kw = battery.kw_creator(cfg)
    names = battery.scenario_names_creator(cfg.num_scens)
    hub = vanilla.ph_hub(cfg, battery.scenario_creator,
                         all_scenario_names=names,
                         scenario_creator_kwargs=kw)
    spokes = []
    if cfg.lagrangian:
        spokes.append(vanilla.lagrangian_spoke(
            cfg, battery.scenario_creator, all_scenario_names=names,
            scenario_creator_kwargs=kw))
    if cfg.xhatshuffle:
        spokes.append(vanilla.xhatshuffle_spoke(
            cfg, battery.scenario_creator, all_scenario_names=names,
            scenario_creator_kwargs=kw))
    ws = WheelSpinner(hub, spokes).spin()
    print(f"BestInnerBound={ws.BestInnerBound:.4f} "
          f"BestOuterBound={ws.BestOuterBound:.4f}")
    return ws


if __name__ == "__main__":
    main(sys.argv[1:])
