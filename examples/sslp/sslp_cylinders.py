"""SSLP (stochastic server location) cylinders driver.

Behavioral analogue of the reference's ``examples/sslp/sslp_cylinders.py``:
Config-driven CLI assembling a PH hub plus fwph / lagrangian / xhatlooper /
xhatshuffle / cross-scenario-cut spokes.  Example::

    python sslp_cylinders.py --num-scens 5 --max-iterations 30 \
        --default-rho 5.0 --rel-gap 0.01 --lagrangian --xhatshuffle
"""

from tpusppy.models import sslp
from tpusppy.spin_the_wheel import WheelSpinner
from tpusppy.utils import cfg_vanilla as vanilla
from tpusppy.utils import config

write_solution = True


def _parse_args():
    cfg = config.Config()
    cfg.num_scens_required()
    cfg.popular_args()
    cfg.two_sided_args()
    cfg.ph_args()
    cfg.fwph_args()
    cfg.lagrangian_args()
    cfg.xhatlooper_args()
    cfg.xhatshuffle_args()
    cfg.cross_scenario_cuts_args()
    sslp.inparser_adder(cfg)
    cfg.parse_command_line("sslp_cylinders")
    return cfg


def main():
    cfg = _parse_args()
    if cfg.default_rho is None:
        raise RuntimeError("specify --default-rho")
    # adaptive rho ON by default for this family: with a static rho the
    # certified gap is hostage to hand-tuning (rho=5 parks the incumbent
    # 16% off; only rho=100 certified) — NormRhoUpdater reaches the same
    # certification from a neutral rho.  --no-adaptive-rho opts out.
    if not cfg.no_adaptive_rho:
        cfg.adaptive_rho = True
    all_scenario_names = sslp.scenario_names_creator(cfg.num_scens)
    kw = sslp.kw_creator(cfg)
    beans = dict(
        cfg=cfg, scenario_creator=sslp.scenario_creator,
        scenario_denouement=sslp.scenario_denouement,
        all_scenario_names=all_scenario_names,
        scenario_creator_kwargs=kw,
    )
    hub_dict = vanilla.ph_hub(**beans)
    if cfg.cross_scenario_cuts:
        vanilla.add_cross_scenario_cuts(hub_dict, cfg)

    spokes = []
    if cfg.fwph:
        spokes.append(vanilla.fwph_spoke(**beans))
    if cfg.lagrangian:
        spokes.append(vanilla.lagrangian_spoke(**beans))
    if cfg.xhatlooper:
        spokes.append(vanilla.xhatlooper_spoke(**beans))
    if cfg.xhatshuffle:
        spokes.append(vanilla.xhatshuffle_spoke(**beans))
    if cfg.cross_scenario_cuts:
        spokes.append(vanilla.cross_scenario_cuts_spoke(**beans))

    ws = WheelSpinner(hub_dict, spokes)
    ws.spin()
    if write_solution:
        ws.write_first_stage_solution("sslp_first_stage.csv")
    return ws


if __name__ == "__main__":
    main()
