"""CCOPF (contingency-constrained OPF, DC) cylinders driver.

Behavioral analogue of the reference's ``examples/acopf3/ccopf2wood.py`` /
``fourstage.py``: multistage PH hub over the line-failure tree with
lagrangian / xhatshuffle spokes.  The AC physics is DC-linearized (see
``tpusppy/models/ccopf.py`` docstring for the honest scope note).

    python ccopf_cylinders.py --branching-factors "2 2" --max-iterations 20 \
        --default-rho 1.0 --rel-gap 0.01 --lagrangian --xhatshuffle
"""

import numpy as np

from tpusppy.models import ccopf
from tpusppy.spin_the_wheel import WheelSpinner
from tpusppy.utils import cfg_vanilla as vanilla
from tpusppy.utils import config


def _parse_args():
    cfg = config.Config()
    cfg.multistage()   # includes popular_args
    cfg.two_sided_args()
    cfg.ph_args()
    cfg.lagrangian_args()
    cfg.xhatshuffle_args()
    ccopf.inparser_adder(cfg)
    cfg.parse_command_line("ccopf_cylinders")
    return cfg


def main():
    cfg = _parse_args()
    if cfg.default_rho is None:
        raise RuntimeError("specify --default-rho")
    bf = [int(f) for f in (cfg.branching_factors or [2, 2])]
    num_scens = int(np.prod(bf))
    names = ccopf.scenario_names_creator(num_scens)
    kw = ccopf.kw_creator(cfg)
    kw["branching_factors"] = bf
    beans = dict(
        cfg=cfg, scenario_creator=ccopf.scenario_creator,
        scenario_denouement=ccopf.scenario_denouement,
        all_scenario_names=names,
        scenario_creator_kwargs=kw,
    )
    hub_dict = vanilla.ph_hub(**beans)
    spokes = []
    if cfg.lagrangian:
        spokes.append(vanilla.lagrangian_spoke(**beans))
    if cfg.xhatshuffle:
        spokes.append(vanilla.xhatshuffle_spoke(**beans))
    ws = WheelSpinner(hub_dict, spokes).spin()
    print(f"BestInnerBound={ws.BestInnerBound:.4f} "
          f"BestOuterBound={ws.BestOuterBound:.4f}")
    return ws


if __name__ == "__main__":
    main()
