"""Hydro via PySP-format inputs: the Pyomo-less ReferenceModel.

Demonstrates :mod:`tpusppy.utils.pysp_model`: the scenario tree and all data
come from ``PySP/scenariodata/*.dat`` (ScenarioStructure grammar + AMPL
data files); only the model algebra below is python.  Usage::

    python hydro_pysp.py            # solves the EF, prints the objective
"""

import os

import numpy as np

from tpusppy.ir import LinearModelBuilder

DATA_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "PySP", "scenariodata")


def pysp_instance_creator(data, scenario_name):
    """Build one hydro scenario from parsed .dat data (the Pyomo-less
    ReferenceModel; compare tpusppy/models/hydro.py which hard-codes the
    same constants)."""
    T = int(data["nb_etap"])
    D = [float(data["D"][t + 1]) for t in range(T)]
    u = [float(data["u"][t + 1]) for t in range(T)]
    dur = [float(data["duration"][t + 1]) for t in range(T)]
    A = [float(data["A"][t + 1]) for t in range(T)]
    disc = [(1.0 / float(data["rate"])) ** (dur[t] / float(data["horizon"]))
            for t in range(T)]
    bgt, bgh, bdns = (float(data["betaGt"]), float(data["betaGh"]),
                      float(data["betaDns"]))
    V0 = float(data["V0"])
    wv = float(data["WaterValue"])

    b = LinearModelBuilder(scenario_name)
    pgt, pgh, pdns, vol = [], [], [], []
    for t in range(T):
        pgt.append(b.add_var(f"Pgt[{t + 1}]", lb=0.0,
                             ub=float(data["PgtMax"]), cost=disc[t] * bgt))
        pgh.append(b.add_var(f"Pgh[{t + 1}]", lb=0.0,
                             ub=float(data["PghMax"]), cost=disc[t] * bgh))
        pdns.append(b.add_var(f"PDns[{t + 1}]", lb=0.0, ub=D[t],
                              cost=disc[t] * bdns))
        vol.append(b.add_var(f"Vol[{t + 1}]", lb=0.0,
                             ub=float(data["VMax"])))
    sl = b.add_var("sl", lb=0.0, cost=1.0)

    for t in range(T):
        b.add_eq({pgt[t]: 1.0, pgh[t]: 1.0, pdns[t]: 1.0}, D[t])
        coeffs = {vol[t]: 1.0, pgh[t]: u[t]}
        rhs = u[t] * A[t]
        if t == 0:
            rhs += V0
        else:
            coeffs[vol[t - 1]] = -1.0
        b.add_le(coeffs, rhs)
    b.add_ge({sl: 1.0, vol[-1]: wv}, wv * V0)
    return b.build()


def make_model():
    from tpusppy.utils.pysp_model import PySPModel

    return PySPModel(
        pysp_instance_creator,
        os.path.join(DATA_DIR, "ScenarioStructure.dat"),
    )


def main():
    from tpusppy.ef import solve_ef
    from tpusppy.ir import ScenarioBatch

    model = make_model()
    batch = ScenarioBatch.from_problems([
        model.scenario_creator(nm) for nm in model.all_scenario_names
    ])
    obj, _ = solve_ef(batch, solver="highs")
    print(f"hydro (PySP inputs) EF objective: {obj:.2f}")
    return obj


if __name__ == "__main__":
    main()
