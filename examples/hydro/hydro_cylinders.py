"""Hydro (3-stage) cylinders driver.

Behavioral analogue of the reference's ``examples/hydro/hydro_cylinders.py``:
multistage PH hub + lagrangian / xhatshuffle / xhatspecific spokes over the
branching-factor tree.  Example::

    python hydro_cylinders.py --branching-factors "3 3" --max-iterations 50 \
        --default-rho 1.0 --rel-gap 0.01 --lagrangian --xhatshuffle
"""

from tpusppy.models import hydro
from tpusppy.spin_the_wheel import WheelSpinner
from tpusppy.utils import cfg_vanilla as vanilla
from tpusppy.utils import config

write_solution = True


def _parse_args():
    cfg = config.Config()
    cfg.multistage()   # includes popular_args
    cfg.two_sided_args()
    cfg.ph_args()
    cfg.fwph_args()
    cfg.lagrangian_args()
    cfg.xhatshuffle_args()
    cfg.xhatspecific_args()
    cfg.parse_command_line("hydro_cylinders")
    return cfg


def main():
    cfg = _parse_args()
    if cfg.default_rho is None:
        raise RuntimeError("specify --default-rho")
    if cfg.branching_factors is None:
        raise RuntimeError("specify --branching-factors (e.g. \"3 3\")")
    bf = cfg.branching_factors
    num_scens = 1
    for f in bf:
        num_scens *= int(f)
    all_scenario_names = hydro.scenario_names_creator(num_scens)
    kw = hydro.kw_creator(cfg)
    beans = dict(
        cfg=cfg, scenario_creator=hydro.scenario_creator,
        scenario_denouement=hydro.scenario_denouement,
        all_scenario_names=all_scenario_names,
        scenario_creator_kwargs=kw,
    )
    hub_dict = vanilla.ph_hub(**beans)

    spokes = []
    if cfg.lagrangian:
        spokes.append(vanilla.lagrangian_spoke(**beans))
    if cfg.xhatshuffle:
        spokes.append(vanilla.xhatshuffle_spoke(**beans))
    if getattr(cfg, "xhatspecific", False):
        # fixed candidate: the first scenario under each nonleaf node
        xhat_dict = {"ROOT": all_scenario_names[0]}
        for i in range(int(bf[0])):
            xhat_dict[f"ROOT_{i}"] = all_scenario_names[i * int(bf[1])]
        spokes.append(vanilla.xhatspecific_spoke(
            xhat_scenario_dict=xhat_dict, **beans))

    ws = WheelSpinner(hub_dict, spokes)
    ws.spin()
    if write_solution:
        ws.write_first_stage_solution("hydro_first_stage.csv")
    return ws


if __name__ == "__main__":
    main()
