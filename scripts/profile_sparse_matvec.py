"""Decide the sparse-A matvec strategy on TPU at reference-UC shapes.

Candidates for y = A x with A (m, n) ~0.03% dense, batched over S:
  dense   — current (S, n) @ (n, m) matmul against dense A
  coo     — gather + segment_sum (scatter-add) in CSR order
  ell     — hybrid: narrow rows via padded row-wise gather (regular, no
            scatter), wide rows (balance/reserves) via a compact dense
            matmul over the columns they touch
Same for the transpose A' y (columns are uniformly narrow: pure ELL).

Usage: python scripts/profile_sparse_matvec.py [S] [horizon]
"""

import sys
import time

import numpy as np

S = int(sys.argv[1]) if len(sys.argv) > 1 else 1000
horizon = int(sys.argv[2]) if len(sys.argv) > 2 else 24

import jax
import jax.numpy as jnp

import tpusppy
tpusppy.disable_tictoc_output()
from tpusppy.ir import ScenarioBatch
from tpusppy.models import uc_data

DATA = "/root/reference/paperruns/larger_uc/1000scenarios_wind"
names = uc_data.scenario_names_creator(data_dir=DATA)[:4]
kw = {"data_dir": DATA, "horizon": horizon, "relax_integers": False,
      "num_scens": 4}
batch = ScenarioBatch.from_problems(
    [uc_data.scenario_creator(nm, **kw) for nm in names])
A = np.asarray(batch.A_shared)
m, n = A.shape
rows, cols = np.nonzero(A)
vals = A[rows, cols]
nnz = vals.size
row_counts = np.bincount(rows, minlength=m)
col_counts = np.bincount(cols, minlength=n)
print(f"A: ({m}, {n}) nnz={nnz} row nnz p50/p99/max="
      f"{np.percentile(row_counts, 50):.0f}/"
      f"{np.percentile(row_counts, 99):.0f}/{row_counts.max()} "
      f"col nnz p50/max={np.percentile(col_counts, 50):.0f}/"
      f"{col_counts.max()}", flush=True)

dt = jnp.float32
x = jnp.asarray(np.random.default_rng(0).normal(size=(S, n)), dt)
y = jnp.asarray(np.random.default_rng(1).normal(size=(S, m)), dt)
Ad = jnp.asarray(A, dt)


def bench(tag, fn, *args):
    # matrices must be ARGUMENTS (closure-captured constants embed in the
    # HLO and overflow the remote-compile request body); timing must END
    # WITH A FETCH — on the axon plugin block_until_ready returns before
    # execution completes, so only a device->host copy proves the queue
    # drained
    f = jax.jit(fn)
    out = f(*args)
    np.asarray(jnp.sum(out))
    reps = 20
    t0 = time.time()
    for _ in range(reps):
        out = f(*args)
    np.asarray(jnp.sum(out))
    dt_ms = (time.time() - t0) / reps * 1e3
    print(f"  {tag:28s} {dt_ms:8.2f} ms", flush=True)
    return out, dt_ms


print(f"\nforward A x -> (S={S}, m):", flush=True)
ref, t_dense = bench("dense matmul", lambda xx, Am: xx @ Am.T, x, Ad)

# --- COO / segment-sum --------------------------------------------------
order = np.lexsort((cols, rows))
r_s, c_s, v_s = rows[order], cols[order], vals[order]
rj = jnp.asarray(r_s, jnp.int32)
cj = jnp.asarray(c_s, jnp.int32)
vj = jnp.asarray(v_s, dt)


def coo_matvec(xx, cjj, vjj, rjj):
    g = xx[:, cjj] * vjj[None, :]
    return jax.ops.segment_sum(g.T, rjj, num_segments=m,
                               indices_are_sorted=True).T


out, t_coo = bench("coo segment_sum", coo_matvec, x, cj, vj, rj)
print(f"    coo relerr {float(jnp.abs(out - ref).max() / jnp.abs(ref).max()):.2e}")

# --- hybrid ELL + dense wide rows --------------------------------------
K_ELL = 8
narrow = row_counts <= K_ELL
wide = ~narrow
print(f"    narrow rows {narrow.sum()} (k<={K_ELL}), wide {wide.sum()} "
      f"touching {np.unique(cols[np.isin(rows, np.flatnonzero(wide))]).size}"
      f" cols")
ell_cols = np.zeros((m, K_ELL), np.int32)
ell_vals = np.zeros((m, K_ELL), np.float64)
for r in np.flatnonzero(narrow):
    mask = rows == r
    k = mask.sum()
    ell_cols[r, :k] = cols[mask]
    ell_vals[r, :k] = vals[mask]
ec = jnp.asarray(ell_cols)
ev = jnp.asarray(ell_vals, dt)
Aw = jnp.asarray(A[wide], dt)          # (mw, n) dense wide rows
widx = jnp.asarray(np.flatnonzero(wide), jnp.int32)


def ell_matvec(xx, ecc, evv, Aww, wii):
    out = jnp.einsum("smk,mk->sm", xx[:, ecc], evv)
    return out.at[:, wii].set(xx @ Aww.T)


out, t_ell = bench("ell + dense wide", ell_matvec, x, ec, ev, Aw, widx)
print(f"    ell relerr {float(jnp.abs(out - ref).max() / jnp.abs(ref).max()):.2e}")

print(f"\ntranspose A' y -> (S={S}, n):", flush=True)
refT, tT_dense = bench("dense matmul", lambda yy, Am: yy @ Am, y, Ad)

orderT = np.lexsort((rows, cols))
rT = jnp.asarray(rows[orderT], jnp.int32)
cT = jnp.asarray(cols[orderT], jnp.int32)
vT = jnp.asarray(vals[orderT], dt)


def coo_rmatvec(yy, rTT, vTT, cTT):
    g = yy[:, rTT] * vTT[None, :]
    return jax.ops.segment_sum(g.T, cTT, num_segments=n,
                               indices_are_sorted=True).T


out, tT_coo = bench("coo segment_sum", coo_rmatvec, y, rT, vT, cT)
print(f"    coo relerr {float(jnp.abs(out - refT).max() / jnp.abs(refT).max()):.2e}")

KT = int(col_counts.max())
ellT_rows = np.zeros((n, KT), np.int32)
ellT_vals = np.zeros((n, KT), np.float64)
fill = np.zeros(n, np.int32)
for idx in range(nnz):
    c = cols[idx]
    ellT_rows[c, fill[c]] = rows[idx]
    ellT_vals[c, fill[c]] = vals[idx]
    fill[c] += 1
erT = jnp.asarray(ellT_rows)
evT = jnp.asarray(ellT_vals, dt)


def ell_rmatvec(yy, err, evv):
    return jnp.einsum("snk,nk->sn", yy[:, err], evv)


out, tT_ell = bench(f"ell (k={KT})", ell_rmatvec, y, erT, evT)
print(f"    ell relerr {float(jnp.abs(out - refT).max() / jnp.abs(refT).max()):.2e}")

print(f"\nspeedups: fwd coo {t_dense/t_coo:.1f}x ell {t_dense/t_ell:.1f}x; "
      f"transpose coo {tT_dense/tT_coo:.1f}x ell {tT_dense/tT_ell:.1f}x",
      flush=True)
