#!/bin/sh
# CI job partition for tests/test_*.py (used by .github/workflows/
# straight.yml): prints the job group a test file belongs to.  Exactly one
# group matches any file — solvers and cylinders-wheel are explicit
# pattern lists, confint-utils is the catch-all — so the three CI jobs
# can never double-run or drop a file as tests are added.
#
#   $ scripts/ci_test_group.sh tests/test_admm.py
#   solvers
case "$(basename "$1")" in
  test_admm.py|test_shared.py|test_shared_admm.py|test_sharded.py|\
  test_segmented.py|test_pipeline.py|test_megastep.py|\
  test_pallas.py|test_sparse_structured.py|test_fused_step.py|\
  test_tune.py|test_precision*.py|test_milp_bound.py|test_bench_smoke.py|\
  test_aot.py|test_scale_out.py|test_integer.py)
    echo solvers ;;
  test_ph.py|test_aph.py|test_fwph.py|test_wheel.py|test_tcp_wheel.py|\
  test_mp_wheel.py|test_distributed*.py|test_dist_aph.py|\
  test_window_service.py|test_one_sided.py|test_xhat.py|\
  test_extensions.py|test_inwheel_bounds.py|\
  test_cross_scen.py|test_mip_incumbents.py|test_lshaped.py|test_sc.py|\
  test_ef.py|test_obs.py|test_resilience.py|test_elastic.py|\
  test_service.py|test_service_durable.py|test_batching.py)
    echo cylinders-wheel ;;
  *)
    echo confint-utils ;;
esac
