"""Decompose the sparse/structured sweep cost at UC shape on TPU.

Times each component of one ADMM sweep in isolation (jitted, fetch-timed):
block/Woodbury Kinv apply, sparse matvec + transpose, the elementwise
z/y updates, and the full refine-k x-update — to show where the next
speedup lives.  Pass the refine count as the third arg to match the
configuration under study (bench_uc runs solve_refine=1).

Usage: python scripts/profile_sweep_parts.py [S] [horizon]
"""

import sys

import numpy as np

S = int(sys.argv[1]) if len(sys.argv) > 1 else 1000
horizon = int(sys.argv[2]) if len(sys.argv) > 2 else 24
refine = int(sys.argv[3]) if len(sys.argv) > 3 else 1

import jax
import jax.numpy as jnp

import tpusppy
tpusppy.disable_tictoc_output()
from tpusppy import tune
from tpusppy.ir import ScenarioBatch
from tpusppy.models import uc_data
from tpusppy.solvers import structured_kkt as sk
from tpusppy.solvers.sparse import SparseA

DATA = "/root/reference/paperruns/larger_uc/1000scenarios_wind"
names = uc_data.scenario_names_creator(data_dir=DATA)[:4]
kw = {"data_dir": DATA, "horizon": horizon, "relax_integers": False,
      "num_scens": 4}
batch = ScenarioBatch.from_problems(
    [uc_data.scenario_creator(nm, **kw) for nm in names])
A = np.asarray(batch.A_shared)
m, n = A.shape
sp = SparseA.from_dense(A, jnp.float32, structure=True)
assert sp.structure is not None
print(f"({m}, {n}) nnz={sp.nnz} r={sp.structure.wide_rows.shape[0]} "
      f"S={S}", flush=True)

rng = np.random.default_rng(0)
d = jnp.asarray(rng.random(n) + 0.5, jnp.float32)
rho = jnp.asarray(rng.random(m) + 0.5, jnp.float32)
bw = sk.factor_structured(sp, sp.structure, d, rho, 1e-6)
x = jnp.asarray(rng.normal(size=(S, n)), jnp.float32)
y = jnp.asarray(rng.normal(size=(S, m)), jnp.float32)


def bench(tag, fn, *args):
    # the jit/fetch timing core moved into tpusppy.tune (reusable by the
    # fused-cadence autotuner); this script keeps the printing shell
    ms = tune.time_jitted(jax.jit(fn), *args)
    print(f"  {tag:34s} {ms:8.2f} ms", flush=True)
    return ms


with jax.default_matmul_precision("highest"):
    t_kinv = bench("block/Woodbury Kinv apply", sk.kinv_apply, bw, x)
    t_mv = bench("sparse matvec A x", lambda a, xx: a.matvec(xx), sp, x)
    t_rmv = bench("sparse rmatvec A' y", lambda a, yy: a.rmatvec(yy), sp, y)

    def elementwise(xx, yy):
        z = jnp.clip(yy * 1.3 + 0.1, -1.0, 1.0)
        return yy + 0.7 * (z - yy)

    t_el = bench("one (S, m) clip+axpy pair", elementwise, x, y)

    def kmul_free(a, xx, dd, rr):
        return xx * dd[None, :] + a.rmatvec(a.matvec(xx) * rr[None, :])

    t_kmul = bench("matrix-free Kmul (refine term)", kmul_free, sp, x, d,
                   rho)

    def full_refine_solve(a, b_, dd, rr):
        # x-update as in _solve_shared_K (dq2 path skipped)
        xx = sk.kinv_apply(bw, b_)
        for _ in range(refine):
            r_ = b_ - kmul_free(a, xx, dd, rr)
            xx = xx + sk.kinv_apply(bw, r_)
        return xx

    t_xupd = bench(f"full x-update (refine={refine})", full_refine_solve,
                   sp, x, d, rho)

print(f"\nper-sweep estimate: x-update {t_xupd:.1f} + Axt {t_mv:.1f} + "
      f"rhs rmv {t_rmv:.1f} + elementwise ~{4*t_el:.1f} "
      f"= {t_xupd + t_mv + t_rmv + 4*t_el:.1f} ms", flush=True)
