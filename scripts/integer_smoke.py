"""Nightly integer-wheel smoke (doc/integer.md): integer families certify
on the fast path.

Two hub-only in-wheel wheels on the true-integer (``relax_integers=
False``) posture:

* **netdes** (S=3): must certify ``rel_gap <= NETDES_GAP`` — strictly
  inside the family's ~5.5% EF integrality gap, which floors ANY LP-only
  bound pair at ~5.85% (outer <= LP EF 376.306, inner >= MIP 398.333) —
  with the device rounding sweep supplying incumbents
  (``integer.feasible_hits > 0``) and the certified outer bound strictly
  ABOVE the LP EF optimum (only the MILP escalation tier can get there).
* **sizes** (S=3): must certify ``rel_gap <= SIZES_GAP`` (the golden
  host-lift gap; the family's EF integrality gap is ~2.07%, flooring
  LP-only pairs at ~2.11%).

Host-tail discipline: each wheel's ``integer.escalation_secs`` must stay
within its configured budget (+ scheduling slack), and strictly below
the ALL-HOST baseline — the wall of one full unranked gap-closed MILP
lift over every scenario times the number of bound events the wheel ran
(what a MIP-backed bound spoke pays per fresh W, the reference posture).

A hard watchdog (INTEGER_SMOKE_DEADLINE_SECS, default 1200) ``os._exit``s
so a wedged wheel can never pin the nightly job for the workflow
timeout.  Exit 0 = pass.
"""

import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

NETDES_GAP = 0.04      # < the ~5.85% LP-only floor
NETDES_LP_EF = 376.306
SIZES_GAP = 0.02       # golden host-lift gap; < the ~2.11% LP-only floor

DEADLINE = float(os.environ.get("INTEGER_SMOKE_DEADLINE_SECS", "1200"))


def _watchdog():
    time.sleep(DEADLINE)
    print(f"INTEGER SMOKE WATCHDOG: {DEADLINE}s deadline passed — "
          "killing", flush=True)
    os._exit(2)


def run_family(name, module, kw, rho, iters, rel_gap, budget_s):
    import numpy as np

    from tpusppy.cylinders import PHHub
    from tpusppy.obs import metrics as obs_metrics
    from tpusppy.opt.ph import PH
    from tpusppy.solvers import integer as integer_solvers
    from tpusppy.solvers.milp_bound import milp_lift
    from tpusppy.spin_the_wheel import WheelSpinner

    opt_kwargs = {
        "options": {"defaultPHrho": rho, "PHIterLimit": iters,
                    "convthresh": -1.0, "in_wheel_bounds": True,
                    "integer_escalation_budget_s": budget_s},
        "all_scenario_names": module.scenario_names_creator(3),
        "scenario_creator": module.scenario_creator,
        "scenario_creator_kwargs": kw,
    }
    hub_dict = {"hub_class": PHHub,
                "hub_kwargs": {"options": {"rel_gap": rel_gap}},
                "opt_class": PH, "opt_kwargs": opt_kwargs}
    t0 = time.time()
    with obs_metrics.window() as w:
        ws = WheelSpinner(hub_dict, []).spin()
    wall = time.time() - t0
    _, gap = ws.spcomm.compute_gaps()
    # all-host baseline unit: ONE full unranked gap-closed lift from the
    # final W.  The pure-host posture (the reference's MIP-backed
    # Lagrangian spoke / the old ``lagrangian_milp_lift every=1`` knob)
    # pays this for EVERY fresh W — once per hub iteration — so the
    # baseline wall is the unit times the iterations this wheel ran.
    qL = integer_solvers._waug_q(ws.opt)
    base = ws.opt.Edualbound_perscen(q=qL, q2=ws.opt.batch.q2)
    t0 = time.time()
    milp_lift(ws.opt.batch, qL, base, budget_s=180.0, mip_rel_gap=1e-4)
    lift_unit_secs = time.time() - t0
    events = max(1, int(getattr(ws.opt, "_iter", 1)))
    res = {
        "family": name,
        "wall_secs": round(wall, 2),
        "rel_gap": float(gap),
        "inner": float(ws.BestInnerBound),
        "outer": float(ws.BestOuterBound),
        "feasible_hits": int(w.delta("integer.feasible_hits")),
        "rcfix_slots": int(w.delta("integer.rcfix_slots")),
        "escalations": int(w.delta("integer.escalations")),
        "escalation_secs": round(w.delta("integer.escalation_secs"), 3),
        "bound_passes": events,
        "all_host_lift_secs": round(lift_unit_secs * events, 3),
    }
    print(json.dumps(res), flush=True)
    bad = []
    if not (np.isfinite(gap) and gap <= rel_gap):
        bad.append(f"rel_gap {gap} > target {rel_gap}")
    if res["feasible_hits"] < 1:
        bad.append("integer.feasible_hits == 0 (no sweep incumbents)")
    if res["escalation_secs"] > budget_s + 30.0:
        bad.append(f"escalation secs {res['escalation_secs']} blew the "
                   f"{budget_s}s budget")
    if not (res["escalation_secs"] < res["all_host_lift_secs"]):
        bad.append(
            f"escalation secs {res['escalation_secs']} not below the "
            f"all-host baseline {res['all_host_lift_secs']}")
    return res, bad


def main():
    threading.Thread(target=_watchdog, daemon=True).start()
    from tpusppy.models import netdes, sizes

    badguys = []
    res_n, bad = run_family(
        "netdes", netdes, {"num_scens": 3, "relax_integers": False},
        rho=1.0, iters=60, rel_gap=NETDES_GAP, budget_s=20.0)
    badguys += [f"netdes: {b}" for b in bad]
    # netdes-only check: the certified outer bound must sit ABOVE the LP
    # EF optimum — only the MILP tier can certify there
    if not (res_n["outer"] > NETDES_LP_EF + 1e-6):
        badguys.append(
            f"netdes: outer {res_n['outer']} not past the LP EF "
            f"{NETDES_LP_EF} — the lift did not engage")
    if not os.environ.get("INTEGER_SMOKE_SKIP_SIZES"):
        _, bad = run_family(
            "sizes", sizes,
            {"scenario_count": 3, "relax_integers": False},
            rho=0.01, iters=80, rel_gap=SIZES_GAP, budget_s=45.0)
        badguys += [f"sizes: {b}" for b in bad]
    if badguys:
        print("INTEGER SMOKE FAILED:", flush=True)
        for b in badguys:
            print("  ", b, flush=True)
        sys.exit(1)
    print("INTEGER SMOKE PASSED", flush=True)
    # daemon threads + device caches: exit hard like bench.py so a
    # lingering teardown can never turn a pass into rc!=0
    os._exit(0)


if __name__ == "__main__":
    main()
