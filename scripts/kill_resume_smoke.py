#!/usr/bin/env python
"""Kill-resume smoke: SIGKILL a live wheel mid-run, resume, compare gaps.

The nightly CI acceptance for the resilience subsystem
(doc/resilience.md), runnable locally too::

    JAX_PLATFORMS=cpu python scripts/kill_resume_smoke.py

Three legs, each a REAL OS process running a farmer wheel (PH hub +
Lagrangian outer + XhatShuffle inner):

1. **golden** — uninterrupted run to a certified rel_gap <= 1e-3; its
   final gap is the bar.
2. **victim** — the same wheel with an impossible gap target and
   per-iteration checkpointing; the parent waits until >= KILL_AFTER
   checkpoints exist, then SIGKILLs it (no cleanup, no atexit — the
   preemption posture).
3. **resume** — the golden configuration warm-started from the victim's
   checkpoint directory; it must certify a rel_gap no worse than the
   golden run's (+ tolerance dust) with bounds monotone w.r.t. the
   snapshot it resumed from.

Exit code 0 = pass.  The worker legs are this same file with
``--worker`` (config via SMOKE_* env), so the smoke has no test-harness
dependencies.
"""

import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
KILL_AFTER = int(os.environ.get("SMOKE_KILL_AFTER_CKPTS", "3"))


def log(msg):
    print(f"kill-resume-smoke: {msg}", file=sys.stderr, flush=True)


# ---------------------------------------------------------------------------
# Worker leg (child process)
# ---------------------------------------------------------------------------
def worker():
    sys.path.insert(0, REPO)
    from tpusppy.cylinders import (LagrangianOuterBound, PHHub,
                                   XhatShuffleInnerBound)
    from tpusppy.models import farmer
    from tpusppy.opt.ph import PH
    from tpusppy.phbase import PHBase
    from tpusppy.spin_the_wheel import WheelSpinner
    from tpusppy.xhat_eval import Xhat_Eval

    mode = os.environ["SMOKE_MODE"]            # golden | victim | resume
    ckdir = os.environ["SMOKE_DIR"]
    n = int(os.environ.get("SMOKE_SCENS", "3"))

    def okw(iters):
        return {
            "options": {"defaultPHrho": 1.0, "PHIterLimit": iters,
                        "convthresh": -1.0,
                        "xhat_looper_options": {"scen_limit": 3}},
            "all_scenario_names": farmer.scenario_names_creator(n),
            "scenario_creator": farmer.scenario_creator,
            "scenario_creator_kwargs": {"num_scens": n},
        }

    hub_options = {"rel_gap": 1e-3, "abs_gap": 1.0, "linger_secs": 60.0}
    # the resume leg's TOTAL budget is set by the parent relative to the
    # kill iteration (SMOKE_RESUME_ITERS), so a fast box that banked many
    # iterations before the SIGKILL still genuinely CONTINUES the run
    iters = int(os.environ.get("SMOKE_RESUME_ITERS", "40"))
    resume = None
    if mode == "victim":
        # impossible target + per-iteration checkpoints: the run CANNOT
        # finish before the parent's SIGKILL lands
        hub_options = {"rel_gap": 1e-12, "linger_secs": 0.0,
                       "checkpoint_dir": ckdir,
                       "checkpoint_every_iters": 1,
                       "checkpoint_every_secs": None}
        iters = 100000
    elif mode == "resume":
        resume = ckdir
    hub = {"hub_class": PHHub, "hub_kwargs": {"options": hub_options},
           "opt_class": PH, "opt_kwargs": okw(iters)}
    spokes = [
        {"spoke_class": LagrangianOuterBound, "opt_class": PHBase,
         "opt_kwargs": okw(60)},
        {"spoke_class": XhatShuffleInnerBound, "opt_class": Xhat_Eval,
         "opt_kwargs": okw(60)},
    ]
    ws = WheelSpinner(hub, spokes, resume=resume).spin()
    gap = ((ws.BestInnerBound - ws.BestOuterBound)
           / abs(ws.BestOuterBound))
    # AOT executable-cache evidence (tpusppy/solvers/aot.py): the victim
    # compiles cold and serializes; the RESUME leg re-arms the cache from
    # the checkpoint's carried pointer (no env knob of its own) and must
    # restart warm — checkpoint + cache compose
    from tpusppy.obs import metrics

    aot = {k: metrics.value(f"aot.{k}")
           for k in ("hits", "misses", "compile_s", "deserialize_s",
                     "load_errors")}
    with open(os.path.join(ckdir, f"result_{mode}.json"), "w") as f:
        json.dump({"inner": ws.BestInnerBound, "outer": ws.BestOuterBound,
                   "rel_gap": gap, "aot": aot,
                   "resumed_from": ws.resumed_from}, f)
    print(json.dumps({"mode": mode, "rel_gap": gap, "aot": aot}),
          flush=True)


# ---------------------------------------------------------------------------
# Orchestration (parent)
# ---------------------------------------------------------------------------
def _run_leg(mode, ckdir, timeout=900, env_extra=None):
    env = dict(os.environ, SMOKE_MODE=mode, SMOKE_DIR=ckdir,
               PYTHONPATH=REPO)
    # the legs control the executable cache EXPLICITLY (env_extra): the
    # victim arms it, the resume leg must inherit it from the checkpoint
    # pointer alone — an ambient knob would fake the composition proof
    env.pop("TPUSPPY_AOT_CACHE", None)
    env.update(env_extra or {})
    env.setdefault("JAX_PLATFORMS", "cpu")
    return subprocess.Popen([sys.executable, os.path.abspath(__file__),
                             "--worker"], env=env), timeout


def _wait(proc, timeout, leg):
    try:
        rc = proc.wait(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.kill()
        raise SystemExit(f"{leg} leg timed out after {timeout}s")
    if rc != 0:
        raise SystemExit(f"{leg} leg failed rc={rc}")


def main():
    import tempfile

    from tpusppy.resilience import checkpoint  # parent: pure-host import

    base = tempfile.mkdtemp(prefix="kill_resume_smoke_")
    log(f"workdir {base}")

    golden_dir = os.path.join(base, "golden")
    os.makedirs(golden_dir)
    proc, t = _run_leg("golden", golden_dir)
    _wait(proc, t, "golden")
    golden = json.load(open(os.path.join(golden_dir, "result_golden.json")))
    log(f"golden rel_gap={golden['rel_gap']:.3e}")
    assert golden["rel_gap"] <= 1e-3 + 1e-12, "golden run did not certify"

    victim_dir = os.path.join(base, "victim")
    os.makedirs(victim_dir)
    # the victim runs with the AOT executable cache armed and its own
    # FRESH jax compile cache (the golden leg must not pre-warm it):
    # its checkpoints carry the cache pointer, and the resume leg —
    # which gets NEITHER knob — must restart warm from that pointer
    aot_dir = os.path.join(base, "aot")
    victim_env = {"TPUSPPY_AOT_CACHE": aot_dir,
                  "JAX_COMPILATION_CACHE_DIR": os.path.join(base, "xla")}
    proc, _ = _run_leg("victim", victim_dir, env_extra=victim_env)
    def _banked_iteration():
        """Newest checkpointed iteration (0 when none yet) — iteration,
        not file count: the manager prunes to keep=3 files, so counting
        files would cap KILL_AFTER at the retention depth."""
        try:
            ck = checkpoint.load_latest(victim_dir)
            return 0 if ck is None else ck.iteration
        except Exception:        # mid-write transient: just poll again
            return 0

    t0 = time.time()
    t_first_ckpt = None
    try:
        while _banked_iteration() < KILL_AFTER:
            if t_first_ckpt is None and _banked_iteration() >= 1:
                # cold-start anchor: everything the victim compiled plus
                # its first iterations fits in this window — the resumed
                # process must spend far less than this in compiles
                t_first_ckpt = time.time() - t0
            if proc.poll() is not None:
                raise SystemExit(
                    f"victim exited early rc={proc.returncode} — cannot "
                    "SIGKILL a finished run")
            if time.time() - t0 > 600:
                raise SystemExit("victim produced no checkpoints in 600s")
            time.sleep(0.2)
        if t_first_ckpt is None:
            t_first_ckpt = time.time() - t0
        os.kill(proc.pid, signal.SIGKILL)    # the preemption, for real
        proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
    ck = checkpoint.load_latest(victim_dir)
    log(f"victim SIGKILLed at checkpoint iteration {ck.iteration} "
        f"(outer={ck.best_outer:.2f} inner={ck.best_inner:.2f})")
    assert ck.iteration >= KILL_AFTER

    # the resumed wheel must RUN, not just reload: give it a real
    # iteration budget past the snapshot whatever speed the box killed at
    os.environ["SMOKE_RESUME_ITERS"] = str(max(40, ck.iteration + 20))
    # resume gets the victim's jax-cache tier but NOT the aot knob — the
    # executable cache must re-arm from the checkpoint's pointer alone
    proc, t = _run_leg("resume", victim_dir, env_extra={
        "JAX_COMPILATION_CACHE_DIR": os.path.join(base, "xla")})
    _wait(proc, t, "resume")
    res = json.load(open(os.path.join(victim_dir, "result_resume.json")))
    log(f"resumed rel_gap={res['rel_gap']:.3e} "
        f"(golden {golden['rel_gap']:.3e}) aot={res.get('aot')}")

    assert res["resumed_from"] == ck.iteration, \
        f"resume did not pick up the snapshot: {res['resumed_from']}"
    # bounds monotone across the restart
    assert res["outer"] >= ck.best_outer - 1e-9, "outer bound regressed"
    assert res["inner"] <= ck.best_inner + 1e-9, "inner bound regressed"
    # certified no worse than the uninterrupted golden
    assert res["rel_gap"] <= max(golden["rel_gap"], 1e-3) + 1e-9, \
        f"resumed gap {res['rel_gap']} worse than golden {golden['rel_gap']}"
    # warm restart (checkpoint + AOT executable cache compose): the
    # resume leg was launched WITHOUT the cache env knob — its hits can
    # only come from the checkpoint's carried pointer — and its total
    # explicit compile seconds must be a small fraction of the window
    # the cold victim needed to even reach its first snapshot
    aot = res.get("aot") or {}
    assert aot.get("hits", 0) > 0, \
        f"resume did not restart warm from the checkpoint pointer: {aot}"
    assert aot.get("load_errors", 0) == 0, aot
    assert aot.get("compile_s", 1e9) <= 0.5 * t_first_ckpt, \
        (f"resume compiled {aot.get('compile_s'):.1f}s vs victim "
         f"cold-start window {t_first_ckpt:.1f}s — not a warm restart")
    log(f"warm restart ok: {aot.get('hits'):.0f} executable hits, "
        f"{aot.get('compile_s'):.1f}s compiles vs {t_first_ckpt:.1f}s "
        "cold window")
    log("PASS")


if __name__ == "__main__":
    if "--worker" in sys.argv[1:]:
        worker()
    else:
        sys.path.insert(0, REPO)
        main()
