"""Accuracy A/B for candidate UC sweep-cost reductions.

For each settings variant, run the SAME frozen PH prox solve (identical q,
warm start, factors refreshed under that variant's precision) and report:

- worst / median scaled residuals (the floor)
- prob-weighted expected objective (PH trajectory quality proxy)
- NaN presence (the bf16x3 divergence mode from the session-2 A/B)

Usage:  python scripts/profile_uc_accuracy.py [S] [horizon]
"""

import dataclasses
import sys
import time

import numpy as np

S = int(sys.argv[1]) if len(sys.argv) > 1 else 256
horizon = int(sys.argv[2]) if len(sys.argv) > 2 else 24

import jax
import jax.numpy as jnp

import tpusppy
tpusppy.disable_tictoc_output()
from tpusppy.ir import ScenarioBatch
from tpusppy.models import uc_data
from tpusppy.parallel import sharded
from tpusppy.solvers import shared_admm
from tpusppy.solvers.admm import ADMMSettings

DATA = "/root/reference/paperruns/larger_uc/1000scenarios_wind"

names = uc_data.scenario_names_creator(data_dir=DATA)[:S]
kw = {"data_dir": DATA, "horizon": horizon, "relax_integers": False,
      "num_scens": S}
batch = ScenarioBatch.from_problems(
    [uc_data.scenario_creator(nm, **kw) for nm in names])
print(f"batch: {batch.num_scenarios} x ({batch.num_rows} rows, "
      f"{batch.num_vars} vars) platform={jax.devices()[0].platform}",
      flush=True)

base = ADMMSettings(dtype="float32", eps_abs=1e-5, eps_rel=1e-5,
                    max_iter=200, restarts=2, scaling_iters=6,
                    polish_passes=1)

mesh = sharded.make_mesh()
arr = sharded.shard_batch(batch, mesh)

# advance a couple of PH iterations at baseline settings to get a
# REPRESENTATIVE prox state (W, xbars, warm start) — then all variants
# solve that same subproblem
refresh, frozen = sharded.make_ph_step_pair(
    batch.tree.nonant_indices, base, mesh)
state = sharded.init_state(arr, 1.0, base)
state, out, _ = refresh(state, arr, 0.0)
state, out, factors0 = refresh(state, arr, 1.0)
state, out = frozen(state, arr, 1.0, factors0)
np.asarray(out.conv)
print("warmup done", flush=True)

idx = jnp.asarray(batch.tree.nonant_indices)
dt = base.jdtype()
q = arr.c.astype(dt).at[:, idx].add(
    jnp.asarray(np.asarray(state.W), dt)
    - jnp.asarray(np.asarray(state.rho), dt)
    * jnp.asarray(np.asarray(state.xbars), dt))
q2 = arr.q2.astype(dt).at[:, idx].add(jnp.asarray(np.asarray(state.rho), dt))
warm = (state.x, state.z, state.y, state.yx)
probs = np.asarray(arr.probs)


def report(tag, st, reuse_factors=None):
    t0 = time.time()
    if reuse_factors is None:
        sol, fac = shared_admm.solve_shared_factored(
            q, q2, arr.A, arr.cl, arr.cu, arr.lb, arr.ub,
            settings=st, warm=warm)
    else:
        fac = reuse_factors
        sol = shared_admm.solve_shared_frozen(
            q, q2, arr.A, arr.cl, arr.cu, arr.lb, arr.ub, fac,
            settings=st, warm=warm)
    jax.block_until_ready(sol.x)
    wall = time.time() - t0
    x = np.asarray(sol.x)
    pri = np.asarray(sol.pri_res)
    dua = np.asarray(sol.dua_res)
    lin = np.einsum("sn,sn->s", np.asarray(q), x)
    quad = 0.5 * np.einsum("sn,sn->s", np.asarray(q2), x * x)
    eobj = float(probs @ (lin + quad))
    # true constraint violation in UNSCALED space
    A = np.asarray(arr.A)
    Ax = x @ A.T
    viol = np.maximum(np.asarray(arr.cl) - Ax, Ax - np.asarray(arr.cu))
    viol = np.maximum(viol, 0).max()
    print(f"  {tag:34s} wall={wall:6.1f}s floor: worst={max(pri.max(), dua.max()):.2e} "
          f"med={np.median(np.maximum(pri, dua)):.2e} "
          f"true_viol={viol:.2e} eobj={eobj:.6e} "
          f"nan={int(np.isnan(x).any())}", flush=True)
    return fac


print("\nvariants (fresh adaptive factors each):", flush=True)
report("baseline (highest, refine=2)", base)
report("refine=1", dataclasses.replace(base, solve_refine=1))
report("high (bf16x3)", dataclasses.replace(base, matmul_precision="high"))
report("high + refine=1",
       dataclasses.replace(base, matmul_precision="high", solve_refine=1))
report("high + refine=3",
       dataclasses.replace(base, matmul_precision="high", solve_refine=3))
print("\nfrozen-only on baseline factors:", flush=True)
report("frozen high + refine=2",
       dataclasses.replace(base, matmul_precision="high"),
       reuse_factors=factors0)
report("frozen high + refine=1",
       dataclasses.replace(base, matmul_precision="high", solve_refine=1),
       reuse_factors=factors0)
report("frozen baseline", base, reuse_factors=factors0)
