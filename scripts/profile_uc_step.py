"""Profile the UC frozen PH step on TPU: segment counts, sweep time, knobs.

Quantifies where the ~40 s/PH-iteration goes at reference shape
(WECC-240 horizon 24, shared-A, n=16008 m=12408):

- how many segment dispatches `continue_frozen` issues per frozen step and
  what each costs (is the plateau detector's 2-stall rule the bottleneck?)
- per-sweep device time at the current settings vs candidate knobs
  (solve_refine, extra dq2 passes, check_every)

Usage:  python scripts/profile_uc_step.py [S] [horizon] [iters]
"""

import sys
import time

import numpy as np

S = int(sys.argv[1]) if len(sys.argv) > 1 else 256
horizon = int(sys.argv[2]) if len(sys.argv) > 2 else 24
iters = int(sys.argv[3]) if len(sys.argv) > 3 else 4

import jax

import tpusppy
tpusppy.disable_tictoc_output()
from tpusppy.ir import ScenarioBatch
from tpusppy.models import uc_data
from tpusppy.parallel import sharded
from tpusppy.solvers import segmented
from tpusppy.solvers.admm import ADMMSettings

DATA = "/root/reference/paperruns/larger_uc/1000scenarios_wind"

names = uc_data.scenario_names_creator(data_dir=DATA)[:S]
kw = {"data_dir": DATA, "horizon": horizon, "relax_integers": False,
      "num_scens": S}
batch = ScenarioBatch.from_problems(
    [uc_data.scenario_creator(nm, **kw) for nm in names])
print(f"batch: {batch.num_scenarios} x ({batch.num_rows} rows, "
      f"{batch.num_vars} vars) platform={jax.devices()[0].platform}",
      flush=True)

import os
plateau = float(os.environ.get("PROFILE_PLATEAU", "0"))
settings = ADMMSettings(dtype="float32", eps_abs=1e-5, eps_rel=1e-5,
                        max_iter=200, restarts=2, scaling_iters=6,
                        polish_passes=1, sweep_plateau_rtol=plateau,
                        sweep_plateau_window=32)

# --- instrument segment dispatches --------------------------------------
orig_continue = segmented.continue_frozen
seg_log = []


def logged_continue(run_segment, sol, seg_f, budget, **kw):
    # forward everything (all_done/plateau_rtol/pipeline/check_incoming…)
    # — the timing fence below serializes segments, so force the serial
    # protocol to keep the per-segment numbers meaningful
    kw["pipeline"] = False

    def timed_segment(warm):
        t0 = time.time()
        out = run_segment(warm)
        jax.block_until_ready(out.x)
        seg_log.append(time.time() - t0)
        return out

    return orig_continue(timed_segment, sol, seg_f, budget, **kw)


segmented.continue_frozen = logged_continue
sharded.segmented_solvers = segmented  # already same module; belt+braces

mesh = sharded.make_mesh()
arr = sharded.shard_batch(batch, mesh)
S_dev = arr.c.shape[0]
n = arr.c.shape[1]
m = arr.cl.shape[1]
seg_r, seg_f = sharded._dispatch_segments(S_dev, n, m, settings,
                                          factor_batch=1)
print(f"dispatch segments: refresh={seg_r} frozen={seg_f} sweeps "
      f"(max_iter={settings.max_iter} restarts={settings.restarts})",
      flush=True)

refresh, frozen = sharded.make_ph_step_pair(
    batch.tree.nonant_indices, settings, mesh)
state = sharded.init_state(arr, 1.0, settings)

t0 = time.time()
state, out, _ = refresh(state, arr, 0.0)
np.asarray(out.conv)
print(f"compile+iter0: {time.time() - t0:.1f}s "
      f"(segments: {[f'{t:.1f}' for t in seg_log]})", flush=True)
seg_log.clear()

t0 = time.time()
state, out, factors = refresh(state, arr, 1.0)
np.asarray(out.conv)
print(f"refresh iter: {time.time() - t0:.1f}s "
      f"segments={len(seg_log)} {[f'{t:.1f}' for t in seg_log]}",
      flush=True)

for i in range(iters):
    seg_log.clear()
    t0 = time.time()
    state, out = frozen(state, arr, 1.0, factors)
    np.asarray(out.conv)
    worst = max(float(np.asarray(out.pri_res).max()),
                float(np.asarray(out.dua_res).max()))
    print(f"frozen iter {i}: {time.time() - t0:.1f}s "
          f"segments={len(seg_log)} {[f'{t:.1f}' for t in seg_log]} "
          f"last_iters={int(np.asarray(out.pri_res).shape[0])}S "
          f"worst_res={worst:.2e}", flush=True)

# --- raw sweep throughput: time the frozen solver at fixed sweep counts --
print("\nsweep-cost A/B (frozen solver, one dispatch, no continuation):",
      flush=True)
import dataclasses

from tpusppy.solvers import shared_admm


def time_sweeps(tag, st, k_sweeps, **kw_solver):
    st1 = dataclasses.replace(st, max_iter=k_sweeps)
    q, q2, W, rho = None, None, None, None

    import jax.numpy as jnp
    dt = st1.jdtype()
    idx = jnp.asarray(batch.tree.nonant_indices)
    q = arr.c.astype(dt).at[:, idx].add(
        jnp.asarray(np.asarray(state.W), dt)
        - jnp.asarray(np.asarray(state.rho), dt)
        * jnp.asarray(np.asarray(state.xbars), dt))
    q2 = arr.q2.astype(dt).at[:, idx].add(
        jnp.asarray(np.asarray(state.rho), dt))

    def run():
        return shared_admm.solve_shared_frozen(
            q, q2, arr.A, arr.cl, arr.cu, arr.lb, arr.ub, factors,
            settings=st1, warm=(state.x, state.z, state.y, state.yx))

    sol = run()
    jax.block_until_ready(sol.x)   # compile
    t0 = time.time()
    sol = run()
    jax.block_until_ready(sol.x)
    dt_s = time.time() - t0
    it = int(np.asarray(sol.iters).max())
    print(f"  {tag:42s} {dt_s:6.2f}s for {it} sweeps "
          f"=> {dt_s / max(it, 1) * 1e3:7.1f} ms/sweep", flush=True)
    return dt_s / max(it, 1)


base = time_sweeps("baseline (refine=2, ce=4)", settings, seg_f)
t_r1 = time_sweeps("solve_refine=1",
                   dataclasses.replace(settings, solve_refine=1), seg_f)
t_r0 = time_sweeps("solve_refine=0",
                   dataclasses.replace(settings, solve_refine=0), seg_f)
t_ce8 = time_sweeps("check_every=8",
                    dataclasses.replace(settings, check_every=8), seg_f)
t_hi = time_sweeps("matmul high (bf16x3)",
                   dataclasses.replace(settings, matmul_precision="high"),
                   seg_f)
print(f"\nspeedups vs baseline: refine1={base/t_r1:.2f}x "
      f"refine0={base/t_r0:.2f}x ce8={base/t_ce8:.2f}x "
      f"high={base/t_hi:.2f}x", flush=True)
