#!/usr/bin/env python
"""Chaos smoke: SIGKILL one of three wheel CONTROLLERS mid-run; the
survivors must detect, re-mesh, resume from the sharded checkpoints, and
still certify — never hang.

The nightly acceptance for elastic mesh recovery
(tpusppy/parallel/elastic.py, doc/resilience.md "Elastic recovery"),
runnable locally::

    JAX_PLATFORMS=cpu python scripts/chaos_smoke.py

Topology per leg: a 3-controller CPU Gloo hub cylinder (scenarios
sharded across the processes) + 2 spoke processes (Lagrangian outer,
XhatXbar inner) attached over the TCP window fabric.  The fabric boxes
are served by THIS parent process — off-controller, so spoke state
survives controller re-exec (the production posture for an elastic
wheel; a controller-served fabric works too but rides the reconnect
path).

1. **golden** — uninterrupted run to a certified ``rel_gap <= 1e-3``;
   its final gap is the bar.
2. **chaos** — same wheel, per-iteration SHARDED checkpoints; once >= 2
   complete 3-shard sets exist the parent SIGKILLs controller rank 1 (a
   real, uncatchable kill).  Both survivors must turn the next hung/
   failed collective into ControllerLost within ``TPUSPPY_MESH_TIMEOUT``,
   agree on the survivor set over the liveness side-channel, re-exec
   onto a fresh 2-controller mesh (epoch 1), restore the wheel via
   row-range shard reads, and certify a gap no worse than the golden's —
   with the whole recovery visible in the final processes' obs counters
   (``mesh.controller_lost`` / ``mesh.remesh`` /
   ``checkpoint.elastic_restores``) and bounds monotone w.r.t. the
   checkpoint they resumed from.

Known NON-survivable cases (typed errors, documented in
doc/resilience.md): loss of a majority of the original controllers, and
loss of the epoch's rank-min CONTROLLER (the jax coordination service
lives there; its client terminates peers on coordinator transport
failure) — which is why the victim here is rank 1.

The whole script is bounded by a HARD watchdog (``CHAOS_DEADLINE_SECS``,
default 1500): a regression that hangs fails loudly instead of pinning
CI.  Worker legs are this same file with ``--controller`` / ``--spoke``.
Exit code 0 = pass.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCENS = int(os.environ.get("CHAOS_SCENS", "6"))
K = 3                       # farmer root nonants (crops)
MESH_TIMEOUT = float(os.environ.get("TPUSPPY_MESH_TIMEOUT", "20"))
DEADLINE = float(os.environ.get("CHAOS_DEADLINE_SECS", "1800"))
GAP = float(os.environ.get("CHAOS_GAP", "1e-3"))
# bound-harvest budget after the PH loop: 7 concurrent jax processes on
# one CI box make spoke rounds slow — the gap target needs wall time,
# not more hub iterations
HARVEST = float(os.environ.get("CHAOS_HARVEST_SECS", "420"))


def log(msg):
    print(f"chaos-smoke: {msg}", file=sys.stderr, flush=True)


# ---------------------------------------------------------------------------
# Controller leg (child process; re-execs itself on re-mesh)
# ---------------------------------------------------------------------------
def controller():
    sys.path.insert(0, REPO)
    from tpusppy.models import farmer
    from tpusppy.obs import metrics
    from tpusppy.parallel import elastic
    from tpusppy.runtime.tcp_window_service import TcpWindowFabric

    spec = elastic.ElasticSpec(
        rank=int(os.environ["CHAOS_RANK"]),
        n_original=int(os.environ["CHAOS_N"]),
        checkpoint_dir=os.environ["CHAOS_CKPT_DIR"],
        coord_port_base=int(os.environ["CHAOS_COORD_BASE"]),
        liveness_port_base=int(os.environ["CHAOS_LIVENESS_BASE"]),
        secret=int(os.environ["CHAOS_SECRET"]),
        mesh_timeout_secs=MESH_TIMEOUT)

    def fabric_factory(spec):
        # every controller is a CLIENT of the parent-served box fabric
        return TcpWindowFabric(
            connect=("127.0.0.1", int(os.environ["CHAOS_FABRIC_PORT"])),
            secret=int(os.environ["CHAOS_FABRIC_SECRET"]))

    options = {
        "defaultPHrho": 1.0, "PHIterLimit": 200,
        "rel_gap": GAP, "linger_secs": 8.0, "harvest_secs": HARVEST,
        "checkpoint_every_iters": 1, "checkpoint_every_secs": None,
        "solver_options": {"dtype": "float64", "eps_abs": 1e-8,
                           "eps_rel": 1e-8, "max_iter": 300,
                           "restarts": 3}}
    res = elastic.elastic_wheel_hub(
        spec, farmer.scenario_names_creator(SCENS),
        farmer.scenario_creator,
        scenario_creator_kwargs={"num_scens": SCENS},
        options=options, fabric_factory=fabric_factory,
        spoke_roles=[{"bound": "outer", "wants": "W"},
                     {"bound": "inner", "wants": "nonants"}])
    print(json.dumps({
        "rank": spec.rank,
        "epoch": int(os.environ.get(elastic.ENV_EPOCH, "0")),
        "detect_secs": float(os.environ.get(elastic.ENV_DETECT_SECS, "0")),
        "inner": res.BestInnerBound, "outer": res.BestOuterBound,
        "rel_gap": res.rel_gap, "iters": res.iters,
        "controller_lost": metrics.value("mesh.controller_lost"),
        "remesh": metrics.value("mesh.remesh"),
        "elastic_restores": metrics.value("checkpoint.elastic_restores"),
    }), flush=True)


# ---------------------------------------------------------------------------
# Spoke leg (child process; attached to the PARENT's fabric — must ride
# straight through the controller outage)
# ---------------------------------------------------------------------------
def spoke():
    sys.path.insert(0, REPO)
    from tpusppy.models import farmer
    from tpusppy.spin_the_wheel import _spoke_worker

    rank = int(os.environ["SPOKE_RANK"])
    if os.environ["SPOKE_KIND"] == "lagrangian":
        from tpusppy.cylinders import LagrangianOuterBound
        from tpusppy.phbase import PHBase

        spoke_class, opt_class = LagrangianOuterBound, PHBase
    else:
        from tpusppy.cylinders import XhatXbarInnerBound
        from tpusppy.xhat_eval import Xhat_Eval

        spoke_class, opt_class = XhatXbarInnerBound, Xhat_Eval
    sd = {
        "spoke_class": spoke_class, "opt_class": opt_class,
        "opt_kwargs": {
            "options": {"defaultPHrho": 1.0, "PHIterLimit": 300,
                        "convthresh": -1.0,
                        "solver_options": {"dtype": "float64",
                                           "eps_abs": 1e-8,
                                           "eps_rel": 1e-8,
                                           "max_iter": 300,
                                           "restarts": 3}},
            "all_scenario_names": farmer.scenario_names_creator(SCENS),
            "scenario_creator": farmer.scenario_creator,
            "scenario_creator_kwargs": {"num_scens": SCENS},
        },
    }
    _spoke_worker(
        ("tcp", "127.0.0.1", int(os.environ["CHAOS_FABRIC_PORT"]),
         f"chaos{os.getpid()}_{rank}",
         int(os.environ["CHAOS_FABRIC_SECRET"])),
        sd, rank)


# ---------------------------------------------------------------------------
# Orchestration (parent: serves the fabric, runs both legs, hard watchdog)
# ---------------------------------------------------------------------------
def _arm_hard_watchdog(procs_box):
    """A regression must FAIL CI, not hang it: past the deadline, kill
    every child and the parent itself."""
    def fire():
        time.sleep(DEADLINE)
        log(f"HARD WATCHDOG: {DEADLINE}s deadline breached — killing "
            "everything")
        for p in procs_box:
            try:
                p.kill()
            except Exception:
                pass
        os._exit(2)

    t = threading.Thread(target=fire, daemon=True)
    t.start()


def _env_for(role_env):
    env = {k: v for k, v in os.environ.items()
           if "AXON" not in k and not k.startswith("TPU_")
           and k != "PYTHONPATH"}
    env.update({
        "PYTHONPATH": REPO, "JAX_PLATFORMS": "cpu",
        "JAX_ENABLE_X64": "1",
        "TPUSPPY_MESH_TIMEOUT": str(MESH_TIMEOUT),
        "JAX_COMPILATION_CACHE_DIR": os.environ.get(
            "JAX_COMPILATION_CACHE_DIR",
            os.path.join(os.path.expanduser("~"), ".cache",
                         "tpusppy_xla")),
    })
    env.update({k: str(v) for k, v in role_env.items()})
    return env


def _run_leg(tag, ckdir, procs_box, kill_rank=None):
    from tpusppy.resilience import checkpoint as _ckpt
    from tpusppy.runtime.tcp_window_service import TcpWindowFabric

    from tpusppy.parallel.elastic import free_port_block

    n_ctl = 3
    lengths = [(SCENS * K + 2, 1), (SCENS * K + 2, 1)]
    fabric = TcpWindowFabric(spoke_lengths=lengths)
    common = {
        "CHAOS_N": n_ctl, "CHAOS_CKPT_DIR": ckdir,
        # whole CONSECUTIVE blocks reserved: coordinators use base+epoch,
        # liveness servers base+rank — a single free port only vouches
        # for the base
        "CHAOS_COORD_BASE": free_port_block(n_ctl),
        "CHAOS_LIVENESS_BASE": free_port_block(n_ctl),
        "CHAOS_SECRET": 0x5EC0DE + os.getpid(),
        "CHAOS_FABRIC_PORT": fabric.port,
        "CHAOS_FABRIC_SECRET": fabric.secret,
        "CHAOS_SCENS": SCENS,
        # one virtual device per controller: 3-way sharded epoch 0,
        # 2-way (uneven, ghost-padded) epoch 1
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
    }
    me = os.path.abspath(__file__)
    ctls = [subprocess.Popen(
        [sys.executable, me, "--controller"],
        env=_env_for(common | {"CHAOS_RANK": r}),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        for r in range(n_ctl)]
    spoke_env = {k: v for k, v in common.items() if k != "XLA_FLAGS"}
    spokes = [subprocess.Popen(
        [sys.executable, me, "--spoke"],
        env=_env_for(spoke_env | {"SPOKE_RANK": r, "SPOKE_KIND": kind}),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        for r, kind in ((1, "lagrangian"), (2, "xhatxbar"))]
    procs_box.extend(ctls + spokes)

    killed_at = None
    if kill_rank is not None:
        # wait for >= 2 COMPLETE sharded sets, then the real kill
        t0 = time.time()
        while True:
            sets = [p for _it, p in _ckpt.list_checkpoints(ckdir)
                    if f".s000of{n_ctl:03d}.npz" in p]
            if len(sets) >= 2:
                break
            dead = [i for i, c in enumerate(ctls)
                    if c.poll() is not None]
            assert not dead, \
                f"controller(s) {dead} exited before the kill: " \
                + str([ctls[i].communicate()[1][-2000:] for i in dead])
            assert time.time() - t0 < 900, \
                "no sharded snapshots within 900s"
            time.sleep(0.25)
        killed_at = _ckpt.load_latest(ckdir)
        os.kill(ctls[kill_rank].pid, signal.SIGKILL)
        log(f"{tag}: SIGKILLed controller rank {kill_rank} at "
            f"checkpoint iteration {killed_at.iteration} "
            f"(outer={killed_at.best_outer:.2f} "
            f"inner={killed_at.best_inner:.2f})")

    outs = {}
    raw = {}
    for r, c in enumerate(ctls):
        if kill_rank is not None and r == kill_rank:
            c.wait(timeout=60)
            continue
        try:
            raw[r] = c.communicate(timeout=DEADLINE)
        except subprocess.TimeoutExpired:
            c.kill()
            raw[r] = c.communicate()
    # post-mortem trail for EVERY controller before any verdict: the
    # interesting failures are cross-process timing, and asserting on
    # the first bad controller would discard its peer's evidence
    for r, (out, err) in raw.items():
        with open(os.path.join(ckdir, f"controller_{r}.stderr"),
                  "w") as f:
            f.write(err)
    for r, (out, err) in raw.items():
        assert ctls[r].returncode == 0, \
            f"{tag}: controller {r} rc={ctls[r].returncode}\n{err[-4000:]}"
        outs[r] = json.loads(
            [ln for ln in out.splitlines() if ln.startswith("{")][-1])
    for sp in spokes:
        try:
            sp.wait(timeout=120)
        except subprocess.TimeoutExpired:
            sp.kill()                       # bounded teardown, not a fail
    fabric.close()
    return outs, killed_at


def main():
    import tempfile

    sys.path.insert(0, REPO)
    procs_box = []
    _arm_hard_watchdog(procs_box)
    base = tempfile.mkdtemp(prefix="chaos_smoke_")
    log(f"workdir {base} (mesh timeout {MESH_TIMEOUT}s)")

    t0 = time.time()
    golden, _ = _run_leg("golden", os.path.join(base, "golden_ck"),
                         procs_box)
    g_gap = golden[0]["rel_gap"]
    log(f"golden rel_gap={g_gap:.3e} in {time.time() - t0:.0f}s")
    assert g_gap <= GAP + 1e-12, "golden run did not certify"
    assert all(o["epoch"] == 0 for o in golden.values())

    t1 = time.time()
    chaos, killed_at = _run_leg("chaos", os.path.join(base, "chaos_ck"),
                                procs_box, kill_rank=1)
    log(f"chaos leg done in {time.time() - t1:.0f}s")
    r0, r2 = chaos[0], chaos[2]

    # survivors re-meshed exactly once and agree bit-for-bit
    assert r0["epoch"] == 1 and r2["epoch"] == 1, (r0, r2)
    assert r0["inner"] == r2["inner"] and r0["outer"] == r2["outer"]
    # detection within the mesh timeout (+ first-poll slack), never a hang
    for r in (r0, r2):
        assert 0 < r["detect_secs"] <= MESH_TIMEOUT + 10.0, r
    # the whole recovery is visible in the FINAL processes' registries
    for r in (r0, r2):
        assert r["controller_lost"] >= 1, r
        assert r["remesh"] >= 1, r
        assert r["elastic_restores"] >= 1, r
    # bounds monotone w.r.t. the snapshot the survivors resumed from
    assert r0["outer"] >= killed_at.best_outer - 1e-9, \
        (r0["outer"], killed_at.best_outer)
    assert r0["inner"] <= killed_at.best_inner + 1e-9, \
        (r0["inner"], killed_at.best_inner)
    # certified no worse than the uninterrupted golden
    assert r0["rel_gap"] <= max(g_gap, GAP) + 1e-9, \
        f"post-recovery gap {r0['rel_gap']} worse than golden {g_gap}"
    log(f"recovered: detect {r0['detect_secs']:.1f}s + "
        f"{r2['detect_secs']:.1f}s, epoch-1 gap {r0['rel_gap']:.3e} "
        f"(golden {g_gap:.3e})")
    log("PASS")


if __name__ == "__main__":
    if "--controller" in sys.argv[1:]:
        controller()
    elif "--spoke" in sys.argv[1:]:
        spoke()
    else:
        main()
