#!/usr/bin/env python
"""Continuous-batching smoke: K-slot fused serving vs forced time-slicing.

Nightly CI acceptance for doc/serving.md "Continuous batching", runnable
locally::

    JAX_PLATFORMS=cpu python scripts/batching_smoke.py

Two phases against a warm same-family farmer workload:

1. SEMANTICS (untimed, ``SolveServer(batch_slots=3)``): six staggered
   requests — the back half submitted only after the front half's batch
   has executed windows, so they must JOIN mid-run — plus one forced
   preemption of a running member, which must EVICT (bank through the
   checkpoint seam), free the slot for a queued backfill, and later
   rejoin and complete certified.  Joiners bind the batch's programs
   WARM: zero ``aot.misses`` on every post-leader request.

2. THROUGHPUT (timed, min-of-``SMOKE_BATCH_REPS`` bursts): six requests
   submitted at once through the batched server, then through a FORCED
   time-sliced baseline — ``batch_slots=None`` plus a churn driver that
   ``preempt()``s the running tenant every ``SMOKE_BATCH_QUANTUM``
   seconds.  The forcing matters: without it the server's family
   affinity serializes same-family requests FCFS (head-of-line
   blocking, no concurrent progress), which is not time-slicing at all.
   The churned baseline grants every tenant a quantum — the same
   fairness the batch gives all K slots each window — and pays the
   park/bank/resume/Iter0 cycle per quantum that continuous batching
   deletes.  Asserts the batched burst sustains at least
   ``SMOKE_BATCH_SPEEDUP``x (default 3) the baseline's aggregate
   requests/s, with every request in BOTH modes certified at the same
   gap target (certification is unchanged; the per-request gap values
   legitimately differ because certification is checked at window
   boundaries and the two modes traverse different window grids).

Prints one JSON line with the measured figures.  Exit 0 = pass.  A hard
watchdog (``SMOKE_BATCH_DEADLINE_SECS``, default 900) ``os._exit(2)``s a
wedged run so CI never hangs.
"""

import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SPEEDUP = float(os.environ.get("SMOKE_BATCH_SPEEDUP", "3.0"))
DEADLINE = float(os.environ.get("SMOKE_BATCH_DEADLINE_SECS", "900"))
QUANTUM = float(os.environ.get("SMOKE_BATCH_QUANTUM", "0.2"))
REPS = int(os.environ.get("SMOKE_BATCH_REPS", "2"))
N_REQ = 6
K = 3
S = int(os.environ.get("SMOKE_BATCH_SCENS", "3"))
ITERS = 400


def _arm_watchdog():
    def _bomb():
        time.sleep(DEADLINE)
        print(json.dumps({"ok": False, "error": "deadline exceeded"}),
              flush=True)
        os._exit(2)

    threading.Thread(target=_bomb, daemon=True).start()


def _req(SolveRequest, rid, i):
    return SolveRequest(model="farmer", num_scens=S, request_id=rid,
                        creator_kwargs={"seedoffset": 31 * i},
                        options={"PHIterLimit": ITERS})


def main():
    _arm_watchdog()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import tempfile

    from tpusppy.obs import metrics
    from tpusppy.service import SolveRequest, SolveServer

    # ---- phase 1: boundary semantics on the batched server ----------------
    work_b = tempfile.mkdtemp(prefix="batching_smoke_b_")
    with SolveServer(work_dir=work_b, batch_slots=K,
                     in_wheel_bounds=True, quantum_secs=300.0,
                     linger_secs=0.0) as srv:
        # warm the family: the one-time program build must not pollute
        # either the semantics run or the throughput comparison
        srv.result(srv.submit(_req(SolveRequest, "warm-b", 99)),
                   timeout=600)
        joins0 = metrics.value("batching.joins")
        evict0 = metrics.value("batching.evictions")
        rids = [srv.submit(_req(SolveRequest, f"b{i}", i))
                for i in range(K)]
        # stagger the back half: they must JOIN mid-run.  Wait until the
        # front half's batch has executed windows AND is still live.
        w0 = metrics.value("batching.windows")
        deadline = time.monotonic() + 300
        while (metrics.value("batching.windows") <= w0
               and time.monotonic() < deadline):
            time.sleep(0.002)
        mid_run = any(srv._tenants[r].status == "running" for r in rids)
        rids += [srv.submit(_req(SolveRequest, f"b{i}", i))
                 for i in range(K, N_REQ)]
        # force ONE eviction-with-backfill: preempt a running member —
        # its slot banks + frees at the next boundary, a queued request
        # backfills it, and the preempted tenant rejoins later
        evicted = None
        for _ in range(5000):
            running = [r for r in rids
                       if srv._tenants[r].status == "running"
                       and srv._tenants[r].record["iters"] > 0]
            if running:
                evicted = running[0]
                srv.preempt(evicted)
                break
            if all(srv._tenants[r].status in ("done", "failed")
                   for r in rids):
                break
            time.sleep(0.002)
        recs_sem = {r: srv.result(r, timeout=600) for r in rids}
        joins = metrics.value("batching.joins") - joins0
        evictions = metrics.value("batching.evictions") - evict0
        warm_misses = sum(recs_sem[f"b{i}"]["aot_misses"]
                          for i in range(N_REQ))

        # ---- phase 2a: timed batched bursts (clean, all-at-once) ----------
        walls_b, gaps_b = [], []
        for rep in range(REPS):
            t0 = time.monotonic()
            burst = [srv.submit(_req(SolveRequest, f"tb{rep}_{i}", i))
                     for i in range(N_REQ)]
            recs = [srv.result(r, timeout=600) for r in burst]
            walls_b.append(time.monotonic() - t0)
            gaps_b = [r["rel_gap"] for r in recs]
            cert_b = all(r["certified"] and r["batched"] for r in recs)
        summary_b = srv.slo_summary()
    wall_b = min(walls_b)

    # ---- phase 2b: forced time-sliced baseline ----------------------------
    # batch_slots=None alone is NOT time-slicing — family affinity runs
    # same-family requests serially FCFS.  The churn driver imposes the
    # fairness quantum a real time-sliced scheduler grants each tenant.
    work_t = tempfile.mkdtemp(prefix="batching_smoke_t_")
    with SolveServer(work_dir=work_t, batch_slots=None,
                     in_wheel_bounds=True, quantum_secs=QUANTUM,
                     linger_secs=0.0) as srv:
        srv.result(srv.submit(_req(SolveRequest, "warm-t", 99)),
                   timeout=600)
        stop = threading.Event()
        active = set()

        def _churn():
            while not stop.is_set():
                time.sleep(QUANTUM)
                for t in list(srv._tenants.values()):
                    if t.status == "running" and t.id in active:
                        srv.preempt(t.id)
                        break

        threading.Thread(target=_churn, daemon=True).start()
        walls_t, gaps_t, slices_t = [], [], []
        for rep in range(REPS):
            t0 = time.monotonic()
            burst = [srv.submit(_req(SolveRequest, f"tt{rep}_{i}", i))
                     for i in range(N_REQ)]
            active.update(burst)
            recs = [srv.result(r, timeout=600) for r in burst]
            walls_t.append(time.monotonic() - t0)
            active.clear()
            gaps_t = [r["rel_gap"] for r in recs]
            slices_t = [r["slices"] for r in recs]
            cert_t = all(r["certified"] for r in recs)
        stop.set()
        summary_t = srv.slo_summary()
    wall_t = min(walls_t)

    batched_rps = N_REQ / wall_b
    timesliced_rps = N_REQ / wall_t
    gap_drift = max(abs(a - b) / max(abs(b), 1e-12)
                    for a, b in zip(gaps_b, gaps_t))

    checks = {
        "semantics_all_certified": all(r["certified"] and r["batched"]
                                       for r in recs_sem.values()),
        "mid_run_join": bool(mid_run),
        "eviction_with_backfill": (evicted is not None
                                   and evictions >= 1
                                   and joins >= N_REQ + 1
                                   and recs_sem[evicted]["certified"]
                                   and recs_sem[evicted]["slices"] >= 2),
        "joiners_warm_zero_misses": warm_misses == 0,
        "all_batched_certified": bool(cert_b),
        "all_timesliced_certified": bool(cert_t),
        "baseline_actually_timesliced": min(slices_t) >= 2,
        "speedup_ok": batched_rps >= SPEEDUP * timesliced_rps,
    }
    line = {
        "ok": all(checks.values()),
        "checks": checks,
        "requests": N_REQ, "batch_slots": K, "S": S,
        "batched_walls_s": [round(w, 3) for w in walls_b],
        "timesliced_walls_s": [round(w, 3) for w in walls_t],
        "batched_requests_per_s": round(batched_rps, 3),
        "timesliced_requests_per_s": round(timesliced_rps, 3),
        "speedup": round(batched_rps / max(timesliced_rps, 1e-9), 2),
        "speedup_bar": SPEEDUP,
        "quantum_s": QUANTUM,
        "baseline_slices": slices_t,
        "gap_drift": gap_drift,
        "joins": joins, "evictions": evictions,
        "evicted_rejoined": evicted,
        "p50_queue_wait_batched_s": summary_b["p50_queue_wait_s"],
        "p50_queue_wait_timesliced_s": summary_t["p50_queue_wait_s"],
        "gaps_batched": [round(g, 8) for g in gaps_b],
        "gaps_timesliced": [round(g, 8) for g in gaps_t],
    }
    print(json.dumps(line), flush=True)
    return 0 if line["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
