#!/usr/bin/env python
"""Serving chaos smoke: SIGKILL the solve server mid-slice, restart it,
recover every journaled tenant warm.

The nightly CI acceptance for DURABLE serving (doc/serving.md
"Durability"), runnable locally::

    JAX_PLATFORMS=cpu python scripts/serving_chaos_smoke.py

Three legs, each a REAL OS process:

1. **golden** — an uninterrupted server runs the 4 requests (two
   isomorphic pairs across two model families: farmer + uc-lite) to
   completion; the per-request certified gaps are the bar.
2. **victim** — a TCP-served SolveServer over a fresh work dir receives
   the same 4 requests (fixed request ids) from 4 client slots with a
   ~1 s scheduling quantum, so the two family LEADERS time-slice
   (park/resume) while the followers queue behind family affinity.  The
   parent watches the request journal until one leader is PARKED (its
   checkpoint banked) and the other is mid-slice RUNNING, then SIGKILLs
   the server — no cleanup, no atexit.
3. **recover** — ``SolveServer.recover_from`` on the SAME work dir (a
   fresh TCP frontend, new port).  The parent reconnects with fresh
   clients and asserts the durability contract:

   - every journaled tenant recovered: all 4 finish ``done``;
   - resumed tenants certify <= the golden's gap (+ dust) with
     ``bounds_monotone`` vs the pre-kill snapshot;
   - the leader that was PARKED at the kill resumed WARM from its park
     checkpoint (``recovered == "warm"``);
   - recovery is warm for previously-compiled families: the followers
     (queued at the kill, running only after their family's leader
     completed in the restarted lifetime) bind with ``aot_misses == 0``;
   - queued tenants re-entered the queue in original submission order
     (first ``running`` transitions after the recovery marker);
   - reconnected clients get their ORIGINAL results by id
     (``fetch``), and a duplicate submit of a journaled id resolves
     idempotently to the original record.

Exit 0 = pass.  A hard watchdog (``CHAOS_DEADLINE_SECS``, default 1500)
``os._exit(2)``s a wedged run so CI never hangs.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEADLINE = float(os.environ.get("CHAOS_DEADLINE_SECS", "1500"))

REQUESTS = {
    # two isomorphic pairs; the *-1 member of each family is its
    # compile leader (submitted first), *-2 the warm follower
    "req-f1": {"model": "farmer", "num_scens": 4,
               "creator_kwargs": {"seedoffset": 0},
               "options": {"PHIterLimit": 150}},
    "req-u1": {"model": "uc_lite", "num_scens": 3,
               "creator_kwargs": {"num_gens": 2, "horizon": 4,
                                  "relax_integers": True, "seedoffset": 0},
               "options": {"PHIterLimit": 300, "rel_gap": 5e-3}},
    "req-f2": {"model": "farmer", "num_scens": 4,
               "creator_kwargs": {"seedoffset": 901},
               "options": {"PHIterLimit": 150}},
    "req-u2": {"model": "uc_lite", "num_scens": 3,
               "creator_kwargs": {"num_gens": 2, "horizon": 4,
                                  "relax_integers": True, "seedoffset": 44},
               "options": {"PHIterLimit": 300, "rel_gap": 5e-3}},
}
ORDER = ["req-f1", "req-u1", "req-f2", "req-u2"]
LEADERS = ("req-f1", "req-u1")
FOLLOWERS = ("req-f2", "req-u2")
GAP_TARGET = {"req-f1": 1e-3, "req-u1": 5e-3, "req-f2": 1e-3,
              "req-u2": 5e-3}


def log(msg):
    print(f"serving-chaos: {msg}", file=sys.stderr, flush=True)


# ---------------------------------------------------------------------------
# journal folding (parent-side, pure stdlib — no tpusppy imports needed
# to WATCH the victim)
# ---------------------------------------------------------------------------
def fold_journal(path):
    """{rid: status} + the raw event list (tolerates a torn tail)."""
    status, events = {}, []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    ev = json.loads(line)
                except ValueError:
                    continue
                events.append(ev)
                if ev.get("ev") == "accepted":
                    status[ev["rid"]] = "queued"
                elif ev.get("ev") == "status" and ev.get("rid") in status:
                    status[ev["rid"]] = ev["status"]
    except OSError:
        pass
    return status, events


def has_checkpoint(work, rid):
    d = os.path.join(work, "tenants", rid)
    try:
        return any(nm.startswith("ckpt_") and nm.endswith(".npz")
                   for nm in os.listdir(d))
    except OSError:
        return False


# ---------------------------------------------------------------------------
# server legs (child processes)
# ---------------------------------------------------------------------------
def serve():
    sys.path.insert(0, REPO)
    from tpusppy.service import SolveServer
    from tpusppy.service.net import TcpServiceFrontend

    mode = os.environ["SERVE_MODE"]        # golden | victim | recover
    work = os.environ["SERVE_DIR"]

    if mode == "golden":
        from tpusppy.service import SolveRequest

        with SolveServer(work_dir=work, quantum_secs=600.0,
                         linger_secs=45.0) as srv:
            rids = [srv.submit(SolveRequest(
                request_id=f"golden-{rid}", **REQUESTS[rid]))
                for rid in ORDER]
            gaps = {r.split("golden-")[1]: srv.result(r, timeout=900)
                    for r in rids}
        bad = {k: v["status"] for k, v in gaps.items()
               if v["status"] != "done" or not v["certified"]}
        out = {rid: rec["rel_gap"] for rid, rec in gaps.items()}
        with open(os.path.join(work, "golden.json"), "w") as f:
            json.dump({"gaps": out, "bad": bad}, f)
        print(json.dumps({"mode": "golden", "gaps": out}), flush=True)
        return 0 if not bad else 1

    recover = mode == "recover"
    srv = (SolveServer.recover_from(work, quantum_secs=1.0,
                                    linger_secs=45.0)
           if recover else
           SolveServer(work_dir=work, quantum_secs=1.0, linger_secs=45.0))
    front = TcpServiceFrontend(srv, slots=4)
    conn = {"port": front.port, "secret": front.secret}
    # atomic conn-file publish (the parent polls for it)
    tmp = os.path.join(work, f".conn_{mode}.tmp")
    with open(tmp, "w") as f:
        json.dump(conn, f)
    os.replace(tmp, os.path.join(work, f"conn_{mode}.json"))
    log(f"{mode} serving on port {front.port} (pid {os.getpid()})")
    # run until the parent is done with us (victim: SIGKILLed; recover:
    # parent drops a PARENT_DONE marker after its assertions)
    marker = os.path.join(work, "PARENT_DONE")
    while not os.path.exists(marker):
        time.sleep(0.2)
    front.close()
    srv.shutdown(drain=True, timeout=120)
    return 0


# ---------------------------------------------------------------------------
# orchestration (parent)
# ---------------------------------------------------------------------------
def _arm_watchdog():
    def _bomb():
        time.sleep(DEADLINE)
        print(json.dumps({"ok": False, "error": "deadline exceeded"}),
              flush=True)
        os._exit(2)

    threading.Thread(target=_bomb, daemon=True).start()


def _spawn(mode, work):
    env = dict(os.environ, SERVE_MODE=mode, SERVE_DIR=work, PYTHONPATH=REPO)
    env.setdefault("JAX_PLATFORMS", "cpu")
    return subprocess.Popen([sys.executable, os.path.abspath(__file__),
                             "--serve"], env=env)


def _wait_file(path, timeout, what):
    t0 = time.time()
    while not os.path.exists(path):
        if time.time() - t0 > timeout:
            raise SystemExit(f"timed out waiting for {what}")
        time.sleep(0.2)
    with open(path) as f:
        return json.load(f)


def main():
    import tempfile

    _arm_watchdog()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    base = tempfile.mkdtemp(prefix="serving_chaos_")
    log(f"workdir {base}")

    # ---- leg 1: golden --------------------------------------------------
    golden_dir = os.path.join(base, "golden")
    os.makedirs(golden_dir)
    proc = _spawn("golden", golden_dir)
    if proc.wait(timeout=900) != 0:
        raise SystemExit("golden leg failed")
    golden = json.load(open(os.path.join(golden_dir, "golden.json")))
    assert not golden["bad"], f"golden leg uncertified: {golden['bad']}"
    gaps = golden["gaps"]
    log(f"golden gaps: { {k: round(v, 6) for k, v in gaps.items()} }")

    # ---- leg 2: victim --------------------------------------------------
    work = os.path.join(base, "work")
    os.makedirs(work)
    victim = _spawn("victim", work)
    conn = _wait_file(os.path.join(work, "conn_victim.json"), 120,
                      "victim conn file")
    from tpusppy.service.net import SolveClient

    clients = {rid: SolveClient("127.0.0.1", conn["port"], conn["secret"],
                                slot=i + 1)
               for i, rid in enumerate(ORDER)}
    for rid in ORDER:                      # fixed ids => idempotent retries
        clients[rid].submit(dict(REQUESTS[rid], request_id=rid))
        time.sleep(0.3)                    # deterministic admission order

    # kill window: one leader PARKED with its checkpoint banked, the
    # other mid-slice RUNNING, both unfinished, followers still queued
    jpath = os.path.join(work, "journal.jsonl")
    parked_rid = None
    t0 = time.time()
    while time.time() - t0 < 600:
        if victim.poll() is not None:
            raise SystemExit("victim exited early — nothing to SIGKILL")
        status, _ = fold_journal(jpath)
        if len(status) == 4 and \
                all(status[r] == "queued" for r in FOLLOWERS):
            st = {r: status[r] for r in LEADERS}
            parked = [r for r, s in st.items()
                      if s == "parked" and has_checkpoint(work, r)]
            running = [r for r, s in st.items() if s == "running"]
            if parked and running:
                parked_rid = parked[0]
                break
        time.sleep(0.1)
    if parked_rid is None:
        raise SystemExit("kill window never materialized (leaders "
                         f"finished too fast? journal: {fold_journal(jpath)[0]})")
    os.kill(victim.pid, signal.SIGKILL)    # the crash, for real
    victim.wait(timeout=60)
    status_at_kill, _ = fold_journal(jpath)
    log(f"SIGKILLed victim with journal state {status_at_kill} "
        f"(parked leader: {parked_rid})")
    for cli in clients.values():
        cli.close()

    # ---- leg 3: recover -------------------------------------------------
    recov = _spawn("recover", work)
    conn2 = _wait_file(os.path.join(work, "conn_recover.json"), 180,
                       "recover conn file")
    # "reconnected clients": fresh client objects, same request ids
    clients = {rid: SolveClient("127.0.0.1", conn2["port"], conn2["secret"],
                                slot=i + 1)
               for i, rid in enumerate(ORDER)}
    failures = []

    def check(cond, msg):
        if not cond:
            failures.append(msg)

    recs = {}
    for rid in ORDER:
        rec = clients[rid].fetch(rid, timeout=900)
        recs[rid] = rec
        check(rec.get("request_id") == rid,
              f"{rid}: fetched someone else's record: {rec}")
        check(rec.get("status") == "done",
              f"{rid}: {rec.get('status')} ({rec.get('error')})")
        check(rec.get("certified"),
              f"{rid}: uncertified (gap {rec.get('rel_gap')})")
        check(rec.get("bounds_monotone"),
              f"{rid}: bounds regressed across the recovery")
        g = rec.get("rel_gap")
        check(g is not None
              and g <= max(gaps[rid], GAP_TARGET[rid]) + 1e-9,
              f"{rid}: recovered gap {g} worse than golden {gaps[rid]}")
    # the parked leader resumed WARM from its park checkpoint
    check(recs[parked_rid].get("recovered") == "warm",
          f"{parked_rid} was parked with a checkpoint but recovered "
          f"{recs[parked_rid].get('recovered')!r}")
    check(recs[parked_rid].get("slices", 0) >= 2,
          f"{parked_rid} did not resume ({recs[parked_rid].get('slices')} "
          "slices)")
    # warm recovery for previously-compiled families: the followers ran
    # only in the restarted lifetime, AFTER their family's leader
    # completed there — zero recompiles (aot.misses delta 0)
    for rid in FOLLOWERS:
        check(recs[rid].get("warm_hit") is True,
              f"{rid}: follower did not bind warm")
        check(recs[rid].get("aot_misses") == 0,
              f"{rid}: follower recompiled ({recs[rid].get('aot_misses')} "
              "misses) — recovery was not warm")
    # queued tenants re-entered in ORIGINAL order: among the followers,
    # first `running` transitions after the recovery marker follow the
    # journaled admission (seq) order
    _, events = fold_journal(jpath)
    seqs = {e["rid"]: e["seq"] for e in events
            if e.get("ev") == "accepted" and e.get("rid") in FOLLOWERS}
    expect_order = sorted(FOLLOWERS, key=lambda r: seqs.get(r, 1 << 30))
    last_marker = max((i for i, e in enumerate(events)
                       if e.get("ev") == "recovery"), default=-1)
    check(last_marker >= 0, "no recovery marker journaled")
    first_run = {}
    for e in events[last_marker + 1:]:
        if e.get("ev") == "status" and e.get("status") == "running":
            first_run.setdefault(e["rid"], len(first_run))
    f_order = [r for r in sorted(first_run, key=first_run.get)
               if r in FOLLOWERS]
    check(f_order == expect_order,
          f"followers ran out of order after recovery: {f_order} "
          f"(admitted {expect_order})")
    # duplicate submit after reconnect resolves idempotently to the
    # ORIGINAL record (same id, same result — not a second run)
    dup = clients[ORDER[0]]
    dup.submit(dict(REQUESTS["req-f1"], request_id="req-f1"))
    rec = dup.wait(timeout=120)
    check(rec.get("request_id") == "req-f1"
          and rec.get("rel_gap") == recs["req-f1"]["rel_gap"],
          f"duplicate submit did not resolve to the original: {rec}")

    # let the recover leg drain + exit
    with open(os.path.join(work, "PARENT_DONE"), "w") as f:
        f.write("ok")
    rc = recov.wait(timeout=240)
    check(rc == 0, f"recover leg exited rc={rc}")
    for cli in clients.values():
        cli.close()

    out = {
        "ok": not failures, "failures": failures,
        "parked_leader": parked_rid,
        "status_at_kill": status_at_kill,
        "recovered": {r: recs[r].get("recovered") for r in ORDER},
        "gaps": {r: recs[r].get("rel_gap") for r in ORDER},
        "golden_gaps": gaps,
        "follower_misses": {r: recs[r].get("aot_misses")
                            for r in FOLLOWERS},
        "slices": {r: recs[r].get("slices") for r in ORDER},
    }
    print(json.dumps(out), flush=True)
    if failures:
        for f_ in failures:
            log(f"FAIL: {f_}")
        return 1
    log("PASS")
    return 0


if __name__ == "__main__":
    if "--serve" in sys.argv[1:]:
        sys.exit(serve())
    sys.path.insert(0, REPO)
    sys.exit(main())
