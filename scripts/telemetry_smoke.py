#!/usr/bin/env python
"""Telemetry-plane smoke: live observability of a batched serving run.

Nightly CI acceptance for doc/observability.md, runnable locally::

    JAX_PLATFORMS=cpu python scripts/telemetry_smoke.py

Two phases:

1. WATCHABLE SERVING (``SolveServer(batch_slots=3)`` + TCP frontend
   with a scrape endpoint): three same-family farmer requests run
   fused while one ``SolveClient.watch`` stream per tenant drains its
   live progress events.  Asserts, per tenant: at least one
   ``bound_update`` streamed, the terminal ``done`` is certified, and
   the live gap series ENDS at the certified gap of the tenant's own
   record.  Meanwhile ``GET /metrics`` is scraped MID-RUN and must
   serve per-tenant gauges (Prometheus text format) while the batch is
   still executing; the ``status`` RPC must answer with every
   request's live row.
2. MULTI-PROCESS TRACE MERGE: a 2-controller spokeless ``dist_wheel``
   run (tests/dist_wheel_smoke_worker.py, ``DIST_TRACE_OUT``) exports
   one Perfetto ring per process; ``scripts/trace_merge.py`` must
   stitch them into one timeline — exit 0 (every B/E span matched),
   both controllers' ``clock_sync``-derived process rows present.

Prints one JSON line with the measured figures.  Exit 0 = pass.  A hard
watchdog (``TELEMETRY_SMOKE_DEADLINE_SECS``, default 900) ``os._exit(2)``s
a wedged run so CI never hangs.
"""

import json
import os
import socket
import subprocess
import sys
import threading
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DEADLINE = float(os.environ.get("TELEMETRY_SMOKE_DEADLINE_SECS", "900"))
S = int(os.environ.get("TELEMETRY_SMOKE_SCENS", "3"))
ITERS = 400
N_REQ = 3


def _arm_watchdog():
    def _bomb():
        time.sleep(DEADLINE)
        print(json.dumps({"ok": False, "error": "deadline exceeded"}),
              flush=True)
        os._exit(2)

    threading.Thread(target=_bomb, daemon=True).start()


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def phase_serving():
    """Batched 3-tenant run, watched end-to-end + scraped mid-run."""
    import tempfile

    from tpusppy.service import SolveRequest, SolveServer
    from tpusppy.service.net import SolveClient, TcpServiceFrontend

    out = {}
    with tempfile.TemporaryDirectory() as work:
        with SolveServer(work_dir=work, batch_slots=3,
                         in_wheel_bounds=True, quantum_secs=300.0,
                         linger_secs=0.0) as srv:
            front = TcpServiceFrontend(srv, slots=N_REQ, scrape_port=0)
            clients, streams, mid_scrapes = [], {}, []
            running = threading.Event()
            running.set()

            def scraper():
                url = (f"http://127.0.0.1:{front.scrape_port}"
                       f"/metrics")
                while running.is_set():
                    try:
                        with urllib.request.urlopen(url, timeout=5) as r:
                            body = r.read().decode()
                        if "tpusppy_tenant_rel_gap{" in body:
                            mid_scrapes.append(body)
                    except Exception:
                        pass
                    time.sleep(0.25)

            def watcher(cli, rid):
                evs = list(cli.watch(rid, timeout=DEADLINE))
                streams[rid] = {"events": evs, "record": cli.last_record}

            threads = [threading.Thread(target=scraper, daemon=True)]
            threads[0].start()
            try:
                rids = []
                for i in range(N_REQ):
                    cli = SolveClient("127.0.0.1", front.port,
                                      front.secret, slot=i + 1)
                    clients.append(cli)
                    rid = cli.submit({
                        "model": "farmer", "num_scens": S,
                        "creator_kwargs": {"seedoffset": 31 * i},
                        "options": {"PHIterLimit": ITERS}})
                    rids.append(rid)
                    th = threading.Thread(target=watcher,
                                          args=(cli, rid), daemon=True)
                    th.start()
                    threads.append(th)
                for th in threads[1:]:
                    th.join(timeout=DEADLINE)
                running.clear()

                # the status RPC serves every request's live row
                snap = clients[0].status()
                assert set(rids) <= set(snap["requests"]), snap
                out["status_rows"] = len(snap["requests"])

                bound_updates = {}
                for rid in rids:
                    st = streams.get(rid)
                    assert st is not None, f"{rid}: watch never finished"
                    evs, rec = st["events"], st["record"]
                    assert rec and rec.get("status") == "done", rec
                    assert rec.get("certified"), rec
                    kinds = [e["kind"] for e in evs]
                    bound_updates[rid] = kinds.count("bound_update")
                    assert bound_updates[rid] >= 1, \
                        f"{rid}: no bound_update streamed ({kinds})"
                    gaps = [e for e in evs if e["kind"] == "gap"]
                    assert gaps, f"{rid}: no gap points streamed"
                    last = gaps[-1]["rel_gap"]
                    want = rec["rel_gap"]
                    assert abs(last - want) <= 1e-9 * max(
                        1.0, abs(want)), \
                        (f"{rid}: live gap series ends at {last}, "
                         f"record says {want}")
                out["bound_updates"] = bound_updates
                out["batched"] = all(
                    streams[r]["record"].get("batched") for r in rids)
                assert out["batched"], {
                    r: streams[r]["record"].get("batched")
                    for r in rids}

                assert mid_scrapes, \
                    "scrape endpoint never served tenant gauges mid-run"
                assert any(f'request_id="{rid}"' in body
                           for body in mid_scrapes for rid in rids)
                out["mid_scrapes"] = len(mid_scrapes)
            finally:
                running.clear()
                for cli in clients:
                    cli.close()
                front.close()
    return out


def phase_trace_merge(tmp):
    """2-controller dist_wheel -> per-process rings -> one timeline."""
    port = _free_port()
    script = os.path.join(REPO, "tests", "dist_wheel_smoke_worker.py")
    rings = [os.path.join(tmp, f"ring{pid}.json") for pid in range(2)]
    common = {
        "DIST_COORD": f"127.0.0.1:{port}", "DIST_NPROC": "2",
        "DIST_SCENS": "8", "JAX_PLATFORMS": "cpu",
        "JAX_ENABLE_X64": "1", "PYTHONPATH": REPO,
        "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
    }
    procs = [
        subprocess.Popen(
            [sys.executable, script],
            env={**os.environ, **common, "DIST_PID": str(pid),
                 "DIST_TRACE_OUT": rings[pid]},
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        for pid in range(2)
    ]
    try:
        for p in procs:
            _, err = p.communicate(timeout=DEADLINE)
            assert p.returncode == 0, \
                f"worker rc={p.returncode}\n{err[-3000:]}"
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    merged = os.path.join(tmp, "merged.json")
    rc = subprocess.call(
        [sys.executable, os.path.join(REPO, "scripts", "trace_merge.py"),
         "-o", merged] + rings)
    assert rc == 0, "trace_merge found unmatched B/E spans"
    with open(merged) as f:
        doc = json.load(f)
    evs = doc["traceEvents"]
    roles = {e["args"]["name"] for e in evs
             if e.get("ph") == "M" and e.get("name") == "process_name"}
    assert roles == {"controller0", "controller1"}, roles
    spans = sum(1 for e in evs if e.get("ph") == "B")
    assert spans > 0, "merged trace carries no spans"
    return {"merged_events": len(evs), "merged_spans": spans}


def main():
    _arm_watchdog()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import tempfile

    serving = phase_serving()
    with tempfile.TemporaryDirectory() as tmp:
        merge = phase_trace_merge(tmp)
    print(json.dumps({"ok": True, "serving": serving, "merge": merge}),
          flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
