#!/usr/bin/env python
"""Merge per-process Perfetto rings into ONE aligned timeline.

Each tpusppy process exports its own trace ring as Perfetto JSON with
timestamps relative to ITS OWN first event (``obs/perfetto.py``) — a
client, a TCP frontend and the controllers of a ``dist_wheel`` mesh each
produce a file that loads alone but says nothing about cross-process
causality.  This tool stitches them (doc/observability.md "Merging
multi-process traces"):

1. **Clock alignment.**  Every process stamps a ``clock_sync`` instant
   (track ``clock``, args ``{wall, perf, role, pid}``) into its ring at
   startup (``telemetry.record_clock_sync``).  The instant's own ``ts``
   plus its ``wall`` arg map the file's relative microseconds onto the
   absolute wall clock: ``wall_of(ev) = wall_sync + (ev.ts - ts_sync)
   * 1e-6``.  With ``--align handshake`` the file's first
   ``clock_handshake`` instant (the NTP-style offset the client measured
   over the status/watch RPC round trip) is ADDED, so traces from a
   host with a skewed wall clock still land on the server's timeline.
2. **Process separation.**  File *i* keeps its thread rows but moves to
   ``pid=i+1`` with a ``process_name`` metadata row (the file's stem, or
   its clock_sync role), so the merged view shows one process group per
   ring: client -> frontend -> scheduler/slots -> device wheel.
3. **Validation.**  ``--validate`` (default on) checks every ``B`` has
   its matching ``E`` per (pid, tid) stack — the invariant the nightly
   telemetry smoke asserts on the merged 2-process dist_wheel trace.

Usage::

    python scripts/trace_merge.py -o merged.json ring0.json ring1.json
    python scripts/trace_merge.py -o merged.json --align handshake \
        client.json server.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _load(path: str) -> list:
    with open(path) as f:
        doc = json.load(f)
    evs = doc.get("traceEvents") if isinstance(doc, dict) else doc
    if not isinstance(evs, list):
        raise ValueError(f"{path}: not a Perfetto trace-event document")
    return evs


def _first_instant(events: list, name: str):
    """The lowest-ts instant event called ``name`` (None if absent)."""
    best = None
    for ev in events:
        if ev.get("ph") == "i" and ev.get("name") == name:
            if best is None or ev.get("ts", 0.0) < best.get("ts", 0.0):
                best = ev
    return best


def file_offset(events: list, align: str = "clock"):
    """``(wall_offset_s, role)`` placing this file on the absolute wall
    timeline: ``wall_of(ev) = ev.ts * 1e-6 + wall_offset_s``.  None when
    the file carries no ``clock_sync`` instant (pre-telemetry export)."""
    sync = _first_instant(events, "clock_sync")
    if sync is None:
        return None, None
    args = sync.get("args") or {}
    off = float(args.get("wall", 0.0)) - float(sync.get("ts", 0.0)) * 1e-6
    role = args.get("role")
    if align == "handshake":
        hs = _first_instant(events, "clock_handshake")
        if hs is not None:
            # offset_s measured (server - local): adding it moves this
            # file's wall times onto the SERVER's clock
            off += float((hs.get("args") or {}).get("offset_s", 0.0))
    return off, role


def validate_spans(events: list) -> list:
    """Unmatched B/E begin-end pairs, as human-readable problem strings
    (empty = every span is closed — no orphaned open spans)."""
    stacks: dict = {}
    problems = []
    for ev in sorted(events, key=lambda e: (e.get("ts", 0.0),
                                            0 if e.get("ph") != "E" else 1)):
        ph = ev.get("ph")
        if ph not in ("B", "E"):
            continue
        key = (ev.get("pid"), ev.get("tid"))
        stack = stacks.setdefault(key, [])
        if ph == "B":
            stack.append(ev.get("name"))
        elif not stack:
            problems.append(f"pid={key[0]} tid={key[1]}: E "
                            f"{ev.get('name')!r} with empty stack")
        else:
            stack.pop()
    for key, stack in stacks.items():
        for name in stack:
            problems.append(f"pid={key[0]} tid={key[1]}: B {name!r} "
                            f"never closed")
    return problems


def merge(paths, align: str = "clock"):
    """Merge Perfetto files into one document; returns (doc, notes).

    Files WITH clock_sync land on the shared absolute timeline; files
    without one (noted) are left start-aligned to the merged origin —
    visible, ordered internally, but not causally placed."""
    notes = []
    loaded = []
    for path in paths:
        evs = _load(path)
        off, role = file_offset(evs, align=align)
        if off is None:
            notes.append(f"{path}: no clock_sync instant — "
                         f"start-aligned only")
        loaded.append((path, evs, off, role))
    # the merged origin: earliest aligned wall instant (fallback 0)
    walls = [off + min((e.get("ts", 0.0) for e in evs
                        if e.get("ph") != "M"), default=0.0) * 1e-6
             for _, evs, off, _ in loaded if off is not None]
    origin = min(walls) if walls else 0.0
    out = []
    for i, (path, evs, off, role) in enumerate(loaded):
        pid = i + 1
        pname = role or os.path.splitext(os.path.basename(path))[0]
        out.append({"name": "process_name", "ph": "M", "pid": pid,
                    "args": {"name": pname}})
        if off is None:
            shift = -min((e.get("ts", 0.0) for e in evs
                          if e.get("ph") != "M"), default=0.0)
        else:
            shift = (off - origin) * 1e6
        for ev in evs:
            ev = dict(ev)
            ev["pid"] = pid
            if ev.get("ph") != "M":
                ev["ts"] = float(ev.get("ts", 0.0)) + shift
            out.append(ev)
    meta = [e for e in out if e.get("ph") == "M"]
    rest = sorted((e for e in out if e.get("ph") != "M"),
                  key=lambda e: (e["ts"], 0 if e.get("ph") != "E" else 1))
    return {"traceEvents": meta + rest, "displayTimeUnit": "ms"}, notes


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("inputs", nargs="+", help="per-process Perfetto JSONs")
    ap.add_argument("-o", "--out", required=True, help="merged output path")
    ap.add_argument("--align", choices=("clock", "handshake"),
                    default="clock",
                    help="clock: wall-vs-perf clock_sync stamps (same "
                         "host); handshake: additionally apply the "
                         "measured NTP-style client/server offset")
    ap.add_argument("--no-validate", action="store_true",
                    help="skip the matched-B/E span check")
    args = ap.parse_args(argv)

    doc, notes = merge(args.inputs, align=args.align)
    for note in notes:
        print(f"trace_merge: NOTE: {note}", file=sys.stderr)
    if not args.no_validate:
        problems = validate_spans(doc["traceEvents"])
        for p in problems:
            print(f"trace_merge: UNMATCHED: {p}", file=sys.stderr)
        if problems:
            return 1
    with open(args.out, "w") as f:
        json.dump(doc, f)
    n = sum(1 for e in doc["traceEvents"] if e.get("ph") != "M")
    print(f"trace_merge: {len(args.inputs)} file(s) -> {args.out} "
          f"({n} events, align={args.align})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
