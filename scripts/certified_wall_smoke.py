#!/usr/bin/env python
"""Certified-wall smoke: in-wheel certification acceptance (doc/pipeline.md
"In-wheel certification"), runnable locally::

    JAX_PLATFORMS=cpu python scripts/certified_wall_smoke.py

Two certified UC-lite wheels over the SAME family and solver settings:

A. the **3-cylinder golden** — PH hub + Lagrangian outer spoke + XhatXbar
   inner spoke, every cylinder its own batched device programs (the
   pre-in-wheel certification topology);
B. the **hub-only in-wheel wheel** — ``in_wheel_bounds``: the megastep's
   fused bound pass produces both bounds, ZERO spoke cylinders.

Asserts (the CPU-portable acceptance signals — wall clock is reported,
not asserted, because in-process CPU fetches are nearly free and the
contention the in-wheel pass removes only exists on a real device):

1. **Certification** — both wheels terminate on the gap; the in-wheel
   wheel's certified rel_gap is <= the golden's (plus float slack).
2. **Strictly fewer host syncs** — the in-wheel leg's ``host_sync.count``
   delta is strictly below the golden's (the spokes' own solve/bound
   fetches are gone).
3. **Zero spoke device programs** — the in-wheel leg spins no spoke
   comms at all, at least one fused bound pass ran
   (``megastep.bound_passes``), and both bounds are finite (with no
   spokes, in-wheel evidence is the only possible source).

The summary JSON line carries ``certified_wall_s`` for both legs — the
field the bench wheel segment banks for the driver artifact.

The whole script is bounded by a HARD watchdog
(``CERTIFIED_WALL_DEADLINE_SECS``, default 1500 s): a hang past the
deadline exits 2 via ``os._exit`` instead of pinning the CI job.  Env
knobs: ``CWS_SCENS`` (default 4), ``CWS_ITERS`` (default 240),
``CWS_REL_GAP`` (default 2e-2 — UC-lite's outer bound tightens slowly
on CPU budgets; the acceptance signal is the RELATIVE one, in-wheel gap
<= golden gap, not the absolute target).  Exit code 0 = pass.
"""

import json
import os
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("JAX_ENABLE_X64", "1")

DEADLINE = float(os.environ.get("CERTIFIED_WALL_DEADLINE_SECS", "1500"))


def log(msg):
    print(f"certified-wall-smoke: {msg}", file=sys.stderr, flush=True)


def _arm_hard_watchdog():
    def killer():
        time.sleep(DEADLINE)
        log(f"HARD WATCHDOG: {DEADLINE}s deadline breached — exiting 2")
        os._exit(2)

    threading.Thread(target=killer, daemon=True).start()


def main():
    import numpy as np

    import tpusppy
    from tpusppy.cylinders import (LagrangianOuterBound, PHHub,
                                   XhatXbarInnerBound)
    from tpusppy.models import uc_lite
    from tpusppy.obs import metrics
    from tpusppy.opt.ph import PH
    from tpusppy.phbase import PHBase
    from tpusppy.spin_the_wheel import WheelSpinner
    from tpusppy.xhat_eval import Xhat_Eval

    tpusppy.disable_tictoc_output()
    S = int(os.environ.get("CWS_SCENS", "4"))
    iters = int(os.environ.get("CWS_ITERS", "240"))
    rel_gap = float(os.environ.get("CWS_REL_GAP", "2e-2"))

    def opt_kwargs(extra=None):
        options = {"defaultPHrho": 500.0, "PHIterLimit": iters,
                   "convthresh": -1.0}
        options.update(extra or {})
        return {
            "options": options,
            "all_scenario_names": uc_lite.scenario_names_creator(S),
            "scenario_creator": uc_lite.scenario_creator,
            "scenario_creator_kwargs": {"num_scens": S,
                                        "relax_integers": True},
        }

    hub_kwargs = {"options": {"rel_gap": rel_gap, "abs_gap": 0.0,
                              "linger_secs": 60.0}}

    # ---- leg A: the 3-cylinder golden -----------------------------------
    golden_hub = {"hub_class": PHHub, "hub_kwargs": hub_kwargs,
                  "opt_class": PH, "opt_kwargs": opt_kwargs()}
    golden_spokes = [
        {"spoke_class": LagrangianOuterBound, "spoke_kwargs": {},
         "opt_class": PHBase, "opt_kwargs": opt_kwargs()},
        {"spoke_class": XhatXbarInnerBound, "spoke_kwargs": {},
         "opt_class": Xhat_Eval, "opt_kwargs": opt_kwargs()},
    ]
    log(f"leg A (3-cylinder golden): S={S} rel_gap={rel_gap}")
    t0 = time.time()
    with metrics.window() as wa:
        ws_a = WheelSpinner(golden_hub, golden_spokes).spin()
    wall_a = time.time() - t0
    _, gap_a = ws_a.spcomm.compute_gaps()
    sync_a = int(wa.delta("host_sync.count"))
    log(f"leg A: rel_gap={gap_a:.3e} host_syncs={sync_a} "
        f"wall={wall_a:.1f}s")

    # ---- leg B: hub-only, in-wheel certification ------------------------
    inwheel_hub = {"hub_class": PHHub, "hub_kwargs": hub_kwargs,
                   "opt_class": PH,
                   "opt_kwargs": opt_kwargs({"in_wheel_bounds": True})}
    log("leg B (hub-only, in-wheel bounds)")
    t0 = time.time()
    with metrics.window() as wb:
        ws_b = WheelSpinner(inwheel_hub, []).spin()
    wall_b = time.time() - t0
    _, gap_b = ws_b.spcomm.compute_gaps()
    sync_b = int(wb.delta("host_sync.count"))
    passes = int(wb.delta("megastep.bound_passes"))
    log(f"leg B: rel_gap={gap_b:.3e} host_syncs={sync_b} "
        f"bound_passes={passes} wall={wall_b:.1f}s")

    # 1. certification: the in-wheel wheel certifies the golden's gap
    assert np.isfinite(gap_a) and gap_a <= rel_gap + 1e-12, \
        f"golden leg failed to certify: rel_gap={gap_a}"
    assert np.isfinite(gap_b) and gap_b <= max(rel_gap, gap_a) + 1e-9, \
        f"in-wheel leg missed the golden's gap: {gap_b} vs {gap_a}"
    # 2. strictly fewer host syncs
    assert sync_b < sync_a, \
        f"in-wheel host_syncs not strictly lower: {sync_b} vs {sync_a}"
    # 3. zero spoke device programs
    assert not ws_b.spoke_comms, "in-wheel leg spun spoke comms"
    assert passes >= 1, "no fused bound pass executed"
    assert np.isfinite(ws_b.BestOuterBound), "no in-wheel outer bound"
    assert np.isfinite(ws_b.BestInnerBound), "no in-wheel inner bound"
    # validity cross-check: legs agree the optimum sits in both sandwiches
    assert ws_b.BestOuterBound <= ws_b.BestInnerBound + 1e-9

    print(json.dumps({
        "certified_wall_smoke": "ok",
        "S": S,
        "rel_gap_golden": float(gap_a),
        "rel_gap_inwheel": float(gap_b),
        "host_sync_count_golden": sync_a,
        "host_sync_count_inwheel": sync_b,
        "bound_passes": passes,
        "spoke_cylinders_inwheel": 0,
        "certified_wall_s": round(wall_b, 2),
        "certified_wall_s_3cyl": round(wall_a, 2),
    }), flush=True)
    log("PASS")
    return 0


if __name__ == "__main__":
    _arm_hard_watchdog()
    sys.exit(main())
