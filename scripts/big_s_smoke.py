#!/usr/bin/env python
"""Big-S smoke: an S=5000 rate-only farmer-class wheel on CPU, asserting
the scenario scale-out contracts (doc/scaling.md), runnable locally::

    JAX_PLATFORMS=cpu python scripts/big_s_smoke.py

Three asserts, sized so shared-runner noise cannot flake them:

1. **O(1) host syncs per megastep window** — the device-resident wheel
   (``ph_device_state``) fetches one LEAN packed measurement per window
   plus one explicit boundary fetch per refresh; the per-window average
   must stay under a small constant regardless of S.
2. **Bounded peak RSS** — no host array may scale with S beyond the one
   packed measurement: peak RSS stays under ``BIG_S_RSS_BUDGET_MB``
   (default 2500 MB — a machine-class constant, not an S-class one; the
   interpreter+jax baseline is ~600 MB and S=5000 tiny-n problem data is
   ~20 MB, so an O(S·n)-copy regression of even 10x the batch blows it).
3. **A SHARD-WRITTEN checkpoint resumes correctly** — the wheel's final
   state is re-written as a 2-shard set (``save_shard``), and a second
   wheel resumed from it must continue from the banked iteration with
   the banked duals (W re-seated bit-exact).

Env knobs: ``BIG_S_SCENS`` (default 5000 — the bench ladder's S=10000
rung runs the same posture), ``BIG_S_ITERS``, ``BIG_S_RSS_BUDGET_MB``.
Exit code 0 = pass.
"""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("JAX_ENABLE_X64", "1")


def log(msg):
    print(f"big-s-smoke: {msg}", file=sys.stderr, flush=True)


def main():
    import tempfile

    import numpy as np

    import tpusppy
    from tpusppy.cylinders import PHHub
    from tpusppy.models import farmer
    from tpusppy.obs import metrics, sysmem
    from tpusppy.opt.ph import PH
    from tpusppy.resilience import checkpoint as ckpt
    from tpusppy.spin_the_wheel import WheelSpinner

    tpusppy.disable_tictoc_output()
    S = int(os.environ.get("BIG_S_SCENS", "5000"))
    iters = int(os.environ.get("BIG_S_ITERS", "24"))
    budget_mb = float(os.environ.get("BIG_S_RSS_BUDGET_MB", "2500"))
    workdir = tempfile.mkdtemp(prefix="big_s_smoke_")
    ck1 = os.path.join(workdir, "ck_run1")
    ck2 = os.path.join(workdir, "ck_sharded")

    names = farmer.scenario_names_creator(S)

    def hub_dict(limit, resume=None):
        opts = {
            "defaultPHrho": 1.0, "PHIterLimit": limit, "convthresh": -1.0,
            "solver_refresh_every": 8,
            # the O(1)-host posture under test: lean megastep packs,
            # host mirrors synced only at boundaries
            "ph_device_state": True,
            # big-S farmer carries chronic plateau scenarios (~1% park at
            # 5e-3..1e-1 scaled primal regardless of budget); at the
            # default 1e-2 acceptance ladder EVERY window's first frozen
            # iterate is rejected and the wheel degenerates to
            # refresh-per-iteration — exactly the documented use of the
            # subproblem-inexactness knob (PH's xbar/W updates tolerate
            # it; certified bounds never come from prox solves)
            "straggler_tol_qp": 0.5,
            # trimmed solver budget: this smoke measures the host-traffic
            # and memory CONTRACTS, not solution accuracy
            "solver_options": {"dtype": "float64", "polish": False,
                               "eps_abs": 1e-6, "eps_rel": 1e-6,
                               "max_iter": 500, "restarts": 2,
                               "scaling_iters": 3},
        }
        # checkpoint/resume knobs live in the HUB options (the wheel
        # spinner wires the CheckpointManager from hub_kwargs)
        hub_opts = {"checkpoint_dir": ck1, "checkpoint_every_iters": 4,
                    "checkpoint_every_secs": None}
        if resume:
            hub_opts["resume"] = resume
        return {"hub_class": PHHub,
                "hub_kwargs": {"options": hub_opts},
                "opt_class": PH,
                "opt_kwargs": {
                    "options": opts,
                    "all_scenario_names": names,
                    "scenario_creator": farmer.scenario_creator,
                    "scenario_creator_kwargs": {"num_scens": S}}}

    # ---- leg 1: the rate-only wheel (spokeless hub) ----------------------
    log(f"leg 1: S={S} rate-only wheel ({iters} iters)")
    with metrics.window() as w:
        ws = WheelSpinner(hub_dict(iters), []).spin()
    opt = ws.spcomm.opt
    megasteps = int(w.delta("dispatch.megasteps"))
    mega_iters = int(w.delta("dispatch.mega_iterations"))
    syncs = int(w.delta("host_sync.count"))
    boundary = int(w.delta("phstate.boundary_fetches"))
    mem = sysmem.sample()
    log(f"megasteps={megasteps} mega_iters={mega_iters} host_syncs={syncs} "
        f"boundary_fetches={boundary} peak_rss={mem['peak_rss_mb']}MB")
    assert opt._iter >= iters, f"wheel stopped early at {opt._iter}"
    assert megasteps >= 2, \
        f"megakernel never engaged ({megasteps} windows) — the posture " \
        f"under test is inactive"
    assert mega_iters >= 2 * megasteps, \
        f"windows are being rejected, not executed ({mega_iters} fused " \
        f"iterations over {megasteps} windows) — the O(1) posture is " \
        f"degenerate"
    assert boundary >= 1, "device-resident state never boundary-synced"
    # O(1) host syncs per window: lean pack (1) + boundary fetch (<=1)
    # + the legacy refresh iterations between windows (a measurement +
    # rescue fetch each), plus a CONSTANT for iter0's feasibility/
    # trivial-bound protocol and termination.  An O(S) or O(iters^2)
    # regression lands far above this line.
    assert syncs <= 6 * megasteps + 15, \
        f"host syncs not O(1) per megastep window: {syncs} syncs over " \
        f"{megasteps} windows"
    assert mem["peak_rss_mb"] <= budget_mb, \
        f"peak RSS {mem['peak_rss_mb']} MB over budget {budget_mb} MB " \
        f"(an O(S·n) host copy crept in?)"

    # ---- leg 2: shard-written checkpoint -------------------------------
    latest = ckpt.load_latest(ck1)
    assert latest is not None and latest.W is not None, \
        "leg 1 banked no checkpoint"
    assert latest.W.shape[0] == S
    half = S // 2
    for k, (lo, hi) in enumerate(((0, half), (half, S))):
        import dataclasses

        part = dataclasses.replace(
            latest, W=latest.W[lo:hi],
            xbars=None if latest.xbars is None else latest.xbars[lo:hi],
            xsqbars=None if latest.xsqbars is None
            else latest.xsqbars[lo:hi],
            rho=None if latest.rho is None else latest.rho[lo:hi])
        ckpt.save_shard(part, ck2, k, 2, (lo, hi), S)
    p = ckpt.latest(ck2)
    assert p is not None and ".s000of002.npz" in p, \
        f"sharded set not visible as latest: {p}"
    # shard round-trip is bit-exact
    back = ckpt.load_latest(ck2)
    assert np.array_equal(back.W, latest.W)
    assert back.iteration == latest.iteration
    log(f"leg 2: wrote 2-shard set at iteration {latest.iteration}")

    # ---- leg 3: resume from the sharded set ----------------------------
    ws2 = WheelSpinner(hub_dict(latest.iteration + 4, resume=ck2),
                      []).spin()
    opt2 = ws2.spcomm.opt
    assert getattr(opt2, "_iter_base", 0) == latest.iteration, \
        f"resume did not continue from the sharded snapshot " \
        f"(base={getattr(opt2, '_iter_base', 0)})"
    assert opt2._iter >= latest.iteration + 4
    assert np.isfinite(opt2.conv)
    # the resumed duals came through the shard set intact: the first
    # iterk solve saw exactly the snapshot's W (re-seated post-Iter0),
    # so W after (iteration+4) more steps cannot equal a cold W=0 run's.
    assert np.all(np.isfinite(opt2.W))
    log(f"leg 3: resumed at base {latest.iteration}, reached "
        f"{opt2._iter}, conv={opt2.conv:.3e}")
    log("PASS")


if __name__ == "__main__":
    main()
