#!/usr/bin/env python
"""Serving smoke: the wheel-as-a-service warm path, end to end.

Nightly CI acceptance for ``tpusppy/service`` (doc/serving.md), runnable
locally::

    JAX_PLATFORMS=cpu python scripts/serving_smoke.py

One long-lived :class:`~tpusppy.service.SolveServer` receives FOUR
concurrent requests forming two isomorphic pairs across two model
families (farmer + uc-lite).  Asserts the serving contract:

- every request completes CERTIFIED (rel_gap <= target) with a full SLO
  record (queue wait / ttfi / compile_s / iters/s / gap / wall);
- the SECOND member of each pair binds warm: ``aot.misses`` delta == 0
  and zero compile seconds — the executables compiled for the first
  member serve the isomorphic repeat;
- the warm farmer request reaches iter-1 at least ``SMOKE_SPEEDUP``x
  (default 3, the PR-7 nightly bar) faster than its cold twin did;
- concurrency is real: with a sub-second quantum at least one
  preempt-park-resume cycle fires, and bounds stay monotone across it;
- shutdown is clean: queue drained, executor joined, no tenant left
  running, the content-keyed device caches released (no orphan device
  state).

Prints one JSON line with the measured figures.  Exit 0 = pass.  A hard
deadline (``SMOKE_DEADLINE_SECS``, default 900) ``os._exit(2)``s a
wedged run so CI never hangs.
"""

import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SPEEDUP = float(os.environ.get("SMOKE_SPEEDUP", "3.0"))
DEADLINE = float(os.environ.get("SMOKE_DEADLINE_SECS", "900"))


def _arm_watchdog():
    def _bomb():
        time.sleep(DEADLINE)
        print(json.dumps({"ok": False, "error": "deadline exceeded"}),
              flush=True)
        os._exit(2)

    threading.Thread(target=_bomb, daemon=True).start()


def main():
    _arm_watchdog()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import tempfile

    from tpusppy.service import SolveRequest, SolveServer

    work = tempfile.mkdtemp(prefix="serving_smoke_")
    reqs = [
        SolveRequest(model="farmer", num_scens=4,
                     creator_kwargs={"seedoffset": 0},
                     options={"PHIterLimit": 80}),
        SolveRequest(model="uc_lite", num_scens=3,
                     creator_kwargs={"num_gens": 2, "horizon": 4,
                                     "relax_integers": True,
                                     "seedoffset": 0},
                     options={"PHIterLimit": 300, "rel_gap": 5e-3}),
        SolveRequest(model="farmer", num_scens=4,
                     creator_kwargs={"seedoffset": 901},
                     options={"PHIterLimit": 80}),
        SolveRequest(model="uc_lite", num_scens=3,
                     creator_kwargs={"num_gens": 2, "horizon": 4,
                                     "relax_integers": True,
                                     "seedoffset": 44},
                     options={"PHIterLimit": 300, "rel_gap": 5e-3}),
    ]
    srv = SolveServer(work_dir=work, quantum_secs=1.5, linger_secs=45.0)
    t0 = time.time()
    rids = [srv.submit(r) for r in reqs]
    recs = [srv.result(r, timeout=DEADLINE - 60) for r in rids]
    wall = time.time() - t0

    failures = []

    def check(cond, msg):
        if not cond:
            failures.append(msg)

    slo_keys = ("queue_wait_s", "ttfi_s", "compile_s", "iters_per_sec",
                "rel_gap", "wall_s", "aot_misses", "slices")
    for rec in recs:
        check(rec["status"] == "done", f"{rec['request_id']}: {rec['status']}")
        check(rec["certified"],
              f"{rec['request_id']} uncertified (gap {rec['rel_gap']})")
        check(rec["bounds_monotone"],
              f"{rec['request_id']} bounds regressed across a resume")
        check(all(rec.get(k) is not None for k in slo_keys),
              f"{rec['request_id']} SLO record incomplete: {rec}")
    # pair warmness: zero recompiles after the first of each family
    for cold, warmr in ((recs[0], recs[2]), (recs[1], recs[3])):
        check(cold["aot_misses"] > 0,
              f"cold {cold['request_id']} compiled nothing?")
        check(warmr["warm_hit"], f"{warmr['request_id']} not warm")
        check(warmr["aot_misses"] == 0,
              f"{warmr['request_id']} recompiled "
              f"({warmr['aot_misses']} misses)")
        check(warmr["compile_s"] == 0.0,
              f"{warmr['request_id']} spent {warmr['compile_s']}s compiling")
    # the PR-7 bar, through the serving path: warm time-to-iter-1
    ttfi_cold, ttfi_warm = recs[0]["ttfi_s"], recs[2]["ttfi_s"]
    if ttfi_cold is None or ttfi_warm is None:
        check(False, f"ttfi missing (cold={ttfi_cold}, warm={ttfi_warm})")
        ttfi_cold, ttfi_warm = float("nan"), float("nan")
    else:
        check(ttfi_warm * SPEEDUP <= ttfi_cold,
              f"warm ttfi {ttfi_warm:.3f}s not {SPEEDUP}x faster than "
              f"cold {ttfi_cold:.3f}s")
    # real time-slicing under a sub-second quantum
    preempts = sum(r["preemptions"] for r in recs)
    check(preempts >= 1, "no preempt-park-resume cycle fired")
    summary = srv.slo_summary()
    check(summary["completed"] == 4, f"summary: {summary}")
    check(summary["p95_latency_s"] is not None, "no latency percentiles")

    srv.shutdown()
    from tpusppy import spopt

    check(not srv._executor.is_alive(), "executor still alive after shutdown")
    check(all(t.status == "done" for t in srv._tenants.values()),
          "tenant left unfinished at shutdown")
    check(len(spopt._DEV_A_CACHE) == 0,
          "device-A cache not released at shutdown")

    out = {
        "ok": not failures, "failures": failures, "wall_s": round(wall, 2),
        "ttfi_cold_s": round(ttfi_cold, 3), "ttfi_warm_s": round(ttfi_warm, 4),
        "warm_speedup": round(ttfi_cold / max(ttfi_warm, 1e-9), 1),
        "preemptions": preempts,
        "gaps": [None if r["rel_gap"] is None else round(r["rel_gap"], 6)
                 for r in recs],
        "p50_latency_s": summary["p50_latency_s"],
        "p95_latency_s": summary["p95_latency_s"],
        "warm_hit_rate": summary["warm_hit_rate"],
    }
    print(json.dumps(out), flush=True)
    return 0 if not failures else 1


if __name__ == "__main__":
    sys.exit(main())
