#!/usr/bin/env python
"""Cold-vs-warm smoke: the AOT executable cache must kill the cold start.

The nightly CI acceptance for the executable cache
(tpusppy/solvers/aot.py; doc/autotuner.md "Cold start"), runnable
locally too::

    JAX_PLATFORMS=cpu python scripts/cold_warm_smoke.py

Two legs, each a REAL OS process (fresh interpreter, fresh jax — the
posture the cache exists for), sharing ONE fresh cache directory created
by this parent (both the executable cache and the jax persistent
compilation cache live inside it, so NOTHING ambient can pre-warm the
cold leg):

1. **cold** — empty cache: every program lowers + compiles; serializable
   executables (frozen sweeps, wheel megastep, packed measurements) are
   persisted, factorization programs fall to the jax-cache tier.
2. **warm** — same directory, second identical-shape run: must reach its
   FIRST PH ITERATION (program build + Iter0 + the first frozen
   iteration, the step-pair path every bench segment starts with) at
   least ``SMOKE_SPEEDUP``x faster than the cold leg, with
   ``aot.hits > 0`` and the warm leg's ``compile_iter0_s`` at most
   ``SMOKE_ITER0_FRAC`` of the cold leg's.  The warm leg runs TWICE and
   the faster run counts: warmness is not degraded by repetition, and
   the co-tenant noise on shared CI/container hosts is the dominant
   wobble on a ~3 s measurement.

Threshold honesty: measured best-case on this container is ~8x
first-iter speedup with warm iter0 at ~0.12x cold (banked in
BENCH_r07.json), but the cold leg's compile wall wobbles 2-3x with box
load, and on CPU the adaptive/refresh programs can never serialize
(their LAPACK custom calls are by-pointer — see
``aot.SAFE_CUSTOM_CALLS``), leaving a retrace+cached-compile floor of
~2-3 s on the warm side.  The DEFAULT assertions are therefore set
where they hold under noise (3x / 0.5x); on TPU, where cholesky lowers
natively and the refresh programs persist too, tighten via
``SMOKE_SPEEDUP`` / ``SMOKE_ITER0_FRAC``.

Each leg reports ``t_first_iter_s`` (wall from "batch on host" to the
first PH iteration's fetched result), ``compile_iter0_s``, and the
``aot.*`` counters.  Exit code 0 = pass.  The worker leg is this same
file with ``--worker`` (config via SMOKE_* env), so the smoke has no
test-harness dependencies.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SPEEDUP = float(os.environ.get("SMOKE_SPEEDUP", "3.0"))
ITER0_FRAC = float(os.environ.get("SMOKE_ITER0_FRAC", "0.5"))


def log(msg):
    print(f"cold-warm-smoke: {msg}", file=sys.stderr, flush=True)


# ---------------------------------------------------------------------------
# Worker leg (child process)
# ---------------------------------------------------------------------------
def worker():
    import time

    sys.path.insert(0, REPO)
    import jax

    jax.config.update("jax_enable_x64", True)
    import numpy as np

    from tpusppy import tune as tuner
    from tpusppy.ir import ScenarioBatch
    from tpusppy.models import farmer
    from tpusppy.obs import metrics
    from tpusppy.parallel import sharded
    from tpusppy.solvers.admm import ADMMSettings

    leg = os.environ["SMOKE_MODE"]
    S = int(os.environ.get("SMOKE_SCENS", "24"))
    mult = int(os.environ.get("SMOKE_CROPS_MULT", "2"))
    chunk = int(os.environ.get("SMOKE_CHUNK", "8"))

    names = farmer.scenario_names_creator(S)
    batch = ScenarioBatch.from_problems([
        farmer.scenario_creator(nm, num_scens=S, crops_multiplier=mult)
        for nm in names])
    st = ADMMSettings(dtype="float64", eps_abs=1e-5, eps_rel=1e-5,
                      max_iter=200, restarts=2, scaling_iters=6,
                      polish_passes=1)
    mesh = sharded.make_mesh(1)
    arr = sharded.shard_batch(batch, mesh)
    idx = batch.tree.nonant_indices

    # ---- the measured window: everything between "batch is on the
    # host" and "the first PH iteration's result is in host hands" —
    # program construction, compiles/deserializes, Iter0 (adaptive
    # refresh: falls to the jax-cache tier on CPU, where its LAPACK
    # custom calls bar executable serialization), then the first REAL PH
    # iteration on the frozen steady-state program (fully AOT-cached:
    # the warm leg deserializes it instead of compiling).  This is the
    # step-pair path bench.py's flagship segment starts every run with.
    t0 = time.perf_counter()
    tuner.prewarm_aot()
    refresh, frozen = sharded.make_ph_step_pair(idx, st, mesh)
    state = sharded.init_state(arr, 1.0, st)
    t_i0 = time.perf_counter()
    state, out, factors = refresh(state, arr, 0.0)  # Iter0 (compiles here)
    np.asarray(out.conv)
    compile_iter0_s = time.perf_counter() - t_i0
    state, out = frozen(state, arr, 1.0, factors)   # first PH iteration
    conv1 = float(np.asarray(out.conv))
    t_first_iter_s = time.perf_counter() - t0
    # the fused multi-iteration program rides the same caches (jax-cache
    # tier on CPU — its refresh blocks carry the LAPACK calls; full AOT
    # on TPU); build + run one window so the smoke exercises it too,
    # OUTSIDE the first-iteration clock
    fused = sharded.make_ph_fused_step(idx, st, mesh, chunk=chunk,
                                       refresh_every=chunk)
    state, out = fused(state, arr, 1.0)
    np.asarray(out.conv)

    res = {
        "leg": leg,
        "t_first_iter_s": t_first_iter_s,
        "compile_iter0_s": compile_iter0_s,
        "conv1": conv1,
        "aot": {k: metrics.value(f"aot.{k}")
                for k in ("hits", "misses", "unserializable", "compile_s",
                          "deserialize_s", "serialize_errors",
                          "load_errors")},
    }
    with open(os.path.join(os.environ["SMOKE_DIR"],
                           f"result_{leg}.json"), "w") as f:
        json.dump(res, f)
    print(json.dumps(res), flush=True)


# ---------------------------------------------------------------------------
# Orchestration (parent)
# ---------------------------------------------------------------------------
def _run_leg(mode, base, timeout=900):
    env = dict(os.environ, SMOKE_MODE=mode, SMOKE_DIR=base,
               PYTHONPATH=REPO,
               TPUSPPY_AOT_CACHE=os.path.join(base, "aot"),
               TPUSPPY_TUNE_CACHE=os.path.join(base, "tune.json"),
               # hermetic: the cold leg must not warm-start from an
               # ambient jax cache
               JAX_COMPILATION_CACHE_DIR=os.path.join(base, "xla"))
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.setdefault("JAX_ENABLE_X64", "1")
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--worker"], env=env)
    try:
        rc = proc.wait(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.kill()
        raise SystemExit(f"{mode} leg timed out after {timeout}s")
    if rc != 0:
        raise SystemExit(f"{mode} leg failed rc={rc}")
    with open(os.path.join(base, f"result_{mode}.json")) as f:
        return json.load(f)


def main():
    import tempfile

    base = tempfile.mkdtemp(prefix="cold_warm_smoke_")
    log(f"workdir {base}")

    cold = _run_leg("cold", base)
    log(f"cold: first-iter {cold['t_first_iter_s']:.2f}s "
        f"iter0 {cold['compile_iter0_s']:.2f}s aot={cold['aot']}")
    assert cold["aot"]["misses"] > 0, "cold leg compiled nothing?"
    assert cold["aot"]["serialize_errors"] == 0, cold["aot"]

    # two warm runs, fastest counts (see the module docstring): each is
    # a REAL fresh process; repetition cannot fake warmness, it only
    # sheds co-tenant noise from the small measurement
    warm_runs = [_run_leg("warm", base) for _ in range(2)]
    warm = min(warm_runs, key=lambda w: w["t_first_iter_s"])
    for w in warm_runs:
        log(f"warm: first-iter {w['t_first_iter_s']:.2f}s "
            f"iter0 {w['compile_iter0_s']:.2f}s aot={w['aot']}")

    speedup = cold["t_first_iter_s"] / max(warm["t_first_iter_s"], 1e-9)
    iter0_frac = (warm["compile_iter0_s"]
                  / max(cold["compile_iter0_s"], 1e-9))
    log(f"first-iter speedup {speedup:.1f}x "
        f"(need >= {SPEEDUP}x), warm iter0 at {iter0_frac:.2f}x cold "
        f"(need <= {ITER0_FRAC}x)")

    assert warm["aot"]["hits"] > 0, \
        f"warm leg hit nothing: {warm['aot']}"
    assert warm["aot"]["load_errors"] == 0, warm["aot"]
    # identical trajectory, cold or warm — the cache must never change
    # the math
    assert abs(warm["conv1"] - cold["conv1"]) < 1e-9, \
        f"warm conv {warm['conv1']} != cold conv {cold['conv1']}"
    assert speedup >= SPEEDUP, \
        f"warm first-iter only {speedup:.1f}x faster (need {SPEEDUP}x)"
    assert iter0_frac <= ITER0_FRAC, \
        f"warm iter0 at {iter0_frac:.2f}x cold (need <= {ITER0_FRAC}x)"
    print(json.dumps({"cold": cold, "warm": warm,
                      "speedup": round(speedup, 2),
                      "iter0_frac": round(iter0_frac, 3)}))
    log("PASS")


if __name__ == "__main__":
    if "--worker" in sys.argv[1:]:
        worker()
    else:
        main()
