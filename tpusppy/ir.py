"""Scenario-problem intermediate representation (IR).

The reference represents each scenario as a Pyomo ``ConcreteModel`` built by a
user-supplied ``scenario_creator`` and solved by an external MIP solver
(spbase.py:255-291, spopt.py:85-223).  Here a scenario is a dense tensor record in
the canonical conic-box form used by first-order LP/QP solvers (OSQP/PDLP style):

    minimize    0.5 * x' diag(q2) x + c' x  (+ const)
    subject to  cl <= A x <= cu
                lb <=   x <= ub
                x[i] integer for is_int[i]

Equality rows are cl == cu; one-sided rows use +/-inf.  A batch of scenarios from
one model family shares shapes, so the whole batch lives in HBM as stacked arrays
and every solve is a single vmapped device program — this is the TPU replacement
for the per-rank serial ``solve_loop`` (spopt.py:226-307).

Nonanticipativity structure comes from :mod:`tpusppy.scenario_tree` annotations;
``ScenarioBatch`` packs them into device-friendly index arrays.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from .scenario_tree import ScenarioNode, TreeInfo, build_tree

INF = np.inf


class LinearModelBuilder:
    """Tiny row-wise builder so model files read declaratively.

    The Pyomo-analogue authoring surface: declare variables with bounds and
    costs, then add rows ``cl <= sum coef*var <= cu``.  Produces a
    :class:`ScenarioProblem`.
    """

    def __init__(self, name: str):
        self.name = name
        self._varnames: list[str] = []
        self._lb: list[float] = []
        self._ub: list[float] = []
        self._c: list[float] = []
        self._q2: list[float] = []
        self._is_int: list[bool] = []
        self._rows: list[tuple[dict, float, float]] = []
        self.nodes: list[ScenarioNode] = []
        self.prob: float | None = None
        self.const: float = 0.0

    def add_var(self, name, lb=0.0, ub=INF, cost=0.0, quad=0.0, integer=False) -> int:
        """Declare a variable; returns its flat index."""
        if name in self._varnames:
            raise ValueError(f"duplicate variable {name}")
        self._varnames.append(name)
        self._lb.append(float(lb))
        self._ub.append(float(ub))
        self._c.append(float(cost))
        self._q2.append(float(quad))
        self._is_int.append(bool(integer))
        return len(self._varnames) - 1

    def add_vars(self, prefix, k, **kw) -> list[int]:
        return [self.add_var(f"{prefix}[{i}]", **kw) for i in range(k)]

    def add_row(self, coeffs: dict, cl=-INF, cu=INF):
        """Add constraint cl <= sum_j coeffs[j]*x_j <= cu (indices or names)."""
        idx = {
            (self._varnames.index(k) if isinstance(k, str) else int(k)): float(v)
            for k, v in coeffs.items()
        }
        self._rows.append((idx, float(cl), float(cu)))

    def add_eq(self, coeffs, rhs):
        self.add_row(coeffs, rhs, rhs)

    def add_le(self, coeffs, rhs):
        self.add_row(coeffs, -INF, rhs)

    def add_ge(self, coeffs, rhs):
        self.add_row(coeffs, rhs, INF)

    def set_cost(self, var, cost):
        i = self._varnames.index(var) if isinstance(var, str) else int(var)
        self._c[i] = float(cost)

    def build(self) -> "ScenarioProblem":
        n = len(self._varnames)
        m = len(self._rows)
        A = np.zeros((m, n))
        cl = np.zeros(m)
        cu = np.zeros(m)
        for r, (coeffs, lo, hi) in enumerate(self._rows):
            for j, v in coeffs.items():
                A[r, j] = v
            cl[r], cu[r] = lo, hi
        return ScenarioProblem(
            name=self.name,
            c=np.asarray(self._c),
            q2=np.asarray(self._q2),
            A=A,
            cl=cl,
            cu=cu,
            lb=np.asarray(self._lb),
            ub=np.asarray(self._ub),
            is_int=np.asarray(self._is_int, dtype=bool),
            prob=self.prob,
            nodes=list(self.nodes),
            var_names=list(self._varnames),
            const=self.const,
        )


@dataclasses.dataclass
class ScenarioProblem:
    """One scenario in canonical form (host-side, numpy)."""

    name: str
    c: np.ndarray          # (n,)
    q2: np.ndarray         # (n,) diagonal of the quadratic term (0 => LP)
    A: np.ndarray          # (m, n)
    cl: np.ndarray         # (m,)
    cu: np.ndarray         # (m,)
    lb: np.ndarray         # (n,)
    ub: np.ndarray         # (n,)
    is_int: np.ndarray     # (n,) bool
    prob: float | None     # _mpisppy_probability; None => uniform (spbase.py:505-520)
    nodes: list            # list[ScenarioNode], stage order
    var_names: list | None = None
    const: float = 0.0     # objective constant
    # optional model-declared feasibility repair: callable
    # ``(x: (S, n), batch) -> (S, n)`` mapping near-feasible solver points
    # to EXACTLY feasible ones (full-recourse families close violations in
    # their slack columns in closed form).  The scalable certified-inner-
    # bound mechanism: Xhat_Eval repairs + verifies + prices exactly
    # instead of per-scenario host LP rescues (O(S) seconds each).
    repair_fn: object = None

    @property
    def num_vars(self) -> int:
        return int(self.c.shape[0])

    @property
    def num_rows(self) -> int:
        return int(self.A.shape[0])

    def nonant_indices(self) -> np.ndarray:
        return np.concatenate([nd.nonant_indices for nd in self.nodes])


def _pad_problem(p: ScenarioProblem, n: int, m: int) -> ScenarioProblem:
    """Pad a scenario to (n vars, m rows) with inert slots (fixed-at-0 vars,
    0 <= 0 <= 0 rows) so ragged families batch under vmap (SURVEY §7 hard part 2)."""
    dn, dm = n - p.num_vars, m - p.num_rows
    if dn == 0 and dm == 0:
        return p
    return dataclasses.replace(
        p,
        c=np.pad(p.c, (0, dn)),
        q2=np.pad(p.q2, (0, dn)),
        A=np.pad(p.A, ((0, dm), (0, dn))),
        cl=np.pad(p.cl, (0, dm)),
        cu=np.pad(p.cu, (0, dm)),
        lb=np.pad(p.lb, (0, dn)),
        ub=np.pad(p.ub, (0, dn)),
        is_int=np.pad(p.is_int, (0, dn)),
        var_names=None if p.var_names is None else p.var_names + [f"_pad{i}" for i in range(dn)],
    )


@dataclasses.dataclass
class ScenarioBatch:
    """A stacked batch of scenarios + compiled tree info.

    This is the unit of work the TPU runtime operates on: the analogue of one
    rank's ``local_scenarios`` dict (spbase.py:255-291), but stored as arrays of
    shape (S, ...) ready for vmapped solves and node-grouped reductions.
    """

    names: list
    c: np.ndarray          # (S, n)
    q2: np.ndarray         # (S, n)
    A: np.ndarray          # (S, m, n) — a zero-copy broadcast view when shared
    cl: np.ndarray         # (S, m)
    cu: np.ndarray         # (S, m)
    lb: np.ndarray         # (S, n)
    ub: np.ndarray         # (S, n)
    is_int: np.ndarray     # (n,) bool (shared across scenarios)
    const: np.ndarray      # (S,)
    tree: TreeInfo
    var_names: list | None = None  # (n,) shared column names, if known
    # mutation counter: bump after ANY in-place edit of the arrays above
    # (e.g. cross-scenario cut injection) so cached solver factorizations
    # keyed on it (SPOpt._solve_sig) invalidate
    version: int = 0
    # Shared constraint matrix (m, n), set when every scenario carries the
    # SAME A object (uncertainty in costs/rhs/bounds only — the reference's
    # headline UC is this shape: wind enters the power-balance rhs).  Model
    # creators opt in by reusing one numpy array across their
    # ScenarioProblems; ``.A`` is then a broadcast view (no (S, m, n) memory)
    # and solves dispatch to the shared-A engine
    # (tpusppy.solvers.shared_admm), which keeps ONE (n, n) factorization
    # for the whole batch.
    A_shared: np.ndarray | None = None
    # model-declared feasibility repair (see ScenarioProblem.repair_fn)
    repair_fn: object = None

    @classmethod
    def from_problems(cls, problems: list[ScenarioProblem]) -> "ScenarioBatch":
        probs = [p.prob for p in problems]
        if all(pr is None for pr in probs):
            # uniform default, as spbase.py:505-520
            problems = [
                dataclasses.replace(p, prob=1.0 / len(problems)) for p in problems
            ]
        elif any(pr is None for pr in probs):
            raise ValueError("either all or no scenarios may carry a probability")

        n = max(p.num_vars for p in problems)
        m = max(p.num_rows for p in problems)
        # identity-shared A detection BEFORE padding (padding never triggers
        # for a shared family — all members have the same shape by
        # construction)
        A0 = problems[0].A
        a_shared = all(p.A is A0 for p in problems)
        problems = [_pad_problem(p, n, m) for p in problems]

        tree = build_tree(problems)
        is_int = problems[0].is_int
        for p in problems:
            if not np.array_equal(p.is_int, is_int):
                raise ValueError("integer pattern must match across scenarios")
        # Column names are only meaningful if every scenario agrees; degrade to
        # index labels otherwise (never mislabel a checkpoint column).
        var_names = problems[0].var_names
        if any(p.var_names != var_names for p in problems):
            var_names = None

        if a_shared:
            A_shared = np.ascontiguousarray(A0)
            A = np.broadcast_to(A_shared[None], (len(problems), m, n))
        else:
            A_shared = None
            A = np.stack([p.A for p in problems])
        return cls(
            names=[p.name for p in problems],
            c=np.stack([p.c for p in problems]),
            q2=np.stack([p.q2 for p in problems]),
            A=A,
            A_shared=A_shared,
            cl=np.stack([p.cl for p in problems]),
            cu=np.stack([p.cu for p in problems]),
            lb=np.stack([p.lb for p in problems]),
            ub=np.stack([p.ub for p in problems]),
            is_int=is_int,
            const=np.array([p.const for p in problems]),
            tree=tree,
            var_names=var_names,
            repair_fn=problems[0].repair_fn,
        )

    @property
    def num_scenarios(self) -> int:
        return len(self.names)

    @property
    def num_vars(self) -> int:
        return int(self.c.shape[1])

    @property
    def num_rows(self) -> int:
        return int(self.A.shape[1])

    @property
    def probs(self) -> np.ndarray:
        return self.tree.scen_prob

    def nonant_mask(self) -> np.ndarray:
        """(n,) bool mask of nonant slots."""
        mask = np.zeros(self.num_vars, dtype=bool)
        mask[self.tree.nonant_indices] = True
        return mask

    def augment(self, extra_cols: int, extra_rows: int,
                col_lb=0.0, col_ub=0.0,
                col_names=None) -> "ScenarioBatch":
        """A NEW batch with ``extra_cols`` zero-cost columns and
        ``extra_rows`` inactive (-inf, +inf) row slots appended.

        The device-batch analogue of the reference's model reshaping
        (cross_scen_extension.py:120-283 attaches eta variables and cut
        Constraints to every scenario model): fixed shapes mean one compiled
        program, so structural additions must be PREALLOCATED slots that
        later in-place writes activate (then bump ``version``).  Appending
        keeps every existing column index — tree/nonant arrays stay valid.
        """
        S, m, n = self.A.shape
        dc, dr = int(extra_cols), int(extra_rows)
        pad_c = np.zeros((S, dc))
        if self.A_shared is not None:
            # sharedness SURVIVES augmentation: the new slots start zero in
            # the single (m+dr, n+dc) matrix and later in-place writes must
            # go through ``A_shared`` (identical coefficients for every
            # scenario — the eta-vector cut formulation guarantees this;
            # per-scenario structure belongs in costs/rhs/bounds)
            A_shared = np.zeros((m + dr, n + dc))
            A_shared[:m, :n] = self.A_shared
            A = np.broadcast_to(A_shared[None], (S, m + dr, n + dc))
        else:
            A_shared = None
            A = np.zeros((S, m + dr, n + dc))
            A[:, :m, :n] = self.A
        names = None
        if self.var_names is not None:
            names = self.var_names + list(
                col_names or [f"_aug{i}" for i in range(dc)])
        return dataclasses.replace(
            self,
            c=np.concatenate([self.c, pad_c], axis=1),
            q2=np.concatenate([self.q2, pad_c], axis=1),
            A=A,
            A_shared=A_shared,
            cl=np.concatenate([self.cl, np.full((S, dr), -INF)], axis=1),
            cu=np.concatenate([self.cu, np.full((S, dr), INF)], axis=1),
            lb=np.concatenate(
                [self.lb, np.full((S, dc), float(col_lb))], axis=1),
            ub=np.concatenate(
                [self.ub, np.full((S, dc), float(col_ub))], axis=1),
            is_int=np.concatenate([self.is_int, np.zeros(dc, dtype=bool)]),
            var_names=names,
            version=self.version + 1,
        )

    def objective(self, x: np.ndarray) -> np.ndarray:
        """(S,) per-scenario objective values at x of shape (S, n)."""
        lin = np.einsum("sn,sn->s", self.c, x)
        quad = 0.5 * np.einsum("sn,sn->s", self.q2, x * x)
        return lin + quad + self.const


def _quantize(v: int, quantum: int) -> int:
    return int(-(-v // quantum) * quantum)


@dataclasses.dataclass
class BucketedBatch:
    """Shape-bucketed scenario batch for RAGGED families (SURVEY §7 hard
    part 2; VERDICT r1 weak #9).

    ``ScenarioBatch`` pads every scenario to the family maximum — one
    oversized scenario makes the whole (S, m, n) constraint tensor pay
    quadratically.  Here scenarios are grouped by their (n, m) rounded up to
    a quantum; each bucket is its own compact :class:`ScenarioBatch` (its
    own compiled solver program), while the LINEAR-memory bookkeeping
    arrays (c, q2, lb, ub, cl, cu — all 2-D) are still exposed padded to
    the global maxima so PH/xhat bookkeeping code is unchanged.  The
    quadratic ``A`` tensor deliberately has NO padded global view.

    Uneven bundling (np.array_split remainders) is the in-repo source of
    ragged shapes; per-bucket ``is_int`` also lifts ScenarioBatch's
    same-integer-pattern-across-scenarios restriction for bundles.
    """

    names: list
    buckets: list          # [(np.ndarray scenario indices, ScenarioBatch)]
    tree: "TreeInfo"
    c: np.ndarray          # (S, n_max) — bookkeeping views, zero-padded
    q2: np.ndarray
    lb: np.ndarray
    ub: np.ndarray
    cl: np.ndarray         # (S, m_max)
    cu: np.ndarray
    const: np.ndarray      # (S,)
    var_names: list | None = None   # column names are bucket-local; the
    # global bookkeeping layout degrades to slot indices (None)
    version: int = 0

    @classmethod
    def from_problems(cls, problems, quantum: int = 16) -> "BucketedBatch":
        groups: dict = {}
        for i, p in enumerate(problems):
            nq = _quantize(p.num_vars, quantum)
            mq = _quantize(p.num_rows, quantum)
            # subgroup by the PADDED integer pattern: ScenarioBatch requires
            # one is_int pattern per batch, and shape-padding alone can make
            # patterns differ within a quantized bucket (integer columns in
            # the tail of the wider member)
            patt = np.zeros(nq, dtype=bool)
            patt[:p.num_vars] = p.is_int
            key = (nq, mq, patt.tobytes())
            groups.setdefault(key, []).append(i)
        order = sorted(groups)          # deterministic bucket order
        probs = [p.prob for p in problems]
        if all(pr is None for pr in probs):
            problems = [dataclasses.replace(p, prob=1.0 / len(problems))
                        for p in problems]
        elif any(pr is None for pr in probs):
            raise ValueError(
                "either all or no scenarios may carry a probability")
        buckets = []
        for key in order:
            idx = np.asarray(groups[key], dtype=np.int64)
            members = [problems[i] for i in idx]
            # normalize probs within the bucket: the sub-batch's internal
            # tree is solver plumbing only (reductions use the OUTER tree),
            # but its construction validates a unit probability mass
            tot = sum(p.prob for p in members)
            members = [dataclasses.replace(p, prob=p.prob / tot)
                       for p in members]
            sub = ScenarioBatch.from_problems(members)
            buckets.append((idx, sub))
        tree = build_tree(problems)
        S = len(problems)
        n_max = max(p.num_vars for p in problems)
        m_max = max(p.num_rows for p in problems)

        def pad2(get, width):
            out = np.zeros((S, width))
            for i, p in enumerate(problems):
                v = get(p)
                out[i, :v.shape[0]] = v
            return out

        lb = pad2(lambda p: p.lb, n_max)
        ub = pad2(lambda p: p.ub, n_max)   # padded slots clamp at 0
        return cls(
            names=[p.name for p in problems],
            buckets=buckets, tree=tree,
            c=pad2(lambda p: p.c, n_max), q2=pad2(lambda p: p.q2, n_max),
            lb=lb, ub=ub,
            cl=pad2(lambda p: p.cl, m_max), cu=pad2(lambda p: p.cu, m_max),
            const=np.array([p.const for p in problems]),
        )

    # ---- ScenarioBatch-compatible surface -------------------------------
    @property
    def num_scenarios(self) -> int:
        return len(self.names)

    @property
    def num_vars(self) -> int:
        return int(self.c.shape[1])

    @property
    def num_rows(self) -> int:
        return int(self.cl.shape[1])

    @property
    def probs(self) -> np.ndarray:
        return self.tree.scen_prob

    @property
    def A(self):
        raise AttributeError(
            "BucketedBatch has no global A tensor (that padding is the "
            "quadratic cost bucketing exists to avoid); iterate .buckets "
            "or disable shape_buckets for features needing batch.A")

    @property
    def is_int(self):
        ints = [sub.is_int[:sub.c.shape[1]] for _, sub in self.buckets]
        if any(i.any() for i in ints):
            raise AttributeError(
                "BucketedBatch does not expose a shared is_int pattern "
                "(buckets differ); integer xhat diving requires an unbucketed "
                "batch")
        return np.zeros(self.num_vars, dtype=bool)

    def nonant_mask(self) -> np.ndarray:
        mask = np.zeros(self.num_vars, dtype=bool)
        mask[self.tree.nonant_indices] = True
        return mask

    def padded_elements(self) -> int:
        """Total A elements across buckets (the memory the solve pays)."""
        return int(sum(idx.size * sub.num_rows * sub.num_vars
                       for idx, sub in self.buckets))

    def objective(self, x: np.ndarray) -> np.ndarray:
        out = np.zeros(self.num_scenarios)
        for idx, sub in self.buckets:
            out[idx] = sub.objective(x[idx][:, :sub.num_vars])
        return out
