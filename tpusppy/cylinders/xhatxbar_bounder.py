"""XhatXbar inner-bound spoke: round the per-node average and evaluate it.

TPU-native analogue of ``mpisppy/cylinders/xhatxbar_bounder.py:31-118``: the
candidate is the probability-weighted per-node mean of the hub's nonants
(xbar), with integer slots rounded — automatically nonanticipative, and often
good once PH is nearly converged.
"""

from __future__ import annotations

import numpy as np

from .spoke import InnerBoundNonantSpoke


def xbar_candidate(opt, xk: np.ndarray, threshold: float = 0.5) -> np.ndarray:
    """(S, K) per-node weighted mean of xk, integer slots rounded
    (xhatxbar_bounder.py:31-80 semantics on the batched layout).

    ``threshold``: integer slots round UP when their fractional part is at
    least this (0.5 = nearest).  Lower thresholds commit more — on UC-like
    families where under-commitment prices VOLL shedding, a small ladder of
    thresholds beats nearest-rounding by an order of magnitude.
    """
    onehot = opt.tree.onehot_sk_n()           # (S, K, N)
    p = opt.probs[:, None]
    num = np.einsum("skn,sk->nk", onehot, p * xk)
    den = np.einsum("skn,sk->nk", onehot, np.broadcast_to(p, xk.shape))
    xbar_nk = num / np.maximum(den, 1e-300)
    kidx = np.arange(xk.shape[1])[None, :]
    cand = xbar_nk[opt.nid_sk, kidx]
    ints = opt.batch.is_int[opt.tree.nonant_indices]
    if ints.any():
        cand = np.where(ints[None, :],
                        np.floor(cand + (1.0 - threshold)), cand)
    return cand


class XhatXbarInnerBound(InnerBoundNonantSpoke):
    """'X' spoke (xhatxbar_bounder.py:31-118).

    ``xhat_xbar_options: {"thresholds": [...]}`` evaluates a rounding
    ladder per fresh nonants (default [0.5]; integer families benefit from
    adding commit-biased entries like 0.35/0.25).
    """

    converger_spoke_char = 'X'

    def _sweep(self, xk, final=False):
        for th in self._thresholds:
            cand = xbar_candidate(self.opt, xk, threshold=th)
            obj = self.opt.evaluate(cand)
            self.update_if_improving(obj)
            # mid-run sweeps yield to fresher nonants; the finalize pass
            # must NOT take this exit — the sentinel is permanently set by
            # then, and the whole point is to finish the ladder
            if not final and self.peek_kill_signal():
                return

    def main(self):
        self._thresholds = list(self.opt.options.get(
            "xhat_xbar_options", {}).get("thresholds", [0.5]))
        self._seen = False
        while not self.got_kill_signal():
            if self.new_nonants:
                self._seen = True
                self._sweep(self.localnonants)

    def finalize(self):
        """Final ladder pass with the last hub nonants (see XhatShuffle)."""
        if getattr(self, "_seen", False):
            self._sweep(self.localnonants, final=True)
        return super().finalize()
