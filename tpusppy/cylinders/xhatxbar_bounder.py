"""XhatXbar inner-bound spoke: round the per-node average and evaluate it.

TPU-native analogue of ``mpisppy/cylinders/xhatxbar_bounder.py:31-118``: the
candidate is the probability-weighted per-node mean of the hub's nonants
(xbar), with integer slots rounded — automatically nonanticipative, and often
good once PH is nearly converged.
"""

from __future__ import annotations

import numpy as np

from .spoke import InnerBoundNonantSpoke


def candidate_rule(batch, nid, cand: np.ndarray,
                   threshold: float = 0.5) -> np.ndarray:
    """THE host-side xhat candidate rule, single-sourced for every
    consumer (:func:`xbar_candidate`, :func:`in_wheel_inner_bound`,
    ``PHBase._inwheel_host_rescue``; ``parallel.sharded.
    _bound_pass_terms`` is its traced jnp twin, pinned by 1e-9 parity
    tests): round integer nonant slots at ``threshold``, then CLIP to
    the nonant box.

    The clip is load-bearing: the mean of eps-accurate ADMM solutions
    carries tolerance noise (``u = 1 + 4e-6``, ``u = -4e-8``), and a
    clamped column eps OUTSIDE its box poisons every row coupling to it
    (``p <= pmax*u`` with ``u < 0`` forces ``p < 0`` against ``p >= 0``)
    — the whole evaluation would read infeasible over a 1e-8 rounding
    artifact.  Touches only (S, K) column slices — no full-bound
    copies, so the spoke's per-pass call stays allocation-light."""
    ints = np.asarray(batch.is_int, bool)[nid]
    if ints.any():
        cand = np.where(ints[None, :],
                        np.floor(cand + (1.0 - threshold)), cand)
    return np.clip(cand, np.asarray(batch.lb)[:, nid],
                   np.asarray(batch.ub)[:, nid])


def clamp_candidate(batch, nid, cand: np.ndarray, threshold: float = 0.5):
    """:func:`candidate_rule` plus the clamp: returns ``(cand, lb, ub)``
    with FRESH full bound copies whose nonant columns are fixed at the
    candidate — the form the clamped-evaluation consumers (the in-wheel
    host twin and the host-exact rescue) feed a solver."""
    cand = candidate_rule(batch, nid, cand, threshold)
    lb = np.array(batch.lb, copy=True)
    ub = np.array(batch.ub, copy=True)
    lb[:, nid] = cand
    ub[:, nid] = cand
    return cand, lb, ub


def xbar_candidate(opt, xk: np.ndarray, threshold: float = 0.5) -> np.ndarray:
    """(S, K) per-node weighted mean of xk, integer slots rounded
    (xhatxbar_bounder.py:31-80 semantics on the batched layout).

    ``threshold``: integer slots round UP when their fractional part is at
    least this (0.5 = nearest).  Lower thresholds commit more — on UC-like
    families where under-commitment prices VOLL shedding, a small ladder of
    thresholds beats nearest-rounding by an order of magnitude.
    """
    onehot = opt.tree.onehot_sk_n()           # (S, K, N)
    p = opt.probs[:, None]
    num = np.einsum("skn,sk->nk", onehot, p * xk)
    den = np.einsum("skn,sk->nk", onehot, np.broadcast_to(p, xk.shape))
    xbar_nk = num / np.maximum(den, 1e-300)
    kidx = np.arange(xk.shape[1])[None, :]
    cand = xbar_nk[opt.nid_sk, kidx]
    return candidate_rule(opt.batch, opt.tree.nonant_indices, cand,
                          threshold)


def in_wheel_inner_bound(opt, threshold: float = 0.5, feas_tol=None):
    """The xhat-at-xbar inner bound computed from ``opt``'s CURRENT state
    — the host-side twin of the megastep's fused bound pass
    (``parallel.sharded._bound_pass_terms``), single-sourcing the
    candidate rule with :func:`xbar_candidate` semantics: the candidate
    is ``opt.xbars`` (already the per-node weighted mean, gathered per
    scenario) with integer nonant slots rounded at ``threshold``, clamped
    onto the nonant columns and evaluated by ONE frozen solve on the
    window's cached factors.  The clamped problem is solved under the
    PH-augmented (q, q2) — identical minimizer (the augmentation is
    constant on the clamped coordinates) and exactly-matching factors —
    and the PLAIN expected objective is reported.

    Returns ``(inner, feas_mass)``: the expected objective at the
    evaluated point and the feasible probability mass under the
    ``Xhat_Eval`` residual gate (``inner`` is only a certified-to-
    tolerance incumbent when ``feas_mass >= 1 - 1e-9``, the all-scenarios
    rule).  Requires frozen-ready state (factors + warm); parity tests
    pin this against the in-megastep scalars at 1e-9.
    """
    import jax.numpy as jnp

    from ..solvers import admm, hostsync, shared_admm

    if getattr(opt, "_host_state_stale", False):
        opt._sync_host_state()
    if opt._factors is None or opt._warm is None:
        raise RuntimeError("in_wheel_inner_bound requires frozen-ready "
                           "state (a prior refresh solve)")
    b = opt.batch
    nid = np.asarray(opt.tree.nonant_indices)
    cand, lb, ub = clamp_candidate(
        b, nid, np.array(opt.xbars, dtype=float), threshold)
    q, q2 = opt._augmented_q()
    st = opt.admm_settings
    dt = st.jdtype()
    A_d, cl_d, cu_d = opt._device_consts(dt)
    x, z, y, yx = opt._warm
    x0 = jnp.asarray(x, dt).at[:, nid].set(jnp.asarray(cand, dt))
    warm = (x0, jnp.asarray(z, dt), jnp.asarray(y, dt),
            jnp.asarray(yx, dt))
    args = (jnp.asarray(q, dt), jnp.asarray(q2, dt), A_d, cl_d, cu_d,
            jnp.asarray(lb, dt), jnp.asarray(ub, dt))
    if getattr(b, "A_shared", None) is not None:
        sol = shared_admm.solve_shared_frozen(
            *args, factors=opt._factors, settings=st, warm=warm)
    else:
        sol = admm.solve_batch_frozen(
            *args, factors=opt._factors, settings=st, warm=warm)
    xs, pri = (np.asarray(a) for a in hostsync.fetch((sol.x, sol.pri_res)))
    obj = (np.einsum("sn,sn->s", np.asarray(b.c), xs)
           + 0.5 * np.einsum("sn,sn->s", np.asarray(b.q2), xs * xs)
           + np.broadcast_to(np.asarray(b.const), (b.num_scenarios,)))
    if feas_tol is None:
        feas_tol = opt._inwheel_feas_tol()
    probs = np.asarray(opt.probs, dtype=float)
    return float(probs @ obj), float(probs @ (pri < feas_tol))


class XhatXbarInnerBound(InnerBoundNonantSpoke):
    """'X' spoke (xhatxbar_bounder.py:31-118).

    ``xhat_xbar_options: {"thresholds": [...]}`` evaluates a rounding
    ladder per fresh nonants (default [0.5]; integer families benefit from
    adding commit-biased entries like 0.35/0.25).
    """

    converger_spoke_char = 'X'

    def _sweep(self, xk, final=False):
        for th in self._thresholds:
            cand = xbar_candidate(self.opt, xk, threshold=th)
            obj = self.opt.evaluate(cand)
            self.update_if_improving(obj)
            # mid-run sweeps yield to fresher nonants; the finalize pass
            # must NOT take this exit — the sentinel is permanently set by
            # then, and the whole point is to finish the ladder
            if not final and self.peek_kill_signal():
                return

    def main(self):
        th = self.opt.options.get(
            "xhat_xbar_options", {}).get("thresholds")
        if th is None:
            # integer families default to the SAME rounding ladder the
            # in-wheel batched integer sweep evaluates on device
            # (solvers.integer.DEFAULT_THRESHOLDS — one candidate rule,
            # two execution paths); continuous families keep the single
            # pass-through candidate.  Bucketed batches carry is_int
            # per bucket (no shared global pattern — reading batch.is_int
            # raises), so the check walks the buckets.
            from ..ir import BucketedBatch

            b = self.opt.batch
            if isinstance(b, BucketedBatch):
                ints_any = any(
                    np.asarray(sub.is_int,
                               bool)[sub.tree.nonant_indices].any()
                    for _, sub in b.buckets)
            else:
                ints_any = bool(np.asarray(
                    b.is_int, bool)[self.opt.tree.nonant_indices].any())
            if ints_any:
                from ..solvers.integer import DEFAULT_THRESHOLDS
                th = list(DEFAULT_THRESHOLDS)
            else:
                th = [0.5]
        self._thresholds = list(th)
        self._seen = False
        while not self.got_kill_signal():
            if self.new_nonants:
                self._seen = True
                self._sweep(self.localnonants)

    def finalize(self):
        """Final ladder pass with the last hub nonants (see XhatShuffle)."""
        if getattr(self, "_seen", False):
            self._sweep(self.localnonants, final=True)
        return super().finalize()
