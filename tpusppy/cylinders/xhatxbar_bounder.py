"""XhatXbar inner-bound spoke: round the per-node average and evaluate it.

TPU-native analogue of ``mpisppy/cylinders/xhatxbar_bounder.py:31-118``: the
candidate is the probability-weighted per-node mean of the hub's nonants
(xbar), with integer slots rounded — automatically nonanticipative, and often
good once PH is nearly converged.
"""

from __future__ import annotations

import numpy as np

from .spoke import InnerBoundNonantSpoke


def xbar_candidate(opt, xk: np.ndarray) -> np.ndarray:
    """(S, K) per-node weighted mean of xk, integer slots rounded
    (xhatxbar_bounder.py:31-80 semantics on the batched layout)."""
    onehot = opt.tree.onehot_sk_n()           # (S, K, N)
    p = opt.probs[:, None]
    num = np.einsum("skn,sk->nk", onehot, p * xk)
    den = np.einsum("skn,sk->nk", onehot, np.broadcast_to(p, xk.shape))
    xbar_nk = num / np.maximum(den, 1e-300)
    kidx = np.arange(xk.shape[1])[None, :]
    cand = xbar_nk[opt.nid_sk, kidx]
    ints = opt.batch.is_int[opt.tree.nonant_indices]
    if ints.any():
        cand = np.where(ints[None, :], np.round(cand), cand)
    return cand


class XhatXbarInnerBound(InnerBoundNonantSpoke):
    """'X' spoke (xhatxbar_bounder.py:31-118)."""

    converger_spoke_char = 'X'

    def main(self):
        while not self.got_kill_signal():
            if self.new_nonants:
                cand = xbar_candidate(self.opt, self.localnonants)
                obj = self.opt.evaluate(cand)
                self.update_if_improving(obj)
