"""Lagrangian outer-bound spoke.

TPU-native analogue of ``mpisppy/cylinders/lagrangian_bounder.py:5-95``: take
the hub's PH dual weights W, solve every scenario subproblem with W active and
the prox term OFF, and report the weighted sum of subproblem objectives — a
valid lower (outer) bound for minimization since PH keeps the probability-
weighted W summing to zero per node.  One batched ADMM call per fresh W.
"""

from __future__ import annotations

import numpy as np

from .. import global_toc
from ..obs import metrics as _metrics
from .spoke import OuterBoundWSpoke


def in_wheel_outer_bound(opt) -> float:
    """The Lagrangian outer bound computed from ``opt``'s CURRENT state —
    no fresh batched solve: the W-augmented (W on, prox off) objective
    evaluated through the weak-duality assembly with the warm state's row
    duals.  This is EXACTLY what the in-wheel bound pass fuses into the
    megastep window (``parallel.sharded._bound_pass_terms``), exposed
    host-side so parity tests and spoke-less callers share one
    definition.  Any duals certify (weak duality); the carried duals of a
    near-converged wheel are tight, which is why a self-certifying wheel
    needs no spoke device program (doc/pipeline.md "In-wheel
    certification").

    The device-resident posture syncs the host mirrors first (one billed
    boundary fetch); requires a prior solve (warm duals must exist).
    """
    if getattr(opt, "_host_state_stale", False):
        opt._sync_host_state()
    b = opt.batch
    idx = opt.tree.nonant_indices
    q = np.array(b.c, copy=True)
    q[:, idx] += np.asarray(opt.W, dtype=float)
    return opt.Edualbound(q=q, q2=b.q2)


class LagrangianOuterBound(OuterBoundWSpoke):
    """'L' spoke: Lagrangian dual bound from hub Ws
    (lagrangian_bounder.py:5-95)."""

    converger_spoke_char = 'L'

    def lagrangian_prep(self):
        """The reference's PH_Prep(attach_prox=False) + _reenable_W
        (lagrangian_bounder.py:9-17): our opt object needs no model surgery —
        just force the W-on/prox-off objective mode."""
        self.opt.W_on = True
        self.opt.prox_on = False

    def lagrangian(self) -> float:
        """Solve the W-augmented batch and return the dual bound
        (lagrangian_bounder.py:19-56): E[obj + W·x_nonant].

        The objective comes from the opt object's own ``_augmented_q`` (with
        W on, prox off per ``lagrangian_prep``) so the assembly stays single-
        sourced with PH.

        With ``lagrangian_milp_lift`` in the opt options (a dict of
        :func:`tpusppy.solvers.milp_bound.milp_lift` kwargs plus ``every``),
        per-scenario LP certificates are lifted to host MILP dual bounds on
        integer families — the reference spoke's integer subproblem minima
        (its persistent solver is a MIP solver), which close the integrality
        gap a pure LP-relaxation bound cannot.  The lift is budget-elastic
        and valid at ANY completed subset of scenarios.
        """
        opt = self.opt
        q, q2 = opt._augmented_q()
        donor_cfg = opt.options.get("lagrangian_dual_donors")
        # full scale (lagrangian_skip_solve): the batched S-solve exists
        # only to produce ADMM duals, which plateau orders-of-magnitude
        # loose at reference scale AND starve the chip for the hub/eval
        # cylinders (the r5 run-4 trace: the spoke's first pass never
        # finished inside a 3000s wheel).  Donor transfer needs no solve —
        # bound from k host-exact donor duals alone.
        skip_solve = bool(opt.options.get("lagrangian_skip_solve")
                          and donor_cfg)
        if opt.options.get("lagrangian_skip_solve") and not donor_cfg:
            # the knob reads as armed but is NOT: skipping the solve is
            # only sound when donor duals supply the bound, so without
            # ``lagrangian_dual_donors`` this silently downgraded to the
            # full batched solve the caller believed they had skipped —
            # say so loudly once, and record the decline
            _metrics.inc("lagrangian.skip_declined")
            if not getattr(self, "_skip_declined_warned", False):
                self._skip_declined_warned = True
                global_toc(
                    "WARNING: lagrangian_skip_solve is set but "
                    "lagrangian_dual_donors is not — the skip is "
                    "DECLINED (full batched solve runs; configure "
                    "donors, or drop the knob)", True)
        if not skip_solve:
            opt.solve_loop(q=q, q2=q2)
        # CERTIFIED bound: dual objective of the W-augmented subproblems
        # (weak duality absorbs solver tolerance; an inexact primal objective
        # can overshoot the true bound and falsely certify rel_gap)
        base = None
        if donor_cfg:
            # plateaued ADMM duals are orders-of-magnitude loose and
            # per-scenario host rescue is O(S) seconds — transfer k
            # host-EXACT donor duals batch-wide instead
            # (spopt.dual_donor_bounds; any y is valid for any scenario)
            donors = opt.dual_donor_bounds(q=q, q2=q2, **dict(donor_cfg))
            if donors is not None:
                base = donors
                if not skip_solve:
                    base = np.maximum(
                        opt.Edualbound_perscen(q=q, q2=q2), donors)
            elif skip_solve:
                # donors failed entirely: fall back to the solve path
                opt.solve_loop(q=q, q2=q2)
        lift_cfg = opt.options.get("lagrangian_milp_lift")
        if lift_cfg and bool(np.asarray(opt.batch.is_int).any()):
            every = max(1, int(lift_cfg.get("every", 1)))
            if getattr(self, "dk_iter", 1) % every == 0:
                from ..solvers.milp_bound import milp_lift

                if base is None:
                    base = opt.Edualbound_perscen(q=q, q2=q2)
                kw = {k: v for k, v in lift_cfg.items() if k != "every"}
                lifted, n = milp_lift(opt.batch, q, base, **kw)
                self.last_milp_lift_count = n
                return float(opt.probs @ lifted)
        if base is not None:
            return float(opt.probs @ base)
        return opt.Edualbound(q=q, q2=q2)

    def _set_weights_and_solve(self) -> float:
        self.opt.W = np.asarray(self.localWs, dtype=float).copy()
        return self.lagrangian()

    def main(self):
        self.lagrangian_prep()
        self.opt.W = np.zeros(
            (self.opt.batch.num_scenarios, self.opt.nonant_length)
        )
        self.trivial_bound = self.lagrangian()
        self.bound = self.trivial_bound
        self.dk_iter = 1
        while not self.got_kill_signal():
            if self.new_Ws:
                bound = self._set_weights_and_solve()
                if bound is not None and np.isfinite(bound):
                    self.bound = bound
                self.dk_iter += 1

    def finalize(self):
        """One final pass with the last Ws (lagrangian_bounder.py:85-95).

        With ``lagrangian_milp_ascent`` in the opt options (kwargs for
        :func:`tpusppy.solvers.milp_bound.milp_dual_ascent`), the final W is
        additionally polished by projected subgradient ascent on the INTEGER
        Lagrangian dual — every iterate is a certified bound, the best one
        is reported.  This is the reference Lagranger spoke's own-steps
        posture (lagranger_bounder.py) with MIP subproblem minima.
        """
        self.final_bound = self._set_weights_and_solve()
        if np.isfinite(self.final_bound):
            self.bound = self.final_bound
        ascent_cfg = dict(self.opt.options.get("lagrangian_milp_ascent")
                          or {})
        # the hub ships its current (outer, inner) bounds in the W payload
        # tail: when the wheel has ALREADY certified a gap at or below
        # ``skip_if_gap_at``, the ascent polish can only burn the wall
        # clock the watchdog is counting
        skip_at = float(ascent_cfg.pop("skip_if_gap_at", 0.0))
        if ascent_cfg and skip_at > 0.0 and self._locals.shape[0] >= 2:
            ob, ib = self.hub_outer_bound, self.hub_inner_bound
            # the HUB's own gap convention (hub.py): minimization,
            # (ib - ob)/|ob|; a negative difference means crossed bounds —
            # never a reason to skip
            if (self.opt.is_minimizing and np.isfinite(ob)
                    and np.isfinite(ib) and abs(ob) > 0
                    and 0 <= (ib - ob) / abs(ob) <= skip_at):
                ascent_cfg = None
        if ascent_cfg and bool(np.asarray(self.opt.batch.is_int).any()):
            from ..solvers.milp_bound import milp_dual_ascent

            opt = self.opt

            def base_fn(W):
                opt.W = np.asarray(W, dtype=float)
                q, q2 = opt._augmented_q()
                # no straggler rescue inside ascent steps: the MILP lift
                # supplies the certificates, the LP duals are only the
                # partial-lift fallback — host-rescuing dozens of stalled
                # LPs per subgradient step would eat the ascent budget
                saved = opt.options.get("straggler_rescue", True)
                opt.options["straggler_rescue"] = False
                try:
                    opt.solve_loop(q=q, q2=q2)
                finally:
                    opt.options["straggler_rescue"] = saved
                return q, opt.Edualbound_perscen(q=q, q2=q2)

            best, _ = milp_dual_ascent(
                opt.batch, opt.W, base_fn, **dict(ascent_cfg))
            if np.isfinite(best) and (not np.isfinite(self.final_bound)
                                      or best > self.final_bound):
                self.final_bound = best
                self.bound = best
        return self.final_bound
