"""Lagranger outer-bound spoke: independent Lagrangian from hub nonants.

TPU-native analogue of ``mpisppy/cylinders/lagranger_bounder.py:11-119``: takes
the hub's **x** values (not its Ws), runs its own xbar/W updates at possibly
rescaled rho, and reports the Lagrangian bound of its own duals.  This gives a
second, independently-weighted outer bound stream ('A' vs the 'L' spoke).
"""

from __future__ import annotations

import numpy as np

from .spoke import OuterBoundNonantSpoke


class LagrangerOuterBound(OuterBoundNonantSpoke):
    """'A' spoke (lagranger_bounder.py:11-119)."""

    converger_spoke_char = 'A'

    def lagrangian_prep(self):
        self.opt.W_on = True
        self.opt.prox_on = False
        # per-iteration rho rescale schedule {iter: factor}; factors ACCUMULATE
        # (lagranger_bounder.py:55-60)
        sched = self.opt.options.get("lagranger_rho_rescale_factors")
        json_path = self.opt.options.get("lagranger_rho_rescale_factors_json")
        if sched is None and json_path is not None:
            import json

            with open(json_path) as fin:
                sched = {int(k): float(v) for k, v in json.load(fin).items()}
        self.rho_rescale_factors = (
            {int(k): float(v) for k, v in sched.items()} if sched else None
        )

    def _lagrangian(self, iternum) -> float:
        if self.rho_rescale_factors is not None \
                and iternum in self.rho_rescale_factors:
            self.opt.rho = self.opt.rho * self.rho_rescale_factors[iternum]
        q, q2 = self.opt._augmented_q()
        self.opt.solve_loop(q=q, q2=q2)
        # certified dual-objective bound (see LagrangianOuterBound.lagrangian)
        return self.opt.Edualbound(q=q, q2=q2)

    def _update_weights_and_solve(self, iternum) -> float:
        """Adopt hub x, recompute own xbar/W, solve
        (lagranger_bounder.py:85-93)."""
        opt = self.opt
        # hub nonants define the "current x" for the xbar/W update
        xfull = np.array(opt.batch.lb, copy=True) * 0.0
        if opt.local_x is not None:
            xfull = np.array(opt.local_x, copy=True)
        xfull[:, opt.tree.nonant_indices] = self.localnonants
        opt.local_x = xfull
        opt.Compute_Xbar()
        opt.Update_W()
        return self._lagrangian(iternum)

    def main(self):
        self.lagrangian_prep()
        self.A_iter = 1
        self._ever_nonants = False
        self.trivial_bound = self._lagrangian(0)
        self.bound = self.trivial_bound
        while not self.got_kill_signal():
            if self.new_nonants:
                self._ever_nonants = True
                bound = self._update_weights_and_solve(self.A_iter)
                if np.isfinite(bound):
                    self.bound = bound
                self.A_iter += 1

    def finalize(self):
        """One final pass with the last nonants (lagranger_bounder.py:108-119)."""
        if not getattr(self, "_ever_nonants", False):
            return None
        self.final_bound = self._update_weights_and_solve(self.A_iter)
        if np.isfinite(self.final_bound):
            self.bound = self.final_bound
        return self.final_bound
