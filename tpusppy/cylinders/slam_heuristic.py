"""Slam heuristic inner-bound spokes: per-variable max/min candidates.

TPU-native analogue of ``mpisppy/cylinders/slam_heuristic.py:24-125``
(two-stage only there; here the per-node aggregation in
:func:`tpusppy.extensions.xhatbase.slam_cache` generalizes to multistage for
free): the candidate slams every nonant to the max (or min) over scenarios —
an integer-friendly incumbent guess evaluated in one batched solve.
"""

from __future__ import annotations

import numpy as np

from .spoke import InnerBoundNonantSpoke
from ..extensions.xhatbase import slam_cache


class _SlamHeuristic(InnerBoundNonantSpoke):
    converger_spoke_char = 'S'
    how = None  # "max" / "min"

    def _slam_once(self):
        ints = self.opt.batch.is_int[self.opt.tree.nonant_indices]
        cand = slam_cache(self.opt, self.localnonants, how=self.how)
        if ints.any():
            # directional rounding keeps the slam semantics on
            # fractional (LP-relaxation) inputs: a max-slam commits
            # anything any scenario wants committed (ceil), a
            # min-slam only what every scenario agrees on (floor)
            snap = (np.ceil(cand - 1e-9) if self.how == "max"
                    else np.floor(cand + 1e-9))
            cand = np.where(ints[None, :], snap, cand)
        obj = self.opt.evaluate(cand)
        self.update_if_improving(obj)

    def main(self):
        self._seen = False
        while not self.got_kill_signal():
            if self.new_nonants:
                self._seen = True
                self._slam_once()

    def finalize(self):
        """Final slam pass with the last hub nonants (see XhatShuffle)."""
        if getattr(self, "_seen", False):
            self._slam_once()
        return super().finalize()


class SlamMaxHeuristic(_SlamHeuristic):
    """'S' spoke slamming to the per-node max (slam_heuristic.py:107-115)."""
    how = "max"


class SlamMinHeuristic(_SlamHeuristic):
    """'S' spoke slamming to the per-node min (slam_heuristic.py:117-125)."""
    how = "min"
