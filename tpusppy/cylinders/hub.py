"""Hub communicators: bound bookkeeping, gap termination, spoke traffic.

TPU-native analogue of ``mpisppy/cylinders/hub.py:23-771``.  The hub owns the
optimization object (PH here), pushes W / nonant / bound payloads into the
per-spoke outbound mailboxes each ``sync()`` (hub.py:501-514), pulls spoke
bounds with write-id freshness checks (hub.py:174-200,396-436), tracks the
best inner/outer bounds, and terminates the wheel on ``rel_gap`` / ``abs_gap``
/ ``max_stalled_iters`` (hub.py:77-161) by broadcasting the kill sentinel
(hub.py:438-450).

Bound source chars: spokes report through their class chars (L/X/I/O/...),
``'T'`` is the trivial bound, ``'R'`` a checkpoint re-seed, ``'B'`` the
Benders root, and ``'M'`` an IN-WHEEL bound — the megastep's fused bound
pass (doc/pipeline.md "In-wheel certification") landing through the same
typed ``OuterBoundUpdate``/``InnerBoundUpdate`` path, so gap termination
and the gap-vs-wall trace treat in-wheel and spoke bounds identically; a
single-cylinder wheel certifies with zero spoke device programs.
"""

from __future__ import annotations

from math import inf

import numpy as np

from .. import global_toc
from ..obs import metrics as _metrics
from ..obs import trace as _trace
from .spcommunicator import SPCommunicator
from .spoke import ConvergerSpokeType


class Hub(SPCommunicator):
    """Base hub (hub.py:23-450)."""

    def __init__(self, spbase_object, strata_rank, fabric, spokes,
                 options=None):
        super().__init__(spbase_object, strata_rank, fabric, options)
        self.spokes = list(spokes)           # list of dicts with spoke_class
        self.remote_write_ids = {}           # spoke idx -> last accepted id
        self.latest_ib_char = None
        self.latest_ob_char = None
        self.print_init = True
        self.stalled_iter_cnt = 0
        self.last_gap = inf
        # resilience attachments (tpusppy.resilience): the wheel spinner
        # wires a SpokeSupervisor (degradation) and a CheckpointManager
        # (async snapshots) when configured; both stay None otherwise
        self.supervisor = None
        self._ckpt_mgr = None
        self.latest_spoke_bounds = {}        # idx -> last bound read (meta)
        self.resumed_from_iteration = None
        # tenant preemption (tpusppy.service, doc/serving.md): True once
        # options["preempt_check"] asked this wheel to park — the run
        # terminated at a window boundary WITHOUT certifying, and its
        # final checkpoint is the parked state a later resume continues
        self.preempted = False

    # ---- resilience (tpusppy.resilience) ------------------------------------
    def attach_supervisor(self, sup):
        self.supervisor = sup

    def attach_checkpointer(self, mgr):
        self._ckpt_mgr = mgr

    def seed_resume(self, ckpt):
        """Re-seed the hub's bounds from a checkpoint (call after
        ``setup_hub``).  Bound updates only ever improve on these, so the
        certified gap trajectory is monotone across the restart.

        Per-spoke bounds re-seed by their STORED kind ([kind, bound]
        entries — the kind, not the resumed wheel's slot assignment,
        decides whether a value may tighten the outer or the inner side,
        so a reordered/trimmed spoke list can never install an outer
        bound as an incumbent).  Kind-less legacy floats are skipped —
        the global bests already carry their contribution."""
        if np.isfinite(ckpt.best_outer):
            self.OuterBoundUpdate(float(ckpt.best_outer), char='R')
        if np.isfinite(ckpt.best_inner):
            self.InnerBoundUpdate(float(ckpt.best_inner), char='R')
        for key, entry in (ckpt.spoke_bounds or {}).items():
            if not (isinstance(entry, (list, tuple)) and len(entry) == 2):
                continue
            kind, b = entry
            try:
                idx, b = int(key), float(b)
            except (TypeError, ValueError):
                continue
            if not np.isfinite(b):
                continue
            self.latest_spoke_bounds[idx] = b
            # idx only picks the display char, and only when the resumed
            # slot still has the same role
            if kind == "outer":
                same = idx in self.outerbound_spoke_indices
                self.OuterBoundUpdate(b, idx if same else None, char='R')
            elif kind == "inner":
                same = idx in self.innerbound_spoke_indices
                self.InnerBoundUpdate(b, idx if same else None, char='R')
        self.resumed_from_iteration = int(ckpt.iteration)

    def checkpoint_due(self, iteration) -> bool:
        """Whether the next :meth:`sync` will capture a checkpoint at
        ``iteration`` — the device-resident wheel posture asks BEFORE
        syncing so it can refresh the host mirrors the capture reads
        (the capture itself stays pinned zero-fetch)."""
        return (self._ckpt_mgr is not None
                and self._ckpt_mgr._due(int(iteration)))

    def _resilience_tick(self):
        """Per-sync health + checkpoint pass: observe spoke liveness and
        capture a snapshot when the cadence is due.  The snapshot reads
        only host-resident PH state (capture_ph), so this adds zero
        blocking fetches to the dispatch decision path."""
        if self.supervisor is not None:
            self.supervisor.observe()
        if self._ckpt_mgr is not None:
            from ..resilience import checkpoint as _ckpt
            from ..resilience import supervisor as _sup

            _sup.heartbeat("hub")
            if getattr(self.opt, "_host_state_stale", False):
                # device-resident posture (doc/scaling.md): the host
                # mirrors are stale mid-window.  The boundary pre-sync
                # (PHBase._spcomm_needs_host_state) refreshes them when
                # checkpoint_due() fires, but a WALL-CLOCK cadence can
                # cross its threshold between that check and this tick —
                # capturing here would stamp one-window-old W/xbars with
                # the current iteration.  Skip without advancing the
                # cadence: the next boundary's due check pre-syncs and
                # the capture lands fresh.
                return
            try:
                self._ckpt_mgr.maybe_capture(
                    self.current_iteration(),
                    lambda: _ckpt.capture_ph(self.opt, hub=self))
            except Exception as e:
                # a capture failure (host OOM copying (S, K) arrays, a
                # transfer-guard trip on an exotic opt) costs the run's
                # RESUMABILITY, never the run — same policy as the write
                # path and the final capture
                _metrics.inc("checkpoint.capture_errors")
                if not getattr(self, "_ckpt_err_warned", False):
                    self._ckpt_err_warned = True
                    global_toc(
                        f"WARNING: checkpoint capture failed (run "
                        f"continues, resumability degraded): {e!r}", True)

    # ---- spoke typing (hub.py:297-344) --------------------------------------
    def initialize_spoke_indices(self):
        self.outerbound_spoke_indices = set()
        self.innerbound_spoke_indices = set()
        self.nonant_spoke_indices = set()
        self.w_spoke_indices = set()
        self.outerbound_spoke_chars = {}
        self.innerbound_spoke_chars = {}
        for i, spoke in enumerate(self.spokes):
            cls = spoke["spoke_class"]
            for cst in getattr(cls, "converger_spoke_types", ()):
                if cst == ConvergerSpokeType.OUTER_BOUND:
                    self.outerbound_spoke_indices.add(i + 1)
                    self.outerbound_spoke_chars[i + 1] = cls.converger_spoke_char
                elif cst == ConvergerSpokeType.INNER_BOUND:
                    self.innerbound_spoke_indices.add(i + 1)
                    self.innerbound_spoke_chars[i + 1] = cls.converger_spoke_char
                elif cst == ConvergerSpokeType.W_GETTER:
                    self.w_spoke_indices.add(i + 1)
                elif cst == ConvergerSpokeType.NONANT_GETTER:
                    self.nonant_spoke_indices.add(i + 1)
        self.bounds_only_indices = (
            (self.outerbound_spoke_indices | self.innerbound_spoke_indices)
            - (self.w_spoke_indices | self.nonant_spoke_indices)
        )
        self.has_outerbound_spokes = bool(self.outerbound_spoke_indices)
        self.has_innerbound_spokes = bool(self.innerbound_spoke_indices)
        self.has_nonant_spokes = bool(self.nonant_spoke_indices)
        self.has_w_spokes = bool(self.w_spoke_indices)
        self.has_bounds_only_spokes = bool(self.bounds_only_indices)

    def initialize_bound_values(self):
        if self.opt.is_minimizing:
            self.BestInnerBound, self.BestOuterBound = inf, -inf
            self._ib_better = lambda new, old: new < old
            self._ob_better = lambda new, old: new > old
        else:
            self.BestInnerBound, self.BestOuterBound = -inf, inf
            self._ib_better = lambda new, old: new > old
            self._ob_better = lambda new, old: new < old

    # ---- gap / termination (hub.py:77-161) ----------------------------------
    def compute_gaps(self):
        if self.opt.is_minimizing:
            abs_gap = self.BestInnerBound - self.BestOuterBound
        else:
            abs_gap = self.BestOuterBound - self.BestInnerBound
        if np.isfinite(abs_gap) and np.isfinite(self.BestOuterBound):
            # a legitimately-zero outer bound (optimum at 0) falls back to
            # the absolute gap as the "relative" gap so rel_gap termination
            # still fires; the reference (hub.py:88-97) returns inf there
            # and can never terminate on rel_gap.  Nonzero bounds keep the
            # reference's convention exactly.
            rel_gap = abs_gap / (abs(self.BestOuterBound) or 1.0)
        else:
            rel_gap = inf
        if _trace.enabled() and np.isfinite(rel_gap):
            # the gap-vs-wall series of the flight recorder: one sample
            # per gap computation, so the report's array ends at the
            # final certified gap (report.py collects "rel_gap"/"abs_gap")
            _trace.counter("hub", "rel_gap", rel_gap)
            _trace.counter("hub", "abs_gap", abs_gap)
        # live progress seam (doc/observability.md): the solve service
        # plants options["progress_cb"] the way it plants preempt_check;
        # the callback dedupes, so calling on EVERY gap computation is
        # fine — and a progress fault must never kill a solve
        cb = self.options.get("progress_cb")
        if cb is not None:
            try:
                cb(abs_gap, rel_gap, self.BestOuterBound,
                   self.BestInnerBound, self.current_iteration())
            except Exception:
                pass
        return abs_gap, rel_gap

    def _check_preempt(self) -> bool:
        """Tenant preemption (doc/serving.md): the scheduler's
        ``options["preempt_check"]`` fires between iterations — at
        exactly the window boundaries checkpoint capture already owns —
        and a True verdict means PARK: the wheel tears down normally,
        the final checkpoint banks (W, xbars, rho, bounds), and the
        resumed run continues with bounds monotone by the
        ``seed_resume`` contract."""
        # getattr: unit tests build bare hubs via __new__ (no __init__)
        if not hasattr(self, "preempted"):
            self.preempted = False
        pc = self.options.get("preempt_check")
        if pc is not None and not self.preempted and pc():
            self.preempted = True
            _metrics.inc("service.preemptions")
            global_toc("Hub preempted: parking wheel at window boundary",
                       True)
            if _trace.enabled():
                _trace.instant("hub", "preempt",
                               iter=self.current_iteration(),
                               best_outer=self.BestOuterBound,
                               best_inner=self.BestInnerBound)
        return self.preempted

    def determine_termination(self) -> bool:
        opts = self.options
        if not any(k in opts for k in ("rel_gap", "abs_gap",
                                       "max_stalled_iters")):
            # no gap targets: preemption is the only possible verdict
            return self._check_preempt()
        abs_gap, rel_gap = self.compute_gaps()
        rel_ok = "rel_gap" in opts and rel_gap <= opts["rel_gap"]
        abs_ok = "abs_gap" in opts and abs_gap <= opts["abs_gap"]
        stalled = False
        if "max_stalled_iters" in opts:
            if abs_gap < self.last_gap:
                self.last_gap = abs_gap
                self.stalled_iter_cnt = 0
            else:
                self.stalled_iter_cnt += 1
                stalled = self.stalled_iter_cnt >= opts["max_stalled_iters"]
        if abs_ok:
            global_toc(f"Terminating: absolute gap {abs_gap:.4f}", True)
        if rel_ok:
            global_toc(f"Terminating: relative gap {rel_gap * 100:.3f}%", True)
        if stalled:
            global_toc(f"Terminating: stalled {self.stalled_iter_cnt} iters", True)
        if (abs_ok or rel_ok or stalled) and _trace.enabled():
            # the termination verdict WITH its evidence, on the timeline
            _trace.instant(
                "hub", "terminate",
                reason=("abs_gap" if abs_ok else
                        "rel_gap" if rel_ok else "stalled"),
                abs_gap=abs_gap, rel_gap=rel_gap,
                best_outer=self.BestOuterBound,
                best_inner=self.BestInnerBound,
                stalled_iters=self.stalled_iter_cnt)
        if abs_ok or rel_ok or stalled:
            # certification outranks preemption: a wheel whose gap just
            # closed must COMPLETE, not pay a park/resume cycle for a
            # quantum that expired in the same window
            return True
        return self._check_preempt()

    # ---- screen trace (hub.py:111-123) --------------------------------------
    def _update_string(self):
        ob = self.latest_ob_char or ' '
        ib = self.latest_ib_char or ' '
        return f"{ob} {ib}"

    def screen_trace(self):
        it = self.current_iteration()
        abs_gap, rel_gap = self.compute_gaps()
        if self.print_init:
            global_toc(
                f'{"Iter.":>5s}     {"Best Bound":>14s}  {"Best Incumbent":>14s}'
                f'  {"Rel. Gap":>12s}  {"Abs. Gap":>14s}', True)
            self.print_init = False
        global_toc(
            f"{it:5d} {self._update_string()} {self.BestOuterBound:14.4f}  "
            f"{self.BestInnerBound:14.4f}  {rel_gap * 100:12.3f}%  "
            f"{abs_gap:14.4f}", True)
        self.latest_ib_char = None
        self.latest_ob_char = None

    # ---- mailbox traffic (hub.py:370-436) -----------------------------------
    def hub_to_spoke(self, values, idx: int):
        self.fabric.to_spoke[idx].put(values)

    def hub_to_spoke_versioned(self, idx: int, token, build):
        """Put that SKIPS when the payload source (``token``) has not
        advanced since the last send to this spoke: redundant Puts bump
        write-ids and make spokes recompute on data they already acted on
        (acute in the hub linger loop, which polls sync() twice a
        second).  ``build`` is a zero-arg payload constructor, called only
        when a send actually happens.  Transports without versioned puts
        (the TCP window fabric) fall back to hub-side token tracking."""
        mb = self.fabric.to_spoke[idx]
        if hasattr(mb, "put_versioned"):
            mb.put_versioned(token, build)
            return
        sent = getattr(self, "_sent_tokens", None)
        if sent is None:
            sent = self._sent_tokens = {}
        if sent.get(idx) == token:
            return
        self.hub_to_spoke(build(), idx)
        sent[idx] = token

    def hub_from_spoke(self, idx: int):
        """Returns (payload, True) when the spoke's write-id is fresh."""
        data, wid = self.fabric.to_hub[idx].get()
        last = self.remote_write_ids.get(idx, 0)
        if wid > last or wid < 0:
            self.remote_write_ids[idx] = wid
            return data, True
        return data, False

    def receive_outerbounds(self):
        # lost spokes are still READ (a bound posted before death is
        # valid); loss only stops the hub waiting on them (linger/join)
        for idx in self.outerbound_spoke_indices:
            data, is_new = self.hub_from_spoke(idx)
            if is_new:
                self.latest_spoke_bounds[idx] = float(data[0])
                self.OuterBoundUpdate(float(data[0]), idx)

    def receive_innerbounds(self):
        for idx in self.innerbound_spoke_indices:
            data, is_new = self.hub_from_spoke(idx)
            if is_new:
                self.latest_spoke_bounds[idx] = float(data[0])
                self.InnerBoundUpdate(float(data[0]), idx)

    def OuterBoundUpdate(self, new_bound, idx=None, char='*'):
        if self._ob_better(new_bound, self.BestOuterBound):
            old = self.BestOuterBound
            self.latest_ob_char = (
                char if idx is None else self.outerbound_spoke_chars[idx]
            )
            self.BestOuterBound = new_bound
            _metrics.inc("hub.outer_bound_updates")
            if _trace.enabled():
                _trace.instant("hub", "outer_bound_update", old=old,
                               new=new_bound, spoke=idx, char=char)
                _trace.counter("hub", "best_outer", new_bound)
        return self.BestOuterBound

    def InnerBoundUpdate(self, new_bound, idx=None, char='*'):
        if self._ib_better(new_bound, self.BestInnerBound):
            old = self.BestInnerBound
            self.latest_ib_char = (
                char if idx is None else self.innerbound_spoke_chars[idx]
            )
            self.BestInnerBound = new_bound
            _metrics.inc("hub.inner_bound_updates")
            if _trace.enabled():
                _trace.instant("hub", "inner_bound_update", old=old,
                               new=new_bound, spoke=idx, char=char)
                _trace.counter("hub", "best_inner", new_bound)
        return self.BestInnerBound

    def send_terminate(self):
        self.fabric.send_terminate()

    def hub_finalize(self):
        if self.has_outerbound_spokes:
            self.receive_outerbounds()
        if self.has_innerbound_spokes:
            self.receive_innerbounds()
        self.print_init = True
        global_toc("Statistics at termination", True)
        self.screen_trace()

    def current_iteration(self):
        raise NotImplementedError


class PHHub(Hub):
    """PH-flavored hub (hub.py:453-598): sends W and nonants, receives bounds.

    Payload layouts (flat float64, mirroring the reference buffers):
      W spokes:       [W.ravel() (S*K), BestOuterBound, BestInnerBound]
      nonant spokes:  [xk.ravel() (S*K), BestOuterBound, BestInnerBound]
      bounds-only:    [BestOuterBound, BestInnerBound]
    """

    def setup_hub(self):
        self.initialize_spoke_indices()
        self.initialize_bound_values()
        if self.outerbound_spoke_indices & self.innerbound_spoke_indices:
            raise RuntimeError(
                "A spoke providing both inner and outer bounds is unsupported"
            )
        if self.w_spoke_indices & self.nonant_spoke_indices:
            raise RuntimeError(
                "A spoke needing both Ws and nonants is unsupported"
            )

    def sync(self):
        with _trace.span("hub", "sync"):
            if self.has_w_spokes:
                self.send_ws()
            if self.has_nonant_spokes:
                self.send_nonants()
            if self.has_bounds_only_spokes:
                self.send_boundsout()
            if self.has_outerbound_spokes:
                self.receive_outerbounds()
            if self.has_innerbound_spokes:
                self.receive_innerbounds()
        self._resilience_tick()

    sync_with_spokes = sync

    def is_converged(self):
        # first PAST-THE-BASE iteration: resumed runs offer the (re-derived)
        # trivial bound too — the update keeps whichever is better
        if self.opt._iter - getattr(self.opt, "_iter_base", 0) == 1:
            self.OuterBoundUpdate(self.opt.trivial_bound, char='T')
        # in-hub xhat extensions land their incumbents on the opt object
        bib = getattr(self.opt, "best_inner_bound", None)
        if bib is not None and np.isfinite(bib):
            self.InnerBoundUpdate(float(bib), char='X')
        self.screen_trace()
        if not self.has_innerbound_spokes and not np.isfinite(
                self.BestInnerBound):
            # a park request must still land: preemption is the ONE
            # termination that needs no bounds at all (gap termination
            # stays blocked — the stall counter must not advance while
            # no incumbent exists)
            return self._check_preempt()
        return self.determine_termination()

    def current_iteration(self):
        return self.opt._iter

    def main(self):
        self.opt.ph_main(finalize=False)
        self._linger()

    def _linger(self):
        """Keep syncing after the hub's own iterations finish, harvesting
        late spoke bounds until the gap certifies or ``linger_secs`` pass.

        The reference hub's iterations each take an external-MIP-solve long,
        so spokes get wall-time for free; our iterations are milliseconds,
        and a hub that exits immediately throws away whatever the spokes are
        mid-way through computing (acute for cross-process spokes that
        cold-start).  Lingering costs idle time only and can only improve
        the certified gap.
        """
        import time

        linger = float(self.options.get("linger_secs", 0.0))
        if linger <= 0.0 or not self.spokes:
            return
        # nudge cadence: the versioned puts skip identical state, so
        # without an advancing epoch the spokes would idle for the whole
        # linger window after their first non-improving round; a re-send
        # every ``linger_nudge_secs`` keeps their warm-started refinement
        # going at a fraction of the old every-poll Put traffic
        nudge = float(self.options.get("linger_nudge_secs", 2.0))
        t0 = time.time()
        last_trace = 0.0
        while time.time() - t0 < linger:
            if self.supervisor is not None and self.supervisor.all_lost():
                # nobody left to harvest from: idling out the linger
                # budget would only delay the (already best-known) result
                global_toc("Hub linger: all spokes lost — ending harvest",
                           True)
                break
            self._nudge_epoch = int((time.time() - t0) / max(nudge, 0.25))
            self.sync()
            # quiet convergence check (is_converged prints a trace row per
            # call — at poll frequency that floods the screen); trace at
            # most every 5s
            if time.time() - last_trace > 5.0:
                last_trace = time.time()
                if self.is_converged():
                    global_toc("Hub linger: gap certified", True)
                    break
            elif self.determine_termination():
                global_toc("Hub linger: gap certified", True)
                break
            time.sleep(0.5)

    def finalize(self):
        return self.opt.post_loops()

    def _state_token(self, kind):
        """Freshness token for outbound payloads: the opt's PH state
        version (bumped by solves / W updates, frozen during linger)
        plus the bounds that ride every payload, plus the linger NUDGE
        epoch — during the linger harvest a slow periodic re-send of the
        (unchanged) final state keeps spokes refining on it (their
        warm-started solves tighten bounds across re-runs), without the
        old 2x/sec redundant Puts during the hot loop."""
        return (kind, getattr(self.opt, "sync_version", None),
                getattr(self, "_nudge_epoch", 0),
                self.BestOuterBound, self.BestInnerBound)

    @staticmethod
    def _build_once(build):
        """Memoize a payload constructor for one send round: the payload
        is identical for every spoke of the round, and Mailbox.put copies
        it into each buffer — assemble it at most once even when several
        spokes accept the token."""
        box = []

        def cached():
            if not box:
                box.append(build())
            return box[0]

        return cached

    def send_ws(self):
        build = self._build_once(lambda: np.concatenate(
            [np.asarray(self.opt.W, dtype=np.float64).ravel(),
             [self.BestOuterBound, self.BestInnerBound]]))
        token = self._state_token("W")
        for idx in self.w_spoke_indices:
            self.hub_to_spoke_versioned(idx, token, build)

    def _nonant_payload(self):
        xk = (self.opt._nonants_cached()
              if hasattr(self.opt, "_nonants_cached")
              else self.opt.nonants_of(self.opt.local_x))
        return np.concatenate(
            [np.asarray(xk, dtype=np.float64).ravel(),
             [self.BestOuterBound, self.BestInnerBound]]
        )

    def send_nonants(self):
        token = self._state_token("nonants")
        build = self._build_once(self._nonant_payload)
        for idx in self.nonant_spoke_indices:
            self.hub_to_spoke_versioned(idx, token, build)

    def send_boundsout(self):
        token = self._state_token("bounds")
        build = self._build_once(
            lambda: np.array([self.BestOuterBound, self.BestInnerBound]))
        for idx in self.bounds_only_indices:
            self.hub_to_spoke_versioned(idx, token, build)


class CrossScenarioHub(PHHub):
    """PH hub that additionally feeds nonants to cross-scenario cut spokes
    and routes their cut payloads to the CrossScenarioExtension
    (cross_scen_hub.py:11-156)."""

    def setup_hub(self):
        super().setup_hub()
        from .cross_scen_spoke import CrossScenarioCutSpoke

        self.cs_spoke_indices = {
            i + 1 for i, sd in enumerate(self.spokes)
            if sd["spoke_class"] is CrossScenarioCutSpoke
        }

    def sync(self):
        super().sync()
        if not self.cs_spoke_indices:
            return
        token = self._state_token("cs-nonants")
        build = self._build_once(self._nonant_payload)
        S = self.opt.batch.num_scenarios
        K = self.opt.nonant_length
        ext = getattr(self.opt, "extobject", None)
        for idx in self.cs_spoke_indices:
            self.hub_to_spoke_versioned(idx, token, build)
            data, is_new = self.hub_from_spoke(idx)
            if is_new and ext is not None and hasattr(ext, "add_cuts"):
                ext.add_cuts(data.reshape(S, K + 1))

    sync_with_spokes = sync


class APHHub(PHHub):
    """APH-flavored hub (hub.py:691-771).  The reference's variant skips
    cylinder barriers in Put/Get; our mailboxes are barrier-free already, so
    only the driver differs."""

    def main(self):
        self.opt.APH_main(spcomm=self, finalize=False)

    def finalize(self):
        return self.opt.post_loops()


class LShapedHub(Hub):
    """L-shaped-flavored hub (hub.py:600-689): nonant-only sync, outer bound
    from the Benders root objective."""

    def setup_hub(self):
        self.initialize_spoke_indices()
        self.initialize_bound_values()
        if self.has_w_spokes:
            raise RuntimeError("LShaped hub does not compute dual weights (Ws)")
        if self.outerbound_spoke_indices & self.innerbound_spoke_indices:
            raise RuntimeError(
                "A spoke providing both inner and outer bounds is unsupported"
            )
        self._iter_count = 0

    def sync(self, send_nonants=True):
        self._iter_count += 1
        if send_nonants and self.has_nonant_spokes:
            self.send_nonants()
        if self.has_bounds_only_spokes:
            self.send_boundsout()
        if self.has_outerbound_spokes:
            self.receive_outerbounds()
        if self.has_innerbound_spokes:
            self.receive_innerbounds()
        self._resilience_tick()   # Benders roots have no W: capture skips

    def is_converged(self):
        # the Benders root objective is itself a valid outer bound
        ob = getattr(self.opt, "outer_bound", None)
        if ob is not None and np.isfinite(ob):
            self.OuterBoundUpdate(float(ob), char='B')
        ib = getattr(self.opt, "inner_bound", None)
        if ib is not None and np.isfinite(ib):
            self.InnerBoundUpdate(float(ib), char='B')
        self.screen_trace()
        return self.determine_termination()

    def current_iteration(self):
        return self._iter_count

    def main(self):
        self.opt.lshaped_algorithm()

    def send_nonants(self):
        """Broadcast the root x to nonant spokes (every scenario row gets the
        same candidate — it is already nonanticipative)."""
        x = self.opt.root_x
        if x is None:
            return
        S = self.opt.batch.num_scenarios
        xk = np.broadcast_to(np.asarray(x, dtype=np.float64),
                             (S, x.shape[0]))
        payload = np.concatenate(
            [xk.ravel(), [self.BestOuterBound, self.BestInnerBound]]
        )
        for idx in self.nonant_spoke_indices:
            self.hub_to_spoke(payload, idx)
