"""Spoke type lattice: typed bases for bound/W/nonant spokes.

TPU-native analogue of ``mpisppy/cylinders/spoke.py:18-376``.  A spoke runs an
opt object in its own cylinder (host thread here), puts its bound into its
hub-facing mailbox, polls the hub's outbound mailbox for W / nonant / bound
payloads with write-id freshness semantics, and exits on the kill sentinel
(write_id == -1, spoke.py:84-145).
"""

from __future__ import annotations

import enum
import math
import threading
import os
import time

import numpy as np

from ..resilience import faults as _faults
from ..resilience import supervisor as _supervisor
from .spcommunicator import KILL_ID, SPCommunicator


class ConvergerSpokeType(enum.Enum):
    OUTER_BOUND = 1
    INNER_BOUND = 2
    W_GETTER = 3
    NONANT_GETTER = 4


class Spoke(SPCommunicator):
    """Base spoke (spoke.py:24-145)."""

    def __init__(self, spbase_object, strata_rank, fabric, options=None):
        super().__init__(spbase_object, strata_rank, fabric, options)
        self.remote_write_id = 0
        self._recv_count = 0     # fresh hub payloads seen (fault-plan clock)
        # gauge hoisted out of the ~500 Hz poll loop (the registry
        # get-or-create costs a lock + dict probe per call)
        self._hb_gauge = _supervisor.heartbeat_gauge(
            f"spoke{self.strata_rank}")

    # lengths negotiated by WheelSpinner before mailbox construction
    def buffer_lengths(self) -> tuple[int, int]:
        """(spoke_to_hub_len, hub_to_spoke_len), excluding write-id slots."""
        raise NotImplementedError

    def spoke_to_hub(self, values):
        self.fabric.to_hub[self.strata_rank].put(values)

    def spoke_from_hub(self):
        """Snapshot the hub's outbound payload; True when fresh
        (spoke.py:84-118 with the all-ranks-agree vote collapsed: one host
        thread per cylinder reads one consistent snapshot)."""
        # liveness for the hub's supervisor: a spoke polling its mailbox
        # is alive even when it has nothing new to Put
        self._hb_gauge.set(time.monotonic())
        data, wid = self.fabric.to_spoke[self.strata_rank].get()
        self._locals = data
        if wid > self.remote_write_id or wid < 0:
            self.remote_write_id = wid
            if wid >= 0:
                self._recv_count += 1
                if _faults.active():   # deterministic dead-spoke injection
                    _faults.on_spoke_payload(self)
            return True
        return False

    def got_kill_signal(self) -> bool:
        self._new_locals = self.spoke_from_hub()
        if not self._new_locals:
            # nothing fresh: yield the core so the hub thread can progress
            # (the reference relies on MPI async progress for the same effect)
            time.sleep(0.002)
        return self.remote_write_id == KILL_ID

    def peek_kill_signal(self) -> bool:
        """Kill check that does NOT consume payload freshness — safe to call
        mid-computation without causing the next ``got_kill_signal`` to treat
        a payload posted meanwhile as stale."""
        return self.fabric.to_spoke[self.strata_rank].write_id == KILL_ID

    def get_serial_number(self) -> int:
        return self.remote_write_id

    def main(self):
        raise NotImplementedError


class _BoundSpoke(Spoke):
    """A spoke that reports a single bound (spoke.py:147-208), with optional
    CSV bound tracing via options["trace_prefix"]."""

    def __init__(self, spbase_object, strata_rank, fabric, options=None):
        super().__init__(spbase_object, strata_rank, fabric, options)
        self._bound = 0.0
        self._locals = np.zeros(2)
        self._new_locals = False
        trace_prefix = spbase_object.options.get("trace_prefix")
        if trace_prefix is not None:
            filen = trace_prefix + self.__class__.__name__ + ".csv"
            if os.path.exists(filen):
                raise RuntimeError(f"Spoke trace file {filen} already exists!")
            with open(filen, "w") as f:
                f.write("time,bound\n")
            self.trace_filen = filen
            self.start_time = time.perf_counter()
        else:
            self.trace_filen = None

    def buffer_lengths(self):
        return 1, 2  # bound out; hub outer/inner bounds in

    @property
    def bound(self):
        return self._bound

    @bound.setter
    def bound(self, value):
        self._append_trace(value)
        self._bound = float(value)
        self.spoke_to_hub(np.array([self._bound]))

    @property
    def hub_outer_bound(self):
        return self._locals[-2]

    @property
    def hub_inner_bound(self):
        return self._locals[-1]

    def _append_trace(self, value):
        if self.trace_filen is None:
            return
        with open(self.trace_filen, "a") as f:
            f.write(f"{time.perf_counter() - self.start_time},{value}\n")


class InnerBoundSpoke(_BoundSpoke):
    """Inner bound, no hub data needed (spoke.py:239-244)."""
    converger_spoke_types = (ConvergerSpokeType.INNER_BOUND,)
    converger_spoke_char = 'I'


class OuterBoundSpoke(_BoundSpoke):
    """Outer bound, no hub data needed (spoke.py:246-252)."""
    converger_spoke_types = (ConvergerSpokeType.OUTER_BOUND,)
    converger_spoke_char = 'O'


class _BoundNonantLenSpoke(_BoundSpoke):
    """A bound spoke whose inbound payload is nonant-length (spoke.py:210-237):
    (S*K) values + hub outer/inner bounds."""

    def buffer_lengths(self):
        S = self.opt.batch.num_scenarios
        K = self.opt.nonant_length
        return 1, S * K + 2


class _BoundWSpoke(_BoundNonantLenSpoke):
    """Gets the hub's W (spoke.py:254-270)."""

    @property
    def localWs(self) -> np.ndarray:
        """(S, K) view of the hub's dual weights."""
        S = self.opt.batch.num_scenarios
        K = self.opt.nonant_length
        return self._locals[:-2].reshape(S, K)

    @property
    def new_Ws(self) -> bool:
        return self._new_locals


class OuterBoundWSpoke(_BoundWSpoke):
    converger_spoke_types = (
        ConvergerSpokeType.OUTER_BOUND,
        ConvergerSpokeType.W_GETTER,
    )
    converger_spoke_char = 'O'


class _BoundNonantSpoke(_BoundNonantLenSpoke):
    """Gets the hub's nonants (spoke.py:288-304)."""

    @property
    def localnonants(self) -> np.ndarray:
        """(S, K) view of the hub's current nonant values."""
        S = self.opt.batch.num_scenarios
        K = self.opt.nonant_length
        return self._locals[:-2].reshape(S, K)

    @property
    def new_nonants(self) -> bool:
        return self._new_locals


class InnerBoundNonantSpoke(_BoundNonantSpoke):
    """Incumbent finder over hub nonants, with best-solution cache
    (spoke.py:306-363)."""

    converger_spoke_types = (
        ConvergerSpokeType.INNER_BOUND,
        ConvergerSpokeType.NONANT_GETTER,
    )
    converger_spoke_char = 'I'

    def __init__(self, spbase_object, strata_rank, fabric, options=None):
        super().__init__(spbase_object, strata_rank, fabric, options)
        self.is_minimizing = self.opt.is_minimizing
        self.best_inner_bound = math.inf if self.is_minimizing else -math.inf
        self.best_solution_cache = None   # (S, n) full solutions
        # (bound, cache) are written as a pair; teardown may read them from
        # another thread while a hung spoke is still mid-update
        self._best_lock = threading.Lock()

    def update_if_improving(self, candidate_inner_bound) -> bool:
        if candidate_inner_bound is None or not np.isfinite(
                candidate_inner_bound):
            return False
        better = (candidate_inner_bound < self.best_inner_bound
                  if self.is_minimizing
                  else candidate_inner_bound > self.best_inner_bound)
        if not better:
            return False
        with self._best_lock:
            self.best_inner_bound = float(candidate_inner_bound)
            self.bound = self.best_inner_bound
            self._cache_best_solution()
        return True

    def best_snapshot(self):
        """(bound, cache) read atomically w.r.t. update_if_improving —
        safe even while the spoke's main loop is still running."""
        with self._best_lock:
            return self.best_inner_bound, self.best_solution_cache

    def _cache_best_solution(self):
        if self.opt.local_x is not None:
            self.best_solution_cache = np.asarray(self.opt.local_x).copy()

    def finalize(self):
        if self.best_solution_cache is None:
            return None
        self.opt.local_x = self.best_solution_cache
        self.opt.first_stage_solution_available = True
        self.final_bound = self.bound
        return self.final_bound


class OuterBoundNonantSpoke(_BoundNonantSpoke):
    converger_spoke_types = (
        ConvergerSpokeType.OUTER_BOUND,
        ConvergerSpokeType.NONANT_GETTER,
    )
    converger_spoke_char = 'A'
