"""XhatShuffle inner-bound spoke: shuffled scenario cycling over hub nonants.

TPU-native analogue of ``mpisppy/cylinders/xhatshufflelooper_bounder.py:20-300``.
Each pass: take the hub's current nonant values, pick the next donor scenario
from a seeded shuffle (the reference's ``ScenarioCycler``, multistage-aware via
per-node donor completion), fix the nonant columns to the donated candidate,
solve the whole batch in one device program (``Xhat_Eval``), and push the
expected objective to the hub when it improves the incumbent.
"""

from __future__ import annotations

import numpy as np

from .spoke import InnerBoundNonantSpoke
from ..extensions.xhatbase import donor_cache


class ScenarioCycler:
    """Seeded shuffled cycle over donor scenario indices
    (xhatshufflelooper_bounder.py:158-300).

    ``reverse``: iterate the shuffle backwards (the reference's
    reverse-looper option).
    """

    def __init__(self, num_scenarios: int, seed: int = 0, reverse: bool = False):
        self._S = int(num_scenarios)
        self._rng = np.random.default_rng(seed)
        self._reverse = reverse
        self._order = []
        self._pos = 0

    def _reshuffle(self):
        self._order = list(self._rng.permutation(self._S))
        if self._reverse:
            self._order.reverse()
        self._pos = 0

    def get_next(self) -> int:
        if self._pos >= len(self._order):
            self._reshuffle()
        s = self._order[self._pos]
        self._pos += 1
        return int(s)


class XhatShuffleInnerBound(InnerBoundNonantSpoke):
    """'X' spoke (xhatshufflelooper_bounder.py:20-157)."""

    converger_spoke_char = 'X'

    def xhatbase_prep(self):
        """No iter0 solves needed — the opt object (Xhat_Eval) evaluates
        candidates directly (xhatshufflelooper_bounder.py:24-61)."""
        opts = self.opt.options
        lopts = opts.get("xhat_looper_options", {})
        self.cycler = ScenarioCycler(
            self.opt.batch.num_scenarios,
            seed=int(lopts.get("seed", 0)),
            reverse=bool(lopts.get("reverse", False)),
        )
        self.scen_limit = int(lopts.get("scen_limit", 3))
        # Donor-MILP mode: candidates come from an exact host MILP of the
        # donor scenario instead of the donor's row of the hub nonants.
        # This is the reference's donor semantics — its donors are solved
        # (MIP) scenario instances (xhatshufflelooper_bounder.py:139-141)
        # — where ours carry LP-relaxation values from the device solves,
        # which integer-snap poorly on families like UC whose relaxation
        # is fractional in exactly the nonant (commitment) coordinates.
        # Two-stage only (per-node donors would need per-node MILPs).
        self.donor_milp = bool(lopts.get("donor_milp", False)) and \
            self.opt.tree.num_stages == 2
        self.donor_milp_gap = float(lopts.get("donor_milp_gap", 1e-3))
        self.donor_milp_time = float(lopts.get("donor_milp_time", 30.0))
        self._milp_donor_cache: dict = {}
        self._milp_evaluated: set = set()

    def _donor_milp_candidate(self, donor):
        """(K,) nonant candidate from the donor scenario's exact MILP
        (cached: the plain-c scenario optimum is iteration-independent)."""
        if donor in self._milp_donor_cache:
            return self._milp_donor_cache[donor]
        from ..solvers import scipy_backend

        b = self.opt.batch
        res = scipy_backend.solve_lp(
            b.c[donor], b.A[donor], b.cl[donor], b.cu[donor],
            b.lb[donor], b.ub[donor], is_int=b.is_int,
            mip_rel_gap=self.donor_milp_gap,
            time_limit=self.donor_milp_time)
        cand = (np.asarray(res.x)[self.opt.tree.nonant_indices]
                if res.feasible else None)
        # cache misses only for DEFINITIVE outcomes: a time-limit hit with
        # no incumbent (status "1", x None) is transient host load, and the
        # donor deserves a retry on a later pass
        if cand is not None or res.status == "2":
            self._milp_donor_cache[donor] = cand
        return cand

    def _try_candidates(self, final=False):
        """Try up to scen_limit donors against the current hub nonants.

        Aborts early on the kill sentinel via ``peek_kill_signal`` so a
        nonant payload posted mid-evaluation keeps its freshness for the
        next main-loop poll — except on the finalize pass, where the
        sentinel is permanently set and all donors should be tried."""
        xk = self.localnonants
        for _ in range(self.scen_limit):
            donor = self.cycler.get_next()
            if self.donor_milp:
                if donor in self._milp_evaluated:
                    # donor-MILP candidates are iteration-independent: a
                    # re-evaluation can never improve the incumbent.  Once
                    # every donor has been tried, fall back to hub-nonant
                    # donors (those DO evolve with the hub iterates).
                    if (len(self._milp_evaluated)
                            >= self.opt.batch.num_scenarios):
                        self.donor_milp = False
                    continue
                cache = self._donor_milp_candidate(donor)
                if cache is None:       # infeasible donor (or retry later)
                    continue
                self._milp_evaluated.add(donor)
            else:
                cache = donor_cache(self.opt, xk, donor)
            obj = self.opt.evaluate(cache)
            self.update_if_improving(obj)
            if not final and self.peek_kill_signal():
                return

    def main(self):
        self.xhatbase_prep()
        self._seen = False
        while not self.got_kill_signal():
            if self.new_nonants:
                self._seen = True
                self._try_candidates()

    def finalize(self):
        """One final candidate pass with the last hub nonants (the
        reference's spokes also sweep once after the kill sentinel —
        without it a fast hub can outrun the spoke and terminate with a
        stale incumbent, which made short wheels timing-flaky)."""
        if getattr(self, "_seen", False):
            self._try_candidates(final=True)
        return super().finalize()
