"""Cylinder communication fabric: versioned mailboxes + SPCommunicator base.

TPU-native analogue of ``mpisppy/cylinders/spcommunicator.py:21-120``.  The
reference exchanges flat float64 vectors between cylinder process groups
through one-sided MPI RMA windows whose last slot is a monotone **write_id**;
readers accept a payload only when the id is fresh, and id ``-1`` is the kill
signal (hub.py:370-450, spoke.py:60-118).

Here cylinders are host *threads* of one process (each driving its own jitted
device programs; a single TPU mesh is time-sliced through the device queue),
so the RMA window becomes a lock-guarded in-memory :class:`Mailbox` with
identical write-id semantics.  The protocol — not the transport — is the
contract: the planned C++ shared-memory window service (for multi-process /
multi-host cylinders over DCN) implements this same class interface, which is
why reads return ``(data, write_id)`` pairs instead of sharing state.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from ..obs import metrics as _metrics
from ..obs import trace as _trace
from ..resilience import faults as _faults

KILL_ID = -1

# mailbox traffic counters (tpusppy.obs.metrics): puts vs versioned-put
# SKIPS are the observable of the linger-loop fix (redundant Puts used to
# re-trigger full spoke solve rounds); gets are the spokes' poll traffic
_CTR_PUTS = _metrics.counter("mailbox.puts")
_CTR_PUT_SKIPS = _metrics.counter("mailbox.put_skips")
_CTR_GETS = _metrics.counter("mailbox.gets")
_CTR_KILLS = _metrics.counter("mailbox.kills")


class Mailbox:
    """A versioned one-writer many-reader buffer (the RMA-window analogue).

    The payload is ``length`` float64 slots; a trailing write-id slot is kept
    internally (buf[-1]), exactly mirroring ``_make_window``'s +1 layout
    (spcommunicator.py:93-120).
    """

    def __init__(self, length: int, name: str = ""):
        self.name = name
        self.length = int(length)
        self._buf = np.zeros(self.length + 1)
        self._lock = threading.Lock()
        self._last_token = None

    def put(self, values) -> int:
        """Owner-side Put: write payload, bump write_id (spoke.py:60-82)."""
        values = np.asarray(values, dtype=np.float64)
        if values.shape != (self.length,):
            raise RuntimeError(
                f"Mailbox {self.name}: putting length {values.shape} into "
                f"buffer of length {self.length}"
            )
        with self._lock:
            if int(self._buf[-1]) == KILL_ID:
                # the kill sentinel is terminal: a late writer must not
                # resurrect the mailbox (readers treat -1 as final)
                return KILL_ID
            new_id = int(self._buf[-1]) + 1
            self._buf[:-1] = values
            self._buf[-1] = new_id
        _CTR_PUTS.inc(1)
        if _trace.enabled():
            _trace.instant("mailbox", "put", box=self.name, write_id=new_id)
        return new_id

    def put_versioned(self, token, values) -> int:
        """Owner-side Put that SKIPS when the writer's state snapshot
        (``token``, any ==-comparable value) has not advanced since the
        previous versioned put.

        Re-Putting unchanged state would bump the write-id and force
        every reader to re-digest a payload it has already acted on — the
        hub's linger loop polls ``sync()`` twice a second, and each
        redundant Put used to re-trigger a full spoke solve round on
        identical (W, bounds).  ``values`` may be a zero-arg callable so
        payload ASSEMBLY is skipped too.  Returns the write-id (unchanged
        on skip); the kill sentinel stays terminal exactly as in
        :meth:`put`.
        """
        with self._lock:
            if self._last_token is not None and token == self._last_token:
                _CTR_PUT_SKIPS.inc(1)
                if _trace.enabled():
                    _trace.instant("mailbox", "put_skip", box=self.name)
                return int(self._buf[-1])
        wid = self.put(values() if callable(values) else values)
        if wid != KILL_ID:
            self._last_token = token
        return wid

    def get(self) -> tuple[np.ndarray, int]:
        """Reader-side Get: snapshot (payload copy, write_id)."""
        _CTR_GETS.inc(1)
        with self._lock:
            data, wid = self._buf[:-1].copy(), int(self._buf[-1])
        if _faults.active():   # deterministic stale-write-id injection
            wid = _faults.on_mailbox_get(self.name, wid)
        return data, wid

    def kill(self):
        """Write the termination sentinel (write_id = -1, hub.py:438-450).

        Deviation from the reference (which Puts zero dummies): the last
        payload is preserved, so spokes that finalize with "the last hub data"
        (e.g. the Lagrangian's final-Ws pass, lagrangian_bounder.py:85-95)
        really do use the last data rather than zeros.
        """
        with self._lock:
            self._buf[-1] = KILL_ID
        _CTR_KILLS.inc(1)
        if _trace.enabled():
            _trace.instant("mailbox", "kill", box=self.name)

    @property
    def write_id(self) -> int:
        with self._lock:
            return int(self._buf[-1])


class WindowFabric:
    """The set of hub<->spoke mailboxes for one wheel (the star graph).

    For each spoke strata rank i (1-based, hub is 0): ``to_spoke[i]`` is the
    hub-owned outbound window, ``to_hub[i]`` the spoke-owned inbound one —
    matching the reference's per-spoke window pairs (hub.py:345-368,
    spoke.py:34-58).
    """

    def __init__(self):
        self.to_spoke: dict[int, Mailbox] = {}
        self.to_hub: dict[int, Mailbox] = {}

    def add_spoke(self, strata_rank: int, hub_to_spoke_len: int,
                  spoke_to_hub_len: int):
        self.to_spoke[strata_rank] = Mailbox(
            hub_to_spoke_len, f"hub->spoke{strata_rank}"
        )
        self.to_hub[strata_rank] = Mailbox(
            spoke_to_hub_len, f"spoke{strata_rank}->hub"
        )

    @property
    def n_spokes(self) -> int:
        return len(self.to_spoke)

    def send_terminate(self):
        for mb in self.to_spoke.values():
            mb.kill()


class SPCommunicator:
    """Base for hub/spoke communicators (spcommunicator.py:21-92).

    Owns the opt object (an SPBase derivative) and its strata position.
    Subclasses implement ``main``; ``sync``/``is_converged``/``finalize`` are
    optional hooks invoked by the opt object's iteration loop.
    """

    def __init__(self, spbase_object, strata_rank: int, fabric: WindowFabric,
                 options=None):
        self.opt = spbase_object
        self.strata_rank = int(strata_rank)
        self.fabric = fabric
        self.options = dict(options or {})
        self.inst_time = time.time()
        self.opt.spcomm = self

    @property
    def n_spokes(self) -> int:
        return self.fabric.n_spokes

    def main(self):
        raise NotImplementedError

    def sync(self):
        pass

    def is_converged(self):
        return False

    def finalize(self):
        """Optional final calculations after convergence."""
        pass

    def hub_finalize(self):
        pass
