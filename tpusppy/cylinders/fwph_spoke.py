"""FWPH outer-bound spoke.

TPU-native analogue of ``mpisppy/cylinders/fwph_spoke.py:5-33``: wraps an
:class:`~tpusppy.fwph.FWPH` opt object; the algorithm drives itself and the
spoke pushes ``opt._local_bound`` on each sync.
"""

from __future__ import annotations

import numpy as np

from .spoke import OuterBoundSpoke


class FrankWolfeOuterBound(OuterBoundSpoke):
    converger_spoke_char = 'F'

    def main(self):
        self.opt.fwph_main()

    def is_converged(self):
        return self.got_kill_signal()

    def sync(self):
        bound = getattr(self.opt, "_local_bound", None)
        if bound is not None and np.isfinite(bound):
            self.bound = bound

    def finalize(self):
        bound = getattr(self.opt, "_local_bound", None)
        if bound is None:
            return None
        self.bound = bound
        self.final_bound = bound
        return self.final_bound
