"""XhatSpecific inner-bound spoke: a fixed scenario-per-node candidate.

TPU-native analogue of ``mpisppy/cylinders/xhatspecific_bounder.py`` (102 LoC):
the user names one donor scenario per tree node
(``options["xhat_specific_options"]["xhat_scenario_dict"]``, mapping node name
to scenario name); every fresh hub payload is completed from those donors and
evaluated.
"""

from __future__ import annotations

from .spoke import InnerBoundNonantSpoke
from ..extensions.xhatbase import donor_cache


class XhatSpecificInnerBound(InnerBoundNonantSpoke):
    """'X' spoke (xhatspecific_bounder.py)."""

    converger_spoke_char = 'X'

    def xhatspecific_prep(self):
        xs_opts = self.opt.options.get("xhat_specific_options", {})
        sdict = xs_opts.get("xhat_scenario_dict")
        if sdict is None:
            raise RuntimeError(
                "XhatSpecific needs options['xhat_specific_options']"
                "['xhat_scenario_dict'] ({node_name: scenario_name})"
            )
        name_to_idx = {nm: i for i, nm in enumerate(self.opt.all_scenario_names)}
        self.donors = {
            node: name_to_idx[scen] if isinstance(scen, str) else int(scen)
            for node, scen in sdict.items()
        }

    def main(self):
        self.xhatspecific_prep()
        while not self.got_kill_signal():
            if self.new_nonants:
                cache = donor_cache(self.opt, self.localnonants, self.donors)
                obj = self.opt.evaluate(cache)
                self.update_if_improving(obj)
