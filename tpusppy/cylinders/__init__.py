"""Cylinder (hub/spoke) fabric — versioned mailboxes, hubs, spokes.

TPU-native analogue of ``mpisppy/cylinders/`` (SURVEY §1 L4).
"""

from .spcommunicator import KILL_ID, Mailbox, SPCommunicator, WindowFabric
from .spoke import (
    ConvergerSpokeType,
    InnerBoundNonantSpoke,
    InnerBoundSpoke,
    OuterBoundNonantSpoke,
    OuterBoundSpoke,
    OuterBoundWSpoke,
    Spoke,
)
from .cross_scen_spoke import CrossScenarioCutSpoke
from .fwph_spoke import FrankWolfeOuterBound
from .hub import APHHub, CrossScenarioHub, Hub, LShapedHub, PHHub
from .lagrangian_bounder import LagrangianOuterBound
from .lshaped_bounder import XhatLShapedInnerBound
from .lagranger_bounder import LagrangerOuterBound
from .slam_heuristic import SlamMaxHeuristic, SlamMinHeuristic
from .xhatlooper_bounder import XhatLooperInnerBound
from .xhatshufflelooper_bounder import ScenarioCycler, XhatShuffleInnerBound
from .xhatspecific_bounder import XhatSpecificInnerBound
from .xhatxbar_bounder import XhatXbarInnerBound
from .xhat_ef_restricted import XhatRestrictedEF

__all__ = [
    "KILL_ID", "Mailbox", "SPCommunicator", "WindowFabric",
    "ConvergerSpokeType", "Spoke", "InnerBoundSpoke", "OuterBoundSpoke",
    "OuterBoundWSpoke", "InnerBoundNonantSpoke", "OuterBoundNonantSpoke",
    "APHHub", "CrossScenarioCutSpoke", "CrossScenarioHub",
    "FrankWolfeOuterBound",
    "Hub", "LShapedHub", "PHHub", "LagrangianOuterBound",
    "LagrangerOuterBound",
    "SlamMaxHeuristic", "SlamMinHeuristic", "ScenarioCycler",
    "XhatLooperInnerBound", "XhatLShapedInnerBound",
    "XhatShuffleInnerBound", "XhatSpecificInnerBound",
    "XhatXbarInnerBound", "XhatRestrictedEF",
]
