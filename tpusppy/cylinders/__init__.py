"""Cylinder (hub/spoke) fabric — versioned mailboxes, hubs, spokes.

TPU-native analogue of ``mpisppy/cylinders/`` (SURVEY §1 L4).
"""

from .spcommunicator import KILL_ID, Mailbox, SPCommunicator, WindowFabric
from .spoke import (
    ConvergerSpokeType,
    InnerBoundNonantSpoke,
    InnerBoundSpoke,
    OuterBoundNonantSpoke,
    OuterBoundSpoke,
    OuterBoundWSpoke,
    Spoke,
)
from .hub import Hub, PHHub
from .lagrangian_bounder import LagrangianOuterBound
from .xhatshufflelooper_bounder import ScenarioCycler, XhatShuffleInnerBound

__all__ = [
    "KILL_ID", "Mailbox", "SPCommunicator", "WindowFabric",
    "ConvergerSpokeType", "Spoke", "InnerBoundSpoke", "OuterBoundSpoke",
    "OuterBoundWSpoke", "InnerBoundNonantSpoke", "OuterBoundNonantSpoke",
    "Hub", "PHHub", "LagrangianOuterBound", "ScenarioCycler",
    "XhatShuffleInnerBound",
]
