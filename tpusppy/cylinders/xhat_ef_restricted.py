"""Restricted-EF MILP polish spoke: consensus-guided incumbents.

Generic relax-and-fix over the hub's consensus (a RINS-flavored heuristic —
no counterpart module in the reference, which gets integral candidates for
free because its subproblems are solved by a MIP solver; this spoke is how
tpusppy's LP-relaxation device path recovers MIP-quality first stages):

1. integer nonant coordinates the hub's scenarios AGREE on are fixed
   (mean >= ``hi`` -> 1, mean <= ``lo`` -> 0);
2. the few contested coordinates stay binary, and a probability-
   renormalized extensive form over a small scenario subsample is solved on
   the host (HiGHS MILP) — with only dozens of free binaries this cracks in
   seconds;
3. the resulting first stage is evaluated on the FULL batch on device
   (``Xhat_Eval``) — a certified incumbent like any other xhat.

Two-stage families only (the restricted EF shares one nonant block; a
multistage restriction would need per-node blocks) — the spoke is silently
idle otherwise, and on continuous families (nothing to fix) it defers to
the cheaper xbar/looper spokes.
"""

from __future__ import annotations

import time

import numpy as np

from .spoke import InnerBoundNonantSpoke


class XhatRestrictedEF(InnerBoundNonantSpoke):
    """'E' spoke: host-MILP restricted EF on the hub's consensus."""

    converger_spoke_char = 'E'

    def xhat_prep(self):
        opts = self.opt.options.get("xhat_ef_options", {})
        self.every = max(1, int(opts.get("every", 4)))
        self.ksub = int(opts.get("ksub", 6))
        self.hi = float(opts.get("hi", 0.75))
        self.lo = float(opts.get("lo", 0.10))
        self.time_limit = float(opts.get("time_limit", 60.0))
        self.mip_rel_gap = float(opts.get("mip_rel_gap", 1e-4))
        b = self.opt.batch
        self.enabled = (
            self.opt.tree.num_stages == 2
            and bool(np.asarray(b.is_int).any())
            and getattr(b, "buckets", None) is None)
        self._iter = 0
        self._last_fix = None

    def _restricted_candidate(self, xk):
        """Solve the restricted subsample EF; returns a (K,) candidate or
        None (MILP failed / consensus unchanged since last call)."""
        import scipy.optimize as sopt
        import scipy.sparse as sp

        b = self.opt.batch
        nid = np.asarray(b.tree.nonant_indices)
        ints = np.asarray(b.is_int)[nid].astype(bool)
        probs = np.asarray(self.opt.probs)
        xbar = probs @ xk
        fix1 = ints & (xbar >= self.hi)
        fix0 = ints & (xbar <= self.lo)
        key = (fix1.tobytes(), fix0.tobytes())
        if key == self._last_fix:
            return None              # same restriction: nothing new to try
        self._last_fix = key
        S = b.num_scenarios
        K = nid.size
        other = np.setdiff1d(np.arange(b.num_vars), nid)
        no = other.size
        sub = np.unique(np.linspace(0, S - 1, min(self.ksub, S)).astype(int))
        w = probs[sub] / probs[sub].sum()
        k = sub.size
        NV = K + k * no
        c_ef = np.zeros(NV)
        blocks, cls, cus = [], [], []
        m = b.num_rows
        A_sh = getattr(b, "A_shared", None)
        for j, s in enumerate(sub):
            cs = np.asarray(b.c[s], float)
            c_ef[:K] += w[j] * cs[nid]
            c_ef[K + j * no: K + (j + 1) * no] = w[j] * cs[other]
            As = np.asarray(A_sh if A_sh is not None else b.A[s])
            Ar = sp.lil_matrix((m, NV))
            Ar[:, :K] = As[:, nid]
            Ar[:, K + j * no: K + (j + 1) * no] = As[:, other]
            blocks.append(Ar.tocsr())
            cls.append(np.asarray(b.cl[s]))
            cus.append(np.asarray(b.cu[s]))
        lb_u = np.where(fix1, 1.0, np.asarray(b.lb[0])[nid])
        ub_u = np.where(fix0, 0.0, np.asarray(b.ub[0])[nid])
        lb = np.concatenate(
            [lb_u] + [np.asarray(b.lb[s])[other] for s in sub])
        ub = np.concatenate(
            [ub_u] + [np.asarray(b.ub[s])[other] for s in sub])
        integ = np.zeros(NV)
        integ[:K] = np.asarray(b.is_int)[nid]
        res = sopt.milp(
            c=c_ef,
            constraints=sopt.LinearConstraint(
                sp.vstack(blocks), np.concatenate(cls), np.concatenate(cus)),
            bounds=sopt.Bounds(lb, ub), integrality=integ,
            options={"time_limit": self.time_limit,
                     "mip_rel_gap": self.mip_rel_gap})
        if res.x is None:
            return None
        cand = res.x[:K]
        return np.where(ints, np.round(cand), cand)

    def _polish_once(self):
        t0 = time.time()
        cand = self._restricted_candidate(self.localnonants)
        if cand is None:
            return
        obj = self.opt.evaluate(cand)
        if self.update_if_improving(obj):
            from .. import global_toc
            global_toc(
                f"XhatRestrictedEF incumbent {obj:.4e} "
                f"({time.time() - t0:.1f}s)",
                self.opt.options.get("verbose", False))

    def main(self):
        self.xhat_prep()
        self._seen = False
        while not self.got_kill_signal():
            if self.new_nonants and self.enabled:
                self._seen = True
                self._iter += 1
                if self._iter % self.every:
                    continue
                self._polish_once()

    def finalize(self):
        """Final restricted-EF polish with the last hub consensus (the
        reference's spokes also sweep once after the kill sentinel)."""
        if getattr(self, "_seen", False) and self.enabled:
            self._last_fix = None        # always re-try at the final state
            self._polish_once()
        return super().finalize()
