"""XhatLShaped inner-bound spoke: evaluate the Benders root x.

TPU-native analogue of ``mpisppy/cylinders/lshaped_bounder.py:15-74``: the
L-shaped hub's root solution is already a complete nonanticipative candidate,
so the spoke just fixes and evaluates it (one batched solve per fresh payload).
"""

from __future__ import annotations

from .spoke import InnerBoundNonantSpoke


class XhatLShapedInnerBound(InnerBoundNonantSpoke):
    converger_spoke_char = 'X'

    def main(self):
        while not self.got_kill_signal():
            if self.new_nonants:
                obj = self.opt.evaluate(self.localnonants)
                self.update_if_improving(obj)
