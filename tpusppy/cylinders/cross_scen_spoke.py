"""Cross-scenario cut spoke.

TPU-native analogue of ``mpisppy/cylinders/cross_scen_spoke.py:11`` (297 LoC).
The reference drives a Benders cut generator over all scenarios and ships
(eta coefficient, nonant coefficients, constant) rows back to the hub, which
distributes them into the scenario models (cross_scen_extension.py).

Here the cut generation IS one batched clamp solve: fixing the nonant columns
of every scenario to the hub's current values yields each scenario's total
value Q_s(x_s) and its exact subgradient (the clamp duals), i.e. one
optimality cut per scenario per pass:

    Q_s(x) >= Q_s(x_hat_s) + g_s . (x - x_hat_s)

Payload to the hub: S rows of [g_s (K), const_s] — consumed by
:class:`tpusppy.extensions.cross_scen_extension.CrossScenarioExtension`.
"""

from __future__ import annotations

import numpy as np

from .spoke import Spoke
from ..solvers import admm


def make_clamp_cuts(opt, xhat_sk: np.ndarray) -> np.ndarray:
    """(S, K+1) optimality-cut rows from one batched clamp solve at xhat.

    Cut semantics: row s bounds the SECOND-STAGE value function only,
    ``Q2_s(x) >= g_s.x + const_s``.  Uses the weak-duality construction
    (admm.dual_cut) with an exact-simplex host fallback where the batch
    duals leave a cut gap — shared by the cut spoke and the hub-side
    Benders refinement in CrossScenarioExtension.
    """
    b = opt.batch
    idx = opt.tree.nonant_indices
    q = np.array(b.c, copy=True)
    q[:, idx] = 0.0
    lb = np.array(b.lb, copy=True)
    ub = np.array(b.ub, copy=True)
    lb[:, idx] = xhat_sk
    ub[:, idx] = xhat_sk
    from ..spopt import batch_solve_dispatch, dispatch_A
    sol = batch_solve_dispatch(b, q, b.q2, b.cl, b.cu, lb, ub,
                               settings=opt.admm_settings)
    x = np.asarray(sol.x)
    Q = (np.einsum("sn,sn->s", q, x)
         + 0.5 * np.einsum("sn,sn->s", b.q2, x * x) + b.const)
    import jax.numpy as jnp

    from ..spopt import host_exact_clamp_cut

    dt = opt.admm_settings.jdtype()
    base, g_full = admm.dual_cut(
        jnp.asarray(q, dt), jnp.asarray(b.q2, dt),
        jnp.asarray(np.asarray(dispatch_A(b)), dt),
        jnp.asarray(b.cl, dt), jnp.asarray(b.cu, dt),
        jnp.asarray(lb, dt), jnp.asarray(ub, dt),
        sol.y, sol.x, jnp.asarray(b.nonant_mask()))
    consts = np.asarray(base, dtype=float) + b.const
    grads = np.asarray(g_full, dtype=float)[:, idx]
    tol = max(opt.options.get("feas_tol", 1e-3),
              10.0 * opt.admm_settings.eps_rel)
    pri = np.asarray(sol.pri_res)
    gap_w = Q - (consts + np.einsum("sk,sk->s", grads, xhat_sk))
    cut_tol = 1e-5 * (1.0 + np.abs(Q))
    ok = pri <= tol
    for s in np.flatnonzero((pri > tol) | (gap_w > cut_tol)):
        if np.any(b.q2[s] != 0.0):
            continue
        okay, _, cb, gs = host_exact_clamp_cut(b, q, s, lb, ub, idx)
        if okay:
            consts[s], grads[s] = cb, gs
            ok[s] = True
    rows = np.concatenate([grads, consts[:, None]], axis=1)
    rows[~ok] = np.nan                           # consumers drop NaN rows
    return rows


class CrossScenarioCutSpoke(Spoke):
    converger_spoke_char = 'C'

    def __init__(self, spbase_object, strata_rank, fabric, options=None):
        super().__init__(spbase_object, strata_rank, fabric, options)
        S = self.opt.batch.num_scenarios
        K = self.opt.nonant_length
        self._locals = np.zeros(S * K + 2)
        self._new_locals = False

    def buffer_lengths(self):
        S = self.opt.batch.num_scenarios
        K = self.opt.nonant_length
        # cuts out: S rows of (g, const); nonants + bounds in
        return S * (K + 1), S * K + 2

    @property
    def localnonants(self) -> np.ndarray:
        S = self.opt.batch.num_scenarios
        K = self.opt.nonant_length
        return self._locals[:-2].reshape(S, K)

    @property
    def new_nonants(self) -> bool:
        return self._new_locals

    def make_cuts(self, xhat_sk: np.ndarray) -> np.ndarray:
        """(S, K+1) cut rows from one batched clamp solve at the hub's x
        (see :func:`make_clamp_cuts`)."""
        return make_clamp_cuts(self.opt, xhat_sk)

    def main(self):
        while not self.got_kill_signal():
            if self.new_nonants:
                cuts = self.make_cuts(self.localnonants)
                self.spoke_to_hub(cuts.ravel())
