"""Cross-scenario cut spoke.

TPU-native analogue of ``mpisppy/cylinders/cross_scen_spoke.py:11`` (297 LoC).
The reference drives a Benders cut generator over all scenarios and ships
(eta coefficient, nonant coefficients, constant) rows back to the hub, which
distributes them into the scenario models (cross_scen_extension.py).

Here the cut generation IS one batched clamp solve: fixing the nonant columns
of every scenario to the hub's current values yields each scenario's total
value Q_s(x_s) and its exact subgradient (the clamp duals), i.e. one
optimality cut per scenario per pass:

    Q_s(x) >= Q_s(x_hat_s) + g_s . (x - x_hat_s)

Payload to the hub: S rows of [g_s (K), const_s] — consumed by
:class:`tpusppy.extensions.cross_scen_extension.CrossScenarioExtension`.
"""

from __future__ import annotations

import numpy as np

from .spoke import Spoke
from ..solvers import admm


class CrossScenarioCutSpoke(Spoke):
    converger_spoke_char = 'C'

    def __init__(self, spbase_object, strata_rank, fabric, options=None):
        super().__init__(spbase_object, strata_rank, fabric, options)
        S = self.opt.batch.num_scenarios
        K = self.opt.nonant_length
        self._locals = np.zeros(S * K + 2)
        self._new_locals = False

    def buffer_lengths(self):
        S = self.opt.batch.num_scenarios
        K = self.opt.nonant_length
        # cuts out: S rows of (g, const); nonants + bounds in
        return S * (K + 1), S * K + 2

    @property
    def localnonants(self) -> np.ndarray:
        S = self.opt.batch.num_scenarios
        K = self.opt.nonant_length
        return self._locals[:-2].reshape(S, K)

    @property
    def new_nonants(self) -> bool:
        return self._new_locals

    def make_cuts(self, xhat_sk: np.ndarray) -> np.ndarray:
        """(S, K+1) cut rows from one batched clamp solve at the hub's x."""
        opt = self.opt
        b = opt.batch
        idx = opt.tree.nonant_indices
        lb = np.array(b.lb, copy=True)
        ub = np.array(b.ub, copy=True)
        lb[:, idx] = xhat_sk
        ub[:, idx] = xhat_sk
        sol = admm.solve_batch(b.c, b.q2, b.A, b.cl, b.cu, lb, ub,
                               settings=opt.admm_settings)
        x = np.asarray(sol.x)
        Q = b.objective(x)
        grads = -np.asarray(sol.yx)[:, idx]      # dQ/dxhat (Benders trick)
        consts = Q - np.einsum("sk,sk->s", grads, xhat_sk)
        # suppress cuts from solves that did not certify feasibility
        tol = max(opt.options.get("feas_tol", 1e-3),
                  10.0 * opt.admm_settings.eps_rel)
        ok = np.asarray(sol.pri_res) <= tol
        rows = np.concatenate([grads, consts[:, None]], axis=1)
        rows[~ok] = np.nan                       # hub side drops NaN rows
        return rows

    def main(self):
        while not self.got_kill_signal():
            if self.new_nonants:
                cuts = self.make_cuts(self.localnonants)
                self.spoke_to_hub(cuts.ravel())
