"""XhatLooper inner-bound spoke: in-order scenario cycling.

TPU-native analogue of ``mpisppy/cylinders/xhatlooper_bounder.py:12-77``:
like XhatShuffle but tries donor scenarios in their natural order, up to
``xhat_looper_options["scen_limit"]`` per fresh hub payload.
"""

from __future__ import annotations

from .spoke import InnerBoundNonantSpoke
from ..extensions.xhatbase import donor_cache


class XhatLooperInnerBound(InnerBoundNonantSpoke):
    """'X' spoke (xhatlooper_bounder.py:12-77)."""

    converger_spoke_char = 'X'

    def xhatlooper_prep(self):
        opts = self.opt.options.get("xhat_looper_options", {})
        self.scen_limit = int(opts.get("scen_limit", 3))
        self._next = 0

    def main(self):
        self.xhatlooper_prep()
        S = self.opt.batch.num_scenarios
        while not self.got_kill_signal():
            if self.new_nonants:
                xk = self.localnonants
                for _ in range(self.scen_limit):
                    donor = self._next % S
                    self._next += 1
                    cache = donor_cache(self.opt, xk, donor)
                    obj = self.opt.evaluate(cache)
                    self.update_if_improving(obj)
                    if self.peek_kill_signal():
                        return
