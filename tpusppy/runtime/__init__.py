"""Native runtime components (C++): shared-memory window service."""

from .window_service import ShmMailbox, ShmWindowFabric, load_library

__all__ = ["ShmMailbox", "ShmWindowFabric", "load_library"]
