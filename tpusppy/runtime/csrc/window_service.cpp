// Shared-memory window service: versioned mailboxes for cross-process
// cylinder exchange.
//
// TPU-native replacement for the reference's one-sided MPI RMA windows
// (mpisppy/cylinders/spcommunicator.py:93-120 and the Lock/Put/Get/Unlock +
// write-id protocol in hub.py:370-450 / spoke.py:60-118).  Each mailbox is a
// fixed-length double payload plus an atomic write-id; writers use a seqlock
// (sequence odd while writing) so readers never block a writer and always
// obtain a consistent (payload, write_id) snapshot -- the moral equivalent of
// MPI.Win.Lock/Unlock without requiring progress threads
// (cf. the reference's MPICH_ASYNC_PROGRESS caveat, README.rst).
//
// Layout of the POSIX shm segment:
//   Header  { magic, n_boxes }
//   BoxDesc { offset, length } * n_boxes
//   per box: { atomic<int64> write_id; atomic<uint64> seq; double[length] }
//
// The kill sentinel is write_id == -1, terminal as in the Python Mailbox.

#include <atomic>
#include <cstdint>
#include <cstring>
#include <new>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <time.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x7451u;
constexpr int64_t kKillId = -1;

struct Header {
  uint64_t magic;
  uint64_t n_boxes;
};

struct BoxDesc {
  uint64_t offset;  // bytes from segment start
  uint64_t length;  // payload doubles
};

struct BoxHead {
  std::atomic<int64_t> write_id;
  std::atomic<uint64_t> seq;
};

struct Handle {
  void* base;
  size_t size;
  int fd;
  bool owner;
  char name[256];
};

inline BoxDesc* descs(void* base) {
  return reinterpret_cast<BoxDesc*>(static_cast<char*>(base) +
                                    sizeof(Header));
}

inline BoxHead* box_head(void* base, uint64_t off) {
  return reinterpret_cast<BoxHead*>(static_cast<char*>(base) + off);
}

inline double* box_payload(void* base, uint64_t off) {
  return reinterpret_cast<double*>(static_cast<char*>(base) + off +
                                   sizeof(BoxHead));
}

size_t segment_size(int n_boxes, const int64_t* lengths) {
  size_t sz = sizeof(Header) + n_boxes * sizeof(BoxDesc);
  for (int i = 0; i < n_boxes; ++i) {
    sz = (sz + 63) & ~size_t(63);  // cacheline-align each box
    sz += sizeof(BoxHead) + lengths[i] * sizeof(double);
  }
  return sz;
}

}  // namespace

extern "C" {

// Create a named segment with n_boxes mailboxes of the given payload lengths.
// Returns an opaque handle or nullptr.
void* ws_create(const char* name, int n_boxes, const int64_t* lengths) {
  shm_unlink(name);  // stale segment from a crashed run
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  size_t size = segment_size(n_boxes, lengths);
  if (ftruncate(fd, static_cast<off_t>(size)) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* base =
      mmap(nullptr, size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  std::memset(base, 0, size);
  auto* hdr = static_cast<Header*>(base);
  hdr->n_boxes = static_cast<uint64_t>(n_boxes);
  size_t off = sizeof(Header) + n_boxes * sizeof(BoxDesc);
  for (int i = 0; i < n_boxes; ++i) {
    off = (off + 63) & ~size_t(63);
    descs(base)[i].offset = off;
    descs(base)[i].length = static_cast<uint64_t>(lengths[i]);
    new (box_head(base, off)) BoxHead{};
    off += sizeof(BoxHead) + lengths[i] * sizeof(double);
  }
  hdr->magic = kMagic;  // publish last
  auto* h = new Handle{base, size, fd, true, {0}};
  std::strncpy(h->name, name, sizeof(h->name) - 1);
  return h;
}

// Attach to an existing segment (spoke processes).
void* ws_attach(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return nullptr;
  }
  void* base = mmap(nullptr, static_cast<size_t>(st.st_size),
                    PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    return nullptr;
  }
  if (static_cast<Header*>(base)->magic != kMagic) {
    munmap(base, static_cast<size_t>(st.st_size));
    close(fd);
    return nullptr;
  }
  auto* h = new Handle{base, static_cast<size_t>(st.st_size), fd, false, {0}};
  std::strncpy(h->name, name, sizeof(h->name) - 1);
  return h;
}

int64_t ws_num_boxes(void* handle) {
  auto* h = static_cast<Handle*>(handle);
  return static_cast<int64_t>(static_cast<Header*>(h->base)->n_boxes);
}

int64_t ws_length(void* handle, int box) {
  auto* h = static_cast<Handle*>(handle);
  return static_cast<int64_t>(descs(h->base)[box].length);
}

// Owner-side Put: seqlock write, bump write_id.  Returns the new id, the
// kill sentinel if the box was killed, or -2 on a length mismatch.
int64_t ws_put(void* handle, int box, const double* values, int64_t n) {
  auto* h = static_cast<Handle*>(handle);
  BoxDesc d = descs(h->base)[box];
  if (n != static_cast<int64_t>(d.length)) return -2;
  BoxHead* bh = box_head(h->base, d.offset);
  int64_t id = bh->write_id.load(std::memory_order_acquire);
  if (id == kKillId) return kKillId;  // terminal (Mailbox.put parity)
  uint64_t s = bh->seq.load(std::memory_order_relaxed);
  bh->seq.store(s + 1, std::memory_order_relaxed);  // odd: write in progress
  // Standard seqlock write idiom: the fence orders the odd-seq store before
  // the payload writes on every architecture (a release store alone does not
  // keep *subsequent* writes after it).
  std::atomic_thread_fence(std::memory_order_seq_cst);
  std::memcpy(box_payload(h->base, d.offset), values, n * sizeof(double));
  bh->write_id.store(id + 1, std::memory_order_relaxed);
  std::atomic_thread_fence(std::memory_order_release);
  bh->seq.store(s + 2, std::memory_order_relaxed);  // even: stable
  return id + 1;
}

// Reader-side Get: consistent snapshot; returns the write_id, or -3 if the
// sequence never stabilized within timeout_us microseconds (writer died or
// stalled mid-put; timeout_us <= 0 means wait forever, with backoff).
int64_t ws_get(void* handle, int box, double* out, int64_t n,
               int64_t timeout_us) {
  auto* h = static_cast<Handle*>(handle);
  BoxDesc d = descs(h->base)[box];
  if (n != static_cast<int64_t>(d.length)) return -2;
  BoxHead* bh = box_head(h->base, d.offset);
  // A put is a memcpy of at most a few MB: microseconds.  Spin briefly, then
  // back off with nanosleep so a writer that crashed mid-put (seq left odd
  // forever) cannot wedge readers in a 100%-CPU loop.
  constexpr int64_t kSpins = 1 << 14;
  for (int64_t attempt = 0;; ++attempt) {
    if (attempt >= kSpins) {
      if (timeout_us > 0 && (attempt - kSpins) * 100 >= timeout_us) return -3;
      struct timespec ts = {0, 100000};  // 100us
      nanosleep(&ts, nullptr);
    }
    uint64_t s0 = bh->seq.load(std::memory_order_acquire);
    if (s0 & 1u) continue;  // writer mid-flight
    int64_t id = bh->write_id.load(std::memory_order_relaxed);
    std::memcpy(out, box_payload(h->base, d.offset), n * sizeof(double));
    // Fence before re-reading seq: orders the payload reads before the
    // validation load (the mirror of the writer-side fences).
    std::atomic_thread_fence(std::memory_order_acquire);
    uint64_t s1 = bh->seq.load(std::memory_order_relaxed);
    if (s0 == s1) return id;
  }
}

int64_t ws_write_id(void* handle, int box) {
  auto* h = static_cast<Handle*>(handle);
  BoxDesc d = descs(h->base)[box];
  return box_head(h->base, d.offset)
      ->write_id.load(std::memory_order_acquire);
}

// Kill sentinel: payload preserved (see the Python Mailbox.kill docstring).
void ws_kill(void* handle, int box) {
  auto* h = static_cast<Handle*>(handle);
  BoxDesc d = descs(h->base)[box];
  box_head(h->base, d.offset)
      ->write_id.store(kKillId, std::memory_order_release);
}

void ws_close(void* handle) {
  auto* h = static_cast<Handle*>(handle);
  munmap(h->base, h->size);
  close(h->fd);
  if (h->owner) shm_unlink(h->name);
  delete h;
}

}  // extern "C"
