// TCP window service: versioned mailboxes for cross-HOST cylinder exchange.
//
// The multi-host sibling of the shared-memory seqlock service
// (window_service.cpp).  The reference's wheel spans 256 nodes / 4000 ranks
// over one-sided MPI RMA (mpisppy/spin_the_wheel.py:219-237,
// cylinders/spcommunicator.py:93-120); here the hub process runs a tiny
// in-memory box server and every spoke — on this host or another — speaks a
// fixed-frame binary protocol over TCP.  Semantics are IDENTICAL to the shm
// service and to the in-process Mailbox: monotone write_id per box, kill
// sentinel write_id == -1 (terminal), length-checked puts/gets, consistent
// snapshots (mutex per box here; seqlock in shm).
//
// Protocol (little-endian, one request in flight per connection):
//   hello    { u64 magic; u64 secret; } -> { i64 0 } ack, or closed on
//            mismatch (shared-secret handshake; the hub hands the secret to
//            its spokes out-of-band, e.g. on the spawn command line)
//   request  { u8 op; u8 pad[3]; i32 box; i64 n; }   [+ n doubles for PUT]
//   reply    { i64 id; }                              [+ n doubles for GET]
//   ops: 1=PUT 2=GET 3=WRITE_ID 4=KILL 5=INFO
//   INFO reply: id = n_boxes, followed by n_boxes i64 lengths.
//   id == -2 signals a length mismatch (no payload follows).
// Requests with n above the largest configured box length close the
// connection (no attacker-sized scratch allocations).  The server binds
// 127.0.0.1 unless an explicit bind address is supplied.
//
// C ABI mirrors ws_*: tws_serve / tws_connect / tws_put / tws_get /
// tws_write_id / tws_kill / tws_port / tws_num_boxes / tws_length /
// tws_close.  A server handle also serves LOCAL (in-process) operations for
// the hub side — same mutexes, no sockets.

#include <arpa/inet.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <sys/time.h>

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <memory>
#include <mutex>
#include <thread>
#include <vector>

namespace {

constexpr int64_t kKillId = -1;
constexpr int64_t kLenErr = -2;
constexpr int64_t kIoErr = -4;
constexpr int64_t kTimeoutErr = -5;
constexpr uint64_t kMagic = 0x7470757370707931ULL;  // "tpusppy1"

// Every socket is close-on-exec: an elastic re-mesh replaces the process
// image with execve (tpusppy/parallel/elastic.py), and a leaked listen fd
// would keep the port bound forever — the re-exec'd process could never
// re-serve its liveness/fabric endpoint.
void set_cloexec(int fd) { fcntl(fd, F_SETFD, FD_CLOEXEC); }

struct Request {
  uint8_t op;
  uint8_t pad[3];
  int32_t box;
  int64_t n;
};

struct Hello {
  uint64_t magic;
  uint64_t secret;
};

struct Box {
  std::mutex mu;
  int64_t write_id = 0;
  std::vector<double> payload;
};

struct Conn {
  std::thread th;
  int fd = -1;
  std::atomic<bool> done{false};
};

struct Server {
  int listen_fd = -1;
  uint16_t port = 0;
  std::atomic<bool> stop{false};
  std::thread accept_thread;
  std::mutex conn_mu;
  // finished connections (rejected handshakes, disconnected spokes) are
  // reaped on the next accept, so hostile probing cannot grow this
  std::vector<std::unique_ptr<Conn>> conns;
  std::vector<Box> boxes;
  uint64_t secret = 0;
  int64_t max_len = 0;  // largest configured box; caps request n
};

struct Handle {
  Server* server = nullptr;  // set for the hub-side handle
  int sock = -1;             // set for client handles
  std::mutex io_mu;          // one request in flight per client
  int64_t op_timeout_ms = 0;  // 0 = block forever (legacy behavior)
};

bool read_full(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = recv(fd, p, n, 0);
    if (r <= 0) {
      if (r < 0 && (errno == EINTR)) continue;
      return false;
    }
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_full(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t r = send(fd, p, n, MSG_NOSIGNAL);
    if (r <= 0) {
      if (r < 0 && errno == EINTR) continue;
      return false;
    }
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

int64_t local_put(Box& b, const double* values, int64_t n) {
  std::lock_guard<std::mutex> lock(b.mu);
  if (n != static_cast<int64_t>(b.payload.size())) return kLenErr;
  if (b.write_id == kKillId) return kKillId;  // terminal, as in shm/Mailbox
  std::memcpy(b.payload.data(), values, n * sizeof(double));
  return ++b.write_id;
}

int64_t local_get(Box& b, double* out, int64_t n) {
  std::lock_guard<std::mutex> lock(b.mu);
  if (n != static_cast<int64_t>(b.payload.size())) return kLenErr;
  std::memcpy(out, b.payload.data(), n * sizeof(double));
  return b.write_id;
}

void serve_connection(Server* s, Conn* conn) {
  const int fd = conn->fd;
  struct MarkDone {
    Conn* c;
    ~MarkDone() { c->done.store(true, std::memory_order_release); }
  } mark{conn};
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  // shared-secret handshake before any request is honored; the hello read
  // is time-bounded so a half-open probe cannot pin this thread (and its
  // Conn slot) forever — after the timeout the reap loop frees it
  timeval tv{10, 0};
  setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  Hello hello{};
  if (!read_full(fd, &hello, sizeof(hello)) || hello.magic != kMagic ||
      hello.secret != s->secret) {
    close(fd);
    return;
  }
  int64_t ack = 0;
  if (!write_full(fd, &ack, sizeof(ack))) { close(fd); return; }
  timeval off{0, 0};  // authenticated: back to blocking reads
  setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &off, sizeof(off));
  std::vector<double> scratch;
  Request req;
  while (!s->stop.load(std::memory_order_relaxed)) {
    if (!read_full(fd, &req, sizeof(req))) break;
    const bool box_ok =
        req.box >= 0 && req.box < static_cast<int32_t>(s->boxes.size());
    int64_t id = kLenErr;
    switch (req.op) {
      case 1: {  // PUT: payload follows regardless; must be drained
        if (req.n < 0 || req.n > s->max_len) { close(fd); return; }
        scratch.resize(static_cast<size_t>(req.n));
        if (!read_full(fd, scratch.data(), req.n * sizeof(double))) {
          close(fd);
          return;
        }
        if (box_ok) id = local_put(s->boxes[req.box], scratch.data(), req.n);
        if (!write_full(fd, &id, sizeof(id))) { close(fd); return; }
        break;
      }
      case 2: {  // GET
        if (req.n < 0 || req.n > s->max_len) { close(fd); return; }
        scratch.resize(box_ok ? static_cast<size_t>(req.n) : 0);
        if (box_ok) id = local_get(s->boxes[req.box], scratch.data(), req.n);
        if (!write_full(fd, &id, sizeof(id))) { close(fd); return; }
        if (id != kLenErr &&
            !write_full(fd, scratch.data(), req.n * sizeof(double))) {
          close(fd);
          return;
        }
        break;
      }
      case 3: {  // WRITE_ID
        if (box_ok) {
          std::lock_guard<std::mutex> lock(s->boxes[req.box].mu);
          id = s->boxes[req.box].write_id;
        }
        if (!write_full(fd, &id, sizeof(id))) { close(fd); return; }
        break;
      }
      case 4: {  // KILL
        if (box_ok) {
          std::lock_guard<std::mutex> lock(s->boxes[req.box].mu);
          s->boxes[req.box].write_id = kKillId;
          id = kKillId;
        }
        if (!write_full(fd, &id, sizeof(id))) { close(fd); return; }
        break;
      }
      case 5: {  // INFO
        id = static_cast<int64_t>(s->boxes.size());
        if (!write_full(fd, &id, sizeof(id))) { close(fd); return; }
        std::vector<int64_t> lens(s->boxes.size());
        for (size_t i = 0; i < s->boxes.size(); ++i)
          lens[i] = static_cast<int64_t>(s->boxes[i].payload.size());
        if (!write_full(fd, lens.data(), lens.size() * sizeof(int64_t))) {
          close(fd);
          return;
        }
        break;
      }
      default:
        close(fd);
        return;
    }
  }
  close(fd);
}

void accept_loop(Server* s) {
  while (!s->stop.load(std::memory_order_relaxed)) {
    sockaddr_in peer;
    socklen_t plen = sizeof(peer);
    int fd = accept(s->listen_fd, reinterpret_cast<sockaddr*>(&peer), &plen);
    if (fd < 0) {
      if (s->stop.load(std::memory_order_relaxed)) return;
      if (errno == EINTR) continue;
      return;  // listener closed
    }
    set_cloexec(fd);
    std::lock_guard<std::mutex> lock(s->conn_mu);
    // reap finished connections before tracking the new one
    for (auto it = s->conns.begin(); it != s->conns.end();) {
      if ((*it)->done.load(std::memory_order_acquire)) {
        if ((*it)->th.joinable()) (*it)->th.join();
        it = s->conns.erase(it);
      } else {
        ++it;
      }
    }
    auto conn = std::make_unique<Conn>();
    conn->fd = fd;
    conn->th = std::thread(serve_connection, s, conn.get());
    s->conns.push_back(std::move(conn));
  }
}

}  // namespace

extern "C" {

// Start a box server on `port` (0 = kernel-assigned; read back via
// tws_port).  Binds `bind_addr` — 127.0.0.1 when null/empty; pass
// "0.0.0.0" EXPLICITLY to accept spokes from other hosts (the handshake
// secret still gates every connection).
void* tws_serve(const char* bind_addr, int port, int n_boxes,
                const int64_t* lengths, uint64_t secret) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  set_cloexec(fd);
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  const char* baddr =
      (bind_addr && bind_addr[0]) ? bind_addr : "127.0.0.1";
  if (inet_pton(AF_INET, baddr, &addr.sin_addr) != 1) {
    close(fd);
    return nullptr;
  }
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      listen(fd, 64) != 0) {
    close(fd);
    return nullptr;
  }
  socklen_t alen = sizeof(addr);
  getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &alen);

  auto* s = new Server();
  s->listen_fd = fd;
  s->port = ntohs(addr.sin_port);
  s->secret = secret;
  s->boxes = std::vector<Box>(static_cast<size_t>(n_boxes));
  for (int i = 0; i < n_boxes; ++i) {
    s->boxes[i].payload.assign(static_cast<size_t>(lengths[i]), 0.0);
    if (lengths[i] > s->max_len) s->max_len = lengths[i];
  }
  s->accept_thread = std::thread(accept_loop, s);
  auto* h = new Handle();
  h->server = s;
  return h;
}

// Connect to a server, retrying for up to timeout_ms (spokes may start
// before the hub finishes binding).  Sends the shared-secret hello and
// waits for the ack; a secret mismatch fails immediately (server closes).
void* tws_connect(const char* host, int port, int64_t timeout_ms,
                  uint64_t secret) {
  addrinfo hints{};
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  char portstr[16];
  std::snprintf(portstr, sizeof(portstr), "%d", port);
  for (int64_t waited = 0;;) {
    addrinfo* res = nullptr;
    if (getaddrinfo(host, portstr, &hints, &res) == 0 && res != nullptr) {
      int fd = socket(res->ai_family, res->ai_socktype, res->ai_protocol);
      if (fd >= 0) set_cloexec(fd);
      if (fd >= 0 &&
          connect(fd, res->ai_addr, static_cast<socklen_t>(res->ai_addrlen))
              == 0) {
        freeaddrinfo(res);
        int one = 1;
        setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
        // the handshake itself is bounded by the remaining budget (a
        // non-tpusppy listener would otherwise hang the ack read forever)
        int64_t left = timeout_ms - waited;
        if (left < 1000) left = 1000;
        timeval tv{static_cast<time_t>(left / 1000),
                   static_cast<suseconds_t>((left % 1000) * 1000)};
        setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
        setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
        Hello hello{kMagic, secret};
        int64_t ack = -1;
        if (!write_full(fd, &hello, sizeof(hello)) ||
            !read_full(fd, &ack, sizeof(ack)) || ack != 0) {
          close(fd);
          return nullptr;  // bad secret / not our service; don't retry
        }
        timeval off{0, 0};  // back to blocking for normal operation
        setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &off, sizeof(off));
        setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &off, sizeof(off));
        auto* h = new Handle();
        h->sock = fd;
        return h;
      }
      if (fd >= 0) close(fd);
      freeaddrinfo(res);
    }
    if (waited >= timeout_ms) return nullptr;
    usleep(100000);  // 100 ms
    waited += 100;
  }
}

int tws_port(void* handle) {
  auto* h = static_cast<Handle*>(handle);
  return h->server ? h->server->port : -1;
}

// Per-op deadline for CLIENT handles (ms; 0 restores blocking forever).
// After the deadline an op returns kTimeoutErr and the connection is
// closed (frame desync) — the caller must reconnect.  Server handles are
// local mutexed memory: the deadline is meaningless there (no-op).
int tws_set_op_timeout(void* handle, int64_t timeout_ms) {
  auto* h = static_cast<Handle*>(handle);
  if (h->server) return 0;
  h->op_timeout_ms = timeout_ms < 0 ? 0 : timeout_ms;
  if (h->sock < 0) return -1;
  timeval tv{static_cast<time_t>(h->op_timeout_ms / 1000),
             static_cast<suseconds_t>((h->op_timeout_ms % 1000) * 1000)};
  setsockopt(h->sock, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  setsockopt(h->sock, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
  return 0;
}

// One client op failed mid-frame: the connection is out of sync (a late
// reply to the timed-out request would be parsed as the NEXT op's reply),
// so it is closed and invalidated here, never reused.  EAGAIN/EWOULDBLOCK
// means the op deadline (tws_set_op_timeout) expired on a connected-but-
// unresponsive server — the wedged-server case a plain IO error can't
// name — and is reported distinctly as kTimeoutErr.
static int64_t client_fail(Handle* h) {
  // gate the timeout classification on an ARMED deadline: an orderly
  // server close (recv()==0) leaves errno untouched, so a stale EAGAIN
  // from unrelated earlier I/O must not masquerade as "op timed out"
  const bool timed_out = h->op_timeout_ms > 0 &&
                         (errno == EAGAIN || errno == EWOULDBLOCK);
  close(h->sock);
  h->sock = -1;
  return timed_out ? kTimeoutErr : kIoErr;
}

static int64_t request_reply(Handle* h, uint8_t op, int box, int64_t n,
                             const double* in, double* out) {
  std::lock_guard<std::mutex> lock(h->io_mu);
  if (h->sock < 0) return kIoErr;
  Request req{};
  req.op = op;
  req.box = box;
  req.n = n;
  if (!write_full(h->sock, &req, sizeof(req))) return client_fail(h);
  if (op == 1 && n > 0 &&
      !write_full(h->sock, in, n * sizeof(double)))
    return client_fail(h);
  int64_t id;
  if (!read_full(h->sock, &id, sizeof(id))) return client_fail(h);
  if (op == 2 && id != kLenErr &&
      !read_full(h->sock, out, n * sizeof(double)))
    return client_fail(h);
  return id;
}

// Client-side INFO: the reply is the box count followed by ALL lengths,
// which must be fully drained to keep the connection framed.
static int64_t client_info(Handle* h, std::vector<int64_t>* lens_out) {
  std::lock_guard<std::mutex> lock(h->io_mu);
  if (h->sock < 0) return kIoErr;
  Request req{};
  req.op = 5;
  if (!write_full(h->sock, &req, sizeof(req))) return client_fail(h);
  int64_t nb;
  if (!read_full(h->sock, &nb, sizeof(nb))) return client_fail(h);
  std::vector<int64_t> lens(static_cast<size_t>(nb));
  if (!read_full(h->sock, lens.data(), lens.size() * sizeof(int64_t)))
    return client_fail(h);
  if (lens_out) *lens_out = std::move(lens);
  return nb;
}

int64_t tws_num_boxes(void* handle) {
  auto* h = static_cast<Handle*>(handle);
  if (h->server) return static_cast<int64_t>(h->server->boxes.size());
  return client_info(h, nullptr);
}

int64_t tws_length(void* handle, int box) {
  auto* h = static_cast<Handle*>(handle);
  if (h->server) {
    if (box < 0 || box >= static_cast<int>(h->server->boxes.size()))
      return kLenErr;
    return static_cast<int64_t>(h->server->boxes[box].payload.size());
  }
  std::vector<int64_t> lens;
  int64_t nb = client_info(h, &lens);
  if (nb < 0) return nb;
  if (box < 0 || box >= nb) return kLenErr;
  return lens[static_cast<size_t>(box)];
}

// The hub-local (server-handle) branches apply the same box-range check as
// the socket path (box_ok): out-of-range ids report kLenErr, never UB.
static bool server_box_ok(const Server* s, int box) {
  return box >= 0 && box < static_cast<int>(s->boxes.size());
}

int64_t tws_put(void* handle, int box, const double* values, int64_t n) {
  auto* h = static_cast<Handle*>(handle);
  if (h->server) {
    if (!server_box_ok(h->server, box)) return kLenErr;
    return local_put(h->server->boxes[box], values, n);
  }
  return request_reply(h, 1, box, n, values, nullptr);
}

int64_t tws_get(void* handle, int box, double* out, int64_t n) {
  auto* h = static_cast<Handle*>(handle);
  if (h->server) {
    if (!server_box_ok(h->server, box)) return kLenErr;
    return local_get(h->server->boxes[box], out, n);
  }
  return request_reply(h, 2, box, n, nullptr, out);
}

int64_t tws_write_id(void* handle, int box) {
  auto* h = static_cast<Handle*>(handle);
  if (h->server) {
    if (!server_box_ok(h->server, box)) return kLenErr;
    std::lock_guard<std::mutex> lock(h->server->boxes[box].mu);
    return h->server->boxes[box].write_id;
  }
  return request_reply(h, 3, box, 0, nullptr, nullptr);
}

int64_t tws_kill(void* handle, int box) {
  auto* h = static_cast<Handle*>(handle);
  if (h->server) {
    if (!server_box_ok(h->server, box)) return kLenErr;
    std::lock_guard<std::mutex> lock(h->server->boxes[box].mu);
    h->server->boxes[box].write_id = kKillId;
    return kKillId;
  }
  return request_reply(h, 4, box, 0, nullptr, nullptr);
}

void tws_close(void* handle) {
  auto* h = static_cast<Handle*>(handle);
  if (h->server) {
    Server* s = h->server;
    s->stop.store(true, std::memory_order_relaxed);
    shutdown(s->listen_fd, SHUT_RDWR);
    close(s->listen_fd);
    if (s->accept_thread.joinable()) s->accept_thread.join();
    {
      // unblock every handler (recv returns 0 after shutdown), then JOIN:
      // detaching would let a late request dereference the freed Server
      std::lock_guard<std::mutex> lock(s->conn_mu);
      for (auto& c : s->conns) shutdown(c->fd, SHUT_RDWR);
    }
    for (auto& c : s->conns)
      if (c->th.joinable()) c->th.join();
    delete s;
  } else if (h->sock >= 0) {
    close(h->sock);
  }
  delete h;
}

}  // extern "C"
