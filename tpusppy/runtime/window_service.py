"""ctypes bindings for the C++ shared-memory window service.

Exposes :class:`ShmMailbox` with the exact interface of the in-process
:class:`tpusppy.cylinders.spcommunicator.Mailbox` (put/get/kill/write_id and
the terminal −1 sentinel), so a :class:`ShmWindowFabric` drops into
``WheelSpinner`` unchanged when cylinders are separate OS processes — the
cross-process analogue of the reference's MPI RMA windows
(spcommunicator.py:93-120).

The library is compiled on first use with g++ (cached beside the source);
pybind11 is unavailable in this image, hence ctypes over a C ABI.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_SRC = os.path.join(os.path.dirname(__file__), "csrc", "window_service.cpp")
_LIB_PATH = os.path.join(os.path.dirname(__file__), "csrc",
                         "libwindow_service.so")
_lib = None
_lib_lock = threading.Lock()


class WindowServiceUnavailable(RuntimeError):
    """The shm window service cannot exist on this host — no C++
    toolchain, or the platform lacks working POSIX shm.  Tests skip
    (with this reason) instead of erroring; a COMPILE failure of the
    source is deliberately NOT this class — that is a code regression
    and must stay an error (see tests/test_window_service.py)."""


def _compile():
    """Build the shared library.  ``shm_open`` lives in librt on older
    glibc (this container) and in libc proper since glibc 2.34 — link
    ``-lrt`` on Linux either way (a no-op stub where unneeded); macOS has
    neither librt nor the need for it."""
    import sys

    cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
           _SRC, "-o", _LIB_PATH]
    if sys.platform.startswith("linux"):
        cmd.append("-lrt")
    try:
        proc = subprocess.run(cmd, capture_output=True)
    except FileNotFoundError as e:
        raise WindowServiceUnavailable(f"no C++ toolchain: {e}") from e
    if proc.returncode != 0:
        # a present toolchain failing on our source is a regression, not
        # an environment limitation: surface it as a hard error
        raise RuntimeError(
            "window_service.cpp failed to compile: "
            f"{proc.stderr.decode(errors='replace')[-500:]}")


def load_library() -> ctypes.CDLL:
    """Compile (once) and load the shared library.

    A stale .so that no longer loads (e.g. built before the ``-lrt`` link
    fix: ``undefined symbol: shm_open``) is rebuilt once and retried.
    Raises :class:`WindowServiceUnavailable` when the library genuinely
    cannot be produced here."""
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        if (not os.path.exists(_LIB_PATH)
                or os.path.getmtime(_LIB_PATH) < os.path.getmtime(_SRC)):
            _compile()
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            # stale/broken artifact (wrong link flags, interrupted write):
            # rebuild from source once, then let a second failure surface
            try:
                os.remove(_LIB_PATH)
            except OSError:
                pass
            _compile()
            try:
                lib = ctypes.CDLL(_LIB_PATH)
            except OSError as e:
                # a FRESHLY compiled library failing to load is a link
                # regression in our source/flags (the shm_open class this
                # path exists to catch), not an environment limitation —
                # it must fail loudly, never skip
                raise RuntimeError(
                    f"freshly rebuilt library fails to load: {e}") from e
        lib.ws_create.restype = ctypes.c_void_p
        lib.ws_create.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                  ctypes.POINTER(ctypes.c_int64)]
        lib.ws_attach.restype = ctypes.c_void_p
        lib.ws_attach.argtypes = [ctypes.c_char_p]
        lib.ws_num_boxes.restype = ctypes.c_int64
        lib.ws_num_boxes.argtypes = [ctypes.c_void_p]
        lib.ws_length.restype = ctypes.c_int64
        lib.ws_length.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.ws_put.restype = ctypes.c_int64
        lib.ws_put.argtypes = [ctypes.c_void_p, ctypes.c_int,
                               ctypes.POINTER(ctypes.c_double),
                               ctypes.c_int64]
        lib.ws_get.restype = ctypes.c_int64
        lib.ws_get.argtypes = [ctypes.c_void_p, ctypes.c_int,
                               ctypes.POINTER(ctypes.c_double),
                               ctypes.c_int64, ctypes.c_int64]
        lib.ws_write_id.restype = ctypes.c_int64
        lib.ws_write_id.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.ws_kill.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.ws_close.argtypes = [ctypes.c_void_p]
        _lib = lib
        return lib


class ShmSegment:
    """One named segment holding several mailboxes."""

    def __init__(self, name: str, lengths=None, attach=False):
        self._lib = load_library()
        self.name = name
        if attach:
            handle = self._lib.ws_attach(name.encode())
            if not handle:
                raise RuntimeError(f"cannot attach shm segment {name!r}")
        else:
            arr = (ctypes.c_int64 * len(lengths))(*[int(x) for x in lengths])
            handle = self._lib.ws_create(name.encode(), len(lengths), arr)
            if not handle:
                raise RuntimeError(f"cannot create shm segment {name!r}")
        self._handle = ctypes.c_void_p(handle)

    @property
    def num_boxes(self) -> int:
        return int(self._lib.ws_num_boxes(self._handle))

    def length(self, box: int) -> int:
        return int(self._lib.ws_length(self._handle, box))

    def close(self):
        if self._handle:
            self._lib.ws_close(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class ShmMailbox:
    """Mailbox-view over one box of a segment (Mailbox API parity)."""

    KILL_ID = -1

    def __init__(self, segment: ShmSegment, box: int, name: str = ""):
        self.segment = segment
        self.box = int(box)
        self.name = name
        self.length = segment.length(box)

    def put(self, values) -> int:
        values = np.ascontiguousarray(values, dtype=np.float64)
        if values.shape != (self.length,):
            raise RuntimeError(
                f"ShmMailbox {self.name}: putting length {values.shape} into "
                f"buffer of length {self.length}"
            )
        rc = self.segment._lib.ws_put(
            self.segment._handle, self.box,
            values.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            self.length,
        )
        if rc == -2:
            raise RuntimeError("length mismatch in ws_put")
        return int(rc)

    def get(self, timeout=60.0):
        """Snapshot (values, write_id).  ``timeout`` (seconds) bounds the wait
        for a stable snapshot; <= 0 waits forever (with sleep backoff, so a
        dead writer never spins a reader at 100% CPU)."""
        out = np.empty(self.length, dtype=np.float64)
        wid = self.segment._lib.ws_get(
            self.segment._handle, self.box,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), self.length,
            int(timeout * 1e6),
        )
        if wid == -2:
            raise RuntimeError("length mismatch in ws_get")
        if wid == -3:
            raise RuntimeError(
                f"ShmMailbox {self.name}: no stable snapshot within "
                f"{timeout}s (writer died or stalled mid-put)"
            )
        return out, int(wid)

    def kill(self):
        self.segment._lib.ws_kill(self.segment._handle, self.box)

    @property
    def write_id(self) -> int:
        return int(self.segment._lib.ws_write_id(self.segment._handle,
                                                 self.box))


class ShmWindowFabric:
    """WindowFabric API over a shm segment: 2 boxes per spoke
    (hub->spoke then spoke->hub), creatable by the hub process and attachable
    by spoke processes."""

    def __init__(self, name: str, spoke_lengths=None, attach=False):
        """``spoke_lengths``: list of (hub_to_spoke_len, spoke_to_hub_len)."""
        self.name = name
        if attach:
            self.segment = ShmSegment(name, attach=True)
            n = self.segment.num_boxes // 2
        else:
            lengths = []
            for (h2s, s2h) in spoke_lengths:
                lengths.extend([h2s, s2h])
            self.segment = ShmSegment(name, lengths=lengths)
            n = len(spoke_lengths)
        self.to_spoke = {}
        self.to_hub = {}
        for i in range(1, n + 1):
            self.to_spoke[i] = ShmMailbox(self.segment, 2 * (i - 1),
                                          f"hub->spoke{i}")
            self.to_hub[i] = ShmMailbox(self.segment, 2 * (i - 1) + 1,
                                        f"spoke{i}->hub")

    @property
    def n_spokes(self) -> int:
        return len(self.to_spoke)

    def send_terminate(self):
        for mb in self.to_spoke.values():
            mb.kill()

    def close(self):
        self.segment.close()
