"""ctypes bindings for the C++ TCP window service — the MULTI-HOST fabric.

Same Mailbox/WindowFabric API as the shm service
(:mod:`tpusppy.runtime.window_service`), but over TCP so cylinders can live
on different hosts, the way the reference's wheel spans nodes over MPI RMA
(mpisppy/spin_the_wheel.py:219-237).  The hub process serves the boxes
in-memory (its own accesses are local, mutex-guarded, no sockets); every
spoke — local or remote — connects by ``host:port``.

Multi-host launch recipe (see doc/multihost.md):
  hub host:   fabric = TcpWindowFabric(spoke_lengths=[...], port=7077,
                                       bind="0.0.0.0")  # default is loopback
              ... WheelSpinner hub side with this fabric ...
              # hand (host, port, fabric.secret) to the spoke launchers
  spoke host: fabric = TcpWindowFabric(connect=("hub-host", 7077),
                                       secret=<hub's fabric.secret>)
              ... build the spoke opt + comm, comm.main() ...
``MultiprocessWheelSpinner(..., fabric="tcp")`` drives the same path with
spawned local processes (the single-host degenerate case and the CI test).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
import time

import numpy as np

from ..obs import metrics as _obs_metrics
from ..obs.log import get_logger
from ..resilience import faults as _faults

_SRC = os.path.join(os.path.dirname(__file__), "csrc",
                    "tcp_window_service.cpp")
_LIB_PATH = os.path.join(os.path.dirname(__file__), "csrc",
                         "libtcp_window_service.so")
_lib = None
_lib_lock = threading.Lock()
_log = get_logger("tcp_window")

KILL_ID = -1
_LEN_ERR = -2
_IO_ERR = -4
_TIMEOUT_ERR = -5

# mid-run fault tolerance knobs (doc/resilience.md): a CLIENT endpoint
# retries a failed op with bounded exponential backoff, reconnecting
# between attempts — a transient network blip or hub restart inside the
# run no longer kills the spoke (previously only the FIRST-collective
# rendezvous skew was retried, by the connect timeout).  Servers never
# retry: their ops are local mutexed memory and an error there is a bug.
_RETRIES = int(os.environ.get("TPUSPPY_TCP_RETRIES", "4"))
_BACKOFF_BASE = float(os.environ.get("TPUSPPY_TCP_BACKOFF", "0.1"))
_BACKOFF_CAP = float(os.environ.get("TPUSPPY_TCP_BACKOFF_CAP", "5.0"))

_CTR_IO_ERRORS = _obs_metrics.counter("tcp_window.io_errors")
_CTR_RETRIES = _obs_metrics.counter("tcp_window.retries")
_CTR_RECONNECTS = _obs_metrics.counter("tcp_window.reconnects")
_CTR_OP_TIMEOUTS = _obs_metrics.counter("tcp_window.op_timeouts")


def default_op_timeout() -> float:
    """Per-op client deadline in seconds (``TPUSPPY_TCP_OP_TIMEOUT``;
    0 = block forever, the legacy behavior).  Read at endpoint
    construction, not import, so tests and the elastic wheel can arm it
    per run.  Bounds the wedged-yet-connected-server hang the plain IO
    retry path cannot see: a dead connection errors, a wedged server
    simply never replies (runtime/csrc/tcp_window_service.cpp keeps the
    server-side analogue note)."""
    return float(os.environ.get("TPUSPPY_TCP_OP_TIMEOUT", "0") or 0.0)


def load_library() -> ctypes.CDLL:
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        if (not os.path.exists(_LIB_PATH)
                or os.path.getmtime(_LIB_PATH) < os.path.getmtime(_SRC)):
            subprocess.run(
                ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
                 _SRC, "-o", _LIB_PATH],
                check=True, capture_output=True,
            )
        lib = ctypes.CDLL(_LIB_PATH)
        lib.tws_serve.restype = ctypes.c_void_p
        lib.tws_serve.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
                                  ctypes.POINTER(ctypes.c_int64),
                                  ctypes.c_uint64]
        lib.tws_connect.restype = ctypes.c_void_p
        lib.tws_connect.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                    ctypes.c_int64, ctypes.c_uint64]
        lib.tws_port.restype = ctypes.c_int
        lib.tws_port.argtypes = [ctypes.c_void_p]
        for fn, argt in [
            ("tws_num_boxes", [ctypes.c_void_p]),
            ("tws_length", [ctypes.c_void_p, ctypes.c_int]),
            ("tws_write_id", [ctypes.c_void_p, ctypes.c_int]),
            ("tws_kill", [ctypes.c_void_p, ctypes.c_int]),
        ]:
            getattr(lib, fn).restype = ctypes.c_int64
            getattr(lib, fn).argtypes = argt
        lib.tws_put.restype = ctypes.c_int64
        lib.tws_put.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                ctypes.POINTER(ctypes.c_double),
                                ctypes.c_int64]
        lib.tws_get.restype = ctypes.c_int64
        lib.tws_get.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                ctypes.POINTER(ctypes.c_double),
                                ctypes.c_int64]
        lib.tws_set_op_timeout.restype = ctypes.c_int
        lib.tws_set_op_timeout.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.tws_close.argtypes = [ctypes.c_void_p]
        _lib = lib
        return lib


class TcpEndpoint:
    """A server (hub) or client (spoke) handle over the box set.

    The server binds 127.0.0.1 by default; pass ``bind="0.0.0.0"`` (or a
    specific interface) to accept spokes from other hosts.  Every
    connection must present the server's ``secret`` (a random 64-bit token
    generated here unless supplied) — hand it to remote spoke launchers
    out-of-band along with host:port."""

    def __init__(self, lengths=None, port: int = 0, connect=None,
                 connect_timeout: float = 60.0, bind: str = "127.0.0.1",
                 secret: int | None = None, op_timeout: float | None = None):
        self._lib = load_library()
        self.op_timeout = (default_op_timeout() if op_timeout is None
                           else float(op_timeout))
        if connect is not None:
            host, prt = connect
            self.secret = int(secret or 0)
            self._connect_spec = (str(host), int(prt),
                                  float(connect_timeout))
            handle = self._lib.tws_connect(
                str(host).encode(), int(prt), int(connect_timeout * 1000),
                ctypes.c_uint64(self.secret))
            if not handle:
                raise RuntimeError(
                    f"cannot connect to window service at {host}:{prt} "
                    f"(down, or shared secret rejected)")
            self.port = int(prt)
            self.is_server = False
            self._handle = ctypes.c_void_p(handle)
            self._apply_op_timeout()
            return
        else:
            if secret is None:
                import secrets as _secrets

                secret = _secrets.randbits(64)
            self.secret = int(secret)
            arr = (ctypes.c_int64 * len(lengths))(*[int(x) for x in lengths])
            handle = self._lib.tws_serve(
                str(bind).encode(), int(port), len(lengths), arr,
                ctypes.c_uint64(self.secret))
            if not handle:
                raise RuntimeError(f"cannot serve window service on "
                                   f"{bind}:{port}")
            self.is_server = True
            self._handle = ctypes.c_void_p(handle)
            self.port = int(self._lib.tws_port(self._handle))

    def _apply_op_timeout(self):
        """Install the per-op deadline on the live client socket (called
        after every connect/reconnect — the C side stores it per handle,
        and a fresh handle starts blocking)."""
        if self.op_timeout and getattr(self, "_handle", None):
            self._lib.tws_set_op_timeout(
                self._handle, int(self.op_timeout * 1000))

    @property
    def num_boxes(self) -> int:
        return self._check(self._lib.tws_num_boxes(self._handle))

    def length(self, box: int) -> int:
        return self._check(self._lib.tws_length(self._handle, box))

    def _check(self, rc: int) -> int:
        if rc == _TIMEOUT_ERR:
            # connected but unresponsive: the op deadline expired and the
            # C side closed the (desynced) connection — loud by contract
            _CTR_OP_TIMEOUTS.inc(1)
            _CTR_IO_ERRORS.inc(1)
            _log.warning("window-service op timed out after %.1fs "
                         "(TPUSPPY_TCP_OP_TIMEOUT) — connection dropped",
                         self.op_timeout)
            raise RuntimeError(
                f"TCP window service op timed out after "
                f"{self.op_timeout:.1f}s (server wedged?); "
                "connection lost")
        if rc == _IO_ERR:
            _CTR_IO_ERRORS.inc(1)
            raise RuntimeError("TCP window service connection lost")
        return int(rc)

    @property
    def can_reconnect(self) -> bool:
        return not self.is_server and hasattr(self, "_connect_spec")

    def reconnect(self):
        """Tear down the (possibly dead) client connection and dial the
        server again with the original host/port/secret — the mid-run
        recovery primitive behind the mailbox retry path."""
        if not self.can_reconnect:
            raise RuntimeError("server endpoints cannot reconnect")
        host, prt, timeout = self._connect_spec
        self.close()
        handle = self._lib.tws_connect(
            host.encode(), prt, int(timeout * 1000),
            ctypes.c_uint64(self.secret))
        if not handle:
            _CTR_IO_ERRORS.inc(1)
            raise RuntimeError(
                f"reconnect to window service at {host}:{prt} failed")
        self._handle = ctypes.c_void_p(handle)
        self._apply_op_timeout()
        _CTR_RECONNECTS.inc(1)

    def drop_for_test(self):
        """Sever the connection NOW (close the handle) without touching
        the server — the deterministic 'network died' hook the reconnect
        test drives.  Subsequent ops raise connection-lost until a
        :meth:`reconnect` (the mailbox retry path does it)."""
        self.close()

    def close(self):
        if getattr(self, "_handle", None):
            self._lib.tws_close(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class TcpMailbox:
    """Mailbox-API view over one box (put/get/kill/write_id, −1 sentinel).

    Client-side ops are wrapped in a bounded retry: a transient IO
    failure (dead connection, injected fault) backs off exponentially
    (``TPUSPPY_TCP_BACKOFF`` base, doubled per attempt, capped), the
    endpoint RECONNECTS, and the op re-runs — up to
    ``TPUSPPY_TCP_RETRIES`` retries, then the error propagates.  Server
    ops never retry (local memory).  All traffic is billed to the
    ``tcp_window.*`` obs counters.
    """

    KILL_ID = KILL_ID

    def __init__(self, ep: TcpEndpoint, box: int, name: str = ""):
        self.ep = ep
        self.box = int(box)
        self.name = name
        self.length = self._io("length", lambda: ep.length(self.box))

    def _io(self, opname: str, fn):
        """Run one window op under the transient-failure retry policy.
        An endpoint may pin ``io_retries`` (e.g. 0) when a HIGHER layer
        owns reconnection — :class:`~tpusppy.service.net.SolveClient`
        does, so its dead-server detection isn't multiplied through two
        nested retry stacks reading the same env knobs."""
        delay = _BACKOFF_BASE
        retries = getattr(self.ep, "io_retries", _RETRIES)
        for attempt in range(retries + 1):
            try:
                if _faults.active():    # deterministic drop/delay injection
                    _faults.on_tcp_io(self.name)
                if self.ep._handle is None:
                    # a severed connection: NULL handles must never reach
                    # the C side (that would be UB, not an error return)
                    _CTR_IO_ERRORS.inc(1)
                    raise RuntimeError(
                        "TCP window service connection lost")
                return fn()
            except (RuntimeError, OSError) as e:
                # injected faults count under faults.*; real IO errors are
                # already billed where they surface (_check / reconnect)
                transient = "connection lost" in str(e)
                if (not transient or not self.ep.can_reconnect
                        or attempt == retries):
                    raise
                _CTR_RETRIES.inc(1)
                time.sleep(delay)
                delay = min(delay * 2.0, _BACKOFF_CAP)
                try:
                    self.ep.reconnect()
                except RuntimeError:
                    # server still unreachable: keep backing off — the
                    # next attempt's handle-None guard re-raises cleanly
                    continue

    def put(self, values) -> int:
        values = np.ascontiguousarray(values, dtype=np.float64)
        if values.shape != (self.length,):
            raise RuntimeError(
                f"TcpMailbox {self.name}: putting length {values.shape} "
                f"into buffer of length {self.length}")
        rc = self._io("put", lambda: self.ep._check(self.ep._lib.tws_put(
            self.ep._handle, self.box,
            values.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            self.length)))
        if rc == _LEN_ERR:
            raise RuntimeError("length mismatch in tws_put")
        return rc

    def get(self, timeout=None):
        """(values, write_id) snapshot; always immediate (server-side boxes
        are mutex-consistent — no seqlock wait states)."""
        out = np.empty(self.length, dtype=np.float64)
        wid = self._io("get", lambda: self.ep._check(self.ep._lib.tws_get(
            self.ep._handle, self.box,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            self.length)))
        if wid == _LEN_ERR:
            raise RuntimeError("length mismatch in tws_get")
        return out, int(wid)

    def kill(self):
        self._io("kill", lambda: self.ep._check(
            self.ep._lib.tws_kill(self.ep._handle, self.box)))

    @property
    def write_id(self) -> int:
        return self._io("write_id", lambda: self.ep._check(
            self.ep._lib.tws_write_id(self.ep._handle, self.box)))


class TcpWindowFabric:
    """WindowFabric API over TCP: 2 boxes per spoke (hub->spoke, spoke->hub).

    Hub side: ``TcpWindowFabric(spoke_lengths=[(h2s, s2h), ...], port=0)``
    (port 0 = kernel-assigned; read ``fabric.port``; loopback bind by
    default — pass ``bind="0.0.0.0"`` for cross-host wheels).  Spoke side
    (any host): ``TcpWindowFabric(connect=(host, port),
    secret=<hub fabric.secret>)`` — the handshake rejects missing/wrong
    secrets.
    """

    def __init__(self, spoke_lengths=None, port: int = 0, connect=None,
                 connect_timeout: float = 60.0, bind: str = "127.0.0.1",
                 secret: int | None = None, op_timeout: float | None = None):
        if connect is not None:
            self.ep = TcpEndpoint(connect=connect,
                                  connect_timeout=connect_timeout,
                                  secret=secret, op_timeout=op_timeout)
            n = self.ep.num_boxes // 2
        else:
            lengths = []
            for (h2s, s2h) in spoke_lengths:
                lengths.extend([h2s, s2h])
            self.ep = TcpEndpoint(lengths=lengths, port=port, bind=bind,
                                  secret=secret, op_timeout=op_timeout)
            n = len(spoke_lengths)
        self.port = self.ep.port
        self.secret = self.ep.secret
        self.to_spoke = {}
        self.to_hub = {}
        for i in range(1, n + 1):
            self.to_spoke[i] = TcpMailbox(self.ep, 2 * (i - 1),
                                          f"hub->spoke{i}")
            self.to_hub[i] = TcpMailbox(self.ep, 2 * (i - 1) + 1,
                                        f"spoke{i}->hub")

    @property
    def n_spokes(self) -> int:
        return len(self.to_spoke)

    def send_terminate(self):
        for mb in self.to_spoke.values():
            mb.kill()

    def close(self):
        self.ep.close()
