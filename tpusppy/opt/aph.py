"""APH: Asynchronous Projective Hedging (Eckstein et al.) — batched.

TPU-native analogue of ``mpisppy/opt/aph.py:46-982``.  The reference overlaps
a listener thread doing background MPI Allreduces with workers that dispatch
only a *fraction* of subproblems per pass, chosen by (staleness, phi)
(aph.py:198-330, 554-668).  In the batched runtime the reductions are cheap
einsums over device arrays, so the listener/Synchronizer machinery collapses
to synchronous host code (its ``async_frac_needed`` vote is trivially
satisfied by the single controller), while the *algorithmic* asynchrony —
stale subproblem solutions, fractional dispatch — is preserved exactly:

* ``dispatch_frac`` selects scnt = max(1, round(S*frac)) scenarios by the
  reference's (last-dispatch-iteration, phi) sort (aph.py:602-657);
* the dispatched rows are gathered into a COMPACT sub-batch of fixed shape
  (scnt is constant), solved in one device program with prox center z, and
  scattered back — non-dispatched scenarios keep their stale x, exactly the
  APH semantics, and the device does scnt/S of the work.

State arrays, all (S, K): x (stale solutions' nonants), z (projective
center), W (duals), y (subgradient estimates), u = x - xbar, plus the scalar
tau/phi/theta and the four probability-weighted norms driving the convergence
metric (aph.py:332-553).
"""

from __future__ import annotations

import numpy as np

from .. import global_toc
from ..phbase import PHBase


class APH(PHBase):
    """(aph.py:46-143 constructor semantics; options: APHgamma, APHnu,
    async_frac_needed, dispatch_frac, async_sleep_secs)."""

    def __init__(self, options, all_scenario_names, scenario_creator,
                 **kwargs):
        super().__init__(options, all_scenario_names, scenario_creator,
                         **kwargs)
        self.APHgamma = float(self.options.get("APHgamma", 1.0))
        self.nu = float(self.options.get("APHnu", 1.0))
        self.dispatch_frac = float(self.options.get("dispatch_frac", 1.0))
        self.use_lag = bool(self.options.get("APHuse_lag", False))
        S = self.batch.num_scenarios
        K = self.nonant_length
        self.z = np.zeros((S, K))
        self.y = np.zeros((S, K))
        self.ybars = np.zeros((S, K))
        self.uk = np.zeros((S, K))
        self.phis = np.zeros(S)
        self.theta = 0.0
        self.global_tau = 0.0
        self.global_phi = 0.0
        self.tau_summand = 0.0
        self.local_pwsqnorm = 0.0
        self.local_pzsqnorm = 0.0
        self.global_pusqnorm = 0.0
        self.global_pvsqnorm = 0.0
        self.global_pwsqnorm = 0.0
        self.global_pzsqnorm = 0.0
        # dispatch record: (last iteration dispatched, jittered start order)
        rng = np.random.default_rng(self.options.get("seed", 1134))
        self._last_dispatch = rng.random(S) * 1e-3
        self._scnt = max(1, round(S * self.dispatch_frac))

    # ---- node-grouped averages (Compute_Averages, aph.py:332-453) -----------
    def _node_avg(self, arr_sk: np.ndarray) -> np.ndarray:
        """Per-node probability-weighted mean, broadcast back to (S, K)."""
        p = self.probs[:, None]
        num = np.einsum("skn,sk->nk", self._onehot, p * arr_sk)
        den = getattr(self, "_node_den", None)
        if den is None:
            # depends only on probs + tree: compute once, reuse across the
            # three averages per reduction (worker or listener thread)
            den = np.maximum(np.einsum(
                "skn,sk->nk", self._onehot,
                np.broadcast_to(p, (p.shape[0], self.nonant_length))), 1e-300)
            self._node_den = den
        avg_nk = num / den
        kidx = np.arange(self.nonant_length)[None, :]
        return avg_nk[self.nid_sk, kidx]

    def Update_y(self, dispatched: np.ndarray):
        """y_s = W_s + rho (x_s - z_s) on dispatched rows (aph.py:151-182);
        all-zero at the first pass."""
        if self._iter == 1:
            self.y[:] = 0.0
            return
        xk = self.nonants_of(self.local_x)
        newy = self.W + self.rho * (xk - self.z)
        self.y[dispatched] = newy[dispatched]

    def _averages_from(self, xk, y, W, z):
        """The pure reduction math (aph.py:198-330 side-gig): node averages
        of x and y, u/v norms, tau and phi summands — computable by either
        the worker inline or the listener thread from published copies."""
        xbars = self._node_avg(xk)
        xsqbars = self._node_avg(xk * xk)
        ybars = self._node_avg(y)
        uk = xk - xbars
        p = self.probs
        usq = (uk * uk).sum(axis=1)
        vsq = (ybars * ybars).sum(axis=1)
        phis = p * np.einsum("sk,sk->s", z - xk, W - y)
        return {
            "xbars": xbars, "xsqbars": xsqbars, "ybars": ybars, "uk": uk,
            "pusqnorm": float(p @ usq), "pvsqnorm": float(p @ vsq),
            "tau": float(p @ (usq + vsq / self.APHgamma)),
            "phis": phis, "phi": float(phis.sum()),
        }

    def _apply_averages(self, red: dict):
        self.xbars = red["xbars"]
        self.xsqbars = red["xsqbars"]
        self.ybars = red["ybars"]
        self.uk = red["uk"]
        self.global_pusqnorm = red["pusqnorm"]
        self.global_pvsqnorm = red["pvsqnorm"]
        self.tau_summand = red["tau"]
        self.global_tau = red["tau"]
        self.phis = red["phis"]
        self.global_phi = red["phi"]

    def Compute_Averages(self):
        """xbar, xsqbar, ybar + the u/v/tau/phi side-gig (aph.py:198-330).

        With the listener enabled (``APHuse_listener``), the worker PUBLISHES
        its state through the Synchronizer and reads back the averages the
        listener thread computed — possibly one publish stale, exactly the
        reference's asynchronous reduction overlap (aph.py:198-330 +
        listener_util.py:277-327).  Inline otherwise.
        """
        xk = self.nonants_of(self.local_x)
        if getattr(self, "_synchronizer", None) is not None:
            self._publish_and_read(xk)
            return
        self._apply_averages(self._averages_from(xk, self.y, self.W, self.z))

    # ---- listener-thread reduction overlap (aph.py:198-330) -----------------
    def _publish_and_read(self, xk):
        """Publish (x, y, W, z) to the Synchronizer; read back the listener's
        latest reduction.  Waits briefly for freshness; under load the stale
        previous reduction is used — APH's tolerated staleness."""
        import time

        sync = self._synchronizer
        S, K = xk.shape
        flat = {
            "xk": xk.ravel(), "y": self.y.ravel(),
            "W": self.W.ravel(), "z": self.z.ravel(),
            "serial": np.array([float(self._iter)]),
        }
        sync.compute_global_data(flat, enable_side_gig=True)
        # freshness wait: by default the worker gives the listener ~100
        # sleep quanta to produce THIS iteration's reduction (near-inline
        # trajectory).  APH_listener_wait_secs=0 is the full-overlap mode:
        # read whatever reduction exists — one publish stale — and let the
        # listener crunch the new publication WHILE the next solve runs
        # (the reference's tolerated staleness, aph.py:198-330).
        wait = self.options.get("APH_listener_wait_secs")
        if wait is None:
            wait = float(self.options.get("async_sleep_secs", 0.01)) * 100
        deadline = time.time() + float(wait)
        fresh = False
        while True:
            with sync._lock:
                red = sync.reduced
                if red is not None and red["serial"] >= self._iter:
                    fresh = True
                    break
            if time.time() >= deadline:
                break
            time.sleep(0.0005)
        with sync._lock:
            red = sync.reduced
        if red is None:           # listener never ran yet: compute inline
            self._apply_averages(
                self._averages_from(xk, self.y, self.W, self.z))
            return
        if not fresh:
            self._stale_reductions += 1
        self._apply_averages({k: v for k, v in red.items() if k != "serial"})

    def _make_side_gig(self):
        """The listener's side gig: recompute averages from the workers'
        latest published state into ``sync.reduced`` (runs on the listener
        thread, under the Synchronizer lock)."""
        def side_gig(sync):
            slot = sync._locals.get(0)
            if not slot or "xk" not in slot:
                return
            S = self.batch.num_scenarios
            K = self.nonant_length
            shp = (S, K)
            red = self._averages_from(
                slot["xk"].reshape(shp), slot["y"].reshape(shp),
                slot["W"].reshape(shp), slot["z"].reshape(shp))
            red["serial"] = float(slot["serial"][0])
            sync.reduced = red
        return side_gig

    def Update_theta_zw(self):
        """theta from phi/tau; W += theta u; z step toward ybar
        (aph.py:453-498)."""
        if self.global_tau <= 0 or self.global_phi <= 0:
            self.theta = 0.0
        else:
            self.theta = self.global_phi * self.nu / self.global_tau
        self.W = self.W + self.theta * self.uk
        self._bump_state_version()    # APHHub mailbox writes key on this
        if self._iter != 1:
            self.z = self.z + (self.theta / self.APHgamma) * self.ybars
        else:
            self.z = np.array(self.xbars, copy=True)
        p = self.probs
        self.global_pwsqnorm = float(p @ (self.W * self.W).sum(axis=1))
        self.global_pzsqnorm = float(p @ (self.z * self.z).sum(axis=1))

    def Compute_Convergence(self):
        """conv = punorm/pwnorm + pvnorm/pznorm (aph.py:499-528)."""
        pw = np.sqrt(self.global_pwsqnorm)
        pz = np.sqrt(self.global_pzsqnorm)
        if pw > 0 and pz > 0:
            self.conv = (np.sqrt(self.global_pusqnorm) / pw
                         + np.sqrt(self.global_pvsqnorm) / pz)
        return self.conv

    # ---- fractional dispatch (APH_solve_loop, aph.py:554-668) ---------------
    def _dispatch_rows(self) -> np.ndarray:
        """scnt scenario indices by (staleness, phi) sort."""
        order = np.lexsort((self.phis, self._last_dispatch))
        rows = order[: self._scnt]
        self._last_dispatch[rows] = self._iter
        return rows

    def APH_solve_loop(self) -> np.ndarray:
        """Solve the dispatched sub-batch with prox center z; scatter back.

        Returns the dispatched row indices."""
        from ..spopt import batch_solve_dispatch

        rows = self._dispatch_rows()
        b = self.batch
        idx = self.tree.nonant_indices
        q = np.array(b.c[rows], copy=True)
        q2 = np.array(b.q2[rows], copy=True)
        q[:, idx] += self.W[rows] - self.rho[rows] * self.z[rows]
        q2[:, idx] += self.rho[rows]
        warm = None
        if self._warm is not None:
            warm = tuple(np.asarray(w)[rows] for w in self._warm)
        sol = batch_solve_dispatch(
            b, q, q2, b.cl[rows], b.cu[rows], b.lb[rows], b.ub[rows],
            settings=self.admm_settings, warm=warm, rows=rows,
        )
        if self.local_x is None:
            self.local_x = np.zeros((b.num_scenarios, b.num_vars))
        elif not self.local_x.flags.writeable:
            self.local_x = np.array(self.local_x)
        self.local_x[rows] = np.asarray(sol.x)
        self._xk_src = None   # in-place row update: drop the nonant cache
        self._bump_state_version()
        if self._warm is None:
            S = b.num_scenarios
            self._warm = (
                np.zeros((S, b.num_vars)), np.zeros((S, b.num_rows)),
                np.zeros((S, b.num_rows)), np.zeros((S, b.num_vars)),
            )
        warm_full = tuple(np.array(w) for w in self._warm)
        for wf, part in zip(warm_full, (sol.x, sol.z, sol.y, sol.yx)):
            wf[rows] = np.asarray(part)
        self._warm = warm_full
        if self.pri_res is None:
            self.pri_res = np.zeros(b.num_scenarios)
            self.dua_res = np.zeros(b.num_scenarios)
        elif not self.pri_res.flags.writeable:
            self.pri_res = np.array(self.pri_res)
            self.dua_res = np.array(self.dua_res)
        self.pri_res[rows] = np.asarray(sol.pri_res)
        self.dua_res[rows] = np.asarray(sol.dua_res)
        return rows

    # ---- driver (APH_main, aph.py:820-982) ----------------------------------
    def APH_main(self, spcomm=None, finalize=True):
        if spcomm is not None:
            self.spcomm = spcomm
        self._stale_reductions = 0
        self._synchronizer = None
        if bool(self.options.get("APHuse_listener", False)):
            # the reference's listener-thread reduction overlap
            # (listener_util.Synchronizer driving the side gig concurrently
            # with worker solves; aph.py:198-330 + listener_util.py:82-103)
            from ..utils.listener_util import Synchronizer

            S = self.batch.num_scenarios
            K = self.nonant_length
            lens = {"xk": S * K, "y": S * K, "W": S * K, "z": S * K,
                    "serial": 1}
            self._synchronizer = Synchronizer(
                lens, asynch=True,
                sleep_secs=float(self.options.get("async_sleep_secs", 0.01)))
            self._synchronizer.reduced = None
            out = [None]

            def worker():
                out[0] = self._APH_main_body(finalize)

            self._synchronizer.run(worker,
                                   side_gig=self._make_side_gig())
            if self._stale_reductions:
                global_toc(
                    f"APH listener: {self._stale_reductions} stale "
                    "reductions tolerated",
                    self.options.get("display_progress", False))
            return out[0]
        return self._APH_main_body(finalize)

    def _APH_main_body(self, finalize=True):
        self.extobject.pre_iter0()
        self._iter = 0
        self.solve_loop()                       # iter0: plain objective
        feas = self.feas_prob()
        if feas < 1.0 - 1e-6:
            raise RuntimeError(
                f"Infeasibility detected at APH iter0; mass {feas:.4f}"
            )
        # certified (weak-duality) trivial bound — see phbase.iter0: the
        # primal Ebound of a plateaued iter0 solve is NOT a valid bound
        self.trivial_bound = self.Edualbound()
        self.best_bound = self.trivial_bound
        self.extobject.post_iter0()
        if self.spcomm is not None:
            self.spcomm.sync()

        conv = None
        dispatched = np.arange(self.batch.num_scenarios)
        for it in range(1, int(self.options["PHIterLimit"]) + 1):
            self._iter = it
            self.Update_y(dispatched)
            self.Compute_Averages()
            self.Update_theta_zw()
            conv = self.Compute_Convergence()
            self.extobject.miditer()
            dispatched = self.APH_solve_loop()
            self.extobject.enditer()
            if self.spcomm is not None:
                self.spcomm.sync()
                if self.spcomm.is_converged():
                    global_toc("APH cylinder termination", True)
                    break
            global_toc(
                f"APH iter {it} theta {self.theta:.4f} "
                f"phi {self.global_phi:.4e} tau {self.global_tau:.4e} "
                f"conv {self.conv if self.conv is None else round(self.conv, 8)}",
                self.options.get("display_progress", False),
            )
            if self.conv is not None and \
                    self.conv < self.options.get("convthresh", 0.0):
                break
            if self.ph_converger is not None \
                    and self.ph_converger.is_converged():
                break
        self.extobject.post_everything()
        eobj = self.Eobjective() if finalize else None
        return self.conv, eobj, self.trivial_bound

    # hub-facing alias used by APHHub
    def ph_main(self, finalize=False):
        return self.APH_main(finalize=finalize)
