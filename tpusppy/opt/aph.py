"""APH: Asynchronous Projective Hedging (Eckstein et al.) — batched.

TPU-native analogue of ``mpisppy/opt/aph.py:46-982``.  The reference overlaps
a listener thread doing background MPI Allreduces with workers that dispatch
only a *fraction* of subproblems per pass, chosen by (staleness, phi)
(aph.py:198-330, 554-668).  In the batched runtime the reductions are cheap
einsums over device arrays, so the listener/Synchronizer machinery collapses
to synchronous host code (its ``async_frac_needed`` vote is trivially
satisfied by the single controller), while the *algorithmic* asynchrony —
stale subproblem solutions, fractional dispatch — is preserved exactly:

* ``dispatch_frac`` selects scnt = max(1, round(S*frac)) scenarios by the
  reference's (last-dispatch-iteration, phi) sort (aph.py:602-657);
* the dispatched rows are gathered into a COMPACT sub-batch of fixed shape
  (scnt is constant), solved in one device program with prox center z, and
  scattered back — non-dispatched scenarios keep their stale x, exactly the
  APH semantics, and the device does scnt/S of the work.

State arrays, all (S, K): x (stale solutions' nonants), z (projective
center), W (duals), y (subgradient estimates), u = x - xbar, plus the scalar
tau/phi/theta and the four probability-weighted norms driving the convergence
metric (aph.py:332-553).
"""

from __future__ import annotations

import numpy as np

from .. import global_toc
from ..phbase import PHBase


class APH(PHBase):
    """(aph.py:46-143 constructor semantics; options: APHgamma, APHnu,
    async_frac_needed, dispatch_frac, async_sleep_secs)."""

    def __init__(self, options, all_scenario_names, scenario_creator,
                 **kwargs):
        super().__init__(options, all_scenario_names, scenario_creator,
                         **kwargs)
        self.APHgamma = float(self.options.get("APHgamma", 1.0))
        self.nu = float(self.options.get("APHnu", 1.0))
        self.dispatch_frac = float(self.options.get("dispatch_frac", 1.0))
        self.use_lag = bool(self.options.get("APHuse_lag", False))
        S = self.batch.num_scenarios
        K = self.nonant_length
        self.z = np.zeros((S, K))
        self.y = np.zeros((S, K))
        self.ybars = np.zeros((S, K))
        self.uk = np.zeros((S, K))
        self.phis = np.zeros(S)
        self.theta = 0.0
        self.global_tau = 0.0
        self.global_phi = 0.0
        self.tau_summand = 0.0
        self.local_pwsqnorm = 0.0
        self.local_pzsqnorm = 0.0
        self.global_pusqnorm = 0.0
        self.global_pvsqnorm = 0.0
        self.global_pwsqnorm = 0.0
        self.global_pzsqnorm = 0.0
        # dispatch record: (last iteration dispatched, jittered start order)
        rng = np.random.default_rng(self.options.get("seed", 1134))
        self._last_dispatch = rng.random(S) * 1e-3
        self._scnt = max(1, round(S * self.dispatch_frac))

    # ---- node-grouped averages (Compute_Averages, aph.py:332-453) -----------
    def _node_avg(self, arr_sk: np.ndarray) -> np.ndarray:
        """Per-node probability-weighted mean, broadcast back to (S, K)."""
        p = self.probs[:, None]
        num = np.einsum("skn,sk->nk", self._onehot, p * arr_sk)
        den = np.einsum("skn,sk->nk", self._onehot,
                        np.broadcast_to(p, arr_sk.shape))
        avg_nk = num / np.maximum(den, 1e-300)
        kidx = np.arange(self.nonant_length)[None, :]
        return avg_nk[self.nid_sk, kidx]

    def Update_y(self, dispatched: np.ndarray):
        """y_s = W_s + rho (x_s - z_s) on dispatched rows (aph.py:151-182);
        all-zero at the first pass."""
        if self._iter == 1:
            self.y[:] = 0.0
            return
        xk = self.nonants_of(self.local_x)
        newy = self.W + self.rho * (xk - self.z)
        self.y[dispatched] = newy[dispatched]

    def Compute_Averages(self):
        """xbar, xsqbar, ybar + the u/v/tau/phi side-gig (aph.py:198-330)."""
        xk = self.nonants_of(self.local_x)
        self.Compute_Xbar()                       # xbars, xsqbars
        self.ybars = self._node_avg(self.y)
        self.uk = xk - self.xbars
        p = self.probs
        usq = (self.uk * self.uk).sum(axis=1)
        vsq = (self.ybars * self.ybars).sum(axis=1)
        self.global_pusqnorm = float(p @ usq)
        self.global_pvsqnorm = float(p @ vsq)
        self.tau_summand = float(p @ (usq + vsq / self.APHgamma))
        self.global_tau = self.tau_summand
        # phi summand (aph.py:185-196)
        self.phis = p * np.einsum("sk,sk->s", self.z - xk, self.W - self.y)
        self.global_phi = float(self.phis.sum())

    def Update_theta_zw(self):
        """theta from phi/tau; W += theta u; z step toward ybar
        (aph.py:453-498)."""
        if self.global_tau <= 0 or self.global_phi <= 0:
            self.theta = 0.0
        else:
            self.theta = self.global_phi * self.nu / self.global_tau
        self.W = self.W + self.theta * self.uk
        if self._iter != 1:
            self.z = self.z + (self.theta / self.APHgamma) * self.ybars
        else:
            self.z = np.array(self.xbars, copy=True)
        p = self.probs
        self.global_pwsqnorm = float(p @ (self.W * self.W).sum(axis=1))
        self.global_pzsqnorm = float(p @ (self.z * self.z).sum(axis=1))

    def Compute_Convergence(self):
        """conv = punorm/pwnorm + pvnorm/pznorm (aph.py:499-528)."""
        pw = np.sqrt(self.global_pwsqnorm)
        pz = np.sqrt(self.global_pzsqnorm)
        if pw > 0 and pz > 0:
            self.conv = (np.sqrt(self.global_pusqnorm) / pw
                         + np.sqrt(self.global_pvsqnorm) / pz)
        return self.conv

    # ---- fractional dispatch (APH_solve_loop, aph.py:554-668) ---------------
    def _dispatch_rows(self) -> np.ndarray:
        """scnt scenario indices by (staleness, phi) sort."""
        order = np.lexsort((self.phis, self._last_dispatch))
        rows = order[: self._scnt]
        self._last_dispatch[rows] = self._iter
        return rows

    def APH_solve_loop(self) -> np.ndarray:
        """Solve the dispatched sub-batch with prox center z; scatter back.

        Returns the dispatched row indices."""
        from ..solvers import admm

        rows = self._dispatch_rows()
        b = self.batch
        idx = self.tree.nonant_indices
        q = np.array(b.c[rows], copy=True)
        q2 = np.array(b.q2[rows], copy=True)
        q[:, idx] += self.W[rows] - self.rho[rows] * self.z[rows]
        q2[:, idx] += self.rho[rows]
        warm = None
        if self._warm is not None:
            warm = tuple(np.asarray(w)[rows] for w in self._warm)
        sol = admm.solve_batch(
            q, q2, b.A[rows], b.cl[rows], b.cu[rows], b.lb[rows], b.ub[rows],
            settings=self.admm_settings, warm=warm,
        )
        if self.local_x is None:
            self.local_x = np.zeros((b.num_scenarios, b.num_vars))
        elif not self.local_x.flags.writeable:
            self.local_x = np.array(self.local_x)
        self.local_x[rows] = np.asarray(sol.x)
        if self._warm is None:
            S = b.num_scenarios
            self._warm = (
                np.zeros((S, b.num_vars)), np.zeros((S, b.num_rows)),
                np.zeros((S, b.num_rows)), np.zeros((S, b.num_vars)),
            )
        warm_full = tuple(np.array(w) for w in self._warm)
        for wf, part in zip(warm_full, (sol.x, sol.z, sol.y, sol.yx)):
            wf[rows] = np.asarray(part)
        self._warm = warm_full
        if self.pri_res is None:
            self.pri_res = np.zeros(b.num_scenarios)
            self.dua_res = np.zeros(b.num_scenarios)
        elif not self.pri_res.flags.writeable:
            self.pri_res = np.array(self.pri_res)
            self.dua_res = np.array(self.dua_res)
        self.pri_res[rows] = np.asarray(sol.pri_res)
        self.dua_res[rows] = np.asarray(sol.dua_res)
        return rows

    # ---- driver (APH_main, aph.py:820-982) ----------------------------------
    def APH_main(self, spcomm=None, finalize=True):
        if spcomm is not None:
            self.spcomm = spcomm
        self.extobject.pre_iter0()
        self._iter = 0
        self.solve_loop()                       # iter0: plain objective
        feas = self.feas_prob()
        if feas < 1.0 - 1e-6:
            raise RuntimeError(
                f"Infeasibility detected at APH iter0; mass {feas:.4f}"
            )
        self.trivial_bound = self.Ebound()
        self.best_bound = self.trivial_bound
        self.extobject.post_iter0()
        if self.spcomm is not None:
            self.spcomm.sync()

        conv = None
        dispatched = np.arange(self.batch.num_scenarios)
        for it in range(1, int(self.options["PHIterLimit"]) + 1):
            self._iter = it
            self.Update_y(dispatched)
            self.Compute_Averages()
            self.Update_theta_zw()
            conv = self.Compute_Convergence()
            self.extobject.miditer()
            dispatched = self.APH_solve_loop()
            self.extobject.enditer()
            if self.spcomm is not None:
                self.spcomm.sync()
                if self.spcomm.is_converged():
                    global_toc("APH cylinder termination", True)
                    break
            global_toc(
                f"APH iter {it} theta {self.theta:.4f} "
                f"phi {self.global_phi:.4e} tau {self.global_tau:.4e} "
                f"conv {self.conv if self.conv is None else round(self.conv, 8)}",
                self.options.get("display_progress", False),
            )
            if self.conv is not None and \
                    self.conv < self.options.get("convthresh", 0.0):
                break
            if self.ph_converger is not None \
                    and self.ph_converger.is_converged():
                break
        self.extobject.post_everything()
        eobj = self.Eobjective() if finalize else None
        return self.conv, eobj, self.trivial_bound

    # hub-facing alias used by APHHub
    def ph_main(self, finalize=False):
        return self.APH_main(finalize=finalize)
