"""SchurComplement: batched Schur-complement interior point (continuous SPs).

Analogue of ``mpisppy/opt/sc.py:59-106``.  The reference wraps parapint's
MPI block-structured interior point with MA27 linear algebra (sc.py:4,
95-97): each rank factors its scenario's KKT block and a dense Schur system
couples the first-stage variables.  Here the numerics are NATIVE to the
batch (:mod:`tpusppy.solvers.ipm`): every IP iteration condenses all
scenario KKT systems in one batched (S, n, n) factorization on the MXU, and
the nonant coupling is one small dense Schur solve — same algorithmic
structure, no external solver.  Continuous problems only, refused exactly as
the reference does (sc.py:18-21).
"""

from __future__ import annotations

import numpy as np

from ..solvers import ipm
from ..spbase import SPBase


class SchurComplement(SPBase):
    def __init__(self, options, all_scenario_names, scenario_creator,
                 scenario_creator_kwargs=None, all_nodenames=None, **kwargs):
        super().__init__(options, all_scenario_names, scenario_creator,
                         scenario_creator_kwargs=scenario_creator_kwargs,
                         all_nodenames=all_nodenames, **kwargs)
        if bool(np.any(self.batch.is_int)):
            raise ValueError(
                "SchurComplement does not support mixed-integer problems "
                "(continuous only, cf. sc.py:18-21)"
            )

    def solve(self):
        """Solve the continuous SP; returns the objective (sc.py:89-106)."""
        settings = ipm.IPMSettings(
            tol=float(self.options.get("sc_tol", 1e-6)),
            max_iter=int(self.options.get("sc_max_iter", 100)),
        )
        res = ipm.solve_sc(self.batch, settings)
        self.local_x = res.x
        self.ipm_result = res
        self.first_stage_solution_available = True
        self.objective_value = res.obj + float(
            self.probs @ self.batch.const)
        return self.objective_value
