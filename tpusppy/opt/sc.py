"""SchurComplement: distributed interior-point entry point (continuous SPs).

API analogue of ``mpisppy/opt/sc.py:59-106``.  The reference is a thin
wrapper over parapint's MPI Schur-complement interior point with MA27 linear
algebra (sc.py:4,95-97) — all the numerics live in external native code.  On
TPU the same block-arrowhead KKT structure is what the batched ADMM already
exploits: scenario blocks factor independently (the batched Cholesky) and the
coupling (Schur) system is the nonant consensus, handled by the node-grouped
reductions.  So this class keeps the reference's constructor/solve surface
and solves the continuous extensive form through the merged-column EF +
batched first-order path, refusing integer problems exactly as the reference
does (sc.py:18-21).
"""

from __future__ import annotations

import numpy as np

from ..ef import build_ef, solve_ef
from ..spbase import SPBase


class SchurComplement(SPBase):
    def __init__(self, options, all_scenario_names, scenario_creator,
                 scenario_creator_kwargs=None, all_nodenames=None, **kwargs):
        super().__init__(options, all_scenario_names, scenario_creator,
                         scenario_creator_kwargs=scenario_creator_kwargs,
                         all_nodenames=all_nodenames, **kwargs)
        if bool(np.any(self.batch.is_int)):
            raise ValueError(
                "SchurComplement does not support mixed-integer problems "
                "(continuous only, cf. sc.py:18-21)"
            )

    def solve(self):
        """Solve the continuous SP; returns the objective (sc.py:89-106)."""
        obj, x = solve_ef(self.batch, solver="admm")
        self.local_x = x
        self.first_stage_solution_available = True
        self.objective_value = obj
        return obj
