"""SchurComplement: batched Schur-complement interior point (continuous SPs).

Analogue of ``mpisppy/opt/sc.py:59-106``.  The reference wraps parapint's
MPI block-structured interior point with MA27 linear algebra (sc.py:4,
95-97): each rank factors its scenario's KKT block and a dense Schur system
couples the first-stage variables.  Here the numerics are NATIVE to the
batch (:mod:`tpusppy.solvers.ipm`): every IP iteration condenses all
scenario KKT systems in one batched (S, n, n) factorization on the MXU, and
the nonant coupling is one small dense Schur solve — same algorithmic
structure, no external solver.  Continuous problems only, refused exactly as
the reference does (sc.py:18-21).
"""

from __future__ import annotations

import numpy as np

from ..solvers import ipm
from ..spbase import SPBase


class SchurComplement(SPBase):
    def __init__(self, options, all_scenario_names, scenario_creator,
                 scenario_creator_kwargs=None, all_nodenames=None, **kwargs):
        super().__init__(options, all_scenario_names, scenario_creator,
                         scenario_creator_kwargs=scenario_creator_kwargs,
                         all_nodenames=all_nodenames, **kwargs)
        if bool(np.any(self.batch.is_int)):
            raise ValueError(
                "SchurComplement does not support mixed-integer problems "
                "(continuous only, cf. sc.py:18-21)"
            )

    def solve(self):
        """Solve the continuous SP; returns the objective (sc.py:89-106).

        Two phases: the Schur-complement IPM finds the consensus decision w,
        then a CROSSOVER-style cleanup evaluates it exactly — nonants
        clamped at w, one polished batched solve — so the reported value is
        the true (feasible) objective of the returned decision, with error
        quadratic in ||w - w*|| instead of O(mu) at the barrier stop."""
        settings = ipm.IPMSettings(
            tol=float(self.options.get("sc_tol", 1e-6)),
            max_iter=int(self.options.get("sc_max_iter", 100)),
            crossover=bool(self.options.get("sc_crossover", True)),
        )
        res = ipm.solve_sc(self.batch, settings)
        self.ipm_result = res
        self.local_x = res.x
        obj = res.obj + float(self.probs @ self.batch.const)

        import dataclasses

        from ..spopt import batch_solve_dispatch

        b = self.batch
        idx = self.tree.nonant_indices
        K = idx.shape[0]
        w_sel = res.w[self.nid_sk, np.arange(K)[None, :]]     # (S, K)
        if res.crossover:
            # the IPM's own crossover (solvers/ipm._crossover_ef: restricted
            # exact-simplex cleanup) already produced a solver-exact
            # solution — an ADMM re-evaluation could only blur it back to
            # eps accuracy
            self.crossover_applied = True
        elif (self.options.get("sc_crossover", True)
                and np.isfinite(w_sel).all()):
            # same clamp construction as SPOpt.fix_nonants (SC extends
            # SPBase, not SPOpt, so no fixing overlay machinery exists here)
            lb = b.lb.copy()
            ub = b.ub.copy()
            lb[:, idx] = w_sel
            ub[:, idx] = w_sel
            # user solver_options honored; only the budget/polish raised
            st = dataclasses.replace(self.admm_settings, max_iter=2000,
                                     restarts=6, polish=True)
            sol = batch_solve_dispatch(b, b.c, b.q2, b.cl, b.cu, lb, ub,
                                       settings=st)
            resid = float(np.max(np.maximum(np.asarray(sol.pri_res),
                                            np.asarray(sol.dua_res))))
            # feas_tol convention as in xhat_eval: the cleanup value is used
            # only when the clamped solve certifies feasibility
            tol = max(float(self.options.get("feas_tol", 1e-3)),
                      10.0 * st.eps_rel)
            self.crossover_applied = resid < tol
            if self.crossover_applied:
                x = np.asarray(sol.x)
                self.local_x = x
                obj = float(self.probs @ b.objective(x))
        else:
            self.crossover_applied = False
        self.first_stage_solution_available = True
        self.objective_value = obj
        return self.objective_value
