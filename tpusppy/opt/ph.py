"""PH: synchronous Progressive Hedging driver.

Mirrors ``mpisppy/opt/ph.py:18-71``: thin driver over PHBase —
``PH_Prep -> Iter0 -> iterk_loop -> post_loops``.  (PH_Prep is implicit: the
augmented objective is materialized per solve, no model mutation needed.)
"""

from ..phbase import PHBase


class PH(PHBase):
    """Synchronous PH hub-capable optimizer."""

    def ph_main(self, finalize=True):
        """Run PH; returns (conv, Eobj, trivial_bound) like opt/ph.py:25-71."""
        self.trivial_bound = self.Iter0()
        self.iterk_loop()
        eobj = self.post_loops() if finalize else None
        return self.conv, eobj, self.trivial_bound
