"""L-shaped method (two-stage Benders decomposition) — batched.

TPU-native analogue of ``mpisppy/opt/lshaped.py:23-776``.  The reference
builds a Pyomo root model plus per-scenario subproblems and generates cuts
through ``pyomo.contrib.benders`` (lshaped.py:144-506, utils/lshaped_cuts.py).
Here:

* the **root** is one small LP over [first-stage x, per-scenario eta] with a
  preallocated cut block (fixed shape -> one compiled program; inactive cut
  rows are free), solved by the batched ADMM kernel as a batch of 1;
* the **subproblems** are the whole scenario batch with nonant columns
  clamped to the root x (lb = ub = x_hat, the Xhat_Eval trick) and first-stage
  costs zeroed; ONE batched solve yields every Q_s(x_hat) *and* every cut
  gradient, because the clamp duals ``yx`` on the nonant columns are exactly
  -dQ_s/dx_hat (verified sign convention; replaces the per-scenario dual
  extraction of lshaped.py:508-679).

Multi-cut by default (one eta per scenario).  Feasibility cuts: scenarios the
batched clamp solve leaves infeasible get a host-exact phase-1 LP (elastic row
slacks, HiGHS duals); its value/subgradient become a feasibility cut
``g.x <= g.xhat - v`` (no eta term), matching the capability of
``mpisppy/opt/lshaped.py:380-506`` + ``utils/lshaped_cuts.py:1-85`` —
incomplete-recourse models are in scope.
"""

from __future__ import annotations

import numpy as np

from .. import global_toc
from ..solvers import admm
from ..spopt import SPOpt, batch_solve_dispatch, dispatch_A


class LShapedMethod(SPOpt):
    """(lshaped.py:23-143 constructor semantics; options: max_iter, tol,
    valid_eta_lb, verbose)."""

    def __init__(self, options, all_scenario_names, scenario_creator,
                 **kwargs):
        super().__init__(options, all_scenario_names, scenario_creator,
                         **kwargs)
        if self.tree.num_stages != 2:
            raise RuntimeError("LShapedMethod only supports two-stage models")
        self.max_iter = int(self.options.get("max_iter", 50))
        self.tol = float(self.options.get("tol", 1e-7))
        self.valid_eta_lb = self.options.get("valid_eta_lb")
        self.verbose = self.options.get("verbose", False)
        self.root_x = None
        self.outer_bound = -np.inf
        self.inner_bound = np.inf
        # the root LP is one tiny problem but its optimum sits at cut
        # intersections far from cold starts; give it a heavier budget and
        # warm-start it across Benders iterations
        import dataclasses

        self._root_settings = dataclasses.replace(
            self.admm_settings, max_iter=4000, restarts=8)
        self._root_warm = None

    # ---- root construction (lshaped.py:144-366) -----------------------------
    def _build_root(self):
        b = self.batch
        idx = self.tree.nonant_indices            # first-stage columns
        S = b.num_scenarios
        K = idx.shape[0]
        # first-stage rows: support entirely within the nonant columns
        mask = np.zeros(b.num_vars, dtype=bool)
        mask[idx] = True
        A0 = b.A[0]
        touches_stage2 = (np.abs(A0[:, ~mask]) > 0).any(axis=1)
        has_support = (np.abs(A0) > 0).any(axis=1)
        fs_rows = np.where(~touches_stage2 & has_support)[0]

        ncuts = self.max_iter * S
        nv = K + S                                 # [x, eta]
        nr = len(fs_rows) + ncuts
        A = np.zeros((nr, nv))
        cl = np.full(nr, -np.inf)
        cu = np.full(nr, np.inf)
        A[: len(fs_rows), :K] = A.dtype.type(0)
        A[: len(fs_rows), :K] = A0[np.ix_(fs_rows, idx)]
        cl[: len(fs_rows)] = b.cl[0, fs_rows]
        cu[: len(fs_rows)] = b.cu[0, fs_rows]

        c = np.zeros(nv)
        c[:K] = b.c[0, idx]                        # first-stage costs
        c[K:] = self.probs                         # E[eta]
        lb = np.zeros(nv)
        ub = np.zeros(nv)
        lb[:K] = b.lb[0, idx]
        ub[:K] = b.ub[0, idx]
        if self.valid_eta_lb is not None:
            eta_lb = np.full(S, float(self.valid_eta_lb))
        else:
            # valid per-scenario eta bound from one wait-and-see batched
            # solve with first-stage costs zeroed: Q_s(x) >= min over ALL
            # (x, y) of the second-stage objective (replaces the reference's
            # _create_root_with_scenarios eta-bound estimation)
            q = np.array(b.c, copy=True)
            q[:, idx] = 0.0
            sol = batch_solve_dispatch(b, q, b.q2, b.cl, b.cu, b.lb, b.ub,
                                       settings=self.admm_settings)
            x = np.asarray(sol.x)
            Qws = np.einsum("sn,sn->s", q, x) + 0.5 * np.einsum(
                "sn,sn->s", b.q2, x * x) + b.const
            eta_lb = Qws - 1e-3 * np.abs(Qws) - 1.0
        lb[K:] = eta_lb
        ub[K:] = np.inf

        self._root = {
            "A": A, "cl": cl, "cu": cu, "c": c, "lb": lb, "ub": ub,
            "n_fs_rows": len(fs_rows), "next_cut": len(fs_rows),
            "K": K, "S": S,
        }
        # seed the first root solve at (x=0, eta=eta_lb): without cuts that
        # is the optimum, and ADMM otherwise crawls the 1e5-scale eta range
        x0 = np.concatenate([np.zeros(K), eta_lb])[None]
        z0 = (A @ x0[0])[None]
        self._root_warm = (x0, z0, np.zeros((1, nr)), np.zeros((1, nv)))

    def _solve_root(self):
        """Solve the Benders root.

        Default backend is the exact host simplex (HiGHS): the root is ONE
        tiny SERIAL LP — the reference solves it with Gurobi on rank 0
        (lshaped.py:144-366) — and exactness matters doubly here because the
        root x is clamped into every subproblem (primal error in x makes the
        clamped batch infeasible by the same amount).  The TPU owns the
        batched subproblem solves, which is where the scenario-scaled work
        is; ``options["root_solver"]="admm"`` keeps the on-device path.
        """
        r = self._root
        if self.options.get("root_solver", "highs") == "admm":
            sol = admm.solve_batch(
                r["c"][None], np.zeros_like(r["c"])[None], r["A"][None],
                r["cl"][None], r["cu"][None], r["lb"][None], r["ub"][None],
                settings=self._root_settings, warm=self._root_warm,
            )
            self._root_warm = sol.raw
            self._root_loose = (float(sol.dua_res[0]) > 1e-4
                                or float(sol.pri_res[0]) > 1e-4)
            if self._root_loose:
                global_toc(
                    f"WARNING: L-shaped root solve loose (pri "
                    f"{float(sol.pri_res[0]):.2e} "
                    f"dua {float(sol.dua_res[0]):.2e})", True)
            x = np.asarray(sol.x[0])
        else:
            from ..solvers import scipy_backend

            res = scipy_backend.solve_lp(
                r["c"], r["A"], r["cl"], r["cu"], r["lb"], r["ub"])
            if not res.feasible:
                raise RuntimeError(
                    f"L-shaped root LP solve failed: {res.status}")
            self._root_loose = False
            x = np.asarray(res.x)
        K = r["K"]
        return x[:K], x[K:], float(r["c"] @ x)

    # ---- subproblems (lshaped.py:380-506 collapsed to one batched solve) ----
    def _phase1(self, s, xhat):
        """Host-exact phase-1 LP for one clamped scenario: minimize the
        1-norm of elastic row slacks.  Returns (violation v >= 0, subgradient
        g = dv/dxhat (K,)) — the feasibility-cut data (the reference gets the
        same from its solver's Farkas/infeasibility certificate through
        pyomo.contrib.benders; an elastic phase-1 is the solver-agnostic
        equivalent)."""
        from ..solvers import scipy_backend

        b = self.batch
        idx = self.tree.nonant_indices
        m, n = b.A[s].shape
        A_aug = np.hstack([b.A[s], np.eye(m), -np.eye(m)])
        c_aug = np.concatenate([np.zeros(n), np.ones(2 * m)])
        lb = np.array(b.lb[s], copy=True)
        ub = np.array(b.ub[s], copy=True)
        lb[idx] = xhat
        ub[idx] = xhat
        lb_aug = np.concatenate([lb, np.zeros(2 * m)])
        ub_aug = np.concatenate([ub, np.full(2 * m, np.inf)])
        res = scipy_backend.solve_lp_with_duals(
            c_aug, A_aug, b.cl[s], b.cu[s], lb_aug, ub_aug)
        if not res.feasible or res.duals is None:
            raise RuntimeError(
                f"phase-1 LP unsolvable for {self.all_scenario_names[s]}")
        v = float(c_aug @ res.x)
        # weak-duality cut construction (see _solve_subproblems): for any
        # duals y, v(x̂') >= base + g[idx].x̂'; feasibility then requires
        # base + g.x <= 0
        from ..spopt import _np_dual_cut, _pick_dual_sign

        ys = _pick_dual_sign(c_aug, A_aug, b.cl[s], b.cu[s],
                             lb_aug, ub_aug, res.duals, res.x, v)
        mask = np.zeros(A_aug.shape[1], dtype=bool)
        mask[idx] = True
        base, g = _np_dual_cut(c_aug, A_aug, b.cl[s], b.cu[s],
                               lb_aug, ub_aug, ys, res.x, mask)
        return base, g[idx]

    def _host_exact_sub(self, s, q, lb, ub):
        """Host-exact clamped-subproblem solve (straggler path): returns
        (feasible, Q_s, cut_base, grad (K,)) with exact simplex duals."""
        from ..spopt import host_exact_clamp_cut

        return host_exact_clamp_cut(self.batch, q, s, lb, ub,
                                    self.tree.nonant_indices)

    def _solve_subproblems(self, xhat):
        """Returns (Q (S,), gradients (S, K), feasible, feas_cuts list)."""
        b = self.batch
        idx = self.tree.nonant_indices
        q = np.array(b.c, copy=True)
        q[:, idx] = 0.0                            # first-stage cost in root
        lb = np.array(b.lb, copy=True)
        ub = np.array(b.ub, copy=True)
        lb[:, idx] = xhat[None, :]
        ub[:, idx] = xhat[None, :]
        sol = batch_solve_dispatch(b, q, b.q2, b.cl, b.cu, lb, ub,
                                   settings=self.admm_settings)
        pri = np.asarray(sol.pri_res)
        tol = max(self.options.get("feas_tol", 1e-3),
                  10.0 * self.admm_settings.eps_rel)
        x = np.asarray(sol.x)
        Q = np.einsum("sn,sn->s", q, x) + 0.5 * np.einsum(
            "sn,sn->s", b.q2, x * x) + b.const
        # cut data via the weak-duality construction (admm.dual_cut): valid
        # for ANY duals — raw clamp duals -yx can be sign-infeasible at
        # DEGENERATE clamped optima (stationarity holds, residuals can't see
        # it) and then cut off the true optimum
        import jax.numpy as jnp

        dt = self.admm_settings.jdtype()
        cut_base, g_full = admm.dual_cut(
            jnp.asarray(q, dt), jnp.asarray(b.q2, dt),
            jnp.asarray(np.asarray(dispatch_A(b)), dt),
            jnp.asarray(b.cl, dt), jnp.asarray(b.cu, dt),
            jnp.asarray(lb, dt), jnp.asarray(ub, dt),
            sol.y, sol.x, jnp.asarray(b.nonant_mask()))
        cut_base = np.asarray(cut_base, dtype=float) + b.const
        grads = np.asarray(g_full, dtype=float)[:, idx]
        # weak-duality cut TIGHTNESS check: gap_s = Q_s - cut-value-at-x̂ is
        # >= 0 by construction and ~0 when the batch duals are exact and
        # sign-feasible; a large gap flags degenerate/stalled duals, where
        # the exact simplex fallback restores a tight (still valid) cut
        gap_w = Q - (cut_base + grads @ xhat)
        cut_tol = 1e-5 * (1.0 + np.abs(Q))
        # scenarios the batch left unconverged (or with loose cuts):
        # host-exact re-solve decides feasibility + tightens the cut; truly
        # infeasible ones yield phase-1 feasibility cuts
        feas_cuts = []
        skip_opt = set()                           # no optimality cut from
        feasible = True                            # infeasible scenarios
        gross = max(1e3 * tol, 1.0)
        for s in np.flatnonzero((pri > tol) | (gap_w > cut_tol)):
            if np.any(b.q2[s] != 0.0):
                if pri[s] > gross:
                    # QP scenario with a grossly infeasible clamp: there is
                    # no host-exact LP path and no feasibility-cut support
                    # for QPs — fail loudly rather than looping to max_iter
                    raise RuntimeError(
                        "L-shaped QP subproblem infeasible at root x: "
                        f"{self.all_scenario_names[s]} (pri {pri[s]:.2e}; "
                        "ensure complete recourse for QP scenarios)")
                if pri[s] > tol:                   # QP scenario: no host path
                    feasible = False
                continue
            ok, Qs, cb, gs = self._host_exact_sub(s, q, lb, ub)
            if ok:
                Q[s], cut_base[s], grads[s] = Qs, cb, gs
            else:
                feasible = False
                skip_opt.add(int(s))
                base_f, gf = self._phase1(s, xhat)
                feas_cuts.append((base_f, gf))
                global_toc(
                    f"L-shaped: feasibility cut from "
                    f"{self.all_scenario_names[s]} "
                    f"(violation {base_f + gf @ xhat:.3e})",
                    self.verbose)
        for s in skip_opt:
            Q[s] = np.inf          # candidate is infeasible: honest ub = inf
        return Q, cut_base, grads, feasible, feas_cuts, skip_opt

    def _add_cuts(self, xhat, cut_base, grads, feas_cuts=(), skip_opt=()):
        """eta_s >= cut_base_s + g_s.x as rows of the root cut block;
        feasibility cuts ``g.x <= g.xhat - v`` use no eta column."""
        r = self._root
        K, S = r["K"], r["S"]
        for s in range(S):
            if s in skip_opt:                      # infeasible: junk Q/grad
                continue
            row = r["next_cut"]
            if row >= r["A"].shape[0]:
                return  # cut capacity exhausted; root keeps old cuts
            r["A"][row, :K] = -grads[s]
            r["A"][row, K + s] = 1.0
            r["cl"][row] = cut_base[s]
            r["cu"][row] = np.inf
            r["next_cut"] += 1
        for base, g in feas_cuts:
            row = r["next_cut"]
            if row >= r["A"].shape[0]:
                return
            # 0 >= base + g.x  (weak-duality phase-1 cut; see _phase1)
            r["A"][row, :K] = g
            r["cl"][row] = -np.inf
            r["cu"][row] = float(-base)
            r["next_cut"] += 1

    # ---- driver (lshaped.py:508-679) ---------------------------------------
    def lshaped_algorithm(self):
        self._build_root()
        b = self.batch
        idx = self.tree.nonant_indices
        for it in range(1, self.max_iter + 1):
            xhat, eta, root_obj = self._solve_root()
            if not self._root_loose:
                self.outer_bound = root_obj        # certified lower bound
            Q, cut_base, grads, feasible, feas_cuts, skip_opt = \
                self._solve_subproblems(xhat)
            ub_val = float(b.c[0, idx] @ xhat + self.probs @ Q)
            if feasible:
                # only certified-feasible evaluations move the incumbent
                self.inner_bound = min(self.inner_bound, ub_val)
            self.root_x = xhat
            gap = ub_val - root_obj
            global_toc(
                f"L-shaped iter {it} lb {root_obj:.6f} ub {ub_val:.6f} "
                f"gap {gap:.3e} fcuts {len(feas_cuts)}", self.verbose)
            if self.spcomm is not None:
                self.spcomm.sync()
                if self.spcomm.is_converged():
                    break
            if feasible and gap <= self.tol * max(1.0, abs(ub_val)):
                break
            self._add_cuts(xhat, cut_base, grads, feas_cuts, skip_opt)
        # final full solve at root x for solution reporting
        self.fix_nonants(xhat)
        try:
            self.solve_loop(warm=False)
        finally:
            self.restore_nonants()
        self.first_stage_solution_available = True
        return self.outer_bound

    # hub-facing aliases
    def lshaped_prep(self):
        self._build_root()
