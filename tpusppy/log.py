"""Compatibility shim: logging moved into :mod:`tpusppy.obs.log`.

The observability subsystem owns the logger factory now — one
``get_logger(name)`` with the ``[track] message`` format and the
``TPUSPPY_LOG_LEVEL`` env knob.  This module keeps the historical import
surface (``tpusppy.log.logger`` / ``setup_logger``, the analogue of
``mpisppy/log.py:52-67``) pointing at the same objects.
"""

from __future__ import annotations

from .obs.log import get_logger, root as logger, set_level, setup_logger

log_format = "%(message)s"   # historical constant (pre-obs consumers)

__all__ = ["get_logger", "logger", "set_level", "setup_logger",
           "log_format"]
