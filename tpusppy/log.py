"""Per-module logger configuration.

Analogue of ``mpisppy/log.py:52-67``: a root ``tpusppy`` logger writing
messages to stdout at INFO, plus :func:`setup_logger` for components that
want their own stream/file logger (the reference's hub/spoke modules create
``hub.log``-style CRITICAL loggers this way; ours do the same through this
factory).
"""

from __future__ import annotations

import logging
import sys

log_format = "%(message)s"

logger = logging.getLogger("tpusppy")
logger.setLevel(logging.INFO)
if not logger.handlers:
    _h = logging.StreamHandler(sys.stdout)
    _h.setFormatter(logging.Formatter(log_format))
    logger.addHandler(_h)


def setup_logger(name, out, level=logging.DEBUG, mode="w", fmt=None):
    """Set up a custom logger quickly (mpisppy/log.py:52-67 semantics):
    ``out`` is a stream (stdout/stderr) or a filename."""
    if fmt is None:
        fmt = "(%(asctime)s) %(message)s"
    lg = logging.getLogger(name)
    lg.setLevel(level)
    lg.propagate = False
    formatter = logging.Formatter(fmt)
    if out in (sys.stdout, sys.stderr):
        handler = logging.StreamHandler(out)
    else:
        handler = logging.FileHandler(out, mode=mode)
    handler.setFormatter(formatter)
    lg.addHandler(handler)
    return lg
