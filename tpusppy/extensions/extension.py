"""Extension plugin ABC — hub-side callout points.

Mirrors ``mpisppy/extensions/extension.py:12-169``: the same 11 callout points,
called from PHBase.Iter0/iterk_loop and SPOpt.solve_loop, plus MultiExtension
composition.  Extensions receive the opt object (``self.opt``) and may read or
mutate PH state arrays (W, rho, xbar, local_x ...).
"""


class Extension:
    """Base class; subclasses override any subset of the callouts."""

    def __init__(self, spopt_object):
        self.opt = spopt_object

    def pre_solve(self):            # before each batch solve
        pass

    def post_solve(self):           # after each batch solve
        pass

    def pre_solve_loop(self):
        pass

    def post_solve_loop(self):
        pass

    def pre_iter0(self):
        pass

    def post_iter0(self):
        pass

    def post_iter0_after_sync(self):
        pass

    def miditer(self):              # after xbar/W update, before the solve
        pass

    def enditer(self):              # after the solve
        pass

    def enditer_after_sync(self):
        pass

    def post_everything(self):
        pass


class MultiExtension(Extension):
    """Compose several extensions (extension.py:113-169)."""

    def __init__(self, spopt_object, ext_classes=None):
        super().__init__(spopt_object)
        ext_classes = ext_classes or spopt_object.options.get("ext_classes", [])
        self.extensions = [cls(spopt_object) for cls in ext_classes]

    def __getattribute__(self, name):
        callouts = {
            "pre_solve", "post_solve", "pre_solve_loop", "post_solve_loop",
            "pre_iter0", "post_iter0", "post_iter0_after_sync",
            "miditer", "enditer", "enditer_after_sync", "post_everything",
        }
        if name in callouts:
            exts = object.__getattribute__(self, "extensions")

            def fanout():
                for e in exts:
                    getattr(e, name)()

            return fanout
        return object.__getattribute__(self, name)
