"""MultRhoUpdater: hold rho at a constant multiple of the convergence metric.

TPU-native analogue of ``mpisppy/extensions/mult_rho_updater.py:29-106``:
rho_k = rho0_k * conv0 / conv_t, updated only when convergence improves, with
optional start/stop iteration gates.
"""

from __future__ import annotations

import numpy as np

from .extension import Extension

_mult_rho_defaults = {
    "convergence_tolerance": 1e-4,
    "rho_update_stop_iteration": None,
    "rho_update_start_iteration": None,
    "verbose": False,
}


class MultRhoUpdater(Extension):
    def __init__(self, opt):
        super().__init__(opt)
        options = opt.options.get("mult_rho_options", {})
        g = lambda k: options.get(k, _mult_rho_defaults[k])
        self._tol = g("convergence_tolerance")
        self._stop_iter = g("rho_update_stop_iteration")
        self._start_iter = g("rho_update_start_iteration")
        self._verbose = g("verbose")
        self._first_rho = None
        self.first_c = None
        self.best_conv = float("inf")

    def _conv(self):
        conv_obj = getattr(self.opt, "ph_converger", None)
        if conv_obj is not None and getattr(conv_obj, "conv", None) is not None:
            return conv_obj.conv
        return self.opt.conv

    def miditer(self):
        opt = self.opt
        it = opt._iter
        if (self._stop_iter is not None and it > self._stop_iter) or \
                (self._start_iter is not None and it < self._start_iter):
            return
        conv = self._conv()
        if conv is None:
            return
        if conv < self.best_conv:
            self.best_conv = conv
        else:
            return  # only act on a new best
        if self._first_rho is None:
            if conv == self._tol:
                return
            self.first_c = conv
            self._first_rho = np.array(opt.rho, copy=True)
        elif conv != 0:
            opt.rho = self._first_rho * (self.first_c / conv)
            if self._verbose:
                print(f"MultRhoUpdater iter={it}; rho[0,0] now "
                      f"{opt.rho[0, 0]}")
