"""Wtracker extension: record Ws each iteration, report at the end.

TPU-native analogue of ``mpisppy/extensions/wtracker_extension.py`` (53 LoC).
Options (``opt.options["wtracker_options"]``): wlen, reportlen, stdevthresh,
file_prefix.
"""

from __future__ import annotations

from .extension import Extension
from ..utils.wtracker import WTracker


class Wtracker_extension(Extension):
    def __init__(self, opt):
        super().__init__(opt)
        wo = opt.options.get("wtracker_options", {})
        self.wlen = wo.get("wlen", 20)
        self.reportlen = wo.get("reportlen", 100)
        self.stdevthresh = wo.get("stdevthresh")
        self.file_prefix = wo.get("file_prefix", "")
        self.wtracker = WTracker(opt)

    def enditer(self):
        self.wtracker.grab_local_Ws()

    def post_everything(self):
        if self.file_prefix:
            self.wtracker.write_or_append_to_csv(
                f"{self.file_prefix}_wtracker.csv")
        self.wtracker.report_by_moving_stats(
            self.wlen, reportlen=self.reportlen,
            stdevthresh=self.stdevthresh)
