"""XhatXbar: evaluate (rounded) xbar as the incumbent candidate.

Analogue of ``mpisppy/extensions/xhatxbar.py`` and the spoke at
``cylinders/xhatxbar_bounder.py:31``: xbar is already nonanticipative by
construction, so the candidate cache is just the per-scenario xbars (integers
are rounded inside ``fix_nonants``).
"""

from __future__ import annotations

from .xhatbase import XhatBase


class XhatXbar(XhatBase):
    def _try(self):
        xbars = getattr(self.opt, "xbars", None)
        if xbars is None:
            return None
        obj = self._try_one(xbars)
        self._update_if_improving(obj, xbars)
        return obj

    def post_iter0(self):
        self._try()

    def enditer(self):
        self._try()
