"""PHTracker: per-iteration CSV tracking of gaps, bounds, nonants, Ws, rhos.

TPU-native analogue of ``mpisppy/extensions/phtracker.py`` (510 LoC;
``TrackedData:14``): each enabled track writes one CSV row per PH iteration
under ``options["phtracker_options"]["results_folder"]/<cylinder_name>/``,
and ``plot_results`` renders the convergence curves when matplotlib is
available.

Options (mirroring ``tracking_args``, config.py:673-706): track_convergence,
track_xbars, track_duals, track_nonants, track_scen_gaps — integers giving
the tracking period (0 disables).
"""

from __future__ import annotations

import csv
import os

import numpy as np

from .extension import Extension


class TrackedData:
    """One CSV-backed track (phtracker.py:14-110)."""

    def __init__(self, name, folder, plot=False, verbose=False):
        self.name = name
        self.folder = folder
        self.plot = plot
        self.verbose = verbose
        self.fname = None
        self.plot_fname = None
        self.columns = None
        self.rows = []

    def initialize_fnames(self, name=None):
        base = name or self.name
        self.fname = os.path.join(self.folder, base + ".csv")
        self.plot_fname = os.path.join(self.folder, base + ".png")

    def initialize_df(self, columns):
        self.columns = list(columns)

    def add_row(self, row):
        self.rows.append(list(row))

    def write_out_data(self):
        new_file = not os.path.exists(self.fname)
        with open(self.fname, "a", newline="") as f:
            w = csv.writer(f)
            if new_file and self.columns:
                w.writerow(self.columns)
            w.writerows(self.rows)
        self.rows = []


class PHTracker(Extension):
    def __init__(self, opt):
        super().__init__(opt)
        topt = opt.options.get("phtracker_options", {})
        cylinder_name = topt.get("cylinder_name", "hub")
        folder = os.path.join(topt.get("results_folder", "results"),
                              cylinder_name)
        os.makedirs(folder, exist_ok=True)
        self.folder = folder
        g = lambda k: int(opt.options.get(k, topt.get(k, 0)) or 0)
        self.periods = {
            "convergence": g("track_convergence"),
            "xbars": g("track_xbars"),
            "duals": g("track_duals"),
            "nonants": g("track_nonants"),
            "scen_gaps": g("track_scen_gaps"),
        }
        self.tracks = {}
        for name, period in self.periods.items():
            if period > 0:
                t = TrackedData(name, folder)
                t.initialize_fnames()
                self.tracks[name] = t
        if "convergence" in self.tracks:
            self.tracks["convergence"].initialize_df(
                ["iteration", "conv", "best_outer", "best_inner",
                 "abs_gap", "rel_gap"])
        K = opt.nonant_length
        for name in ("xbars", "duals", "nonants"):
            if name in self.tracks:
                self.tracks[name].initialize_df(
                    ["iteration"] + [f"k{k}" for k in range(K)])
        if "scen_gaps" in self.tracks:
            self.tracks["scen_gaps"].initialize_df(
                ["iteration"] + list(opt.all_scenario_names))

    def _due(self, name):
        p = self.periods.get(name, 0)
        return name in self.tracks and p > 0 and self.opt._iter % p == 0

    def _snapshot(self):
        opt = self.opt
        it = opt._iter
        if self._due("convergence"):
            spcomm = getattr(opt, "spcomm", None)
            if spcomm is not None and hasattr(spcomm, "compute_gaps"):
                abs_gap, rel_gap = spcomm.compute_gaps()
                ob, ib = spcomm.BestOuterBound, spcomm.BestInnerBound
            else:
                abs_gap = rel_gap = np.nan
                ob = ib = np.nan
            self.tracks["convergence"].add_row(
                [it, opt.conv, ob, ib, abs_gap, rel_gap])
        if self._due("xbars"):
            self.tracks["xbars"].add_row([it] + list(opt.xbars[0]))
        if self._due("duals"):
            self.tracks["duals"].add_row([it] + list(opt.W.mean(axis=0)))
        if self._due("nonants") and opt.local_x is not None:
            xk = opt.nonants_of(opt.local_x)
            self.tracks["nonants"].add_row([it] + list(xk.mean(axis=0)))
        if self._due("scen_gaps") and opt.local_x is not None:
            objs = opt.batch.objective(opt.local_x)
            self.tracks["scen_gaps"].add_row([it] + list(objs))
        for t in self.tracks.values():
            if t.rows:
                t.write_out_data()

    def post_iter0(self):
        self._snapshot()

    def enditer_after_sync(self):
        self._snapshot()

    def enditer(self):
        if getattr(self.opt, "spcomm", None) is None:
            self._snapshot()

    def post_everything(self):
        self.plot_results()

    def plot_results(self):
        """Render convergence curves if matplotlib is present
        (phtracker.py plot path)."""
        t = self.tracks.get("convergence")
        if t is None or not os.path.exists(t.fname):
            return
        try:
            import matplotlib

            matplotlib.use("Agg")
            import matplotlib.pyplot as plt
        except Exception:
            return
        data = np.genfromtxt(t.fname, delimiter=",", names=True)
        if data.size < 2:
            return
        plt.figure()
        plt.semilogy(data["iteration"], np.abs(data["conv"]), label="conv")
        plt.xlabel("Iteration")
        plt.ylabel("Convergence metric")
        plt.legend()
        plt.savefig(t.plot_fname)
        plt.close()
