"""NormRhoUpdater: adaptive per-slot rho from primal/dual residual balance.

TPU-native analogue of ``mpisppy/extensions/norm_rho_updater.py:33-164``
(adapted there from PySP's adaptive_rho_converger).  Per nonant slot:
primal residual = sum_s p_s |x_sk - xbar_sk| (node-grouped), dual residual =
rho * |xbar_t - xbar_{t-1}|; rho is increased when primal dominates, decreased
when dual dominates, gently decreased when both are converged.  All slots
update in one vectorized sweep.
"""

from __future__ import annotations

import numpy as np

from .extension import Extension

_norm_rho_defaults = {
    "convergence_tolerance": 1e-4,
    "rho_decrease_multiplier": 2.0,
    "rho_increase_multiplier": 2.0,
    "primal_dual_difference_factor": 100.0,
    "iterations_converged_before_decrease": 0,
    "rho_converged_decrease_multiplier": 1.1,
    "rho_update_stop_iterations": None,
    "verbose": False,
}


class NormRhoUpdater(Extension):
    def __init__(self, opt):
        super().__init__(opt)
        options = opt.options.get("norm_rho_options", {})
        g = lambda k: options.get(k, _norm_rho_defaults[k])
        self._tol = g("convergence_tolerance")
        self._rho_decrease = g("rho_decrease_multiplier")
        self._rho_increase = g("rho_increase_multiplier")
        self._pd_factor = g("primal_dual_difference_factor")
        self._required_converged_before_decrease = g(
            "iterations_converged_before_decrease")
        self._rho_converged_residual_decrease = g(
            "rho_converged_decrease_multiplier")
        self._stop_iter_rho_update = g("rho_update_stop_iterations")
        self._verbose = g("verbose")
        self._prev_avg = None
        opt._norm_rho_update_inuse = True   # allow NormRhoConverger

    def _primal_residuals(self) -> np.ndarray:
        """(S, K): per-slot node-grouped weighted L1 residual, broadcast back
        to every member scenario (norm_rho_updater.py:55-97)."""
        opt = self.opt
        xk = opt.nonants_of(opt.local_x)
        onehot = opt.tree.onehot_sk_n()
        p = opt.probs[:, None]
        resid_nk = np.einsum("skn,sk->nk", onehot, p * np.abs(xk - opt.xbars))
        kidx = np.arange(xk.shape[1])[None, :]
        return resid_nk[opt.nid_sk, kidx]

    def miditer(self):
        opt = self.opt
        if self._stop_iter_rho_update is not None and \
                opt._iter > self._stop_iter_rho_update:
            return
        if self._prev_avg is None:
            self._prev_avg = np.array(opt.xbars, copy=True)
            return
        primal = self._primal_residuals()
        dual = opt.rho * np.abs(opt.xbars - self._prev_avg)
        self._prev_avg = np.array(opt.xbars, copy=True)

        inc = (primal > self._pd_factor * dual) & (primal > self._tol)
        dec = (dual > self._pd_factor * primal) & (dual > self._tol) & (
            opt._iter >= self._required_converged_before_decrease)
        conv = (primal < self._tol) & (dual < self._tol)
        rho = opt.rho
        rho = np.where(inc, rho * self._rho_increase, rho)
        rho = np.where(~inc & dec, rho / self._rho_decrease, rho)
        rho = np.where(~inc & ~dec & conv,
                       rho / self._rho_converged_residual_decrease, rho)
        opt.rho = rho
        if self._verbose:
            n_inc, n_dec = int(inc.sum()), int((~inc & dec).sum())
            print(f"NormRhoUpdater iter={opt._iter}: "
                  f"increased {n_inc}, decreased {n_dec} rho entries")
