"""XhatLooper: try scenarios in order as xhat candidates each iteration.

Analogue of ``mpisppy/extensions/xhatlooper.py`` (and the simple looper spoke,
cylinders/xhatlooper_bounder.py:12): after iter0 and after each PH iteration,
walk up to ``xhat_looper_options["scen_limit"]`` scenarios, evaluate each as an
incumbent candidate, and keep the best.
"""

from __future__ import annotations

from .xhatbase import XhatBase


class XhatLooper(XhatBase):
    def __init__(self, spopt_object):
        super().__init__(spopt_object)
        xo = self.opt.options.get("xhat_looper_options", {})
        self.scen_limit = int(xo.get("scen_limit", 1))
        self._next = 0

    def _loop(self):
        S = self.opt.batch.num_scenarios
        for _ in range(min(self.scen_limit, S)):
            self.try_scenario(self._next % S)
            self._next += 1

    def post_iter0(self):
        self._loop()

    def enditer(self):
        self._loop()
