"""Fixer: WW-style fixing of (integer) nonants on convergence signatures.

TPU-native analogue of ``mpisppy/extensions/fixer.py:20-330``.  A slot is a
candidate when sqrt|xsqbar - xbar^2| < threshold (scenarios agree); counts of
consecutive converged iterations drive fixing (nb), with variants requiring
the value to also sit at the variable's lower (lb) or upper (ub) bound.
Fixing is a persistent clamp of the batch bound columns (lb = ub = value) —
the batched analogue of ``xvar.fix()``.

Options (``opt.options["fixeroptions"]``):
  id_fix_list_fct: callable(batch) -> (iter0_tuples, iterk_tuples), each a
    list of ``(slot, th, nb, lb, ub)`` over *nonant slot indices* (the IR
    analogue of Pyomo var ids); or pass the lists directly as
    ``iter0_fixer_tuples`` / ``fixer_tuples``.
  boundtol: tolerance for "at its bound".
"""

from __future__ import annotations

import numpy as np

from .extension import Extension


def Fixer_tuple(slot, th=None, nb=None, lb=None, ub=None):
    """Self-documenting tuple maker (fixer.py:20-48); ``slot`` is a nonant
    slot index (reference passes id(xvar))."""
    if th is None and nb is None and lb is None and ub is None:
        print(f"warning: Fixer_tuple called for slot={slot} "
              "but no arguments were given")
    return (int(slot), 0.0 if th is None else th, nb, lb, ub)


class Fixer(Extension):
    def __init__(self, opt):
        super().__init__(opt)
        fo = opt.options["fixeroptions"]
        self.verbose = opt.options.get("verbose", False) or fo.get(
            "verbose", False)
        self.boundtol = fo["boundtol"]
        if "id_fix_list_fct" in fo and fo["id_fix_list_fct"] is not None:
            self.iter0_tuples, self.iterk_tuples = fo["id_fix_list_fct"](
                opt.batch)
        else:
            self.iter0_tuples = fo.get("iter0_fixer_tuples") or []
            self.iterk_tuples = fo.get("fixer_tuples") or []
        K = opt.nonant_length
        self.conv_iter_count = np.zeros(K, dtype=np.int64)
        self.fixed = np.zeros(K, dtype=bool)
        self.fixed_so_far = 0

    # ---- the fixing primitive ----------------------------------------------
    def _fix_slots(self, slots: np.ndarray, values: np.ndarray):
        """Persistently clamp nonant slots across all scenarios
        (fixer.py _update_fix_counts/_fix_loop collapsed to one clamp)."""
        opt = self.opt
        idx = opt.tree.nonant_indices[slots]
        ints = opt.batch.is_int[idx]
        values = np.where(ints, np.round(values), values)
        # respect original bounds
        values = np.clip(values, opt.batch.lb[:, idx], opt.batch.ub[:, idx])
        opt._ensure_private_batch()   # never write through a cache-shared batch
        opt.batch.lb[:, idx] = values
        opt.batch.ub[:, idx] = values
        self.fixed[slots] = True
        self.fixed_so_far += len(slots)
        if self.verbose:
            print(f"Fixer: fixed slots {list(slots)} "
                  f"(total {self.fixed_so_far})")

    def _sqrt_dev(self) -> np.ndarray:
        """(S, K) sqrt|xsqbar - xbar^2| — the WW convergence signature."""
        opt = self.opt
        return np.sqrt(np.abs(opt.xsqbars - opt.xbars * opt.xbars))

    def _apply_tuples(self, tuples, use_counts: bool):
        opt = self.opt
        dev = self._sqrt_dev().max(axis=0)          # (K,) worst over scenarios
        xbar = opt.xbars[0]                          # nonanticipative per node
        idx = opt.tree.nonant_indices
        varlb = opt.batch.lb[0, idx]
        varub = opt.batch.ub[0, idx]
        to_fix, fix_vals = [], []
        for (slot, th, nb, lb, ub) in tuples:
            if self.fixed[slot]:
                continue
            conv = dev[slot] <= th
            at_lb = conv and abs(xbar[slot] - varlb[slot]) <= self.boundtol
            at_ub = conv and abs(xbar[slot] - varub[slot]) <= self.boundtol
            if use_counts:
                self.conv_iter_count[slot] = (
                    self.conv_iter_count[slot] + 1 if conv else 0
                )
                cnt = self.conv_iter_count[slot]
                trigger = (
                    (nb is not None and conv and cnt >= nb)
                    or (lb is not None and at_lb and cnt >= lb)
                    or (ub is not None and at_ub and cnt >= ub)
                )
            else:
                trigger = (
                    (nb is not None and conv)
                    or (lb is not None and at_lb)
                    or (ub is not None and at_ub)
                )
            if trigger:
                to_fix.append(slot)
                fix_vals.append(xbar[slot])
        if to_fix:
            self._fix_slots(np.asarray(to_fix), np.asarray(fix_vals))

    def post_iter0(self):
        if self.iter0_tuples:
            self._apply_tuples(self.iter0_tuples, use_counts=False)

    def miditer(self):
        if self.iterk_tuples:
            self._apply_tuples(self.iterk_tuples, use_counts=True)

    def post_everything(self):
        if self.verbose:
            print(f"Fixer: {self.fixed_so_far} slots fixed in total")
