"""WXBarWriter: checkpoint W / xbar each iteration (or at the end).

TPU-native analogue of ``mpisppy/utils/wxbarwriter.py`` (an Extension in the
reference's utils): options ``W_fname`` / ``Xbar_fname`` /
``separate_W_files``.
"""

from __future__ import annotations

import os

from .extension import Extension
from ..utils import wxbarutils


class WXBarWriter(Extension):
    def __init__(self, opt):
        super().__init__(opt)
        self.W_fname = opt.options.get("W_fname")
        self.Xbar_fname = opt.options.get("Xbar_fname")
        self.sep_files = opt.options.get("separate_W_files", False)
        # start fresh (the writers append per iteration)
        for fname in (self.W_fname, self.Xbar_fname):
            if fname and not self.sep_files and os.path.exists(fname):
                os.remove(fname)

    def enditer(self):
        if self.W_fname:
            wxbarutils.write_W_to_file(self.opt, self.W_fname,
                                       sep_files=self.sep_files)
        if self.Xbar_fname:
            wxbarutils.write_xbar_to_file(self.opt, self.Xbar_fname)
