"""WXBarWriter: checkpoint W / xbar each iteration (or at the end).

TPU-native analogue of ``mpisppy/utils/wxbarwriter.py`` (an Extension in
the reference's utils): options ``W_fname`` / ``Xbar_fname`` /
``separate_W_files``.

Routed through the resilience checkpoint engine
(:func:`tpusppy.resilience.checkpoint.write_wxbar`): a ``.npz`` target
gets a REAL checkpoint — atomic write-tmp-then-rename, versioned, W and
xbar (and rho) together, loadable by ``WheelSpinner(resume=...)`` — while
csv targets keep the reference's append-per-iteration
``scenario,varname,value`` format byte-compatible for mpi-sppy
interchange (the golden round-trip is pinned in tests/test_resilience).
"""

from __future__ import annotations

import os

from ..resilience import checkpoint as _checkpoint
from .extension import Extension


class WXBarWriter(Extension):
    def __init__(self, opt):
        super().__init__(opt)
        self.W_fname = opt.options.get("W_fname")
        self.Xbar_fname = opt.options.get("Xbar_fname")
        self.sep_files = opt.options.get("separate_W_files", False)
        # start fresh (the csv writers append per iteration; npz
        # checkpoints replace atomically and need no unlink)
        for fname in (self.W_fname, self.Xbar_fname):
            if (fname and not self.sep_files
                    and not str(fname).endswith(".npz")
                    and os.path.exists(fname)):
                os.remove(fname)

    def enditer(self):
        if self.W_fname or self.Xbar_fname:
            _checkpoint.write_wxbar(self.opt, self.W_fname, self.Xbar_fname,
                                    sep_files=self.sep_files)
