"""CrossScenarioExtension: hub-side management of cross-scenario cuts.

TPU-native analogue of ``mpisppy/extensions/cross_scen_extension.py:16`` (283
LoC).  The reference distributes Benders rows into every scenario model
(an eta variable per scenario inside each subproblem).  In the batched
runtime the same information is exploited WITHOUT reshaping the device batch:
the accumulated cuts define a host-side cutting-plane relaxation

    min_x  sum_s p_s eta_s
    s.t.   eta_s >= g_s . x + c_s          (every accumulated cut)
           x in the first-stage feasible set

whose optimum is a certified OUTER bound the hub reports each iteration —
the cuts tighten it monotonically, which is the role the reference's
`boundsout` path plays (cross_scen_hub.py:11).
"""

from __future__ import annotations

import numpy as np

from .extension import Extension


class CrossScenarioExtension(Extension):
    def __init__(self, opt):
        super().__init__(opt)
        so = opt.options.get("cross_scen_options", {})
        self.check_bound_iterations = so.get("check_bound_improve_iterations",
                                             4)
        self._cuts = []            # list of (S, K+1) arrays
        self._last_lb = -np.inf

    def add_cuts(self, rows: np.ndarray):
        """Accept a (S, K+1) payload from the cut spoke (NaN rows dropped)."""
        rows = rows[~np.isnan(rows).any(axis=1)]
        if rows.size:
            self._cuts.append(rows)

    def compute_outer_bound(self):
        """Solve the host cutting-plane LP; returns the bound or None."""
        if not self._cuts:
            return None
        from ..solvers import scipy_backend

        opt = self.opt
        b = opt.batch
        idx = opt.tree.nonant_indices
        K = idx.shape[0]
        S = b.num_scenarios
        cuts = np.concatenate(self._cuts, axis=0)   # (C, K+1) but per-scen?
        # rebuild per-scenario cut lists: rows arrive S at a time in order
        ncut_rounds = len(self._cuts)
        nv = K + S
        rows = []
        cl, cu = [], []
        # first-stage rows from scenario 0 (support within nonant columns)
        mask = np.zeros(b.num_vars, dtype=bool)
        mask[idx] = True
        A0 = b.A[0]
        fs = ~(np.abs(A0[:, ~mask]) > 0).any(axis=1) & (np.abs(A0) > 0).any(
            axis=1)
        for r in np.where(fs)[0]:
            row = np.zeros(nv)
            row[:K] = A0[r, idx]
            rows.append(row)
            cl.append(b.cl[0, r])
            cu.append(b.cu[0, r])
        for rnd in self._cuts:
            for s in range(rnd.shape[0]):
                if np.isnan(rnd[s]).any():
                    continue
                row = np.zeros(nv)
                row[:K] = -rnd[s, :K]
                row[K + s] = 1.0
                rows.append(row)
                cl.append(rnd[s, K])
                cu.append(np.inf)
        if len(rows) <= fs.sum():
            return None
        A = np.stack(rows)
        c = np.zeros(nv)
        c[K:] = opt.probs
        lbv = np.concatenate([b.lb[0, idx], np.full(S, -1e9)])
        ubv = np.concatenate([b.ub[0, idx], np.full(S, np.inf)])
        res = scipy_backend.solve_lp(c, A, np.asarray(cl), np.asarray(cu),
                                     lbv, ubv)
        if not res.feasible:
            return None
        return float(res.obj)

    def miditer(self):
        it = self.opt._iter
        if it % max(1, self.check_bound_iterations) != 0:
            return
        lb = self.compute_outer_bound()
        if lb is None or lb <= self._last_lb:
            return
        self._last_lb = lb
        spcomm = getattr(self.opt, "spcomm", None)
        if spcomm is not None and hasattr(spcomm, "OuterBoundUpdate"):
            spcomm.OuterBoundUpdate(lb, char='C')
