"""CrossScenarioExtension: hub-side management of cross-scenario cuts.

TPU-native analogue of ``mpisppy/extensions/cross_scen_extension.py:16`` (283
LoC).  The reference distributes Benders rows into every scenario model
(an eta variable per scenario inside each subproblem).  In the batched
runtime the same information is exploited WITHOUT reshaping the device batch:
the accumulated cuts define a host-side cutting-plane relaxation

    min_x  sum_s p_s eta_s
    s.t.   eta_s >= g_s . x + c_s          (every accumulated cut)
           x in the first-stage feasible set

whose optimum is a certified OUTER bound the hub reports each iteration —
the cuts tighten it monotonically, which is the role the reference's
`boundsout` path plays (cross_scen_hub.py:11).
"""

from __future__ import annotations

import numpy as np

from .extension import Extension


class CrossScenarioExtension(Extension):
    def __init__(self, opt):
        super().__init__(opt)
        so = opt.options.get("cross_scen_options", {})
        self.check_bound_iterations = so.get("check_bound_improve_iterations",
                                             4)
        self.max_cut_rounds = int(so.get("max_cut_rounds", 32))
        from collections import deque

        # bounded: the host cutting-plane LP pays per retained round, and
        # the device slots roll (see add_cuts) — keep a few generations
        self._cuts = deque(maxlen=4 * self.max_cut_rounds)
        self._last_lb = -np.inf
        self._phi_col = None       # set by pre_iter0's batch reform
        self._cut_row0 = None
        self._next_row = None
        self._q2lb = None          # certified per-scenario Q2 lower bounds

    # ---- in-batch reform (cross_scen_extension.py:120-283 analogue) --------
    def pre_iter0(self):
        """Reshape the scenario batch: one aggregate ``phi`` column (the
        epigraph of the OTHER scenarios' probability-weighted costs — the
        reference's per-scenario eta vector, aggregated so the column count
        stays O(1)) plus preallocated cut-row slots.  Regular PH solves are
        unaffected (phi has zero cost and only cut rows touch it); the
        periodic ``_check_bound`` alt-objective solve uses it to turn every
        subproblem into a certified EF relaxation."""
        opt = self.opt
        if opt.tree.num_stages != 2:
            raise RuntimeError(
                "CrossScenarioExtension supports two-stage problems only "
                "(as the reference, cross_scen_extension.py:120-122)")
        b = opt.batch
        self._phi_col = b.num_vars
        self._cut_row0 = b.num_rows
        self._next_row = 0
        # a CERTIFIED finite phi lower bound (the reference's valid_eta_bound,
        # cross_scen_extension.py:130-141): phi_s >= sum_{s'!=s} p' d_s' with
        # d_s the dual-certified scenario minima from one plain batched solve
        # — a huge-magnitude artificial lb would poison the dual-objective
        # certificate of the _check_bound solve (eps * |lb| error terms)
        so = opt.options.get("cross_scen_options", {})
        # certified per-scenario minima of the SECOND-STAGE-only problems
        # (first-stage cost zeroed — the lshaped.py eta-bound trick): used
        # for phi's lower bound AND as the safe substitute constant when a
        # scenario's cut row is invalid (see add_cuts)
        q0 = np.array(b.c, copy=True)
        q0[:, opt.tree.nonant_indices] = 0.0
        opt.solve_loop(q=q0, warm=False)
        x, _, y, _ = opt._warm
        import jax.numpy as jnp

        from ..solvers import admm

        dt = opt.admm_settings.jdtype()
        args = (jnp.asarray(q0, dt), jnp.asarray(b.q2, dt),
                jnp.asarray(b.A, dt), jnp.asarray(b.cl, dt),
                jnp.asarray(b.cu, dt), jnp.asarray(b.lb, dt),
                jnp.asarray(b.ub, dt), jnp.asarray(y, dt),
                jnp.asarray(x, dt))
        dvals = (np.asarray(admm.dual_objective(*args), dtype=float)
                 - np.asarray(admm.dual_objective_margin(*args), dtype=float))
        self._q2lb = dvals + b.const - 1.0       # Q2_s(x) >= _q2lb[s], all x
        if "phi_lb" in so:
            phi_lb = np.full(b.num_scenarios, float(so["phi_lb"]))
        else:
            d = opt.probs * self._q2lb
            phi_lb = d.sum() - d
        opt.batch = b.augment(
            1, self.max_cut_rounds, col_lb=0.0, col_ub=np.inf,
            col_names=["_cross_scen_phi"])
        opt.batch.lb[:, self._phi_col] = phi_lb
        # shapes changed: the PH warm chain and cached factors are void
        opt._warm = None
        opt._factors = None
        opt._factors_sig = None

    def add_cuts(self, rows: np.ndarray):
        """Accept a (S, K+1) payload from the cut spoke (NaN rows dropped)
        and inject the aggregate cut into every scenario's preallocated slot:

            phi_s >= sum_{s' != s} p_s' [g_s' . x + const_s']

        written as the row  phi - G_s.x >= C_s  (cl finite, cu = +inf).
        """
        if self.max_cut_rounds <= 0:
            return                 # device cut slots disabled
        valid = ~np.isnan(rows).any(axis=1)
        if not valid.any():
            return
        # Device cut slots ROLL: past max_cut_rounds the oldest slot is
        # overwritten (every cut is individually valid, so dropping one can
        # only loosen the relaxation, never invalidate it) — steering
        # continues indefinitely instead of freezing at the preallocation
        # (r2 known-gap).
        # scenarios whose cut row is invalid (NaN) CANNOT simply be omitted
        # from the aggregate: Q2 can be negative, so dropping a term would
        # raise the aggregate "lower bound" above the true sum — an invalid
        # cut that can push the EF-relaxation bound above the optimum.
        # Substitute the certified constant cut Q2_t(x) >= _q2lb[t] instead.
        clean = np.where(valid[:, None], rows, 0.0)
        if self._q2lb is not None:
            clean[~valid, -1] = self._q2lb[~valid]
        elif not valid.all():
            return      # no safe substitute available: skip this round
        # store the FULL round (NaN rows kept): compute_outer_bound binds
        # row s to scenario s's eta by POSITION, so filtering would
        # misalign cuts with etas and could certify an invalid bound
        self._cuts.append(rows)
        if self._phi_col is None:
            return
        opt = self.opt
        b = opt.batch
        idx = opt.tree.nonant_indices
        p = opt.probs                             # every scenario contributes
        G_tot = p @ clean[:, :-1]                 # (K,)
        C_tot = float(p @ clean[:, -1])
        G_s = G_tot[None, :] - p[:, None] * clean[:, :-1]     # (S, K)
        C_s = C_tot - p * clean[:, -1]                        # (S,)
        row = self._cut_row0 + (self._next_row % self.max_cut_rounds)
        b.A[:, row, :] = 0.0
        b.A[:, row, idx] = -G_s
        b.A[:, row, self._phi_col] = 1.0
        b.cl[:, row] = C_s
        b.cu[:, row] = np.inf
        b.version += 1
        self._next_row += 1

    def _check_bound(self):
        """Alt-objective batched solve: each subproblem becomes
        ``min  c1.x + p_s c2.y + phi``  s.t. own rows + cut rows — an EF
        relaxation, so max_s of the CERTIFIED per-scenario dual values is a
        valid EF outer bound (the reference's EF_Obj flip + max reduce,
        cross_scen_extension.py:72-117)."""
        opt = self.opt
        if self._phi_col is None or self._next_row == 0:
            return None
        b = opt.batch
        nm = b.nonant_mask()
        p = opt.probs
        q = np.where(nm[None, :], b.c, b.c * p[:, None])
        q[:, self._phi_col] = 1.0
        q2 = np.where(nm[None, :], b.q2, b.q2 * p[:, None])
        # hold the PH warm chain harmless across the side solve
        saved = (opt._warm, opt._factors, opt._factors_sig, opt._factors_age)
        try:
            opt.solve_loop(q=q, q2=q2, warm=False)
            x, _, y, _ = opt._warm
            import jax.numpy as jnp

            from ..solvers import admm

            dt = opt.admm_settings.jdtype()
            dvals = admm.dual_objective(
                jnp.asarray(q, dt), jnp.asarray(q2, dt),
                jnp.asarray(b.A, dt), jnp.asarray(b.cl, dt),
                jnp.asarray(b.cu, dt), jnp.asarray(b.lb, dt),
                jnp.asarray(b.ub, dt), jnp.asarray(y, dt),
                jnp.asarray(x, dt))
            vals = np.asarray(dvals, dtype=float) + p * b.const
            return float(np.max(vals))
        finally:
            (opt._warm, opt._factors, opt._factors_sig,
             opt._factors_age) = saved

    def compute_outer_bound(self):
        """Solve the host cutting-plane LP; returns the bound or None."""
        if not self._cuts:
            return None
        from ..solvers import scipy_backend

        opt = self.opt
        b = opt.batch
        idx = opt.tree.nonant_indices
        K = idx.shape[0]
        S = b.num_scenarios
        cuts = np.concatenate(self._cuts, axis=0)   # (C, K+1) but per-scen?
        # rebuild per-scenario cut lists: rows arrive S at a time in order
        ncut_rounds = len(self._cuts)
        nv = K + S
        rows = []
        cl, cu = [], []
        # first-stage rows from scenario 0 (support within nonant columns)
        mask = np.zeros(b.num_vars, dtype=bool)
        mask[idx] = True
        A0 = b.A[0]
        fs = ~(np.abs(A0[:, ~mask]) > 0).any(axis=1) & (np.abs(A0) > 0).any(
            axis=1)
        for r in np.where(fs)[0]:
            row = np.zeros(nv)
            row[:K] = A0[r, idx]
            rows.append(row)
            cl.append(b.cl[0, r])
            cu.append(b.cu[0, r])
        for rnd in self._cuts:
            for s in range(rnd.shape[0]):
                if np.isnan(rnd[s]).any():
                    continue
                row = np.zeros(nv)
                row[:K] = -rnd[s, :K]
                row[K + s] = 1.0
                rows.append(row)
                cl.append(rnd[s, K])
                cu.append(np.inf)
        if len(rows) <= fs.sum():
            return None
        A = np.stack(rows)
        c = np.zeros(nv)
        c[:K] = b.c[0, idx]        # first-stage cost (cuts are 2nd-stage-only)
        c[K:] = opt.probs
        lbv = np.concatenate([b.lb[0, idx], np.full(S, -1e9)])
        ubv = np.concatenate([b.ub[0, idx], np.full(S, np.inf)])
        res = scipy_backend.solve_lp(c, A, np.asarray(cl), np.asarray(cu),
                                     lbv, ubv)
        if not res.feasible:
            return None
        return float(res.obj), np.asarray(res.x[:K])

    def miditer(self):
        it = self.opt._iter
        if it % max(1, self.check_bound_iterations) != 0:
            return
        # two certified outer bounds from the same cuts: the host
        # cutting-plane LP (exact, first-stage space) and the in-batch
        # EF-relaxation check (steered subproblems, device batch)
        cands = []
        host = self.compute_outer_bound()
        if host is not None:
            lb_host, x_cp = host
            cands.append(lb_host)
            # hub-side Benders refinement: new cuts at the cutting-plane
            # ARGMIN (hub iterates cluster near one point, so spoke cuts
            # alone leave the relaxation loose away from it; cutting at the
            # relaxation's own minimizer is the classical convergent choice).
            if self._next_row is not None:
                from ..cylinders.cross_scen_spoke import make_clamp_cuts

                S = self.opt.batch.num_scenarios
                self.add_cuts(make_clamp_cuts(
                    self.opt, np.broadcast_to(
                        x_cp, (S, x_cp.shape[0])).copy()))
        chk = self._check_bound()
        if chk is not None:
            cands.append(chk)
        if not cands:
            return
        lb = max(cands)
        if lb <= self._last_lb:
            return
        self._last_lb = lb
        spcomm = getattr(self.opt, "spcomm", None)
        if spcomm is not None and hasattr(spcomm, "OuterBoundUpdate"):
            spcomm.OuterBoundUpdate(lb, char='C')
