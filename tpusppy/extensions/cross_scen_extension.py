"""CrossScenarioExtension: hub-side management of cross-scenario cuts.

TPU-native analogue of ``mpisppy/extensions/cross_scen_extension.py:16`` (283
LoC).  The reference distributes Benders rows into every scenario model
(an eta variable per scenario inside each subproblem).  In the batched
runtime the same information is exploited WITHOUT reshaping the device batch:
the accumulated cuts define a host-side cutting-plane relaxation

    min_x  sum_s p_s eta_s
    s.t.   eta_s >= g_s . x + c_s          (every accumulated cut)
           x in the first-stage feasible set

whose optimum is a certified OUTER bound the hub reports each iteration —
the cuts tighten it monotonically, which is the role the reference's
`boundsout` path plays (cross_scen_hub.py:11).
"""

from __future__ import annotations

import numpy as np

from .extension import Extension


class CrossScenarioExtension(Extension):
    def __init__(self, opt):
        super().__init__(opt)
        so = opt.options.get("cross_scen_options", {})
        self.check_bound_iterations = so.get("check_bound_improve_iterations",
                                             4)
        # rounds preallocate S rows each (the eta-vector form), so the
        # default scales the retained generations to a ~4k-row budget:
        # small families keep 32 rounds, S=1000 keeps 4
        S = opt.batch.num_scenarios
        self.max_cut_rounds = int(so.get(
            "max_cut_rounds", max(2, min(32, 4096 // max(S, 1)))))
        from collections import deque

        # bounded: the host cutting-plane LP pays per retained round, and
        # the device slots roll (see add_cuts) — keep a few generations
        self._cuts = deque(maxlen=4 * self.max_cut_rounds)
        self._last_lb = -np.inf
        self._eta0 = None          # first eta column (pre_iter0 reform)
        self._cut_row0 = None
        self._next_row = None
        self._q2lb = None          # certified per-scenario Q2 lower bounds

    # ---- in-batch reform (cross_scen_extension.py:120-283 analogue) --------
    def pre_iter0(self):
        """Reshape the scenario batch: the reference's per-scenario ETA
        VECTOR (one epigraph column per scenario, added to every model —
        cross_scen_extension.py:120-283) plus ``max_cut_rounds`` rounds of
        preallocated cut-row slots, S rows per round.

        The eta-vector form is what keeps shared-A families shared: the cut
        for scenario s' (``eta_{s'} >= g_{s'}.x + c_{s'}``) has IDENTICAL
        coefficients in every scenario's model, so rows write once into the
        single shared matrix (r3 weak #5: the aggregated-phi design needed
        per-scenario coefficients and densified the family).  Regular PH
        solves are unaffected (etas cost zero and only cut rows touch
        them); the periodic ``_check_bound`` alt-objective solve prices
        them to turn every subproblem into a certified EF relaxation."""
        opt = self.opt
        if opt.tree.num_stages != 2:
            raise RuntimeError(
                "CrossScenarioExtension supports two-stage problems only "
                "(as the reference, cross_scen_extension.py:120-122)")
        b = opt.batch
        self._eta0 = b.num_vars
        self._cut_row0 = b.num_rows
        self._next_row = 0
        # a CERTIFIED finite phi lower bound (the reference's valid_eta_bound,
        # cross_scen_extension.py:130-141): phi_s >= sum_{s'!=s} p' d_s' with
        # d_s the dual-certified scenario minima from one plain batched solve
        # — a huge-magnitude artificial lb would poison the dual-objective
        # certificate of the _check_bound solve (eps * |lb| error terms)
        so = opt.options.get("cross_scen_options", {})
        # certified per-scenario minima of the SECOND-STAGE-only problems
        # (first-stage cost zeroed — the lshaped.py eta-bound trick): used
        # for phi's lower bound AND as the safe substitute constant when a
        # scenario's cut row is invalid (see add_cuts)
        q0 = np.array(b.c, copy=True)
        q0[:, opt.tree.nonant_indices] = 0.0
        opt.solve_loop(q=q0, warm=False)
        x, _, y, _ = opt._warm
        import jax.numpy as jnp

        from ..solvers import admm

        dt = opt.admm_settings.jdtype()
        args = (jnp.asarray(q0, dt), jnp.asarray(b.q2, dt),
                jnp.asarray(b.A, dt), jnp.asarray(b.cl, dt),
                jnp.asarray(b.cu, dt), jnp.asarray(b.lb, dt),
                jnp.asarray(b.ub, dt), jnp.asarray(y, dt),
                jnp.asarray(x, dt))
        dvals = (np.asarray(admm.dual_objective(*args), dtype=float)
                 - np.asarray(admm.dual_objective_margin(*args), dtype=float))
        self._q2lb = dvals + b.const - 1.0       # Q2_s(x) >= _q2lb[s], all x
        S = b.num_scenarios
        eta_lb = (np.full(S, float(so["eta_lb"]))
                  if "eta_lb" in so else self._q2lb)
        opt.batch = b.augment(
            S, self.max_cut_rounds * S, col_lb=0.0, col_ub=np.inf,
            col_names=[f"_cs_eta[{s}]" for s in range(S)])
        # augment is functional: opt.batch is now a private copy whatever
        # the cache says, and the slot writes below touch only its arrays
        opt._batch_shared = False
        # every scenario model carries the full eta vector with the same
        # certified lower bounds (the reference's valid_eta_bound)
        opt.batch.lb[:, self._eta0:self._eta0 + S] = eta_lb[None, :]
        # shapes changed: the PH warm chain and cached factors are void
        opt._warm = None
        opt._factors = None
        opt._factors_sig = None

    def add_cuts(self, rows: np.ndarray):
        """Accept a (S, K+1) payload from the cut spoke and inject one cut
        ROUND — for every scenario s' the row

            eta_{s'} - g_{s'} . x >= c_{s'}        (cl finite, cu = +inf)

        into the preallocated slots.  Coefficients are identical across
        scenario models, so for a shared-A family the round writes ONCE
        into the shared matrix; each cut is individually certified, so a
        NaN (failed) payload row degrades to the constant certified cut
        ``eta_{s'} >= q2lb_{s'}`` without touching the others.
        """
        if self.max_cut_rounds <= 0:
            return                 # device cut slots disabled
        valid = ~np.isnan(rows).any(axis=1)
        if not valid.any():
            return
        # store the FULL round (NaN rows kept): compute_outer_bound binds
        # row s to scenario s's eta by POSITION, so filtering would
        # misalign cuts with etas and could certify an invalid bound
        self._cuts.append(rows)
        if getattr(self, "_eta0", None) is None:
            return
        opt = self.opt
        b = opt.batch
        idx = opt.tree.nonant_indices
        S = b.num_scenarios
        clean = np.where(valid[:, None], rows, 0.0)
        # failed payload rows degrade to the constant certified cut
        # eta_{s'} >= q2lb_{s'} (pre_iter0 always computes _q2lb)
        consts = np.where(valid, clean[:, -1], self._q2lb)
        grads = np.where(valid[:, None], clean[:, :-1], 0.0)
        # Device cut slots ROLL by round: past max_cut_rounds the oldest
        # round is overwritten (each cut is individually valid, so dropping
        # one can only loosen the relaxation) — steering continues
        # indefinitely instead of freezing at the preallocation.
        r0 = self._cut_row0 + (self._next_row % self.max_cut_rounds) * S
        if b.A_shared is not None:
            A_rows = b.A_shared[r0:r0 + S]        # write ONCE, all models
        else:
            A_rows = b.A[:, r0:r0 + S, :]         # same values per scenario
        A_rows[..., :] = 0.0
        tgt = A_rows if b.A_shared is not None else A_rows[0]
        tgt[:, idx] = -grads
        tgt[np.arange(S), self._eta0 + np.arange(S)] = 1.0
        if b.A_shared is None:
            A_rows[:] = A_rows[0][None]
        b.cl[:, r0:r0 + S] = consts[None, :]
        b.cu[:, r0:r0 + S] = np.inf
        b.version += 1
        self._next_row += 1

    def _check_bound(self):
        """Alt-objective batched solve: each subproblem becomes
        ``min  c1.x + p_s c2.y + phi``  s.t. own rows + cut rows — an EF
        relaxation, so max_s of the CERTIFIED per-scenario dual values is a
        valid EF outer bound (the reference's EF_Obj flip + max reduce,
        cross_scen_extension.py:72-117)."""
        opt = self.opt
        if getattr(self, "_eta0", None) is None or self._next_row == 0:
            return None
        b = opt.batch
        nm = b.nonant_mask()
        p = opt.probs
        S = b.num_scenarios
        q = np.where(nm[None, :], b.c, b.c * p[:, None])
        # price the OTHER scenarios' epigraphs (own second stage is real):
        # q[s, eta_{s'}] = p_{s'} for s' != s, 0 on the own column
        q[:, self._eta0:self._eta0 + S] = p[None, :]
        q[np.arange(S), self._eta0 + np.arange(S)] = 0.0
        q2 = np.where(nm[None, :], b.q2, b.q2 * p[:, None])
        # hold the PH warm chain harmless across the side solve
        saved = (opt._warm, opt._factors, opt._factors_sig, opt._factors_age)
        try:
            opt.solve_loop(q=q, q2=q2, warm=False)
            x, _, y, _ = opt._warm
            import jax.numpy as jnp

            from ..solvers import admm

            dt = opt.admm_settings.jdtype()
            dvals = admm.dual_objective(
                jnp.asarray(q, dt), jnp.asarray(q2, dt),
                jnp.asarray(b.A, dt), jnp.asarray(b.cl, dt),
                jnp.asarray(b.cu, dt), jnp.asarray(b.lb, dt),
                jnp.asarray(b.ub, dt), jnp.asarray(y, dt),
                jnp.asarray(x, dt))
            vals = np.asarray(dvals, dtype=float) + p * b.const
            return float(np.max(vals))
        finally:
            (opt._warm, opt._factors, opt._factors_sig,
             opt._factors_age) = saved

    def compute_outer_bound(self):
        """Solve the host cutting-plane LP; returns the bound or None."""
        if not self._cuts:
            return None
        from ..solvers import scipy_backend

        opt = self.opt
        b = opt.batch
        idx = opt.tree.nonant_indices
        K = idx.shape[0]
        S = b.num_scenarios
        cuts = np.concatenate(self._cuts, axis=0)   # (C, K+1) but per-scen?
        # rebuild per-scenario cut lists: rows arrive S at a time in order
        ncut_rounds = len(self._cuts)
        nv = K + S
        rows = []
        cl, cu = [], []
        # first-stage rows from scenario 0 (support within nonant columns)
        mask = np.zeros(b.num_vars, dtype=bool)
        mask[idx] = True
        A0 = b.A[0]
        fs = ~(np.abs(A0[:, ~mask]) > 0).any(axis=1) & (np.abs(A0) > 0).any(
            axis=1)
        for r in np.where(fs)[0]:
            row = np.zeros(nv)
            row[:K] = A0[r, idx]
            rows.append(row)
            cl.append(b.cl[0, r])
            cu.append(b.cu[0, r])
        for rnd in self._cuts:
            for s in range(rnd.shape[0]):
                if np.isnan(rnd[s]).any():
                    continue
                row = np.zeros(nv)
                row[:K] = -rnd[s, :K]
                row[K + s] = 1.0
                rows.append(row)
                cl.append(rnd[s, K])
                cu.append(np.inf)
        if len(rows) <= fs.sum():
            return None
        A = np.stack(rows)
        c = np.zeros(nv)
        c[:K] = b.c[0, idx]        # first-stage cost (cuts are 2nd-stage-only)
        c[K:] = opt.probs
        lbv = np.concatenate([b.lb[0, idx], np.full(S, -1e9)])
        ubv = np.concatenate([b.ub[0, idx], np.full(S, np.inf)])
        res = scipy_backend.solve_lp(c, A, np.asarray(cl), np.asarray(cu),
                                     lbv, ubv)
        if not res.feasible:
            return None
        return float(res.obj), np.asarray(res.x[:K])

    def miditer(self):
        it = self.opt._iter
        if it % max(1, self.check_bound_iterations) != 0:
            return
        # two certified outer bounds from the same cuts: the host
        # cutting-plane LP (exact, first-stage space) and the in-batch
        # EF-relaxation check (steered subproblems, device batch)
        cands = []
        host = self.compute_outer_bound()
        if host is not None:
            lb_host, x_cp = host
            cands.append(lb_host)
            # hub-side Benders refinement: new cuts at the cutting-plane
            # ARGMIN (hub iterates cluster near one point, so spoke cuts
            # alone leave the relaxation loose away from it; cutting at the
            # relaxation's own minimizer is the classical convergent choice).
            if self._next_row is not None:
                from ..cylinders.cross_scen_spoke import make_clamp_cuts

                S = self.opt.batch.num_scenarios
                self.add_cuts(make_clamp_cuts(
                    self.opt, np.broadcast_to(
                        x_cp, (S, x_cp.shape[0])).copy()))
        chk = self._check_bound()
        if chk is not None:
            cands.append(chk)
        if not cands:
            return
        lb = max(cands)
        if lb <= self._last_lb:
            return
        self._last_lb = lb
        spcomm = getattr(self.opt, "spcomm", None)
        if spcomm is not None and hasattr(spcomm, "OuterBoundUpdate"):
            spcomm.OuterBoundUpdate(lb, char='C')
