"""XhatBase: in-hub incumbent (inner-bound) finders.

TPU-native analogue of ``mpisppy/extensions/xhatbase.py:38-230``.  The core
primitive ``_try_one`` fixes the nonant columns to a candidate, solves the whole
scenario batch in one device program, takes the probability-weighted objective,
and restores state — the reference's fix/solve-all/restore loop
(xhatbase.py:38-230, spopt.py:557-591) collapsed into a bound clamp + one
batched ADMM call.

Multistage candidates are built from *donor scenarios per tree node*: the
candidate value of nonant slot k in scenario s is taken from the donor scenario
of the node owning (s, k).  Any donor assignment yields a nonanticipative
candidate; two-stage reduces to a single donor (the reference's
"xhat from one scenario").
"""

from __future__ import annotations

import numpy as np

from .extension import Extension


def donor_cache(opt, xk: np.ndarray, donors) -> np.ndarray:
    """(S, K) candidate cache from per-node donor scenarios.

    Args:
      opt: an SPOpt-derived object (provides tree indexing).
      xk: (S, K) nonant values to draw from.
      donors: (N,) int array, or dict {node_name: scenario index}, or a single
        int (two-stage convenience: that scenario donates everywhere it can,
        other nodes fall back to their first member scenario).
    """
    tree = opt.tree
    N = tree.num_nodes
    nid = opt.nid_sk                    # (S, K)
    if isinstance(donors, (int, np.integer)):
        base = int(donors)
        arr = np.zeros(N, dtype=np.int64)
        member = tree.membership_matrix()   # (N, S)
        for n_ in range(N):
            arr[n_] = base if member[n_, base] > 0 else int(
                np.argmax(member[n_] > 0)
            )
        donors = arr
    elif isinstance(donors, dict):
        arr = np.zeros(N, dtype=np.int64)
        name_to_id = {nm: i for i, nm in enumerate(tree.node_names)}
        for nm, s in donors.items():
            arr[name_to_id[nm]] = int(s)
        donors = arr
    donors = np.asarray(donors, dtype=np.int64)
    kidx = np.arange(nid.shape[1])[None, :]
    return xk[donors[nid], kidx]


def slam_cache(opt, xk: np.ndarray, how: str = "max") -> np.ndarray:
    """Per-node max/min "slamming" candidate (cylinders/slam_heuristic.py:24-125).

    For each nonant slot, takes the max (or min) over the scenarios of its
    owning node — a cheap integer-friendly incumbent guess.
    """
    assert how in ("max", "min")
    onehot = opt.tree.onehot_sk_n()        # (S, K, N)
    big = np.inf if how == "min" else -np.inf
    vals = np.where(onehot.transpose(2, 0, 1) > 0, xk[None], big)  # (N, S, K)
    agg = vals.max(axis=1) if how == "max" else vals.min(axis=1)   # (N, K)
    kidx = np.arange(xk.shape[1])[None, :]
    return agg[opt.nid_sk, kidx]


class XhatBase(Extension):
    """Base for in-hub xhat finders; tracks the best inner bound on the opt
    object (``opt.best_inner_bound`` / ``opt.best_xhat_cache``)."""

    def __init__(self, spopt_object):
        super().__init__(spopt_object)
        opt = self.opt
        if not hasattr(opt, "best_inner_bound"):
            opt.best_inner_bound = np.inf
            opt.best_xhat_cache = None

    # ---- the primitive ------------------------------------------------------
    def _try_one(self, cache, restore=True) -> float:
        """Evaluate one candidate; returns expected objective or +inf.

        Saves and restores the opt object's solver state so PH's warm starts
        and current iterate are unperturbed (the reference's
        _fix_nonants/._restore_nonants bracketing, xhatbase.py:38-230).
        """
        opt = self.opt
        saved = (opt._warm, opt.local_x, opt.pri_res, opt.dua_res)
        try:
            opt.fix_nonants(cache)
            x = opt.solve_loop(warm=False)
            if opt.feas_prob() < 1.0 - 1e-9:
                return np.inf
            obj = float(opt.probs @ opt.batch.objective(x))
        finally:
            opt.restore_nonants()
            if restore:
                opt._warm, opt.local_x, opt.pri_res, opt.dua_res = saved
        return obj

    def _update_if_improving(self, obj: float, cache) -> bool:
        if obj < self.opt.best_inner_bound:
            self.opt.best_inner_bound = obj
            self.opt.best_xhat_cache = np.asarray(cache).copy()
            return True
        return False

    def try_scenario(self, s: int) -> float:
        """Candidate = donor scenario s's nonants (per-node completion)."""
        xk = self.opt.nonants_of(self.opt.local_x)
        cache = donor_cache(self.opt, xk, int(s))
        obj = self._try_one(cache)
        self._update_if_improving(obj, cache)
        return obj
