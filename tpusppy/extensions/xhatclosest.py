"""XhatClosest: evaluate the scenario whose nonants are closest to xbar.

Analogue of ``mpisppy/extensions/xhatclosest.py``: pick the scenario minimizing
||x_s - xbar||^2 over the nonant slots and try it as the donor.
"""

from __future__ import annotations

import numpy as np

from .xhatbase import XhatBase


class XhatClosest(XhatBase):
    def _try(self):
        opt = self.opt
        xbars = getattr(opt, "xbars", None)
        if xbars is None:
            return None
        xk = opt.nonants_of(opt.local_x)
        dist = ((xk - xbars) ** 2).sum(axis=1)
        return self.try_scenario(int(np.argmin(dist)))

    def post_iter0(self):
        self._try()

    def enditer(self):
        self._try()
