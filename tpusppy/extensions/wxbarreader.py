"""WXBarReader: warm-start W / xbar from files before iteration 0.

TPU-native analogue of ``mpisppy/utils/wxbarreader.py``: options
``init_W_fname`` / ``init_Xbar_fname`` / ``init_separate_W_files``.
"""

from __future__ import annotations

from .extension import Extension
from ..utils import wxbarutils


class WXBarReader(Extension):
    def __init__(self, opt):
        super().__init__(opt)
        self.W_fname = opt.options.get("init_W_fname")
        self.Xbar_fname = opt.options.get("init_Xbar_fname")
        self.sep_files = opt.options.get("init_separate_W_files", False)

    def post_iter0(self):
        if self.W_fname:
            wxbarutils.set_W_from_file(self.W_fname, self.opt,
                                       sep_files=self.sep_files)
        if self.Xbar_fname:
            wxbarutils.set_xbar_from_file(self.Xbar_fname, self.opt)
