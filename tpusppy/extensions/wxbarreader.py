"""WXBarReader: warm-start W / xbar from files before iteration 0.

TPU-native analogue of ``mpisppy/utils/wxbarreader.py``: options
``init_W_fname`` / ``init_Xbar_fname`` / ``init_separate_W_files``.

Routed through the resilience checkpoint engine
(:func:`tpusppy.resilience.checkpoint.read_wxbar`): a ``.npz`` path
restores W, xbar AND rho from a real wheel checkpoint in one shot; any
other path keeps reading the reference's csv formats
(``scenario,varname,value`` W rows, ``varname,value`` xbar rows) via
:mod:`tpusppy.utils.wxbarutils` — checkpoints stay interchangeable with
mpi-sppy runs (doc/porting_from_mpisppy.md).
"""

from __future__ import annotations

from ..resilience import checkpoint as _checkpoint
from .extension import Extension


class WXBarReader(Extension):
    def __init__(self, opt):
        super().__init__(opt)
        self.W_fname = opt.options.get("init_W_fname")
        self.Xbar_fname = opt.options.get("init_Xbar_fname")
        self.sep_files = opt.options.get("init_separate_W_files", False)

    def post_iter0(self):
        if self.W_fname or self.Xbar_fname:
            _checkpoint.read_wxbar(self.opt, self.W_fname, self.Xbar_fname,
                                   sep_files=self.sep_files)
