"""Diagnoser: per-scenario per-iteration diagnostic dumps.

TPU-native analogue of ``mpisppy/extensions/diagnoser.py`` (71 LoC): writes a
CSV per iteration with per-scenario objective, primal/dual residuals, and
deviation from xbar, into ``options["diagnoser_options"]["diagnoser_outdir"]``.
"""

from __future__ import annotations

import os

import numpy as np

from .extension import Extension


class Diagnoser(Extension):
    def __init__(self, opt):
        super().__init__(opt)
        do = opt.options.get("diagnoser_options", {})
        self.outdir = do.get("diagnoser_outdir", "diagnoser_out")

    def _write(self, tag):
        opt = self.opt
        if opt.local_x is None:
            return
        os.makedirs(self.outdir, exist_ok=True)
        objs = opt.batch.objective(opt.local_x)
        xk = opt.nonants_of(opt.local_x)
        dev = np.abs(xk - opt.xbars).mean(axis=1) if hasattr(opt, "xbars") \
            else np.zeros_like(objs)
        path = os.path.join(self.outdir, f"diagnose_{tag}.csv")
        with open(path, "w") as f:
            f.write("scenario,objective,pri_res,dua_res,mean_dev_from_xbar\n")
            for s, name in enumerate(opt.all_scenario_names):
                pri = opt.pri_res[s] if opt.pri_res is not None else np.nan
                dua = opt.dua_res[s] if opt.dua_res is not None else np.nan
                f.write(f"{name},{objs[s]!r},{pri!r},{dua!r},{dev[s]!r}\n")

    def post_iter0(self):
        self._write("iter0")

    def enditer(self):
        self._write(f"iter{self.opt._iter}")
