"""XhatSpecific: evaluate a fixed scenario-per-node candidate each iteration.

Analogue of ``mpisppy/extensions/xhatspecific.py`` (and the spoke at
cylinders/xhatspecific_bounder.py): the user names one donor scenario per
nonleaf tree node (``xhat_specific_dict``: {node_name: scenario name or
index}); each callout evaluates that candidate.
"""

from __future__ import annotations

from .xhatbase import XhatBase, donor_cache


class XhatSpecific(XhatBase):
    def __init__(self, spopt_object):
        super().__init__(spopt_object)
        spec = self.opt.options.get("xhat_specific_options", {}).get(
            "xhat_specific_dict"
        ) or self.opt.options.get("xhat_specific_dict")
        if spec is None:
            raise RuntimeError("XhatSpecific requires options['xhat_specific_dict']")
        names = self.opt.all_scenario_names
        self.donors = {
            node: (names.index(s) if isinstance(s, str) else int(s))
            for node, s in spec.items()
        }

    def _try(self):
        xk = self.opt.nonants_of(self.opt.local_x)
        cache = donor_cache(self.opt, xk, self.donors)
        obj = self._try_one(cache)
        self._update_if_improving(obj, cache)
        return obj

    def post_iter0(self):
        self._try()

    def enditer(self):
        self._try()
