"""MinMaxAvg: report avg/min/max of a quantity across scenarios.

TPU-native analogue of ``mpisppy/extensions/avgminmaxer.py`` (39 LoC).  The
reference evaluates a named Pyomo component per scenario; here
``options["avgminmax_name"]`` may be "objective" or a variable name from the
model's ``var_names``.
"""

from __future__ import annotations

import numpy as np

from .extension import Extension


class MinMaxAvg(Extension):
    def __init__(self, opt, compstr=None):
        super().__init__(opt)
        self.compstr = compstr or opt.options.get("avgminmax_name",
                                                  "objective")

    def _values(self) -> np.ndarray:
        opt = self.opt
        if opt.local_x is None:
            return np.zeros(opt.batch.num_scenarios)
        if self.compstr == "objective":
            return opt.batch.objective(opt.local_x)
        var_names = getattr(opt, "_var_names", None)
        if var_names is None:
            p0 = opt.scenario_creator(
                opt.all_scenario_names[0], **opt.scenario_creator_kwargs
            )
            var_names = p0.var_names or []
            opt._var_names = var_names
        j = var_names.index(self.compstr)
        return np.asarray(opt.local_x)[:, j]

    def _report(self, when):
        v = self._values()
        print(f"  {self.compstr} {when}: avg={v.mean():.6g} "
              f"min={v.min():.6g} max={v.max():.6g}")

    def post_iter0(self):
        self._report("post iter0")

    def enditer(self):
        self._report(f"iter {self.opt._iter}")

    def post_everything(self):
        self._report("final")
