"""Gapper: solver-tolerance schedule by iteration.

TPU-native analogue of ``mpisppy/extensions/mipgapper.py:11-57``.  The
reference schedules the external MIP solver's relative gap; here the analogue
knob is the batched ADMM solver's relative tolerance (loose early iterations
are cheaper, exactly the trick the mipgap schedule plays).

Options: ``opt.options["gapperoptions"] = {"mipgapdict": {iter: gap}, ...}``.
"""

from __future__ import annotations

import dataclasses

from .extension import Extension


class Gapper(Extension):
    def __init__(self, opt):
        super().__init__(opt)
        go = opt.options["gapperoptions"]
        self.mipgapdict = go["mipgapdict"]
        self.verbose = opt.options.get("verbose", False) or go.get(
            "verbose", False)

    def set_mipgap(self, mipgap):
        old = self.opt.admm_settings.eps_rel
        self.opt.admm_settings = dataclasses.replace(
            self.opt.admm_settings, eps_rel=float(mipgap),
        )
        if self.verbose:
            print(f"mipgapper: changing solver eps_rel from {old} "
                  f"to {mipgap}")

    def pre_iter0(self):
        if self.mipgapdict and 0 in self.mipgapdict:
            self.set_mipgap(self.mipgapdict[0])

    def miditer(self):
        if self.mipgapdict and self.opt._iter in self.mipgapdict:
            self.set_mipgap(self.mipgapdict[self.opt._iter])
