"""Gradient_extension: per-iteration gradient-based dynamic rho.

TPU-native analogue of ``mpisppy/extensions/gradient_extension.py:18-111``:
each iteration, recompute gradient costs at the current iterate and reset rho
via the WW heuristic order statistic.
"""

from __future__ import annotations

import numpy as np

from .extension import Extension
from ..utils.find_rho import Find_Rho, _nonant_var_names
from ..utils.gradient import Find_Grad


class Gradient_extension(Extension):
    def __init__(self, opt, cfg=None):
        super().__init__(opt)
        self.cfg = cfg or opt.options.get("gradient_extension_options", {})
        self.grad_object = Find_Grad(opt, self.cfg)
        self.rho_finder = Find_Rho(opt, self.cfg)
        self._vnames = None

    def _update_rho(self):
        opt = self.opt
        grads = self.grad_object.compute_grad()
        if self._vnames is None:
            self._vnames = _nonant_var_names(opt)
        self.rho_finder.c = {
            (sname, self._vnames[k]): float(grads[s, k])
            for s, sname in enumerate(opt.all_scenario_names)
            for k in range(grads.shape[1])
        }
        rho_by_name = self.rho_finder.compute_rho()
        rho_k = np.array([rho_by_name[v] for v in self._vnames])
        opt.rho = np.broadcast_to(
            rho_k[None, :], (opt.batch.num_scenarios, rho_k.shape[0])
        ).copy()

    def post_iter0(self):
        self._update_rho()

    def miditer(self):
        it = self.opt._iter
        start = self.cfg.get("grad_rho_start_iter", 1)
        step = self.cfg.get("grad_rho_setter_step", 1)
        if it >= start and (it - start) % step == 0:
            self._update_rho()
