"""Xhat_Eval: fix-and-evaluate candidate first-stage solutions.

TPU-native analogue of ``mpisppy/utils/xhat_eval.py:29-434``.  The reference
fixes the nonant Pyomo variables to a candidate and re-solves every scenario
through the external solver (``evaluate`` / ``evaluate_one``,
xhat_eval.py:261-330).  Here "fixing" is a bound clamp on the nonant columns of
the HBM-resident batch (lb = ub = candidate) and the evaluation is one batched
ADMM solve — so trying a candidate costs a single device program, which is what
makes the inner-bound spokes (xhatshuffle et al.) cheap.

Feasibility of the fixed problem is judged by the solver's primal residual
(the analogue of spopt.py:175-195 solver-status checks); an infeasible
candidate evaluates to +inf (for minimization).
"""

from __future__ import annotations

import numpy as np

from .spopt import SPOpt


class Xhat_Eval(SPOpt):
    """An SPOpt that evaluates fixed first-stage candidates.

    Typical use (mirrors xhat_eval.py:261-330)::

        ev = Xhat_Eval(options, names, scenario_creator, ...)
        z_hat = ev.evaluate(nonant_cache)   # expected objective, or +inf

    Integer recourse: the reference's external MIP solver returns integral
    second-stage solutions natively; here a ROUND-AND-DIVE loop over the
    batched LP solves does (fix near-integral integer columns, re-solve,
    repeat) — options["xhat_dive_rounds"] bounds the dives (default 12).
    """

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.tee_rank0_solves = False

    def _integer_dive(self, lb, ub):
        """Drive remaining fractional integer columns integral.

        Per round: solve the batch; clamp integer columns within 0.1 of an
        integer to that integer, plus (to guarantee progress) each
        scenario's single most fractional integer column to its rounding.
        """
        import numpy as np

        from .solvers import admm

        b = self.batch
        ints = b.is_int
        rounds = int(self.options.get("xhat_dive_rounds", 12))
        lb = np.array(lb, copy=True)
        ub = np.array(ub, copy=True)
        x = None
        for _ in range(rounds):
            sol = admm.solve_batch(b.c, b.q2, b.A, b.cl, b.cu, lb, ub,
                                   settings=self.admm_settings)
            x = np.asarray(sol.x)
            self.local_x = x
            self.pri_res = np.asarray(sol.pri_res)
            self.dua_res = np.asarray(sol.dua_res)
            free = ints[None, :] & (ub > lb)          # (S, n) undecided ints
            if not free.any():
                break
            frac = np.where(free, np.abs(x - np.round(x)), -1.0)
            if frac.max() < 1e-6:
                break
            near = free & (frac < 0.1)
            # force progress: most fractional free int column per scenario,
            # rounded UP (covering-style constraints stay satisfiable; the
            # re-solve lets other free columns compensate)
            worst = frac.argmax(axis=1)
            has_free = free.any(axis=1)
            force = np.zeros_like(near)
            force[np.arange(x.shape[0]), worst] = has_free
            vals = np.round(np.where(near, x, 0.0))
            vals = np.where(force, np.ceil(np.where(force, x, 0.0) - 1e-9),
                            vals)
            clamp = near | force
            lb = np.where(clamp, np.maximum(vals, lb), lb)
            ub = np.where(clamp, np.minimum(vals, ub), ub)
            lb = np.minimum(lb, ub)  # keep boxes sane after rounding
        return x

    def _host_milp(self, lb, ub):
        """Per-scenario HiGHS MILP with nonants clamped — the fallback when
        diving wedges (e.g. capacity-binding all-integer recourse).  This is
        exactly the role the reference's external MIP solver plays for
        incumbent evaluation; each scenario MILP is small and independent.
        """
        import numpy as np

        from .solvers import scipy_backend

        b = self.batch
        S = b.num_scenarios
        xs = np.zeros((S, b.num_vars))
        pri = np.zeros(S)
        limit = float(self.options.get("xhat_mip_time_limit", 2.0))
        gap = float(self.options.get("xhat_mip_rel_gap", 1e-4))
        for s in range(S):
            res = scipy_backend.solve_lp(
                b.c[s], b.A[s], b.cl[s], b.cu[s], lb[s], ub[s],
                is_int=b.is_int, mip_rel_gap=gap, time_limit=limit)
            if res.feasible:
                xs[s] = res.x
            else:
                pri[s] = np.inf
        self.local_x = xs
        self.pri_res = pri
        self.dua_res = np.zeros(S)
        return xs

    def _fix_and_solve(self, nonant_cache):
        """Clamp nonants to the candidate and solve the whole batch.

        ``nonant_cache``: (K,) single candidate shared by all scenarios, or
        (S, K) per-scenario (multistage xhats fix per-node values; scenarios of
        one node must carry identical values there).
        """
        import numpy as np

        self.fix_nonants(nonant_cache)
        try:
            b = self.batch
            leftover_ints = b.is_int.any() and bool(
                (b.is_int[None, :] & (self._fixed_ub > self._fixed_lb)).any()
            )
            if leftover_ints:
                x = self._integer_dive(self._fixed_lb, self._fixed_ub)
                tol = max(self.options.get("feas_tol", 1e-3),
                          10.0 * self.admm_settings.eps_rel)
                if (np.asarray(self.pri_res) > tol).any():
                    x = self._host_milp(self._fixed_lb, self._fixed_ub)
            else:
                # cold start: the clamped problem's geometry differs enough
                # that stale warm duals slow ADMM down rather than help
                x = self.solve_loop(warm=False)
        finally:
            self.restore_nonants()
        return x

    def evaluate_one(self, nonant_cache, scenario_index: int) -> float:
        """Objective of ONE scenario at the fixed candidate
        (xhat_eval.py:261-292)."""
        x = self._fix_and_solve(nonant_cache)
        if self.pri_res is not None:
            tol = self.options.get("feas_tol", 1e-3)
            if self.pri_res[scenario_index] > tol:
                return np.inf
        return float(self.batch.objective(x)[scenario_index])

    def evaluate(self, nonant_cache) -> float:
        """Expected objective at the fixed candidate; +inf if any scenario is
        infeasible (xhat_eval.py:293-330 + feas_prob check)."""
        x = self._fix_and_solve(nonant_cache)
        if self.feas_prob() < 1.0 - 1e-9:
            return np.inf
        return float(self.probs @ self.batch.objective(x))

    def objective_values(self, nonant_cache) -> np.ndarray:
        """(S,) per-scenario objectives at the fixed candidate."""
        x = self._fix_and_solve(nonant_cache)
        return self.batch.objective(x)
