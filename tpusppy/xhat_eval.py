"""Xhat_Eval: fix-and-evaluate candidate first-stage solutions.

TPU-native analogue of ``mpisppy/utils/xhat_eval.py:29-434``.  The reference
fixes the nonant Pyomo variables to a candidate and re-solves every scenario
through the external solver (``evaluate`` / ``evaluate_one``,
xhat_eval.py:261-330).  Here "fixing" is a bound clamp on the nonant columns of
the HBM-resident batch (lb = ub = candidate) and the evaluation is one batched
ADMM solve — so trying a candidate costs a single device program, which is what
makes the inner-bound spokes (xhatshuffle et al.) cheap.

Feasibility of the fixed problem is judged by the solver's primal residual
(the analogue of spopt.py:175-195 solver-status checks); an infeasible
candidate evaluates to +inf (for minimization).
"""

from __future__ import annotations

import numpy as np

from .spopt import SPOpt


class Xhat_Eval(SPOpt):
    """An SPOpt that evaluates fixed first-stage candidates.

    Typical use (mirrors xhat_eval.py:261-330)::

        ev = Xhat_Eval(options, names, scenario_creator, ...)
        z_hat = ev.evaluate(nonant_cache)   # expected objective, or +inf
    """

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.tee_rank0_solves = False

    def _fix_and_solve(self, nonant_cache):
        """Clamp nonants to the candidate and solve the whole batch.

        ``nonant_cache``: (K,) single candidate shared by all scenarios, or
        (S, K) per-scenario (multistage xhats fix per-node values; scenarios of
        one node must carry identical values there).
        """
        self.fix_nonants(nonant_cache)
        try:
            # cold start: the clamped problem's geometry differs enough that
            # stale warm duals slow ADMM down rather than help
            x = self.solve_loop(warm=False)
        finally:
            self.restore_nonants()
        return x

    def evaluate_one(self, nonant_cache, scenario_index: int) -> float:
        """Objective of ONE scenario at the fixed candidate
        (xhat_eval.py:261-292)."""
        x = self._fix_and_solve(nonant_cache)
        if self.pri_res is not None:
            tol = self.options.get("feas_tol", 1e-3)
            if self.pri_res[scenario_index] > tol:
                return np.inf
        return float(self.batch.objective(x)[scenario_index])

    def evaluate(self, nonant_cache) -> float:
        """Expected objective at the fixed candidate; +inf if any scenario is
        infeasible (xhat_eval.py:293-330 + feas_prob check)."""
        x = self._fix_and_solve(nonant_cache)
        if self.feas_prob() < 1.0 - 1e-9:
            return np.inf
        return float(self.probs @ self.batch.objective(x))

    def objective_values(self, nonant_cache) -> np.ndarray:
        """(S,) per-scenario objectives at the fixed candidate."""
        x = self._fix_and_solve(nonant_cache)
        return self.batch.objective(x)
