"""Xhat_Eval: fix-and-evaluate candidate first-stage solutions.

TPU-native analogue of ``mpisppy/utils/xhat_eval.py:29-434``.  The reference
fixes the nonant Pyomo variables to a candidate and re-solves every scenario
through the external solver (``evaluate`` / ``evaluate_one``,
xhat_eval.py:261-330).  Here "fixing" is a bound clamp on the nonant columns of
the HBM-resident batch (lb = ub = candidate) and the evaluation is one batched
ADMM solve — so trying a candidate costs a single device program, which is what
makes the inner-bound spokes (xhatshuffle et al.) cheap.

Feasibility of the fixed problem is judged by the solver's primal residual
(the analogue of spopt.py:175-195 solver-status checks); an infeasible
candidate evaluates to +inf (for minimization).
"""

from __future__ import annotations

import numpy as np

from .spopt import SPOpt


class Xhat_Eval(SPOpt):
    """An SPOpt that evaluates fixed first-stage candidates.

    Typical use (mirrors xhat_eval.py:261-330)::

        ev = Xhat_Eval(options, names, scenario_creator, ...)
        z_hat = ev.evaluate(nonant_cache)   # expected objective, or +inf

    Integer recourse: the reference's external MIP solver returns integral
    second-stage solutions natively; here a ROUND-AND-DIVE loop over the
    batched LP solves does (fix near-integral integer columns, re-solve,
    repeat) — options["xhat_dive_rounds"] bounds the dives (default 12).
    """

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.tee_rank0_solves = False

    @staticmethod
    def _dive_round(x, ints, lb, ub, choose_up):
        """One dive clamp: snap near-integral free integer columns, then
        force the single most fractional free column per row toward the
        direction ``choose_up`` picks (True=ceil).  Forced values are CLIPPED
        into the current box first — an out-of-box force (e.g. flooring an
        x-iterate that sits just below lb) must tighten inside the domain,
        never collapse the box past its true bounds.
        Returns the updated (lb, ub) or None when nothing is left to do."""
        import numpy as np

        free = ints[None, :] & (ub > lb)
        frac = np.where(free, np.abs(x - np.round(x)), -1.0)
        if not free.any() or frac.max() < 1e-6:
            return None
        near = free & (frac < 0.1)
        vals = np.round(np.where(near, x, 0.0))
        pick = frac.argmax(axis=1)
        # force only when the worst column is OUTSIDE the snap band: if all
        # free columns are near-integral, snapping already progresses, and a
        # force would override the snap and round a ~0.08 binary the wrong way
        has = free.any(axis=1) & (frac.max(axis=1) >= 0.1)
        B = x.shape[0]
        up = choose_up(B)
        force = np.zeros_like(near)
        force[np.arange(B), pick] = has
        fx = np.where(force, x, 0.0)
        fv = np.where(up[:, None], np.ceil(fx - 1e-9), np.floor(fx + 1e-9))
        vals = np.where(force, fv, vals)
        vals = np.clip(vals, lb, ub)
        clamp = near | force
        lb = np.where(clamp, np.maximum(vals, lb), lb)
        ub = np.where(clamp, np.minimum(vals, ub), ub)
        return lb, np.maximum(ub, lb)

    def _integer_dive(self, lb, ub):
        """Drive remaining fractional integer columns integral.

        Per round: solve the batch; clamp integer columns within 0.1 of an
        integer to that integer, plus (to guarantee progress) each
        scenario's single most fractional integer column rounded UP
        (covering-style constraints stay satisfiable; the re-solve lets
        other free columns compensate).
        """
        import numpy as np

        from .spopt import batch_solve_dispatch

        b = self.batch
        ints = b.is_int
        rounds = max(1, int(self.options.get("xhat_dive_rounds", 12)))
        lb = np.array(lb, copy=True)
        ub = np.array(ub, copy=True)
        x = None
        for _ in range(rounds):
            sol = batch_solve_dispatch(b, b.c, b.q2, b.cl, b.cu, lb, ub,
                                       settings=self.admm_settings)
            x = np.asarray(sol.x)
            self.local_x = x
            self.pri_res = np.asarray(sol.pri_res)
            self.dua_res = np.asarray(sol.dua_res)
            nxt = self._dive_round(x, ints, lb, ub,
                                   lambda B: np.ones(B, dtype=bool))
            if nxt is None:
                break
            lb, ub = nxt
        return x

    def _retry_dive(self, lb0, ub0, bad):
        """Batched randomized-rounding retries for the scenarios a plain dive
        wedged (device path; replaces most uses of the serial host MILP).

        Each wedged scenario is tiled R times; every replica gets a random
        rounding direction for its forced column each round, and all
        replicas re-dive TOGETHER in one batch.  The deterministic round-up
        dive wedges exactly when some column needed the other direction
        (e.g. cardinality rows); randomization explores the corners at batch
        cost instead of per-scenario host MILPs.  Work is chunked so the
        replica batch never exceeds ``xhat_dive_retry_batch`` rows.
        Returns (solutions (len(bad), n), feasible flags).
        """
        import numpy as np

        from .spopt import batch_solve_dispatch

        b = self.batch
        cap = max(1, int(self.options.get("xhat_dive_retry_batch", 512)))
        # R in [1, cap] so the replica batch honors the memory cap
        R = max(1, min(int(self.options.get("xhat_dive_retries", 8)), cap))
        rng = np.random.RandomState(
            int(self.options.get("xhat_dive_seed", 0)))
        ints = b.is_int
        tol = max(self.options.get("feas_tol", 1e-3),
                  10.0 * self.admm_settings.eps_rel)
        rounds = max(1, int(self.options.get("xhat_dive_rounds", 12)))
        chunk = max(1, cap // R)

        xs = np.zeros((bad.size, b.num_vars))
        feas = np.zeros(bad.size, dtype=bool)
        for c0 in range(0, bad.size, chunk):
            sel = bad[c0:c0 + chunk]
            tile = lambda a: np.repeat(a[sel], R, axis=0)
            c_t, q2_t = tile(b.c), tile(b.q2)
            cl_t, cu_t = tile(b.cl), tile(b.cu)
            lb_t, ub_t = tile(lb0), tile(ub0)
            x = None
            for _ in range(rounds):
                sol = batch_solve_dispatch(
                    b, c_t, q2_t, cl_t, cu_t, lb_t, ub_t,
                    settings=self.admm_settings, rows=sel, tile=R)
                x = np.asarray(sol.x)
                nxt = self._dive_round(x, ints, lb_t, ub_t,
                                       lambda B: rng.rand(B) < 0.5)
                if nxt is None:
                    break
                lb_t, ub_t = nxt
            # best feasible replica per wedged scenario
            objs = (np.einsum("bn,bn->b", c_t, x)
                    + 0.5 * np.einsum("bn,bn->b", q2_t, x * x))
            pri = np.asarray(sol.pri_res)
            frac = np.where(ints[None, :], np.abs(x - np.round(x)), 0.0)
            ok = (pri <= tol) & (frac.max(axis=1) < 1e-5)
            objs = np.where(ok, objs, np.inf)
            for i in range(sel.size):
                grp = objs[i * R:(i + 1) * R]
                j = int(np.argmin(grp))
                feas[c0 + i] = np.isfinite(grp[j])
                xs[c0 + i] = x[i * R + j]
        return xs, feas

    def _host_milp(self, lb, ub, only=None):
        """Per-scenario HiGHS MILP with nonants clamped — the LAST-DITCH
        fallback when both diving and batched retries wedge.  This is the
        role the reference's external MIP solver plays for incumbent
        evaluation; ``only`` restricts the loop to the still-wedged slice.
        """
        import numpy as np

        from .solvers import scipy_backend

        b = self.batch
        S = b.num_scenarios
        scens = range(S) if only is None else only
        xs = np.array(self.local_x, copy=True) if self.local_x is not None \
            else np.zeros((S, b.num_vars))
        pri = np.zeros(S)
        limit = float(self.options.get("xhat_mip_time_limit", 2.0))
        gap = float(self.options.get("xhat_mip_rel_gap", 1e-4))
        for s in scens:
            res = scipy_backend.solve_lp(
                b.c[s], b.A[s], b.cl[s], b.cu[s], lb[s], ub[s],
                is_int=b.is_int, mip_rel_gap=gap, time_limit=limit)
            if res.feasible:
                xs[s] = res.x
            else:
                pri[s] = np.inf
        self.local_x = xs
        self.pri_res = pri
        self.dua_res = np.zeros(S)
        return xs

    def _fix_and_solve_bucketed(self, nonant_cache):
        """Ragged (bucketed) fix-and-evaluate with INTEGER support: each
        bucket runs the full homogeneous machinery (clamp, dive, batched
        retries, host-MILP residue) on its compact sub-batch, results
        scattered back to the bookkeeping layout.  Valid because bundle
        construction keeps the packed nonant-slot order identical between
        the global tree and every bucket's local tree (same root nonants,
        same order — only the column indices differ)."""
        import numpy as np

        from .ir import BucketedBatch

        b = self.batch
        assert isinstance(b, BucketedBatch)
        cache = np.asarray(nonant_cache, dtype=float)
        if cache.ndim == 1:
            cache = np.broadcast_to(cache, (b.num_scenarios, cache.shape[0]))
        S, n_max = b.c.shape
        x_out = np.zeros((S, n_max))
        pri = np.zeros(S)
        dua = np.zeros(S)
        # snapshot EVERY solver-state attribute the solve path touches
        # (including caches keyed on the batch — they'd go stale against the
        # sub-batches otherwise).  No cross-call amortization is lost here:
        # the homogeneous clamp path itself solves cold (solve_loop with
        # warm=False; clamped geometry makes stale duals counterproductive).
        saved = {k: getattr(self, k, None) for k in (
            "batch", "tree", "nid_sk", "_warm", "_factors", "_factors_sig",
            "_factors_age", "local_x", "pri_res", "dua_res", "_fixed_lb",
            "_fixed_ub", "_dev_consts", "_bucket_dev_consts",
            "_cached_nonants")}
        try:
            for idx_arr, sub in b.buckets:
                self.batch = sub
                self.tree = sub.tree
                self.nid_sk = sub.tree.nid_sk()
                self._warm = None
                self._factors = None
                self._factors_sig = None
                self._factors_age = 0
                self.local_x = None
                self.pri_res = None
                self.dua_res = None
                x = self._fix_and_solve(cache[idx_arr])
                x_out[idx_arr, :sub.num_vars] = np.asarray(x)
                if self.pri_res is not None:
                    pri[idx_arr] = np.asarray(self.pri_res)
                if self.dua_res is not None:
                    dua[idx_arr] = np.asarray(self.dua_res)
        finally:
            for k, v in saved.items():
                setattr(self, k, v)
        self.local_x = x_out
        self.pri_res = pri
        self.dua_res = dua
        return x_out

    def _round_int_nonants(self, cache):
        """Snap integer nonant coordinates of a candidate to integers (see
        :meth:`_fix_and_solve`); no-op for continuous families."""
        import numpy as np

        if not self.options.get("xhat_round_ints", True):
            return cache
        nid = np.asarray(self.batch.tree.nonant_indices)
        ints = np.asarray(self.batch.is_int)[nid].astype(bool)
        if not ints.any():
            return cache
        cache = np.array(cache, dtype=float, copy=True)
        cache[..., ints] = np.round(cache[..., ints])
        return cache

    def _fix_and_solve(self, nonant_cache):
        """Clamp nonants to the candidate and solve the whole batch.

        ``nonant_cache``: (K,) single candidate shared by all scenarios, or
        (S, K) per-scenario (multistage xhats fix per-node values; scenarios of
        one node must carry identical values there).

        Integer nonant coordinates are snapped to the nearest integer first
        (``xhat_round_ints``, default on): device-path donors carry
        LP-relaxation values, so families whose integers are ALL first-stage
        (UC commitment) would otherwise be "evaluated" at fractional
        commitments — never a valid incumbent, and catastrophically priced
        when fractional capacity triggers VOLL shedding.  The reference
        never faces this: its donors come from MIP subproblem solves and are
        integral already (xhatshufflelooper_bounder.py donor caches).
        Snapping preserves per-node equality, so multistage fixing is safe.
        """
        import numpy as np

        from .ir import BucketedBatch

        if isinstance(self.batch, BucketedBatch):
            return self._fix_and_solve_bucketed(nonant_cache)
        nonant_cache = self._round_int_nonants(nonant_cache)
        self.fix_nonants(nonant_cache)
        try:
            b = self.batch
            leftover_ints = b.is_int.any() and bool(
                (b.is_int[None, :] & (self._fixed_ub > self._fixed_lb)).any()
            )
            if leftover_ints and self.options.get(
                    "xhat_integer_strategy", "dive") == "milp":
                # exact per-scenario host MILPs instead of device dives:
                # the right tool for families whose SECOND stage is mostly
                # binary scheduling (e.g. USAR), where rounding dives wedge
                # on hundreds of coupled binaries but each scenario MILP is
                # solver-trivial — the reference's posture for every
                # incumbent evaluation (extensions/xhatbase.py:38-230)
                x = self._host_milp(self._fixed_lb, self._fixed_ub)
            elif leftover_ints:
                x = self._integer_dive(self._fixed_lb, self._fixed_ub)
                tol = max(self.options.get("feas_tol", 1e-3),
                          10.0 * self.admm_settings.eps_rel)
                ints = b.is_int[None, :]
                frac = np.where(ints, np.abs(x - np.round(x)), 0.0)
                bad = np.flatnonzero(
                    (np.asarray(self.pri_res) > tol)
                    | (frac.max(axis=1) > 1e-5))
                if bad.size:
                    # batched randomized-rounding retries for wedged
                    # scenarios (device path)
                    xs, feas = self._retry_dive(self._fixed_lb,
                                                self._fixed_ub, bad)
                    x = np.array(x, copy=True)   # jax arrays are read-only
                    x[bad[feas]] = xs[feas]
                    self.local_x = x
                    pri = np.array(self.pri_res, copy=True)
                    pri[bad[feas]] = 0.0
                    self.pri_res = pri
                    still = bad[~feas]
                    if still.size:
                        # last ditch: exact host MILPs on the residue only
                        x = self._host_milp(self._fixed_lb, self._fixed_ub,
                                            only=still)
            else:
                # cold start: the clamped problem's geometry differs enough
                # that stale warm duals slow ADMM down rather than help.
                # With a model repair available, the host-LP straggler
                # rescue is pure waste here (O(seconds) per plateaued
                # scenario; the repair certifies feasibility for free)
                saved_rescue = self.options.get("straggler_rescue", True)
                if getattr(self.batch, "repair_fn", None) is not None:
                    self.options["straggler_rescue"] = False
                try:
                    x = self.solve_loop(warm=False)
                finally:
                    self.options["straggler_rescue"] = saved_rescue
            x = self._repair_and_verify(x)
        finally:
            self.restore_nonants()
        return x

    def _repair_and_verify(self, x):
        """Model-declared feasibility repair + EXACT verification.

        Families with full recourse attach ``repair_fn`` to their batch
        (e.g. UC: shed/reserve slacks close any dispatch residual in closed
        form — models/uc_data._make_repair).  The repaired point is
        verified against the ORIGINAL rows/bounds with one sparse matvec
        per scenario; verified scenarios get an exact zero residual, so
        ``evaluate``'s feasibility gate passes on true feasibility instead
        of ADMM residuals.  This is what makes S=1000 incumbent evaluation
        affordable: the host-LP straggler rescue prices O(seconds) PER
        plateaued scenario (spopt straggler_lp_max), which forbade
        full-scale evaluation outright.
        """
        rf = getattr(self.batch, "repair_fn", None)
        if rf is None:
            return x
        import numpy as np
        import scipy.sparse as sp

        b = self.batch
        x = rf(np.asarray(x, float), b)
        A_sh = getattr(b, "A_shared", None)
        key = (id(A_sh if A_sh is not None else b.A), b.version)
        cached = getattr(self, "_verify_csr", None)
        if cached is None or cached[0] != key:
            An = np.asarray(A_sh) if A_sh is not None \
                else None
            self._verify_csr = (key, sp.csr_matrix(An)
                                if An is not None else None)
            cached = self._verify_csr
        tol = float(self.options.get("repair_verify_tol", 1e-6))
        S = b.num_scenarios
        if cached[1] is not None:
            r = np.asarray((cached[1] @ x.T).T)          # (S, m)
        else:
            r = np.einsum("smn,sn->sm", np.asarray(b.A), x)
        scale = np.maximum(1.0, np.maximum(
            np.abs(np.where(np.isfinite(b.cl), b.cl, 0.0)),
            np.abs(np.where(np.isfinite(b.cu), b.cu, 0.0))))
        row_viol = np.maximum(
            np.maximum(b.cl - r, r - b.cu), 0.0) / scale
        bscale = np.maximum(1.0, np.maximum(
            np.abs(np.where(np.isfinite(b.lb), b.lb, 0.0)),
            np.abs(np.where(np.isfinite(b.ub), b.ub, 0.0))))
        bnd_viol = np.maximum(
            np.maximum(b.lb - x, x - b.ub), 0.0) / bscale
        pri = np.maximum(row_viol.max(axis=1), bnd_viol.max(axis=1))
        # verified scenarios are EXACTLY feasible; the rest keep their true
        # violation (the inf gate then reports genuine infeasibility, e.g.
        # a candidate breaking min-up/down rows the repair cannot touch)
        self.local_x = x
        self.pri_res = np.where(pri <= tol, 0.0, pri + 1.0)
        self.dua_res = np.zeros(S)
        return x

    def evaluate_one(self, nonant_cache, scenario_index: int) -> float:
        """Objective of ONE scenario at the fixed candidate
        (xhat_eval.py:261-292)."""
        x = self._fix_and_solve(nonant_cache)
        if self.pri_res is not None:
            tol = self.options.get("feas_tol", 1e-3)
            if self.pri_res[scenario_index] > tol:
                return np.inf
        return float(self.batch.objective(x)[scenario_index])

    def evaluate(self, nonant_cache) -> float:
        """Expected objective at the fixed candidate; +inf if any scenario is
        infeasible (xhat_eval.py:293-330 + feas_prob check)."""
        x = self._fix_and_solve(nonant_cache)
        if self.feas_prob() < 1.0 - 1e-9:
            return np.inf
        return float(self.probs @ self.batch.objective(x))

    def objective_values(self, nonant_cache) -> np.ndarray:
        """(S,) per-scenario objectives at the fixed candidate."""
        x = self._fix_and_solve(nonant_cache)
        return self.batch.objective(x)
