"""Extensive-form assembly and solve.

Reference semantics: ``sputils.create_EF / _create_EF_from_scen_dict``
(sputils.py:127-341) make each scenario a sub-block of one model with a
probability-weighted objective and nonanticipativity equalities.  Here nonant
variables that share a tree node are *merged into one column* (equivalent to the
reference's reference-variable + equality formulation, but smaller), and the EF
is solved either by the HiGHS validation backend or by the TPU ADMM solver.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from .ir import ScenarioBatch
from .solvers import scipy_backend


@dataclasses.dataclass
class EFProblem:
    """Monolithic EF in canonical form, plus the column maps back to scenarios."""

    c: np.ndarray
    q2: np.ndarray
    A: np.ndarray
    cl: np.ndarray
    cu: np.ndarray
    lb: np.ndarray
    ub: np.ndarray
    is_int: np.ndarray
    const: float
    col_of: np.ndarray       # (S, n) scenario-var -> EF column
    batch: ScenarioBatch

    def split_solution(self, x_ef: np.ndarray) -> np.ndarray:
        """(S, n) per-scenario solution from an EF solution vector."""
        return x_ef[self.col_of]


def build_ef(batch: ScenarioBatch) -> EFProblem:
    S, n = batch.num_scenarios, batch.num_vars
    tree = batch.tree
    nonant_idx = tree.nonant_indices            # (K,) var slots
    K = nonant_idx.shape[0]

    # EF column map: one column per (node, nonant-slot-within-stage); leaf vars
    # get a private column per scenario.
    col_of = -np.ones((S, n), dtype=np.int64)
    node_slot_col: dict[tuple[int, int], int] = {}
    ncols = 0
    for s in range(S):
        for k in range(K):
            stage = tree.nonant_stage[k]
            node = int(tree.scen_node_ids[s, stage - 1])
            key = (node, k)
            if key not in node_slot_col:
                node_slot_col[key] = ncols
                ncols += 1
            col_of[s, nonant_idx[k]] = node_slot_col[key]
    for s in range(S):
        for j in range(n):
            if col_of[s, j] < 0:
                col_of[s, j] = ncols
                ncols += 1

    probs = batch.probs
    c = np.zeros(ncols)
    q2 = np.zeros(ncols)
    lb = np.full(ncols, -np.inf)
    ub = np.full(ncols, np.inf)
    is_int = np.zeros(ncols, dtype=bool)
    for s in range(S):
        cols = col_of[s]
        np.add.at(c, cols, probs[s] * batch.c[s])
        np.add.at(q2, cols, probs[s] * batch.q2[s])
        lb[cols] = np.maximum(lb[cols], batch.lb[s])
        ub[cols] = np.minimum(ub[cols], batch.ub[s])
        is_int[cols] |= batch.is_int

    m = batch.num_rows
    A = np.zeros((S * m, ncols))
    cl = np.zeros(S * m)
    cu = np.zeros(S * m)
    for s in range(S):
        rows = slice(s * m, (s + 1) * m)
        np.add.at(A[rows], (slice(None), col_of[s]), batch.A[s])
        cl[rows] = batch.cl[s]
        cu[rows] = batch.cu[s]

    return EFProblem(
        c=c, q2=q2, A=A, cl=cl, cu=cu, lb=lb, ub=ub, is_int=is_int,
        const=float(probs @ batch.const), col_of=col_of, batch=batch,
    )


def solve_ef(batch: ScenarioBatch, solver="highs", mip=True, **kw):
    """Solve the EF; returns (objective, per-scenario solutions (S, n)).

    ``solver='highs'`` is the validation path (external-solver analogue,
    ef.py:66-93); ``solver='admm'`` runs the TPU-native batched solver on the
    single monolithic problem.
    """
    ef = build_ef(batch)
    if solver == "highs":
        res = scipy_backend.solve_lp(
            ef.c, ef.A, ef.cl, ef.cu, ef.lb, ef.ub,
            is_int=ef.is_int if mip else None, q2=ef.q2, const=ef.const, **kw,
        )
        if not res.feasible:
            raise RuntimeError(f"EF infeasible or solver failure: {res.status}")
        return res.obj, ef.split_solution(res.x)
    elif solver == "admm":
        from .solvers import admm

        if mip and np.any(ef.is_int):
            raise NotImplementedError(
                "solver='admm' solves the continuous relaxation only; pass "
                "mip=False explicitly, or use solver='highs' for integer EFs"
            )
        sol = admm.solve_single(
            c=ef.c, q2=ef.q2, A=ef.A, cl=ef.cl, cu=ef.cu, lb=ef.lb, ub=ef.ub, **kw
        )
        obj = float(ef.c @ sol.x + 0.5 * ef.q2 @ (sol.x * sol.x) + ef.const)
        return obj, ef.split_solution(np.asarray(sol.x))
    raise ValueError(f"unknown EF solver {solver!r}")
