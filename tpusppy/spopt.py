"""SPOpt: batched subproblem solving and expectation reductions.

TPU-native analogue of ``mpisppy/spopt.py:23-868``.  The reference's
``solve_one``/``solve_loop`` (spopt.py:85-307) — a serial per-rank loop handing
each Pyomo model to an external solver — becomes ONE vmapped ADMM call on the
HBM-resident batch, warm-started between calls (the persistent-solver analogue,
spopt.py:129-144).  Expectations (``Eobjective``/``Ebound``/``feas_prob``,
spopt.py:310-466) are probability-weighted contractions; under a mesh they are
psums on the scenario axis.
"""

from __future__ import annotations

import collections
import dataclasses
import itertools
import threading
import time

import numpy as np

from . import global_toc
from .obs import metrics as _metrics
from .obs import trace as _trace
from .spbase import SPBase
from .solvers import admm, hostsync

_BATCH_TOKENS = itertools.count(1)


def _batch_token(b):
    """Monotone identity token for cache keys: unlike ``id()`` it is never
    reused after the batch is collected, and unlike the object itself it is
    safely ``==``-comparable inside key tuples (dataclass ``__eq__`` on
    numpy fields raises)."""
    tok = getattr(b, "_sig_token", None)
    if tok is None:
        tok = next(_BATCH_TOKENS)
        b._sig_token = tok
    return tok


# Content-keyed device cache for big constraint matrices.  Every cylinder in
# a wheel builds its own ScenarioBatch from the same scenario_creator, so
# without content sharing each one uploads (and keeps) its own device copy
# of the identical shared (m, n) A — ~800 MB x n_cylinders at reference UC
# shapes, a large slice of one chip's HBM.  Keyed by sha1 of the bytes;
# tiny LRU since distinct big matrices rarely coexist.  The lock matters:
# wheel cylinders are threads that reach their first solve near-
# simultaneously, and both the hash and the host->device upload release
# the GIL — unlocked, every thread would miss and upload its own copy.
_DEV_A_CACHE: dict = collections.OrderedDict()
_DEV_A_LOCK = threading.Lock()


def _cached_dev_A(A_np, tag_key, build):
    """Content-keyed device-A cache insert/lookup with the shared eviction
    policy: keep the single newest prior same-(shape, dtype, kind) entry
    (cut rounds mutate the shared A; round k and k-1 coexist) and a
    4-entry LRU cap — stale versions must never strand HBM, on the dense
    OR the sparse path."""
    import hashlib

    with _DEV_A_LOCK:
        digest = hashlib.sha1(
            memoryview(np.ascontiguousarray(A_np))).hexdigest()
        key = (digest,) + tag_key
        dev = _DEV_A_CACHE.pop(key, None)
        if dev is None:
            same = [k for k in _DEV_A_CACHE if k[1:] == key[1:]]
            for k in same[:-1]:
                del _DEV_A_CACHE[k]
            dev = build()
        _DEV_A_CACHE[key] = dev         # re-insert = LRU touch
        while len(_DEV_A_CACHE) > 4:
            _DEV_A_CACHE.popitem(last=False)
        return dev


def _device_A(A_src, dt, sparse="auto"):
    import jax.numpy as jnp

    from .solvers.sparse import SparseA, should_sparsify

    A_np = np.asarray(A_src)
    # large very-sparse SHARED matrices upload as SparseA: gather/
    # segment-sum matvecs + block/Woodbury structured KKT (see
    # tpusppy/solvers/sparse.py) — the same policy the sharded rate path
    # applies in parallel/sharded.shard_batch.  (Checked before the
    # small-matrix early return so tests can force sparse=True on small
    # families.)
    if A_np.ndim == 2 and (sparse is True or
                           (sparse == "auto" and should_sparsify(A_np))):
        return _cached_dev_A(
            A_np, (A_np.shape, str(dt), "sparse"),
            lambda: SparseA.from_dense(A_np, jnp.dtype(dt), structure=True))
    if A_np.nbytes < 16 << 20:          # small matrices: not worth hashing
        return jnp.asarray(A_np, dt)
    return _cached_dev_A(A_np, (A_np.shape, str(dt)),
                         lambda: jnp.asarray(A_np, dt))


def clear_device_caches():
    """Release the content-keyed device-A cache (e.g. between benchmark
    phases that need the HBM back; ``jax.clear_caches()`` doesn't reach
    module-level array references)."""
    with _DEV_A_LOCK:
        _DEV_A_CACHE.clear()


def _np_dual_objective(q, A, cl, cu, lb, ub, y, x_hint, margin_scale=100.0):
    """Single-scenario numpy twin of :func:`admm.dual_objective` (LP case),
    used by the straggler rescue to validate host duals."""
    base, g = _np_dual_cut(q, A, cl, cu, lb, ub, y, x_hint,
                           np.zeros(q.shape[0], dtype=bool), margin_scale)
    return base


def _np_dual_cut(q, A, cl, cu, lb, ub, y, x_hint, clamp_mask,
                 margin_scale=100.0):
    """Single-scenario numpy twin of :func:`admm.dual_cut` (LP case):
    ``Q(x̂') >= base + g[clamp].x̂'`` for any y (weak duality)."""
    big = admm.BIG
    cl = np.clip(np.nan_to_num(cl, nan=-big), -big, big)
    cu = np.clip(np.nan_to_num(cu, nan=big), -big, big)
    fin_cl, fin_cu = cl > -big / 2, cu < big / 2
    fin_lb, fin_ub = lb > -big / 2, ub < big / 2
    y = np.where(~fin_cu & (y > 0), 0.0, y)
    y = np.where(~fin_cl & (y < 0), 0.0, y)
    row = (-np.maximum(y, 0) * np.where(fin_cu, cu, 0.0)
           - np.minimum(y, 0) * np.where(fin_cl, cl, 0.0)).sum()
    X = margin_scale * (1.0 + np.abs(x_hint).max())
    L = np.where(fin_lb, np.maximum(lb, -big), -X)
    U = np.where(fin_ub, np.minimum(ub, big), X)
    g = q + A.T @ y
    term = g * np.where(g >= 0, L, U)
    base = float(row + np.where(clamp_mask, 0.0, term).sum())
    return base, g


def batch_solve_dispatch(b, q, q2, cl, cu, lb, ub, settings, warm=None,
                         rows=None, tile=1):
    """One-shot batched solve honoring shared-A.

    Callers pass their (possibly row-sliced / replica-tiled) objective and
    bound arrays; the constraint matrix is taken from the batch: the single
    (m, n) ``A_shared`` when present (NEVER materializing the (S, m, n)
    broadcast view — that is the memory wall shared-A exists to break),
    else the dense per-scenario tensor sliced by ``rows`` / repeated
    ``tile`` times to match the leading axis.
    """
    from .solvers import shared_admm

    if getattr(b, "A_shared", None) is not None:
        return shared_admm.solve_shared(q, q2, b.A_shared, cl, cu, lb, ub,
                                        settings=settings, warm=warm)
    A = b.A if rows is None else b.A[rows]
    if tile > 1:
        A = np.repeat(A, tile, axis=0)
    return admm.solve_batch(q, q2, A, cl, cu, lb, ub, settings=settings,
                            warm=warm)


def dispatch_A(b):
    """The A to hand device code: the single (m, n) shared matrix when the
    batch has one (never the (S, m, n) broadcast view), else the dense
    per-scenario tensor."""
    A_shared = getattr(b, "A_shared", None)
    return b.A if A_shared is None else A_shared


def mega_arrays_for_batch(b, dt, sparse="auto"):
    """Device-resident :class:`~tpusppy.parallel.sharded.PHArrays` for
    one HOMOGENEOUS ScenarioBatch, built WITHOUT an opt instance — the
    standalone twin of :meth:`SPOpt._mega_arrays` for callers that own
    no PHBase (the continuous-batching runner,
    :mod:`tpusppy.service.batching`, builds one per tenant slot).  Rides
    the same content-keyed device-A cache (``_device_A``), so K tenants
    of one family with identical shared A hold ONE device copy."""
    import jax.numpy as jnp

    from .parallel import sharded

    A_shared = getattr(b, "A_shared", None)
    A_src = b.A if A_shared is None else A_shared
    if A_shared is None:
        sparse = False            # per-scenario A: dense batched path
    S = b.num_scenarios
    tree = b.tree
    return sharded.PHArrays(
        c=jnp.asarray(b.c, dt), q2=jnp.asarray(b.q2, dt),
        A=_device_A(A_src, dt, sparse=sparse),
        cl=jnp.asarray(b.cl, dt), cu=jnp.asarray(b.cu, dt),
        lb=jnp.asarray(b.lb, dt), ub=jnp.asarray(b.ub, dt),
        const=jnp.asarray(np.broadcast_to(b.const, (S,)), dt),
        probs=jnp.asarray(tree.scen_prob, dt),
        onehot=jnp.asarray(tree.onehot_sk_n(), dt),
        nid_sk=jnp.asarray(tree.nid_sk(), jnp.int32))


def bucket_shared(sub) -> bool:
    """Whether a bucket's sub-batch runs the SHARED-A engine.  Sharing
    must be real: a singleton sub-batch trivially detects identity-shared
    A (``all(p.A is A0)`` over one member), but dense is equally cheap at
    S_b=1 and the shared engine's batch-level rho adaptation/termination
    semantics converge differently on some families — the observed case
    is a 3-merge farmer bundle whose shared solve stalls where the dense
    solve converges."""
    return getattr(sub, "A_shared", None) is not None \
        and sub.num_scenarios > 1


def _certified_dual_eval(args):
    """(dvals, margin) — the weak-duality bound with its X-cap hardening
    margin (admm.dual_objective_margin: extends the certificate's validity
    box on free coordinates from X to 10X; ~0 for tight duals).  Single
    source for every certified dual-bound site (Edualbound_perscen, donor
    transfer).  ONE device program + ONE fetch
    (admm.dual_objective_with_margin) — bound spokes call this every wheel
    iteration, and two separate jitted evaluations cost two serial RPCs
    over a remote tunnel."""
    packed = hostsync.fetch(admm.dual_objective_with_margin(*args))
    packed = np.asarray(packed, dtype=float)
    return packed[0], packed[1]


def _pick_dual_sign(q, A, cl, cu, lb, ub, duals, x, obj):
    """scipy's marginal sign convention is opposite ours and varies by
    constraint shape; rather than trust it, pick the sign whose dual
    objective is closest to the primal optimum (strong duality makes the
    right one ~exact; the wrong one collapses toward -inf).  Returns y."""
    best = None
    for sign in (-1.0, 1.0):
        ys = sign * duals
        dval = _np_dual_objective(q, A, cl, cu, lb, ub, ys, x)
        if best is None or abs(obj - dval) < abs(best[0]):
            best = (obj - dval, ys)
    return best[1]


def host_exact_clamp_cut(batch, q, s, lb, ub, clamp_idx):
    """Host-exact clamped-scenario solve + weak-duality cut (LP only).

    Returns ``(ok, obj, cut_base, grad)`` with const included in obj/base;
    ``Q_s(x̂') >= cut_base + grad . x̂'`` for every clamp value x̂'.  Simplex
    duals are exact and sign-feasible, so the weak-duality cut is TIGHT —
    the shared fallback for Benders/cross-scenario cut generation when the
    batched solve's duals leave a cut gap (degenerate or stalled scenarios).
    """
    from .solvers import scipy_backend

    res = scipy_backend.solve_lp_with_duals(
        q[s], batch.A[s], batch.cl[s], batch.cu[s], lb[s], ub[s])
    if not res.feasible or res.duals is None:
        return False, np.inf, None, None
    obj = float(q[s] @ res.x)
    ys = _pick_dual_sign(q[s], batch.A[s], batch.cl[s], batch.cu[s],
                         lb[s], ub[s], res.duals, res.x, obj)
    mask = np.zeros(batch.A.shape[2], dtype=bool)
    mask[clamp_idx] = True
    base, g = _np_dual_cut(q[s], batch.A[s], batch.cl[s], batch.cu[s],
                           lb[s], ub[s], ys, res.x, mask)
    return (True, obj + batch.const[s], base + batch.const[s], g[clamp_idx])


class SPOpt(SPBase):
    """Adds solving to SPBase."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._warm = None            # (x, z, y, yx) device arrays
        self.local_x = None          # (S, n) last solution
        self.pri_res = None
        self.dua_res = None
        self._fixed_lb = None        # active nonant fixing overlay (S, n) or None
        self._fixed_ub = None
        self._cached_nonants = None
        self._factors = None         # admm.Factors of the last refresh solve
        self._factors_sig = None
        self._factors_age = 0
        self._dev_state = None       # device-resident PHState (lean megasteps)
        self._host_state_stale = False

    def _device_consts(self, dt):
        """Device-resident (A, cl, cu) cached on batch.version: the (S, m, n)
        constraint tensor dominates host->device traffic and never changes
        between solves (both the solve_loop hot path and the spokes'
        Edualbound calls go through here)."""
        import jax.numpy as jnp

        b = self.batch
        # the batch token in the key: version numbers can collide across
        # DIFFERENT batch objects (e.g. sub-batches temporarily installed
        # by _fix_and_solve_bucketed, all at version 0)
        key = (_batch_token(b), getattr(b, "version", 0), str(dt))
        cached = getattr(self, "_dev_consts", None)
        if cached is None or cached[0] != key:
            # shared-A batches upload the single (m, n) matrix, not the
            # (S, m, n) broadcast view (which would materialize S copies)
            A_src = b.A if getattr(b, "A_shared", None) is None else b.A_shared
            sparse = self.options.get("sparse_device_A", "auto")
            if getattr(b, "A_shared", None) is None:
                sparse = False            # per-scenario A: dense batched path
            cached = (key, (_device_A(A_src, dt, sparse=sparse),
                            jnp.asarray(b.cl, dt),
                            jnp.asarray(b.cu, dt)))
            self._dev_consts = cached
        return cached[1]

    def _solve_sig(self, q2, lb, ub):
        """Validity signature of cached Factors.

        The factorization depends on (A, q2, rho patterns); rho patterns
        depend only on which rows are equalities/loose and which columns are
        clamped/finite — NOT on bound values.  So fix-and-evaluate solves
        (same clamp pattern, new candidate values) keep reusing factors.
        """
        lb = np.asarray(lb)
        ub = np.asarray(ub)
        patt = ((np.abs(ub - lb) < 1e-10).astype(np.uint8)
                + 2 * (lb > -admm.BIG / 2).astype(np.uint8)
                + 4 * (ub < admm.BIG / 2).astype(np.uint8))
        return (float(np.sum(np.asarray(q2))), hash(patt.tobytes()),
                _batch_token(self.batch),
                getattr(self.batch, "version", 0), self.admm_settings)

    # ---- the hot loop -------------------------------------------------------
    def solve_loop(self, q=None, q2=None, warm=True, dis_W=None, dis_prox=None):
        """Solve the whole local batch; returns (S, n) solutions.

        ``q``/``q2`` override the linear/diagonal-quadratic objective (PH passes
        its augmented objective here).  ``dis_W``/``dis_prox`` exist for API
        parity (PHBase computes q itself); they are accepted and ignored here.

        Factorization-amortized: a full adaptive "refresh" solve every
        ``solver_refresh_every`` calls (and whenever the problem structure
        changes) caches Ruiz scaling + rho vectors + the KKT factorization;
        calls in between are sweep-only frozen solves — no batched
        factorization or polish in the program at all.  A frozen solve that
        exhausts its sweep budget triggers an immediate adaptive re-solve, so
        accuracy never silently degrades.
        """
        ext = getattr(self, "extobject", None)
        if ext is not None:
            ext.pre_solve()
        # any host-path solve supersedes the device-resident wheel state
        # (callers synced the mirrors first — PHBase.iterk_loop's
        # boundary protocol); keeping a stale _dev_state here would let a
        # later megastep window resume from pre-refresh duals
        self._dev_state = None
        b = self.batch
        q = b.c if q is None else q
        q2 = b.q2 if q2 is None else q2
        lb = b.lb if self._fixed_lb is None else self._fixed_lb
        ub = b.ub if self._fixed_ub is None else self._fixed_ub

        from .ir import BucketedBatch

        if isinstance(b, BucketedBatch):
            x = self._solve_loop_bucketed(b, q, q2, lb, ub, warm)
            if ext is not None:
                ext.post_solve()
            return x

        shared = getattr(b, "A_shared", None) is not None
        # device-resident (A, cl, cu): avoids re-uploading the constraint
        # tensor (up to ~GB for shared-A UC) on EVERY solve call, and shares
        # one device copy of identical A across wheel cylinders
        A_d, cl_d, cu_d = self._device_consts(self.admm_settings.jdtype())
        slot = {"warm": self._warm, "factors": self._factors,
                "sig": self._factors_sig, "age": self._factors_age,
                "ref_worst": getattr(self, "_factors_ref_worst", None),
                "n_div_prev": getattr(self, "_n_div_prev", 0)}
        sol, meas = self._solve_amortized(
            (q, q2, A_d, cl_d, cu_d, lb, ub), slot, warm, None,
            shared=shared)
        self._warm = slot["warm"]
        self._factors = slot["factors"]
        self._factors_sig = slot["sig"]
        self._factors_age = slot["age"]
        self._factors_ref_worst = slot.get("ref_worst")
        self._n_div_prev = slot.get("n_div_prev", 0)
        # everything the iteration reads came back in the ONE packed fetch
        # _solve_amortized already performed (doc/pipeline.md)
        self.local_x = meas["x"]
        self.pri_res = meas["pri"]
        self.dua_res = meas["dua"]
        self._last_all_done = bool(meas["all_done"])
        if ext is not None:
            ext.post_solve()
        return self.local_x

    def _fetch_measure(self, sol):
        """ONE device fetch of everything the host reads from a solve
        (admm.measure_pack: residuals + iteration counter + convergence
        vote + x) — the single-fetch wheel-iteration discipline
        (doc/pipeline.md).  Returns the measure_unpack dict."""
        S, n = sol.x.shape
        return admm.measure_unpack(
            hostsync.fetch(admm.measure_pack(sol)), S, n)

    def _solve_amortized(self, args, slot: dict, warm: bool, rescue_batch,
                         shared: bool = False):
        """The factorization-amortization protocol shared by the homogeneous
        and bucketed paths: frozen attempt under a validity signature with a
        sweep-budget fallback, else an adaptive factored solve + straggler
        rescue.  ``slot`` carries warm/factors/sig/age state; ``args`` is
        the (q, q2, A, cl, cu, lb, ub) tuple (A is (m, n) when ``shared``,
        dispatching to the shared-A engine).  Polished states warm-start
        the NEXT objective's solve well (the PH persistent-solver pattern);
        raw iterates matter only when re-solving the SAME problem repeatedly
        (e.g. the Benders root).

        Returns ``(sol, meas)``: the device solution (its warm state never
        leaves the device) and the single-fetch measurement dict
        (:meth:`_fetch_measure`) every downstream host read — acceptance
        test, mixed-precision guard, straggler rescue, ``local_x`` — is
        served from.  Steady-state frozen cost: ONE measurement RPC per
        PH iteration for shapes that fit a single dispatch (the common
        wheel families), plus — only when the shape segments — the
        continuation's own per-segment stop-stats fetches (one for the
        incoming verdict, the rest overlapped with device compute under
        the pipelined protocol).  Previously every iteration paid 3-4
        separate array fetches regardless.
        """
        if shared:
            from .solvers import shared_admm
            frozen_fn = shared_admm.solve_shared_frozen
            factored_fn = shared_admm.solve_shared_factored
        else:
            frozen_fn = admm.solve_batch_frozen
            factored_fn = admm.solve_batch_factored
        refresh_every = self._refresh_every()
        sig = (self._solve_sig(args[1], args[5], args[6])
               if refresh_every > 1 else None)
        sol = meas = None
        from .solvers import segmented

        if (refresh_every > 1 and warm and slot.get("warm") is not None
                and slot.get("factors") is not None
                and slot.get("sig") == sig
                and slot.get("age", 0) < refresh_every):
            # segmented: oversized sweep loops are split into bounded
            # dispatches (the remote TPU worker kills ~60s+ executions);
            # want_converged=False — the convergence vote rides the packed
            # measurement below instead of a separate done fetch
            with _trace.span(None, "solve.frozen") as _sp:
                cand, _ = segmented.solve_frozen_segmented(
                    frozen_fn, args, slot["factors"], self.admm_settings,
                    warm=slot["warm"], want_converged=False)
                meas_c = self._fetch_measure(cand)
                if _trace.enabled():   # payload dicts only when tracing
                    _sp.add(iters=meas_c["iters"],
                            all_done=meas_c["all_done"])
            worst_c = float(max(np.max(meas_c["pri"]),
                                np.max(meas_c["dua"])))
            if admm.precision_guard_trips(
                    cand, self.admm_settings, slot.get("ref_worst"),
                    stats=(worst_c, meas_c["all_done"])):
                # mixed-precision residual guard: the low-precision frozen
                # solve parked far above the family's full-precision floor
                # — fall back to the full-precision frozen program on the
                # SAME cached factors (no refactorization)
                _metrics.inc("precision.guard_trips")
                if _trace.enabled():
                    _trace.instant(None, "precision_guard_trip",
                                   worst=worst_c,
                                   ref_worst=slot.get("ref_worst"))
                st_full = dataclasses.replace(self.admm_settings,
                                              sweep_precision="highest")
                with _trace.span(None, "solve.frozen_full_precision"):
                    cand, _ = segmented.solve_frozen_segmented(
                        frozen_fn, args, slot["factors"], st_full,
                        warm=slot["warm"], want_converged=False)
                    meas_c = self._fetch_measure(cand)
            # accept when the sweep budget sufficed (converged to eps) OR
            # every scenario already sits inside the rescue-tolerance
            # ladder: an adaptive re-solve of a plateaued batch (UC prox
            # batches plateau at ~1e-3 primal no matter the budget) burns
            # a full factored solve per hub iteration for nothing — the
            # refresh cadence (slot age) re-solves adaptively anyway
            tol_lp, tol_qp = self._straggler_tols()
            tol_s = np.where(
                np.any(np.asarray(args[1]) != 0.0, axis=-1), tol_qp, tol_lp)
            if (meas_c["all_done"]
                    or bool(np.all((meas_c["pri"] <= tol_s)
                                   & (meas_c["dua"] <= tol_s)))):
                sol, meas = cand, meas_c
                slot["age"] = slot.get("age", 0) + 1
        if sol is None:
            # the REFRESH runs full precision end to end — including its
            # segmented frozen continuations and polish finale — both by
            # design (doc/precision.md: refresh solves are never lowered)
            # and so ref_worst below is a genuine full-precision floor for
            # the guard to anchor on
            st_adpt = self.admm_settings
            if st_adpt.sweep_precision not in (None, "highest"):
                st_adpt = dataclasses.replace(st_adpt,
                                              sweep_precision="highest")
            with _trace.span(None, "solve.refresh"):
                sol, factors, _ = segmented.solve_factored_segmented(
                    frozen_fn, factored_fn, args, st_adpt,
                    warm=slot.get("warm") if warm else None, shared=shared,
                    want_converged=False)
                slot["factors"] = factors
                slot["sig"] = sig
                slot["age"] = 1
                meas = self._fetch_measure(sol)
            # full-precision residual floor of this family at this
            # operating point — the mixed-precision guard's reference
            slot["ref_worst"] = float(
                max(np.max(meas["pri"]), np.max(meas["dua"])))
            sol, meas = self._rescue_stragglers(
                sol, args[0], args[1], args[5], args[6],
                batch=rescue_batch, meas=meas)
        # shared-A divergence guard observability: frozen (exploded)
        # scenarios surface as non-finite residuals in the packed
        # measurement — count them so a run quietly degrading to frozen
        # iterates is visible in the flight recorder, not just in a
        # failed convergence assertion three reruns later.  Billed on
        # the INCREASE over this slot's previous solve only: a frozen
        # scenario stays non-finite every subsequent iteration, and
        # re-counting it would inflate the freeze count ~iterations-fold
        n_div = int(np.count_nonzero(~np.isfinite(meas["pri"])))
        new_div = n_div - slot.get("n_div_prev", 0)
        slot["n_div_prev"] = n_div
        if new_div > 0:
            _metrics.inc("solve.divergence_freezes", new_div)
            if _trace.enabled():
                _trace.instant(None, "divergence_freeze", scenarios=new_div,
                               total_frozen=n_div)
        slot["warm"] = (sol.x, sol.z, sol.y, sol.yx)
        return sol, meas

    def _solve_loop_bucketed(self, b, q, q2, lb, ub, warm):
        """Per-bucket batched solves for ragged families (one compact
        compiled program per shape bucket), scattered back into the
        (S, n_max) bookkeeping layout.  Per-bucket warm states chain like
        the homogeneous path's; factors amortization is per-bucket too.

        Device-lifted (ROADMAP item 1): each bucket's (A, cl, cu) is
        device-resident (:meth:`_bucket_device_consts` — no re-upload per
        solve), and a bucket whose sub-batch carries ``A_shared``
        dispatches the shared-A engine on the single (m, n) matrix
        instead of materializing the (S_b, m, n) broadcast.
        """
        S, n_max = b.c.shape
        x_out = np.zeros((S, n_max))
        pri = np.zeros(S)
        dua = np.zeros(S)
        all_done = True
        slots = getattr(self, "_bucket_slots", None)
        if slots is None or len(slots) != len(b.buckets):
            slots = self._bucket_slots = [dict() for _ in b.buckets]
        consts = self._bucket_device_consts(self.admm_settings.jdtype())
        for k, (idx, sub) in enumerate(b.buckets):
            n, m = sub.num_vars, sub.num_rows
            A_d, cl_d, cu_d = consts[k]
            args = (np.asarray(q)[idx, :n], np.asarray(q2)[idx, :n],
                    A_d, cl_d, cu_d,
                    np.asarray(lb)[idx, :n], np.asarray(ub)[idx, :n])
            _, meas = self._solve_amortized(
                args, slots[k], warm, sub, shared=bucket_shared(sub))
            x_out[idx, :n] = meas["x"]
            pri[idx] = meas["pri"]
            dua[idx] = meas["dua"]
            all_done = all_done and bool(meas["all_done"])
        self._warm = None          # homogeneous-path caches do not apply
        self._factors = None
        self._last_all_done = all_done
        self.local_x = x_out
        self.pri_res = pri
        self.dua_res = dua
        return x_out

    def _refresh_every(self) -> int:
        """Frozen-factor refresh cadence — the ONE knob every consumer
        (amortized solve slot, megastep window sizing/eligibility, age
        exhaustion) must read identically."""
        return int(self.options.get("solver_refresh_every", 16) or 0)

    def _straggler_tols(self):
        """(tol_lp, tol_qp) rescue-tolerance ladder.

        LP scenarios (bound spokes, xhat dives) rescue at ``straggler_tol``
        (default 1e-4) — exact primal/dual states keep bounds tight.  QP
        (prox-on PH hub) scenarios rescue only past ``straggler_tol_qp``
        (default 1e-2): PH is a fixed-point iteration whose xbar/W updates
        tolerate subproblem inexactness of that order (the reference hub
        runs Gurobi at default tolerances for the same reason), and host
        rescue of hundreds of mildly-stalled prox solves per iteration is
        exactly the wheel-stalling cost the batch exists to avoid.  An
        explicitly-set ``straggler_tol`` with no ``straggler_tol_qp``
        covers both kinds (explicit intent, and what round-3 tests pin).
        """
        tol_lp = max(float(self.options.get("straggler_tol", 1e-4)),
                     10.0 * self.admm_settings.eps_rel)
        if "straggler_tol_qp" in self.options:
            # explicit setting is honored as-is (floored only by solver eps)
            tol_qp = max(float(self.options["straggler_tol_qp"]),
                         10.0 * self.admm_settings.eps_rel)
        elif "straggler_tol" in self.options:
            tol_qp = tol_lp
        else:
            tol_qp = max(1e-2, tol_lp)
        return tol_lp, tol_qp

    def _rescue_stragglers(self, sol, q, q2, lb, ub, batch=None, meas=None):
        """Host-exact re-solve of the few scenarios batched ADMM left
        unconverged.  Returns ``(sol, meas)``.

        Strongly-coupled LPs (UC ramp/genlim rows) occasionally stall a
        handful of scenarios at ~1e-1 residuals regardless of sweep budget.
        Re-solving that straggler slice host-exact — primal AND dual, so
        bounds stay certified — costs milliseconds per scenario once per
        refresh, while the batch stays the hot path.  LP scenarios go
        through HiGHS; QP scenarios (prox-on PH-hub solves) through the
        dense Mehrotra IPM (:func:`scipy_backend.solve_qp_with_duals`),
        whose dual convention is ours, so no sign vote is needed.  The
        hybrid mirrors the reference's posture: an exact solver where
        exactness matters (spopt.py:85-223), tensor batching everywhere
        else.

        ``meas`` (the caller's packed measurement) serves pri/dua/x; the
        ADMM aux state (z, y, yx, done) is fetched only when stragglers
        actually exist — the common all-converged refresh costs ZERO
        device round-trips here.
        """
        if meas is None:
            meas = self._fetch_measure(sol)
        if not self.options.get("straggler_rescue", True):
            return sol, meas
        tol_lp, tol_qp = self._straggler_tols()
        pri = meas["pri"]
        dua = meas["dua"]
        q2_np = np.asarray(q2)
        is_qp = np.any(q2_np != 0.0, axis=-1)
        tol_s = np.where(is_qp, tol_qp, tol_lp)
        # negated <= so NaN residuals (diverged solves) are selected too
        bad = np.flatnonzero(~(pri <= tol_s) | ~(dua <= tol_s))
        if bad.size == 0:
            return sol, meas
        from .solvers import scipy_backend

        b = self.batch if batch is None else batch
        q = np.asarray(q, dtype=float)
        q2 = np.asarray(q2, dtype=float)
        lb = np.asarray(lb, dtype=float)
        ub = np.asarray(ub, dtype=float)
        x = np.array(meas["x"], copy=True)
        # straggler path only: the aux state the rescue rewrites
        z, y, yx = (np.array(hostsync.fetch(a), copy=True)
                    for a in (sol.z, sol.y, sol.yx))
        pri = pri.copy()
        dua = dua.copy()
        done = np.array(hostsync.fetch(sol.done), copy=True)
        n_resc = 0
        qp_bad = bad[is_qp[bad]]
        if qp_bad.size:
            # QP scenarios: batched host IPM over the straggler slice
            # (duals already in our convention); shared-A families pass the
            # single (m, n) A through with zero extra memory.  Chunked: the
            # IPM's KKT workspace is k*(n+me)^2 doubles, so an unbounded k
            # (hundreds of stalled prox solves at reference UC shape) would
            # OOM the host for no throughput gain
            A_shared = getattr(b, "A_shared", None)
            max_n = int(self.options.get("straggler_qp_max_n", 2000))
            if b.num_vars > max_n:
                # the host IPM is dense ((n, n) factorization per Newton
                # step): past ~2k vars one rescue costs minutes and stalls
                # the wheel worse than the inexact prox solves it repairs.
                # PH tolerates the inexactness; certified bounds never come
                # from prox solves (weak duality / LP rescue paths).
                if not getattr(self, "_qp_rescue_size_warned", False):
                    self._qp_rescue_size_warned = True
                    global_toc(
                        f"straggler rescue: {qp_bad.size} stalled QP "
                        f"scenario(s) left at batch accuracy (n="
                        f"{b.num_vars} > straggler_qp_max_n={max_n})",
                        True)
                qp_bad = np.empty(0, dtype=int)
            chunk = max(1, int(self.options.get("straggler_qp_chunk", 16)))
            for lo in range(0, qp_bad.size, chunk):
                sl = qp_bad[lo:lo + chunk]
                A_arg = A_shared if A_shared is not None else b.A[sl]
                xb, yb, feas = scipy_backend.solve_qp_batch_with_duals(
                    q[sl], q2[sl], A_arg,
                    b.cl[sl], b.cu[sl], lb[sl], ub[sl])
                for j, s in enumerate(sl):
                    if not feas[j]:
                        continue    # genuine infeasibility: leave residuals
                    xs, ys = xb[j], yb[j]
                    yx[s] = -(q[s] + q2[s] * xs + b.A[s].T @ ys)
                    x[s], y[s] = xs, ys
                    z[s] = b.A[s] @ xs
                    pri[s] = 0.0
                    dua[s] = 0.0
                    done[s] = True
                    n_resc += 1
        lp_bad = bad[~is_qp[bad]]
        max_lp = int(self.options.get("straggler_lp_max", 64))
        if lp_bad.size > max_lp:
            # big-batch stall tails (hundreds of mildly-stalled scenarios at
            # reference scale) would serialize hundreds of host LPs per
            # solve; rescue the worst offenders, leave the rest at batch
            # accuracy (bounds stay certified via weak duality regardless)
            worst = np.argsort(-np.maximum(pri[lp_bad], dua[lp_bad]))
            lp_bad = lp_bad[worst[:max_lp]]
        # shared-A families: ONE csr conversion per rescue round (the
        # (m, n) dense scan per scenario was the hot cost at WECC scale) —
        # built only when there is LP work, so QP-only rounds skip it
        import scipy.sparse as _sp

        A_csr = (_sp.csr_matrix(np.asarray(b.A_shared))
                 if lp_bad.size
                 and getattr(b, "A_shared", None) is not None else None)
        for s in lp_bad:
            res = scipy_backend.solve_lp_with_duals(
                q[s], A_csr if A_csr is not None else b.A[s],
                b.cl[s], b.cu[s], lb[s], ub[s])
            if not res.feasible or res.duals is None:
                continue        # genuine infeasibility: leave residuals
            xs = res.x
            obj_s = float(q[s] @ xs)
            ys = _pick_dual_sign(q[s], b.A[s], b.cl[s], b.cu[s],
                                 lb[s], ub[s], res.duals, xs, obj_s)
            # stationarity-exact bound duals
            yxs = -(q[s] + q2[s] * xs + b.A[s].T @ ys)
            x[s], y[s], yx[s] = xs, ys, yxs
            z[s] = b.A[s] @ xs
            pri[s] = 0.0
            dua[s] = 0.0
            done[s] = True
            n_resc += 1
        if n_resc:
            global_toc(
                f"straggler rescue: {n_resc}/{b.num_scenarios} scenarios "
                "re-solved host-exact", self.options.get("verbose", False))
        meas = dict(meas, x=x, pri=pri, dua=dua, all_done=bool(done.all()))
        return (sol._replace(x=x, z=z, y=y, yx=yx, pri_res=pri, dua_res=dua,
                             done=done, raw=(x, z, y, yx)), meas)

    # ---- wheel megakernel (device-resident N-iteration dispatch) ------------
    def _mega_arrays(self, dt):
        """Device-resident :class:`~tpusppy.parallel.sharded.PHArrays` for
        the wheel megakernel (single-controller host path), cached on
        batch identity/version like ``_device_consts`` (whose A/cl/cu it
        shares — one device copy across cylinders).  Requires the PH-layer
        attributes (``_onehot``/``nid_sk``/``probs``) the megastep's
        device outer update contracts over; only :class:`PHBase` callers
        reach here (the eligibility gate)."""
        import jax.numpy as jnp

        from .parallel import sharded

        b = self.batch
        key = (_batch_token(b), getattr(b, "version", 0), str(dt))
        cached = getattr(self, "_mega_arr_cache", None)
        if cached is not None and cached[0] == key:
            return cached[1]
        A_d, cl_d, cu_d = self._device_consts(dt)
        S = b.num_scenarios
        arr = sharded.PHArrays(
            c=jnp.asarray(b.c, dt), q2=jnp.asarray(b.q2, dt), A=A_d,
            cl=cl_d, cu=cu_d,
            lb=jnp.asarray(b.lb, dt), ub=jnp.asarray(b.ub, dt),
            const=jnp.asarray(np.broadcast_to(b.const, (S,)), dt),
            probs=jnp.asarray(self.probs, dt),
            onehot=jnp.asarray(self._onehot, dt),
            nid_sk=jnp.asarray(self.nid_sk, jnp.int32))
        self._mega_arr_cache = (key, arr)
        return arr

    def _device_state_on(self) -> bool:
        """Device-resident PH-state posture (the O(1)-host big-S wheel):
        megastep windows fetch the LEAN packed measurement only, and the
        (S, K)/(S, n) host mirrors are refreshed by one explicit billed
        fetch at checkpoint/termination/refresh boundaries
        (:meth:`tpusppy.phbase.PHBase._sync_host_state`) instead of every
        window.  Opt-in: the ``ph_device_state`` hub option or
        ``TPUSPPY_DEVICE_STATE=1``."""
        import os

        v = self.options.get("ph_device_state")
        if v is None:
            v = os.environ.get("TPUSPPY_DEVICE_STATE", "0") != "0"
        return bool(v)

    def _inwheel_int_mask(self, batch=None):
        """(K,) integer mask of nonant slots for the in-wheel xhat
        candidate rounding (None when the family has no integer
        nonants)."""
        b = self.batch if batch is None else batch
        mask = np.asarray(b.is_int, bool)[self.tree.nonant_indices]
        return mask if mask.any() else None

    def _inwheel_feas_tol(self) -> float:
        """THE feasibility-gate tolerance — single-sourced for
        :meth:`feas_prob`, the ``Xhat_Eval`` integer gate, and the fused
        in-wheel evaluation (their claimed parity depends on one
        definition): option ``feas_tol`` floored at 10x the solver's own
        eps (a loose solve cannot certify tighter than itself)."""
        return max(float(self.options.get("feas_tol", 1e-3)),
                   10.0 * self.admm_settings.eps_rel)

    def _inwheel_threshold(self) -> float:
        """Integer rounding threshold of the in-wheel xhat candidate (the
        ``xbar_candidate`` rule; ``in_wheel_xhat_threshold`` option)."""
        return float(self.options.get("in_wheel_xhat_threshold", 0.5))

    def _inwheel_int_thresholds(self):
        """The batched integer sweep's rounding ladder (doc/integer.md),
        or None when the sweep is off: no integer nonants, or the
        ``in_wheel_int_sweep`` option disables it.  Resolution order:
        the ``in_wheel_int_thresholds`` option, then the autotuner's
        banked "integer" verdict (which truncates the default ladder to
        its measured K), then :data:`~tpusppy.solvers.integer.
        DEFAULT_THRESHOLDS`."""
        from .ir import BucketedBatch

        b = self.batch
        if isinstance(b, BucketedBatch):
            if all(self._inwheel_int_mask(batch=sub) is None
                   for _, sub in b.buckets):
                return None
        elif self._inwheel_int_mask() is None:
            return None
        if not self.options.get("in_wheel_int_sweep", True):
            return None
        th = self.options.get("in_wheel_int_thresholds")
        if th:
            return tuple(float(t) for t in th)
        from .solvers import integer as integer_solvers

        ladder = integer_solvers.DEFAULT_THRESHOLDS
        try:
            from . import tune

            v = tune.integer_verdict(self._mega_shape_key(),
                                     settings=self.admm_settings)
        except AttributeError:      # non-PH opt: no shape key — default
            v = None
        if v is not None and v.k:
            ladder = ladder[:max(1, int(v.k))]
        return tuple(float(t) for t in ladder)

    def _inwheel_int_sweep_on(self) -> bool:
        """Whether the bounds=True megastep for this instance compiles
        the batched integer sweep (and its longer packed tail)."""
        return self._inwheel_int_thresholds() is not None

    def _inwheel_pass_evals(self) -> int:
        """Frozen-evaluation count of ONE in-wheel bound pass — the
        watchdog-reservation and FLOP-billing unit: 1 for the legacy
        single-candidate pass; for the batched integer sweep, the
        ladder evaluations (+ the SLAM slams on the homogeneous kernel
        only — the bucketed posture drops them) + 1 reduced-cost
        re-solve when the fixing is certificate-safe for the family."""
        th = self._inwheel_int_thresholds()
        if th is None:
            return 1
        from .ir import BucketedBatch
        from .solvers import integer as integer_solvers

        c = len(th)
        if not isinstance(self.batch, BucketedBatch):
            c += integer_solvers.N_SLAM
        return c + (1 if self._inwheel_inner_ok() else 0)

    def _megastep_fn(self, n_req: int, pack: str = "full",
                     bounds: bool = False):
        """The jitted megakernel for this instance at width ``n_req``
        (one compile per distinct (N, pack, bounds); the traced
        ``n_live`` budget serves every executed count below it, and the
        traced ``bound_live`` flag serves every bound cadence)."""
        cache = getattr(self, "_mega_fn_cache", None)
        if cache is None:
            cache = self._mega_fn_cache = {}
        fn = cache.get((n_req, pack, bounds))
        if fn is None:
            from .parallel import sharded

            int_rounding = (self._inwheel_int_thresholds() if bounds
                            else None)
            fn = sharded.make_wheel_megastep(
                self.tree.nonant_indices, self.admm_settings, None,
                n_iters=n_req, donate=True, pack=pack, bounds=bounds,
                int_nonants=self._inwheel_int_mask() if bounds else None,
                xhat_threshold=(self._inwheel_threshold() if bounds
                                else 0.5),
                int_rounding=int_rounding,
                int_cols=(np.asarray(self.batch.is_int, bool)
                          if bounds and int_rounding else None),
                # reduced-cost fixing is only certificate-safe when the
                # candidate evaluation is at a true integer-feasible
                # point — every integer column a nonant slot
                int_rcfix=(self._inwheel_inner_ok()
                           if bounds and int_rounding else True))
            cache[(n_req, pack, bounds)] = fn
        return fn

    def _megastep_solve(self, n_req: int, n_live: int, convthresh: float,
                        W, xbars, rho, bound_live=None):
        """Dispatch ONE wheel megastep window and fetch its packed
        measurement — the megakernel twin of ``n_live`` frozen
        ``_solve_amortized`` iterations, sharing the same amortization
        slot: warm state stays device-resident (the returned
        :class:`~tpusppy.parallel.sharded.PHState` buffers become
        ``self._warm``), the factors age advances by the executed count,
        and the mega-dispatch is billed
        (:func:`~tpusppy.solvers.segmented.bill_megastep`).  ONE host
        fetch per window; the divergence / mixed-precision-guard
        bookkeeping runs on the fetched measurement, and an unclean
        final iterate forces the NEXT solve onto the legacy refresh path
        (``_factors_age`` maxed) — the serial acceptance test at window
        granularity.

        ``bound_live`` (None = the bound-pass program variant is not even
        compiled): in-wheel certification — True runs the fused
        outer/inner bound pass on the window's final device state, False
        rides the same compiled program through the dead cadence branch.
        """
        import jax.numpy as jnp

        from .parallel import sharded
        from .solvers import segmented
        from .solvers.sparse import SparseA

        st = self.admm_settings
        dt = st.jdtype()
        arr = self._mega_arrays(dt)
        b = self.batch
        S, n, m = b.num_scenarios, b.num_vars, b.num_rows
        K = self.nonant_length
        pack = "lean" if self._device_state_on() else "full"
        state = getattr(self, "_dev_state", None)
        if state is None:
            warm = self._warm
            state = sharded.PHState(
                W=jnp.asarray(W, dt), xbars=jnp.asarray(xbars, dt),
                rho=jnp.asarray(rho, dt),
                x=jnp.asarray(warm[0], dt), z=jnp.asarray(warm[1], dt),
                y=jnp.asarray(warm[2], dt), yx=jnp.asarray(warm[3], dt))
        # in-scan acceptance at the serial ladder: the megastep solves
        # the PH prox objective, so every scenario is QP
        _, tol_qp = self._straggler_tols()
        bounds = bound_live is not None
        with _trace.span(None, "solve.megastep") as _sp:
            fn = self._megastep_fn(n_req, pack, bounds=bounds)
            if bounds:
                state, packed = fn(
                    state, arr, 1.0, self._factors, convthresh, n_live,
                    tol_qp, bool(bound_live), self._inwheel_feas_tol())
            else:
                state, packed = fn(
                    state, arr, 1.0, self._factors, convthresh, n_live,
                    tol_qp)
            # rebind the warm slot BEFORE the blocking fetch: the old
            # buffers were donated into the dispatch, so a fetch failure
            # (remote-tunnel error, fault injection) must not leave
            # self._warm pointing at deleted device memory
            self._warm = (state.x, state.z, state.y, state.yx)
            # device-resident posture: the RETURNED state (W/xbars
            # included) is the authoritative wheel state; host mirrors
            # go stale until a boundary sync fetches them explicitly
            self._dev_state = state if pack == "lean" else None
            meas = sharded.megastep_unpack(
                hostsync.fetch(packed), n_req, S, n, K, pack=pack,
                bounds=bounds,
                int_sweep=bounds and self._inwheel_int_sweep_on())
            if _trace.enabled():
                _sp.add(n_live=n_live, executed=meas["executed"],
                        refresh_hit=meas["refresh_hit"],
                        bound_pass=bool(meas.get("bound_computed")))
        executed = meas["executed"]
        self._factors_age += executed
        sf = (segmented.SPARSE_DISPATCH_FACTOR
              if isinstance(arr.A, SparseA) else 1.0)
        sweeps = float(np.mean(meas["iters"][:executed])) if executed else 0.0
        # a rejected iterate (refresh_hit) is dispatched-but-discarded
        # work; its stats sit at index ``executed`` of the packed arrays
        rej = (float(meas["iters"][executed])
               if meas["refresh_hit"] and executed < n_req else None)
        segmented.bill_megastep(S, n, m, executed, sweeps, sparse_factor=sf,
                                rejected_sweeps=rej)
        if meas.get("bound_computed"):
            segmented.bill_bound_pass(S, n, m, meas["bound_sweeps"],
                                      sparse_factor=sf,
                                      n_evals=self._inwheel_pass_evals())

        refresh_every = self._refresh_every()
        guard = False
        if executed:
            # mixed-precision residual guard on EVERY accepted iterate
            # (the serial path runs it per frozen solve — a mid-window
            # iterate parked above the precision floor must force the
            # refresh even when the final iterate dips back under): the
            # packed measurement's per-iteration worst residuals make
            # this free of extra fetches.  The in-scan program cannot
            # re-run at full precision, so a trip routes the NEXT solve
            # through the legacy refresh (full precision by design).
            ref = getattr(self, "_factors_ref_worst", None)
            worsts = np.maximum(meas["pri_max"][:executed],
                                meas["dua_max"][:executed])
            guard = any(
                admm.precision_guard_trips(
                    None, st, ref,
                    stats=(float(worsts[i]), bool(meas["all_done"][i])))
                for i in range(executed))
            if guard:
                _metrics.inc("precision.guard_trips")
        if meas["refresh_hit"] or guard:
            # an in-scan iterate failed the serial acceptance test and
            # was discarded (or the guard tripped): exhaust the factors
            # age so the next iteration runs the legacy adaptive refresh
            # + straggler rescue — exactly where the serial protocol
            # lands, minus the already-discarded frozen attempt
            self._factors_age = max(self._factors_age, refresh_every)
            _metrics.inc("megastep.refresh_hits")
        return meas

    def _mega_arrays_bucketed(self, dt):
        """Per-bucket :class:`~tpusppy.parallel.sharded.PHArrays` tuple
        for the bucketed wheel megakernel: each bucket's compact problem
        data (sharing :meth:`_bucket_device_consts`' device A/cl/cu) plus
        its GLOBAL-tree slices of probs/onehot/nid_sk — the cross-bucket
        outer update couples through those, so bucket-local probability
        normalization never enters the device reductions."""
        import jax.numpy as jnp

        from .parallel import sharded

        b = self.batch
        key = (_batch_token(b), getattr(b, "version", 0), str(dt))
        cached = getattr(self, "_mega_arr_bucket_cache", None)
        if cached is not None and cached[0] == key:
            return cached[1]
        consts = self._bucket_device_consts(dt)
        arrs = []
        for (idx, sub), (A_d, cl_d, cu_d) in zip(b.buckets, consts):
            n = sub.num_vars
            S_b = idx.size
            arrs.append(sharded.PHArrays(
                c=jnp.asarray(sub.c, dt), q2=jnp.asarray(sub.q2, dt),
                A=A_d, cl=cl_d, cu=cu_d,
                lb=jnp.asarray(sub.lb, dt), ub=jnp.asarray(sub.ub, dt),
                const=jnp.asarray(
                    np.broadcast_to(sub.const, (S_b,)), dt),
                probs=jnp.asarray(self.probs[idx], dt),
                onehot=jnp.asarray(self._onehot[idx], dt),
                nid_sk=jnp.asarray(self.nid_sk[idx], jnp.int32)))
        arrs = tuple(arrs)
        self._mega_arr_bucket_cache = (key, arrs)
        return arrs

    def _bucketed_megastep_fn(self, n_req: int, bounds: bool = False):
        cache = getattr(self, "_mega_fn_cache", None)
        if cache is None:
            cache = self._mega_fn_cache = {}
        keyb = ("bucketed", n_req, bounds)
        fn = cache.get(keyb)
        if fn is None:
            from .parallel import sharded

            int_masks = None
            int_rounding = None
            int_cols = None
            if bounds:
                # per-bucket integer masks: bucketing may key on the
                # integer pattern, so nonant integrality can differ
                int_masks = tuple(
                    self._inwheel_int_mask(batch=sub)
                    for _, sub in self.batch.buckets)
                int_rounding = self._inwheel_int_thresholds()
                if int_rounding:
                    int_cols = tuple(
                        np.asarray(sub.is_int, bool)
                        for _, sub in self.batch.buckets)
            fn = sharded.make_bucketed_wheel_megastep(
                self.tree.nonant_indices, self.admm_settings,
                n_iters=n_req, donate=True, bounds=bounds,
                int_nonants=int_masks,
                xhat_threshold=(self._inwheel_threshold() if bounds
                                else 0.5),
                int_rounding=int_rounding, int_cols=int_cols,
                int_rcfix=(self._inwheel_inner_ok()
                           if bounds and int_rounding else True))
            cache[keyb] = fn
        return fn

    def _megastep_solve_bucketed(self, n_req: int, n_live: int,
                                 convthresh: float, W, xbars, rho,
                                 bound_live=None):
        """Bucketed twin of :meth:`_megastep_solve`: ONE device dispatch
        runs ``n_live`` wheel iterations over every bucket's compact
        shapes, the packed per-bucket blocks scatter back through each
        bucket's scenario indices into the global bookkeeping layout, and
        each bucket's amortization slot advances exactly as its scattered
        host solves would have (warm rebind before the fetch, age +=
        executed, per-bucket billing)."""
        import jax.numpy as jnp

        from .parallel import sharded
        from .solvers import segmented

        st = self.admm_settings
        dt = st.jdtype()
        if self._device_state_on() and \
                not getattr(self, "_bucketed_lean_warned", False):
            # the lean (device-resident) pack is homogeneous-only today:
            # a bucketed family silently running full-pack windows would
            # look like the O(1)-host posture while paying O(S·n) per
            # window — say so once instead
            self._bucketed_lean_warned = True
            global_toc(
                "ph_device_state: bucketed families run FULL-pack "
                "megasteps (the lean O(1)-host posture is homogeneous-"
                "only; doc/scaling.md)", True)
        arrs = self._mega_arrays_bucketed(dt)
        b = self.batch
        slots = self._bucket_slots
        K = self.nonant_length
        W = np.asarray(W)
        xbars = np.asarray(xbars)
        rho = np.asarray(rho)
        states = []
        for (idx, sub), slot in zip(b.buckets, slots):
            warm = slot["warm"]
            states.append(sharded.PHState(
                W=jnp.asarray(W[idx], dt),
                xbars=jnp.asarray(xbars[idx], dt),
                rho=jnp.asarray(rho[idx], dt),
                x=jnp.asarray(warm[0], dt), z=jnp.asarray(warm[1], dt),
                y=jnp.asarray(warm[2], dt), yx=jnp.asarray(warm[3], dt)))
        factors = tuple(slot["factors"] for slot in slots)
        _, tol_qp = self._straggler_tols()
        shapes = [(idx.size, sub.num_vars) for idx, sub in b.buckets]
        bounds = bound_live is not None
        with _trace.span(None, "solve.megastep") as _sp:
            fnb = self._bucketed_megastep_fn(n_req, bounds=bounds)
            if bounds:
                states, packed = fnb(
                    tuple(states), arrs, 1.0, factors, convthresh,
                    n_live, tol_qp, bool(bound_live),
                    self._inwheel_feas_tol())
            else:
                states, packed = fnb(
                    tuple(states), arrs, 1.0, factors, convthresh,
                    n_live, tol_qp)
            # rebind every bucket's warm slot BEFORE the blocking fetch
            # (the donated buffers are gone — same contract as the
            # homogeneous path)
            for slot, stb in zip(slots, states):
                slot["warm"] = (stb.x, stb.z, stb.y, stb.yx)
            bmeas = sharded.bucketed_megastep_unpack(
                hostsync.fetch(packed), n_req, shapes, K, bounds=bounds,
                int_sweep=bounds and self._inwheel_int_sweep_on())
            if _trace.enabled():
                _sp.add(n_live=n_live, executed=bmeas["executed"],
                        refresh_hit=bmeas["refresh_hit"], buckets=len(arrs))
        executed = bmeas["executed"]
        # scatter the per-bucket blocks into the global layout so the
        # caller's install path (_apply_megastep_meas) is bucket-agnostic
        S, n_max = b.num_scenarios, b.num_vars
        meas = {k: bmeas[k] for k in (
            "conv", "eobj", "pri_max", "dua_max", "iters", "all_done",
            "executed", "refresh_hit")}
        if bounds:
            meas.update({k: bmeas[k] for k in (
                "bound_computed", "bound_outer", "bound_inner_obj",
                "bound_inner_feas", "bound_sweeps")})
            for k in ("int_feas_cands", "int_best_idx",
                      "int_rcfix_slots", "bound_outer_base"):
                if k in bmeas:
                    meas[k] = bmeas[k]
        pri = np.zeros(S)
        dua = np.zeros(S)
        done = np.zeros(S, dtype=bool)
        x = np.zeros((S, n_max))
        Wg = np.zeros((S, K))
        xbg = np.zeros((S, K))
        for bi, (idx, sub) in enumerate(b.buckets):
            pri[idx] = bmeas["pri"][bi]
            dua[idx] = bmeas["dua"][bi]
            done[idx] = bmeas["done"][bi]
            x[idx, :sub.num_vars] = bmeas["x"][bi]
            Wg[idx] = bmeas["W"][bi]
            xbg[idx] = bmeas["xbars"][bi]
        meas.update(pri=pri, dua=dua, done=done, x=x, W=Wg, xbars=xbg)
        refresh_every = self._refresh_every()
        guard = False
        if executed:
            ref = max((slot.get("ref_worst") or 0.0) for slot in slots) \
                if any(slot.get("ref_worst") is not None
                       for slot in slots) else None
            worsts = np.maximum(meas["pri_max"][:executed],
                                meas["dua_max"][:executed])
            guard = any(
                admm.precision_guard_trips(
                    None, st, ref,
                    stats=(float(worsts[i]), bool(meas["all_done"][i])))
                for i in range(executed))
            if guard:
                _metrics.inc("precision.guard_trips")
        sweeps = float(np.mean(meas["iters"][:executed])) if executed \
            else 0.0
        rej = (float(meas["iters"][executed])
               if meas["refresh_hit"] and executed < n_req else None)
        # loop-invariant: the threshold-ladder resolution behind this is
        # a per-bucket scan + verdict lookup, not per-bucket billing work
        pass_evals = (self._inwheel_pass_evals()
                      if meas.get("bound_computed") else 1)
        for bi, (slot, (idx, sub)) in enumerate(zip(slots, b.buckets)):
            # per-bucket FLOP billing on each bucket's own shapes (the
            # packed sweep counter is the cross-bucket max —
            # conservative); the window is ONE dispatch, so only the
            # first bucket counts toward the dispatch counters
            segmented.bill_megastep(idx.size, sub.num_vars, sub.num_rows,
                                    executed, sweeps, rejected_sweeps=rej,
                                    count_dispatch=bi == 0)
            if meas.get("bound_computed"):
                segmented.bill_bound_pass(
                    idx.size, sub.num_vars, sub.num_rows,
                    meas["bound_sweeps"], count_pass=bi == 0,
                    n_evals=pass_evals)
            slot["age"] = slot.get("age", 0) + executed
            if meas["refresh_hit"] or guard:
                slot["age"] = max(slot["age"], refresh_every)
        if meas["refresh_hit"] or guard:
            _metrics.inc("megastep.refresh_hits")
        return meas

    # ---- expectations (Allreduce analogues) ---------------------------------
    def Eobjective(self, x=None) -> float:
        """Probability-weighted expected objective (spopt.py:310-345)."""
        x = self.local_x if x is None else np.asarray(x)
        return float(self.probs @ self.batch.objective(x))

    def Ebound(self, x=None, extra_obj=None) -> float:
        """Expected bound from current subproblem objectives (spopt.py:346-393).

        With W active and prox off, this is the Lagrangian outer bound.
        ``extra_obj``: (S,) additive per-scenario objective terms (e.g. W·x).
        """
        x = self.local_x if x is None else np.asarray(x)
        vals = self.batch.objective(x)
        if extra_obj is not None:
            vals = vals + np.asarray(extra_obj)
        return float(self.probs @ vals)

    def Edualbound(self, q=None, q2=None) -> float:
        """Expectation of :meth:`Edualbound_perscen` (see there)."""
        return float(self.probs @ self.Edualbound_perscen(q, q2))

    def Edualbound_perscen(self, q=None, q2=None) -> np.ndarray:
        """CERTIFIED per-scenario outer bounds ((S,)) from the last solve's
        row duals; ``Edualbound`` is their expectation, and the MILP lift
        (:mod:`tpusppy.solvers.milp_bound`) raises individual entries.

        ``Ebound`` evaluates the primal objective of an inexact solve — valid
        only to solver tolerance (the reference gets exactness from its
        external MIP solver).  This uses weak duality instead: for any duals
        y, the per-scenario dual objective bounds the subproblem optimum from
        below, so solver tolerance can only make the reported bound WEAKER,
        never invalid.  See :func:`tpusppy.solvers.admm.dual_objective` for
        the free-variable margin caveat.
        """
        from .ir import BucketedBatch

        if isinstance(self.batch, BucketedBatch):
            return self._Edualbound_bucketed_perscen(q, q2)
        if self._warm is None:
            raise RuntimeError("Edualbound requires a prior solve_loop")
        b = self.batch
        q = b.c if q is None else q
        q2 = b.q2 if q2 is None else q2
        lb = b.lb if self._fixed_lb is None else self._fixed_lb
        ub = b.ub if self._fixed_ub is None else self._fixed_ub
        x, _, y, _ = self._warm
        dt = self.admm_settings.jdtype()
        import jax.numpy as jnp

        A_d, cl_d, cu_d = self._device_consts(dt)
        args = (jnp.asarray(q, dt), jnp.asarray(q2, dt), A_d, cl_d, cu_d,
                jnp.asarray(lb, dt), jnp.asarray(ub, dt),
                jnp.asarray(y, dt), jnp.asarray(x, dt))
        dvals, margin = _certified_dual_eval(args)
        self.last_bound_margin = margin
        return dvals - margin + b.const

    def dual_donor_bounds(self, q=None, q2=None, k=16, budget_s=90.0,
                          time_limit=30.0,
                          refresh_every=4) -> np.ndarray | None:
        """(S,) certified bounds from EXACT donor duals, transferred
        batch-wide — the scalable outer-bound mechanism at full scale.

        The per-scenario ADMM duals of plateaued reference-scale solves
        are loose (bounds off by ORDERS of magnitude), and host-exact dual
        rescue prices O(seconds) per scenario — at S=1000 neither works
        (the r5 full-scale traces showed Lagrangian bounds of -2e9 against
        an optimum near 1.2e7).  But weak duality accepts ANY y per
        scenario: solve ``k`` donor scenarios host-exact (HiGHS, with
        THEIR W-augmented objectives), then evaluate every donor's dual
        against ALL scenarios through :func:`admm.dual_objective` (one
        batched device call per donor) and keep the per-scenario best.
        Wind-ladder scenarios are small perturbations of each other, so
        exact duals transfer nearly tight — O(k) host LPs total instead
        of O(S).

        Donor duals are CACHED across calls: a y computed for an earlier W
        remains a valid certificate for any new q (weak duality), so each
        round re-evaluates every cached dual with two cheap batched device
        calls and re-solves the host LPs only every ``refresh_every``-th
        call (the host LP cost would otherwise dominate the spoke at
        exactly the scale this exists for).  ``time_limit`` caps each
        donor LP; the budget is also enforced between solves.

        Returns None when no donor duals are available (e.g. bucketed
        batches — no homogeneous warm state — or every LP failed); callers
        degrade to their base bound.
        """
        from .ir import BucketedBatch
        from .solvers import scipy_backend

        b = self.batch
        if isinstance(b, BucketedBatch):
            return None
        q = np.asarray(b.c if q is None else q, dtype=float)
        q2 = np.asarray(b.q2 if q2 is None else q2, dtype=float)
        lb = np.asarray(b.lb if self._fixed_lb is None else self._fixed_lb)
        ub = np.asarray(b.ub if self._fixed_ub is None else self._fixed_ub)
        S = b.num_scenarios
        if self._warm is not None:
            x_hint = np.asarray(self._warm[0])
        else:
            # no prior batched solve (the full-scale Lagrangian skips it —
            # donors ARE the bound): a conservative hint sized from the
            # finite problem data keeps the X-cap certificate box far
            # outside any reachable optimizer (exact donor duals leave
            # ~zero reduced cost on capped coordinates, so the margin
            # stays ~0 regardless)
            finite_max = 1.0
            for arr in (b.cl, b.cu, lb, ub):
                fa = np.abs(arr[np.isfinite(arr)])
                if fa.size:
                    finite_max = max(finite_max, float(fa.max()))
            x_hint = np.full((S, b.num_vars), finite_max)
        cache = getattr(self, "_donor_dual_cache", None)
        age = getattr(self, "_donor_dual_age", 0)
        if cache is None or age >= max(1, int(refresh_every)):
            sel = np.unique(
                np.linspace(0, S - 1, min(int(k), S)).astype(int))
            import scipy.sparse as _sp

            A_sh = getattr(b, "A_shared", None)
            A_csr = (_sp.csr_matrix(np.asarray(A_sh))
                     if A_sh is not None else None)
            deadline = time.monotonic() + float(budget_s)
            cache = []
            for s_k in sel:
                remaining = deadline - time.monotonic()
                if remaining <= 1.0:
                    break
                res = scipy_backend.solve_lp_with_duals(
                    q[s_k], A_csr if A_csr is not None else b.A[s_k],
                    b.cl[s_k], b.cu[s_k], lb[s_k], ub[s_k],
                    time_limit=min(float(time_limit), remaining))
                if not res.feasible or res.duals is None:
                    continue
                obj_k = float(q[s_k] @ res.x)
                cache.append(_pick_dual_sign(
                    q[s_k], b.A[s_k], b.cl[s_k], b.cu[s_k],
                    lb[s_k], ub[s_k], res.duals, res.x, obj_k))
            if not cache:
                # refresh produced nothing (every LP timed out): KEEP the
                # previous duals — still valid certificates — and leave the
                # cache unset otherwise so the next call retries instead of
                # serving an empty cache for refresh_every-1 rounds
                prev = getattr(self, "_donor_dual_cache", None)
                if prev:
                    cache = prev
                else:
                    self._donor_dual_cache = None
                    self._donor_dual_age = 0
                    return None
            self._donor_dual_cache = cache
            age = 0
        self._donor_dual_age = age + 1
        if not cache:
            return None
        dt = self.admm_settings.jdtype()
        import jax.numpy as jnp

        A_d, cl_d, cu_d = self._device_consts(dt)
        lb_d, ub_d = jnp.asarray(lb, dt), jnp.asarray(ub, dt)
        q_d, q2_d = jnp.asarray(q, dt), jnp.asarray(q2, dt)
        xh_d = jnp.asarray(x_hint, dt)
        const = np.asarray(np.broadcast_to(b.const, (S,)))
        best = None
        for y_k in cache:
            y_tiled = jnp.broadcast_to(jnp.asarray(y_k, dt), (S, y_k.size))
            args = (q_d, q2_d, A_d, cl_d, cu_d, lb_d, ub_d, y_tiled, xh_d)
            dvals, margin = _certified_dual_eval(args)
            dv = dvals - margin + const
            best = dv if best is None else np.maximum(best, dv)
        return best

    def _Edualbound_bucketed_perscen(self, q=None, q2=None) -> np.ndarray:
        """Certified dual bound for RAGGED (bucketed) batches: the weak-
        duality construction per compact bucket, scattered back — closes
        the r2 limitation where bound-spoke wheels required unbucketed
        batches."""
        import jax.numpy as jnp

        b = self.batch
        slots = getattr(self, "_bucket_slots", None)
        # freshness: a rebucketed batch invalidates the slot list exactly as
        # the solve path's own check does (zip would silently truncate and
        # report a falsely tight "certificate" otherwise)
        if (not slots or len(slots) != len(b.buckets)
                or any(s.get("warm") is None for s in slots)):
            raise RuntimeError("Edualbound requires a prior solve_loop")
        q = np.asarray(b.c if q is None else q)
        q2 = np.asarray(b.q2 if q2 is None else q2)
        lb = np.asarray(b.lb if self._fixed_lb is None else self._fixed_lb)
        ub = np.asarray(b.ub if self._fixed_ub is None else self._fixed_ub)
        dt = self.admm_settings.jdtype()
        consts = self._bucket_device_consts(dt)
        vals = np.zeros(b.num_scenarios)
        margin_out = np.zeros(b.num_scenarios)
        for (idx_arr, sub), slot, (A_d, cl_d, cu_d) in zip(
                b.buckets, slots, consts):
            n = sub.num_vars
            x, _, y, _ = slot["warm"]
            args = (jnp.asarray(q[idx_arr, :n], dt),
                    jnp.asarray(q2[idx_arr, :n], dt), A_d, cl_d, cu_d,
                    jnp.asarray(lb[idx_arr, :n], dt),
                    jnp.asarray(ub[idx_arr, :n], dt),
                    jnp.asarray(y, dt), jnp.asarray(x, dt))
            dv, mg = _certified_dual_eval(args)
            vals[idx_arr] = dv
            margin_out[idx_arr] = mg
        self.last_bound_margin = margin_out
        return vals - margin_out + b.const

    def _bucket_device_consts(self, dt):
        """Per-bucket device-resident (A, cl, cu), cached on batch.version —
        the bucketed analogue of _device_consts (spoke hot loops call
        Edualbound per iteration)."""
        import jax.numpy as jnp

        b = self.batch
        key = (_batch_token(b), getattr(b, "version", 0), str(dt),
               len(b.buckets))
        cached = getattr(self, "_bucket_dev_consts", None)
        if cached is None or cached[0] != key:
            # a (really) shared-A bucket uploads its single (m, n) matrix
            # (the shared engine and the dual-bound programs both accept
            # the 2-D form), never the (S_b, m, n) broadcast view
            consts = [
                (jnp.asarray(
                    sub.A_shared if bucket_shared(sub) else sub.A, dt),
                 jnp.asarray(sub.cl, dt),
                 jnp.asarray(sub.cu, dt)) for _, sub in b.buckets]
            cached = (key, consts)
            self._bucket_dev_consts = cached
        return cached[1]

    def feas_prob(self, tol=None) -> float:
        """Probability mass of feasible scenarios (spopt.py:394-433): here,
        scenarios whose ADMM primal residual is within tolerance.

        Default tolerance 1e-3 (option "feas_tol"): the float32 TPU path
        floors its scaled primal residual around 1e-4.  A solver run at loose
        eps (e.g. via the Gapper schedule) cannot certify feasibility tighter
        than its own tolerance, so the floor scales with eps_rel."""
        if tol is None:
            tol = self._inwheel_feas_tol()   # the ONE gate tolerance
        if self.pri_res is None:
            return 1.0
        return float(self.probs @ (self.pri_res < tol))

    def infeas_prob(self, tol=None) -> float:
        return 1.0 - self.feas_prob(tol)

    # ---- nonant caches / fixing (spopt.py:528-740) --------------------------
    def save_nonants(self):
        self._cached_nonants = self.nonants_of(self.local_x).copy()

    def restore_nonants(self):
        """Drop any fixing overlay (the cache itself is for xhat bookkeeping)."""
        self._fixed_lb = None
        self._fixed_ub = None

    def fix_nonants(self, cache):
        """Clamp nonant slots to candidate values (spopt.py:557-591): the batch
        equivalent of fixing Pyomo vars — lb=ub=candidate on nonant columns.

        ``cache``: (K,) a single candidate for all scenarios, or (S, K).
        """
        b = self.batch
        cache = np.asarray(cache, dtype=float)
        if cache.ndim == 1:
            cache = np.broadcast_to(cache, (b.num_scenarios, cache.shape[0]))
        if np.any(self.batch.is_int[self.tree.nonant_indices]):
            ints = self.batch.is_int[self.tree.nonant_indices]
            cache = np.where(ints[None, :], np.round(cache), cache)
        lb = b.lb.copy()
        ub = b.ub.copy()
        idx = self.tree.nonant_indices
        lb[:, idx] = cache
        ub[:, idx] = cache
        self._fixed_lb, self._fixed_ub = lb, ub

    # Scenario bundling (spbase.py:219-253, spopt.py:743-836): in the batched
    # design a bundle is a block-diagonal merge of member scenarios applied at
    # batch construction — see tpusppy.bundles once implemented (not yet).
