"""SPOpt: batched subproblem solving and expectation reductions.

TPU-native analogue of ``mpisppy/spopt.py:23-868``.  The reference's
``solve_one``/``solve_loop`` (spopt.py:85-307) — a serial per-rank loop handing
each Pyomo model to an external solver — becomes ONE vmapped ADMM call on the
HBM-resident batch, warm-started between calls (the persistent-solver analogue,
spopt.py:129-144).  Expectations (``Eobjective``/``Ebound``/``feas_prob``,
spopt.py:310-466) are probability-weighted contractions; under a mesh they are
psums on the scenario axis.
"""

from __future__ import annotations

import numpy as np

from .spbase import SPBase
from .solvers import admm


class SPOpt(SPBase):
    """Adds solving to SPBase."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._warm = None            # (x, z, y, yx) device arrays
        self.local_x = None          # (S, n) last solution
        self.pri_res = None
        self.dua_res = None
        self._fixed_lb = None        # active nonant fixing overlay (S, n) or None
        self._fixed_ub = None
        self._cached_nonants = None

    # ---- the hot loop -------------------------------------------------------
    def solve_loop(self, q=None, q2=None, warm=True, dis_W=None, dis_prox=None):
        """Solve the whole local batch; returns (S, n) solutions.

        ``q``/``q2`` override the linear/diagonal-quadratic objective (PH passes
        its augmented objective here).  ``dis_W``/``dis_prox`` exist for API
        parity (PHBase computes q itself); they are accepted and ignored here.
        """
        ext = getattr(self, "extobject", None)
        if ext is not None:
            ext.pre_solve()
        b = self.batch
        q = b.c if q is None else q
        q2 = b.q2 if q2 is None else q2
        lb = b.lb if self._fixed_lb is None else self._fixed_lb
        ub = b.ub if self._fixed_ub is None else self._fixed_ub
        sol = admm.solve_batch(
            q, q2, b.A, b.cl, b.cu, lb, ub,
            settings=self.admm_settings,
            warm=self._warm if warm else None,
        )
        # polished states warm-start the NEXT objective's solve well (the
        # PH persistent-solver pattern); raw iterates matter only when
        # re-solving the SAME problem repeatedly (e.g. the Benders root)
        self._warm = (sol.x, sol.z, sol.y, sol.yx)
        self.local_x = np.asarray(sol.x)
        self.pri_res = np.asarray(sol.pri_res)
        self.dua_res = np.asarray(sol.dua_res)
        if ext is not None:
            ext.post_solve()
        return self.local_x

    # ---- expectations (Allreduce analogues) ---------------------------------
    def Eobjective(self, x=None) -> float:
        """Probability-weighted expected objective (spopt.py:310-345)."""
        x = self.local_x if x is None else np.asarray(x)
        return float(self.probs @ self.batch.objective(x))

    def Ebound(self, x=None, extra_obj=None) -> float:
        """Expected bound from current subproblem objectives (spopt.py:346-393).

        With W active and prox off, this is the Lagrangian outer bound.
        ``extra_obj``: (S,) additive per-scenario objective terms (e.g. W·x).
        """
        x = self.local_x if x is None else np.asarray(x)
        vals = self.batch.objective(x)
        if extra_obj is not None:
            vals = vals + np.asarray(extra_obj)
        return float(self.probs @ vals)

    def feas_prob(self, tol=None) -> float:
        """Probability mass of feasible scenarios (spopt.py:394-433): here,
        scenarios whose ADMM primal residual is within tolerance.

        Default tolerance 1e-3 (option "feas_tol"): the float32 TPU path
        floors its scaled primal residual around 1e-4.  A solver run at loose
        eps (e.g. via the Gapper schedule) cannot certify feasibility tighter
        than its own tolerance, so the floor scales with eps_rel."""
        if tol is None:
            tol = max(self.options.get("feas_tol", 1e-3),
                      10.0 * self.admm_settings.eps_rel)
        if self.pri_res is None:
            return 1.0
        return float(self.probs @ (self.pri_res < tol))

    def infeas_prob(self, tol=None) -> float:
        return 1.0 - self.feas_prob(tol)

    # ---- nonant caches / fixing (spopt.py:528-740) --------------------------
    def save_nonants(self):
        self._cached_nonants = self.nonants_of(self.local_x).copy()

    def restore_nonants(self):
        """Drop any fixing overlay (the cache itself is for xhat bookkeeping)."""
        self._fixed_lb = None
        self._fixed_ub = None

    def fix_nonants(self, cache):
        """Clamp nonant slots to candidate values (spopt.py:557-591): the batch
        equivalent of fixing Pyomo vars — lb=ub=candidate on nonant columns.

        ``cache``: (K,) a single candidate for all scenarios, or (S, K).
        """
        b = self.batch
        cache = np.asarray(cache, dtype=float)
        if cache.ndim == 1:
            cache = np.broadcast_to(cache, (b.num_scenarios, cache.shape[0]))
        if np.any(self.batch.is_int[self.tree.nonant_indices]):
            ints = self.batch.is_int[self.tree.nonant_indices]
            cache = np.where(ints[None, :], np.round(cache), cache)
        lb = b.lb.copy()
        ub = b.ub.copy()
        idx = self.tree.nonant_indices
        lb[:, idx] = cache
        ub[:, idx] = cache
        self._fixed_lb, self._fixed_ub = lb, ub

    # Scenario bundling (spbase.py:219-253, spopt.py:743-836): in the batched
    # design a bundle is a block-diagonal merge of member scenarios applied at
    # batch construction — see tpusppy.bundles once implemented (not yet).
