"""Deterministic fault injection: dead spokes, dropped TCP reads, stale ids,
dead controllers, fabric partitions, slow collectives.

Recovery paths that are only exercised by real outages rot silently.
This harness injects the failure classes the resilience layer
handles — a spoke dying mid-run, a transient TCP window-service IO
failure, a mailbox serving stale write-ids, and (controller-grade, for
the elastic mesh of :mod:`tpusppy.parallel.elastic`) a CONTROLLER
process dying at an exact wheel iteration, a permanent TCP fabric
partition, and delayed collectives — at DETERMINISTIC points
(payload counts, read counts, iteration numbers), so tests prove the
degradation and retry/reconnect/re-mesh machinery instead of hoping
for it.

Usage (tests/test_resilience.py is the living example)::

    plan = FaultPlan(kill_spoke={"LagrangianOuterBound": 2})
    with faults.inject(plan) as stats:
        WheelSpinner(hub, spokes).spin()
    assert stats["spoke_kills"] == 1

The hooks live on hot paths (mailbox gets, spoke polls, TCP ops) and cost
ONE module-attribute check when disarmed (``_PLAN is None``) — the same
contract the trace ring's disabled fast path keeps.

Injection is process-local: a multiprocess wheel's spokes run in child
processes and do not see the parent's plan (the threaded
:class:`~tpusppy.spin_the_wheel.WheelSpinner` is the deterministic
harness; TCP faults for cross-process runs are injected on whichever
side armed the plan).
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import signal
import threading
import time

from ..obs import metrics as _metrics

KILL_ID = -1


def _self_sigkill():          # module hook so unit tests can observe the
    os.kill(os.getpid(), signal.SIGKILL)    # decision without dying


_SELF_KILL = _self_sigkill


class InjectedFault(RuntimeError):
    """Base class of every injected failure."""


class SpokeKilled(InjectedFault):
    """Raised inside a spoke's main loop to simulate its death."""


@dataclasses.dataclass
class FaultPlan:
    """What to break, and exactly when.

    kill_spoke: {spoke key: k} — raise :class:`SpokeKilled` inside the
      spoke when it receives its k-th FRESH hub payload.  Keys are strata
      ranks (int) or spoke class names (str).
    stale_mailbox: {mailbox name: n} — the next ``n`` reads of that
      mailbox report write-id 0 (as if no Put ever landed), simulating a
      stale window generation.  The kill sentinel (-1) is never masked —
      it is terminal by protocol, and masking it would turn a bounded
      test into a hang.
    drop_tcp: {mailbox name or "*": n} — the next ``n`` TCP window ops on
      that box raise a transient connection-lost error (consumed by the
      bounded retry/reconnect path in
      :mod:`tpusppy.runtime.tcp_window_service`).
    delay_reads: {mailbox name or "*": secs} — sleep before each read
      (slow-network emulation; bounded by the caller's own timeouts).
    kill_controller: {process index (int) or "*": iteration} — SIGKILL
      THIS process (for real — no cleanup, no atexit) the moment the
      distributed wheel reaches that iteration, via the
      ``on_controller_iter`` hook in ``dist_wheel``.  The deterministic
      sibling of the chaos smoke's external SIGKILL; drives the elastic
      detection/re-mesh path (:mod:`tpusppy.parallel.elastic`) in tests.
    partition_tcp: {mailbox/channel name or "*": True} — EVERY op on
      that channel fails with connection-lost from now on (a network
      partition, not a transient blip): the retry budget exhausts and
      the error propagates, which is how a wedged-but-reachable peer
      looks to the liveness protocol.
    delay_collectives: secs — sleep before each watchdog-guarded mesh
      collective (slow-fabric emulation; a delay under
      ``TPUSPPY_MESH_TIMEOUT`` must NOT trip the watchdog, over it
      must).
    kill_server_after_slices: k — SIGKILL THIS process (for real) the
      moment the solve server finishes its k-th scheduler slice, via the
      ``on_server_slice`` hook in ``service/server.py``.  The kill lands
      MID-TRANSITION: the slice's wheel has torn down (its terminal
      checkpoint is banked) but the park/completion has NOT been
      journaled — exactly the window the restart-recovery path must
      handle (doc/serving.md "Durability").
    drop_client: {slot (int) or "*": n} — the next ``n`` SolveClient ops
      on that request slot raise a transient connection-lost error,
      consumed by the client's bounded reconnect-with-backoff path
      (:class:`tpusppy.service.net.SolveClient`); exhausting it raises
      the typed ``ServerLost``.
    stall_ingest: secs — sleep inside ``SolveServer.submit`` before
      ingest (a slow/stuck canonicalization: admission control and the
      shutdown-race path must stay correct while ingest crawls).
    """

    kill_spoke: dict = dataclasses.field(default_factory=dict)
    stale_mailbox: dict = dataclasses.field(default_factory=dict)
    drop_tcp: dict = dataclasses.field(default_factory=dict)
    delay_reads: dict = dataclasses.field(default_factory=dict)
    kill_controller: dict = dataclasses.field(default_factory=dict)
    partition_tcp: dict = dataclasses.field(default_factory=dict)
    delay_collectives: float = 0.0
    kill_server_after_slices: int = 0
    drop_client: dict = dataclasses.field(default_factory=dict)
    stall_ingest: float = 0.0


_PLAN: FaultPlan | None = None
_LOCK = threading.Lock()
_STATS: dict = {}


def _record(kind: str):
    with _LOCK:
        _STATS[kind] = _STATS.get(kind, 0) + 1
    _metrics.inc(f"faults.{kind}")


def injected_counts() -> dict:
    with _LOCK:
        return dict(_STATS)


def arm(plan: FaultPlan):
    global _PLAN
    with _LOCK:
        _STATS.clear()
    # remaining-budget counters live on a working copy so a plan object
    # can be reused across tests without carrying decremented state
    plan = dataclasses.replace(
        plan, stale_mailbox=dict(plan.stale_mailbox),
        drop_tcp=dict(plan.drop_tcp),
        partition_tcp=dict(plan.partition_tcp),
        drop_client=dict(plan.drop_client))
    _PLAN = plan
    return plan


def disarm():
    global _PLAN
    _PLAN = None


@contextlib.contextmanager
def inject(plan: FaultPlan):
    """Arm ``plan`` for the duration; yields the live stats dict view
    (read it after the block via :func:`injected_counts` for a copy)."""
    arm(plan)
    try:
        yield _STATS
    finally:
        disarm()


# ---------------------------------------------------------------------------
# Hooks (called from instrumented seams; no-ops unless armed)
# ---------------------------------------------------------------------------
def on_spoke_payload(spoke):
    """Called by ``Spoke.spoke_from_hub`` on every FRESH payload; raises
    :class:`SpokeKilled` when the plan schedules this spoke's death at
    the current payload count."""
    plan = _PLAN
    if plan is None or not plan.kill_spoke:
        return
    k = plan.kill_spoke.get(spoke.strata_rank)
    if k is None:
        k = plan.kill_spoke.get(type(spoke).__name__)
    if k is not None and spoke._recv_count >= int(k):
        _record("spoke_kills")
        raise SpokeKilled(
            f"injected death of {type(spoke).__name__} "
            f"(strata {spoke.strata_rank}) at payload {spoke._recv_count}")


def on_mailbox_get(name: str, write_id: int) -> int:
    """Called by ``Mailbox.get``: may return a STALE write-id (0) for the
    next budgeted reads of ``name``.  Kill sentinels pass through."""
    plan = _PLAN
    if plan is None or not plan.stale_mailbox or write_id == KILL_ID:
        return write_id
    with _LOCK:
        left = plan.stale_mailbox.get(name, 0)
        if left <= 0:
            return write_id
        plan.stale_mailbox[name] = left - 1
    _record("stale_reads")
    return 0


def _budget(table: dict, name: str) -> bool:
    with _LOCK:
        for key in (name, "*"):
            left = table.get(key, 0)
            if left > 0:
                table[key] = left - 1
                return True
    return False


def on_tcp_io(name: str):
    """Called inside each TCP window op attempt: sleeps (delay plan) and
    raises a transient connection-lost error (drop plan) or a PERMANENT
    one (partition plan) so the bounded retry/backoff/reconnect path —
    and its exhaustion — is exercised on demand."""
    plan = _PLAN
    if plan is None:
        return
    if plan.delay_reads:
        secs = plan.delay_reads.get(name, plan.delay_reads.get("*"))
        if secs:
            _record("delayed_reads")
            time.sleep(float(secs))
    if plan.partition_tcp and (plan.partition_tcp.get(name)
                               or plan.partition_tcp.get("*")):
        # a partition is not a budgeted blip: every op fails until the
        # plan is disarmed — retries exhaust, the error propagates, and
        # the peer looks DEAD to liveness without any process dying
        _record("partitioned_ops")
        raise InjectedFault(
            f"TCP window service connection lost (injected partition, "
            f"box {name})")
    if plan.drop_tcp and _budget(plan.drop_tcp, name):
        _record("tcp_drops")
        raise InjectedFault(
            f"TCP window service connection lost (injected, box {name})")


def on_controller_iter(process_index: int, iteration: int):
    """Called by the distributed wheel loop at the top of every
    iteration: SIGKILLs THIS controller process when the plan schedules
    its death at (or before) ``iteration`` — a real uncatchable kill,
    exactly what the elastic recovery path must survive on the OTHER
    controllers."""
    plan = _PLAN
    if plan is None or not plan.kill_controller:
        return
    k = plan.kill_controller.get(int(process_index),
                                 plan.kill_controller.get("*"))
    if k is not None and iteration >= int(k):
        _record("controller_kills")
        _SELF_KILL()


def on_server_slice(slices_done: int):
    """Called by the solve server after each scheduler slice's wheel
    tears down (checkpoint banked, status transition NOT yet journaled):
    SIGKILLs this process when the plan schedules the server's death at
    (or before) the ``slices_done``-th slice — the deterministic sibling
    of the serving-chaos smoke's external SIGKILL."""
    plan = _PLAN
    if plan is None or not plan.kill_server_after_slices:
        return
    if int(slices_done) >= int(plan.kill_server_after_slices):
        _record("server_kills")
        _SELF_KILL()


def on_client_op(slot):
    """Called inside each SolveClient transport op: raises a budgeted
    transient connection-lost error (consumed by the client's bounded
    reconnect-with-backoff; exhaustion surfaces as the typed
    ``ServerLost``)."""
    plan = _PLAN
    if plan is None or not plan.drop_client:
        return
    if _budget(plan.drop_client, int(slot) if str(slot).isdigit()
               else slot):
        _record("client_drops")
        raise InjectedFault(
            f"TCP window service connection lost (injected client drop, "
            f"slot {slot})")


def on_ingest():
    """Called by ``SolveServer.submit`` before canonicalization: stalls
    the (unlocked) ingest for the configured seconds so the admission /
    shutdown races around a slow ingest are drivable on demand."""
    plan = _PLAN
    if plan is None or not plan.stall_ingest:
        return
    _record("ingest_stalls")
    time.sleep(float(plan.stall_ingest))


def on_collective(what: str = ""):
    """Called before each watchdog-guarded mesh collective: injects the
    configured delay (slow-fabric emulation — under the mesh timeout it
    must be absorbed, over it the watchdog must fire)."""
    plan = _PLAN
    if plan is None or not plan.delay_collectives:
        return
    _record("delayed_collectives")
    time.sleep(float(plan.delay_collectives))


def active() -> bool:
    return _PLAN is not None
