"""tpusppy.resilience: checkpoint/restart, fault injection, degradation.

The reference treats warm starts as an afterthought (csv dumps of W/xbar
read back by extensions, ``mpisppy/utils/wxbarutils.py``); at production
scale a TPU preemption, a dropped TCP connection, or one dead spoke
currently meant losing the whole run or hanging the hub.  This package is
the robustness layer:

- :mod:`.checkpoint` — versioned, atomic (write-tmp-then-rename),
  asynchronous snapshots of full wheel state (W / xbar / rho, iteration
  counter, best bounds, autotuner verdicts) on a wall-clock or iteration
  cadence, plus the ``resume=`` restore path the wheel spinners consume.
  Capture reads only host-resident state (the single-fetch wheel
  iteration already mirrors everything the host needs — doc/pipeline.md),
  so snapshotting adds ZERO blocking fetches to the dispatch decision
  path (regression-pinned under ``jax.transfer_guard``).
- :mod:`.faults` — a deterministic fault-injection harness: kill a spoke
  at payload k, drop/delay TCP window reads, stale mailbox write-ids.
  Tests PROVE the recovery paths instead of hoping for them.
- :mod:`.supervisor` — per-cylinder heartbeat gauges and the hub-side
  spoke supervisor: a dead or wedged spoke (stale mailbox generation past
  a timeout) is marked LOST and the wheel keeps certifying with the
  remaining bounders instead of hanging.

See doc/resilience.md for the checkpoint format, cadence and resume
semantics, and the degradation rules.
"""

from . import checkpoint, faults, supervisor  # noqa: F401

__all__ = ["checkpoint", "faults", "supervisor"]
