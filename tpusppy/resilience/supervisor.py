"""Cylinder heartbeats + the hub-side spoke supervisor (degradation).

A wheel's availability used to be min() over its cylinders: one dead
spoke thread surfaced only as an exception AFTER the hub finished (or as
a 900 s teardown join), and a wedged spoke (alive but making no mailbox
progress) could pin the hub's linger harvest for its whole budget.  The
supervisor turns spoke health into data the hub acts on each ``sync()``:

- every cylinder publishes a **heartbeat gauge**
  (``heartbeat.<cylinder>`` in :mod:`tpusppy.obs.metrics`, monotonic
  seconds) from its poll loop;
- the hub's :class:`SpokeSupervisor` watches, per spoke, the inbound
  mailbox write-id (real progress), the heartbeat gauge (liveness), and
  the thread/process handle (death), and marks a spoke **LOST** when it
  crashed, silently died, or — with ``spoke_timeout_secs`` set — made no
  progress past the timeout;
- a lost spoke stops gating anything: the linger harvest ends early when
  every spoke is lost, teardown joins give lost spokes a short grace
  instead of the full deadline, their finalize is skipped, and the wheel
  completes with whatever the remaining bounders certified
  (``WheelSpinner.lost_spokes`` names them; the strict_spokes option
  restores the old raise-at-join behavior).

Payloads a spoke posted BEFORE dying remain valid and are still read —
loss only stops the hub WAITING on the dead, never discards bounds.
"""

from __future__ import annotations

import threading
import time

from .. import global_toc
from ..obs import metrics as _metrics
from ..obs import trace as _trace

HEARTBEAT_PREFIX = "heartbeat."

_CTR_LOST = _metrics.counter("supervisor.spokes_lost")


def heartbeat_gauge(cylinder: str):
    """The liveness gauge for ``cylinder`` — poll loops hoist this once
    and ``set(time.monotonic())`` per beat (one lock + a float store)."""
    return _metrics.gauge(HEARTBEAT_PREFIX + cylinder)


def heartbeat(cylinder: str):
    """Publish liveness for ``cylinder`` (gauge = monotonic seconds)."""
    heartbeat_gauge(cylinder).set(time.monotonic())


def last_heartbeat(cylinder: str):
    return _metrics.gauge(HEARTBEAT_PREFIX + cylinder).get()


class _Watch:
    __slots__ = ("name", "last_wid", "last_progress", "thread", "proc",
                 "lost", "reason", "error")

    def __init__(self, name):
        self.name = name
        self.last_wid = None
        self.last_progress = time.monotonic()
        self.thread = None
        self.proc = None
        self.lost = False
        self.reason = None
        self.error = None


class SpokeSupervisor:
    """Hub-side per-spoke health tracker.

    ``fabric`` supplies the inbound (``to_hub``) mailboxes whose write-id
    progression is the progress signal; ``spoke_names`` maps strata rank
    -> display name.  ``timeout_secs=None`` disables staleness-based loss
    (death-based loss is always on): a spoke legitimately deep in a host
    MILP makes no mailbox progress for minutes, so the timeout is an
    operator knob, not a default.

    Staleness is judged on the MONOTONIC clock with a LOAD-ADAPTIVE
    grace: while the sync loop is healthy (inter-``observe`` latency
    within ``timeout_secs``) the operator's window applies UNCHANGED;
    only when the loop itself stalls PAST the window — meaning no valid
    observation could have happened inside it, so any verdict would be
    about the machine, not the spoke — does the effective timeout widen
    to ``grace_factor × observed latency`` (latency = max of the EWMA
    and the latest gap).  Under full-suite CPU contention the hub's own
    loop stalls for seconds at a time — if the observer was starved,
    the spokes were starved too, and a fixed window read that as
    "wedged" (the PR-5 heartbeat false positive that slow-marked the
    dist resume leg).  A wheel whose ROUTINE cadence merely approaches
    the window keeps the configured semantics.
    """

    def __init__(self, fabric, spoke_names: dict, timeout_secs=None,
                 grace_factor: float = 8.0):
        self.fabric = fabric
        self.timeout_secs = (None if timeout_secs in (None, 0)
                             else float(timeout_secs))
        self.grace_factor = float(grace_factor)
        self._last_observe = None
        self._latency_ewma = 0.0
        self._latency_last = 0.0
        self._lock = threading.Lock()
        self._watch = {int(i): _Watch(str(nm))
                       for i, nm in (spoke_names or {}).items()}

    # ---- registration ------------------------------------------------------
    def note_thread(self, idx: int, thread):
        with self._lock:
            if idx in self._watch:
                self._watch[idx].thread = thread

    def note_process(self, idx: int, proc):
        with self._lock:
            if idx in self._watch:
                self._watch[idx].proc = proc

    def note_error(self, idx: int, exc):
        """A spoke's main loop raised: immediate loss."""
        with self._lock:
            w = self._watch.get(idx)
            if w is not None:
                w.error = exc
        self._mark_lost(idx, "crashed")

    # ---- observation (hub sync cadence) ------------------------------------
    def effective_timeout(self):
        """The staleness window actually applied this pass: the operator
        knob, widened ONLY when the observe loop itself stalled past it
        (None = staleness loss disabled)."""
        if self.timeout_secs is None:
            return None
        lat = max(self._latency_ewma, self._latency_last)
        if self.grace_factor <= 0 or lat <= self.timeout_secs:
            return self.timeout_secs
        return self.grace_factor * lat

    def observe(self):
        """One health pass over every non-lost spoke; called by the hub
        each sync.  Reads are mailbox write-ids and gauges — never a
        device or network round-trip beyond what the fabric's write_id
        accessor costs."""
        now = time.monotonic()
        if self._last_observe is not None:
            dt = now - self._last_observe
            self._latency_last = dt
            self._latency_ewma = (dt if self._latency_ewma == 0.0
                                  else 0.8 * self._latency_ewma + 0.2 * dt)
        self._last_observe = now
        eff_timeout = self.effective_timeout()
        for idx, w in list(self._watch.items()):
            if w.lost:
                continue
            progressed = False
            try:
                wid = self.fabric.to_hub[idx].write_id
            except Exception:
                wid = None          # fabric op failed: no progress signal
            if wid is not None and wid != w.last_wid:
                w.last_wid = wid
                progressed = True
            hb = last_heartbeat(f"spoke{idx}")
            if hb is not None and hb > w.last_progress:
                progressed = True
            if progressed:
                w.last_progress = now
                continue
            dead = (w.thread is not None and not w.thread.is_alive()) or \
                   (w.proc is not None and w.proc.exitcode is not None)
            if dead:
                self._mark_lost(idx, "died")
            elif (eff_timeout is not None
                    and now - w.last_progress > eff_timeout):
                self._mark_lost(idx, "wedged")

    def _mark_lost(self, idx: int, reason: str):
        with self._lock:
            w = self._watch.get(idx)
            if w is None or w.lost:
                return
            w.lost = True
            w.reason = reason
        _CTR_LOST.inc(1)
        if _trace.enabled():
            _trace.instant("hub", "spoke_lost", spoke=idx, name=w.name,
                           reason=reason)
        global_toc(
            f"WARNING: spoke {idx} ({w.name}) marked LOST ({reason}) — "
            "continuing with the remaining cylinders", True)

    # ---- queries -----------------------------------------------------------
    def is_lost(self, idx: int) -> bool:
        w = self._watch.get(idx)
        return bool(w and w.lost)

    def lost(self) -> dict:
        """{idx: (name, reason)} of every lost spoke."""
        with self._lock:
            return {i: (w.name, w.reason)
                    for i, w in self._watch.items() if w.lost}

    def lost_names(self) -> list:
        return [f"{nm} ({why})" for nm, why in self.lost().values()]

    def errors(self) -> list:
        with self._lock:
            return [(w.name, w.error) for w in self._watch.values()
                    if w.error is not None]

    def all_lost(self) -> bool:
        with self._lock:
            return bool(self._watch) and all(
                w.lost for w in self._watch.values())
