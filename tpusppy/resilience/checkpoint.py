"""Checkpoint engine: versioned, atomic, asynchronous wheel snapshots.

One checkpoint is one ``.npz`` file holding the full warm-startable wheel
state: per-scenario W, xbar (and xsqbar), rho, the hub iteration counter,
the best inner/outer bounds (overall and per cylinder), and the
autotuner's banked verdicts — everything a resumed run needs for the
certified gap trajectory to continue monotonically.  The write is atomic
(write to a tempfile in the same directory, ``os.replace`` into place), so
a kill at ANY instant leaves either the previous checkpoint or the new
one, never a torn file.

Capture never blocks the dispatch pipeline: the hub's PH state is already
host-resident by the single-fetch wheel-iteration discipline
(doc/pipeline.md — each solve ends in ONE packed measurement fetch, and
W/xbar/rho live as host mirrors), so a snapshot is pure host ``copy()``s,
and the file IO runs on a dedicated writer thread that coalesces to the
newest pending snapshot.  ``CheckpointManager.maybe_capture`` bills the
whole capture through :mod:`tpusppy.obs` (``checkpoint.*`` counters, a
``ckpt`` trace track) and asserts the zero-fetch property at runtime: the
snapshot builder runs under ``jax.transfer_guard_device_to_host`` and any
:func:`tpusppy.solvers.hostsync.fetch` it performed is counted into
``checkpoint.capture_fetches`` (pinned at zero by tests/test_resilience).

Resume: ``WheelSpinner(..., resume=<dir-or-file>)`` (and the hub option
``"resume"``) loads :func:`load_latest` and hands the checkpoint to the
hub opt; :func:`restore_ph` re-seats W/xbars/rho AFTER the warm-up Iter0
(the same seam the reference's WXBarReader uses) and offsets the
iteration counter so ``PHIterLimit`` keeps meaning TOTAL iterations
across restarts.  The hub re-seeds its best bounds from the checkpoint
(:meth:`tpusppy.cylinders.hub.Hub.seed_resume`), so bounds are monotone
across the restart by construction.

Legacy interchange: :func:`write_wxbar` / :func:`read_wxbar` are the
engine's compatibility surface for the reference's W/xbar csv files
(``scenario,varname,value`` rows) — the WXBarWriter/WXBarReader
extensions route through them, writing real checkpoints for ``.npz``
paths and the mpi-sppy csv format for anything else.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import re
import tempfile
import threading
import time

import numpy as np

from ..obs import metrics as _metrics
from ..obs import trace as _trace
from ..obs.log import get_logger

CHECKPOINT_VERSION = 1

_log = get_logger("resilience.checkpoint")

_CTR_CAPTURES = _metrics.counter("checkpoint.captures")
_CTR_CAPTURE_FETCHES = _metrics.counter("checkpoint.capture_fetches")
_CTR_WRITES = _metrics.counter("checkpoint.writes")
_CTR_WRITE_ERRORS = _metrics.counter("checkpoint.write_errors")
_CTR_COALESCED = _metrics.counter("checkpoint.coalesced")
_CTR_RESTORES = _metrics.counter("checkpoint.restores")
_CTR_CORRUPT_SKIPPED = _metrics.counter("checkpoint.corrupt_skipped")
_HIST_WRITE_SECS = _metrics.histogram("checkpoint.write_secs")


@dataclasses.dataclass
class WheelCheckpoint:
    """One snapshot of warm-startable wheel state (all host arrays)."""

    iteration: int
    W: np.ndarray | None = None           # (S, K) dual weights
    xbars: np.ndarray | None = None       # (S, K) node averages
    xsqbars: np.ndarray | None = None     # (S, K)
    rho: np.ndarray | None = None         # (S, K) penalty
    best_inner: float = float("inf")
    best_outer: float = float("-inf")
    spoke_bounds: dict = dataclasses.field(default_factory=dict)
    tune_state: dict = dataclasses.field(default_factory=dict)
    meta: dict = dataclasses.field(default_factory=dict)
    version: int = CHECKPOINT_VERSION

    @property
    def shape(self):
        return None if self.W is None else tuple(self.W.shape)


_ARRAY_FIELDS = ("W", "xbars", "xsqbars", "rho")


# ---------------------------------------------------------------------------
# File format (atomic npz)
# ---------------------------------------------------------------------------
def atomic_write_json(path: str, obj) -> str:
    """THE atomic small-file write (tempfile in the target dir, fsync,
    ``os.replace``) — shared by every JSON sidecar of the resilience
    layer (tune verdict cache, bench ladder state) so the discipline
    lives in one place."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(prefix=".json_tmp_", suffix=".json", dir=d)
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(obj, f, indent=1)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        with contextlib.suppress(OSError):
            os.remove(tmp)
        raise
    return path


def save(ckpt: WheelCheckpoint, path: str) -> str:
    """Atomically write ``ckpt`` to ``path`` (npz).  The tempfile lives in
    the target directory so ``os.replace`` is a same-filesystem rename —
    a kill mid-write can never leave a torn checkpoint."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    meta = {
        "version": int(ckpt.version),
        "iteration": int(ckpt.iteration),
        "best_inner": float(ckpt.best_inner),
        "best_outer": float(ckpt.best_outer),
        # per-spoke entries are [kind, bound] so a resumed wheel with a
        # DIFFERENT spoke topology can still apply each bound under its
        # true semantics (an outer bound is outer whatever slot it came
        # from); bare floats from hand-built checkpoints are tolerated
        "spoke_bounds": {
            str(k): (list(v) if isinstance(v, (list, tuple))
                     else float(v))
            for k, v in (ckpt.spoke_bounds or {}).items()},
        "tune_state": ckpt.tune_state or {},
        "meta": ckpt.meta or {},
        "arrays": [f for f in _ARRAY_FIELDS
                   if getattr(ckpt, f) is not None],
    }
    arrays = {f: np.asarray(getattr(ckpt, f), dtype=np.float64)
              for f in meta["arrays"]}
    fd, tmp = tempfile.mkstemp(prefix=".ckpt_tmp_", suffix=".npz", dir=d)
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, meta=np.array(json.dumps(meta)), **arrays)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        with contextlib.suppress(OSError):
            os.remove(tmp)
        raise
    return path


def load(path: str, _assemble: bool = True) -> WheelCheckpoint:
    """Read one checkpoint file; unknown versions are refused loudly (a
    silent partial restore would corrupt the gap trajectory it exists to
    preserve).  A member of a SHARDED set (``.s<k>of<n>.npz``) loads the
    whole set assembled — pass through :func:`load_sharded` explicitly
    (or :class:`ShardedCheckpointReader` for row reads) to control that."""
    if _assemble and _SHARD_RE.match(os.path.basename(path)):
        return load_sharded(path)
    with np.load(path, allow_pickle=False) as z:
        meta = json.loads(str(z["meta"][()]))
        if int(meta.get("version", -1)) > CHECKPOINT_VERSION:
            raise RuntimeError(
                f"checkpoint {path} has version {meta.get('version')}; "
                f"this build reads <= {CHECKPOINT_VERSION}")
        arrays = {f: np.array(z[f]) for f in meta.get("arrays", [])
                  if f in z}
    return WheelCheckpoint(
        iteration=int(meta["iteration"]),
        best_inner=float(meta.get("best_inner", float("inf"))),
        best_outer=float(meta.get("best_outer", float("-inf"))),
        spoke_bounds=dict(meta.get("spoke_bounds", {})),
        tune_state=dict(meta.get("tune_state", {})),
        meta=dict(meta.get("meta", {})),
        version=int(meta.get("version", CHECKPOINT_VERSION)),
        **arrays,
    )


_CKPT_RE = re.compile(r"^ckpt_.*_(\d+)\.npz$")
_SHARD_RE = re.compile(r"^ckpt_.*_(\d+)\.s(\d+)of(\d+)\.npz$")


def checkpoint_path(directory: str, iteration: int,
                    tag: str = "wheel") -> str:
    return os.path.join(directory, f"ckpt_{tag}_{int(iteration):08d}.npz")


def shard_checkpoint_path(directory: str, iteration: int, shard: int,
                          num_shards: int, tag: str = "wheel") -> str:
    """Per-shard file of one sharded checkpoint: ``ckpt_<tag>_<iter>.
    s<k>of<n>.npz`` — each process writes ONLY its scenario-row slice, so
    a 100k-scenario snapshot never materializes on one host."""
    return os.path.join(
        directory,
        f"ckpt_{tag}_{int(iteration):08d}"
        f".s{int(shard):03d}of{int(num_shards):03d}.npz")


def list_checkpoints(directory: str) -> list:
    """[(iteration, path)] ascending; tolerates foreign files.

    A SHARDED checkpoint (``.s<k>of<n>.npz`` siblings) appears once, as
    its shard-0 path, and only when the set is COMPLETE — per-shard
    writes are individually atomic but the set is not, so a kill between
    shard renames must leave the previous complete checkpoint as
    ``latest``, never a torn set."""
    out = []
    shard_sets: dict = {}
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    for nm in names:
        m = _CKPT_RE.match(nm)
        if m:
            out.append((int(m.group(1)), os.path.join(directory, nm)))
            continue
        m = _SHARD_RE.match(nm)
        if m:
            it, k, n = (int(m.group(i)) for i in (1, 2, 3))
            shard_sets.setdefault((it, n), {})[k] = os.path.join(
                directory, nm)
    for (it, n), shards in shard_sets.items():
        if len(shards) == n and 0 in shards:
            out.append((it, shards[0]))
    return sorted(out)


def _read_npy_header(f):
    """(shape, dtype) of one npy stream — public numpy surface first,
    the private helper only as the fallback (upstream drift must not be
    able to fail verification, see :func:`verify`)."""
    from numpy.lib import format as _npf

    version = _npf.read_magic(f)
    if version == (1, 0):
        shape, _fortran, dtype = _npf.read_array_header_1_0(f)
    elif version == (2, 0):
        shape, _fortran, dtype = _npf.read_array_header_2_0(f)
    else:
        shape, _fortran, dtype = _npf._read_array_header(f, version)
    return shape, dtype


def _verify_member(path: str):
    """Integrity check of ONE npz checkpoint file without reading array
    data: the zip central directory must be present (truncation chops it
    off — it lives at the END of the file), ``meta`` must parse, and
    every array the meta declares must have a parseable npy header whose
    payload size matches its (stored, uncompressed) zip entry.  Raises
    on any mismatch."""
    import zipfile

    with zipfile.ZipFile(path) as zf:
        names = set(zf.namelist())
        if "meta.npy" not in names:
            raise ValueError(f"{path}: no meta member")
        with zf.open("meta.npy") as f:
            meta = json.loads(str(np.lib.format.read_array(
                f, allow_pickle=False)[()]))
        for fld in meta.get("arrays", []):
            nm = fld + ".npy"
            if nm not in names:
                raise ValueError(f"{path}: declared array {fld!r} missing")
            info = zf.getinfo(nm)
            with zf.open(nm) as f:
                shape, dtype = _read_npy_header(f)
                expect = f.tell() + int(np.prod(shape)) * dtype.itemsize
            if info.file_size != expect:
                raise ValueError(
                    f"{path}: array {fld!r} is {info.file_size} bytes, "
                    f"header promises {expect} — truncated/corrupt")


#: the exception classes that MEAN "this file is corrupt" — everything
#: else (NFS blips, numpy API drift, ...) must NOT be read as corruption
_CORRUPT_ERRORS = (ValueError, KeyError, EOFError)


def verify(path: str) -> bool:
    """True when the checkpoint ARTIFACT at ``path`` (every shard sibling
    when it names a sharded-set member) passes the size + per-array
    header check — cheap enough for the resume walk, strong enough to
    catch a truncated/torn file before it raises out of a restore.

    FAIL-OPEN on unexpected errors: only genuine corruption signatures
    (bad zip, unparsable meta/header, size mismatch) report False.  An
    environmental or drift failure (transient IO, a numpy rename) says
    True and lets :func:`load` fail loud instead — a blanket "corrupt"
    verdict here would make ``latest()`` skip EVERY set, the resume
    silently cold-start, and the manager's ``fresh_start`` then DELETE
    the healthy snapshots."""
    import zipfile

    for p in (_shard_sibling_names(path) or [path]):
        try:
            _verify_member(p)
        except (zipfile.BadZipFile, *_CORRUPT_ERRORS):
            return False
        except Exception as e:      # fail open, loudly
            _log.warning("checkpoint verification of %s errored (%r) — "
                         "treating as valid; the load will decide", p, e)
    return True


def latest(directory: str, verify_integrity: bool = True) -> str | None:
    """Path of the newest VALID checkpoint in ``directory`` (None when
    empty).  A corrupt/truncated newest set — e.g. filesystem damage
    after the atomic rename — is skipped loudly
    (``checkpoint.corrupt_skipped``) and the previous complete set
    serves instead of the resume crashing out of ``load``."""
    for _it, p in reversed(list_checkpoints(directory)):
        if not verify_integrity or verify(p):
            return p
        _CTR_CORRUPT_SKIPPED.inc(1)
        _log.warning("checkpoint %s failed integrity verification — "
                     "falling back to the previous complete set", p)
    return None


def latest_iteration(directory: str) -> int | None:
    """Iteration number of the newest VALID checkpoint in ``directory``
    (None when there is none) — WITHOUT decompressing any array block.
    The solve server's restart-recovery triage runs this per journaled
    tenant to decide warm-resume vs loud cold restart, so it must stay
    cheap even when a work dir holds many parked tenants.  One
    directory walk (``latest()``'s verify loop, keeping the iteration
    instead of discarding it) — re-listing after ``latest()`` could
    race a concurrent prune and miss the match."""
    for it, p in reversed(list_checkpoints(directory)):
        if verify(p):
            return int(it)
        _CTR_CORRUPT_SKIPPED.inc(1)
        _log.warning("checkpoint %s failed integrity verification — "
                     "falling back to the previous complete set", p)
    return None


def load_latest(path: str) -> WheelCheckpoint | None:
    """Load ``path`` directly (a file) or its newest VALID checkpoint (a
    directory — corrupt sets are skipped with a
    ``checkpoint.corrupt_skipped`` count; an explicitly named FILE still
    fails loud, the caller pinned it).  None when nothing is there —
    callers treat a missing checkpoint as a cold start, which is what
    ``--resume`` on a first run must mean.  A sharded set loads
    ASSEMBLED (all rows on this host); big-S callers that must never
    materialize the full state use :class:`ShardedCheckpointReader` /
    :func:`restore_sharded_array`."""
    if path is None:
        return None
    if os.path.isdir(path):
        p = latest(path)
        return None if p is None else load(p)
    if os.path.exists(path):
        return load(path)
    return None


# ---------------------------------------------------------------------------
# Sharded checkpoints (scenario scale-out, ROADMAP item 1): the (S, K)
# wheel state is written as one npz PER SCENARIO-ROW SHARD — each process
# of a multi-controller mesh saves only its local rows, and a resume
# rebuilds the device array via ``jax.make_array_from_callback`` reading
# only the shard files that overlap its addressable rows.  A 100k-scenario
# snapshot therefore never materializes on one host, on either side.
# ---------------------------------------------------------------------------
def save_shard(ckpt: WheelCheckpoint, directory: str, shard: int,
               num_shards: int, rows, S_total: int,
               tag: str = "wheel") -> str:
    """Atomically write ONE shard of a sharded checkpoint.

    ``ckpt``'s arrays hold only this shard's rows; ``rows`` is their
    (lo, hi) global row range and ``S_total`` the full scenario count.
    Every shard carries the full scalar meta (iteration, bounds, ...) so
    any single shard can answer metadata queries without its siblings."""
    lo, hi = (int(rows[0]), int(rows[1]))
    sh_meta = {"index": int(shard), "count": int(num_shards),
               "rows": [lo, hi], "S": int(S_total)}
    for f in _ARRAY_FIELDS:
        a = getattr(ckpt, f, None)
        if a is not None and np.ndim(a) == 2:
            # column width in the meta so readers can answer shape
            # queries without decompressing any shard's array block
            sh_meta["K"] = int(np.shape(a)[1])
            break
    ck = dataclasses.replace(
        ckpt, meta=dict(ckpt.meta or {}, shard=sh_meta))
    return save(ck, shard_checkpoint_path(directory, ckpt.iteration,
                                          shard, num_shards, tag))


def _shard_sibling_names(path: str) -> list:
    """Every sibling shard PATH of one set member, derived from the
    ``.s<k>of<n>`` name pattern alone — no file is opened, and the list
    is independent of which siblings currently exist."""
    d, base = os.path.split(os.path.abspath(path))
    m = _SHARD_RE.match(base)
    if not m:
        return []
    n = int(m.group(3))
    stem = base[:base.rindex(".s")]
    return [os.path.join(d, f"{stem}.s{k:03d}of{n:03d}.npz")
            for k in range(n)]


def shard_set_paths(path: str) -> list:
    """[(lo, hi, path)] for every sibling shard of one sharded-checkpoint
    member ``path``, ascending by row range; [] when ``path`` is not a
    shard file or the set is incomplete/unreadable (a sibling vanishing
    mid-listing — e.g. a concurrent controller's cleanup — reads as
    incomplete, never as a crash)."""
    import zipfile

    out = []
    for p in _shard_sibling_names(path):
        try:
            with np.load(p, allow_pickle=False) as z:
                meta = json.loads(str(z["meta"][()]))
        except (OSError, KeyError, ValueError, zipfile.BadZipFile):
            return []
        lo, hi = meta.get("meta", {}).get("shard", {}).get("rows", (0, 0))
        out.append((int(lo), int(hi), p))
    return sorted(out)


def remove_checkpoint_files(path: str):
    """Remove one checkpoint ARTIFACT: the file itself, plus every
    sibling shard when ``path`` is a member of a sharded set
    (``list_checkpoints`` names a complete set by its shard-0 path, so a
    prune that removed only that file would orphan the siblings
    forever).  Pure name-pattern deletion: nothing is opened, so
    concurrent cleanup across controllers cannot race a read."""
    for p in _shard_sibling_names(path) or [path]:
        with contextlib.suppress(OSError):
            os.remove(p)


def load_sharded(path: str) -> WheelCheckpoint:
    """Assemble one FULL checkpoint from a sharded set (any member path).
    Host-side concatenation — the compatibility loader for single-host
    resumes; the O(1)-host path is :func:`restore_sharded_array`."""
    parts = shard_set_paths(path)
    if not parts:
        raise RuntimeError(f"incomplete or foreign sharded checkpoint "
                           f"set at {path}")
    members = [load(p, _assemble=False) for _, _, p in parts]
    first = members[0]
    S = int((first.meta or {}).get("shard", {}).get("S", 0)) or \
        sum(hi - lo for lo, hi, _ in parts)
    out = dataclasses.replace(first, meta={
        k: v for k, v in (first.meta or {}).items() if k != "shard"})
    for f in _ARRAY_FIELDS:
        if getattr(first, f) is None:
            continue
        full = np.zeros((S,) + getattr(first, f).shape[1:])
        for (lo, hi, _), mem in zip(parts, members):
            full[lo:hi] = getattr(mem, f)
        setattr(out, f, full)
    return out


class ShardedCheckpointReader:
    """Row-range reads over one sharded checkpoint set, opening only the
    shard files a requested slice overlaps (one npz handle cache per
    file).  The ``jax.make_array_from_callback`` feeder: each process
    asks for its addressable rows only, so no host ever reads rows it
    does not own."""

    def __init__(self, path: str):
        self.parts = shard_set_paths(path)
        if not self.parts:
            raise RuntimeError(
                f"incomplete or foreign sharded checkpoint set at {path}")
        with np.load(self.parts[0][2], allow_pickle=False) as z:
            self.meta = json.loads(str(z["meta"][()]))
        sh = self.meta.get("meta", {}).get("shard", {})
        self.S = int(sh.get("S", self.parts[-1][1]))
        self.K = int(sh["K"]) if "K" in sh else None
        self.iteration = int(self.meta.get("iteration", 0))
        self._cache: dict = {}

    def drop_cache(self):
        """Release the per-shard array cache (call once the restore is
        done: the reader may be kept alive by closures for the run's
        lifetime, and cached foreign-row blocks would otherwise dilute
        the O(1)-per-host contract this API exists for)."""
        self._cache = {}

    def _shard_arrays(self, p: str) -> dict:
        got = self._cache.get(p)
        if got is None:
            with np.load(p, allow_pickle=False) as z:
                meta = json.loads(str(z["meta"][()]))
                got = {f: np.array(z[f])
                       for f in meta.get("arrays", []) if f in z}
            self._cache[p] = got
        return got

    def read_rows(self, field: str, lo: int, hi: int) -> np.ndarray:
        """Rows [lo, hi) of ``field`` assembled from the overlapping
        shards.  Rows at/above the stored S (``pad_to`` ghost padding on
        an uneven mesh) come back zero — ghosts never checkpoint."""
        lo, hi = int(lo), int(hi)
        cols = None
        chunks = []
        for slo, shi, p in self.parts:
            if shi <= lo or slo >= hi:
                continue
            a = self._shard_arrays(p).get(field)
            if a is None:
                raise KeyError(f"field {field!r} absent from shard {p}")
            cols = a.shape[1:]
            chunks.append(a[max(lo - slo, 0):max(min(hi, shi) - slo, 0)])
        if cols is None:
            # an ALL-ghost request (a device whose rows are entirely mesh
            # padding): zeros, shaped like the field's columns
            a0 = self._shard_arrays(self.parts[0][2]).get(field)
            if a0 is None:
                raise KeyError(f"field {field!r} absent from shard set")
            return np.zeros((hi - lo,) + a0.shape[1:])
        got = np.concatenate(chunks) if len(chunks) > 1 else chunks[0]
        if got.shape[0] < hi - lo:        # ghost-row tail: zeros
            pad = np.zeros((hi - lo - got.shape[0],) + cols)
            got = np.concatenate([got, pad])
        return got


def restore_sharded_array(src, field: str, sharding, shape, dtype=None):
    """Device array of ``field`` from a sharded checkpoint set, built via
    ``jax.make_array_from_callback`` so each process reads ONLY the shard
    files overlapping its addressable rows — the O(1)-per-host restore.
    ``src`` is a shard-member path or an existing
    :class:`ShardedCheckpointReader` (reused: building a fresh reader
    re-opens every shard's meta, which the caller often already paid).
    ``shape`` is the (possibly ghost-padded) global device shape; rows
    past the stored S fill with zeros."""
    import jax

    reader = src if isinstance(src, ShardedCheckpointReader) \
        else ShardedCheckpointReader(src)

    def cb(idx):
        r = idx[0]
        lo = 0 if r.start is None else r.start
        hi = shape[0] if r.stop is None else r.stop
        block = reader.read_rows(field, lo, hi)
        rest = tuple(idx[1:])
        block = block[(slice(None),) + rest]
        return block if dtype is None else block.astype(dtype)

    return jax.make_array_from_callback(tuple(shape), sharding, cb)


# ---------------------------------------------------------------------------
# PH state capture / restore
# ---------------------------------------------------------------------------
def capture_ph(opt, hub=None) -> WheelCheckpoint | None:
    """Snapshot a PH-like opt object (host copies only — W/xbars/rho are
    host mirrors by the single-fetch discipline, so this performs no
    device fetch).  Returns None for opt objects without PH state (e.g.
    an L-shaped hub) so callers can skip cleanly."""
    W = getattr(opt, "W", None)
    if W is None:
        return None
    ck = WheelCheckpoint(
        iteration=int(getattr(opt, "_iter", 0)),
        W=np.array(W, dtype=np.float64, copy=True),
        xbars=np.array(opt.xbars, dtype=np.float64, copy=True)
        if getattr(opt, "xbars", None) is not None else None,
        xsqbars=np.array(opt.xsqbars, dtype=np.float64, copy=True)
        if getattr(opt, "xsqbars", None) is not None else None,
        rho=np.array(opt.rho, dtype=np.float64, copy=True)
        if getattr(opt, "rho", None) is not None else None,
        meta={
            "S": int(W.shape[0]), "K": int(W.shape[1]),
            "opt_class": type(opt).__name__,
            "num_scenarios": len(getattr(opt, "all_scenario_names", ())),
        },
    )
    from .. import tune as _tune
    from ..solvers import aot as _aot

    # the executable-cache POINTER rides the snapshot: a resumed process
    # (possibly launched without the env knob) re-arms the same cache and
    # reaches its first PH iteration warm — checkpoint + cache compose
    # (WheelSpinner._prewarm_executables consumes this)
    if _aot.cache_path():
        ck.meta["aot_cache"] = os.path.abspath(_aot.cache_path())
    ck.tune_state = _tune.export_state()
    if hub is not None:
        ck.best_inner = float(getattr(hub, "BestInnerBound", float("inf")))
        ck.best_outer = float(getattr(hub, "BestOuterBound", float("-inf")))
        # bounds are stored WITH their kind: validity is a property of
        # the bound, not of which spoke slot happens to hold it in the
        # (possibly different) resumed wheel
        outer = getattr(hub, "outerbound_spoke_indices", set()) or set()
        inner = getattr(hub, "innerbound_spoke_indices", set()) or set()
        ck.spoke_bounds = {
            str(idx): ["outer" if idx in outer else "inner", float(b)]
            for idx, b in (getattr(hub, "latest_spoke_bounds", {})
                           or {}).items()
            if idx in outer or idx in inner}
    return ck


def restore_ph(opt, ckpt: WheelCheckpoint):
    """Re-seat PH state from a checkpoint (the post-Iter0 seam: Iter0's
    plain warm-up solve has run, and the W/xbars/rho it computed are
    REPLACED wholesale, so the next iterk solve sees exactly the
    checkpointed augmented objective).  Also offsets the iteration
    counter: ``PHIterLimit`` keeps meaning TOTAL iterations across
    restarts (``iterk_loop`` starts at ``_iter_base + 1``)."""
    S, K = opt.W.shape
    if ckpt.W is None or ckpt.W.shape != (S, K):
        raise RuntimeError(
            f"checkpoint shape {ckpt.shape} does not match this wheel's "
            f"PH state ({S}, {K}) — resuming a different family?")
    opt.W = np.array(ckpt.W, copy=True)
    if ckpt.xbars is not None:
        opt.xbars = np.array(ckpt.xbars, copy=True)
    if ckpt.xsqbars is not None:
        opt.xsqbars = np.array(ckpt.xsqbars, copy=True)
    if ckpt.rho is not None:
        opt.rho = np.array(ckpt.rho, copy=True)
    opt._iter_base = int(ckpt.iteration)
    if hasattr(opt, "_bump_state_version"):
        opt._bump_state_version()   # hub payload tokens must see new state
    if ckpt.tune_state:
        from .. import tune as _tune

        _tune.import_state(ckpt.tune_state)
    _CTR_RESTORES.inc(1)
    if _trace.enabled():
        _trace.instant("ckpt", "restore", iteration=ckpt.iteration,
                       best_inner=ckpt.best_inner,
                       best_outer=ckpt.best_outer)
    _log.info("restored checkpoint at iteration %d (inner=%.6g outer=%.6g)",
              ckpt.iteration, ckpt.best_inner, ckpt.best_outer)


@contextlib.contextmanager
def _no_d2h_guard():
    """Disallow implicit device->host transfers for the duration (the
    zero-blocking-fetch contract of capture); no-op when jax is absent
    or the guard API is unavailable."""
    try:
        import jax

        ctx = jax.transfer_guard_device_to_host("disallow")
    except Exception:       # pure-host posture / ancient jax
        yield
        return
    with ctx:
        yield


# ---------------------------------------------------------------------------
# Async manager
# ---------------------------------------------------------------------------
class CheckpointManager:
    """Cadence-gated asynchronous checkpointing for one wheel run.

    ``maybe_capture(iteration, snapshot_fn)`` snapshots when the wall
    clock (``every_secs``) or iteration (``every_iters``) cadence is due
    and the iteration advanced; the snapshot is pure host copies
    (guarded: implicit D2H disallowed, explicit hostsync fetches billed
    to ``checkpoint.capture_fetches`` — zero on every shipped path), and
    the npz write runs on a dedicated writer thread that coalesces to
    the newest pending snapshot, so a slow disk can never backlog or
    stall the hub loop.  ``keep`` most-recent files are retained.
    """

    def __init__(self, directory: str, every_secs: float | None = 60.0,
                 every_iters: int | None = None, keep: int = 3,
                 tag: str = "wheel", fresh_start: bool = False,
                 shard=None):
        self.directory = str(directory)
        # shard = (index, count, (row_lo, row_hi), S_total): this manager
        # writes ONE scenario-row shard per snapshot (save_shard) — every
        # process of a multi-controller mesh owns a manager for its rows,
        # so no host ever serializes the full (S, K) state
        self.shard = None if shard is None else (
            int(shard[0]), int(shard[1]),
            (int(shard[2][0]), int(shard[2][1])), int(shard[3]))
        os.makedirs(self.directory, exist_ok=True)
        if fresh_start:
            # a COLD run pointed at a reused directory: a previous run's
            # snapshots must not survive (retention keys on iteration
            # only, so they would out-prune this run's early snapshots
            # AND hijack a later resume with foreign state) — the
            # spinners pass fresh_start=True whenever no resume loaded
            stale = [p for _, p in list_checkpoints(self.directory)]
            stale += [p for _, p in self._own_shard_files()]
            for p in dict.fromkeys(stale):
                remove_checkpoint_files(p)
            if stale:
                _log.info("cold start: cleared %d stale checkpoint(s) "
                          "from %s", len(stale), self.directory)
        self.every_secs = None if every_secs in (None, 0) else float(every_secs)
        self.every_iters = None if not every_iters else int(every_iters)
        self.keep = max(1, int(keep))
        self.tag = str(tag)
        self._last_t = time.monotonic()
        self._last_iter = None
        self._lock = threading.Lock()
        self._pending: WheelCheckpoint | None = None
        self._cv = threading.Condition(self._lock)
        self._writing = False
        self._closed = False
        self._thread: threading.Thread | None = None

    # ---- cadence ----------------------------------------------------------
    def _due(self, iteration: int) -> bool:
        if self._last_iter is not None and iteration <= self._last_iter:
            return False        # never re-capture the same iteration
        if self.every_iters is not None:
            base = -self.every_iters if self._last_iter is None \
                else self._last_iter
            if iteration - base >= self.every_iters:
                return True
        if self.every_secs is not None:
            return time.monotonic() - self._last_t >= self.every_secs
        return False

    def maybe_capture(self, iteration: int, snapshot_fn) -> bool:
        if self._closed or not self._due(iteration):
            return False
        return self.capture(iteration, snapshot_fn)

    def capture(self, iteration: int, snapshot_fn) -> bool:
        """Snapshot NOW and enqueue the write.  Returns False when the
        snapshot builder declined (returned None)."""
        from ..solvers import hostsync

        # THREAD-LOCAL fetch accounting: concurrent spoke threads fetch
        # continuously in a live wheel, and a process-global counter
        # delta would bill their traffic as capture fetches — false
        # positives in the exact signal the zero pin exists to watch
        with _trace.span("ckpt", "capture", iteration=int(iteration)):
            with hostsync.track() as _ftr, _no_d2h_guard():
                snap = snapshot_fn()
        if snap is None:
            # a hub whose opt carries no snapshot-able state (e.g. a
            # Benders root): advance the cadence clocks anyway so the
            # decline doesn't refire on EVERY sync, and say once that
            # the armed checkpoint_dir is inert for this hub
            self._last_t = time.monotonic()
            self._last_iter = int(iteration)
            _metrics.inc("checkpoint.captures_declined")
            if not getattr(self, "_declined_warned", False):
                self._declined_warned = True
                _log.warning(
                    "snapshot builder declined (opt has no checkpointable "
                    "PH state) — checkpointing is inactive for this hub")
            return False
        # the zero-fetch property, measured not presumed: any explicit
        # decision-path fetch inside the snapshot lands here (pinned ==0)
        _CTR_CAPTURE_FETCHES.inc(_ftr.count)
        _CTR_CAPTURES.inc(1)
        snap.iteration = int(iteration)
        self._last_t = time.monotonic()
        self._last_iter = int(iteration)
        if self.shard is not None:
            # SHARDED managers write SYNCHRONOUSLY: the async writer
            # thread coalesces to the newest pending snapshot
            # independently per process, so two controllers on unevenly
            # loaded disks would persist DISJOINT iteration sets and the
            # keep-window prune could leave no COMPLETE set at all.  A
            # synchronous write keeps every process's shard files
            # aligned with the (deterministic, iteration-cadence)
            # capture schedule by construction; the cost is 1/n_shards
            # of the state per write, on a path that is already
            # collective-lockstep across controllers.
            self._write(snap)
            return True
        with self._cv:
            if self._pending is not None:
                _CTR_COALESCED.inc(1)     # newest snapshot wins
            self._pending = snap
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._writer_loop, name="ckpt-writer",
                    daemon=True)
                self._thread.start()
            self._cv.notify_all()
        return True

    # ---- writer thread ----------------------------------------------------
    def _writer_loop(self):
        _trace.set_thread_track("ckpt")
        while True:
            with self._cv:
                while self._pending is None and not self._closed:
                    self._cv.wait(timeout=1.0)
                if self._pending is None and self._closed:
                    return
                snap, self._pending = self._pending, None
                self._writing = True
            try:
                self._write(snap)
            finally:
                with self._cv:
                    self._writing = False
                    self._cv.notify_all()

    def _write(self, snap: WheelCheckpoint):
        t0 = time.perf_counter()
        if self.shard is not None:
            k, n, rows, S = self.shard
            path = shard_checkpoint_path(self.directory, snap.iteration,
                                         k, n, self.tag)
        else:
            path = checkpoint_path(self.directory, snap.iteration, self.tag)
        try:
            with _trace.span("ckpt", "write", iteration=snap.iteration):
                if self.shard is not None:
                    save_shard(snap, self.directory, k, n, rows, S,
                               tag=self.tag)
                else:
                    save(snap, path)
            _CTR_WRITES.inc(1)
            _HIST_WRITE_SECS.add(time.perf_counter() - t0)
            self._prune()
        except Exception as e:
            # a full disk must degrade the run's resumability, never the
            # run itself
            _CTR_WRITE_ERRORS.inc(1)
            _log.warning("checkpoint write failed (%s): %r", path, e)

    def _own_shard_files(self) -> list:
        """[(iteration, path)] of THIS manager's shard files, ascending —
        a sharded manager prunes only the rows it owns (siblings belong
        to their own processes' managers)."""
        if self.shard is None:
            return []
        k, n, _, _ = self.shard
        suffix = f".s{k:03d}of{n:03d}.npz"
        out = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            return []
        for nm in names:
            m = _SHARD_RE.match(nm)
            if m and nm.endswith(suffix):
                out.append((int(m.group(1)),
                            os.path.join(self.directory, nm)))
        return sorted(out)

    def _prune(self):
        if self.shard is not None:
            # a sharded manager prunes ONLY its own shard files — the
            # siblings belong to their processes' managers
            for _, p in self._own_shard_files()[:-self.keep]:
                with contextlib.suppress(OSError):
                    os.remove(p)
            return
        for _, p in list_checkpoints(self.directory)[:-self.keep]:
            # a complete sharded set is listed by its shard-0 path:
            # removing that alone would orphan the sibling shards
            remove_checkpoint_files(p)

    # ---- teardown ---------------------------------------------------------
    def flush(self, timeout: float = 30.0) -> bool:
        """Wait for every enqueued write to land (True on success)."""
        deadline = time.monotonic() + timeout
        with self._cv:
            while self._pending is not None or self._writing:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._cv.wait(timeout=left)
        return True

    def close(self, timeout: float = 30.0):
        self.flush(timeout)
        with self._cv:
            self._closed = True
            self._cv.notify_all()


# ---------------------------------------------------------------------------
# Legacy W/xbar interchange (mpi-sppy wxbarutils csv format)
# ---------------------------------------------------------------------------
def write_wxbar(opt, w_fname=None, xbar_fname=None, sep_files=False):
    """Engine-side writer behind the WXBarWriter extension.

    ``.npz`` targets get a REAL checkpoint (atomic, versioned, holding W
    and xbar together — an ``xbar_fname`` naming the SAME file is then
    redundant); any other target keeps the reference's csv formats
    byte-compatible (``scenario,varname,value`` W rows appended per
    iteration, ``varname,value`` xbar rows) via
    :mod:`tpusppy.utils.wxbarutils`.  Mixed forms write BOTH targets —
    an npz W next to a csv xbar still produces the csv (the read side
    resolves the same mix slot-by-slot).
    """
    from ..utils import wxbarutils

    ck_box = []

    def _ck():
        """One capture per call, however many npz targets consume it."""
        if not ck_box:
            ck_box.append(capture_ph(opt))
        return ck_box[0]

    if w_fname:
        if str(w_fname).endswith(".npz"):
            if _ck() is not None:
                save(_ck(), w_fname)
            if xbar_fname == w_fname:
                return           # one checkpoint already carries both
        else:
            wxbarutils.write_W_to_file(opt, w_fname, sep_files=sep_files)
    if xbar_fname:
        if str(xbar_fname).endswith(".npz"):
            if _ck() is not None:
                save(_ck(), xbar_fname)
        else:
            wxbarutils.write_xbar_to_file(opt, xbar_fname)


def read_wxbar(opt, w_fname=None, xbar_fname=None, sep_files=False):
    """Engine-side reader behind the WXBarReader extension: a ``.npz``
    W target restores the full checkpoint (W, xbar, rho) in one shot;
    csv files go through the legacy readers unchanged.  Mixed forms
    respect their slot — an npz passed as ``xbar_fname`` next to a csv
    ``w_fname`` restores only the xbar fields, never clobbering the W
    the caller explicitly sourced from the csv."""
    from ..utils import wxbarutils

    def _restore_npz(fname, want_w, want_xbar):
        ck = load(fname)
        # same family guard as restore_ph: a wrong-shaped W silently
        # installed here would corrupt the duals instead of failing loud
        S, K = opt.W.shape
        if ck.W is not None and ck.W.shape != (S, K):
            raise RuntimeError(
                f"checkpoint {fname} has W shape {ck.W.shape}; this "
                f"opt's PH state is ({S}, {K}) — a different family?")
        if want_w:
            if ck.W is not None:
                opt.W = np.array(ck.W, copy=True)
            if ck.rho is not None:
                opt.rho = np.array(ck.rho, copy=True)
        if want_xbar:
            if ck.xbars is not None:
                opt.xbars = np.array(ck.xbars, copy=True)
            if ck.xsqbars is not None:
                opt.xsqbars = np.array(ck.xsqbars, copy=True)
        if hasattr(opt, "_bump_state_version"):
            opt._bump_state_version()

    if w_fname and str(w_fname).endswith(".npz"):
        # the W checkpoint covers xbar too UNLESS a distinct xbar source
        # was requested alongside it
        _restore_npz(w_fname, want_w=True,
                     want_xbar=not xbar_fname or xbar_fname == w_fname)
        if xbar_fname == w_fname:
            xbar_fname = None
        w_fname = None
    elif w_fname:
        wxbarutils.set_W_from_file(w_fname, opt, sep_files=sep_files)
        w_fname = None
    if xbar_fname:
        if str(xbar_fname).endswith(".npz"):
            _restore_npz(xbar_fname, want_w=False, want_xbar=True)
        else:
            wxbarutils.set_xbar_from_file(xbar_fname, opt)
