"""SPBase: scenario ownership, probabilities, options — the runtime root.

TPU-native analogue of ``mpisppy/spbase.py:22-651``.  Where the reference
instantiates one Pyomo model per scenario on each MPI rank and splits
communicators per tree node (spbase.py:255-291, 333-375), this class builds the
whole local scenario set as ONE :class:`~tpusppy.ir.ScenarioBatch` and
precomputes the node-grouping index arrays that replace per-node communicators:
node-grouped weighted averages become one-hot matmuls + (when sharded) ``psum``
over the mesh scenario axis.
"""

from __future__ import annotations

import numpy as np

from . import global_toc
from .ir import ScenarioBatch
from .solvers.admm import ADMMSettings


_BATCH_CACHE: dict = {}


def clear_batch_cache():
    _BATCH_CACHE.clear()


def _kwargs_key(kwargs: dict) -> tuple:
    """Exact, collision-safe cache key for scenario_creator_kwargs: numpy
    arrays hash by (shape, dtype, content bytes) — their repr truncates
    past 1000 elements and would alias distinct families."""
    import hashlib

    parts = []
    for k in sorted(kwargs):
        v = kwargs[k]
        if isinstance(v, np.ndarray):
            h = hashlib.sha1(np.ascontiguousarray(v).tobytes()).hexdigest()
            parts.append((k, "ndarray", v.shape, str(v.dtype), h))
        else:
            parts.append((k, repr(v)))
    return tuple(parts)


def build_batch(options, all_scenario_names, scenario_creator,
                scenario_creator_kwargs=None, verbose=False):
    """Model ingest -> canonical batched arrays, as a free function.

    The construction half of :class:`SPBase` (problems -> optional
    bundling -> optional shape-bucketing -> one batched array family),
    split out so it can run WITHOUT an opt object: the serving
    canonicalizer (:mod:`tpusppy.service.canonical`) ingests a request
    once, fingerprints its shape family, and hands the prebuilt batch to
    every cylinder via ``options["canonical_model"]`` — ingest never
    re-runs per cylinder, and wheel execution binds to already-compiled
    programs when the family was seen before (doc/serving.md).

    Returns ``(batch, bundling, names)`` where ``names`` is the
    (possibly bundled) scenario/bundle name list.
    """
    options = dict(options or {})
    names = list(all_scenario_names)
    problems = [
        scenario_creator(name, **dict(scenario_creator_kwargs or {}))
        for name in names
    ]
    # bundling (P6): merge scenario groups into per-bundle EFs before
    # batching (spbase.py:219-253 + spopt.py:743-836 collapsed); with one
    # controller, "bundles_per_rank" is the total bundle count
    nbundles = int(options.get("bundles_per_rank", 0) or 0)
    bundling = nbundles > 0
    if bundling:
        from .bundles import form_bundles

        problems = form_bundles(problems, nbundles)
        names = [p.name for p in problems]
    # ragged families (e.g. uneven bundles): shape-bucket instead of
    # padding everything to the max (SURVEY §7 hard part 2)
    quantum = int(options.get("shape_bucket_quantum", 16))
    # the integer pattern is part of the shape key: same-(n, m)
    # scenarios with DIFFERENT is_int patterns cannot share one
    # ScenarioBatch (it requires one pattern) but bucket cleanly —
    # BucketedBatch subgroups by padded pattern anyway
    shapes = {(p.num_vars, p.num_rows, p.is_int.tobytes())
              for p in problems}
    bucketed = None
    # opt-in: bucketing trades the features needing a global A tensor
    # or a shared integer pattern (cut injection, integer diving,
    # device-const caching) for compact per-shape solves; certified
    # dual bounds work per bucket (_Edualbound_bucketed)
    if len(shapes) > 1 and options.get("shape_buckets", False):
        from .ir import BucketedBatch

        bucketed = BucketedBatch.from_problems(problems, quantum)
        if len(bucketed.buckets) == 1:
            bucketed = None     # one bucket = plain padding; keep the
                                # full-featured ScenarioBatch surface
    if bucketed is not None:
        batch = bucketed
        global_toc(
            "shape-bucketed ragged family: "
            f"{[(int(i.size), s.num_rows, s.num_vars) for i, s in bucketed.buckets]}",
            verbose)
    else:
        batch = ScenarioBatch.from_problems(problems)
    return batch, bundling, names


def make_admm_settings(options, bundling=False) -> ADMMSettings:
    """``solver_options`` -> :class:`ADMMSettings`, shared by
    :class:`SPBase` and the serving canonicalizer (whose family keys must
    embed EXACTLY the settings the wheel will run, or a warm bind could
    serve a differently-compiled program)."""
    so = dict(options.get("solver_options") or {})
    allowed = {f.name for f in ADMMSettings.__dataclass_fields__.values()}
    # bundles are fewer but larger/harder subproblems; spend more solver
    # budget per problem unless the user pinned it (same trade as giving
    # the external solver more time per bundle EF in the reference)
    if bundling:
        so.setdefault("max_iter", 4000)
        so.setdefault("restarts", 6)
    return ADMMSettings(**{k: v for k, v in so.items() if k in allowed})


class SPBase:
    """Base class for scenario-programming objects.

    Args:
      options: dict of options (reference option names honored:
        ``defaultPHrho``, ``convthresh``, ``PHIterLimit``, ``verbose``,
        ``display_progress``, ``solver_options`` ...).
      all_scenario_names: list of scenario names.
      scenario_creator: callable(name, **kwargs) -> ScenarioProblem
        (the IR analogue of the reference's Pyomo scenario_creator).
      scenario_creator_kwargs: kwargs passed through.
      mesh: optional jax Mesh for sharded operation (None => single device).
      scenario_axis: mesh axis name holding the scenario shard.
    """

    def __init__(
        self,
        options,
        all_scenario_names,
        scenario_creator,
        scenario_creator_kwargs=None,
        all_nodenames=None,
        mesh=None,
        scenario_axis="scen",
        variable_probability=None,
        scenario_denouement=None,
    ):
        self.options = dict(options or {})
        self.all_scenario_names = list(all_scenario_names)
        self.scenario_creator = scenario_creator
        self.scenario_creator_kwargs = dict(scenario_creator_kwargs or {})
        self.mesh = mesh
        self.scenario_axis = scenario_axis
        self.verbose = self.options.get("verbose", False)
        # called per scenario after a run completes (spbase.py scenario
        # denouement protocol); signature (rank, scenario_name, scenario)
        self.scenario_denouement = scenario_denouement
        self.spcomm = None  # attached by an SPCommunicator when in a wheel

        # ---- canonical ingest (options["canonical_model"]) ------------------
        # The serving path (tpusppy/service/, doc/serving.md): a request
        # was already ingested/canonicalized ONCE into batched arrays by
        # service.canonical.ingest — every cylinder binds that object
        # instead of re-running model ingest.  Shared like a cache hit:
        # in-place writers must call _ensure_private_batch first.
        cm = self.options.get("canonical_model")
        if cm is not None:
            self.batch = cm.batch
            self.bundling = cm.bundling
            self.all_scenario_names = list(cm.names)
            self.tree = self.batch.tree
            self._batch_shared = True
            self.nid_sk = self.tree.nid_sk()
            self.admm_settings = self._make_admm_settings()
            return

        # ---- batch cache (options["batch_cache"]) ---------------------------
        # Every cylinder of a wheel builds the SAME family: at reference
        # scale (S=1000 WECC-240) one build costs minutes of the single host
        # core, and a 5-cylinder wheel pays it five times BEFORE the hub
        # loop starts — a third of the certification budget.  Identical
        # (creator, names, kwargs, bundling) requests share one object.
        # Normal solve paths only READ the batch (fixing copies bounds,
        # ``augment`` is functional); the known in-place writers (Fixer,
        # cross-scenario cuts, sample trees) call ``_ensure_private_batch``
        # first, which copies a shared batch before the write.
        cache_key = None
        self._batch_shared = False
        if self.options.get("batch_cache"):
            cache_key = (
                # the creator OBJECT, not its name: distinct instances'
                # bound methods share a qualname but build different
                # families (the key also keeps the object alive, so id
                # reuse can't alias)
                scenario_creator,
                tuple(self.all_scenario_names),
                _kwargs_key(self.scenario_creator_kwargs),
                int(self.options.get("bundles_per_rank", 0) or 0),
                int(self.options.get("shape_bucket_quantum", 16)),
                bool(self.options.get("shape_buckets", False)),
            )
            hit = _BATCH_CACHE.get(cache_key)
            if hit is not None:
                self.batch, self.bundling = hit
                self._batch_shared = True
                if self.bundling:
                    self.all_scenario_names = list(self.batch.names)
                self.tree = self.batch.tree
                global_toc(
                    f"Scenario batch from cache: "
                    f"{self.batch.num_scenarios} scenarios", self.verbose)
                self.nid_sk = self.tree.nid_sk()
                self.admm_settings = self._make_admm_settings()
                return

        # the ingest itself now lives in the free function (the serving
        # canonicalizer runs the SAME code without an opt object)
        self.batch, self.bundling, self.all_scenario_names = build_batch(
            self.options, self.all_scenario_names, scenario_creator,
            self.scenario_creator_kwargs, verbose=self.verbose)
        self.tree = self.batch.tree
        global_toc(
            f"Built scenario batch: {self.batch.num_scenarios} scenarios, "
            f"{self.batch.num_vars} vars, {self.batch.num_rows} rows, "
            f"{self.tree.num_nonants} nonants, {self.tree.num_stages} stages",
            self.verbose,
        )

        if cache_key is not None:
            _BATCH_CACHE[cache_key] = (self.batch, self.bundling)
            self._batch_shared = True

        # Node-grouping arrays (replace per-node comm.Split, spbase.py:333-375):
        # nid_sk[s, k] = node-id owning nonant slot k in scenario s.
        self.nid_sk = self.tree.nid_sk()

        self.admm_settings = self._make_admm_settings()

    def _ensure_private_batch(self):
        """In-place batch writers (Fixer, cross-scenario cut slots, sample
        trees) MUST call this before mutating batch arrays: a cache-shared
        batch (``options["batch_cache"]``) is copied first so siblings —
        e.g. the Lagrangian spoke whose outer bound must stay a bound on
        the UNrestricted problem — never see the writes."""
        if not getattr(self, "_batch_shared", False):
            return
        import dataclasses

        b = self.batch
        self.batch = dataclasses.replace(
            b, c=b.c.copy(), q2=b.q2.copy(), cl=b.cl.copy(),
            cu=b.cu.copy(), lb=b.lb.copy(), ub=b.ub.copy())
        self.tree = self.batch.tree
        self._batch_shared = False

    # ---- options ------------------------------------------------------------
    def _make_admm_settings(self) -> ADMMSettings:
        return make_admm_settings(self.options,
                                  getattr(self, "bundling", False))

    def _options_check(self, required, options=None):
        """Hard check for required options (spbase.py:524-531)."""
        options = self.options if options is None else options
        missing = [k for k in required if k not in options]
        if missing:
            raise RuntimeError(f"Missing required options: {missing}")

    @property
    def is_minimizing(self):
        return True  # the IR is always stated as minimization (negate to max)

    # ---- probabilities ------------------------------------------------------
    @property
    def probs(self) -> np.ndarray:
        return self.tree.scen_prob

    @property
    def nonant_length(self) -> int:
        return self.tree.num_nonants

    def nonants_of(self, x) -> np.ndarray:
        """Gather packed nonant vector(s) (…, K) from full x (…, n)."""
        return np.asarray(x)[..., self.tree.nonant_indices]

    @property
    def nonant_var_names(self) -> list:
        """Names of the packed nonant slots (for checkpoint files interchange-
        able with reference wxbarutils CSVs); slot indices when unnamed."""
        vn = self.batch.var_names
        if vn is None:
            return [str(k) for k in range(self.nonant_length)]
        return [vn[i] for i in self.tree.nonant_indices]

    # ---- reporting ----------------------------------------------------------
    def report_var_values_at_rank0(self, x, max_rows=40):
        """Pretty table of nonant values (spbase.py:584-616)."""
        xn = self.nonants_of(x)
        print(f"{'scenario':>12} " + " ".join(
            f"nonant[{k}]" for k in range(min(self.nonant_length, 8))
        ))
        for s, name in enumerate(self.all_scenario_names[:max_rows]):
            vals = " ".join(f"{v:9.4f}" for v in xn[s][:8])
            print(f"{name:>12} {vals}")
