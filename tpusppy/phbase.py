"""PHBase: Progressive Hedging state and iteration.

TPU-native analogue of ``mpisppy/phbase.py:176-1050``.  PH state — duals W,
penalty rho, node averages xbar — are (S, K) arrays over the packed nonant
layout.  The two global reductions of the reference become tensor contractions:

* ``Compute_Xbar`` (phbase.py:27-107): per-tree-node probability-weighted means
  via a one-hot node-membership contraction (replacing one Allreduce per node on
  per-node communicators), sharding-ready (psum over the scenario mesh axis).
* ``convergence_diff`` (phbase.py:321-343): scaled L1 deviation from xbar.

The augmented objective (attach_PH_to_objective, phbase.py:617-699)
``obj += W_on * W.x + prox_on * (rho/2)(x^2 - 2 xbar x + xbar^2)`` never touches
a model: it is just a (q, q2) override for the batched ADMM solve, and the prox
term needs no linearization cuts (prox_approx.py) because the solver is a QP
solver natively.
"""

from __future__ import annotations

import numpy as np

from . import global_toc
from .obs import metrics as _metrics
from .obs import trace as _trace
from .spopt import SPOpt
from .extensions.extension import Extension


class PHBase(SPOpt):
    """PH state + iteration drivers (Iter0 / iterk_loop / post_loops)."""

    def __init__(self, *args, extensions=None, extension_kwargs=None,
                 ph_converger=None, rho_setter=None, **kwargs):
        super().__init__(*args, **kwargs)
        self._options_check(["defaultPHrho", "PHIterLimit"], self.options)
        K = self.nonant_length
        S = self.batch.num_scenarios

        self.W = np.zeros((S, K))
        self.xbars = np.zeros((S, K))       # per-scenario view of node xbar
        self.xsqbars = np.zeros((S, K))
        self.rho = self._initial_rho(rho_setter)
        self.W_on = True
        self.prox_on = True
        self.conv = None
        self._iter = 0
        self.best_bound = -np.inf if self.is_minimizing else np.inf

        ext_cls = extensions if extensions is not None else Extension
        self.extobject = ext_cls(self, **(extension_kwargs or {})) \
            if extension_kwargs else ext_cls(self)
        self.ph_converger = ph_converger(self) if ph_converger else None
        self.spcomm = None

        # Precompute node-membership one-hot for xbar contraction: (S, K) -> N
        self._onehot = self.tree.onehot_sk_n()

    def _initial_rho(self, rho_setter):
        K = self.nonant_length
        S = self.batch.num_scenarios
        rho = np.full((S, K), float(self.options["defaultPHrho"]))
        if rho_setter is not None:
            # rho_setter(batch) -> (K,) or (S, K) array (cf. phbase.py rho_setter)
            r = np.asarray(rho_setter(self.batch), dtype=float)
            rho = np.broadcast_to(r, (S, K)).copy() if r.ndim == 1 else r.copy()
        return rho

    # ---- reductions ---------------------------------------------------------
    def _nonants_cached(self) -> np.ndarray:
        """(S, K) nonants of the CURRENT ``local_x``, gathered once per
        solve: Compute_Xbar / Update_W / convergence_diff and the hub's
        nonant payload all read the same snapshot instead of re-gathering
        4x per iteration (part of the single-fetch wheel-iteration
        discipline, doc/pipeline.md).  Keyed on the ``local_x`` object
        identity — every solve path ASSIGNS a fresh array; paths that
        mutate rows in place (APH's fractional dispatch) drop the cache
        explicitly."""
        if getattr(self, "_xk_src", None) is not self.local_x:
            self._xk = self.nonants_of(self.local_x)
            self._xk_src = self.local_x
        return self._xk

    @property
    def sync_version(self):
        """Monotone token of the hub-visible PH state (W / nonants /
        iteration).  The hub's mailbox writes skip when it has not
        advanced — the linger loop polls sync several times a second, and
        re-Putting identical payloads would bump write-ids and force every
        spoke to recompute on data it already acted on."""
        return (self._iter, getattr(self, "_state_version", 0))

    def _bump_state_version(self):
        self._state_version = getattr(self, "_state_version", 0) + 1

    def _node_avgs(self, xk):
        """(xbars, xsqbars) as scenario-indexed (S, K): per-node
        probability-weighted E[x] and E[x^2] gathered back through
        ``nid_sk`` (the Compute_Xbar core)."""
        p = self.probs[:, None]                                  # (S, 1)
        num = np.einsum("skn,sk->nk", self._onehot, p * xk)      # (N, K)
        sqnum = np.einsum("skn,sk->nk", self._onehot, p * xk * xk)
        den = np.einsum("skn,sk->nk", self._onehot, np.broadcast_to(p, xk.shape))
        den = np.maximum(den, 1e-300)
        kidx = np.arange(self.nonant_length)[None, :]
        return ((num / den)[self.nid_sk, kidx],
                (sqnum / den)[self.nid_sk, kidx])

    def Compute_Xbar(self, verbose=False):
        """Per-node weighted averages of nonants (phbase.py:27-107)."""
        xk = self._nonants_cached()                              # (S, K)
        self.xbars, self.xsqbars = self._node_avgs(xk)
        if verbose:
            global_toc(f"xbar[:8]={self.xbars[0][:8]}")

    def Update_W(self, verbose=False):
        """Dual update W += rho (x - xbar) (phbase.py:293-318)."""
        xk = self._nonants_cached()
        self.W = self.W + self.rho * (xk - self.xbars)
        self._bump_state_version()
        if verbose:
            global_toc(f"W[0][:8]={self.W[0][:8]}")

    def convergence_diff(self) -> float:
        """Scaled norm of x - xbar (phbase.py:321-343)."""
        xk = self._nonants_cached()
        dev = np.abs(xk - self.xbars).mean(axis=1)
        return float(self.probs @ dev)

    # ---- augmented objective ------------------------------------------------
    def _augmented_q(self):
        """(q, q2) for the PH subproblem (attach_PH_to_objective)."""
        b = self.batch
        idx = self.tree.nonant_indices
        q = np.array(b.c, copy=True)
        if self.W_on:
            q[:, idx] += self.W
        if self.prox_on:
            q[:, idx] += -self.rho * self.xbars
        return q, self._augmented_q2()

    def _augmented_q2(self):
        """q2 alone — the Factors-signature input (:meth:`_solve_sig`
        never reads q).  Skips the W/xbars q assembly ``_augmented_q``
        pays, which matters on the megastep hot loop where this runs
        once per window as a pure staleness check."""
        q2 = np.array(self.batch.q2, copy=True)
        if self.prox_on:
            q2[:, self.tree.nonant_indices] += self.rho
        return q2

    def solve_ph_subproblems(self):
        self.extobject.pre_solve_loop()
        q, q2 = self._augmented_q()
        self.solve_loop(q=q, q2=q2)
        self.extobject.post_solve_loop()

    # ---- drivers ------------------------------------------------------------
    def Iter0(self) -> float:
        """Initial solves with W=prox off; returns the trivial bound
        (phbase.py:758-872)."""
        self.extobject.pre_iter0()
        self._iter = 0
        with _trace.span(None, "iter0"):
            self.solve_loop()  # plain objective
        feas = self.feas_prob()
        if feas < 1.0 - 1e-6:
            # residuals above feas_tol conflate two states: a truly
            # infeasible scenario (the reference's hard-quit case,
            # phbase.py:818-823) and a first-order-solver PLATEAU (large
            # coupled families park at ~5e-3 scaled primal regardless of
            # budget).  Disambiguate host-exactly on a bounded sample of
            # the worst offenders: if every checked scenario IS feasible,
            # this is plateau, not infeasibility — proceed.
            from .solvers import scipy_backend

            tol = max(self.options.get("feas_tol", 1e-3),
                      10.0 * self.admm_settings.eps_rel)
            pri0 = np.asarray(self.pri_res)
            # ~(pri <= tol), NOT (pri > tol): NaN residuals (diverged
            # solves) must land in the check set, not slip past it
            bad = np.flatnonzero(~(pri0 <= tol))
            key = np.where(np.isnan(pri0[bad]), np.inf, pri0[bad])
            worst = bad[np.argsort(-key)][:16]
            b = self.batch
            truly_bad = []
            for s in worst:
                r = scipy_backend.solve_lp(
                    np.zeros(b.num_vars), b.A[s], b.cl[s], b.cu[s],
                    b.lb[s], b.ub[s])
                if not r.feasible:
                    truly_bad.append(int(s))
            if truly_bad:
                raise RuntimeError(
                    f"Infeasibility detected at iter0; feasible mass "
                    f"{feas:.4f}, host-verified infeasible scenarios "
                    f"{truly_bad} (cf. phbase.py:818-823 hard quit)"
                )
            checked_all = len(worst) == bad.size
            global_toc(
                f"iter0: {bad.size} scenario(s) above feas_tol are a "
                "solver plateau (host feasibility check passed on "
                + ("ALL of them" if checked_all
                   else f"the {len(worst)} worst — a sampled check")
                + ") — continuing", True)
        # CERTIFIED trivial bound: weak duality, not the primal objective.
        # Ebound() of an inexact iter0 solve OVERESTIMATES the wait-and-see
        # bound by the solver residual — at reference scale (S=1000 WECC,
        # solves parked at plateau) by double digits, which crossed the
        # bounds and FALSELY certified a negative gap in the r5 full-scale
        # wheel.  With converged solves the two coincide to tolerance.
        self.trivial_bound = self.Edualbound()
        eb = self.Ebound()
        if np.isfinite(eb) and abs(eb - self.trivial_bound) > \
                1e-3 * max(1.0, abs(eb)):
            global_toc(
                f"iter0: certified trivial bound {self.trivial_bound:.4e} "
                f"(primal objective {eb:.4e} is solver-tolerance-loose "
                "and NOT used as a bound)", True)
        self.best_bound = self.trivial_bound
        self.Compute_Xbar()
        self.Update_W()
        self._apply_resume()
        self.conv = self.convergence_diff()
        self.extobject.post_iter0()
        if self.spcomm is not None:
            self.spcomm.sync()
            self.extobject.post_iter0_after_sync()
        global_toc(
            f"Iter0 trivial bound {self.trivial_bound:.4f} conv {self.conv:.3e}",
            self.options.get("display_progress", False),
        )
        # serving SLO seam (doc/serving.md): the solve server records
        # time-to-iter-1 per request here — the warm-path acceptance
        # metric (a warm family reaches this point without compiling)
        cb = self.options.get("on_iter0_done")
        if cb is not None:
            try:
                cb()
            except Exception:   # a telemetry hook must never cost the run
                pass
        return self.trivial_bound

    def _apply_resume(self):
        """Re-seat checkpointed PH state, when a resume was requested.

        Runs at the END of Iter0 (the WXBarReader seam): the plain warm-up
        solve has populated warm states and the trivial bound, and the
        (W, xbars, rho) it derived are REPLACED wholesale by the
        checkpoint's, so the first iterk solve reproduces the augmented
        objective of the iteration after the snapshot.  Also sets
        ``_iter_base`` so ``PHIterLimit`` keeps meaning TOTAL iterations
        across restarts (``iterk_loop`` starts past the base)."""
        ck = getattr(self, "_resume_ckpt", None)
        if ck is None:
            return
        from .resilience import checkpoint as _ckpt

        _ckpt.restore_ph(self, ck)
        self._resume_ckpt = None

    # ---- wheel megakernel (N iterations per dispatch) -----------------------
    def _megastep_request(self) -> int:
        """Resolved megakernel width N (>= 2) when the device-resident
        wheel megastep may drive this hub's iterations, else 0 (legacy
        per-iteration dispatch).

        Gates (each falls back to legacy, never errors): the
        ``ADMMSettings.megastep`` knob (1 = forced legacy); homogeneous
        batch; trivial extensions and no ph_converger (their per-
        iteration callouts cannot run inside the scan); no nonant fixing
        overlay; W/prox on (the iterk posture); a frozen-amortized
        refresh cadence; and shapes that fit ONE dispatch (megasteps
        never segment).  N is the autotuner's banked verdict when one
        exists (:func:`tpusppy.tune.megastep_verdict`), else the refresh
        window (``refresh_every - 1``: one legacy refresh dispatch + one
        megastep per cadence block), clamped by the watchdog cap
        (:func:`~tpusppy.solvers.segmented.megastep_cap` — a megastep is
        N iterations of work against the worker's per-execution kill).
        """
        from .extensions.extension import Extension
        from .ir import BucketedBatch
        from .solvers import segmented
        from .solvers.sparse import SparseA

        st = self.admm_settings
        req = int(getattr(st, "megastep", 0) or 0)
        if req == 1:
            return 0
        b = self.batch
        if type(self.extobject) is not Extension \
                or self.ph_converger is not None:
            return 0
        if self._fixed_lb is not None or self._fixed_ub is not None:
            return 0
        if not (self.W_on and self.prox_on):
            return 0
        refresh_every = self._refresh_every()
        if refresh_every <= 2:
            return 0
        if isinstance(b, BucketedBatch):
            # bucketed megakernel: EVERY bucket must fit one dispatch, and
            # the watchdog cap sums the buckets' per-iteration worst cases
            # (one scan step sweeps them all) — megastep_cap_multi
            from .spopt import bucket_shared

            shapes = []
            for idx, sub in b.buckets:
                fb = 1 if bucket_shared(sub) else idx.size
                _, seg_f = segmented.dispatch_segments(
                    idx.size, sub.num_vars, sub.num_rows, st,
                    factor_batch=fb)
                if seg_f < st.max_iter:
                    return 0
                shapes.append((idx.size, sub.num_vars, sub.num_rows, fb))
            cap = self._megastep_cap_with_bounds(
                lambda bp: segmented.megastep_cap_multi(
                    shapes, st, bound_pass=bp))
            if req > 1:
                n_sel = req
            else:
                from . import tune

                n_sel = tune.megastep_verdict(
                    tuple(s[:3] for s in shapes), settings=st) \
                    or (refresh_every - 1)
            n_sel = min(n_sel, refresh_every - 1, cap)
            return n_sel if n_sel >= 2 else 0
        S, n, m = b.num_scenarios, b.num_vars, b.num_rows
        shared = getattr(b, "A_shared", None) is not None
        sf = (segmented.SPARSE_DISPATCH_FACTOR if isinstance(
            self._device_consts(st.jdtype())[0], SparseA) else 1.0)
        fb = 1 if shared else S
        _, seg_f = segmented.dispatch_segments(S, n, m, st, factor_batch=fb,
                                               sparse_factor=sf)
        if seg_f < st.max_iter:
            return 0          # segmentation regime: the step pair owns it
        cap = self._megastep_cap_with_bounds(
            lambda bp: segmented.megastep_cap(S, n, m, st, factor_batch=fb,
                                              sparse_factor=sf,
                                              bound_pass=bp))
        if req > 1:
            n_sel = req
        else:
            from . import tune

            n_sel = tune.megastep_verdict(S, n, m, settings=st) \
                or (refresh_every - 1)
        n_sel = min(n_sel, refresh_every - 1, cap)
        return n_sel if n_sel >= 2 else 0

    def _mega_age(self) -> int:
        """Frozen-factor age for the megastep readiness gate: the
        homogeneous slot's age, or the OLDEST bucket slot's (every bucket
        sweeps in one scan step, so the stalest factors gate the window)."""
        from .ir import BucketedBatch

        if isinstance(self.batch, BucketedBatch):
            slots = getattr(self, "_bucket_slots", None) or []
            if not slots:
                return 10 ** 9
            return max(s.get("age", 0) for s in slots)
        return self._factors_age

    def _mega_slots_ready(self, refresh_every) -> bool:
        """Frozen-amortization slots valid for a megastep window: factors
        + warm present, not aged out, and the validity signature matches
        (per bucket, for a bucketed batch)."""
        from .ir import BucketedBatch

        b = self.batch
        if isinstance(b, BucketedBatch):
            slots = getattr(self, "_bucket_slots", None)
            if not slots or len(slots) != len(b.buckets):
                return False
            q2_full = self._augmented_q2()
            lb = np.asarray(b.lb)
            ub = np.asarray(b.ub)
            for (idx, sub), slot in zip(b.buckets, slots):
                if slot.get("warm") is None or slot.get("factors") is None:
                    return False
                if slot.get("age", 0) >= refresh_every:
                    return False
                n = sub.num_vars
                if self._solve_sig(q2_full[idx, :n], lb[idx, :n],
                                   ub[idx, :n]) != slot.get("sig"):
                    return False
            return True
        if self._factors is None or self._warm is None:
            return False
        if self._factors_age >= refresh_every:
            return False
        return self._solve_sig(self._augmented_q2(), b.lb, b.ub) \
            == self._factors_sig

    def _megastep_dispatch(self, n_req, n_live, convthresh,
                           bound_live=None):
        """Route one window to the homogeneous or bucketed megakernel.
        ``bound_live``: the in-wheel certification flag for THIS window
        (None = bound-pass variant not armed — the legacy program)."""
        from .ir import BucketedBatch

        if isinstance(self.batch, BucketedBatch):
            return self._megastep_solve_bucketed(
                n_req, n_live, convthresh, self.W, self.xbars, self.rho,
                bound_live=bound_live)
        return self._megastep_solve(n_req, n_live, convthresh,
                                    self.W, self.xbars, self.rho,
                                    bound_live=bound_live)

    # ---- in-wheel certification (doc/pipeline.md) ---------------------------
    def _megastep_cap_with_bounds(self, cap_fn):
        """Watchdog cap with the in-wheel bound-pass reservation — and
        the reservation must never KILL the megastep: a family that
        barely fits (plain cap 2, reserved cap < 2) would otherwise
        silently lose both the megastep AND the bounds.  There, in-wheel
        certification is disabled for this family loudly (the bound
        spokes remain the certification path) and the plain cap is
        kept."""
        if not self._inwheel_on():
            return cap_fn(False)
        # the reservation scales with the pass's evaluation count — the
        # batched integer sweep reserves C candidates + 1 re-solve
        cap = cap_fn(self._inwheel_pass_evals())
        if cap >= 2:
            return cap
        cap_plain = cap_fn(False)
        if cap_plain >= 2 and not getattr(self, "_inwheel_cap_declined",
                                          False):
            self._inwheel_cap_declined = True
            global_toc(
                "in_wheel_bounds: the bound-pass watchdog reservation "
                "would disable the megastep for this shape — in-wheel "
                "certification disabled (bound spokes remain the "
                "certification path)", True)
        return cap_plain

    def _inwheel_on(self) -> bool:
        """Whether megastep windows run the fused bound pass — the
        ``in_wheel_bounds`` option, gated to minimization (the
        weak-duality outer assembly and the xhat feasibility gate are
        minimization-convention, like the bound spokes they replace)."""
        if not self.options.get("in_wheel_bounds"):
            return False
        if getattr(self, "_inwheel_cap_declined", False):
            return False    # the bound-pass reservation would kill the
            # megastep for this shape (_megastep_cap_with_bounds)
        if not self.is_minimizing:
            if not getattr(self, "_inwheel_min_warned", False):
                self._inwheel_min_warned = True
                global_toc(
                    "in_wheel_bounds: maximization families are not "
                    "supported (bound spokes remain the certification "
                    "path) — disabled", True)
            return False
        return True

    def _inwheel_inner_ok(self) -> bool:
        """Whether the in-wheel INNER bound may be consumed: every
        integer column must be a nonant slot (the device candidate
        rounds those integral; leftover second-stage integers need the
        Xhat_Eval dive/MILP machinery, which cannot run in-scan — the
        xhat spokes keep that posture)."""
        ok = getattr(self, "_inwheel_inner_ok_cache", None)
        if ok is None:
            from .ir import BucketedBatch

            b = self.batch
            subs = ([sub for _, sub in b.buckets]
                    if isinstance(b, BucketedBatch) else [b])
            ok = True
            for sub in subs:
                free = np.ones(sub.num_vars, dtype=bool)
                free[sub.tree.nonant_indices] = False
                if np.asarray(sub.is_int, bool)[free].any():
                    ok = False
                    break
            self._inwheel_inner_ok_cache = ok
            if not ok:
                global_toc(
                    "in_wheel_bounds: second-stage integer columns — the "
                    "in-wheel INNER bound is not certified (outer-only "
                    "mode; run xhat spokes to evaluate incumbents, or "
                    "the wheel cannot close the gap)", True)
        return ok

    def _inwheel_every(self) -> int:
        """Bound-pass cadence in WINDOWS: ``in_wheel_bound_every`` when
        set, else the autotuner's banked verdict (the ``integer`` kind's
        cadence for integer-sweep families, else the ``bound_cadence``
        kind), else every window."""
        every = self.options.get("in_wheel_bound_every")
        if every:
            return max(1, int(every))
        from . import tune

        if self._inwheel_int_sweep_on():
            vi = tune.integer_verdict(self._mega_shape_key(),
                                      settings=self.admm_settings)
            if vi is not None:
                return max(1, int(vi.every))
        v = tune.bound_cadence_verdict(self._mega_shape_key(),
                                       settings=self.admm_settings)
        return max(1, int(v)) if v else 1

    def _consume_inwheel_bounds(self, meas):
        """Install one window's fused bound evidence through the typed
        hub updates (``OuterBoundUpdate``/``InnerBoundUpdate``, source
        char ``'M'`` — megastep) so ``compute_gaps`` termination and the
        gap-vs-wall trace see in-wheel bounds exactly like spoke bounds;
        tracked on the opt too for hub-less runs.  The inner bound is
        offered only when the frozen evaluation was feasible on the
        whole batch (the ``Xhat_Eval`` all-scenarios gate)."""
        if not meas.get("bound_computed"):
            return
        c = self.spcomm
        ob = float(meas["bound_outer"])
        if np.isfinite(ob):
            if ob > getattr(self, "inwheel_outer_bound", -np.inf):
                self.inwheel_outer_bound = ob
            if ob > self.best_bound:
                self.best_bound = ob
            if c is not None and hasattr(c, "OuterBoundUpdate"):
                c.OuterBoundUpdate(ob, char='M')
        # integer-sweep evidence (doc/integer.md): candidate/fixing
        # counters feed the flight recorder and the bench's integer
        # segment — feasible_hits > 0 is the "device sweep supplies
        # incumbents" acceptance signal
        if "int_feas_cands" in meas:
            from .ir import BucketedBatch
            from .solvers import integer as integer_solvers

            th = self._inwheel_int_thresholds() or ()
            # the bucketed kernel evaluates the ladder WITHOUT the slams
            # (nonanticipativity — doc/integer.md); count what actually
            # ran, matching _inwheel_pass_evals' billing arithmetic
            n_cand = len(th) + (
                0 if isinstance(self.batch, BucketedBatch)
                else integer_solvers.N_SLAM)
            _metrics.inc("integer.candidates", n_cand)
            _metrics.inc("integer.feasible_hits",
                         int(meas["int_feas_cands"]))
            _metrics.inc("integer.rcfix_slots",
                         int(meas["int_rcfix_slots"]))
            self._int_best_idx = int(meas["int_best_idx"])
        # the all-scenarios rule with a DTYPE-AWARE slack (single-sourced
        # in solvers.integer.feas_slack with the device argmin's gate):
        # the device computes the mass as probs @ mask in the settings
        # dtype, and an all-feasible f32 sum over S non-representable
        # probabilities (0.1) lands ~S*eps below 1.0 — a bare 1e-9 gate
        # would reject every feasible window on the float32 TPU posture
        from .solvers.integer import feas_slack as _feas_slack

        slack = _feas_slack(self.batch.num_scenarios,
                            self.admm_settings.jdtype())
        feasible = meas["bound_inner_feas"] >= 1.0 - slack
        if feasible and self._inwheel_inner_ok():
            self._offer_inwheel_inner(float(meas["bound_inner_obj"]))
        elif feasible and "int_best_idx" in meas:
            # second-stage-integer families (sizes): the device eval is a
            # RELAXATION of the true second-stage cost — certify the
            # sweep's best candidate by per-scenario host MIPs instead
            self._maybe_integer_inner_mip(int(meas["int_best_idx"]))
        elif not feasible:
            _metrics.inc("megastep.bound_pass_infeasible")
            if "int_best_idx" in meas and not self._inwheel_inner_ok():
                # gate miss on a second-stage-integer family: the LP
                # rescue cannot certify (relaxed second stage) — the MIP
                # escalation leg is the rescue
                self._maybe_integer_inner_mip(int(meas["int_best_idx"]))
            else:
                self._maybe_inwheel_rescue()
        self._maybe_integer_escalation()

    def _offer_inwheel_inner(self, ib: float, char: str = 'M'):
        """Track + typed-install one certified in-wheel incumbent value
        (source char ``'M'`` — megastep; ``'I'`` — integer host
        escalation)."""
        if not np.isfinite(ib):
            return
        if ib < getattr(self, "inwheel_inner_bound", np.inf):
            self.inwheel_inner_bound = ib
        c = self.spcomm
        if c is not None and hasattr(c, "InnerBoundUpdate"):
            c.InnerBoundUpdate(ib, char=char)

    def _maybe_inwheel_rescue(self):
        """Cadence gate in front of :meth:`_inwheel_host_rescue`: fire on
        the first feasibility-gate miss, then every
        ``in_wheel_rescue_every``-th miss (default 4 — a rescue is S host
        LPs, so it must not run every window on big-S wheels).  A rescue
        that DECLINES (the candidate is genuinely infeasible — the
        iter-1 consensus usually is) retries with a growing backoff
        (next miss, then +2, ... capped at the cadence) instead of
        spending a full cadence slot: the earliest windows fail
        together, and one early decline must not starve the wheel of
        its first certified incumbent for ``every`` more windows.
        ``in_wheel_host_rescue=False`` disables."""
        if not self.options.get("in_wheel_host_rescue", True):
            return
        if not self._inwheel_inner_ok():
            return
        every = max(1, int(self.options.get("in_wheel_rescue_every", 4)))
        miss = getattr(self, "_inwheel_gate_misses", 0)
        self._inwheel_gate_misses = miss + 1
        if miss < getattr(self, "_inwheel_next_rescue", 0):
            return
        ib = self._inwheel_host_rescue()
        if ib is None:
            declines = getattr(self, "_inwheel_rescue_declines", 0) + 1
            self._inwheel_rescue_declines = declines
            self._inwheel_next_rescue = miss + min(declines, every)
        else:
            self._inwheel_next_rescue = miss + every
            self._offer_inwheel_inner(ib)

    def _inwheel_host_rescue(self):
        """Host-EXACT inner-bound rescue — the straggler-rescue
        philosophy applied to the certification path.  Stiff families
        (UC's pmin/ramp coupling at fixed commitments) stall batched
        ADMM on the clamped evaluation even at refresh grade, so the
        fused pass's ``Xhat_Eval`` gate keeps declining; here the SAME
        candidate (the single-sourced ``xbar_candidate`` rule: rounded
        at the in-wheel threshold, clipped to the nonant box) is
        evaluated by per-scenario host solves — an LP, or the exact host
        QP when the scenario carries a quadratic objective (the
        straggler rescue's own split; the LP-only HiGHS wrapper raises
        on q2) — so the expected objective is a certified incumbent.
        Integer nonants are FIXED at their rounded values and
        :meth:`_inwheel_inner_ok` guarantees no other integer columns,
        so the value is the true candidate value, not a relaxation.
        Zero spoke threads, zero device programs.  Returns the bound, or
        None when any scenario is genuinely infeasible at the candidate
        — or when the host solver errors: a rescue failure must decline,
        never kill the wheel."""
        from .cylinders.xhatxbar_bounder import clamp_candidate
        from .ir import BucketedBatch
        from .solvers import scipy_backend

        if getattr(self, "_host_state_stale", False):
            self._sync_host_state()
        _metrics.inc("megastep.bound_rescues")
        thr = self._inwheel_threshold()
        b = self.batch
        xbars = np.asarray(self.xbars, dtype=float)
        eval_clamped = self._inwheel_eval_candidate_host
        try:
            if self._inwheel_int_sweep_on():
                # the batched integer posture: sweep the SAME rounding
                # ladder the device evaluates, device-preferred order
                # (its best index first, then the SLAM-up slam — the
                # most conservative commit, usually the first feasible
                # on under-converged consensus), first feasible wins —
                # the host leg of the best-of-C recovery
                from .solvers import integer as integer_solvers

                th = self._inwheel_int_thresholds() or ()
                cands = integer_solvers.host_candidates(self, th)
                order = list(range(len(cands)))
                slam_up = len(th)      # first slam after the ladder
                pref = [min(getattr(self, "_int_best_idx", 0),
                            len(cands) - 1), slam_up]
                order = list(dict.fromkeys(pref + order))
                for ci in order:
                    total = eval_clamped(np.asarray(cands[ci], float))
                    if total is not None:
                        # a host-CERTIFIED sweep candidate: the device
                        # ladder supplied the incumbent, host LPs
                        # certified it (doc/integer.md counter contract)
                        _metrics.inc("integer.feasible_hits")
                        return total
                return None
            # legacy single-candidate path: the candidate rule applied
            # per part (bucketed batches carry is_int per bucket)
            cand = np.array(xbars, copy=True)
            parts = (b.buckets if isinstance(b, BucketedBatch)
                     else [(np.arange(b.num_scenarios), b)])
            for idx, sub in parts:
                rows = np.asarray(idx)
                cand[rows], _, _ = clamp_candidate(
                    sub, sub.tree.nonant_indices, xbars[rows], thr)
            return eval_clamped(cand)
        except Exception as e:     # a failed rescue declines, loudly
            global_toc(f"in-wheel host rescue failed ({e!r}) — declined",
                       True)
            return None

    def _inwheel_eval_candidate_host(self, cand_sk):
        """Expected objective of ONE fixed candidate via per-scenario
        host solves — the host-EXACT certification leg shared by the
        rescue and the escalation heuristics (None = any scenario
        infeasible).  LP scenarios through HiGHS, quadratic ones through
        the exact host QP (the straggler rescue's split)."""
        from .ir import BucketedBatch
        from .solvers import scipy_backend

        b = self.batch
        probs = np.asarray(self.probs, dtype=float)
        cand_sk = np.asarray(cand_sk, dtype=float)
        total = 0.0
        parts = (b.buckets if isinstance(b, BucketedBatch)
                 else [(np.arange(b.num_scenarios), b)])
        for idx, sub in parts:
            rows = np.asarray(idx)
            lb = np.array(sub.lb, copy=True)
            ub = np.array(sub.ub, copy=True)
            nid = sub.tree.nonant_indices
            lb[:, nid] = cand_sk[rows]
            ub[:, nid] = cand_sk[rows]
            objs = []
            for s in range(sub.num_scenarios):
                q2s = np.asarray(sub.q2[s])
                if q2s.any():
                    r = scipy_backend.solve_qp_with_duals(
                        sub.c[s], q2s, sub.A[s], sub.cl[s],
                        sub.cu[s], lb[s], ub[s], const=sub.const[s])
                else:
                    r = scipy_backend.solve_lp(
                        sub.c[s], sub.A[s], sub.cl[s], sub.cu[s],
                        lb[s], ub[s], const=sub.const[s])
                objs.append(r.obj)
            objs = np.asarray(objs, dtype=float)
            if not np.isfinite(objs).all():
                return None
            total += float(probs[rows] @ objs)
        return total

    # ---- integer host escalation tier (doc/integer.md) ----------------------
    def _integer_budget(self):
        """The wheel's shared :class:`~tpusppy.solvers.integer.
        EscalationBudget` (lazily built; ``integer_escalation_budget_s``
        option, default 30 host-seconds): every host escalation — the
        gap-ranked MILP lift AND the candidate MIP certification — draws
        from this one pool, so the host tail is bounded per wheel."""
        b = getattr(self, "_int_budget", None)
        if b is None:
            from .solvers.integer import EscalationBudget

            b = self._int_budget = EscalationBudget(
                float(self.options.get("integer_escalation_budget_s",
                                       30.0)))
        return b

    def _integer_escalation_on(self) -> bool:
        """Whether the gap-ranked host escalation tier is armed: the
        ``integer_escalation`` option (default on), in-wheel
        certification running, an integer homogeneous family (the MILP
        lift iterates ``batch.A[s]`` — bucketed batches have no global
        A tensor)."""
        if not self.options.get("integer_escalation", True):
            return False
        if not self._inwheel_on():
            return False
        from .ir import BucketedBatch

        b = self.batch
        if isinstance(b, BucketedBatch):
            return False
        return bool(np.asarray(b.is_int).any())

    def _integer_gap_target(self):
        """(rel_gap, abs_gap) certification targets the escalation tier
        aims for — the hub's when attached, else the opt options'."""
        opts = getattr(self.spcomm, "options", None) or {}
        return (opts.get("rel_gap", self.options.get("rel_gap")),
                opts.get("abs_gap", self.options.get("abs_gap")))

    def _integer_bounds_now(self):
        """(inner, outer) best-known bounds across the in-wheel tracking
        and the hub (when attached)."""
        ib = getattr(self, "inwheel_inner_bound", np.inf)
        ob = getattr(self, "inwheel_outer_bound", -np.inf)
        c = self.spcomm
        if c is not None:
            ib = min(ib, getattr(c, "BestInnerBound", np.inf))
            ob = max(ob, getattr(c, "BestOuterBound", -np.inf))
        return ib, ob

    def _maybe_integer_inner_mip(self, best_idx: int):
        """Certify the device sweep's best candidate by per-scenario
        host MIPs — the inner-bound escalation leg for families with
        SECOND-STAGE integers (the device evaluation relaxes those
        columns, so ``_inwheel_inner_ok`` rightly refuses it; fixing the
        nonants at the candidate and solving each scenario MIP exactly
        IS an incumbent).  Cadence-gated like the host rescue (S host
        MIPs must not run every window), budgeted from the shared
        escalation pool, installed under source char ``'I'``."""
        if not self.options.get("in_wheel_host_rescue", True):
            return
        if not self._integer_escalation_on():
            return
        every = max(1, int(self.options.get("in_wheel_rescue_every", 4)))
        cnt = getattr(self, "_int_mip_calls", 0)
        self._int_mip_calls = cnt + 1
        if cnt % every:
            return
        from .solvers import integer as integer_solvers

        budget = self._integer_budget()
        if budget.remaining <= 0.05:
            return
        try:
            th = self._inwheel_int_thresholds() or ()
            cands = integer_solvers.host_candidates(self, th)
            # device-preferred order, then the SLAM-up slam, then the
            # rest — one infeasible best-index candidate must not end
            # the round (the LP rescue's ladder-sweep discipline)
            bi = min(max(int(best_idx), 0), len(cands) - 1)
            order = list(dict.fromkeys(
                [bi, len(th)] + list(range(len(cands)))))
            ib = None
            for ci in order:
                if budget.remaining <= 0.05:
                    break
                ib = integer_solvers.escalate_inner(self, budget,
                                                    cands[ci])
                if ib is not None:
                    break
        except Exception as e:   # a failed escalation declines, loudly
            global_toc(f"integer inner escalation failed ({e!r}) — "
                       "declined", True)
            return
        if ib is not None:
            # a MIP-certified sweep candidate is a sweep-supplied
            # incumbent (the doc/integer.md counter contract)
            _metrics.inc("integer.feasible_hits")
            self._offer_inwheel_inner(ib, char='I')

    def _maybe_integer_escalation(self):
        """ONE gap-gated round of the gap-ranked host MILP escalation
        (doc/integer.md tier 3): when the wheel's certified gap still
        misses its target and integrality gap remains, spend a slice of
        the shared HiGHS budget lifting the per-scenario LP certificates
        with the LARGEST estimated remaining gap first, and install the
        lifted outer bound under source char ``'I'``.  Fires on the
        ``integer_escalation_every`` window cadence (default 4) once an
        incumbent exists; an exhausted budget leaves every untouched
        scenario on its LP certificate (budget-elastic by
        construction)."""
        if not self._integer_escalation_on():
            return
        budget = self._integer_budget()
        if budget.remaining <= 0.05:
            return
        ib, ob = self._integer_bounds_now()
        if not np.isfinite(ib):
            return          # no incumbent yet: nothing to close against
        rel, abs_ = self._integer_gap_target()
        gap = ib - ob
        relgap = (gap / (abs(ob) or 1.0)) if np.isfinite(ob) else np.inf
        hit = ((rel is not None and relgap <= float(rel))
               or (abs_ is not None and gap <= float(abs_)))
        if hit or (rel is None and abs_ is None):
            return          # already certified (or no target to chase)
        every = max(1, int(self.options.get("integer_escalation_every",
                                            4)))
        cnt = getattr(self, "_int_esc_calls", 0)
        self._int_esc_calls = cnt + 1
        if cnt % every:
            return
        from .solvers import integer as integer_solvers

        upper = None
        try:
            th = self._inwheel_int_thresholds()
            if th is not None:
                cands = integer_solvers.host_candidates(self, th)
                bi = min(getattr(self, "_int_best_idx", 0),
                         len(cands) - 1)
                u, ok = integer_solvers.candidate_upper_perscen(
                    self, cands[bi])
                upper = np.where(ok, u, np.inf)
        except Exception:
            upper = None    # ranking falls back to probability order
        try:
            ob2, X = integer_solvers.escalate_outer(
                self, budget,
                want_s=self.options.get("integer_escalation_slice_s"),
                upper_perscen=upper, want_x=True)
        except Exception as e:
            global_toc(f"integer outer escalation failed ({e!r}) — "
                       "declined", True)
            return
        if ob2 is None or not np.isfinite(ob2):
            return
        if ob2 > getattr(self, "inwheel_outer_bound", -np.inf):
            self.inwheel_outer_bound = ob2
        if ob2 > self.best_bound:
            self.best_bound = ob2
        c = self.spcomm
        if c is not None and hasattr(c, "OuterBoundUpdate"):
            c.OuterBoundUpdate(ob2, char='I')
        self._integer_lift_incumbents(X, budget)

    def _integer_lift_incumbents(self, X, budget):
        """Lagrangian-heuristic incumbent recovery from the MILP lift's
        per-scenario minimizers: when every scenario was lifted
        gap-closed, the rows' per-node consensus (rounded) and SLAM-up
        slam are natural integer candidates — the subproblem minima
        under a near-converged W nearly agree, so their consensus is
        usually feasible and far tighter than a relaxation-consensus
        rounding.  Certified host-exact (LPs, or per-scenario MIPs for
        second-stage-integer families), installed under ``'I'``."""
        if X is None or np.isnan(np.asarray(X)[:, 0]).any():
            return
        from .cylinders.xhatxbar_bounder import xbar_candidate
        from .extensions.xhatbase import slam_cache
        from .solvers import integer as integer_solvers

        try:
            nid = self.tree.nonant_indices
            xk = np.asarray(X, dtype=float)[:, nid]
            ints = integer_solvers.int_mask_rows(self)
            lo = np.asarray(self.batch.lb)[:, nid]
            hi = np.asarray(self.batch.ub)[:, nid]
            cands = [xbar_candidate(self, xk, threshold=0.5)]
            up = slam_cache(self, xk, how="max")
            cands.append(np.clip(
                np.where(ints, np.ceil(up - 1e-9), up), lo, hi))
            inner_ok = self._inwheel_inner_ok()
            best = None
            for cand in cands:
                if inner_ok:
                    if budget.remaining <= 0.05:
                        break
                    with budget.timed():
                        ib = self._inwheel_eval_candidate_host(cand)
                else:
                    ib = integer_solvers.escalate_inner(self, budget,
                                                        cand)
                if ib is not None and (best is None or ib < best):
                    best = ib
            # strongest host heuristic last: the restricted-EF dive on
            # the minimizers' agreement pattern (certified by
            # construction — any feasible restricted-EF solution is an
            # EF incumbent)
            ib = integer_solvers.restricted_ef_incumbent(self, X, budget)
            if ib is not None and (best is None or ib < best):
                best = ib
            if best is not None:
                _metrics.inc("integer.feasible_hits")
                self._offer_inwheel_inner(best, char='I')
        except Exception as e:
            global_toc(f"integer lift-incumbent recovery failed ({e!r}) "
                       "— declined", True)

    def _mega_shape_key(self):
        """The autotuner shape key: (S, n, m), or the tuple of per-bucket
        (S_b, n_b, m_b) for a bucketed batch (per-bucket verdict keys —
        an S=1000 verdict can never serve an S=10000 family)."""
        from .ir import BucketedBatch

        b = self.batch
        if isinstance(b, BucketedBatch):
            return tuple((idx.size, sub.num_vars, sub.num_rows)
                         for idx, sub in b.buckets)
        return (b.num_scenarios, b.num_vars, b.num_rows)

    def _megastep_window(self, k, max_iters, convthresh, n_req):
        """One megastep window starting at iteration ``k``: returns
        ``(executed, conv_hit)`` — ``executed == 0`` means the slot was
        not megastep-ready (stale/aged factors, a dirty previous
        measurement) and the caller must run a legacy iteration, which
        refreshes/rescues and restores readiness."""
        refresh_every = self._refresh_every()
        if not self._mega_slots_ready(refresh_every):
            return 0, False
        # previous measurement must be clean — the serial frozen path's
        # acceptance test; a dirty iterate routes through the legacy
        # iteration (adaptive refresh + straggler rescue)
        pri, dua = self.pri_res, self.dua_res
        if pri is None or dua is None:
            return 0, False
        _, tol_qp = self._straggler_tols()
        if not bool(np.all((pri <= tol_qp) & (dua <= tol_qp))):
            # mirror the in-scan acceptance's all-done escape: an
            # eps-converged batch is clean regardless of the residual
            # ladder, and a window accepted that way may carry
            # non-finite residuals on divergence-frozen scenarios —
            # without the escape one frozen scenario would disable the
            # megakernel for the rest of the run
            if not getattr(self, "_last_all_done", False):
                return 0, False
        n_live = min(n_req, refresh_every - self._mega_age(),
                     max_iters - k + 1)
        if n_live < 1:
            return 0, False
        # opt-in measured N (the tune.py megastep stage): the first
        # eligible window runs the three probe windows through the normal
        # machinery — real iterations, applied normally — and banks the
        # verdict (persistent via TPUSPPY_TUNE_CACHE) for SUBSEQUENT runs
        # of this shape; without the knob, auto-N stays cadence-derived
        if (self.options.get("megastep_autotune")
                and not getattr(self, "_mega_tuned", False)
                and n_live >= 10):
            self._mega_tuned = True
            from . import tune

            if tune.megastep_verdict(self._mega_shape_key(),
                                     settings=self.admm_settings) is None:
                prog = {"k": k, "executed": 0}

                def run_window(nl):
                    # a probe must never run past convergence: once the
                    # threshold fired, later windows do nothing (the
                    # serial protocol would have broken the loop)
                    if self.conv is not None and self.conv < convthresh:
                        return 0
                    # a rejected probe exhausts the factors (refresh_hit
                    # ages them out); a further timed window from the
                    # same state would deterministically re-reject — bail
                    # like the normal window's readiness gate does
                    if self._mega_age() >= refresh_every:
                        return 0
                    m = self._megastep_dispatch(n_req, nl, convthresh)
                    ex = m["executed"]
                    if ex:
                        self._apply_megastep_meas(prog["k"], m)
                        prog["k"] += ex
                        prog["executed"] += ex
                    return ex

                tune.autotune_megastep(
                    run_window, self._mega_shape_key(), n_cap=n_req,
                    settings=self.admm_settings)
                return prog["executed"], bool(self.conv < convthresh)
        bound_live = None
        if self._inwheel_on():
            wc = getattr(self, "_mega_window_count", 0)
            self._mega_window_count = wc + 1
            # opt-in measured integer stage (tune.py "integer" kind):
            # two real probe windows — one with the batched integer
            # sweep, one plain — measure the sweep's marginal cost, and
            # the banked (K, cadence) verdict serves this and later runs
            # of the shape.  A verdict can TRUNCATE the ladder, which is
            # a DIFFERENT compiled program: the megastep fn cache is
            # dropped so the next window rebuilds at the picked K.
            if (self._inwheel_int_sweep_on()
                    and self.options.get("in_wheel_int_autotune")
                    and not self.options.get("in_wheel_int_thresholds")
                    and not getattr(self, "_int_tuned", False)):
                self._int_tuned = True
                from . import tune

                if tune.integer_verdict(
                        self._mega_shape_key(),
                        settings=self.admm_settings) is None:
                    prog = {"k": k, "executed": 0}

                    def run_iwin(int_live):
                        if self.conv is not None \
                                and self.conv < convthresh:
                            return 0
                        nl = min(n_req,
                                 refresh_every - self._mega_age(),
                                 max_iters - prog["k"] + 1)
                        if nl < 1:
                            return 0
                        m = self._megastep_dispatch(
                            n_req, nl, convthresh,
                            bound_live=bool(int_live))
                        self._consume_inwheel_bounds(m)
                        ex = m["executed"]
                        if ex:
                            self._apply_megastep_meas(prog["k"], m)
                            prog["k"] += ex
                            prog["executed"] += ex
                        return ex

                    from .solvers.integer import DEFAULT_THRESHOLDS

                    tune.autotune_integer(
                        run_iwin, self._mega_shape_key(),
                        settings=self.admm_settings,
                        k_full=len(self._inwheel_int_thresholds()
                                   or DEFAULT_THRESHOLDS))
                    self._mega_fn_cache = {}
                    return prog["executed"], bool(self.conv < convthresh)
            # opt-in measured cadence (the tune.py bound-cadence stage):
            # two real probe windows — one with the fused bound pass, one
            # without — measure its marginal cost, and the banked verdict
            # (persistent via TPUSPPY_TUNE_CACHE) serves this and later
            # runs of the shape; probes are real iterations, applied
            # normally, so warmup work is never wasted
            if (self.options.get("in_wheel_bound_autotune")
                    and not self.options.get("in_wheel_bound_every")
                    and not getattr(self, "_bound_tuned", False)):
                self._bound_tuned = True
                from . import tune

                if tune.bound_cadence_verdict(
                        self._mega_shape_key(),
                        settings=self.admm_settings) is None:
                    prog = {"k": k, "executed": 0}

                    def run_bwin(bl):
                        if self.conv is not None and self.conv < convthresh:
                            return 0
                        nl = min(n_req, refresh_every - self._mega_age(),
                                 max_iters - prog["k"] + 1)
                        if nl < 1:
                            return 0
                        m = self._megastep_dispatch(n_req, nl, convthresh,
                                                    bound_live=bl)
                        # same contract as the main path: an executed==0
                        # (first-iterate-rejected) window's bound
                        # evidence still certifies the INCOMING state
                        self._consume_inwheel_bounds(m)
                        ex = m["executed"]
                        if ex:
                            self._apply_megastep_meas(prog["k"], m)
                            prog["k"] += ex
                            prog["executed"] += ex
                        return ex

                    tune.autotune_bound_cadence(
                        run_bwin, self._mega_shape_key(),
                        settings=self.admm_settings)
                    return prog["executed"], bool(self.conv < convthresh)
            bound_live = (wc % self._inwheel_every() == 0)
        meas = self._megastep_dispatch(n_req, n_live, convthresh,
                                       bound_live=bound_live)
        if bound_live is not None:
            # bound evidence is valid on whatever state the window ended
            # with — including an executed == 0 (first-iterate-rejected)
            # window, whose bounds certify the INCOMING state
            self._consume_inwheel_bounds(meas)
        executed = meas["executed"]
        if executed == 0:
            # the window's FIRST iterate failed the in-scan acceptance
            # test (discarded; _megastep_solve exhausted the factors age)
            # — the caller's legacy iteration refreshes, as serial would
            return 0, False
        self._apply_megastep_meas(k, meas)
        # a short window is NOT convergence when the in-scan acceptance
        # test ended it (refresh_hit): the loop continues through the
        # legacy refresh instead
        conv_hit = bool(self.conv < convthresh)
        return executed, conv_hit

    def _apply_megastep_meas(self, k, meas):
        """Install one megastep window's packed measurement as the host PH
        state (copies: the unpack returns views into one fetched vector).

        A LEAN measurement (device-resident posture, ``ph_device_state``)
        carries no x/W/xbars blocks: the (S, K)/(S, n) mirrors stay where
        they are and are marked STALE — :meth:`_sync_host_state` refreshes
        them with one explicit billed fetch at the next checkpoint/
        termination/refresh boundary.  The per-scenario residual
        diagnostics and the scalar stats install either way, so the
        readiness gates and the convergence test never read stale data."""
        executed = meas["executed"]
        if "W" in meas:
            self.W = np.array(meas["W"], dtype=float)
            self.xbars = np.array(meas["xbars"], dtype=float)
            self.local_x = np.array(meas["x"], dtype=float)
        else:
            self._host_state_stale = True
        self.pri_res = np.array(meas["pri"], dtype=float)
        self.dua_res = np.array(meas["dua"], dtype=float)
        self._last_all_done = bool(np.all(meas["done"]))
        if "W" in meas:
            # xsqbars is not packed (no in-scan consumer): recompute the
            # second moment host-side from the window's final x so PH
            # state stays internally consistent — checkpoints capture it,
            # and heuristics read it between windows (xbars comes off the
            # device; the redundant E[x] half costs one einsum per
            # WINDOW).  The lean posture defers this to the boundary sync
            _, self.xsqbars = self._node_avgs(self._nonants_cached())
        self.conv = float(meas["conv"][executed - 1])
        self._iter = k + executed - 1
        self._bump_state_version()
        global_toc(
            f"PH megastep {k}..{self._iter} conv {self.conv:.6e}",
            self.options.get("display_progress", False),
        )

    def _sync_host_state(self):
        """Refresh the (S, K)/(S, n) host mirrors from the device-resident
        wheel state — ONE explicit billed fetch (``phstate.boundary_
        fetches``), called only at window boundaries that actually READ
        host state: checkpoint capture, hub payloads, the legacy refresh
        fallback, and loop termination.  No-op when the mirrors are
        already authoritative, so the legacy (full-pack) path never pays
        anything here."""
        st = getattr(self, "_dev_state", None)
        if st is None or not getattr(self, "_host_state_stale", False):
            self._host_state_stale = False
            return
        from .obs import metrics as _metrics
        from .solvers import hostsync

        W, xbars, x = hostsync.fetch((st.W, st.xbars, st.x))
        self.W = np.array(W, dtype=float)
        self.xbars = np.array(xbars, dtype=float)
        self.local_x = np.array(x, dtype=float)
        self._host_state_stale = False
        _, self.xsqbars = self._node_avgs(self._nonants_cached())
        self._bump_state_version()
        _metrics.inc("phstate.boundary_fetches")
        if _trace.enabled():
            _trace.instant(None, "phstate_boundary_fetch", iter=self._iter)

    def _spcomm_needs_host_state(self) -> bool:
        """Whether the imminent ``spcomm.sync()`` will read host PH state:
        W/nonant spoke payloads, or a due checkpoint capture (which must
        find fresh mirrors — the capture itself is pinned zero-fetch)."""
        c = self.spcomm
        if c is None:
            return False
        if getattr(c, "has_w_spokes", False) or \
                getattr(c, "has_nonant_spokes", False):
            return True
        due = getattr(c, "checkpoint_due", None)
        return bool(due and due(self._iter))

    def iterk_loop(self):
        """Main PH loop (phbase.py:875-979).

        When the device-resident wheel megakernel is eligible
        (:meth:`_megastep_request`), iterations run in megastep WINDOWS:
        one donated N-iteration device dispatch + ONE packed fetch per
        window (doc/pipeline.md), with hub/spoke sync, termination checks
        and checkpoint capture at window boundaries.  The legacy
        per-iteration body below remains the refresh/rescue path (and the
        whole path, under ``ADMMSettings.megastep = 1``).
        """
        convthresh = self.options.get("convthresh", 0.0)
        max_iters = self.options["PHIterLimit"]
        # resumed runs continue the ITERATION COUNT from the checkpoint:
        # the limit stays the total-budget knob it always was
        start = int(getattr(self, "_iter_base", 0)) + 1
        mega_n = self._megastep_request()
        k = start
        while k <= max_iters:
            if mega_n:
                executed, conv_hit = self._megastep_window(
                    k, max_iters, convthresh, mega_n)
                if executed:
                    k += executed
                    if self.spcomm is not None:
                        # device-resident posture: refresh the host
                        # mirrors BEFORE a sync that reads them (payload
                        # spokes, a due checkpoint capture) — the capture
                        # itself stays pinned zero-fetch
                        if self._spcomm_needs_host_state():
                            self._sync_host_state()
                        self.spcomm.sync()
                        self.extobject.enditer_after_sync()
                        if self.spcomm.is_converged():
                            global_toc("Cylinder termination", True)
                            break
                    if conv_hit:
                        global_toc(
                            f"Convergence threshold {convthresh} reached "
                            f"at iter {self._iter}",
                            self.options.get("display_progress", False),
                        )
                        break
                    continue
            # the legacy body assembles the augmented objective from the
            # host mirrors — they must be authoritative (no-op unless the
            # device-resident posture left them stale)
            self._sync_host_state()
            k = self._iterk_one(k, convthresh)
            if k is None:
                break
            k += 1
        # loop exit (termination, convergence, iteration limit): whatever
        # reads follow — post_loops' Eobjective, the final checkpoint
        # capture, bench metrics — get authoritative host state
        self._sync_host_state()

    def _iterk_one(self, k, convthresh):
        """One legacy PH iteration (the pre-megakernel loop body).
        Returns ``k`` to continue, or None to terminate the loop."""
        self._iter = k
        # one span per PH iteration on the cylinder's own track
        # (the wheel spinner names cylinder threads; solo runs land
        # on "main") — the hub/spoke timeline rows of the trace
        with _trace.span(None, "ph_iter") as _sp:
            self.extobject.miditer()
            self.solve_ph_subproblems()
            self.Compute_Xbar()
            self.Update_W()
            self.conv = self.convergence_diff()
            if _trace.enabled():   # payload dicts only when tracing
                _sp.add(iter=k, conv=self.conv)
            self.extobject.enditer()
        if self.spcomm is not None:
            self.spcomm.sync()
            self.extobject.enditer_after_sync()
            if self.spcomm.is_converged():
                global_toc("Cylinder termination", True)
                return None
        global_toc(
            f"PH iter {k} conv {self.conv:.6e} Eobj {self.Eobjective():.4f}",
            self.options.get("display_progress", False),
        )
        if self.conv is not None and self.conv < convthresh:
            global_toc(
                f"Convergence threshold {convthresh} reached at iter {k}",
                self.options.get("display_progress", False),
            )
            return None
        if self.ph_converger is not None and self.ph_converger.is_converged():
            global_toc(f"User converger triggered at iter {k}", True)
            return None
        return k

    def post_loops(self) -> float:
        """Final expected objective (phbase.py:982-1037)."""
        self.extobject.post_everything()
        return self.Eobjective()
