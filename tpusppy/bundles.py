"""Scenario bundling: merge scenario groups into per-bundle EF subproblems.

TPU-native analogue of the reference's bundling machinery (P6 in SURVEY
§2.12): ``_assign_bundles`` (spbase.py:219-253) groups contiguous scenarios,
``FormEF`` (spopt.py:743-836) builds one EF model per bundle.  Here a bundle
is a block-merged :class:`~tpusppy.ir.ScenarioProblem` produced by the EF
assembler on the member sub-batch with conditional probabilities, so the
batched solver sees fewer, larger subproblems — same trade as the reference
(shrinks PH subproblem count, tightens iter0 bounds).

Two-stage only (the reference's "proper bundles" for multistage require
whole-subtree alignment, utils/pickle_bundle.py docs).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from .ef import build_ef
from .ir import ScenarioBatch, ScenarioProblem
from .scenario_tree import ScenarioNode


def form_bundles(problems, num_bundles: int) -> list:
    """Contiguous-slice bundling (spbase.py:219-253): ``num_bundles`` merged
    ScenarioProblems from ``len(problems)`` scenarios."""
    S = len(problems)
    if num_bundles <= 0 or num_bundles > S:
        raise ValueError(f"num_bundles={num_bundles} out of range for {S}")
    for p in problems:
        if len(p.nodes) != 1:
            raise ValueError("bundling supports two-stage models only")
    if any(p.prob is None for p in problems):
        problems = [dataclasses.replace(p, prob=1.0 / S) for p in problems]

    slices = np.array_split(np.arange(S), num_bundles)
    bundles = []
    for bnum, sl in enumerate(slices):
        members = [problems[i] for i in sl]
        bprob = sum(p.prob for p in members)
        cond = [dataclasses.replace(p, prob=p.prob / bprob) for p in members]
        sub = ScenarioBatch.from_problems(cond)
        ef = build_ef(sub)
        K = sub.tree.num_nonants
        # build_ef allocates the shared ROOT nonant columns first: 0..K-1
        bundles.append(ScenarioProblem(
            name=f"bundle_{bnum}",
            c=ef.c, q2=ef.q2, A=ef.A, cl=ef.cl, cu=ef.cu,
            lb=ef.lb, ub=ef.ub, is_int=ef.is_int,
            prob=bprob,
            nodes=[ScenarioNode("ROOT", 1.0, 1,
                                np.arange(K, dtype=np.int32))],
            var_names=None,
            const=ef.const,
        ))
    return bundles
