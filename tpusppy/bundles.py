"""Scenario bundling: merge scenario groups into per-bundle EF subproblems.

TPU-native analogue of the reference's bundling machinery (P6 in SURVEY
§2.12): ``_assign_bundles`` (spbase.py:219-253) groups contiguous scenarios,
``FormEF`` (spopt.py:743-836) builds one EF model per bundle.  Here a bundle
is a block-merged :class:`~tpusppy.ir.ScenarioProblem` produced by the EF
assembler on the member sub-batch with conditional probabilities, so the
batched solver sees fewer, larger subproblems — same trade as the reference
(shrinks PH subproblem count, tightens iter0 bounds).

Multistage "proper bundles" (the reference's pickle_bundle semantics +
aircondB family) are supported: bundles must consume ENTIRE second-stage
subtrees, so every inner-stage nonanticipativity constraint lives inside one
bundle; the merged bundle EF bakes those in (build_ef's per-node column
merge) and exposes only the ROOT nonants — the bundled problem is two-stage
from PH's point of view.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from .ef import build_ef
from .ir import ScenarioBatch, ScenarioProblem
from .scenario_tree import ScenarioNode


def _stage2_group_size(problems) -> int:
    """Scenarios per second-stage subtree (contiguous by construction)."""
    names = [p.nodes[1].name for p in problems]
    sizes = {}
    for nm in names:
        sizes[nm] = sizes.get(nm, 0) + 1
    if len(set(sizes.values())) != 1:
        raise ValueError(
            f"uneven second-stage subtrees {sizes}; proper bundles need "
            "uniform branching")
    size = next(iter(sizes.values()))
    # contiguity: scenarios of one subtree must be adjacent
    for i in range(0, len(names), size):
        if len(set(names[i:i + size])) != 1:
            raise ValueError(
                "scenario order is not subtree-contiguous; cannot form "
                "proper bundles")
    return size


def form_bundles(problems, num_bundles: int) -> list:
    """Contiguous-slice bundling (spbase.py:219-253): ``num_bundles`` merged
    ScenarioProblems from ``len(problems)`` scenarios.  Multistage problems
    form PROPER bundles: each bundle must consume whole second-stage
    subtrees (the reference's aircondB rule, tests/examples/aircondB.py:117).
    """
    S = len(problems)
    if num_bundles <= 0 or num_bundles > S:
        raise ValueError(f"num_bundles={num_bundles} out of range for {S}")
    if any(p.prob is None for p in problems):
        problems = [dataclasses.replace(p, prob=1.0 / S) for p in problems]

    stage_counts = {len(p.nodes) for p in problems}
    if len(stage_counts) != 1:
        # a mixed list sliced naively could cut subtrees across bundle
        # boundaries and silently DROP inner-stage nonanticipativity
        raise ValueError(
            f"scenarios disagree on stage structure ({stage_counts} node "
            "counts); cannot bundle")
    multistage = len(problems[0].nodes) > 1
    if multistage:
        gsz = _stage2_group_size(problems)
        n_groups = S // gsz
        if num_bundles > n_groups or n_groups % num_bundles != 0:
            raise ValueError(
                f"proper bundles must consume entire second-stage subtrees: "
                f"{n_groups} subtrees of {gsz} scenarios cannot split into "
                f"{num_bundles} bundles")
        per = (n_groups // num_bundles) * gsz
        slices = [np.arange(b * per, (b + 1) * per)
                  for b in range(num_bundles)]
    else:
        slices = np.array_split(np.arange(S), num_bundles)
    bundles = []
    for bnum, sl in enumerate(slices):
        members = [problems[i] for i in sl]
        bprob = sum(p.prob for p in members)
        cond = [dataclasses.replace(p, prob=p.prob / bprob) for p in members]
        sub = ScenarioBatch.from_problems(cond)
        ef = build_ef(sub)
        # build_ef allocates the shared ROOT (stage-1) nonant columns first:
        # 0..K_root-1; inner-stage nonanticipativity is baked into the EF's
        # merged columns, so only the ROOT nonants remain exposed
        K_root = int((sub.tree.nonant_stage == 1).sum())
        name = (f"bundle_{bnum}" if not multistage
                else f"Bundle_{int(sl[0])}_{int(sl[-1])}")
        bundles.append(ScenarioProblem(
            name=name,
            c=ef.c, q2=ef.q2, A=ef.A, cl=ef.cl, cu=ef.cu,
            lb=ef.lb, ub=ef.ub, is_int=ef.is_int,
            prob=bprob,
            nodes=[ScenarioNode("ROOT", 1.0, 1,
                                np.arange(K_root, dtype=np.int32))],
            var_names=None,
            const=ef.const,
        ))
    return bundles
