"""WheelSpinner: launch a hub and its spokes and spin until termination.

TPU-native analogue of ``mpisppy/spin_the_wheel.py:12-237``.  The reference
splits ``COMM_WORLD`` into strata/cylinder process groups and runs one opt
object per rank (spin_the_wheel.py:219-237).  Here each cylinder is a host
thread driving its own jitted device programs (batched solves share the device
through the run queue — algorithm parallelism P3 of SURVEY §2.12), and the
cross-cylinder fabric is the write-id versioned mailbox set
(:mod:`tpusppy.cylinders.spcommunicator`).

Call sequence mirrors the reference: construct opt + communicator per cylinder,
make windows, ``setup_hub``, run all mains, hub sends the kill sentinel,
spokes finalize, hub_finalize (spin_the_wheel.py:119-144).
"""

from __future__ import annotations

import csv
import threading

import numpy as np

from . import global_toc
from .cylinders.spcommunicator import WindowFabric


class WheelSpinner:
    """Spin a hub and list of spokes (spin_the_wheel.py:12-159)."""

    def __init__(self, hub_dict, list_of_spoke_dict):
        self.hub_dict = dict(hub_dict)
        self.list_of_spoke_dict = [dict(d) for d in (list_of_spoke_dict or [])]
        self.on_hub = True  # single-process: we always see the hub
        self.spun = False

    def spin(self, comm_world=None):
        """comm_world accepted for reference API parity; unused in-process."""
        return self.run()

    def run(self):
        fabric = WindowFabric()

        # Hub opt + communicator (spin_the_wheel.py:92-116)
        hub = self.hub_dict
        hub_opt = hub["opt_class"](**hub["opt_kwargs"])
        hub_comm = hub["hub_class"](
            hub_opt, 0, fabric, spokes=self.list_of_spoke_dict,
            **hub.get("hub_kwargs", {}),
        )

        # Spoke opts + communicators; negotiate mailbox lengths
        spoke_comms = []
        for i, sd in enumerate(self.list_of_spoke_dict):
            opt = sd["opt_class"](**sd["opt_kwargs"])
            comm = sd["spoke_class"](
                opt, i + 1, fabric, **sd.get("spoke_kwargs", {}),
            )
            to_hub_len, to_spoke_len = comm.buffer_lengths()
            fabric.add_spoke(i + 1, to_spoke_len, to_hub_len)
            spoke_comms.append(comm)

        hub_comm.setup_hub()

        # Run spokes on threads, hub on this thread (role dispatch analogue of
        # spin_the_wheel.py:119-127)
        threads = []
        errors = []

        def spoke_runner(comm):
            try:
                comm.main()
            except Exception as e:          # surface spoke crashes at join
                errors.append((comm.__class__.__name__, e))

        for comm in spoke_comms:
            t = threading.Thread(
                target=spoke_runner, args=(comm,),
                name=comm.__class__.__name__, daemon=True,
            )
            t.start()
            threads.append(t)

        try:
            hub_comm.main()
        finally:
            hub_comm.send_terminate()
        for t in threads:
            t.join(timeout=300)
        hung = [t.name for t in threads if t.is_alive()]
        if hung:
            raise RuntimeError(
                f"Spoke threads did not terminate within timeout: {hung}"
            )
        if errors:
            raise RuntimeError(f"Spoke failures: {errors}")

        # finalize: each cylinder flushes, then the hub collects (131-144)
        hub_comm.finalize()
        for comm in spoke_comms:
            comm.finalize()
        hub_comm.hub_finalize()

        self.spcomm = hub_comm
        self.opt = hub_opt
        self.spoke_comms = spoke_comms
        self.spun = True

        # post-run caches (spin_the_wheel.py:166-217)
        self.BestInnerBound = hub_comm.BestInnerBound
        self.BestOuterBound = hub_comm.BestOuterBound
        self.local_nonant_cache = self._best_nonant_cache()
        return self

    # ---- solution access (spin_the_wheel.py:166-217) ------------------------
    def _best_nonant_cache(self):
        """(S, K) nonants of the best incumbent seen anywhere in the wheel."""
        best = getattr(self.opt, "best_xhat_cache", None)  # in-hub xhat ext
        best_val = getattr(self.opt, "best_inner_bound", np.inf)
        for comm in self.spoke_comms:
            cand = getattr(comm, "best_solution_cache", None)
            v = getattr(comm, "best_inner_bound", np.inf)
            if cand is not None and v < best_val:
                best_val = v
                best = self.opt.nonants_of(cand)
        if best is None and self.opt.local_x is not None:
            best = self.opt.nonants_of(self.opt.local_x)
        return None if best is None else np.asarray(best)

    def write_first_stage_solution(self, solution_file_name: str):
        """CSV (or .npy) of root-stage nonant values (sputils.py:37-68)."""
        cache = self.local_nonant_cache
        if cache is None:
            raise RuntimeError("No solution available to write")
        tree = self.opt.tree
        root_slots = np.where(tree.nonant_stage == 1)[0]
        vals = cache[0, root_slots]
        if solution_file_name.endswith(".npy"):
            np.save(solution_file_name, vals)
            return
        names = self.opt.batch.names
        var_names = (
            self.opt.scenario_creator(
                names[0], **self.opt.scenario_creator_kwargs
            ).var_names
        )
        idx = tree.nonant_indices[root_slots]
        with open(solution_file_name, "w", newline="") as f:
            w = csv.writer(f)
            for j, v in zip(idx, vals):
                nm = var_names[j] if var_names else f"x[{j}]"
                w.writerow([nm, repr(float(v))])

    def write_tree_solution(self, directory_name: str):
        """Per-scenario nonant CSVs (spin_the_wheel.py:199-217)."""
        import os

        os.makedirs(directory_name, exist_ok=True)
        cache = self.local_nonant_cache
        if cache is None:
            raise RuntimeError("No solution available to write")
        for s, name in enumerate(self.opt.all_scenario_names):
            with open(os.path.join(directory_name, f"{name}.csv"), "w",
                      newline="") as f:
                w = csv.writer(f)
                for k in range(cache.shape[1]):
                    w.writerow([f"nonant[{k}]", repr(float(cache[s, k]))])


def spin_the_wheel(hub_dict, list_of_spoke_dict, comm_world=None):
    """Functional alias kept for reference parity (deprecated there too)."""
    ws = WheelSpinner(hub_dict, list_of_spoke_dict)
    ws.spin(comm_world)
    global_toc("Spinning complete", True)
    return ws
