"""WheelSpinner: launch a hub and its spokes and spin until termination.

TPU-native analogue of ``mpisppy/spin_the_wheel.py:12-237``.  The reference
splits ``COMM_WORLD`` into strata/cylinder process groups and runs one opt
object per rank (spin_the_wheel.py:219-237).  Here each cylinder is a host
thread driving its own jitted device programs (batched solves share the device
through the run queue — algorithm parallelism P3 of SURVEY §2.12), and the
cross-cylinder fabric is the write-id versioned mailbox set
(:mod:`tpusppy.cylinders.spcommunicator`).

Call sequence mirrors the reference: construct opt + communicator per cylinder,
make windows, ``setup_hub``, run all mains, hub sends the kill sentinel,
spokes finalize, hub_finalize (spin_the_wheel.py:119-144).
"""

from __future__ import annotations

import csv
import os
import threading
import time

import numpy as np

from . import global_toc
from .cylinders.spcommunicator import WindowFabric
from .obs import trace as _trace


class WheelSpinner:
    """Spin a hub and list of spokes (spin_the_wheel.py:12-159).

    Resilience (tpusppy.resilience, doc/resilience.md): the hub options
    may carry ``checkpoint_dir`` (+ ``checkpoint_every_secs`` /
    ``checkpoint_every_iters`` / ``checkpoint_keep``) to snapshot the
    wheel asynchronously, ``resume`` (or the ``resume=`` ctor arg) to
    warm-start from the newest checkpoint, ``spoke_timeout_secs`` to
    declare a progress-less spoke wedged, and ``strict_spokes`` to
    restore the legacy raise-on-spoke-crash teardown.  By default a
    crashed spoke is marked LOST (``self.lost_spokes``) and the wheel
    completes with whatever the remaining bounders certified.
    """

    def __init__(self, hub_dict, list_of_spoke_dict, resume=None):
        self.hub_dict = dict(hub_dict)
        self.list_of_spoke_dict = [dict(d) for d in (list_of_spoke_dict or [])]
        self.on_hub = True  # single-process: we always see the hub
        self.spun = False
        self.resume = resume
        self.lost_spokes = []
        self.spoke_errors = []
        self.resumed_from = None

    def spin(self, comm_world=None):
        """comm_world accepted for reference API parity; unused in-process."""
        return self.run()

    def _hub_options(self) -> dict:
        return dict(self.hub_dict.get("hub_kwargs", {}).get("options") or {})

    def _load_resume(self):
        """The checkpoint to warm-start from (ctor arg wins over the hub
        option); None means cold start — including a --resume pointed at
        a dir that has no checkpoint yet (first run of a retried job)."""
        from .resilience import checkpoint as _ckpt

        src = self.resume or self._hub_options().get("resume")
        if not src:
            return None
        ck = _ckpt.load_latest(src)
        if ck is None:
            global_toc(f"resume: no checkpoint under {src!r} — cold start",
                       True)
        return ck

    def _make_checkpointer(self, fresh_start: bool = False):
        opts = self._hub_options()
        if not opts.get("checkpoint_dir"):
            return None
        from .resilience.checkpoint import CheckpointManager

        return CheckpointManager(
            opts["checkpoint_dir"],
            every_secs=opts.get("checkpoint_every_secs", 60.0),
            every_iters=opts.get("checkpoint_every_iters"),
            keep=opts.get("checkpoint_keep", 3),
            fresh_start=fresh_start)

    def _wire_resilience(self, hub_comm, hub_opt):
        """Shared resume + checkpointer hookup for both spinner variants
        (call after ``setup_hub``).  Returns the CheckpointManager (or
        None).  Bounds always re-seed; the PH-state restore is consumed
        by ``PHBase.Iter0`` — opt classes that never run it (APH's own
        driver) get a bounds-only resume, reported by
        :meth:`_warn_unconsumed_resume` at teardown."""
        ckpt = self._load_resume()
        if ckpt is not None:
            hub_opt._resume_ckpt = ckpt
            hub_comm.seed_resume(ckpt)
            self.resumed_from = ckpt.iteration
        mgr = self._make_checkpointer(fresh_start=ckpt is None)
        if mgr is not None:
            hub_comm.attach_checkpointer(mgr)
        self._prewarm_executables(ckpt)
        return mgr

    def _prewarm_executables(self, ckpt):
        """Warm start for the COMPILES, not just the math: arm the AOT
        executable cache from a resume checkpoint's carried pointer
        (checkpoint + cache compose — the resumed process reaches its
        first PH iteration warm even when its own env never named the
        cache), then deserialize the cached programs NOW, before the
        cylinder threads start: this jaxlib's executable loader races
        in-flight XLA compiles (see tpusppy/solvers/aot.py), so the bulk
        load must happen while this thread is the only one touching the
        backend."""
        from .solvers import aot as _aot

        if ckpt is not None and not _aot.cache_path():
            src = (ckpt.meta or {}).get("aot_cache")
            if src and os.path.isdir(src):
                _aot.set_cache_path(src)
                global_toc(
                    f"resume: AOT executable cache armed from the "
                    f"checkpoint pointer ({src})", True)
        if _aot.enabled():
            n = _aot.prewarm()
            if n:
                global_toc(f"AOT cache: {n} executable(s) prewarmed", True)

    @staticmethod
    def _warn_unconsumed_resume(hub_opt):
        """A resume checkpoint nobody consumed means the opt class never
        ran the PHBase.Iter0 restore seam (e.g. APH's own driver): the
        run still got the re-seeded bounds, but W/rho restarted cold and
        the iteration count did NOT continue — say so instead of letting
        ``resumed_from`` imply a full warm start."""
        if getattr(hub_opt, "_resume_ckpt", None) is not None:
            hub_opt._resume_ckpt = None
            global_toc(
                f"WARNING: resume checkpoint was NOT consumed by "
                f"{type(hub_opt).__name__} (no PHBase.Iter0 in its "
                "driver): bounds were re-seeded but PH state restarted "
                "cold and PHIterLimit did not continue from the "
                "snapshot", True)

    def _final_checkpoint(self, hub_comm, mgr):
        """Bank the terminal state (post bound-harvest) and drain the
        writer: a later ``--resume`` of a COMPLETED run then reloads the
        certified end state instead of re-running the wheel."""
        if mgr is None:
            return
        from .resilience import checkpoint as _ckpt

        try:
            mgr.capture(hub_comm.current_iteration(),
                        lambda: _ckpt.capture_ph(hub_comm.opt, hub=hub_comm))
        except Exception as e:     # capture must never cost the results
            from .obs import metrics as _metrics

            _metrics.inc("checkpoint.capture_errors")
            global_toc(f"WARNING: final checkpoint capture failed: {e!r}",
                       True)
        mgr.close()

    @staticmethod
    def _cylinder_opt_kwargs(opt_kwargs):
        """Wheel-context solver defaults: several cylinders' factors coexist
        on one chip, so shared-A factors drop the exact K and refine
        matrix-free (factors_keep_K) unless the caller pinned it.
        Deep-copies only the dicts it touches."""
        opt_kwargs = dict(opt_kwargs)
        options = dict(opt_kwargs.get("options") or {})
        so = dict(options.get("solver_options") or {})
        so.setdefault("factors_keep_K", False)
        options["solver_options"] = so
        opt_kwargs["options"] = options
        return opt_kwargs

    def run(self):
        from .resilience import supervisor as _supervisor

        t_build0 = time.monotonic()
        fabric = WindowFabric()

        # Hub opt + communicator (spin_the_wheel.py:92-116)
        hub = self.hub_dict
        hub_opt = hub["opt_class"](
            **self._cylinder_opt_kwargs(hub["opt_kwargs"]))
        hub_comm = hub["hub_class"](
            hub_opt, 0, fabric, spokes=self.list_of_spoke_dict,
            **hub.get("hub_kwargs", {}),
        )

        # Spoke opts + communicators; negotiate mailbox lengths
        spoke_comms = []
        for i, sd in enumerate(self.list_of_spoke_dict):
            opt = sd["opt_class"](**self._cylinder_opt_kwargs(sd["opt_kwargs"]))
            comm = sd["spoke_class"](
                opt, i + 1, fabric, **sd.get("spoke_kwargs", {}),
            )
            to_hub_len, to_spoke_len = comm.buffer_lengths()
            fabric.add_spoke(i + 1, to_spoke_len, to_hub_len)
            spoke_comms.append(comm)

        hub_comm.setup_hub()
        # resume + checkpointing (doc/resilience.md): bounds re-seed the
        # hub NOW (post-setup); PH state re-seats after the warm-up Iter0
        ckpt_mgr = self._wire_resilience(hub_comm, hub_opt)
        sup = _supervisor.SpokeSupervisor(
            fabric,
            {i + 1: c.__class__.__name__ for i, c in enumerate(spoke_comms)},
            timeout_secs=self._hub_options().get("spoke_timeout_secs"),
            grace_factor=float(self._hub_options().get(
                "spoke_timeout_grace", 8.0)))
        if spoke_comms:
            hub_comm.attach_supervisor(sup)
        global_toc(
            f"wheel constructed ({1 + len(spoke_comms)} cylinders) in "
            f"{time.monotonic() - t_build0:.1f}s", True)

        # Run spokes on threads, hub on this thread (role dispatch analogue of
        # spin_the_wheel.py:119-127)
        threads = []
        errors = []

        def spoke_runner(comm, track, idx):
            # each cylinder thread is its own trace timeline — the
            # per-cylinder rows of the Perfetto view (doc/observability.md)
            _trace.set_thread_track(track)
            try:
                comm.main()
            except Exception as e:          # surface spoke crashes at join
                errors.append((comm.__class__.__name__, e))
                sup.note_error(idx, e)

        for i, comm in enumerate(spoke_comms):
            t = threading.Thread(
                target=spoke_runner,
                args=(comm, f"spoke{i + 1}:{comm.__class__.__name__}", i + 1),
                name=comm.__class__.__name__, daemon=True,
            )
            t.start()
            threads.append(t)
            sup.note_thread(i + 1, t)

        _trace.set_thread_track("hub")
        try:
            hub_comm.main()
        finally:
            _trace.set_thread_track(None)
            hub_comm.send_terminate()
            # construction + hub loop: gap-based termination happened HERE;
            # the spoke teardown below (final bound-tightening passes,
            # lingering MILPs) can add minutes that are bookkeeping, not
            # time-to-certified-gap — benchmarks report this figure
            self.gap_wall_secs = time.monotonic() - t_build0
        deadline = time.monotonic() + 900.0   # shared across all joins
        for i, t in enumerate(threads):
            # lost spokes get a short grace, not the whole deadline: a
            # crashed thread is already dead and a wedged one is exactly
            # what the supervisor told us not to wait for
            cap = 5.0 if sup.is_lost(i + 1) else deadline - time.monotonic()
            t.join(timeout=max(0.0, min(cap, deadline - time.monotonic())))
        hung = [t.name for t in threads if t.is_alive()]
        if hung:
            # A spoke stuck inside an uninterruptible host MILP (e.g. the
            # restricted EF's 120 s polish under host contention) must not
            # turn a certified run into an error: skip its finalize (it
            # cannot run concurrently with main), keep everything the hub
            # already accepted, and say so loudly.  Threads are daemons,
            # so process exit is not blocked.
            global_toc(
                f"WARNING: spoke thread(s) still running at teardown "
                f"(skipping their finalize): {hung}", True)
            self.hung_spokes = hung
        self.lost_spokes = sup.lost_names()
        self.spoke_errors = list(errors)
        if errors and self._hub_options().get("strict_spokes"):
            self._final_checkpoint(hub_comm, ckpt_mgr)
            raise RuntimeError(f"Spoke failures: {errors}")
        if errors:
            # graceful degradation (the default): the wheel completed on
            # the surviving bounders; the loss is loud, recorded, and on
            # the trace — but it is not an exception
            global_toc(
                f"WARNING: wheel degraded — spoke failures survived: "
                f"{[(n, repr(e)) for n, e in errors]}", True)

        # finalize: each cylinder flushes, then the hub collects (131-144).
        # Identity pairing (threads were created in spoke_comms order): a
        # hung instance must not suppress finalize for a healthy sibling
        # of the same class; a CRASHED spoke's finalize is skipped too
        # (its state is whatever the exception left behind).
        hub_comm.finalize()
        crashed = {idx for idx, (nm, why) in sup.lost().items()
                   if why == "crashed"}
        for i, (t, comm) in enumerate(zip(threads, spoke_comms)):
            if not t.is_alive() and (i + 1) not in crashed:
                comm.finalize()
        hub_comm.hub_finalize()
        self._warn_unconsumed_resume(hub_opt)
        self._final_checkpoint(hub_comm, ckpt_mgr)

        self.spcomm = hub_comm
        self.opt = hub_opt
        self.spoke_comms = spoke_comms
        self.spun = True

        # post-run caches (spin_the_wheel.py:166-217)
        self.BestInnerBound = hub_comm.BestInnerBound
        self.BestOuterBound = hub_comm.BestOuterBound
        self.local_nonant_cache = self._best_nonant_cache()
        self._write_result_sidecar()
        # a traced wheel banks its artifact NOW (not at interpreter exit:
        # the driver may SIGKILL a lingering process)
        _trace.flush_if_enabled()
        return self

    def _write_result_sidecar(self):
        """When TPUSPPY_RESULT_JSON names a path, bank {inner, outer,
        rel_gap} there — machine-checkable driver results, so harnesses
        (examples/run_all.py) can assert OBJECTIVES instead of exit codes
        (the reference harness's known liability, SURVEY §4)."""
        import json
        import os

        path = os.environ.get("TPUSPPY_RESULT_JSON")
        if not path:
            return
        ib, ob = float(self.BestInnerBound), float(self.BestOuterBound)
        if np.isfinite(ib) and np.isfinite(ob):
            rel_gap = abs(ib - ob) / (abs(ob) or 1.0)
        else:
            rel_gap = float("inf")
        with open(path, "w") as f:
            json.dump({"inner": ib, "outer": ob, "rel_gap": rel_gap}, f)

    # ---- solution access (spin_the_wheel.py:166-217) ------------------------
    def _best_nonant_cache(self):
        """(S, K) nonants of the best incumbent seen anywhere in the wheel."""
        best = getattr(self.opt, "best_xhat_cache", None)  # in-hub xhat ext
        best_val = getattr(self.opt, "best_inner_bound", np.inf)
        for comm in self.spoke_comms:
            if hasattr(comm, "best_snapshot"):
                v, cand = comm.best_snapshot()
            else:
                cand = getattr(comm, "best_solution_cache", None)
                v = getattr(comm, "best_inner_bound", np.inf)
            if cand is not None and v < best_val:
                best_val = v
                best = self.opt.nonants_of(cand)
        if best is None and self.opt.local_x is not None:
            best = self.opt.nonants_of(self.opt.local_x)
        return None if best is None else np.asarray(best)

    def write_first_stage_solution(self, solution_file_name: str):
        """CSV (or .npy) of root-stage nonant values (sputils.py:37-68)."""
        cache = self.local_nonant_cache
        if cache is None:
            raise RuntimeError("No solution available to write")
        tree = self.opt.tree
        root_slots = np.where(tree.nonant_stage == 1)[0]
        vals = cache[0, root_slots]
        if solution_file_name.endswith(".npy"):
            np.save(solution_file_name, vals)
            return
        names = self.opt.batch.names
        var_names = (
            self.opt.scenario_creator(
                names[0], **self.opt.scenario_creator_kwargs
            ).var_names
        )
        idx = tree.nonant_indices[root_slots]
        with open(solution_file_name, "w", newline="") as f:
            w = csv.writer(f)
            for j, v in zip(idx, vals):
                nm = var_names[j] if var_names else f"x[{j}]"
                w.writerow([nm, repr(float(v))])

    def write_tree_solution(self, directory_name: str):
        """Per-scenario nonant CSVs (spin_the_wheel.py:199-217)."""
        import os

        os.makedirs(directory_name, exist_ok=True)
        cache = self.local_nonant_cache
        if cache is None:
            raise RuntimeError("No solution available to write")
        for s, name in enumerate(self.opt.all_scenario_names):
            with open(os.path.join(directory_name, f"{name}.csv"), "w",
                      newline="") as f:
                w = csv.writer(f)
                for k in range(cache.shape[1]):
                    w.writerow([f"nonant[{k}]", repr(float(cache[s, k]))])


def spin_the_wheel(hub_dict, list_of_spoke_dict, comm_world=None):
    """Functional alias kept for reference parity (deprecated there too)."""
    ws = WheelSpinner(hub_dict, list_of_spoke_dict)
    ws.spin(comm_world)
    global_toc("Spinning complete", True)
    return ws


# ---- cross-process wheel over the C++ shm window service --------------------

def _scrubbed_child_env():
    """Child-process env for CPU cylinders.

    The axon sitecustomize (TPU tunnel shim, injected via PYTHONPATH) dials
    its relay at interpreter start; spawned CPU children must not inherit it
    or they hang before reaching our code when the relay is down.  A shared
    persistent compilation cache is enabled so sibling cylinder processes
    (which compile identical solver programs) pay the XLA compile once.
    """
    import os

    env = dict(os.environ)
    pp = env.get("PYTHONPATH", "")
    parts = [p for p in pp.split(os.pathsep) if p and ".axon_site" not in p]
    if parts:
        env["PYTHONPATH"] = os.pathsep.join(parts)
    else:
        env.pop("PYTHONPATH", None)
    env["JAX_PLATFORMS"] = "cpu"
    env.setdefault(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(os.path.expanduser("~"), ".cache", "tpusppy_xla"))
    return env


def _ready_path(fabric_name, strata_rank):
    import os
    import tempfile

    tag = fabric_name.strip("/").replace("/", "_")
    return os.path.join(tempfile.gettempdir(), f"{tag}.{strata_rank}.ready")


def _spoke_worker(fabric_spec, spoke_dict, strata_rank):
    """Child-process entry: attach the window fabric, build this cylinder's
    opt, run its main loop (the per-rank role dispatch of
    spin_the_wheel.py:92-127, as an OS process instead of an MPI rank).
    ``fabric_spec`` is ("shm", name) or ("tcp", host, port, tag, secret) —
    the latter is exactly what a REMOTE host's spoke launcher passes
    (doc/multihost.md; ``tag`` names the readiness sentinel file and
    ``secret`` is the hub fabric's shared handshake token).
    A sentinel file marks construction-readiness for the parent's
    first-contact barrier (waiting for a bound Put instead would deadlock:
    xhat-style spokes publish only AFTER receiving hub data)."""
    kind = fabric_spec[0]
    if kind == "shm":
        from .runtime.window_service import ShmWindowFabric

        tag = fabric_spec[1]
        fabric = ShmWindowFabric(tag, attach=True)
    else:
        from .runtime.tcp_window_service import TcpWindowFabric

        _, host, port, tag, secret = fabric_spec
        fabric = TcpWindowFabric(connect=(host, port), secret=secret)
    opt = spoke_dict["opt_class"](**spoke_dict["opt_kwargs"])
    comm = spoke_dict["spoke_class"](
        opt, strata_rank, fabric, **spoke_dict.get("spoke_kwargs", {}))
    with open(_ready_path(tag, strata_rank), "w") as f:
        f.write("ready")
    try:
        comm.main()
    finally:
        comm.finalize()


class MultiprocessWheelSpinner(WheelSpinner):
    """WheelSpinner whose spokes are separate OS processes over the C++
    shared-memory window service — true algorithm parallelism (SURVEY P3).

    The reference gives each cylinder its own process group and exchanges
    one-sided RMA windows (spin_the_wheel.py:219-237, spcommunicator.py:
    93-120); here each cylinder is an OS process and the windows are either
    seqlock shm mailboxes (runtime/csrc/window_service.cpp, single host) or
    the TCP box server (runtime/csrc/tcp_window_service.cpp, any host) with
    identical write-id / kill-sentinel semantics — pick with
    ``fabric="shm"|"tcp"``.  Spokes on OTHER hosts join a "tcp" wheel by
    connecting to ``(hub_host, fabric.port)`` — see doc/multihost.md.
    Intended for CPU cylinders or multi-host deployments where each process
    owns its own device slice; on the shared single-TPU dev box, the
    in-process (threaded) WheelSpinner remains the default.
    """

    def __init__(self, hub_dict, list_of_spoke_dict, fabric: str = "shm",
                 resume=None):
        super().__init__(hub_dict, list_of_spoke_dict, resume=resume)
        if fabric not in ("shm", "tcp"):
            raise ValueError(f"fabric must be 'shm' or 'tcp', got {fabric!r}")
        self.fabric_kind = fabric

    def run(self):
        import multiprocessing as mp
        import os
        import uuid

        hub = self.hub_dict
        hub_opt = hub["opt_class"](**hub["opt_kwargs"])

        # Length negotiation (the Send/Recv of spoke.py:34-58): buffer sizes
        # are functions of the shared model shape, so temporary spoke comms
        # around the HUB's opt compute them without building spoke opts.
        lengths = []
        for i, sd in enumerate(self.list_of_spoke_dict):
            tmp = sd["spoke_class"](hub_opt, i + 1, WindowFabric(),
                                    **sd.get("spoke_kwargs", {}))
            s2h, h2s = tmp.buffer_lengths()
            lengths.append((h2s, s2h))
        hub_opt.spcomm = None

        tag = f"/tpusppy_wheel_{os.getpid()}_{uuid.uuid4().hex[:8]}"
        if self.fabric_kind == "shm":
            from .runtime.window_service import ShmWindowFabric

            fabric = ShmWindowFabric(tag, spoke_lengths=lengths)
            spec = ("shm", tag)
        else:
            from .runtime.tcp_window_service import TcpWindowFabric

            fabric = TcpWindowFabric(spoke_lengths=lengths)
            spec = ("tcp", "127.0.0.1", fabric.port, tag, fabric.secret)

        ctx = mp.get_context("spawn")
        procs = []
        old_env = dict(os.environ)
        os.environ.clear()
        os.environ.update(_scrubbed_child_env())
        try:
            for i, sd in enumerate(self.list_of_spoke_dict):
                p = ctx.Process(
                    target=_spoke_worker, args=(spec, sd, i + 1),
                    name=sd["spoke_class"].__name__, daemon=True,
                )
                p.start()
                procs.append(p)
        finally:
            os.environ.clear()
            os.environ.update(old_env)

        hub_comm = hub["hub_class"](
            hub_opt, 0, fabric, spokes=self.list_of_spoke_dict,
            **hub.get("hub_kwargs", {}),
        )
        hub_comm.setup_hub()
        # resume + checkpointing live on the HUB side (it owns W and the
        # bounds); spokes re-seed from the first sync's payloads
        ckpt_mgr = self._wire_resilience(hub_comm, hub_opt)
        from .resilience import supervisor as _supervisor

        # death-only loss detection here: heartbeat gauges are
        # process-local (the obs registry does not cross the fork), so a
        # healthy child spoke idling between bounds would look exactly
        # like a wedged one — spoke_timeout_secs applies to the THREADED
        # spinner only (doc/resilience.md)
        sup = _supervisor.SpokeSupervisor(
            fabric,
            {i + 1: sd["spoke_class"].__name__
             for i, sd in enumerate(self.list_of_spoke_dict)},
            timeout_secs=None)
        for i, p in enumerate(procs):
            sup.note_process(i + 1, p)
        hub_comm.attach_supervisor(sup)
        # First-contact barrier: spawned cylinders cold-start a full python +
        # jax(+XLA compile) pipeline; a fast hub would otherwise finish and
        # kill them before they ever participate.  (MPI ranks start
        # together; process spawn does not.)  Readiness = the child
        # CONSTRUCTED its comm (sentinel file) — NOT its first bound Put,
        # which for xhat-style spokes only happens after hub data arrives.
        import time as _time

        wait = float(self.hub_dict.get("first_contact_wait", 900.0))
        t0 = _time.time()
        ready = [_ready_path(tag, i + 1)
                 for i in range(len(self.list_of_spoke_dict))]
        while _time.time() - t0 < wait:
            if all(os.path.exists(rp) for rp in ready):
                break
            if any(p.exitcode not in (None, 0) for p in procs):
                break
            _time.sleep(0.25)
        for rp in ready:
            try:
                os.remove(rp)
            except OSError:
                pass
        strict = bool(self._hub_options().get("strict_spokes"))
        try:
            try:
                hub_comm.main()
            finally:
                hub_comm.send_terminate()
            for i, p in enumerate(procs):
                p.join(timeout=5 if sup.is_lost(i + 1) else 300)
            hung = [p.name for p in procs if p.is_alive()]
            for p in procs:
                if p.is_alive():
                    p.terminate()
            if hung and strict:
                raise RuntimeError(
                    f"Spoke processes did not terminate: {hung}")
            bad = [(p.name, p.exitcode) for p in procs
                   if p.exitcode not in (0, None)]
            self.spoke_errors = bad
            if bad and strict:
                raise RuntimeError(f"Spoke process failures: {bad}")
            if bad or hung:
                # graceful degradation (the default, matching the threaded
                # spinner): the hub's accepted bounds stand
                global_toc(
                    f"WARNING: wheel degraded — spoke processes "
                    f"failed/hung: {bad + [(h, 'hung') for h in hung]}",
                    True)
        finally:
            # failure paths must not abandon the hub's results or leak the
            # POSIX shm segment
            hub_comm.finalize()
            hub_comm.hub_finalize()
            self._warn_unconsumed_resume(hub_opt)
            self._final_checkpoint(hub_comm, ckpt_mgr)
            self.lost_spokes = sup.lost_names()
            self.spcomm = hub_comm
            self.opt = hub_opt
            self.spoke_comms = []
            self.spun = True
            self.BestInnerBound = hub_comm.BestInnerBound
            self.BestOuterBound = hub_comm.BestOuterBound
            self.local_nonant_cache = self._best_nonant_cache()
            self._write_result_sidecar()
            fabric.close()
        return self
