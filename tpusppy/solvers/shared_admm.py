"""Shared-constraint-matrix ADMM: the memory-wall breaker for big families.

Most stochastic-programming families at scale (the reference's headline
1000-scenario UC above all — ``paperruns/larger_uc``, wind uncertainty enters
the power-balance rhs) have scenarios that differ only in costs, rhs and
bounds: the constraint matrix ``A`` is IDENTICAL across scenarios.  The dense
batched solver (:mod:`tpusppy.solvers.admm`) stores (S, m, n) A plus an
(S, n, n) KKT inverse — at reference UC scale (30 gens x 48 h, S=1000) that is
~67 GB and cannot fit one chip's HBM.  Here:

- ``A`` is stored ONCE as (m, n): memory drops S-fold (67 GB -> 67 MB);
- Ruiz scaling, row penalties and the KKT matrix are shared, so there is ONE
  (n, n) factorization instead of S of them;
- the hot x-update becomes ``rhs @ Kinv`` — a single large (S, n) x (n, n)
  MXU matmul, and the constraint matvecs are (S, m) x (m, n) matmuls: the
  best-possible TPU shapes (large, static, batched on the leading axis).

Per-scenario DIAGONAL deviations (PH rho vectors that differ across
scenarios, per-scenario clamp boosting) are handled by iterative refinement:
the shared ``K`` is the preconditioner, and the exact per-scenario system
``K_s = K + diag(dq2_s)`` is applied matrix-free in the refinement residual.
Row penalties and the scaling stay shared — scenarios in one family are
near-identically conditioned, which is exactly why they form a family.

No active-set polish on this path (a per-scenario (n+m)^2 KKT batch is the
memory wall all over again): outer bounds stay certified through weak duality
(:func:`tpusppy.solvers.admm.dual_objective` handles 2-D A), and LP-exact
primal residue is delegated to the host straggler rescue
(``spopt.SPOpt._rescue_stragglers``).

Reference analogue: the per-rank persistent-solver loop (spopt.py:85-307);
this module is its shape-shared fast path, dispatched automatically by
``SPOpt.solve_loop`` when ``ScenarioBatch.A_shared`` is set.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..obs import metrics as _metrics
from . import aot as _aot
from .admm import (ADMMSettings, BatchSolution, BIG, _clean_bounds,
                   _done_mask, _explicit_inverse, _frozen_sweep_phases,
                   _plateau_update)
from .sparse import SparseA
from .structured_kkt import (apply_kinv_like, factor_structured,
                             zero_factors)


def _mv(A, x):
    """A x: (S, n) -> (S, m) for dense (m, n) or :class:`SparseA`."""
    return A.matvec(x) if isinstance(A, SparseA) else x @ A.T


def _rmv(A, y):
    """A' y: (S, m) -> (S, n) for dense (m, n) or :class:`SparseA`."""
    return A.rmatvec(y) if isinstance(A, SparseA) else y @ A


class SharedFactors(NamedTuple):
    """Reusable solve state for the frozen path (shared-A analogue of
    :class:`tpusppy.solvers.admm.Factors`)."""

    D: jax.Array       # (n,) Ruiz column scaling (shared)
    E: jax.Array       # (m,) Ruiz row scaling (shared)
    cost: jax.Array    # scalar objective scaling (shared)
    rho_a: jax.Array   # (m,) row penalties actually used last
    rho_x: jax.Array   # (n,) variable-box penalties actually used last
    gamma: jax.Array   # (S,) per-scenario penalty scales actually used last
    Kinv: jax.Array    # (n, n) explicit inverse of the shared x-update
                       # system, or a structured_kkt.BlockWoodbury operator
                       # (sparse-A families with block/Woodbury structure)
    K: jax.Array       # (n, n) exact shared K for dense refinement, or None
                       # (factors_keep_K=False): refinement then runs
                       # matrix-free through the scaled shared A
    q2ref: jax.Array   # (n,) scaled q2 the K was built with


class _Masks(NamedTuple):
    fin_cl: jax.Array  # (S, m)
    fin_cu: jax.Array  # (S, m)
    fin_lb: jax.Array  # (S, n)
    fin_ub: jax.Array  # (S, n)
    eq: jax.Array      # (m,) equality row in EVERY scenario (shared classes)
    loose: jax.Array   # (m,) two-sided-infinite row in every scenario
    eqx: jax.Array     # (n,) zero-width variable box in every scenario


def _ruiz_shared(A, q2ref, iters):
    """Ruiz equilibration of the single shared A (dense or sparse);
    returns (D (n,), E (m,))."""
    m, n = A.shape
    D = jnp.ones((n,), A.dtype)
    E = jnp.ones((m,), A.dtype)
    sparse = isinstance(A, SparseA)

    def body(_, DE):
        D, E = DE
        Ps = q2ref * D * D
        if sparse:
            As = A.scale(E, D)
            col = jnp.maximum(As.col_absmax(), jnp.abs(Ps))
            row = As.row_absmax()
        else:
            As = A * E[:, None] * D[None, :]
            col = jnp.maximum(jnp.max(jnp.abs(As), axis=0), jnp.abs(Ps))
            row = jnp.max(jnp.abs(As), axis=1)
        col = jnp.where(col < 1e-12, 1.0, col)
        row = jnp.where(row < 1e-12, 1.0, row)
        return D / jnp.sqrt(col), E / jnp.sqrt(row)

    D, E = jax.lax.fori_loop(0, iters, body, (D, E))
    return D, E


def _factor_shared(q2ref, A, rho_a, rho_x, sigma):
    """(Kinv, K) of the SHARED K = diag(q2ref + rho_x) + sigma I + A'RA —
    one (n, n) system for the whole scenario batch.

    Three regimes by matrix type:
    - dense (m, n) array: dense K + explicit inverse (unchanged);
    - :class:`SparseA` WITH attached block/Woodbury structure: the
      structured factorization (no (n, n) object at all; K is None and
      refinement runs matrix-free through the sparse A);
    - SparseA without structure: K assembled via a transient dense
      scatter, explicit inverse kept, K dropped (matrix-free refinement
      keeps the factors small)."""
    n = A.shape[1]
    if isinstance(A, SparseA):
        if A.structure is not None:
            bw = factor_structured(A, A.structure, q2ref + rho_x, rho_a,
                                   sigma)
            return bw, None
        Ad = A.todense()
        K = jnp.einsum("mn,m,mk->nk", Ad, rho_a, Ad)
        K = K + jnp.eye(n, dtype=Ad.dtype) * sigma
        K = K + jnp.diag(q2ref + rho_x)
        return _explicit_inverse(K[None])[0], None
    K = jnp.einsum("mn,m,mk->nk", A, rho_a, A)
    K = K + jnp.eye(n, dtype=A.dtype) * sigma
    K = K + jnp.diag(q2ref + rho_x)
    return _explicit_inverse(K[None])[0], K


def _solve_shared_K(Kinv, Kmul, dq2, gamma, b, refine, extra_if_dq2=2,
                    prec=None):
    """x s.t. (gamma_s K + diag(dq2_s)) x_s = b_s per scenario, via the shared
    inverse + refinement against the exact per-scenario system; ``Kmul``
    applies the exact K (dense row-vector product, or matrix-free via the
    scaled A when the factors don't carry K — see ``factors_keep_K``).

    ``gamma`` (S, 1) is the per-scenario penalty scale: rho_a, rho_x and
    sigma are all free ADMM parameters, so scaling the WHOLE penalty profile
    by a per-scenario scalar keeps the x-update system an exact multiple of
    the shared K (plus the diagonal objective deviation dq2) — per-scenario
    rho adaptation without per-scenario factorizations.  The refinement
    iteration matrix has spectral radius max_j dq2_j / (gamma K_jj) — the
    adaptation clamps gamma so this stays < 1 (see the QP clamp in the
    restart loop); ``extra_if_dq2`` adds passes only when a nonzero dq2 is
    actually present (LP batches skip them at runtime via lax.cond).

    ``prec``: mixed-precision mode for the K^-1 applies; ``Kmul`` (the
    defect) must then be full-precision — the caller builds it pinned."""
    def steps(x, k):
        for _ in range(k):
            r = b - (gamma * Kmul(x) + dq2 * x)
            x = x + apply_kinv_like(Kinv, r / gamma, prec)
        return x

    x = steps(apply_kinv_like(Kinv, b / gamma, prec), refine)
    if extra_if_dq2 > 0:
        x = jax.lax.cond(jnp.any(dq2 != 0),
                         lambda v: steps(v, extra_if_dq2), lambda v: v, x)
    return x


class _IterState(NamedTuple):
    x: jax.Array
    z: jax.Array
    zx: jax.Array
    y: jax.Array
    yx: jax.Array
    gamma: jax.Array   # (S,) per-scenario penalty scale — adapts IN-loop
    pri: jax.Array
    dua: jax.Array
    prinorm: jax.Array
    duanorm: jax.Array
    k: jax.Array
    best: jax.Array    # scalar: best batch-worst eps-normalized residual
    stall: jax.Array   # scalar int32: consecutive non-improving windows


def _core(q, q2s, q2ref, A, cl, cu, lb, ub, state, Kinv, K, rho_a, rho_x,
          glo, ghi, st: ADMMSettings, adaptive=False, prec=None,
          allow_pallas=False):
    """Inner ADMM sweep at a fixed shared rho profile with IN-LOOP
    per-scenario gamma adaptation.

    Scaling the whole penalty profile (rho_a, rho_x, sigma) by gamma_s keeps
    the x-update system an exact multiple of the shared K — so adapting
    gamma needs NO refactorization and runs every residual checkpoint
    (OSQP's adaptive rho at zero factorization cost).  Restarts are only
    needed to move the SHARED profile (base rho, row boosts).  All matvecs
    are (S, m) @ (m, n) or (S, n) @ (n, n) matmuls against shared matrices.
    ``glo``/``ghi`` bound gamma: wide for LP batches (dq2 = 0, exact at any
    gamma), clamped near 1 for QP (keeps the dq2 refinement contractive).

    ``prec``: None keeps the legacy program; a mode string runs the sweep
    matvecs at lowered matmul precision with defect/residual bookkeeping
    pinned at full f32 (solvers/precision.py).  ``allow_pallas``: permit
    the fused shared-A Pallas sweep kernel (frozen path only; callers on
    a multi-device auto-partitioned mesh must pass False — a pallas_call
    cannot be auto-partitioned).
    """
    sparse = isinstance(A, SparseA)
    if prec is None or sparse:
        # sparse: gather/segment-sum matvecs are elementwise VPU work — no
        # MXU passes to economize; only the (n, n)/block-Woodbury x-update
        # applies run lowered (via _solve_shared_K's prec)
        mv_lo, rmv_lo = _mv, _rmv
        mv_hi, rmv_hi = _mv, _rmv
    else:
        from . import precision as _precision
        mv_lo = lambda M, x: _precision.contract("sn,mn->sm", x, M, prec)
        rmv_lo = lambda M, y: _precision.contract("sm,mn->sn", y, M, prec)
        mv_hi = lambda M, x: _precision.contract(
            "sn,mn->sm", x, M, "highest")
        rmv_hi = lambda M, y: _precision.contract(
            "sm,mn->sn", y, M, "highest")
    # exact-K application for refinement: dense when K is carried, else
    # matrix-free through the (scaled) shared A — identical product, two
    # (S,m)/(S,n) matmuls instead of one (S,n)x(n,n), and no (n,n) K in
    # the factors (memory matters when several wheel cylinders coexist
    # on one chip).  Pinned full-precision under a low sweep mode: the
    # defect is the refinement's accuracy anchor.
    if K is not None:
        if prec is None:
            Kmul = lambda x: x @ K
        else:
            from . import precision as _precision
            Kmul = lambda x: _precision.contract("sn,nk->sk", x, K,
                                                 "highest")
    else:
        diagK = q2ref + rho_x + st.sigma
        Kmul = lambda x: (x * diagK[None, :]
                          + rmv_hi(A, mv_hi(A, x) * rho_a[None, :]))
    alpha = st.alpha

    # fused shared-A Pallas sweep kernel (frozen path): the whole
    # check_every block runs with A/Kinv/K VMEM-resident and genuine MXU
    # dot_generals at the sweep precision — see pallas_kernels
    from . import pallas_kernels
    from .structured_kkt import BlockWoodbury, kinv_apply
    bs_sh = None
    if (allow_pallas and not adaptive and not sparse and K is not None
            and not isinstance(Kinv, BlockWoodbury)
            and st.use_pallas is not False):
        S_all, n_all = q.shape
        bs_sh = pallas_kernels.usable_shared(S_all, A.shape[0], n_all)
    # sparse/structured engines: fused ELL sweep kernel (frozen path).
    # The structured BlockWoodbury operator participates via a densified
    # (n, n) K^-1 built ONCE per program — at kernel-eligible sizes the
    # shared matrices must fit VMEM anyway, so the structured memory
    # saving is moot and one kernel covers both engines.
    bs_sp = None
    Kinv_dense = diagK_sp = None
    if (allow_pallas and not adaptive and sparse
            and st.use_pallas is not False
            and getattr(A, "ell", None) is not None):
        S_all, n_all = q.shape
        bs_sp = pallas_kernels.usable_sparse(
            S_all, A.shape[0], n_all, A.ell.rowcols.shape[1],
            A.ell.colrows.shape[1])
        if bs_sp is not None:
            # NOTE: the densification sits outside the sweep while_loop
            # but INSIDE the solve program, so it re-runs once per
            # dispatch (n Woodbury applies) even though Kinv only changes
            # at refresh — acceptable while the kernel is the
            # TPUSPPY_PALLAS_SPARSE opt-in (n is VMEM-small there);
            # promoting the dense twin into SharedFactors is the fix if
            # this path graduates to default-on.
            Kinv_dense = (kinv_apply(Kinv, jnp.eye(n_all, dtype=q.dtype))
                          if isinstance(Kinv, BlockWoodbury) else Kinv)
            diagK_sp = (q2ref + rho_x + st.sigma)[None, :]
    kernel_prec = "highest" if prec is None else prec

    def block(x, z, zx, y, yx, Ax, gamma):
        g = gamma[:, None]
        sigma_s = g * st.sigma           # (S, 1): scaled prox parameter
        rho_a_s = g * rho_a[None, :]     # (S, m)
        rho_x_s = g * rho_x[None, :]     # (S, n)
        dq2 = q2s - g * q2ref[None, :]

        if bs_sp is not None:
            has = jnp.any(dq2 != 0).astype(x.dtype).reshape(1, 1)
            return pallas_kernels.fused_sweeps_sparse(
                q, A.ell.rowcols, A.ell.rowvals, A.ell.colrows,
                A.ell.colvals, Kinv_dense, diagK_sp, cl, cu, lb, ub,
                rho_a[None, :], rho_x[None, :], dq2, has, g,
                x, z, zx, y, yx, Ax,
                n_sweeps=max(1, st.check_every),
                n_refine=st.solve_refine, n_extra=2,
                sigma=float(st.sigma), alpha=float(alpha), bs=bs_sp,
                precision=kernel_prec)

        if bs_sh is not None:
            has = jnp.any(dq2 != 0).astype(x.dtype).reshape(1, 1)
            return pallas_kernels.fused_sweeps_shared(
                q, A, Kinv, K, cl, cu, lb, ub,
                rho_a[None, :], rho_x[None, :], dq2, has, g,
                x, z, zx, y, yx, Ax,
                n_sweeps=max(1, st.check_every),
                n_refine=st.solve_refine, n_extra=2,
                sigma=float(st.sigma), alpha=float(alpha), bs=bs_sh,
                precision=kernel_prec)

        for _ in range(max(1, st.check_every)):
            rhs = (sigma_s * x - q + rmv_lo(A, rho_a_s * z - y)
                   + (rho_x_s * zx - yx))
            xt = _solve_shared_K(Kinv, Kmul, dq2, g, rhs, st.solve_refine,
                                 prec=prec)
            Axt = mv_lo(A, xt)
            x_new = alpha * xt + (1 - alpha) * x
            Ax_new = alpha * Axt + (1 - alpha) * Ax

            za_arg = alpha * Axt + (1 - alpha) * z + y / rho_a_s
            z_new = jnp.clip(za_arg, cl, cu)
            y_new = y + rho_a_s * (alpha * Axt + (1 - alpha) * z - z_new)

            zx_arg = alpha * xt + (1 - alpha) * zx + yx / rho_x_s
            zx_new = jnp.clip(zx_arg, lb, ub)
            yx_new = yx + rho_x_s * (alpha * xt + (1 - alpha) * zx - zx_new)
            x, z, zx, y, yx, Ax = x_new, z_new, zx_new, y_new, yx_new, Ax_new
        return x, z, zx, y, yx, Ax

    def residuals(x, z, zx, y, yx, Ax):
        pri = jnp.maximum(
            jnp.max(jnp.abs(Ax - z), axis=1),
            jnp.max(jnp.abs(x - zx), axis=1),
        )
        Aty = rmv_hi(A, y)
        Pxv = q2s * x
        dua = jnp.max(jnp.abs(Pxv + q + Aty + yx), axis=1)
        prinorm = jnp.maximum(
            jnp.max(jnp.abs(Ax), axis=1), jnp.max(jnp.abs(z), axis=1))
        duanorm = jnp.maximum(
            jnp.maximum(jnp.max(jnp.abs(Pxv), axis=1),
                        jnp.max(jnp.abs(Aty), axis=1)),
            jnp.max(jnp.abs(q), axis=1))
        return pri, dua, prinorm, duanorm

    def cont(carry):
        s, _ = carry
        done = _done_mask(s.pri, s.dua, s.prinorm, s.duanorm, st)
        go = (s.k < st.max_iter) & ~jnp.all(done)
        if st.sweep_plateau_rtol > 0:
            go = go & (s.stall < 2)
        return go

    def multi_step(carry):
        s, Ax_prev = carry
        x, z, zx, y, yx, Ax = block(s.x, s.z, s.zx, s.y, s.yx, Ax_prev,
                                    s.gamma)
        Ax = mv_hi(A, x)   # re-anchor carried Ax (see admm._admm_core;
        # pinned f32 under a low sweep mode — the defect control)
        pri, dua, prinorm, duanorm = residuals(x, z, zx, y, yx, Ax)
        # Per-scenario divergence guard: unstructured random families (and
        # frozen solves whose dq2 deviation is large enough to make the
        # shared-K refinement non-contractive) can EXPLODE — iterates race
        # to inf within one checkpoint block and every later residual is
        # NaN, which poisons stop_stats and the plateau detector.  Freeze
        # exploding scenarios at their last finite iterate (the carried-in
        # Ax_prev is exactly A @ s.x from the previous re-anchor, so the
        # revert costs no extra matvec) and report INF residuals: done
        # stays False, the host sees an honest "diverged" instead of NaN,
        # and the straggler rescue / rho-restart machinery owns recovery.
        finite = (jnp.all(jnp.isfinite(x), axis=1)
                  & jnp.all(jnp.isfinite(z), axis=1)
                  & jnp.all(jnp.isfinite(zx), axis=1)
                  & jnp.all(jnp.isfinite(y), axis=1)
                  & jnp.all(jnp.isfinite(yx), axis=1))
        # negated <= so NaN residuals land in the guard set too
        bad = ~finite | ~(pri <= BIG) | ~(dua <= BIG)
        bv = bad[:, None]
        x = jnp.where(bv, s.x, x)
        z = jnp.where(bv, s.z, z)
        zx = jnp.where(bv, s.zx, zx)
        y = jnp.where(bv, s.y, y)
        yx = jnp.where(bv, s.yx, yx)
        Ax = jnp.where(bv, Ax_prev, Ax)
        inf_dt = jnp.asarray(jnp.inf, pri.dtype)
        pri = jnp.where(bad, inf_dt, pri)
        dua = jnp.where(bad, inf_dt, dua)
        prinorm = jnp.where(bad, s.prinorm, prinorm)
        duanorm = jnp.where(bad, s.duanorm, duanorm)
        # OSQP-style per-scenario adaptation on normalized residual ratios.
        # Cadence matters: adapting every checkpoint thrashes (early ratios
        # are always imbalanced and rho oscillates); every ~128 sweeps
        # matches the restart cadence that converges, at zero
        # refactorization cost.  (A faster cadence to beat the in-loop
        # plateau exit was tried and thrashes LP batches, whose free gamma
        # oscillates.  Instead, ADAPTIVE solves delay plateau-stall
        # counting past the first gamma opportunity via min_k below;
        # frozen solves, whose gamma was already adapted at refresh,
        # keep the earliest exit.)
        done = _done_mask(pri, dua, prinorm, duanorm, st)
        pri_rel = pri / jnp.maximum(prinorm, 1e-10)
        dua_rel = dua / jnp.maximum(duanorm, 1e-10)
        ratio = jnp.sqrt(
            jnp.maximum(pri_rel, 1e-12) / jnp.maximum(dua_rel, 1e-12))
        ck = max(1, st.check_every)
        period = max(1, 128 // ck)
        k_next = s.k + ck
        due = (k_next // ck) % period == 0
        move = due & ((ratio > 5.0) | (ratio < 0.2))
        gnew = jnp.clip(s.gamma * jnp.clip(ratio, 0.1, 10.0), glo, ghi)
        gamma = jnp.where(done | ~move, s.gamma, gnew)
        if st.sweep_plateau_rtol > 0:
            best, stall = _plateau_update(s, pri, dua, prinorm, duanorm,
                                          st, min_k=128 if adaptive else 0)
            # an ACTUAL gamma move changes the iteration itself: give the
            # new penalties a fresh plateau grace instead of exiting on
            # residuals produced by the OLD gamma.  (gnew clipped back to
            # its old value is a no-op and must NOT reset the grace — a
            # pinned gamma at the clip bound would otherwise defeat the
            # plateau exit forever.)
            moved = jnp.any(move & ~done & (gnew != s.gamma))
            stall = jnp.where(moved, 0, stall)
            best = jnp.where(moved, jnp.asarray(jnp.inf, best.dtype), best)
        else:
            best, stall = s.best, s.stall
        return (_IterState(x, z, zx, y, yx, gamma, pri, dua, prinorm,
                           duanorm, s.k + max(1, st.check_every),
                           best, stall), Ax)

    Ax0 = _mv(A, state.x)
    state, _ = jax.lax.while_loop(cont, multi_step, (state, Ax0))
    return state


def _prep_shared(c, q2, A, cl, cu, lb, ub, settings, want_masks=True):
    """``want_masks=False`` skips the mask reductions (several (S, m)/(S, n)
    jnp.all's) for the frozen path, which never reads them — inside a fused
    multi-iteration scan they would otherwise run once per PH iteration."""
    dt = settings.jdtype()
    c, q2 = jnp.asarray(c, dt), jnp.asarray(q2, dt)
    A = A.astype(dt) if isinstance(A, SparseA) else jnp.asarray(A, dt)
    cl, cu = _clean_bounds(jnp.asarray(cl, dt), jnp.asarray(cu, dt))
    lb, ub = _clean_bounds(jnp.asarray(lb, dt), jnp.asarray(ub, dt))
    if not want_masks:
        return c, q2, A, cl, cu, lb, ub, None
    masks = _Masks(
        fin_cl=cl > -BIG / 2, fin_cu=cu < BIG / 2,
        fin_lb=lb > -BIG / 2, fin_ub=ub < BIG / 2,
        # shared row/column penalty classes: a row is boosted only when it is
        # an equality in EVERY scenario (families share structure, so in
        # practice these are uniform; a non-uniform row just loses the boost,
        # never correctness)
        eq=jnp.all(jnp.abs(cu - cl) < 1e-10, axis=0),
        loose=jnp.all((cl <= -BIG / 2) & (cu >= BIG / 2), axis=0),
        eqx=jnp.all(jnp.abs(ub - lb) < 1e-10, axis=0),
    )
    return c, q2, A, cl, cu, lb, ub, masks


def _scale_shared(c, q2, A, cl, cu, lb, ub, D, E, cost, warm, dt):
    As = A.scale(E, D) if isinstance(A, SparseA) else (
        A * E[:, None] * D[None, :])
    q2s = q2 * (D * D)[None, :] * cost
    qs = c * D[None, :] * cost
    cls, cus = cl * E[None, :], cu * E[None, :]
    lbs, ubs = lb / D[None, :], ub / D[None, :]
    if warm is not None:
        x0, z0, y0, yx0 = warm
        warm = (
            jnp.asarray(x0, dt) / D[None, :],
            jnp.asarray(z0, dt) * E[None, :],
            jnp.asarray(y0, dt) / E[None, :] * cost,
            jnp.asarray(yx0, dt) * D[None, :] * cost,
        )
    return qs, q2s, As, cls, cus, lbs, ubs, warm


def _solve_shared_impl(c, q2, A, cl, cu, lb, ub, settings, warm,
                       want_factors=False):
    # TRACE-time counter (wrappers are jitted; this body runs only while
    # XLA builds the program): one per adaptive shared-A program compiled
    _metrics.inc("shared_admm.adaptive_programs")
    dt = settings.jdtype()
    c, q2, A, cl, cu, lb, ub, masks = _prep_shared(
        c, q2, A, cl, cu, lb, ub, settings)
    S, n = c.shape
    m = A.shape[0]

    q2ref_raw = jnp.mean(q2, axis=0)
    D, E = _ruiz_shared(A, q2ref_raw, settings.scaling_iters)
    # shared scalar objective scaling (median scenario magnitude): scenarios
    # in a family have comparable cost scales, and a shared scalar keeps the
    # scaled q2 — hence the K — shared
    cost = 1.0 / jnp.maximum(
        jnp.median(jnp.max(jnp.abs(c * D[None, :]), axis=1)), 1e-8)
    qs, q2s, As, cls, cus, lbs, ubs, warm = _scale_shared(
        c, q2, A, cl, cu, lb, ub, D, E, cost, warm, dt)
    q2ref = jnp.mean(q2s, axis=0)

    st = settings
    eq, loose, eqx = masks.eq, masks.loose, masks.eqx

    def rho_vec(base):
        r = jnp.where(eq, base * st.rho_eq_scale, base)
        return jnp.where(loose, st.rho_min, r)

    def rho_x_vec(base):
        return jnp.where(eqx, base * st.rho_eq_scale,
                         jnp.full((n,), base, dt))

    if warm is None:
        x0 = jnp.zeros((S, n), dt)
        z0 = jnp.clip(jnp.zeros((S, m), dt), cls, cus)
        y0 = jnp.zeros((S, m), dt)
        yx0 = jnp.zeros((S, n), dt)
    else:
        x0, z0, y0, yx0 = warm
    zx0 = jnp.clip(x0, lbs, ubs)
    inf = jnp.full((S,), jnp.inf, dt)
    one = jnp.ones((S,), dt)
    state0 = _IterState(x0, z0, zx0, y0, yx0, jnp.ones((S,), dt),
                        inf, inf, one, one, jnp.zeros((), jnp.int32),
                        jnp.asarray(jnp.inf, dt),
                        jnp.zeros((), jnp.int32))

    # Per-scenario gamma runs FREE for (near-)LP batches: dq2 = 0 there, so
    # the shared inverse solves every scenario's x-update exactly at any
    # gamma.  Significant q2 (PH prox solves) clamps gamma near 1 to keep
    # the dq2 = q2(1-gamma) refinement contractive (radius <= |1-gamma|/
    # gamma) — prox solves are strongly convex and need little adaptation.
    lp_like = jnp.max(jnp.abs(q2s)) < 1e-12
    glo = jnp.where(lp_like, 1e-4, 0.6)
    ghi = jnp.where(lp_like, 1e4, 1.8)

    def restart(carry, _):
        state, base, total, mult, multx = carry[:5]
        rho_a = rho_vec(base)
        rho_x = rho_x_vec(base)
        if st.rho_row_adapt:
            rho_a = jnp.minimum(rho_a * mult, st.rho_row_max)
            rho_x = jnp.minimum(rho_x * multx, st.rho_row_max)
        Kinv, K = _factor_shared(q2ref, As, rho_a, rho_x, st.sigma)
        state = _core(qs, q2s, q2ref, As, cls, cus, lbs, ubs,
                      state._replace(k=jnp.zeros((), jnp.int32),
                                     best=jnp.asarray(jnp.inf, dt),
                                     stall=jnp.zeros((), jnp.int32)),
                      Kinv, K, rho_a, rho_x, glo, ghi, st, adaptive=True)
        total = total + state.k
        done = _done_mask(state.pri, state.dua, state.prinorm,
                          state.duanorm, st)
        eps_pri = st.eps_abs + st.eps_rel * jnp.maximum(state.prinorm, 1.0)
        pri_rel = state.pri / jnp.maximum(state.prinorm, 1e-10)
        dua_rel = state.dua / jnp.maximum(state.duanorm, 1e-10)
        ratio = jnp.sqrt(
            jnp.maximum(pri_rel, 1e-12) / jnp.maximum(dua_rel, 1e-12))
        # shared base: adapt on the geometric-mean ratio of UNCONVERGED
        # scenarios (converged ones would anchor the ratio at its stale
        # value); per-scenario adaptation lives in-loop via gamma.
        # Diverged scenarios (inf residuals from the in-loop guard) have a
        # NaN ratio and are EXCLUDED — one exploding scenario must not
        # poison the shared base for the whole batch.
        ok = jnp.isfinite(ratio)
        logr = jnp.where(done | ~ok, 0.0,
                         jnp.log(jnp.clip(ratio, 0.1, 10.0)))
        denom = jnp.maximum(jnp.sum(~done & ok), 1)
        gmean = jnp.exp(jnp.sum(logr) / denom)
        base = jnp.where(jnp.all(done), base,
                         jnp.clip(base * gmean, st.rho_min, st.rho_max))
        if st.rho_row_adapt:
            stuck = (state.pri > 100.0 * eps_pri)[:, None]
            gate = jnp.maximum(0.3 * state.pri, 10.0 * eps_pri)[:, None]
            Ax = _mv(As, state.x)
            viol = jnp.maximum(cls - Ax, Ax - cus)
            hit = jnp.any(stuck & (viol > gate), axis=0)       # max over S
            mult = jnp.where(hit, mult * st.rho_row_boost, mult)
            violx = jnp.maximum(lbs - state.x, state.x - ubs)
            hitx = jnp.any(stuck & (violx > gate), axis=0)
            multx = jnp.where(hitx, multx * st.rho_row_boost, multx)
        return (state, base, total, mult, multx,
                rho_a, rho_x, Kinv, K), None

    # (Kinv, K) carry placeholders must match the factorization regime's
    # pytree structure (lax.scan carries are structure-invariant): dense
    # (n, n) pair for a dense A, (dense, None) for unstructured sparse,
    # (BlockWoodbury, None) for the structured path
    if isinstance(As, SparseA):
        if As.structure is not None:
            zKinv = zero_factors(As.structure, n, dt)
        else:
            zKinv = jnp.zeros((n, n), dt)
        zK = None
    else:
        zKinv = jnp.zeros((n, n), dt)
        zK = zKinv
    carry0 = (state0, jnp.asarray(st.rho, dt), jnp.zeros((), jnp.int32),
              jnp.ones((m,), dt), jnp.ones((n,), dt),
              jnp.zeros((m,), dt), jnp.zeros((n,), dt), zKinv, zK)
    (state, _, total, _, _, rho_a, rho_x, Kinv, K), _ = jax.lax.scan(
        restart, carry0, None, length=st.restarts)
    gamma = state.gamma

    def unscale(s):
        return (s.x * D[None, :], s.z / E[None, :],
                s.y * E[None, :] / cost, s.yx / D[None, :] / cost)

    x, z, y, yx = unscale(state)
    sol = BatchSolution(
        x=x, z=z, y=y, yx=yx,
        pri_res=state.pri, dua_res=state.dua,
        iters=jnp.broadcast_to(total, (S,)),
        done=_done_mask(state.pri, state.dua, state.prinorm,
                        state.duanorm, st),
        raw=(x, z, y, yx),
    )
    if want_factors:
        return sol, SharedFactors(D=D, E=E, cost=cost, rho_a=rho_a,
                                  rho_x=rho_x, gamma=gamma, Kinv=Kinv,
                                  K=K if st.factors_keep_K else None,
                                  q2ref=q2ref)
    return sol


def _solve_shared_frozen_impl(c, q2, A, cl, cu, lb, ub,
                              factors: SharedFactors, warm, settings,
                              allow_pallas=False):
    """Sweep-only shared solve reusing a refresh's :class:`SharedFactors`.
    Valid while (A, bounds structure) are unchanged; per-scenario q2 drift is
    absorbed by the refinement against K + diag(dq2).

    ``settings.sweep_precision`` routes this solve through the
    mixed-precision fast path: a lowered-precision sweep phase (f32-pinned
    residual bookkeeping) followed, when not eps-converged, by a bounded
    full-precision refinement phase on the same factors.  ``allow_pallas``
    permits the fused shared-A Pallas kernel (single-controller callers
    only — a pallas_call cannot be auto-partitioned over a mesh)."""
    # TRACE-time counter: one per frozen shared-A program compiled
    _metrics.inc("shared_admm.frozen_programs")
    dt = settings.jdtype()
    c, q2, A, cl, cu, lb, ub, _ = _prep_shared(
        c, q2, A, cl, cu, lb, ub, settings, want_masks=False)
    D, E, cost = factors.D, factors.E, factors.cost
    qs, q2s, As, cls, cus, lbs, ubs, warm = _scale_shared(
        c, q2, A, cl, cu, lb, ub, D, E, cost, warm, dt)
    S, n = c.shape
    m = A.shape[0]
    if warm is None:
        x0 = jnp.zeros((S, n), dt)
        z0 = jnp.clip(jnp.zeros((S, m), dt), cls, cus)
        y0 = jnp.zeros((S, m), dt)
        yx0 = jnp.zeros((S, n), dt)
    else:
        x0, z0, y0, yx0 = warm
    zx0 = jnp.clip(x0, lbs, ubs)
    inf = jnp.full((S,), jnp.inf, dt)
    one = jnp.ones((S,), dt)
    state0 = _IterState(x0, z0, zx0, y0, yx0, factors.gamma,
                        inf, inf, one, one, jnp.zeros((), jnp.int32),
                        jnp.asarray(jnp.inf, dt),
                        jnp.zeros((), jnp.int32))

    lp_like = jnp.max(jnp.abs(q2s)) < 1e-12
    glo = jnp.where(lp_like, 1e-4, 0.6)
    ghi = jnp.where(lp_like, 1e4, 1.8)
    def run_core(st0, st, prec):
        return _core(qs, q2s, factors.q2ref, As, cls, cus, lbs, ubs, st0,
                     factors.Kinv, factors.K, factors.rho_a,
                     factors.rho_x, glo, ghi, st, prec=prec,
                     allow_pallas=allow_pallas)

    state = _frozen_sweep_phases(run_core, state0, settings, dt)
    x, z, y, yx = (state.x * D[None, :], state.z / E[None, :],
                   state.y * E[None, :] / cost,
                   state.yx / D[None, :] / cost)
    return BatchSolution(
        x=x, z=z, y=y, yx=yx,
        pri_res=state.pri, dua_res=state.dua,
        iters=jnp.broadcast_to(state.k, (S,)),
        done=_done_mask(state.pri, state.dua, state.prinorm,
                        state.duanorm, settings),
        raw=(x, z, y, yx),
    )


@functools.partial(jax.jit, static_argnames=("settings",))
def solve_shared(c, q2, A, cl, cu, lb, ub,
                 settings: ADMMSettings = ADMMSettings(),
                 warm=None) -> BatchSolution:
    """Solve a shared-A batch: A is (m, n); everything else (S, ...)."""
    with jax.default_matmul_precision(settings.matmul_precision):
        return _solve_shared_impl(c, q2, A, cl, cu, lb, ub, settings, warm)


# AOT executable cache (tpusppy/solvers/aot.py): same warm-start wrapping
# as the dense entry points in admm.py — passthrough when disarmed
solve_shared = _aot.cached_program(solve_shared, "shared.solve",
                                   static_names=("settings",))


@functools.partial(jax.jit, static_argnames=("settings",))
def solve_shared_factored(c, q2, A, cl, cu, lb, ub,
                          settings: ADMMSettings = ADMMSettings(),
                          warm=None):
    """Adaptive shared-A solve that also returns :class:`SharedFactors`."""
    with jax.default_matmul_precision(settings.matmul_precision):
        return _solve_shared_impl(c, q2, A, cl, cu, lb, ub, settings, warm,
                                  want_factors=True)


solve_shared_factored = _aot.cached_program(
    solve_shared_factored, "shared.solve_factored",
    static_names=("settings",))


@functools.partial(jax.jit, static_argnames=("settings",))
def solve_shared_frozen(c, q2, A, cl, cu, lb, ub, factors: SharedFactors,
                        settings: ADMMSettings = ADMMSettings(),
                        warm=None) -> BatchSolution:
    """Jitted frozen-factor shared-A solve (single-controller host path:
    the fused shared-A Pallas kernel is permitted)."""
    with jax.default_matmul_precision(settings.matmul_precision):
        return _solve_shared_frozen_impl(c, q2, A, cl, cu, lb, ub, factors,
                                         warm, settings, allow_pallas=True)


solve_shared_frozen = _aot.cached_program(
    solve_shared_frozen, "shared.solve_frozen",
    static_names=("settings",))
