"""AOT executable cache: serialized XLA programs, content-addressed on disk.

ROADMAP item 3 ("kill the cold start").  The hot jitted programs — the
fused PH step and wheel megakernel (:mod:`tpusppy.parallel.sharded`), the
frozen/refresh batch solves behind ``spopt._solve_amortized``
(:mod:`.admm` / :mod:`.shared_admm`), and the packed-measurement/stats
programs — are compiled once per (shape, settings, mesh, toolchain) and
then recompiled from scratch by EVERY process that touches them: every
resume, every ladder rung, every ``dist_wheel`` controller pays the full
XLA lower+compile again (UC ~17 s, farmer ~3.5 s per process —
BENCH_r05/r06 ``compile_iter0_s``).  This module persists the compiled
executables themselves (``jax.jit(...).lower().compile()`` serialized via
:mod:`jax.experimental.serialize_executable`) in a content-addressed
on-disk cache, so a repeated, resumed, or ladder-sibling run skips XLA
entirely and reaches its first PH iteration in milliseconds.

Usage: wrap a jitted function once at build time::

    fused = aot.cached_program(fused, "ph_fused", key_extra=(settings, ...))

The wrapper is a strict passthrough while the cache is disarmed (no
``TPUSPPY_AOT_CACHE`` / :func:`set_cache_path`) or when called under a
trace (nested jit), so cold-path behavior is bitwise-identical to the
plain jitted call.  Armed, each call signature (leaf avals + static
kwargs + ``key_extra`` + jax/jaxlib/platform) maps to one key; the first
call either deserializes ``<dir>/<key>.aotx`` ("aot.load" span,
``aot.hits``) or lower+compiles ("aot.compile" span, ``aot.misses``) and
serializes the result atomically.  Donation semantics ride the
executable (a loaded program donates exactly like its jit twin — tests
pin this).

Keying: the cache key hashes the SAME shape+settings+mesh parts the
autotuner's verdict store uses (:func:`family_parts` — tune's key builder
delegates here so the two caches can never silently drift), the
program-specific extras, and the toolchain fingerprint (jax + jaxlib
versions, backend platform, device count).  A toolchain bump therefore
changes every key — old files are simply never read again (and a
belt-and-braces in-file version guard rejects foreign payloads that were
renamed into place).  Corrupted/truncated files deserialize-fail into a
clean miss-and-recompile, never a crash and never a stale hit.

Fallback tier: arming this cache also points JAX's persistent
compilation cache (``jax_compilation_cache_dir``) at ``<dir>/xla`` when
the process hasn't configured one, so programs nobody explicitly wrapped
still compile warm from the disk cache (they re-pay tracing, not XLA).

Scope: single-controller processes only (``jax.process_count() == 1``) —
a multi-controller mesh's executables embed global device assignments
this loader does not reconstruct.  See doc/autotuner.md ("Cold start")
and doc/observability.md for the ``aot.*`` counter taxonomy.
"""

from __future__ import annotations

import contextlib
import hashlib
import os
import pickle
import re
import tempfile
import threading
import time

import numpy as np

from ..obs import metrics as _metrics
from ..obs import trace as _trace
from ..obs.log import get_logger

_log = get_logger("aot")

#: In-file payload format version (independent of the key hash — guards
#: files renamed/copied into place from a foreign build).
_FORMAT_VERSION = 1

#: Cap for :func:`prewarm` with ``keys=None`` (newest-first): loading a
#: whole long-lived cache directory eagerly would burn startup time on
#: programs this process may never call.
PREWARM_MAX_FILES = 64

_CTR_HITS = _metrics.counter("aot.hits")
_CTR_MISSES = _metrics.counter("aot.misses")
_CTR_LOAD_ERRORS = _metrics.counter("aot.load_errors")
_CTR_SERIALIZE_ERRORS = _metrics.counter("aot.serialize_errors")
_CTR_UNSERIALIZABLE = _metrics.counter("aot.unserializable")
_CTR_QUARANTINED = _metrics.counter("aot.quarantined")
_CTR_PREWARMED = _metrics.counter("aot.prewarmed")
_HIST_COMPILE_S = _metrics.histogram("aot.compile_s")
_HIST_SERIALIZE_S = _metrics.histogram("aot.serialize_s")
_HIST_DESERIALIZE_S = _metrics.histogram("aot.deserialize_s")

_lock = threading.Lock()
# ONE process-wide lock around every deserialize AND aot-initiated
# compile: this jaxlib's XLA:CPU `deserialize_executable` races in-flight
# compilation (observed as "INTERNAL: Symbols not found" in one
# interleaving and a hard segfault in another, reproduced under the
# 3-cylinder wheel's concurrent warm start).  Serializing aot's own XLA
# work removes the aot-vs-aot interleavings; the wheel spinner closes the
# remaining aot-load-vs-plain-jit-compile window by prewarming the cache
# BEFORE its cylinder threads start.
_xla_work_lock = threading.RLock()
_cache_path_override: str | None = None
_loaded: dict = {}            # key -> loaded jax Compiled
_session_keys: list = []      # keys compiled-or-loaded, insertion order
_fallback_armed_for: str | None = None


# ---------------------------------------------------------------------------
# Cache location (the tune-cache scoping discipline: programmatic override
# first, then the env knob; tests use set_cache_path so no env leaks).
# ---------------------------------------------------------------------------
def set_cache_path(path: str | None):
    """Programmatic override of the TPUSPPY_AOT_CACHE knob — scoped to
    this process, the same contract as :func:`tpusppy.tune.set_cache_path`
    (tests must never leak cache state via env vars)."""
    global _cache_path_override
    _cache_path_override = str(path) if path else None


def cache_path() -> str | None:
    """The armed executable-cache DIRECTORY (programmatic override first,
    then ``TPUSPPY_AOT_CACHE``; empty/unset disables the cache entirely —
    every wrapped program then calls its plain jit twin)."""
    return (_cache_path_override
            or os.environ.get("TPUSPPY_AOT_CACHE") or None)


def enabled() -> bool:
    """Cache armed AND usable from this process (single-controller only:
    multi-controller executables embed global device assignments)."""
    if cache_path() is None:
        return False
    return not _multiprocess()


_multiprocess_memo: bool | None = None


def _multiprocess() -> bool:
    # memoized: enabled() sits on every wrapped call, and process count
    # never changes after backend init (reset() clears the memo)
    global _multiprocess_memo
    if _multiprocess_memo is None:
        try:
            import jax

            _multiprocess_memo = jax.process_count() > 1
        except Exception:
            return False
    return _multiprocess_memo


def reset():
    """Drop every in-memory executable and the path override (test
    isolation; on-disk files are untouched)."""
    global _cache_path_override, _fallback_armed_for, _multiprocess_memo
    with _lock:
        _loaded.clear()
        _session_keys.clear()
    _cache_path_override = None
    _fallback_armed_for = None
    _multiprocess_memo = None


def _ensure_fallback_cache(d: str):
    """Arm JAX's persistent compilation cache at ``<dir>/xla`` as the
    fallback tier for programs not explicitly AOT-wrapped — only when the
    process hasn't already configured one (an operator's cache dir always
    wins)."""
    global _fallback_armed_for
    if _fallback_armed_for == d:
        return
    _fallback_armed_for = d
    if os.environ.get("JAX_COMPILATION_CACHE_DIR"):
        return
    try:
        import jax

        if getattr(jax.config, "jax_compilation_cache_dir", None):
            return
        jax.config.update("jax_compilation_cache_dir",
                          os.path.join(d, "xla"))
    except Exception as e:       # never let the fallback tier break a run
        _log.warning("could not arm the jax compilation cache: %r", e)


# ---------------------------------------------------------------------------
# Keys.  family_parts is THE shared shape+settings+mesh key builder: the
# autotuner's verdict keys (tune._tune_key) start with exactly this tuple,
# so tune-cache keys and executable-cache keys cannot silently drift.
# ---------------------------------------------------------------------------
def family_parts(arr, settings, mesh, axis) -> tuple:
    """(c.shape, cl.shape, A-kind, settings, n_devices, axis) — the common
    prefix of every cache key derived from one problem family."""
    ndev = 1 if mesh is None else len(mesh.devices.flat)
    return (arr.c.shape, arr.cl.shape,
            arr.A.ndim if hasattr(arr.A, "ndim") else "sparse",
            settings, ndev, axis)


def shape_family_parts(S, n, m, settings=None, a_kind="?", ndev=1,
                       axis="scen") -> tuple:
    """:func:`family_parts` for callers that know only the (S, n, m)
    shape — SAME tuple structure and field order, so keys built from a
    bare shape (the tune megastep verdicts) can never silently drift
    from keys built from real arrays (drift guard in tests/test_tune).
    ``a_kind`` stays the wildcard ``"?"`` when the engine is not part of
    the caller's identity."""
    return ((int(S), int(n)), (int(S), int(m)), a_kind, settings,
            int(ndev), axis)


def _versions() -> tuple:
    """Toolchain fingerprint every key embeds: executable serialization is
    where jax/jaxlib drift bites first, and a deserialized program must
    only ever run on the toolchain+backend that built it."""
    try:
        import jax
        import jaxlib

        plat = "?"
        with contextlib.suppress(Exception):
            plat = jax.devices()[0].platform
        return (str(jax.__version__), str(jaxlib.__version__), plat)
    except ImportError:
        return ("none", "none", "none")


def mesh_fingerprint(mesh) -> tuple | None:
    """Key part for a mesh: axis names + shape (device COUNT rides the
    toolchain fingerprint's platform and the executable's own device
    assignment)."""
    if mesh is None:
        return None
    return (tuple(mesh.axis_names), tuple(mesh.devices.shape))


def array_digest(a) -> str:
    """Digest of a small host array baked into a program as a constant
    (e.g. ``nonant_idx``): programs differing only in such constants MUST
    key differently."""
    a = np.ascontiguousarray(np.asarray(a))
    return hashlib.sha1(
        repr((a.shape, str(a.dtype))).encode() + a.tobytes()).hexdigest()


def _leaf_sig(leaf):
    from jax.api_util import shaped_abstractify

    aval = shaped_abstractify(leaf)
    return (tuple(aval.shape), str(aval.dtype),
            bool(getattr(aval, "weak_type", False)))


def program_key(kind: str, sig, key_extra) -> str:
    """``<kind>.<digest>`` — the cache filename stem.  ``sig`` is the
    call-signature tuple (treedef + leaf avals), ``key_extra`` the
    build-time identity (settings, cadence, constant digests, ...)."""
    blob = repr((kind, sig, key_extra, _versions())).encode()
    return f"{kind}.{hashlib.sha1(blob).hexdigest()[:20]}"


# ---------------------------------------------------------------------------
# Serialization safety.  XLA:CPU custom-call targets that reference
# runtime symbols by RAW POINTER (the LAPACK FFI kernels — potrf/getrf/
# trsm behind cholesky/lu/triangular_solve) do NOT survive cross-process
# executable deserialization on this toolchain: loading them in a fresh
# process segfaults (reproduced: a jitted `jnp.linalg.cholesky` roundtrip
# dies; pure matmul/while_loop programs — the frozen sweeps, the wheel
# megastep, the packed measurements — roundtrip bit-exact).  So a program
# whose LOWERED module carries any custom_call target outside the
# by-value allowlist below is compiled and used in-memory but NEVER
# persisted (``aot.unserializable``); its recompiles ride the jax
# persistent-compilation-cache fallback tier instead, which handles these
# kernels correctly.  On TPU, cholesky lowers natively (no LAPACK custom
# call), so the adaptive/refresh programs persist there — exactly where
# the UC ~17 s cold start lives.
# ---------------------------------------------------------------------------
#: Custom-call targets serialized BY VALUE (payload/attribute-carried),
#: safe to persist: sharding markers and the Pallas/Mosaic TPU kernels.
SAFE_CUSTOM_CALLS = frozenset({
    "Sharding", "SPMDFullToShardShape", "SPMDShardToFullShape",
    "shape_assertion", "annotate_device_placement", "tpu_custom_call",
})

# all three spellings a custom call prints under: pretty stablehlo
# (`custom_call @target`), the generic MLIR attribute form
# (`call_target_name = "target"`), and classic HLO text
# (`custom_call_target="target"`) — missing one would classify a LAPACK
# program serialize-safe and persist an artifact that segfaults the next
# process's load
_CUSTOM_CALL_RE = re.compile(
    r'custom_call\s+@([\w.$-]+)'
    r'|custom_call_target\s*=\s*"([^"]+)"'
    r'|call_target_name\s*=\s*"([^"]+)"')


def _custom_call_targets(lowered_text: str) -> set:
    return {a or b or c for a, b, c in _CUSTOM_CALL_RE.findall(lowered_text)}


def serialize_safe(lowered) -> tuple[bool, set]:
    """(safe, offending-targets) for one lowered program."""
    try:
        targets = _custom_call_targets(lowered.as_text())
    except Exception:
        return False, set()
    unsafe = targets - SAFE_CUSTOM_CALLS
    return not unsafe, unsafe


# ---------------------------------------------------------------------------
# Disk format: pickle of {"v", "jax", "jaxlib", "platform", "payload"}
# where payload is jax.experimental.serialize_executable.serialize(...).
# Writes are atomic (tempfile + os.replace) so a kill mid-write can never
# leave a torn file; a torn/foreign file is just a cold cache.
# ---------------------------------------------------------------------------
def _entry_path(key: str) -> str:
    return os.path.join(cache_path(), key + ".aotx")


def _quarantine_path(key: str) -> str:
    """Marker for keys whose artifact FAILED to load once: this
    toolchain's CPU executable loader deterministically refuses some
    artifacts (symbol-name drift when the serializing process had
    compiled other programs first — "Symbols not found"), and a
    re-serialized replacement from the same process is usually just as
    unloadable.  The marker stops the probe/fail/rewrite churn: the key
    lives on the jax-cache fallback tier until a toolchain bump renames
    it (keys embed the versions)."""
    return os.path.join(cache_path(), key + ".aotx.bad")


def _atomic_write_bytes(path: str, blob: bytes):
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    # suffix must NOT be ".aotx": prewarm's directory sweep would treat a
    # concurrent writer's half-written temp file as a real entry, fail to
    # load it, delete it out from under the writer and quarantine junk
    fd, tmp = tempfile.mkstemp(prefix=".aot_tmp_", suffix=".tmp", dir=d)
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        with contextlib.suppress(OSError):
            os.remove(tmp)
        raise


def _write_index_entry(key: str, kind: str):
    """Best-effort human-readable sidecar (one ``index.json`` per cache
    dir) via the engine-wide atomic-JSON helper — inspection + debugging,
    never read on the hot path.  Last-writer-wins across processes, like
    the tune cache."""
    try:
        from ..resilience.checkpoint import atomic_write_json

        path = os.path.join(cache_path(), "index.json")
        idx = {}
        if os.path.exists(path):
            import json

            with contextlib.suppress(OSError, ValueError):
                with open(path) as f:
                    idx = json.load(f)
        jv, jlv, plat = _versions()
        idx[key] = {"kind": kind, "jax": jv, "jaxlib": jlv,
                    "platform": plat, "created": time.time()}
        atomic_write_json(path, idx)
    except Exception:            # the index is advisory only
        pass


def _serialize_to_disk(key: str, kind: str, compiled):
    from jax.experimental import serialize_executable as _se

    if os.path.exists(_quarantine_path(key)):
        _CTR_QUARANTINED.inc(1)
        return
    t0 = time.perf_counter()
    try:
        payload = _se.serialize(compiled)
        jv, jlv, plat = _versions()
        blob = pickle.dumps({"v": _FORMAT_VERSION, "jax": jv,
                             "jaxlib": jlv, "platform": plat,
                             "payload": payload})
        _atomic_write_bytes(_entry_path(key), blob)
    except Exception as e:
        # an unserializable program (or a read-only/full cache dir) must
        # cost nothing but the warm-start: the compiled executable is
        # already in memory and the run proceeds normally
        _CTR_SERIALIZE_ERRORS.inc(1)
        _log.warning("executable serialize failed for %s: %r", key, e)
        return
    _HIST_SERIALIZE_S.add(time.perf_counter() - t0)
    _write_index_entry(key, kind)


def _deserialize_from_disk(key: str):
    """Loaded executable, or None on ANY failure (missing, torn,
    truncated, foreign toolchain) — a clean miss, never a crash."""
    path = _entry_path(key)
    if not os.path.exists(path):
        return None
    if os.path.exists(_quarantine_path(key)):
        _CTR_QUARANTINED.inc(1)
        return None
    from jax.experimental import serialize_executable as _se

    t0 = time.perf_counter()
    try:
        with open(path, "rb") as f:
            blob = f.read()
    except OSError:
        # transient read failure (EINTR, permission race, NFS hiccup):
        # a plain miss — the artifact may be perfectly healthy, so it
        # must NOT be deleted or quarantined
        _CTR_LOAD_ERRORS.inc(1)
        return None
    try:
        obj = pickle.loads(blob)
        jv, jlv, plat = _versions()
        if (obj.get("v") != _FORMAT_VERSION or obj.get("jax") != jv
                or obj.get("jaxlib") != jlv or obj.get("platform") != plat):
            # keys embed the toolchain, so this only triggers on files
            # renamed/copied into place — still just a miss
            return None
        exe = _se.deserialize_and_load(*obj["payload"])
    except Exception as e:
        # the ARTIFACT itself is bad (torn pickle, or this toolchain's
        # deterministic "Symbols not found" refusals): quarantine so no
        # process re-pays the failed load or re-banks a twin
        _CTR_LOAD_ERRORS.inc(1)
        _log.warning("executable cache entry %s unreadable (%r) — "
                     "recompiling; key quarantined to the jax-cache "
                     "tier", key, e)
        with contextlib.suppress(OSError):
            os.remove(path)      # don't re-pay the failed read next run
        with contextlib.suppress(OSError):   # see _quarantine_path
            with open(_quarantine_path(key), "w") as f:
                f.write(repr(e)[:500])
        return None
    _HIST_DESERIALIZE_S.add(time.perf_counter() - t0)
    return exe


# ---------------------------------------------------------------------------
# The wrapper.
# ---------------------------------------------------------------------------
class CachedProgram:
    """AOT-cache-aware twin of one jitted function.

    Disabled cache (or a call under an outer trace): a strict passthrough
    to the jitted function.  Enabled: each distinct call signature
    resolves to one serialized executable — deserialized from disk when
    present, else lower+compiled and persisted — and the call dispatches
    the executable directly (no retracing).  Static kwargs
    (``static_names``) join the key and are stripped from the executable
    call, matching ``Compiled``'s calling convention.
    """

    __slots__ = ("_jitted", "kind", "_key_extra", "_static_names",
                 "_sig_keys", "_lock")

    def __init__(self, jitted, kind: str, key_extra=(), static_names=()):
        self._jitted = jitted
        self.kind = str(kind)
        self._key_extra = repr(key_extra)
        self._static_names = tuple(static_names)
        self._sig_keys: dict = {}      # sig -> key (memo)
        self._lock = threading.Lock()

    def __call__(self, *args, **kwargs):
        if not enabled():
            return self._jitted(*args, **kwargs)
        statics = {k: kwargs[k] for k in self._static_names if k in kwargs}
        dyn_kwargs = {k: v for k, v in kwargs.items() if k not in statics}
        import jax

        leaves, treedef = jax.tree_util.tree_flatten((args, dyn_kwargs))
        # FAST dispatch memo: this wrapper sits on the steady-state hot
        # path (one frozen solve / megastep per wheel window), so the
        # per-call key must not pay shaped_abstractify + str(treedef) +
        # static reprs every time.  The memo key uses cheap hashables —
        # jax Arrays' cached .aval, numpy metadata, python scalar types,
        # and the (frozen, value-hashable) static objects themselves —
        # and is at least as discriminating as the canonical signature,
        # which is still what the on-disk key digests (memo-miss path),
        # so cross-process keys stay deterministic.
        try:
            metas = []
            for leaf in leaves:
                if isinstance(leaf, jax.core.Tracer):
                    # nested under an outer trace: inline like jit
                    return self._jitted(*args, **kwargs)
                if isinstance(leaf, jax.Array):
                    metas.append(leaf.aval)
                elif isinstance(leaf, np.ndarray):
                    metas.append(("np", leaf.shape, leaf.dtype.str))
                else:
                    metas.append(("py", type(leaf)))
            memo_key = (treedef, tuple(metas),
                        tuple(sorted(statics.items())))
            key = self._sig_keys.get(memo_key)
        except Exception:
            # unhashable static / exotic leaf: never block the solve
            # over a cache key
            return self._jitted(*args, **kwargs)
        if key is None:
            try:
                sig = (str(treedef),
                       tuple(_leaf_sig(leaf) for leaf in leaves),
                       tuple(sorted((k, repr(v))
                                    for k, v in statics.items())))
            except Exception:
                return self._jitted(*args, **kwargs)
            key = program_key(self.kind, sig, self._key_extra)
            self._sig_keys[memo_key] = key
        exe = _loaded.get(key)
        if exe is None:
            exe = self._resolve(key, args, kwargs)
        return exe(*args, **dyn_kwargs)

    def _resolve(self, key: str, args, kwargs):
        with self._lock:
            exe = _loaded.get(key)
            if exe is not None:
                return exe
            _ensure_fallback_cache(cache_path())
            with _xla_work_lock, _trace.span("compile", "aot.load"):
                exe = _deserialize_from_disk(key)
            if exe is not None:
                _CTR_HITS.inc(1)
                if _trace.enabled():
                    _trace.instant("compile", "aot.hit", key=key,
                                   kind=self.kind)
            else:
                _CTR_MISSES.inc(1)
                t0 = time.perf_counter()
                with _xla_work_lock, \
                        _trace.span("compile", "aot.compile") as _sp:
                    lowered = self._jitted.lower(*args, **kwargs)
                    safe, offending = serialize_safe(lowered)
                    exe = lowered.compile()
                    if _trace.enabled():
                        _sp.add(key=key, kind=self.kind)
                _HIST_COMPILE_S.add(time.perf_counter() - t0)
                if safe:
                    _serialize_to_disk(key, self.kind, exe)
                else:
                    # by-pointer custom calls (see SAFE_CUSTOM_CALLS):
                    # persisting would segfault the NEXT process's load —
                    # leave this program to the jax-cache fallback tier
                    _CTR_UNSERIALIZABLE.inc(1)
                    _log.info(
                        "%s not persisted (by-pointer custom calls: %s) — "
                        "recompiles ride the jax compilation cache",
                        key, sorted(offending) or "unscannable")
            with _lock:
                _loaded[key] = exe
                _session_keys.append(key)
            return exe


def cached_program(jitted, kind: str, key_extra=(), static_names=()):
    """Wrap a jitted function with the executable cache (see
    :class:`CachedProgram`).  ``key_extra`` must carry everything baked
    into the program that the call signature doesn't show: settings,
    cadence/chunk knobs, closure constants (via :func:`array_digest`),
    the mesh (:func:`mesh_fingerprint`)."""
    return CachedProgram(jitted, kind, key_extra=key_extra,
                         static_names=static_names)


# ---------------------------------------------------------------------------
# Prewarm: deserialize executables into memory BEFORE first use — the
# wheel spinner's pre-thread preload, tune.prewarm_aot's pre-iter0 load,
# and the resume path after a checkpoint hands over its cache pointer.
# SYNCHRONOUS callers are the norm: the loader is only reliable while no
# compile is in flight (see _xla_work_lock), so front-loading beats
# overlapping.
# ---------------------------------------------------------------------------
def session_mark() -> int:
    """Position marker into the session key log (pair with
    :func:`session_keys_since` to attribute keys to one tuning call)."""
    with _lock:
        return len(_session_keys)


def session_keys_since(mark: int = 0) -> list:
    """Keys compiled-or-loaded by this process since ``mark``."""
    with _lock:
        return list(_session_keys[int(mark):])


def prewarm(keys=None) -> int:
    """Synchronously deserialize cached executables into memory; returns
    how many loaded.  ``keys=None`` loads the newest
    :data:`PREWARM_MAX_FILES` entries in the cache dir.  Unknown keys and
    unreadable files are skipped silently (they will resolve — or
    recompile — on first call).

    Trade-off note: the directory sweep cannot know which entries this
    run will call, so against a long-lived shared cache dir it may load
    programs of other shape families — bounded by the cap at a few
    seconds of startup and their resident memory, the price of the warm
    start for runs (wheels without banked tune verdicts) whose keys
    nothing recorded.  Prewarmed loads count into ``aot.prewarmed`` AND
    ``aot.hits``, in whatever metrics window the prewarm ran."""
    if not enabled():
        return 0
    d = cache_path()
    if keys is None:
        def _mtime(nm):
            # a sibling process may delete entries (quarantine/wipe)
            # between listdir and here — a vanished file sorts oldest,
            # it must never crash the sweep
            try:
                return os.path.getmtime(os.path.join(d, nm))
            except OSError:
                return 0.0

        try:
            names = [nm for nm in os.listdir(d) if nm.endswith(".aotx")]
            # sweep orphaned atomic-write temp files (a SIGKILL mid-
            # serialize strands one; nothing else ever looks at them) —
            # age-guarded so a LIVE writer's in-flight temp survives
            for nm in os.listdir(d):
                if nm.startswith(".aot_tmp_") and nm.endswith(".tmp"):
                    p = os.path.join(d, nm)
                    with contextlib.suppress(OSError):
                        if time.time() - os.path.getmtime(p) > 3600.0:
                            os.remove(p)
        except OSError:
            return 0
        names.sort(key=_mtime, reverse=True)
        keys = [nm[:-len(".aotx")] for nm in names[:PREWARM_MAX_FILES]]
    n = 0
    for key in keys:
        with _lock:
            if key in _loaded:
                continue
        with _xla_work_lock, _trace.span("compile", "aot.load"):
            exe = _deserialize_from_disk(str(key))
        if exe is None:
            continue
        with _lock:
            if key not in _loaded:
                _loaded[key] = exe
                _session_keys.append(key)
                n += 1
    if n:
        _CTR_PREWARMED.inc(n)
        _CTR_HITS.inc(n)
        _log.info("prewarmed %d executable(s) from %s", n, d)
    return n


def prewarm_async(keys=None) -> threading.Thread | None:
    """Fire-and-forget :func:`prewarm` on a daemon thread (None when the
    cache is disarmed).  Use ONLY when nothing else will compile while
    the thread runs — a concurrent plain-jit compile can crash the
    loader (see :data:`_xla_work_lock`); the shipped call sites all
    prefer the synchronous :func:`prewarm`."""
    if not enabled():
        return None
    th = threading.Thread(target=prewarm, args=(keys,),
                          name="aot-prewarm", daemon=True)
    th.start()
    return th
